"""Round-4 probe: does scan_layers tear down the GPT-2 batch-8 wall?

Round 3's measured negative (bench_lm_gpt2.py docstring): b16 flat,
b32 fails the tunnel's remote compile (HTTP 500) — with 12 UNROLLED
blocks. VERDICT r3 #1: the unrolled program size is the prime suspect;
scan_layers (one block body + a loop) is the tear-down attempt. This
probe measures flash/remat-off at b8 (scan-vs-unroll overhead check),
then walks b16/b32/b64 with scan_layers=True, remat off while memory
admits and remat=dots as the fallback.

Each config runs in THIS process sequentially; tunnel compile failures
are caught and recorded per config.
"""

from __future__ import annotations

import json
import sys
import os

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from bench_lm_gpt2 import bench_config  # noqa: E402


def run(label, **kw):
    try:
        row = bench_config(**kw)
        row["probe"] = label
        print(json.dumps(row), flush=True)
    except Exception as e:
        print(json.dumps({
            "probe": label, "error": f"{type(e).__name__}: {str(e)[:160]}",
            **{k: str(v) for k, v in kw.items()},
        }), flush=True)


def main() -> None:
    # Overhead check at the round-3 headline point.
    run("scan-b8-nomat", attention_impl="flash", fused_xent=False,
        batch=8, remat=False, scan_layers=True)
    # The wall itself.
    run("scan-b16-nomat", attention_impl="flash", fused_xent=False,
        batch=16, remat=False, scan_layers=True)
    run("scan-b32-nomat", attention_impl="flash", fused_xent=False,
        batch=32, remat=False, scan_layers=True)
    run("scan-b32-dots", attention_impl="flash", fused_xent=False,
        batch=32, remat=True, scan_layers=True)
    run("scan-b64-dots", attention_impl="flash", fused_xent=False,
        batch=64, remat=True, scan_layers=True)


if __name__ == "__main__":
    main()
