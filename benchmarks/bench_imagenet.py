"""ResNet-50 at ImageNet shape on the real chip — the scale-out model.

BASELINE.json's north star names ResNet-50/ImageNet scale-out alongside
the scored CIFAR ResNet-18 metric; `tests/test_imagenet.py` pins the
model shapes (7x7/s2 stem + maxpool, torchvision-matching param
counts), and this records single-chip training throughput at 224 px on
synthetic data (real ImageNet bytes are not available in this
environment). Run: python benchmarks/bench_imagenet.py

Measured numbers live in benchmarks/README.md.
"""

from __future__ import annotations

import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax

from cs744_pytorch_distributed_tutorial_tpu.config import TrainConfig
from cs744_pytorch_distributed_tutorial_tpu.data import synthetic_images
from cs744_pytorch_distributed_tutorial_tpu.parallel import make_mesh
from cs744_pytorch_distributed_tutorial_tpu.parallel.mesh import (
    shard_global_batch,
)
from cs744_pytorch_distributed_tutorial_tpu.train import Trainer

BATCH = 256
WARMUP = 8
STEPS = 15


def main() -> None:
    n = len(jax.devices())
    for model in ("resnet50", "resnet18"):
        cfg = TrainConfig(
            model=model,
            sync="auto",
            num_devices=n,
            global_batch_size=BATCH,
            compute_dtype="bfloat16",
            synthetic_data=True,
            image_size=224,
            num_classes=1000,
        )
        mesh = make_mesh({"data": n})
        tr = Trainer(cfg, mesh=mesh)
        state = tr.init()
        ds = synthetic_images(BATCH, 16, image_size=224, num_classes=1000,
                              seed=0)
        x, y = shard_global_batch(mesh, ds.train_images, ds.train_labels)
        key = jax.random.key(cfg.seed)
        if jax.default_backend() != "cpu":
            # Compile failures must surface, not silently fall back — a
            # default-compiled number would not be comparable to the
            # documented vmem-option configuration (same policy as
            # bench.py).
            step = tr.train_step.lower(state, x, y, key).compile(
                compiler_options={"xla_tpu_scoped_vmem_limit_kib": "65536"}
            )
        else:  # CPU smoke runs: the TPU option doesn't exist there
            step = tr.train_step
        for _ in range(WARMUP):
            state, m = step(state, x, y, key)
        float(jax.tree.leaves(state.params)[0].ravel()[0])
        t0 = time.perf_counter()
        for _ in range(STEPS):
            state, m = step(state, x, y, key)
        float(jax.tree.leaves(state.params)[0].ravel()[0])
        dt = (time.perf_counter() - t0) / STEPS
        print(
            f"{model:9s} 224px b{BATCH}: {dt * 1e3:8.1f} ms/step  "
            f"{BATCH / dt / n:8.1f} samples/sec/chip"
        )


if __name__ == "__main__":
    main()
