"""Decode throughput on the real chip: KV-cache generation.

Autoregressive decoding is bound by HBM bandwidth (weights + KV cache
re-read every step) and, for small models, by per-op latency on the
step's serial dependency chain. Measures generated tokens/sec for the
jitted sampling loop (infer/generate.py) across:

- MHA vs GQA vs MQA KV-head counts (the cache-bandwidth lever);
- weight-only int8 (ops/quant.py) at two scopes, on a toy 4L/512d model
  AND a GPT-2-small-scale model (the regime split below).

Timing methodology: the tunneled backend's round-trip latency is
volatile (measured 3-30 ms within one session), so per-call timing with
a fence per generation is RTT-contaminated. Instead each measurement
dispatches CALLS generations back-to-back (they pipeline on device —
each depends only on params) and fences ONCE; best-of-3 rounds,
variants interleaved so drift hits all of them equally.

Measured 2026-07-31 (one TPU v5e chip, greedy, best-of-rounds):

kv sweep (toy 4L/512d): MHA 69.5k / GQA-2 116.2k / MQA 150.3k tok/s
toy 4L/512d/kv2, vocab 32k (weights ~54 MB bf16):
  bf16       35.1 ms/gen  116.7k tok/s
  int8 head  37.1 ms/gen  110.5k tok/s (0.95x)
  int8 all   38.8 ms/gen  105.5k tok/s (0.90x)
GPT-2-small 12L/768d/kv4, vocab 50304 (weights ~325 MB bf16):
  bf16      106.5 ms/gen  19.2k tok/s
  int8 head  91.2 ms/gen  22.5k tok/s (1.17x, reproduced 1.167x/1.168x/1.135x)
  int8 all  104.7 ms/gen  19.6k tok/s (1.02x)
long context (toy model, prompt 4096, ~142 MB bf16 cache; the wall
number carries the constant prefill + dispatch, so the decode LOOP's
device time from the trace is the honest metric):
  bf16 cache       decode loop 232 us/step
  int8 cache       decode loop 184 us/step (1.26x)
  int8 cache+head  decode loop 162 us/step (1.43x)

The regime split the numbers pin: at toy scale the decode step is
op-latency-bound (~137 us/step against ~66 us of weight reads — the
reads hide under the serial chain), so int8 only adds Pallas-call
overhead. At GPT-2 scale the step is bandwidth-bound and quantizing the
wide lm_head matmul alone wins 1.17x, while quantizing the 72 small
per-layer projections gives the win back in per-call dispatch cost —
hence ``QUANT_HEAD_ONLY`` is the decode default
(``LMTrainer.quantized_decode_model``).

Run: python benchmarks/bench_generate.py
"""

from __future__ import annotations

import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp

from cs744_pytorch_distributed_tutorial_tpu.infer import make_generator
from cs744_pytorch_distributed_tutorial_tpu.models import TransformerLM
from cs744_pytorch_distributed_tutorial_tpu.ops.quant import (
    QUANT_HEAD_ONLY,
    QUANT_MODULES,
    quantize_lm_params,
)

BATCH = 16
PROMPT = 128
CALLS = 8  # generations per timing batch (one fence at the end)
ROUNDS = 3


def batch_time(gen, params, prompt, calls=CALLS) -> float:
    outs = [gen(params, prompt, jax.random.key(2)) for _ in range(2)]
    float(outs[-1][0, 0])  # steady-state warm
    t0 = time.perf_counter()
    outs = [gen(params, prompt, jax.random.key(2)) for _ in range(calls)]
    float(outs[-1][0, 0])  # ONE fence: device work pipelines, RTT amortizes
    return (time.perf_counter() - t0) / calls


def run_block(title: str, model: TransformerLM, new_tokens: int) -> None:
    print(title)
    prompt = jax.random.randint(
        jax.random.key(0), (BATCH, PROMPT), 0, model.vocab_size
    )
    params = model.init(jax.random.key(1), jnp.zeros((1, 8), jnp.int32))[
        "params"
    ]
    variants: dict[str, tuple] = {
        "bf16": (
            make_generator(model, max_new_tokens=new_tokens, temperature=0.0),
            params,
        ),
        "int8 head": (
            make_generator(
                model.clone(quant_dense=True, quant_modules=QUANT_HEAD_ONLY),
                max_new_tokens=new_tokens,
                temperature=0.0,
            ),
            quantize_lm_params(params, QUANT_HEAD_ONLY),
        ),
        "int8 all": (
            make_generator(
                model.clone(
                    quant_dense=True,
                    quant_modules=tuple(sorted(QUANT_MODULES)),
                ),
                max_new_tokens=new_tokens,
                temperature=0.0,
            ),
            quantize_lm_params(params, tuple(sorted(QUANT_MODULES))),
        ),
    }
    for gen, p in variants.values():  # compile
        out = gen(p, prompt, jax.random.key(2))
        float(out[0, 0])
    best = {k: float("inf") for k in variants}
    for _ in range(ROUNDS):  # interleave so tunnel drift hits all variants
        for name, (gen, p) in variants.items():
            best[name] = min(best[name], batch_time(gen, p, prompt))
    base = best["bf16"]
    for name, dt in best.items():
        print(
            f"  {name:10s} {dt * 1e3:7.1f} ms/gen  "
            f"{BATCH * new_tokens / dt:9.0f} tok/s  ({base / dt:.3f}x vs bf16)"
        )


def kv_block() -> None:
    """MHA vs GQA vs MQA on the toy model — the KV-cache bandwidth lever
    (the grouped decode_attention reads the cache at kv width)."""
    print("kv-head sweep (4L/512d toy, bf16)")
    for kv in (8, 2, 1):
        model = TransformerLM(
            vocab_size=32768,
            num_layers=4,
            num_heads=8,
            num_kv_heads=kv,
            d_model=512,
            d_ff=2048,
            max_seq_len=PROMPT + 256,
            dtype=jnp.bfloat16,
            attention_impl="dense",
            use_rope=True,
        )
        prompt = jax.random.randint(
            jax.random.key(0), (BATCH, PROMPT), 0, 32768
        )
        params = model.init(jax.random.key(1), jnp.zeros((1, 8), jnp.int32))[
            "params"
        ]
        gen = make_generator(model, max_new_tokens=256, temperature=0.0)
        out = gen(params, prompt, jax.random.key(2))
        float(out[0, 0])
        dt = min(batch_time(gen, params, prompt) for _ in range(ROUNDS))
        print(
            f"  kv_heads={kv}  {dt * 1e3:7.1f} ms/gen  "
            f"{BATCH * 256 / dt:9.0f} tok/s"
        )


def long_context_block() -> None:
    """Int8 KV cache at long context: with a 4096-token prompt the cache
    (~142 MB bf16/step at this config), not the weights (~54 MB), is most
    of what a decode step reads — the regime quant_kv_cache targets. The
    cache mutates every step so XLA cannot hoist its dequant (contrast
    the weight path, which needed the Pallas kernel for exactly that
    reason); pure-XLA int8 reads are the win. Prefill runs the flash
    kernel (dense would materialize [B, H, 4096, 4096] scores)."""
    print("int8 KV cache at long context (4L/512d/kv2, prompt 4096)")
    lc_prompt_len, new = 4096, 128
    model = TransformerLM(
        vocab_size=32768,
        num_layers=4,
        num_heads=8,
        num_kv_heads=2,
        d_model=512,
        d_ff=2048,
        max_seq_len=lc_prompt_len + new,
        dtype=jnp.bfloat16,
        attention_impl="flash",
        use_rope=True,
    )
    prompt = jax.random.randint(
        jax.random.key(0), (BATCH, lc_prompt_len), 0, 32768
    )
    params = model.init(jax.random.key(1), jnp.zeros((1, 8), jnp.int32))[
        "params"
    ]
    variants = {
        "bf16 cache": (
            make_generator(model, max_new_tokens=new, temperature=0.0),
            params,
        ),
        "int8 cache": (
            make_generator(
                model.clone(quant_kv_cache=True),
                max_new_tokens=new,
                temperature=0.0,
            ),
            params,
        ),
        "int8 cache+head": (
            make_generator(
                model.clone(
                    quant_kv_cache=True,
                    quant_dense=True,
                    quant_modules=QUANT_HEAD_ONLY,
                ),
                max_new_tokens=new,
                temperature=0.0,
            ),
            quantize_lm_params(params, QUANT_HEAD_ONLY),
        ),
    }
    from cs744_pytorch_distributed_tutorial_tpu.utils.profiling import (
        device_op_breakdown,
    )

    # Wall-clock per generation is dominated by the CONSTANT 4096-token
    # prefill (~37 ms device) plus dispatch, which masks the decode-loop
    # delta — so report the decode loop's own device time (the single
    # `while` op in the trace) alongside the wall number.
    loop_ms = {}
    best = {k: float("inf") for k in variants}
    for name, (gen, p) in variants.items():
        out = gen(p, prompt, jax.random.key(2))
        float(out[0, 0])
        _, ops = device_op_breakdown(
            gen, p, prompt, jax.random.key(2), iters=2, top=40
        )
        loop_ms[name] = sum(ms for ms, n in ops if n.startswith("while"))
    for _ in range(ROUNDS):
        for name, (gen, p) in variants.items():
            best[name] = min(best[name], batch_time(gen, p, prompt, calls=4))
    base_loop = loop_ms["bf16 cache"]
    for name, dt in best.items():
        print(
            f"  {name:16s} wall {dt * 1e3:7.1f} ms/gen   decode-loop "
            f"{loop_ms[name]:6.1f} ms ({loop_ms[name] / new * 1e3:5.0f} us/"
            f"step, {base_loop / loop_ms[name]:.3f}x vs bf16)"
        )


def main() -> None:
    kv_block()
    run_block(
        "int8 ablation: toy 4L/512d/kv2 (op-latency-bound regime)",
        TransformerLM(
            vocab_size=32768,
            num_layers=4,
            num_heads=8,
            num_kv_heads=2,
            d_model=512,
            d_ff=2048,
            max_seq_len=PROMPT + 256,
            dtype=jnp.bfloat16,
            attention_impl="dense",
            use_rope=True,
        ),
        new_tokens=256,
    )
    run_block(
        "int8 ablation: GPT-2-small 12L/768d/kv4 (bandwidth-bound regime)",
        TransformerLM(
            vocab_size=50304,
            num_layers=12,
            num_heads=12,
            num_kv_heads=4,
            d_model=768,
            d_ff=3072,
            max_seq_len=PROMPT + 128,
            dtype=jnp.bfloat16,
            attention_impl="dense",
            use_rope=True,
        ),
        new_tokens=128,
    )
    long_context_block()


if __name__ == "__main__":
    main()
