"""Decode throughput on the real chip: KV-cache generation, MHA vs GQA.

Autoregressive decoding is bandwidth-bound on the KV cache; grouped-query
attention shrinks the cache by H/KV. Measures generated tokens/sec for
the jitted sampling loop (infer/generate.py). Run: python
benchmarks/bench_generate.py

Measured 2026-07-30 (one TPU v5e chip, this config, greedy):
  kv_heads=8 (MHA)   61.9 ms/gen   66.1k tokens/sec
  kv_heads=2 (GQA)   38.7 ms/gen  105.9k tokens/sec  (1.60x)
  kv_heads=1 (MQA)   39.8 ms/gen  103.0k tokens/sec
The grouped decode_attention reads the cache at kv width — the saving
is real bandwidth, not just capacity; kv=1's tiny head tensors give a
little back to layout overhead.
"""

from __future__ import annotations

import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp

from cs744_pytorch_distributed_tutorial_tpu.infer import make_generator
from cs744_pytorch_distributed_tutorial_tpu.models import TransformerLM

BATCH = 16
PROMPT = 128
NEW = 256
REPEATS = 5


def _time_gen(generate, params, prompt) -> float:
    out = generate(params, prompt, jax.random.key(2))  # compile
    float(out[0, 0])
    for _ in range(4):  # steady-state warm-up (see bench_lm.py)
        out = generate(params, prompt, jax.random.key(2))
    float(out[0, 0])
    t0 = time.perf_counter()
    for _ in range(REPEATS):
        out = generate(params, prompt, jax.random.key(2))
    float(out[0, 0])  # value fetch fences (see bench.py)
    return (time.perf_counter() - t0) / REPEATS


def main() -> None:
    from cs744_pytorch_distributed_tutorial_tpu.ops.quant import quantize_lm_params

    prompt = jax.random.randint(jax.random.key(0), (BATCH, PROMPT), 0, 32768)
    for kv in (8, 2, 1):
        model = TransformerLM(
            vocab_size=32768,
            num_layers=4,
            num_heads=8,
            num_kv_heads=kv,
            d_model=512,
            d_ff=2048,
            max_seq_len=PROMPT + NEW,
            dtype=jnp.bfloat16,
            attention_impl="dense",
            use_rope=True,
        )
        params = model.init(
            jax.random.key(1), jnp.zeros((1, 8), jnp.int32)
        )["params"]
        generate = make_generator(model, max_new_tokens=NEW, temperature=0.0)
        dt = _time_gen(generate, params, prompt)
        print(
            f"kv_heads={kv}             {dt * 1e3:8.1f} ms/gen  "
            f"{BATCH * NEW / dt:10.0f} tokens/sec"
        )
        if kv == 2:
            # Weight-only int8 ablation on the GQA winner: same model,
            # kernels stored int8 + per-channel scale, dequant inside
            # the Pallas matmul (ops/quant.py).
            qgen = make_generator(
                model.clone(quant_dense=True), max_new_tokens=NEW,
                temperature=0.0,
            )
            qdt = _time_gen(qgen, quantize_lm_params(params), prompt)
            print(
                f"kv_heads={kv} int8 dense  {qdt * 1e3:8.1f} ms/gen  "
                f"{BATCH * NEW / qdt:10.0f} tokens/sec  "
                f"({dt / qdt:.2f}x vs bf16)"
            )


if __name__ == "__main__":
    main()
