"""Speculative decoding on the real chip: trained byte-LM draft+target.

Measures what `infer/speculative.py` buys in the regime bench_generate
pinned as OP-LATENCY-bound: batch-1 greedy decoding, where the serial
per-token chain (not bandwidth or FLOPs) sets wall-clock. A 4-layer
target and a 1-layer draft train briefly on this repo's own README as a
byte corpus (enough for real draft/target agreement — random drafts
accept ~nothing and measure only overhead), then tokens/sec and the
realized acceptance are measured for plain greedy vs speculative at
several k.

Timing: whole generations are single dispatches (the entire
draft-propose/verify loop is one jitted while_loop), batched CALLS-deep
with one fence — same RTT-amortization as bench_generate.

Run: python benchmarks/bench_speculative.py

Measured 2026-07-31 (one TPU v5e chip, trained byte-LMs, device time
from the trace; both models reach ~0 train loss and teacher-forced
draft/target agreement 1.00 on the generated text):
  plain greedy      12.6 ms/gen   20.3k tok/s
  speculative k=2    5.5 ms/gen   47.0k tok/s  (2.31x)  acceptance ~1.0
  speculative k=4    4.9 ms/gen   52.5k tok/s  (2.58x)  acceptance 1.00
  speculative k=8    4.6 ms/gen   55.7k tok/s  (2.74x)  acceptance 0.98
Target forwards drop 256 -> 29 at k=8 (8.8x); the draft's own serial
steps bound the remaining time. An earlier version of the decoder
measured only ~0.83 acceptance on this same agreement-1.00 pair — the
draft cache row at pos+k was never written (found in review, fixed,
and the strict self-draft stats test now pins it). Earlier wall-clock
attempts measured 0.4-0.9x "slowdowns" that were pure tunnel weather —
RTT swung 3-500 ms in-session; the trace is ground truth. A random
(untrained-agreement) draft costs ~3x plain in device time at k=8 —
speculation must be earned by a draft that actually agrees.

EARNED-ACCEPTANCE regime, round 4 (VERDICT r3 #3a) — undertrained
drafts picked by a step sweep to land in the 0.5-0.9 agreement band:
  agreement 0.81 (330-step draft):
    k=2 1.87x (acc 0.72)   k=4 1.81x (acc 0.63)   k=8 1.40x (acc 0.44)
  agreement 0.52 (260-step draft):
    k=2 1.44x (acc 0.43)   k=4 1.05x (acc 0.26)   k=8 0.64x (acc 0.13)
  agreement 0.24 (120-step draft):
    k=2 0.99x              k=4 0.68x              k=8 0.41x
The shape is the textbook speculative curve: real speedup needs
agreement >~0.5, moderate-acceptance pairs want SMALL k (k=2 dominates
at 0.5; k=8 only pays at >~0.7), and a weak draft is a net LOSS. Also
measured: the band only exists on in-distribution prompts — on an
off-distribution prompt the target's own continuation is chaotic and
even a near-converged draft scores ~0.2 agreement (agreement-vs-steps:
150->0.27, 200->0.35, 260->0.52, 330->0.81, 420->0.99).

SAMPLING mode, round 4 (VERDICT r3 #3b) — rejection-sampling
speculative at temperature 0.8 vs plain sampling (distribution
exactness pinned separately by the chi-square test):
  plain sampling   12.9 ms/gen  19.9k tok/s
  k=4               6.1 ms/gen  41.9k tok/s  (2.11x)  acceptance 1.00
  k=8               5.6 ms/gen  45.4k tok/s  (2.29x)  acceptance 0.98
"""

from __future__ import annotations

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp

from cs744_pytorch_distributed_tutorial_tpu.data import byte_corpus
from cs744_pytorch_distributed_tutorial_tpu.infer import (
    make_generator,
    make_speculative_generator,
)
from cs744_pytorch_distributed_tutorial_tpu.train import LMConfig, LMTrainer

SEQ = 512
MAX_SEQ = 1024
PROMPT = 128
NEW = 256
STEPS = 800
CALLS = 6
ROUNDS = 3


def train(num_layers: int, d_model: int, d_ff: int, tokens,
          steps: int = STEPS):
    cfg = LMConfig(
        vocab_size=256,
        num_layers=num_layers,
        num_heads=4,
        d_model=d_model,
        d_ff=d_ff,
        max_seq_len=MAX_SEQ,
        seq_len=SEQ,
        attention_impl="dense",
        compute_dtype="bfloat16",
        use_rope=True,
        global_batch_size=8,
        learning_rate=1e-3,
        lr_schedule="warmup_cosine",
        warmup_steps=min(50, steps // 4),
        total_steps=steps,
        optimizer="adamw",
    )
    tr = LMTrainer(cfg)
    params, _, losses = tr.fit(tokens, steps)
    return tr, jax.device_get(params), losses[-1]


def timed(gen, *args) -> float:
    """DEVICE time per generation from the profiler trace — the tunnel's
    round-trip latency has been observed anywhere from 3 to 500 ms in a
    single session, and even pipelined-dispatch wall timing drowns at
    the upper end; the trace is ground truth (see utils/profiling.py)."""
    from cs744_pytorch_distributed_tutorial_tpu.utils.profiling import (
        device_op_breakdown,
    )

    out = gen(*args)
    float(jax.tree.leaves(out)[0].ravel()[0])
    total, _ = device_op_breakdown(gen, *args, iters=3, top=1)
    return total / 1e3


def agreement(draft, tp, dp, plain, prompt) -> float:
    """Teacher-forced agreement of the draft with the target's own
    greedy continuation (via the closed-over ``plain`` generator on
    ``tp``) — the diagnostic upper bound on acceptance."""
    t_out = plain(tp, prompt, jax.random.key(0))
    seq = jnp.concatenate([prompt, t_out.astype(jnp.int32)], axis=1)
    d_logits = draft.apply({"params": dp}, seq)
    d_pred = jnp.argmax(d_logits[:, PROMPT - 1 : -1], axis=-1)
    return float((d_pred == t_out).mean())


def sweep(label, target, draft, tp, dp, base, prompt) -> None:
    for k in (2, 4, 8):
        spec = make_speculative_generator(
            target, draft, max_new_tokens=NEW, k=k, return_stats=True
        )
        dt = min(timed(spec, tp, dp, prompt) for _ in range(ROUNDS))
        _, calls = spec(tp, dp, prompt)
        calls = int(calls)
        accept = (NEW / max(calls, 1) - 1) / k
        print(
            f"{label} k={k}       {dt * 1e3:7.1f} ms/gen  "
            f"{NEW / dt:8.0f} tok/s  ({base / dt:.2f}x)  "
            f"[{calls} target calls, acceptance {accept:.2f}]"
        )


def main() -> None:
    corpus = byte_corpus("README.md", SEQ, max_seqs=512, seed=0)
    target_tr, tp, tl = train(4, 256, 1024, corpus)
    draft_tr, dp, dl = train(1, 256, 1024, corpus)
    print(f"trained: target 4L/256d loss {tl:.3f}, draft 1L/256d loss {dl:.3f}")

    prompt = jnp.asarray(corpus[:1, :PROMPT], jnp.int32)
    target = target_tr.decode_model()
    draft = draft_tr.decode_model()

    plain = make_generator(target, max_new_tokens=NEW, temperature=0.0)
    key = jax.random.key(0)
    base = min(timed(plain, tp, prompt, key) for _ in range(ROUNDS))
    agree = agreement(draft, tp, dp, plain, prompt)
    print(f"teacher-forced draft/target agreement: {agree:.2f}")
    print(
        f"plain greedy          {base * 1e3:7.1f} ms/gen  "
        f"{NEW / base:8.0f} tok/s"
    )
    sweep("speculative", target, draft, tp, dp, base, prompt)

    # ---- earned-acceptance regime (VERDICT r3 #3a) ----------------------
    # UNDERTRAINED shallow drafts against the converged target, picked
    # (by a step sweep) to land teacher-forced agreement in the 0.5-0.9
    # band a real draft/target pair lives at: 260 steps -> ~0.5, 330 ->
    # ~0.8 on this corpus. A byte-LM transitions through the band
    # quickly (agreement vs steps: 150->0.27, 200->0.35, 260->0.52,
    # 330->0.81, 420->0.99), and on OFF-distribution prompts the band
    # does not exist at all — the target's own continuation is chaotic
    # there and even a near-converged draft measures ~0.2 agreement
    # (measured; the tail-prompt rows of an earlier revision).
    for label, steps, dm, dff in (
        ("draft-330step", 330, 256, 1024),
        ("draft-260step", 260, 256, 1024),
        ("draft-120step", 120, 256, 1024),
    ):
        u_tr, up, ul = train(1, dm, dff, corpus, steps=steps)
        u_draft = u_tr.decode_model()
        agree_u = agreement(u_draft, tp, up, plain, prompt)
        print(
            f"{label} (1L/{dm}d, loss {ul:.2f}): "
            f"teacher-forced agreement {agree_u:.2f}"
        )
        sweep(f"  {label}", target, u_draft, tp, up, base, prompt)

    # ---- sampling mode (VERDICT r3 #3b) ---------------------------------
    # Rejection-sampling speculative vs plain sampling at the same
    # temperature: the latency story must survive temperature > 0 (the
    # distribution-exactness itself is pinned by the chi-square test).
    temp = 0.8
    plain_s = make_generator(target, max_new_tokens=NEW, temperature=temp)
    base_s = min(timed(plain_s, tp, prompt, key) for _ in range(ROUNDS))
    print(
        f"plain sampling t={temp}  {base_s * 1e3:7.1f} ms/gen  "
        f"{NEW / base_s:8.0f} tok/s"
    )
    for k in (4, 8):
        spec_s = make_speculative_generator(
            target, draft, max_new_tokens=NEW, k=k, temperature=temp,
            return_stats=True,
        )
        dt = min(
            timed(spec_s, tp, dp, prompt, key) for _ in range(ROUNDS)
        )
        _, calls = spec_s(tp, dp, prompt, key)
        calls = int(calls)
        accept = (NEW / max(calls, 1) - 1) / k
        print(
            f"sampling-spec k={k}    {dt * 1e3:7.1f} ms/gen  "
            f"{NEW / dt:8.0f} tok/s  ({base_s / dt:.2f}x)  "
            f"[{calls} target calls, acceptance {accept:.2f}]"
        )


if __name__ == "__main__":
    main()
