"""GPT-2-medium-class depth point (round 4, VERDICT r3 #4).

24L / 1024d / 16h / d_ff 4096 / T=1024 / vocab 50304 (~350M params),
bf16, RoPE, flash attention, one v5e chip — the first training number
above 12L/768d in this repo, the scale remat/scan_layers/ZeRO exist
for. Ablates scan_layers x remat to answer two questions at once:

1. does the 24L unrolled program still compile through the tunnel's
   remote compile helper (12L b32 did not), and
2. what do scan_layers and remat cost/buy at depth.

MFU accounting matches bench_lm_gpt2.py (2*MACs, 3x-forward train,
remat recompute NOT counted, causal masking not discounted).

Measured 2026-07-31 (one TPU v5e chip):
  unroll + remat=off  b8   197.7 ms  41.4k tok/s  MFU 0.510  <- headline
  unroll + remat=dots b8   230.3 ms  35.6k tok/s  MFU 0.438
  scan   + remat=dots b8   240.8 ms  34.0k tok/s  MFU 0.418
  unroll + remat=off  b12  320.3 ms  38.4k tok/s  MFU 0.472
  b16: remote-compile HTTP 500 in every variant (unroll/scan x
       dots/off) — the same tunnel compile-helper wall as 12L/b32;
       it tracks total program footprint, not layer count alone
       (24L b8 compiles where 12L b32 does not).
Findings: (1) the 24L/b8 UNROLLED program compiles and remat-off FITS
(~0.7 GB bf16 params + 2.8 GB f32 adam + activations < 16 GB HBM) —
at 1024d the bigger matmuls lift MFU past the 12L model's (0.510 vs
0.481); (2) the scan_layers penalty collapses from ~22% at 12L/768d
to ~4.3% at 24L/1024d (the loop overhead amortizes as the block body
grows) — scan remains the compile-scalability option, unrolled remains
the throughput choice while programs still compile.
"""

from __future__ import annotations

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax

from cs744_pytorch_distributed_tutorial_tpu.data import synthetic_tokens
from cs744_pytorch_distributed_tutorial_tpu.parallel import make_mesh
from cs744_pytorch_distributed_tutorial_tpu.train import LMConfig, LMTrainer

SEQ, LAYERS, D_MODEL, HEADS, D_FF = 1024, 24, 1024, 16, 4096
VOCAB = 50304
STEPS, WARMUP = 8, 5
V5E_PEAK_FLOPS = 197e12


def flops_per_token() -> float:
    per_layer = 4 * D_MODEL**2 + 2 * D_MODEL * D_FF + 2 * SEQ * D_MODEL
    return 3.0 * (LAYERS * 2.0 * per_layer + 2.0 * D_MODEL * VOCAB)


def run(label: str, batch: int, scan_layers: bool, remat: bool) -> None:
    try:
        cfg = LMConfig(
            vocab_size=VOCAB, num_layers=LAYERS, num_heads=HEADS,
            d_model=D_MODEL, d_ff=D_FF, max_seq_len=SEQ, seq_len=SEQ,
            global_batch_size=batch, attention_impl="flash",
            compute_dtype="bfloat16", remat=remat,
            remat_policy="dots" if remat else "none",
            scan_layers=scan_layers, use_rope=True,
        )
        tr = LMTrainer(cfg, mesh=make_mesh({"data": 1, "seq": 1}))
        params, opt = tr.init()
        x, y = tr.shard_batch(synthetic_tokens(batch, SEQ, VOCAB, seed=0))
        params, opt, m = tr.train_step(params, opt, x, y)
        float(m["loss"])
        for _ in range(WARMUP):
            params, opt, m = tr.train_step(params, opt, x, y)
        float(m["loss"])
        t0 = time.perf_counter()
        for _ in range(STEPS):
            params, opt, m = tr.train_step(params, opt, x, y)
        float(m["loss"])
        dt = (time.perf_counter() - t0) / STEPS
        tok_s = batch * SEQ / dt
        print(json.dumps({
            "metric": "gpt2medium_train_tokens_per_sec_per_chip",
            "probe": label,
            "ms_per_step": round(dt * 1e3, 2),
            "tokens_per_sec": round(tok_s),
            "mfu": (
                round(tok_s * flops_per_token() / V5E_PEAK_FLOPS, 4)
                if jax.default_backend() != "cpu" else None
            ),
            "config": f"{LAYERS}L/{D_MODEL}d/{HEADS}h/T{SEQ}/V{VOCAB}"
                      f"/b{batch}/bf16/remat={'dots' if remat else 'off'}"
                      f"/rope" + ("/scan" if scan_layers else ""),
        }), flush=True)
    except Exception as e:
        print(json.dumps({
            "probe": label, "batch": batch, "scan_layers": scan_layers,
            "remat": remat, "error": f"{type(e).__name__}: {str(e)[:200]}",
        }), flush=True)


def main() -> None:
    only = sys.argv[1:] or None
    for label, b, sc, rm in (
        ("unroll-nomat", 8, False, False),
        ("unroll-dots", 8, False, True),
        ("scan-dots", 8, True, True),
        ("unroll-dots-b16", 16, False, True),
        ("scan-dots-b16", 16, True, True),
        ("scan-nomat-b16", 16, True, False),
        ("unroll-nomat-b12", 12, False, False),
    ):
        if only and label not in only:
            continue
        run(label, b, scan_layers=sc, remat=rm)


if __name__ == "__main__":
    main()
