"""Tabulate a telemetry JSONL (obs/) for eyeballing a run.

    python benchmarks/metrics_summary.py /tmp/run/metrics.jsonl

Reads the stream the engines write with ``--metrics-dir`` (or a file
``bench.py --metrics-dir`` appended to), filters the ``kind == "step"``
records, and prints a one-screen summary: steps covered, mean step time
(first emission excluded — it amortizes compile), final/best loss, mean
MFU where recorded, and total gradient bytes on the wire. Stdlib only —
usable on any machine the JSONL lands on.

Also accepts the graftfleet ``fleet_report.json`` artifact (a single
pretty-printed object; its ``records`` list flattens into the stream)
and summarizes its ``fleet_skew`` / ``fleet_incident`` /
``fleet_summary`` rows: per-step collective-skew attribution with a
straggler histogram, incident counts, and the run-level audit line.
The graftmem ``memory_report.json`` artifact flattens the same way:
its ``kind:"memory_ledger"`` rows render one ``hbm <entry>`` line per
registered entrypoint — per-device HBM bytes, donation-alias savings,
and any replicated-leaf count TA008 found.
"""

from __future__ import annotations

import argparse
import json
import sys
from typing import Any


def load_records(path: str) -> list[dict[str, Any]]:
    with open(path, encoding="utf-8") as f:
        text = f.read()
    # Whole-file JSON first: a pretty-printed object carrying "records"
    # (the fleet_report.json artifact obs/fleet.py writes) flattens
    # into its row list; a bare object/array is taken as-is. Anything
    # that isn't one JSON document falls through to JSONL.
    try:
        obj = json.loads(text)
    except json.JSONDecodeError:
        obj = None
    if isinstance(obj, dict):
        if isinstance(obj.get("records"), list):
            return [r for r in obj["records"] if isinstance(r, dict)]
        return [obj]
    if isinstance(obj, list):
        return [r for r in obj if isinstance(r, dict)]
    records: list[dict[str, Any]] = []
    for i, line in enumerate(text.splitlines()):
        line = line.strip()
        if not line:
            continue
        try:
            rec = json.loads(line)
        except json.JSONDecodeError as e:
            print(f"{path}:{i + 1}: skipping bad line ({e})",
                  file=sys.stderr)
            continue
        if isinstance(rec, dict):
            records.append(rec)
    return records


def _mean(vals: list[float]) -> float | None:
    return sum(vals) / len(vals) if vals else None


def summarize(records: list[dict[str, Any]]) -> dict[str, Any]:
    """Reduce a record stream to the table rows. Pure — tested directly."""
    steps = [r for r in records if r.get("kind") == "step"]
    losses = [r["loss"] for r in steps
              if isinstance(r.get("loss"), (int, float))]
    # Drop the first recorded step time: it amortizes XLA compilation
    # and would dominate short runs.
    times = [r["step_time_s"] for r in steps
             if isinstance(r.get("step_time_s"), (int, float))][1:]
    mfus = [r["mfu"] for r in steps if isinstance(r.get("mfu"), (int, float))]
    wire = [r["grad_sync_bytes"] for r in steps
            if isinstance(r.get("grad_sync_bytes"), (int, float))]
    events = [r for r in records if r.get("kind") == "event"]
    # Chaos/recovery attribution (docs/reliability.md): kind:"event"
    # records are stamped with process_id/generation, so a merged
    # multi-process stream (e.g. a rendezvous store's events.jsonl)
    # summarizes into per-rank/per-generation "pN/gM" tags — which rank
    # died, who re-elected, who restored, in which generation.
    chaos_events: dict[str, dict[str, Any]] = {}
    for r in events:
        name = r.get("event")
        if not isinstance(name, str):
            continue
        if not (
            name.startswith("recovery_")
            or name
            in (
                "chaos_inject",
                "process_loss",
                "worker_death",
                "worker_exit",
                "reelection",
                "generation_start",
                "run_complete",
            )
        ):
            continue
        row = chaos_events.setdefault(name, {"count": 0, "by": []})
        row["count"] += 1
        pid, gen = r.get("process_id"), r.get("generation")
        if pid is not None or gen is not None:
            tag = f"p{'-' if pid is None else pid}/g{'-' if gen is None else gen}"
            if tag not in row["by"]:
                row["by"].append(tag)
        # recovery_giveup carries the full traceback of the fatal
        # failure (utils/failure.py, serve/guard.py); surface the last
        # non-empty line — the exception itself — as the row's tail.
        tb = r.get("traceback")
        if isinstance(tb, str) and tb.strip():
            row["traceback_tail"] = tb.strip().splitlines()[-1].strip()
    # graftscope per-phase records (bench.py --phase-breakdown) plus the
    # serve-side kind:"serve_phase" twins (serve_cli --trace-dir): one
    # row per phase, keyed by name, latest record wins on repeat runs.
    phases: dict[str, dict[str, Any]] = {}
    for r in records:
        kind = r.get("kind")
        if kind in ("phase", "serve_phase") and isinstance(
            r.get("phase"), str
        ):
            row = {
                k: r.get(k)
                for k in ("clock", "flops", "bytes_accessed",
                          "comm_bytes", "mfu", "roofline")
            }
            row["ms"] = (
                r.get("device_ms")
                if r.get("clock") == "device"
                else r.get("wall_ms")
            )
            name = r["phase"]
            phases[f"serve {name}" if kind == "serve_phase" else name] = row
    sync_exposed = [
        float(r["sync_exposed_ms"]) for r in records
        if r.get("kind") == "phase_summary"
        and isinstance(r.get("sync_exposed_ms"), (int, float))
    ]
    # Fused-vs-overlapped sync comparison rows (bench.py --sync-compare):
    # one row per wire format, latest record wins on repeat runs.
    sync_compare: dict[str, dict[str, Any]] = {}
    for r in records:
        if r.get("kind") == "sync_compare" and isinstance(
            r.get("wire"), str
        ):
            sync_compare[r["wire"]] = {
                k: r.get(k)
                for k in ("sync_overlap", "fused_step_ms", "overlap_step_ms",
                          "sync_exposed_ms_fused", "sync_exposed_ms_overlap",
                          "parity_ok")
            }
    # Serving rows (serve/loadgen.py): one row per engine label
    # ("continuous" / "batch"), latest serve_summary record wins.
    serve: dict[str, dict[str, Any]] = {}
    for r in records:
        if r.get("kind") == "serve_summary" and isinstance(
            r.get("engine"), str
        ):
            serve[r["engine"]] = {
                k: r.get(k)
                for k in ("requests", "ttft_p50_ms", "ttft_p99_ms",
                          "itl_p50_ms", "itl_p99_ms",
                          "tokens_per_sec", "page_high_water",
                          "slot_occupancy", "preemptions",
                          "recovered_requests",
                          "completed", "rejected", "timed_out",
                          "recovered", "restarts")
            }
    # graftguard overload shedding (serve/guard.py): kind:"serve_shed"
    # records aggregated by machine-readable reason; terminal sheds
    # (rejections) counted apart from non-terminal ones (degrade trims).
    serve_shed: dict[str, int] = {}
    shed_terminal = 0
    for r in records:
        if r.get("kind") == "serve_shed":
            reason = r.get("reason")
            if isinstance(reason, str):
                serve_shed[reason] = serve_shed.get(reason, 0) + 1
            if r.get("terminal"):
                shed_terminal += 1
    # graftserve windowed SLO telemetry (obs/serve_trace.py): one
    # aggregate row over every kind:"serve_window" record — TTFT/ITL
    # p99 trajectory (last + worst window), peak pool occupancy, queue
    # depth, preemption rate.
    windows = [r for r in records if r.get("kind") == "serve_window"]
    serve_windows: dict[str, Any] | None = None
    if windows:
        def _col(key: str) -> list[float]:
            return [w[key] for w in windows
                    if isinstance(w.get(key), (int, float))]

        ttft = _col("ttft_p99_ms")
        itl = _col("itl_p99_ms")
        serve_windows = {
            "count": len(windows),
            "span_s": windows[-1].get("t_s"),
            "ttft_p99_ms_last": ttft[-1] if ttft else None,
            "ttft_p99_ms_max": max(ttft) if ttft else None,
            "itl_p99_ms_last": itl[-1] if itl else None,
            "itl_p99_ms_max": max(itl) if itl else None,
            "live_pages_peak": max(_col("live_pages"), default=None),
            "queue_depth_max": max(_col("queue_depth_max"), default=None),
            "preempt_rate_per_s_max": max(
                _col("preempt_rate_per_s"), default=None
            ),
        }
    # decode_host_exposed_ms (kind:"serve_phase_summary"): host
    # scheduling overhead per live decode step — the serving analog of
    # sync_exposed_ms.
    host_exposed = [
        float(r["decode_host_exposed_ms"]) for r in records
        if r.get("kind") == "serve_phase_summary"
        and isinstance(r.get("decode_host_exposed_ms"), (int, float))
    ]
    # graftfleet rows (obs/fleet.py fleet_report.json, flattened by
    # load_records): skew attribution aggregated over post-warmup steps
    # (straggler histogram + worst skew), incidents counted by event
    # name, and the run-level summary (latest record wins).
    fleet_skew_rows = [
        r for r in records
        if r.get("kind") == "fleet_skew" and not r.get("warmup")
    ]
    fleet_skew: dict[str, Any] | None = None
    if fleet_skew_rows:
        skews = [float(r["skew_ms"]) for r in fleet_skew_rows
                 if isinstance(r.get("skew_ms"), (int, float))]
        stragglers: dict[str, int] = {}
        for r in fleet_skew_rows:
            s = r.get("straggler")
            if s is not None:
                stragglers[f"r{s}"] = stragglers.get(f"r{s}", 0) + 1
        fleet_skew = {
            "steps": len(fleet_skew_rows),
            "max_skew_ms": max(skews) if skews else None,
            "mean_skew_ms": _mean(skews),
            "stragglers": stragglers,
        }
    fleet_incidents: dict[str, int] = {}
    for r in records:
        if r.get("kind") == "fleet_incident" and isinstance(
            r.get("event"), str
        ):
            fleet_incidents[r["event"]] = (
                fleet_incidents.get(r["event"], 0) + 1
            )
    # graftmem rows (analysis/trace/memory.py memory_report.json,
    # flattened by load_records): the compiled per-device HBM ledger of
    # each registered entrypoint, latest record per entry wins.
    memory: dict[str, dict[str, Any]] = {}
    for r in records:
        if r.get("kind") == "memory_ledger" and isinstance(
            r.get("entry"), str
        ):
            memory[r["entry"]] = {
                k: r.get(k)
                for k in ("devices", "argument_bytes", "output_bytes",
                          "temp_bytes", "total_bytes", "alias_saved_bytes",
                          "dropped_donation_bytes", "replicated_leaves")
            }
    fleet_summaries = [r for r in records if r.get("kind") == "fleet_summary"]
    fleet_summary = (
        {
            k: fleet_summaries[-1].get(k)
            for k in ("generations", "ranks", "steps_attributed",
                      "max_skew_ms", "problems", "torn_lines")
        }
        if fleet_summaries
        else None
    )
    # Chaos visibility (docs/reliability.md): per-request kind:"serve"
    # lifecycle events — preemption replays and kill/resume recoveries
    # (serve/engine.py emits one record per transition).
    serve_events = [r for r in records if r.get("kind") == "serve"]
    preempt_replays = sum(
        1 for r in serve_events if r.get("event") == "preempt"
    )
    recovered = sum(
        1 for r in serve_events if r.get("event") == "recovered"
    )
    return {
        "records": len(records),
        "step_records": len(steps),
        "step_range": (
            (steps[0].get("step"), steps[-1].get("step")) if steps else None
        ),
        "mean_step_time_s": _mean(times),
        "final_loss": losses[-1] if losses else None,
        "best_loss": min(losses) if losses else None,
        "mean_mfu": _mean(mfus),
        "total_grad_sync_bytes": sum(wire) if wire else None,
        "events": sorted({e.get("event") for e in events}),
        "chaos_events": chaos_events,
        "phases": phases,
        "sync_exposed_ms": sync_exposed[-1] if sync_exposed else None,
        "sync_compare": sync_compare,
        "serve": serve,
        "serve_shed": serve_shed,
        "serve_shed_terminal": shed_terminal,
        "serve_windows": serve_windows,
        "serve_decode_host_exposed_ms": (
            host_exposed[-1] if host_exposed else None
        ),
        "serve_preempt_replays": preempt_replays,
        "serve_recovered": recovered,
        "fleet_skew": fleet_skew,
        "fleet_incidents": fleet_incidents,
        "fleet_summary": fleet_summary,
        "memory": memory,
    }


def _fmt(v: Any) -> str:
    if v is None:
        return "-"
    if isinstance(v, float):
        return f"{v:.6g}"
    return str(v)


def main(argv: list[str] | None = None) -> int:
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("jsonl", help="path to a metrics.jsonl")
    p.add_argument("--json", action="store_true",
                   help="print the summary as one JSON object instead")
    args = p.parse_args(argv)
    summary = summarize(load_records(args.jsonl))
    if args.json:
        print(json.dumps(summary))
        return 0
    rows = [
        ("records", summary["records"]),
        ("step records", summary["step_records"]),
        ("step range", summary["step_range"]),
        ("mean step time (s)", summary["mean_step_time_s"]),
        ("final loss", summary["final_loss"]),
        ("best loss", summary["best_loss"]),
        ("mean MFU", summary["mean_mfu"]),
        ("grad sync bytes (total)", summary["total_grad_sync_bytes"]),
        ("events", ", ".join(summary["events"]) or None),
    ]
    for name, row in summary["chaos_events"].items():
        by = f" ({', '.join(row['by'])})" if row["by"] else ""
        tail = row.get("traceback_tail")
        tail = f" — {tail}" if tail else ""
        rows.append((f"chaos {name}", f"{row['count']}{by}{tail}"))
    for name, row in summary["phases"].items():
        rows.append((
            f"phase {name}",
            f"{_fmt(row['ms'])} ms ({_fmt(row['clock'])}), "
            f"{_fmt(row['flops'])} flops, {_fmt(row['comm_bytes'])} comm B, "
            f"{_fmt(row['roofline'])}",
        ))
    if summary["sync_exposed_ms"] is not None:
        rows.append(("sync exposed (ms)", summary["sync_exposed_ms"]))
    for label, row in summary["serve"].items():
        occ = row.get("slot_occupancy")
        recovered = row.get("recovered_requests")
        # Terminal-status accounting (serve/guard.py): shown whenever
        # any request ended other than plain-completed.
        statuses = ""
        if row.get("rejected") or row.get("timed_out") or row.get("recovered"):
            statuses = (
                f", done/shed/expired/recovered "
                f"{_fmt(row.get('completed'))}/{_fmt(row.get('rejected'))}/"
                f"{_fmt(row.get('timed_out'))}/{_fmt(row.get('recovered'))}"
            )
        restarts = row.get("restarts")
        rows.append((
            f"serve {label}",
            f"{_fmt(row['requests'])} reqs, TTFT p50/p99 "
            f"{_fmt(row['ttft_p50_ms'])}/{_fmt(row['ttft_p99_ms'])} ms, "
            f"ITL p50/p99 "
            f"{_fmt(row.get('itl_p50_ms'))}/{_fmt(row.get('itl_p99_ms'))} ms, "
            f"{_fmt(row['tokens_per_sec'])} tok/s, pages hw "
            f"{_fmt(row.get('page_high_water'))}, occupancy "
            f"{_fmt(round(occ, 3) if isinstance(occ, float) else occ)}"
            + (f", recovered {_fmt(recovered)}" if recovered else "")
            + statuses
            + (f", restarts {_fmt(restarts)}" if restarts else ""),
        ))
    if summary["serve_shed"]:
        by_reason = ", ".join(
            f"{k}={v}" for k, v in sorted(summary["serve_shed"].items())
        )
        rows.append((
            "serve shed",
            f"{by_reason} ({summary['serve_shed_terminal']} terminal)",
        ))
    sw = summary["serve_windows"]
    if sw:
        rows.append((
            "serve windows",
            f"{_fmt(sw['count'])} over {_fmt(sw['span_s'])} s, TTFT p99 "
            f"last/max {_fmt(sw['ttft_p99_ms_last'])}/"
            f"{_fmt(sw['ttft_p99_ms_max'])} ms, ITL p99 last/max "
            f"{_fmt(sw['itl_p99_ms_last'])}/{_fmt(sw['itl_p99_ms_max'])} ms, "
            f"pages peak {_fmt(sw['live_pages_peak'])}, queue max "
            f"{_fmt(sw['queue_depth_max'])}, preempt/s max "
            f"{_fmt(sw['preempt_rate_per_s_max'])}",
        ))
    if summary["serve_decode_host_exposed_ms"] is not None:
        rows.append((
            "serve decode host exposed (ms)",
            summary["serve_decode_host_exposed_ms"],
        ))
    if summary["serve_preempt_replays"] or summary["serve_recovered"]:
        rows.append((
            "serve chaos",
            f"{summary['serve_preempt_replays']} preemption replays, "
            f"{summary['serve_recovered']} recovered requests",
        ))
    fs = summary["fleet_summary"]
    if fs:
        rows.append((
            "fleet",
            f"generations {', '.join(f'g{g}' for g in fs['generations'] or [])}"
            f", ranks {', '.join(f'r{r}' for r in fs['ranks'] or [])}, "
            f"{_fmt(fs['steps_attributed'])} steps attributed, max skew "
            f"{_fmt(fs['max_skew_ms'])} ms, {_fmt(fs['problems'])} audit "
            f"problem(s), {_fmt(fs['torn_lines'])} torn line(s)",
        ))
    fsk = summary["fleet_skew"]
    if fsk:
        hist = ", ".join(
            f"{k}={v}" for k, v in sorted(fsk["stragglers"].items())
        )
        rows.append((
            "fleet skew",
            f"{_fmt(fsk['steps'])} post-warmup steps, mean/max "
            f"{_fmt(fsk['mean_skew_ms'])}/{_fmt(fsk['max_skew_ms'])} ms, "
            f"stragglers {hist or '-'}",
        ))
    if summary["fleet_incidents"]:
        by_event = ", ".join(
            f"{k}={v}" for k, v in sorted(summary["fleet_incidents"].items())
        )
        rows.append(("fleet incidents", by_event))
    for entry, row in summary["memory"].items():
        repl = row.get("replicated_leaves")
        rows.append((
            f"hbm {entry}",
            f"{_fmt(row['total_bytes'])} B/device "
            f"(arg {_fmt(row['argument_bytes'])}, out "
            f"{_fmt(row['output_bytes'])}, temp {_fmt(row['temp_bytes'])}) "
            f"on {_fmt(row['devices'])} dev, alias saved "
            f"{_fmt(row['alias_saved_bytes'])} B, dropped donation "
            f"{_fmt(row['dropped_donation_bytes'])} B"
            + (f", {repl} REPLICATED leaf(s)" if repl else ""),
        ))
    for wire, row in summary["sync_compare"].items():
        rows.append((
            f"overlap {wire}",
            f"step {_fmt(row['fused_step_ms'])} -> "
            f"{_fmt(row['overlap_step_ms'])} ms, sync exposed "
            f"{_fmt(row['sync_exposed_ms_fused'])} -> "
            f"{_fmt(row['sync_exposed_ms_overlap'])} ms "
            f"({_fmt(row['sync_overlap'])})",
        ))
    width = max(len(name) for name, _ in rows)
    for name, val in rows:
        print(f"{name:<{width}}  {_fmt(val)}")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
