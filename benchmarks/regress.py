"""Perf-regression gate: compare bench output against a baseline window.

The repo's throughput history lives in checked-in bench envelopes
(``BENCH_r01.json`` .. at the repo root, each holding the run's parsed
headline record) and in ``kind="bench"`` records on telemetry JSONL
streams (``bench.py --metrics-dir``). This gate reads EITHER format on
either side, takes the **median of the last ``--window`` baseline
values** (median, not mean: one noisy CI run must not move the bar),
and fails when the current value drops more than ``--tolerance`` below
it. When BOTH sides carry graftscope ``phase_summary`` records, the
``sync_exposed_ms`` metric is gated too (higher-is-worse, its own
tolerance) — so a sync-overlap win (ROADMAP item 2), once landed,
cannot silently regress. Independently, any baseline record carrying
``sync_exposed_budget_ms`` (the checked-in
``benchmarks/perf_smoke_budget.json`` envelope) arms an ABSOLUTE
ceiling on the current stream's sync_exposed_ms — the on-by-default CI
gate for the overlapped bucket schedule (``--sync-overlap``).

Exit codes: 0 pass, 1 regression, 2 missing/unusable data (a gate that
can't find its numbers must fail loudly, not pass vacuously).

CLI::

    python benchmarks/regress.py --current run/metrics.jsonl \\
        [--baseline BENCH_r0*.json] [--metric NAME] [--tolerance 0.10]
"""

from __future__ import annotations

import argparse
import glob
import json
import os
import statistics
import sys
from typing import Any

DEFAULT_METRIC = "cifar10_resnet18_train_samples_per_sec_per_chip"
DEFAULT_TOLERANCE = 0.10
DEFAULT_WINDOW = 5

PASS, REGRESSION, MISSING = 0, 1, 2


def load_records(path: str) -> list[dict[str, Any]]:
    """Records from one file, either format:

    - JSONL telemetry stream: one record per line (non-dict lines skipped)
    - bench envelope (``BENCH_rNN.json``): a single JSON object whose
      ``parsed`` field is the headline record (driver format) — or any
      single JSON object/array of records
    """
    with open(path) as f:
        text = f.read()
    records: list[dict[str, Any]] = []
    try:
        obj = json.loads(text)
    except json.JSONDecodeError:
        obj = None
    if obj is not None:
        if isinstance(obj, list):
            records = [r for r in obj if isinstance(r, dict)]
        elif isinstance(obj, dict):
            # Driver envelope: the record of interest rides in "parsed".
            parsed = obj.get("parsed")
            records = [parsed] if isinstance(parsed, dict) else [obj]
        return records
    for line in text.splitlines():
        line = line.strip()
        if not line:
            continue
        try:
            rec = json.loads(line)
        except json.JSONDecodeError:
            continue
        if isinstance(rec, dict):
            records.append(rec)
    return records


def metric_values(records: list[dict[str, Any]], metric: str) -> list[float]:
    """Values of ``metric`` in stream order. A record counts if its
    ``metric`` field matches and it carries a numeric ``value`` —
    ``kind`` is not required, so bare envelope records qualify too."""
    vals = []
    for r in records:
        if r.get("metric") == metric and isinstance(
            r.get("value"), (int, float)
        ):
            vals.append(float(r["value"]))
    return vals


def sync_exposed_values(records: list[dict[str, Any]]) -> list[float]:
    vals = []
    for r in records:
        if r.get("kind") == "phase_summary" and isinstance(
            r.get("sync_exposed_ms"), (int, float)
        ):
            vals.append(float(r["sync_exposed_ms"]))
    return vals


def generic_budgets(records: list[dict[str, Any]]) -> list[dict[str, Any]]:
    """Generic absolute gates armed by baseline records of the form
    ``{"metric": NAME, "budget": V, "direction": "max"|"min"}`` (the
    checked-in ``benchmarks/serve_smoke_budget.json`` idiom). Direction
    "max" (default) means the current value must stay <= budget (a
    latency ceiling, e.g. serve p99 TTFT); "min" means >= budget (a
    throughput floor). Last record per metric wins."""
    budgets: dict[str, dict[str, Any]] = {}
    for r in records:
        if isinstance(r.get("metric"), str) and isinstance(
            r.get("budget"), (int, float)
        ):
            budgets[r["metric"]] = {
                "metric": r["metric"],
                "budget": float(r["budget"]),
                "direction": r.get("direction", "max"),
            }
    return list(budgets.values())


def sync_exposed_budget(records: list[dict[str, Any]]) -> float | None:
    """Absolute sync_exposed_ms ceiling carried by the baseline side.

    A checked-in budget envelope (``benchmarks/perf_smoke_budget.json``)
    carries ``sync_exposed_budget_ms``; its presence among the baseline
    records ARMS the budget gate — no extra CLI flag needed, so the CI
    perf-smoke job gates sync_exposed_ms by default. Last value wins."""
    budget = None
    for r in records:
        if isinstance(r.get("sync_exposed_budget_ms"), (int, float)):
            budget = float(r["sync_exposed_budget_ms"])
    return budget


def evaluate(
    baseline_records: list[dict[str, Any]],
    current_records: list[dict[str, Any]],
    *,
    metric: str = DEFAULT_METRIC,
    tolerance: float = DEFAULT_TOLERANCE,
    window: int = DEFAULT_WINDOW,
    phase_tolerance: float | None = None,
) -> tuple[int, dict[str, Any]]:
    """(exit_code, verdict). Pure — the CLI is I/O around this.

    Throughput gate: current >= median(last ``window`` baseline values)
    * (1 - tolerance). Phase gate (only when BOTH sides have
    ``phase_summary`` records and ``phase_tolerance`` is not None):
    current sync_exposed_ms <= baseline * (1 + phase_tolerance), with a
    0.5 ms absolute grace so a ~0 baseline doesn't make noise a failure.
    """
    base_vals = metric_values(baseline_records, metric)
    cur_vals = metric_values(current_records, metric)
    verdict: dict[str, Any] = {"metric": metric, "tolerance": tolerance}
    if not base_vals:
        verdict["error"] = f"no baseline values for metric {metric!r}"
        return MISSING, verdict
    if not cur_vals:
        verdict["error"] = f"no current values for metric {metric!r}"
        return MISSING, verdict
    base = statistics.median(base_vals[-window:])
    cur = cur_vals[-1]
    floor = base * (1.0 - tolerance)
    verdict.update(
        baseline=base,
        baseline_n=len(base_vals[-window:]),
        current=cur,
        floor=floor,
        ratio=cur / base if base else None,
        throughput_ok=cur >= floor,
    )
    code = PASS if verdict["throughput_ok"] else REGRESSION

    if phase_tolerance is not None:
        base_sync = sync_exposed_values(baseline_records)
        cur_sync = sync_exposed_values(current_records)
        if base_sync and cur_sync:
            b = statistics.median(base_sync[-window:])
            c = cur_sync[-1]
            ceil = b * (1.0 + phase_tolerance) + 0.5
            verdict.update(
                sync_exposed_baseline_ms=b,
                sync_exposed_current_ms=c,
                sync_exposed_ceiling_ms=ceil,
                sync_exposed_ok=c <= ceil,
            )
            if not verdict["sync_exposed_ok"]:
                code = REGRESSION

    budget = sync_exposed_budget(baseline_records)
    if budget is not None:
        cur_sync = sync_exposed_values(current_records)
        if not cur_sync:
            # An armed budget with nothing to gate is missing data, not
            # a pass — the CI stream must carry phase_summary records.
            verdict["error"] = (
                "sync_exposed_budget_ms armed but the current stream has "
                "no phase_summary records"
            )
            return MISSING, verdict
        c = cur_sync[-1]
        verdict.update(
            sync_exposed_budget_ms=budget,
            sync_exposed_current_ms=c,
            sync_budget_ok=c <= budget,
        )
        if not verdict["sync_budget_ok"]:
            code = REGRESSION

    checks = []
    for bgt in generic_budgets(baseline_records):
        vals = metric_values(current_records, bgt["metric"])
        if not vals:
            verdict["error"] = (
                f"budget armed for metric {bgt['metric']!r} but the "
                "current stream has no values for it"
            )
            return MISSING, verdict
        cur_v = vals[-1]
        ok = (
            cur_v >= bgt["budget"]
            if bgt["direction"] == "min"
            else cur_v <= bgt["budget"]
        )
        checks.append({**bgt, "current": cur_v, "ok": ok})
        if not ok:
            code = REGRESSION
    if checks:
        verdict["budgets"] = checks
    return code, verdict


def _default_baselines() -> list[str]:
    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    return sorted(glob.glob(os.path.join(root, "BENCH_r*.json")))


def main(argv: list[str] | None = None) -> int:
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument(
        "--current", required=True,
        help="bench output to gate: a metrics.jsonl stream or envelope JSON",
    )
    p.add_argument(
        "--baseline", nargs="*", default=None,
        help="baseline file(s); default: the checked-in BENCH_r*.json "
        "envelopes at the repo root",
    )
    p.add_argument("--metric", default=DEFAULT_METRIC)
    p.add_argument("--tolerance", type=float, default=DEFAULT_TOLERANCE)
    p.add_argument(
        "--window", type=int, default=DEFAULT_WINDOW,
        help="median over the last N baseline values (default %(default)s)",
    )
    p.add_argument(
        "--phase-tolerance", type=float, default=None,
        help="also gate sync_exposed_ms (phase_summary records) within "
        "this relative headroom; off by default",
    )
    p.add_argument("--json", action="store_true", help="print the verdict as JSON")
    args = p.parse_args(argv)

    baseline_paths = (
        args.baseline if args.baseline else _default_baselines()
    )
    if not baseline_paths:
        print("regress: no baseline files found", file=sys.stderr)
        return MISSING
    baseline_records: list[dict[str, Any]] = []
    for path in baseline_paths:
        baseline_records.extend(load_records(path))
    current_records = load_records(args.current)

    code, verdict = evaluate(
        baseline_records,
        current_records,
        metric=args.metric,
        tolerance=args.tolerance,
        window=args.window,
        phase_tolerance=args.phase_tolerance,
    )
    if args.json:
        print(json.dumps(verdict, indent=1))
    elif "error" in verdict:
        print(f"regress: {verdict['error']}", file=sys.stderr)
    else:
        status = "PASS" if code == PASS else "FAIL"
        print(
            f"regress [{status}] {verdict['metric']}: current "
            f"{verdict['current']:.1f} vs baseline {verdict['baseline']:.1f} "
            f"(floor {verdict['floor']:.1f}, ratio {verdict['ratio']:.3f})"
        )
        if "sync_exposed_ok" in verdict:
            print(
                f"regress [{'PASS' if verdict['sync_exposed_ok'] else 'FAIL'}] "
                f"sync_exposed_ms: current "
                f"{verdict['sync_exposed_current_ms']:.3f} vs baseline "
                f"{verdict['sync_exposed_baseline_ms']:.3f} (ceiling "
                f"{verdict['sync_exposed_ceiling_ms']:.3f})"
            )
        if "sync_budget_ok" in verdict:
            print(
                f"regress [{'PASS' if verdict['sync_budget_ok'] else 'FAIL'}] "
                f"sync_exposed_ms budget: current "
                f"{verdict['sync_exposed_current_ms']:.3f} vs budget "
                f"{verdict['sync_exposed_budget_ms']:.3f}"
            )
        for bgt in verdict.get("budgets", []):
            cmp_ = ">=" if bgt["direction"] == "min" else "<="
            print(
                f"regress [{'PASS' if bgt['ok'] else 'FAIL'}] "
                f"{bgt['metric']} budget: current {bgt['current']:.3f} "
                f"{cmp_} {bgt['budget']:.3f}"
            )
    return code


if __name__ == "__main__":
    sys.exit(main())
