"""GPT-2-large-class + long-context medium points (round 5, VERDICT
r4 #7) — the next perf rungs past the 24L/1024d MFU-0.510 point.

Two regimes on one v5e chip, bf16 + RoPE + Pallas flash, MFU accounting
identical to bench_lm_gpt2.py / probe_gpt2_medium.py (2*MACs,
3x-forward train, remat recompute NOT counted, causal masking not
discounted — flash MFU is understated):

1. **large**: 36L / 1280d / 20h / d_ff 5120 / T=1024 / vocab 50304
   (~770M params). f32 params ~3.1 GB + f32 adam moments ~6.2 GB leave
   ~6 GB for activations on the 16 GB chip — remat and small batches
   are load-bearing here, not optional. The tunnel's remote compile
   helper walls at total program footprint (12L b32 and 24L b16 both
   HTTP-500'd), so the sweep leads with scan_layers variants (the
   ~4.3%-at-24L compile-scalability trade measured round 4; expected
   to amortize further at 36L).
2. **medium-T2048**: 24L / 1024d at T=2048 — the long-context regime
   where flash and remat matter more (attention is 2*S*D of the
   per-layer FLOPs: 17% at T=2048/1024d vs 9% at T=1024).

Measured 2026-08-01 (one TPU v5e chip through the tunnel; wall-clock
over STEPS after warmup):

  medium-T2048 unroll+nomat b4   226.3 ms  36.2k tok/s  MFU 0.5006
  medium-T2048 b8 (unroll/scan x nomat/dots): remote-compile HTTP 500
  large scan+dots  b1   114.2 ms   9.0k tok/s  MFU 0.237
  large scan+dots  b2   160.8 ms  12.7k tok/s  MFU 0.336
  large scan+dots  b3:  remote-compile HTTP 500
  large scan+nomat b2:  remote-compile HTTP 500
  large b4..b16, unroll b8 (every variant): remote-compile HTTP 500

Findings:
- **Context doubles at constant MFU**: medium at T=2048/b4 (the same
  8192 tokens/step as the T=1024/b8 row) lands at 0.5006 vs 0.510 —
  the flash path's S-scaling costs ~2% MFU, and the long-context
  regime keeps the 1024d efficiency. The b8/T2048 point that would
  test for a 0.52+ peak is COMPILE-WALLED (below); the late-round-5
  session filled the gap from the compiling side: b5 = 0.4847,
  b6 = 0.4678 — MFU DEGRADES monotonically past b4 (T=2048 remat-off
  activations push the working set into a worse HBM regime well
  before the wall), so **b4/0.5006 is a measured local optimum**,
  not a truncated curve, and the 0.52+ hope is dead on this chip
  regardless of the compile helper.
- **The compile-helper wall boundary is now pinned from both sides**:
  medium-T2048 compiles at b4 and walls at b8 (= the b16/T1024
  footprint that walled round 4); large compiles at scan+dots b2 and
  walls at b3-dots AND b2-nomat. The wall tracks TOTAL footprint
  (activations + 9.3 GB of large's persistent f32 params+moments),
  not traced-program size — scan_layers (12x smaller program) moves
  it not at all at 36L.
- **GPT-2-large through this tunnel is therefore activation-starved**:
  the only compiling configs (b1/b2 + dots recompute) underfill the
  MXU (0.237/0.336) exactly as small batches always do. The d-model
  trend (0.454@768d -> 0.510@1024d) predicts >=0.51 for 1280d at b8
  remat-off on direct-attached hardware; through this tunnel that
  remains a prediction — recorded with the probe boundary as evidence,
  the same class as the round-4 b32 wall.
"""

from __future__ import annotations

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax

from cs744_pytorch_distributed_tutorial_tpu.data import synthetic_tokens
from cs744_pytorch_distributed_tutorial_tpu.parallel import make_mesh
from cs744_pytorch_distributed_tutorial_tpu.train import LMConfig, LMTrainer

VOCAB = 50304
STEPS, WARMUP = 6, 4
V5E_PEAK_FLOPS = 197e12

SHAPES = {
    "large": dict(layers=36, d_model=1280, heads=20, d_ff=5120, seq=1024),
    "medium-T2048": dict(layers=24, d_model=1024, heads=16, d_ff=4096,
                         seq=2048),
}


def flops_per_token(layers, d_model, d_ff, seq) -> float:
    per_layer = 4 * d_model**2 + 2 * d_model * d_ff + 2 * seq * d_model
    return 3.0 * (layers * 2.0 * per_layer + 2.0 * d_model * VOCAB)


def run(shape: str, batch: int, scan_layers: bool, remat: bool) -> None:
    sh = SHAPES[shape]
    label = (
        f"{shape}-{'scan' if scan_layers else 'unroll'}-"
        f"{'dots' if remat else 'nomat'}-b{batch}"
    )
    try:
        cfg = LMConfig(
            vocab_size=VOCAB, num_layers=sh["layers"], num_heads=sh["heads"],
            d_model=sh["d_model"], d_ff=sh["d_ff"], max_seq_len=sh["seq"],
            seq_len=sh["seq"], global_batch_size=batch,
            attention_impl="flash", compute_dtype="bfloat16", remat=remat,
            remat_policy="dots" if remat else "none",
            scan_layers=scan_layers, use_rope=True,
        )
        tr = LMTrainer(cfg, mesh=make_mesh({"data": 1, "seq": 1}))
        params, opt = tr.init()
        x, y = tr.shard_batch(
            synthetic_tokens(batch, sh["seq"], VOCAB, seed=0)
        )
        params, opt, m = tr.train_step(params, opt, x, y)
        float(m["loss"])
        for _ in range(WARMUP):
            params, opt, m = tr.train_step(params, opt, x, y)
        float(m["loss"])
        t0 = time.perf_counter()
        for _ in range(STEPS):
            params, opt, m = tr.train_step(params, opt, x, y)
        float(m["loss"])
        dt = (time.perf_counter() - t0) / STEPS
        tok_s = batch * sh["seq"] / dt
        fpt = flops_per_token(sh["layers"], sh["d_model"], sh["d_ff"],
                              sh["seq"])
        print(json.dumps({
            "metric": "gpt2large_train_tokens_per_sec_per_chip",
            "probe": label,
            "ms_per_step": round(dt * 1e3, 2),
            "tokens_per_sec": round(tok_s),
            "mfu": (
                round(tok_s * fpt / V5E_PEAK_FLOPS, 4)
                if jax.default_backend() != "cpu" else None
            ),
            "config": f"{sh['layers']}L/{sh['d_model']}d/{sh['heads']}h"
                      f"/T{sh['seq']}/V{VOCAB}/b{batch}/bf16"
                      f"/remat={'dots' if remat else 'off'}/rope"
                      + ("/scan" if scan_layers else ""),
        }), flush=True)
    except Exception as e:
        print(json.dumps({
            "probe": label,
            "error": f"{type(e).__name__}: {str(e)[:200]}",
        }), flush=True)


def main() -> None:
    only = sys.argv[1:] or None
    for shape, b, sc, rm in (
        ("large", 4, True, False),
        ("large", 4, True, True),
        ("large", 8, True, False),
        ("large", 8, True, True),
        ("large", 8, False, False),   # expected: compile-helper wall
        ("large", 16, True, False),
        ("large", 16, True, True),
        ("medium-T2048", 4, False, False),
        ("medium-T2048", 8, False, False),
        ("medium-T2048", 8, False, True),
    ):
        label = (
            f"{shape}-{'scan' if sc else 'unroll'}-"
            f"{'dots' if rm else 'nomat'}-b{b}"
        )
        if only and not any(o in label for o in only):
            continue
        run(shape, b, scan_layers=sc, remat=rm)


if __name__ == "__main__":
    main()
