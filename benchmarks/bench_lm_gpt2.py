"""Realistic LM benchmark: GPT-2-small-ish training with MFU.

VERDICT r2 weak #5: the 4L/512d bench_lm.py config is embedding-
dominated and can't show whether kernel wins survive depth, and
fused_xent had never been benched on-chip in training. This bench runs
a GPT-2-small-shaped model (12 layers, d_model 768, 12 heads, d_ff
3072, seq 1024, vocab 50304) in bf16 with remat on the measured path,
and ablates flash attention and the fused softmax-CE kernel each
on/off. Reports tokens/sec AND MFU (FLOPs = 2*MACs, train = 3x
forward; remat recompute NOT counted, per the standard convention — the
hardware does ~1 extra forward of block FLOPs on top).

Run on the TPU: python benchmarks/bench_lm_gpt2.py
Prints one JSON line per configuration; headline = flash + fused_xent.

Measured 2026-07-31 (one TPU v5e chip, batch 8; re-run later same day
in parens):
  dense           135.7 ms/step   60.4k tok/s  MFU 0.262  (61.0k/0.265)
  flash            84.4 ms/step   97.1k tok/s  MFU 0.421  (98.8k/0.429)
  dense+fxent     145.6 ms/step   56.3k tok/s  MFU 0.244  (56.0k/0.243)
  flash+fxent      96.2 ms/step   85.2k tok/s  MFU 0.370  (83.5k/0.362)
The flash win SURVIVES depth (1.61x at 12L vs 1.62x at 4L);
fused_xent LOSES 12-14% wall-clock in training at this vocab (also at
batch 16) — its value is the absent [N, V] log-softmax buffer when
memory binds, and its off-by-default is now measured, not assumed
(table + discussion in benchmarks/README.md).

Remat ablation (measured): at batch 8 the activations FIT without
remat, and turning it off buys the dots-policy recompute back:
  flash + remat=dots  84.5 ms/step   96.9k tok/s  MFU 0.421
  flash + remat=off   78.3 ms/step  104.6k tok/s  MFU 0.454  (+8%)
The headline when memory allows is remat=off; remat remains the
long-context/major-batch memory lever it was built as.

Batch scaling, round-4 re-measurement (the round-3 "b16 no better"
was a dots-only artifact):
  flash + remat=OFF + b16  144.9 ms/step  113.0k tok/s  MFU 0.490  <- headline
                           (first probe same day: 110.9k / 0.481)
  flash + remat=off + b20  192.9 ms/step  106.2k tok/s  MFU 0.461  (late r5)
  flash + remat=off + b24  239.7 ms/step  102.5k tok/s  MFU 0.445
                           (late-r5 re-measure: 101.4k / 0.440)
  flash + remat=dots + b16  (round 3)      94.5k tok/s  MFU 0.41
The late-round-5 b20 point pins the shape: throughput turns over
MONOTONICALLY past b16 (113.0 -> 106.2 -> 101.4k), the same
pre-compile-wall degradation medium-T2048 shows past b4 — the b16
headline is a measured local optimum, not a wall-truncated curve.
Remat does NOT rescue it (b24-dots 94.4k / 0.410 < b24-off), so the
turnover is not activation capacity; it tracks the matmul/layout
regime at those batch shapes.
Batch 32 fails the tunnel's remote compile helper (HTTP 500) in EVERY
variant tried round 4 — unrolled/scan_layers x dots/off x fused_xent
on/off. scan_layers shrinks the traced program by 12x and fused_xent
removes the 6.6 GB f32 logit buffer, so the wall is the remote compile
helper itself, not program size or planned memory: a measured
environment ceiling, not a framework one.

scan_layers on the chip (measured, negative for THIS regime): at b8
remat-off the scanned stack is 81.7k tok/s (MFU 0.354) vs 104.6k
unrolled — the layer loop costs ~22% (lost cross-layer fusion +
while-loop overhead at d768); at b16 remat=dots it is 90.3k vs 94.5k
unrolled. scan_layers' value is COMPILE scalability (24L+ configs,
probe_gpt2_medium.py) and O(L)-smaller programs, not single-chip
throughput at 12L; the bench keeps the unrolled path.

Scoped-vmem compiler option (measured, negative for the LM):
xla_tpu_scoped_vmem_limit_kib=65536 — the CIFAR bench's +7% lever —
gives 107.1k on the b16 remat-off config vs 110.9k default-compiled.
The LM step's Pallas flash kernels manage their own VMEM; the larger
scoped budget only perturbs XLA's fusion choices here.
"""

from __future__ import annotations

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax

from cs744_pytorch_distributed_tutorial_tpu.data import synthetic_tokens
from cs744_pytorch_distributed_tutorial_tpu.parallel import make_mesh
from cs744_pytorch_distributed_tutorial_tpu.train import LMConfig, LMTrainer

BATCH = 8
SEQ = 1024
LAYERS = 12
D_MODEL = 768
HEADS = 12
D_FF = 3072
VOCAB = 50304  # GPT-2's 50257 padded to a 128-lane multiple
STEPS = 12
WARMUP = 8  # the tunnel's deferred-init window (benchmarks/bench_lm.py)
V5E_PEAK_FLOPS = 197e12


def gpt2ish_train_flops_per_token() -> float:
    """Analytic model FLOPs per token for one training step.

    Per-layer forward matmuls: q/k/v/o projections (4 * d^2 MACs) + MLP
    (2 * d * d_ff) + attention score/value contractions (2 * T * d MACs
    per token, causal masking NOT discounted — flash skips masked
    blocks, so its measured MFU is conservatively understated). Plus the
    embedding-tied-scale LM head (d * V). FLOPs = 2*MACs, train = 3x
    forward (dgrad + wgrad)."""
    per_layer = 4 * D_MODEL**2 + 2 * D_MODEL * D_FF + 2 * SEQ * D_MODEL
    fwd = LAYERS * 2.0 * per_layer + 2.0 * D_MODEL * VOCAB
    return 3.0 * fwd


def bench_config(attention_impl: str, fused_xent: bool, batch: int = BATCH,
                 remat: bool = True, scan_layers: bool = False) -> dict:
    cfg = LMConfig(
        vocab_size=VOCAB,
        num_layers=LAYERS,
        num_heads=HEADS,
        d_model=D_MODEL,
        d_ff=D_FF,
        max_seq_len=SEQ,
        seq_len=SEQ,
        global_batch_size=batch,
        attention_impl=attention_impl,
        compute_dtype="bfloat16",
        remat=remat,
        remat_policy="dots" if remat else "none",
        scan_layers=scan_layers,
        use_rope=True,
        fused_xent=fused_xent,
    )
    mesh = make_mesh({"data": 1, "seq": 1})
    tr = LMTrainer(cfg, mesh=mesh)
    params, opt = tr.init()
    tokens = synthetic_tokens(batch, SEQ, VOCAB, seed=0)
    x, y = tr.shard_batch(tokens)

    params, opt, m = tr.train_step(params, opt, x, y)  # compile
    float(m["loss"])
    for _ in range(WARMUP):
        params, opt, m = tr.train_step(params, opt, x, y)
    float(m["loss"])  # fence: value fetch, not block_until_ready
    t0 = time.perf_counter()
    for _ in range(STEPS):
        params, opt, m = tr.train_step(params, opt, x, y)
    float(m["loss"])
    dt = (time.perf_counter() - t0) / STEPS
    tok_s = batch * SEQ / dt
    flops = gpt2ish_train_flops_per_token()
    return {
        "metric": "gpt2small_train_tokens_per_sec_per_chip",
        "attention_impl": attention_impl,
        "fused_xent": fused_xent,
        "ms_per_step": round(dt * 1e3, 2),
        "tokens_per_sec": round(tok_s, 0),
        "flops_per_token": flops,
        "mfu": (
            round(tok_s * flops / V5E_PEAK_FLOPS, 4)
            if jax.default_backend() != "cpu"
            else None
        ),
        "config": f"{LAYERS}L/{D_MODEL}d/{HEADS}h/T{SEQ}/V{VOCAB}"
                  f"/b{batch}/bf16/remat={'dots' if remat else 'off'}/rope"
                  + ("/scan" if scan_layers else ""),
    }


def main() -> None:
    for impl, fused in (
        ("dense", False),
        ("flash", False),
        ("dense", True),
        ("flash", True),
    ):
        print(json.dumps(bench_config(impl, fused)), flush=True)
    # Batch scaling: batch 8 under-fills the MXU on d768 matmuls; larger
    # batches raise MFU until memory binds. At batch 32 the f32 logit
    # buffer alone is ~6.6 GB — the regime fused_xent's absent [N, V]
    # log-softmax buffer targets, so it is ablated again here where its
    # memory saving (not wall-clock) is the question.
    # Remat ablation: at batch 8 the activations FIT without remat —
    # measures what the dots-policy recompute costs when memory allows
    # turning it off.
    print(json.dumps(bench_config("flash", False, BATCH, remat=False)),
          flush=True)
    # Round-4 headline: batch 16 with remat OFF (round 3 only measured
    # b16 under remat=dots and concluded "no better" — wrongly).
    for batch, fused, remat in (
        (16, False, False), (32, False, True), (32, True, True),
    ):
        try:
            print(json.dumps(bench_config("flash", fused, batch, remat=remat)),
                  flush=True)
        except Exception as e:
            print(json.dumps({
                "attention_impl": "flash", "fused_xent": fused,
                "batch": batch, "error": f"{type(e).__name__}: {str(e)[:120]}",
            }), flush=True)


if __name__ == "__main__":
    main()
