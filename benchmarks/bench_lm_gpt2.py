"""Realistic LM benchmark: GPT-2-small-ish training with MFU.

VERDICT r2 weak #5: the 4L/512d bench_lm.py config is embedding-
dominated and can't show whether kernel wins survive depth, and
fused_xent had never been benched on-chip in training. This bench runs
a GPT-2-small-shaped model (12 layers, d_model 768, 12 heads, d_ff
3072, seq 1024, vocab 50304) in bf16 with remat on the measured path,
and ablates flash attention and the fused softmax-CE kernel each
on/off. Reports tokens/sec AND MFU (FLOPs = 2*MACs, train = 3x
forward; remat recompute NOT counted, per the standard convention — the
hardware does ~1 extra forward of block FLOPs on top).

Run on the TPU: python benchmarks/bench_lm_gpt2.py
Prints one JSON line per configuration; headline = flash + fused_xent.

Measured 2026-07-31 (one TPU v5e chip, batch 8; re-run later same day
in parens):
  dense           135.7 ms/step   60.4k tok/s  MFU 0.262  (61.0k/0.265)
  flash            84.4 ms/step   97.1k tok/s  MFU 0.421  (98.8k/0.429)
  dense+fxent     145.6 ms/step   56.3k tok/s  MFU 0.244  (56.0k/0.243)
  flash+fxent      96.2 ms/step   85.2k tok/s  MFU 0.370  (83.5k/0.362)
The flash win SURVIVES depth (1.61x at 12L vs 1.62x at 4L);
fused_xent LOSES 12-14% wall-clock in training at this vocab (also at
batch 16) — its value is the absent [N, V] log-softmax buffer when
memory binds, and its off-by-default is now measured, not assumed
(table + discussion in benchmarks/README.md).

Remat ablation (measured): at batch 8 the activations FIT without
remat, and turning it off buys the dots-policy recompute back:
  flash + remat=dots  84.5 ms/step   96.9k tok/s  MFU 0.421
  flash + remat=off   78.3 ms/step  104.6k tok/s  MFU 0.454  (+8%)
The headline when memory allows is remat=off; remat remains the
long-context/major-batch memory lever it was built as.

Batch scaling (measured, negative): flash at batch 16 is 94.5k tok/s
(MFU 0.41 — no better than batch 8; the d768 matmuls are already
MXU-shaped), and batch 32 fails to compile through the tunnel's remote
compile helper (HTTP 500, both with and without fused_xent — the
regime fused_xent's memory saving targets is unreachable on this
single tunneled chip). The batch-8 headline stands.
"""

from __future__ import annotations

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax

from cs744_pytorch_distributed_tutorial_tpu.data import synthetic_tokens
from cs744_pytorch_distributed_tutorial_tpu.parallel import make_mesh
from cs744_pytorch_distributed_tutorial_tpu.train import LMConfig, LMTrainer

BATCH = 8
SEQ = 1024
LAYERS = 12
D_MODEL = 768
HEADS = 12
D_FF = 3072
VOCAB = 50304  # GPT-2's 50257 padded to a 128-lane multiple
STEPS = 12
WARMUP = 8  # the tunnel's deferred-init window (benchmarks/bench_lm.py)
V5E_PEAK_FLOPS = 197e12


def gpt2ish_train_flops_per_token() -> float:
    """Analytic model FLOPs per token for one training step.

    Per-layer forward matmuls: q/k/v/o projections (4 * d^2 MACs) + MLP
    (2 * d * d_ff) + attention score/value contractions (2 * T * d MACs
    per token, causal masking NOT discounted — flash skips masked
    blocks, so its measured MFU is conservatively understated). Plus the
    embedding-tied-scale LM head (d * V). FLOPs = 2*MACs, train = 3x
    forward (dgrad + wgrad)."""
    per_layer = 4 * D_MODEL**2 + 2 * D_MODEL * D_FF + 2 * SEQ * D_MODEL
    fwd = LAYERS * 2.0 * per_layer + 2.0 * D_MODEL * VOCAB
    return 3.0 * fwd


def bench_config(attention_impl: str, fused_xent: bool, batch: int = BATCH, remat: bool = True) -> dict:
    cfg = LMConfig(
        vocab_size=VOCAB,
        num_layers=LAYERS,
        num_heads=HEADS,
        d_model=D_MODEL,
        d_ff=D_FF,
        max_seq_len=SEQ,
        seq_len=SEQ,
        global_batch_size=batch,
        attention_impl=attention_impl,
        compute_dtype="bfloat16",
        remat=remat,
        remat_policy="dots" if remat else "none",
        use_rope=True,
        fused_xent=fused_xent,
    )
    mesh = make_mesh({"data": 1, "seq": 1})
    tr = LMTrainer(cfg, mesh=mesh)
    params, opt = tr.init()
    tokens = synthetic_tokens(batch, SEQ, VOCAB, seed=0)
    x, y = tr.shard_batch(tokens)

    params, opt, m = tr.train_step(params, opt, x, y)  # compile
    float(m["loss"])
    for _ in range(WARMUP):
        params, opt, m = tr.train_step(params, opt, x, y)
    float(m["loss"])  # fence: value fetch, not block_until_ready
    t0 = time.perf_counter()
    for _ in range(STEPS):
        params, opt, m = tr.train_step(params, opt, x, y)
    float(m["loss"])
    dt = (time.perf_counter() - t0) / STEPS
    tok_s = batch * SEQ / dt
    flops = gpt2ish_train_flops_per_token()
    return {
        "metric": "gpt2small_train_tokens_per_sec_per_chip",
        "attention_impl": attention_impl,
        "fused_xent": fused_xent,
        "ms_per_step": round(dt * 1e3, 2),
        "tokens_per_sec": round(tok_s, 0),
        "flops_per_token": flops,
        "mfu": (
            round(tok_s * flops / V5E_PEAK_FLOPS, 4)
            if jax.default_backend() != "cpu"
            else None
        ),
        "config": f"{LAYERS}L/{D_MODEL}d/{HEADS}h/T{SEQ}/V{VOCAB}"
                  f"/b{batch}/bf16/remat={'dots' if remat else 'off'}/rope",
    }


def main() -> None:
    for impl, fused in (
        ("dense", False),
        ("flash", False),
        ("dense", True),
        ("flash", True),
    ):
        print(json.dumps(bench_config(impl, fused)), flush=True)
    # Batch scaling: batch 8 under-fills the MXU on d768 matmuls; larger
    # batches raise MFU until memory binds. At batch 32 the f32 logit
    # buffer alone is ~6.6 GB — the regime fused_xent's absent [N, V]
    # log-softmax buffer targets, so it is ablated again here where its
    # memory saving (not wall-clock) is the question.
    # Remat ablation: at batch 8 the activations FIT without remat —
    # measures what the dots-policy recompute costs when memory allows
    # turning it off.
    print(json.dumps(bench_config("flash", False, BATCH, remat=False)),
          flush=True)
    for batch, fused in ((16, False), (32, False), (32, True)):
        try:
            print(json.dumps(bench_config("flash", fused, batch)), flush=True)
        except Exception as e:
            print(json.dumps({
                "attention_impl": "flash", "fused_xent": fused,
                "batch": batch, "error": f"{type(e).__name__}: {str(e)[:120]}",
            }), flush=True)


if __name__ == "__main__":
    main()
