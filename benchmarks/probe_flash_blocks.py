"""Flash-attention block-size probe at short sequence lengths.

The kernel defaults (block_q=512, block_k=1024) were tuned at T=2048
(`ops/flash_attention.py`). At T=1024 (the GPT-2 bench point) block_k
covers the WHOLE sequence, so the causal prune degenerates: the qi=0
row-block multiplies against all 1024 keys with half of them masked —
~25% of the forward MXU work is dead vs a (512, 512) tiling that stops
at the diagonal. This probes fwd and fwd+bwd wall-clock across block
choices at the GPT-2 attention shape to decide whether a per-T default
is worth carrying.

Run: python benchmarks/probe_flash_blocks.py

MEASURED (round 3, one v5e): a dead end, kept as the record. Isolated
kernel timings at these shapes are dominated by per-call overhead
(~4-6 ms against ~0.2 ms of actual per-layer attention compute), and the
config-to-config deltas (±1 ms) do not replicate the causal-prune
arithmetic — they are overhead noise. The decisive argument is upstream:
at T=1024 causal attention is ~0.6% of a GPT-2-small training step's
FLOPs (38 GF of 6.1 TF), so no block tuning can move the step; the
1.6x flash-vs-dense win was about not materializing [B,H,T,T] scores
through HBM, not attention FLOPs. Block defaults stay (512, 1024).
"""

from __future__ import annotations

import os
import sys
import time
from functools import partial

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp

from cs744_pytorch_distributed_tutorial_tpu.ops.flash_attention import (
    flash_attention,
)

B, H, D = 8, 12, 64
REPEATS = 30


def bench(fn, *args) -> float:
    out = fn(*args)
    jax.tree.leaves(out)[0].block_until_ready()
    float(jax.tree.leaves(out)[0].ravel()[0])  # fence (see bench.py)
    t0 = time.perf_counter()
    for _ in range(REPEATS):
        out = fn(*args)
    float(jax.tree.leaves(out)[0].ravel()[0])
    return (time.perf_counter() - t0) / REPEATS * 1e3


def main() -> None:
    for t in (1024, 2048):
        k1, k2, k3 = jax.random.split(jax.random.key(0), 3)
        q = jax.random.normal(k1, (B, t, H, D), jnp.bfloat16)
        k = jax.random.normal(k2, (B, t, H, D), jnp.bfloat16)
        v = jax.random.normal(k3, (B, t, H, D), jnp.bfloat16)
        print(f"T={t}  [B={B}, H={H}, D={D}] bf16 causal")
        for bq, bk in ((512, 1024), (512, 512), (256, 512), (512, 256), (256, 256), (1024, 512)):
            if bq > t or bk > t:
                continue
            # graftlint: disable=GL002 -- each (bq, bk) is a distinct
            # trace by construction; a per-config wrapper is the sweep.
            fwd = jax.jit(
                partial(flash_attention, causal=True, block_q=bq, block_k=bk)
            )

            def loss(q, k, v, f=fwd):
                return f(q, k, v).astype(jnp.float32).sum()

            grad = jax.jit(jax.grad(loss, argnums=(0, 1, 2)))  # graftlint: disable=GL002 -- per-config sweep
            ms_f = bench(fwd, q, k, v)
            ms_g = bench(grad, q, k, v)
            print(
                f"  block_q={bq:5d} block_k={bk:5d}  fwd {ms_f:7.2f} ms   "
                f"fwd+bwd {ms_g:7.2f} ms"
            )


if __name__ == "__main__":
    main()
