"""Round-3 probe: H-pair-packed fwd conv kernel for stage-1 shapes.

The scored step's stem+stage1 region runs at ~35% MFU while the rest of
the net runs at ~86% (benchmarks/breakdown_r3.py). The structural cause:
every stage-1 matmul has a 64-wide output dim, half-filling the MXU's
128 lanes. This kernel packs TWO output rows (h even/odd pair) into one
128-wide output:

    lhs  [B*16*32, 12C=768]  (4 input rows x 3 col-shifts im2col)
    rhs  [768, 128]          (w packed: cols 0:64 even row, 64:128 odd)
    out  [B*16*32, 128]      -> unpack to rows 2m / 2m+1

Useful-MAC ratio 9/12 = 75%, but full K (768 = 6 tiles) and full N
(128) — against the 50% lane ceiling of the naive [*, 576] @ [576, 64]
form. The H-pair view [B, 16, 64, 64] is a FREE reshape of NHWC
[B, 32, 32, 64] (row-major compatible), so both pallas boundaries stay
bitcasts.

Measures the kernel isolated vs XLA's in-step fused conv+stats
(fusion.6-class ops, ~3.5 ms at batch 4096). Kill threshold from the
round-3 plan: >= 3.2 ms means the owned-subgraph route cannot reach
40k sps and the ablation gets written instead.
"""

from __future__ import annotations

import functools
import sys
import os
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.experimental import pallas as pl


def _shift_w3(t: jnp.ndarray, d: int) -> jnp.ndarray:
    """W shift on a [bb, W, C] plane (dim 1), zero at the borders."""
    if d == 1:
        return jnp.concatenate([t[:, 1:], t[:, :1] * 0], axis=1)
    if d == -1:
        return jnp.concatenate([t[:, :1] * 0, t[:, :-1]], axis=1)
    return t


def _fwd_kernel(x_ref, w_ref, o_ref):
    """x_ref [bb, 16, 64, C] paired view; w_ref [12C, 128] packed;
    o_ref [bb, 16, 64, K=C]. Inner fori over the 16 h-pairs keeps the
    per-pair im2col [bb*32, 768] in VMEM budget (the whole-block
    variant spilled 81 MB of vregs)."""
    bb, h2, w2, c = x_ref.shape
    w = w2 // 2
    wmat = w_ref[...]

    def pair(m, _):
        pm1 = x_ref[:, pl.dslice(jnp.maximum(m - 1, 0), 1)][:, 0]
        p0 = x_ref[:, pl.dslice(m, 1)][:, 0]
        pp1 = x_ref[:, pl.dslice(jnp.minimum(m + 1, h2 - 1), 1)][:, 0]
        # Row planes for outputs (2m, 2m+1): input rows 2m-1 .. 2m+2.
        r0 = jnp.where(m > 0, pm1[:, w:, :], 0)   # row 2m-1
        r1 = p0[:, :w, :]                         # row 2m
        r2 = p0[:, w:, :]                         # row 2m+1
        r3 = jnp.where(m < h2 - 1, pp1[:, :w, :], 0)  # row 2m+2
        taps = [
            _shift_w3(r, dx)
            for r in (r0, r1, r2, r3)
            for dx in (-1, 0, 1)
        ]
        lhs = jnp.concatenate(taps, axis=-1).reshape(bb * w, 12 * c)
        out = lax.dot_general(
            lhs, wmat, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        out = out.reshape(bb, w, 2 * c).astype(o_ref.dtype)
        # Even rows live in lanes 0:C, odd in C:2C; two stores place
        # them in the paired-view sublane halves (reshape, not None
        # broadcast — the latter lowers as an unsupported gather).
        o_ref[:, pl.dslice(m, 1), :w] = out[:, :, :c].reshape(bb, 1, w, c)
        o_ref[:, pl.dslice(m, 1), w:] = out[:, :, c:].reshape(bb, 1, w, c)
        return 0

    lax.fori_loop(0, h2, pair, 0)


def pack_weights(wk: jnp.ndarray) -> jnp.ndarray:
    """[3, 3, C, K] -> [12C, 2K]: tap (r_off, dx) rows; cols 0:K = even
    output row (ky = r_off), K:2K = odd (ky = r_off - 1)."""
    k3, _, c, k = wk.shape
    wp = np.zeros((4, 3, c, 2 * k), np.float32)
    wnp = np.asarray(wk, np.float32)
    for r_off in range(4):
        for dx in range(3):
            if r_off < 3:
                wp[r_off, dx, :, :k] = wnp[r_off, dx]
            if r_off >= 1:
                wp[r_off, dx, :, k:] = wnp[r_off - 1, dx]
    return jnp.asarray(wp.reshape(12 * c, 2 * k), jnp.bfloat16)


@functools.partial(jax.jit, static_argnames=("block_batch", "interpret"))
def conv3x3_fwd_hpair(
    x: jax.Array,
    w_packed: jax.Array,
    *,
    block_batch: int = 128,
    interpret: bool = False,
) -> jax.Array:
    b, h, w, c = x.shape
    xp = x.reshape(b, h // 2, 2 * w, c)  # free: row-major compatible
    bb = block_batch
    grid = (b // bb,)
    out = pl.pallas_call(
        _fwd_kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((bb, h // 2, 2 * w, c), lambda i: (i, 0, 0, 0)),
            pl.BlockSpec((12 * c, 2 * c), lambda i: (0, 0)),
        ],
        out_specs=pl.BlockSpec((bb, h // 2, 2 * w, c), lambda i: (i, 0, 0, 0)),
        out_shape=jax.ShapeDtypeStruct((b, h // 2, 2 * w, c), x.dtype),
        interpret=interpret,
    )(xp, w_packed)
    return out.reshape(b, h, w, c)


def main() -> None:
    on_tpu = jax.default_backend() not in ("cpu",)
    B, H, W, C = (4096, 32, 32, 64) if on_tpu else (16, 32, 32, 64)
    key = jax.random.key(0)
    kx, kw = jax.random.split(key)
    x = jax.random.normal(kx, (B, H, W, C), jnp.bfloat16)
    wk = jax.random.normal(kw, (3, 3, C, C), jnp.float32) * 0.1
    wp = pack_weights(wk)

    # Correctness vs XLA conv.
    ref_fn = jax.jit(
        lambda xv, wv: lax.conv_general_dilated(
            xv, wv.astype(jnp.bfloat16), (1, 1), "SAME",
            dimension_numbers=("NHWC", "HWIO", "NHWC"),
        )
    )
    ref = ref_fn(x, wk)
    if on_tpu:
        got = (
            jax.jit(functools.partial(conv3x3_fwd_hpair, block_batch=32))
            .lower(x, wp)
            .compile(
                compiler_options={"xla_tpu_scoped_vmem_limit_kib": "98304"}
            )(x, wp)
        )
    else:
        got = conv3x3_fwd_hpair(
            x, wp, block_batch=min(B, 32), interpret=True
        )
    err = float(
        jnp.max(jnp.abs(got.astype(jnp.float32) - ref.astype(jnp.float32)))
    )
    scale = float(jnp.max(jnp.abs(ref.astype(jnp.float32)))) or 1.0
    print(f"max abs err: {err:.4f} (rel {err / scale:.5f})")
    assert err / scale < 5e-2, "numerics mismatch"
    if not on_tpu:
        print("CPU interpret mode: numerics only, no timing")
        return

    def bench(fn, *args):
        out = fn(*args)
        float(jnp.asarray(out).astype(jnp.float32).ravel()[0])
        t0 = time.perf_counter()
        for _ in range(20):
            out = fn(*args)
        float(jnp.asarray(out).astype(jnp.float32).ravel()[0])
        return (time.perf_counter() - t0) / 20 * 1e3

    for blk in (16, 32, 64, 128):
        try:
            fn = (
                # graftlint: disable=GL002 -- one compile per block_batch
                # IS the probe; nothing to hoist.
                jax.jit(
                    functools.partial(conv3x3_fwd_hpair, block_batch=blk)
                )
                .lower(x, wp)
                .compile(
                    compiler_options={
                        "xla_tpu_scoped_vmem_limit_kib": "98304"
                    }
                )
            )
            t = bench(fn, x, wp)
            print(f"hpair fwd  blk={blk}: {t:7.3f} ms")
        except Exception as ex:
            print(f"hpair fwd  blk={blk}: FAILED {str(ex)[:100]}")
    t = bench(ref_fn, x, wk)
    print(f"XLA conv isolated:  {t:7.3f} ms")


if __name__ == "__main__":
    main()
