"""End-to-end input pipeline at the scored batch (VERDICT r3 #2).

Every throughput number to round 3 stepped over ONE pre-placed sharded
batch; this bench runs the REAL fit loop data path — BatchLoader
epoch-plan indexing + the C++ gather batcher (data/native_batcher via
gather_rows), background prefetch threads, per-batch host->device
transfer — and reports end-to-end samples/sec next to the step-only
number measured in the same process with the same compiled step.

The reference's DataLoader demonstrably keeps its loop fed
(``master/part1/part1.py:80-93``, num_workers=2 + pinned memory); the
parity question here is whether the host side can feed 35.6k
samples/sec of 32x32 images (~437 MB/s of f32 traffic at the scored
point, plus index-gather assembly).

Methodology per the tunnel-timing discipline: each timing region closes
by fetching a scalar derived from the LAST step's params (dependent
host round-trip — ``block_until_ready`` is not a reliable fence here);
the loop steps fetch NO per-step values (the loss stays on device, as
a throughput-mode training loop would keep it).

Run: python benchmarks/bench_e2e_input.py

Measured 2026-07-31 (one TPU v5e chip):
  step-only                     35,345 sps/chip
  end-to-end (loader+prefetch)  12,124 sps/chip  (34%)
with the component decomposition (paired probes, same process):
  C++ gather assembly     4.5 ms/batch  ->  915k sps  (26x requirement)
  host->device transfer   12.5 MB/batch uint8 (the loader ships bytes;
                          the step casts on device), multi-GB/s when
                          puts pipeline; b4096 needs ~110 MB/s
  warm-buffer steps       full speed: alternating two RESIDENT batches
                          runs at the step-only 121 ms — the loop
                          structure itself costs nothing
  fresh-buffer steps      +220-780 ms/step, swinging with the tunnel's
                          session weather (RTT 3-500 ms class), and
                          INVARIANT to prefetch depth (2 vs 8), burst
                          pre-placement of 12 batches, producer-side
                          block_until_ready, and buffer count
Conclusion: every framework component exceeds the scored-point
requirement by 26-500x; the combined-loop gap is the tunneled
backend's handling of executions over freshly transferred argument
buffers — an ENVIRONMENT ceiling (the same loop at full speed over
resident buffers proves the loop/step side; the isolated 915k-sps
loader proves the host side). On a direct-attached TPU host the
components bound end-to-end at >=95% of step-only; through this tunnel
the honest number is the 34% above and it is weather-dependent.
"""

from __future__ import annotations

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax

from bench import COMPILER_OPTIONS  # the scored bench's compile recipe

GLOBAL_BATCH = 4096
N_BATCHES = 24  # dataset = 24 scored batches (~1.2 GB f32 host images)
WARMUP_BATCHES = 6
PREFETCH = 2


def main() -> None:
    from cs744_pytorch_distributed_tutorial_tpu.config import TrainConfig
    from cs744_pytorch_distributed_tutorial_tpu.data import (
        BatchLoader,
        synthetic_cifar10,
    )
    from cs744_pytorch_distributed_tutorial_tpu.data.prefetch import prefetch
    from cs744_pytorch_distributed_tutorial_tpu.parallel import make_mesh
    from cs744_pytorch_distributed_tutorial_tpu.parallel.mesh import (
        shard_global_batch,
    )
    from cs744_pytorch_distributed_tutorial_tpu.train import Trainer

    n_chips = len(jax.devices())
    cfg = TrainConfig(
        model="resnet18",
        sync="auto",
        num_devices=n_chips,
        global_batch_size=GLOBAL_BATCH,
        compute_dtype="bfloat16",
        synthetic_data=True,
        prefetch_depth=PREFETCH,
    )
    mesh = make_mesh({"data": n_chips})
    trainer = Trainer(cfg, mesh=mesh)
    state = trainer.init()
    ds = synthetic_cifar10(GLOBAL_BATCH * N_BATCHES, 16, seed=0)
    key = jax.random.key(cfg.seed)

    # One compiled step, shared by both measurements (bench.py recipe).
    x0, y0 = shard_global_batch(
        mesh, ds.train_images[:GLOBAL_BATCH], ds.train_labels[:GLOBAL_BATCH]
    )
    if jax.default_backend() != "cpu":
        step = trainer.train_step.lower(state, x0, y0, key).compile(
            compiler_options=COMPILER_OPTIONS
        )
    else:
        step = trainer.train_step

    def fence(s) -> None:
        float(jax.tree.leaves(s.params)[0].ravel()[0])

    # ---- step-only (pre-placed batch), the round-3 methodology --------
    for _ in range(WARMUP_BATCHES):
        state, _ = step(state, x0, y0, key)
    fence(state)
    t0 = time.perf_counter()
    for _ in range(N_BATCHES - WARMUP_BATCHES):
        state, _ = step(state, x0, y0, key)
    fence(state)
    step_only = (
        (N_BATCHES - WARMUP_BATCHES) * GLOBAL_BATCH
        / (time.perf_counter() - t0) / n_chips
    )

    # ---- end to end: loader + prefetch + transfer + step ---------------
    loader = BatchLoader(
        ds.train_images, ds.train_labels, GLOBAL_BATCH,
        mesh=mesh, shuffle=True, seed=0,
    )

    def run_epoch(epoch: int) -> float:
        """Samples/sec/chip over the epoch's post-warmup batches; the
        warmup prefix absorbs prefetch ramp + any residual compile."""
        nonlocal state
        it = iter(prefetch(loader.epoch(epoch), PREFETCH))
        for _ in range(WARMUP_BATCHES):
            x, y = next(it)
            state, _ = step(state, x, y, key)
        fence(state)
        n = 0
        t0 = time.perf_counter()
        for x, y in it:
            state, _ = step(state, x, y, key)
            n += 1
        fence(state)
        return n * GLOBAL_BATCH / (time.perf_counter() - t0) / n_chips

    e2e = max(run_epoch(e) for e in range(2))

    # ---- host-side-only: what does the loader cost with no device work?
    # Same fence discipline as the other regions: a dependent scalar
    # fetch from the LAST batch (block_until_ready is not a reliable
    # fence on this backend — see the methodology note above).
    t0 = time.perf_counter()
    n = 0
    for x, y in prefetch(loader.epoch(2), PREFETCH):
        n += 1
    float(y.ravel()[0])
    host_only = n * GLOBAL_BATCH / (time.perf_counter() - t0) / n_chips

    print(json.dumps({
        "metric": "cifar10_resnet18_e2e_input_pipeline",
        "step_only_sps_per_chip": round(step_only, 1),
        "end_to_end_sps_per_chip": round(e2e, 1),
        "e2e_fraction": round(e2e / step_only, 4),
        "loader_alone_sps_per_chip": round(host_only, 1),
        "batch": GLOBAL_BATCH,
        "prefetch_depth": PREFETCH,
    }))


if __name__ == "__main__":
    main()
