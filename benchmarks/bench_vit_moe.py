"""On-chip numbers for the two families that had none (VERDICT r3 #6).

ViT: train ViT-Ti/4 and ViT-S/4 on CIFAR shapes under the same
data-parallel Trainer as VGG/ResNet (bf16, flash attention) — ms/step,
samples/sec, analytic MFU, plus a short loss-descent window on a
learnable synthetic set so the number is a TRAINING number, not a
forward benchmark.

MoE: LMTrainer step with a routed Switch FFN (E=8, top-2, d_ff=F)
against the FLOPs-MATCHED dense model (d_ff=2F — top-2 routing
computes two F-wide expert FFNs per token, so per-token matmul FLOPs
are equal up to the router). Reports tokens/sec for both, the MoE
utilization tax (dispatch/combine einsums + router), and the measured
drop rate / aux loss from the new fit-history metrics.

MFU accounting: FLOPs = 2*MACs, train = 3x forward, remat off; ViT
attention FLOPs counted at full (non-causal) N^2.

Measured 2026-07-31, one TPU v5e chip:
  vit_tiny  b1024: 57.6 ms/step  17.8k samples/sec  MFU 0.099
  vit_small b512:  77.8 ms/step   6.6k samples/sec  MFU 0.190
  vit_tiny descent (3 epochs, learnable synthetic): loss 2.52 -> 0.60,
  test accuracy 80.7% — a training capability, not a forward demo.
  (Low MFU is the small-model regime: d192/d384 matmuls over 65 tokens
  underfill the 128-lane MXU; the table exists to make that measured.)

  moe e8/top2 G=1:   230.1 ms  71.2k tok/s   drop 0.1%  (the negative
                     that motivated grouping: 4.2x slower than dense)
  moe e8/top2 G=16:   77.8 ms  210.5k tok/s  drop 12.7% at init
  dense d_ff 2048:    55.2 ms  297.1k tok/s  (FLOPs-matched oracle)
  GShard grouping cuts the O(N*E*C*D) dispatch by G: 2.96x step
  speedup, leaving a 1.41x routed-vs-dense tax (router + dispatch/
  combine einsums + the all-to-all-free single-chip layout). Init-time
  drop rises at per-group capacity (random router, cf 1.25); training
  balances it: the 60-step fit trajectory measured drop 8.7% -> 0.7%
  (G=1) with aux 4.62 -> 4.09.
"""

from __future__ import annotations

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import numpy as np

V5E_PEAK_FLOPS = 197e12
STEPS, WARMUP = 12, 8


def vit_flops_per_sample(d, layers, d_ff, n_tokens) -> float:
    """Per-sample forward MACs*2*3: qkv/o projections + MLP + full
    (non-causal) attention contractions, patch embed + head ignored
    (<2%)."""
    per_layer = n_tokens * (4 * d * d + 2 * d * d_ff) + 2 * n_tokens**2 * d
    return 3.0 * 2.0 * layers * per_layer


def bench_vit(model: str, batch: int) -> dict:
    from cs744_pytorch_distributed_tutorial_tpu.config import TrainConfig
    from cs744_pytorch_distributed_tutorial_tpu.data import synthetic_cifar10
    from cs744_pytorch_distributed_tutorial_tpu.parallel import make_mesh
    from cs744_pytorch_distributed_tutorial_tpu.parallel.mesh import (
        shard_global_batch,
    )
    from cs744_pytorch_distributed_tutorial_tpu.train import Trainer

    cfg = TrainConfig(
        model=model,
        # ring (explicit collectives): flash can't trace under the
        # 'auto' strategy's check_vma (see engine guard).
        sync="ring",
        num_devices=1,
        global_batch_size=batch,
        compute_dtype="bfloat16",
        synthetic_data=True,
        vit_attention="flash",
    )
    mesh = make_mesh({"data": 1})
    tr = Trainer(cfg, mesh=mesh)
    state = tr.init()
    ds = synthetic_cifar10(batch, 16, seed=0)
    x, y = shard_global_batch(mesh, ds.train_images, ds.train_labels)
    key = jax.random.key(0)
    state, m = tr.train_step(state, x, y, key)
    float(m["loss"])
    for _ in range(WARMUP):
        state, m = tr.train_step(state, x, y, key)
    float(m["loss"])
    t0 = time.perf_counter()
    for _ in range(STEPS):
        state, m = tr.train_step(state, x, y, key)
    float(m["loss"])
    dt = (time.perf_counter() - t0) / STEPS
    dims = {"vit_tiny": (192, 6, 768), "vit_small": (384, 8, 1536)}[model]
    n_tokens = (32 // 4) ** 2 + 1
    flops = vit_flops_per_sample(dims[0], dims[1], dims[2], n_tokens)
    sps = batch / dt
    return {
        "metric": f"cifar10_{model}_train_samples_per_sec_per_chip",
        "ms_per_step": round(dt * 1e3, 2),
        "samples_per_sec": round(sps),
        "mfu": (
            round(sps * flops / V5E_PEAK_FLOPS, 4)
            if jax.default_backend() != "cpu" else None
        ),
        "config": f"{model}/32px/b{batch}/bf16/flash",
    }


def vit_descends() -> dict:
    """Short training window on the learnable synthetic set: the ViT
    number is a training capability, not a kernel demo."""
    from cs744_pytorch_distributed_tutorial_tpu.config import TrainConfig
    from cs744_pytorch_distributed_tutorial_tpu.train import Trainer

    cfg = TrainConfig(
        model="vit_tiny",
        sync="ring",
        num_devices=1,
        global_batch_size=512,
        compute_dtype="bfloat16",
        synthetic_data=True,
        synthetic_train_size=4096,
        synthetic_test_size=1024,
        epochs=3,
        learning_rate=1e-3,
        optimizer="adamw",
        vit_attention="flash",
    )
    tr = Trainer(cfg)
    state, history = tr.fit()
    return {
        "metric": "vit_tiny_synthetic_descent",
        "first_loss": round(history["train_loss"][0][2], 4),
        "final_loss": round(history["train_loss"][-1][2], 4),
        "final_eval": history["eval"][-1],
    }


def bench_moe(batch: int = 32, seq: int = 512) -> list[dict]:
    from cs744_pytorch_distributed_tutorial_tpu.data import synthetic_tokens
    from cs744_pytorch_distributed_tutorial_tpu.parallel import make_mesh
    from cs744_pytorch_distributed_tutorial_tpu.train import LMConfig, LMTrainer

    base = dict(
        vocab_size=50304, num_layers=6, num_heads=8, d_model=512,
        max_seq_len=seq, seq_len=seq, global_batch_size=batch,
        attention_impl="flash", compute_dtype="bfloat16", use_rope=True,
    )
    rows = []
    for name, kw in (
        # top-2 of E=8 F-wide experts vs the FLOPs-matched 2F dense MLP.
        # Ungrouped (G=1) measured 4.8x slower than dense — the
        # O(N*E*C*D) dispatch at N=16k tokens; GShard grouping (G=16,
        # 1024 tokens/group) divides that cost by G.
        ("moe_e8_top2_g1", dict(d_ff=1024, moe_experts=8, moe_top_k=2)),
        ("moe_e8_top2_g16", dict(d_ff=1024, moe_experts=8, moe_top_k=2,
                                 moe_groups=16)),
        ("dense_matched", dict(d_ff=2048)),
    ):
        cfg = LMConfig(**base, **kw)
        tr = LMTrainer(cfg, mesh=make_mesh({"data": 1, "seq": 1}))
        params, opt = tr.init()
        x, y = tr.shard_batch(synthetic_tokens(batch, seq, 50304, seed=0))
        params, opt, m = tr.train_step(params, opt, x, y)
        float(m["loss"])
        for _ in range(WARMUP):
            params, opt, m = tr.train_step(params, opt, x, y)
        float(m["loss"])
        t0 = time.perf_counter()
        for _ in range(STEPS):
            params, opt, m = tr.train_step(params, opt, x, y)
        float(m["loss"])
        dt = (time.perf_counter() - t0) / STEPS
        row = {
            "metric": f"moe_vs_dense_{name}",
            "ms_per_step": round(dt * 1e3, 2),
            "tokens_per_sec": round(batch * seq / dt),
            "config": f"6L/512d/{kw.get('d_ff')}ff/b{batch}/T{seq}",
        }
        if "moe_experts" in kw:
            row["moe_drop"] = round(float(m["moe_drop"]), 4)
            row["moe_aux"] = round(float(m["moe_aux"]), 4)
        rows.append(row)
    return rows


def moe_training_trajectory() -> dict:
    """A short real fit() so drop-rate/aux-loss are shown as measured
    TRAJECTORIES (the test pins the plumbing; this pins the numbers)."""
    from cs744_pytorch_distributed_tutorial_tpu.data import synthetic_tokens
    from cs744_pytorch_distributed_tutorial_tpu.parallel import make_mesh
    from cs744_pytorch_distributed_tutorial_tpu.train import LMConfig, LMTrainer

    cfg = LMConfig(
        vocab_size=512, num_layers=4, num_heads=8, d_model=256, d_ff=512,
        max_seq_len=256, seq_len=256, global_batch_size=32,
        attention_impl="flash", compute_dtype="bfloat16", use_rope=True,
        moe_experts=8, moe_top_k=2, learning_rate=3e-4,
    )
    tr = LMTrainer(cfg, mesh=make_mesh({"data": 1, "seq": 1}))
    tokens = synthetic_tokens(256, 256, 512, seed=0)
    tr.fit(tokens, steps=60)
    h = tr.history
    return {
        "metric": "moe_fit_trajectory",
        "loss_first_last": [round(h["loss"][0], 3), round(h["loss"][-1], 3)],
        "drop_first_last": [
            round(h["moe_drop"][0], 4), round(h["moe_drop"][-1], 4),
        ],
        "aux_first_last": [
            round(h["moe_aux"][0], 4), round(h["moe_aux"][-1], 4),
        ],
    }


def main() -> None:
    which = set(sys.argv[1:]) or {"vit", "vit_descent", "moe", "moe_fit"}
    if "vit" in which:
        for model, batch in (("vit_tiny", 1024), ("vit_small", 512)):
            print(json.dumps(bench_vit(model, batch)), flush=True)
    if "vit_descent" in which:
        print(json.dumps(vit_descends()), flush=True)
    if "moe" in which:
        for row in bench_moe():
            print(json.dumps(row), flush=True)
    if "moe_fit" in which:
        print(json.dumps(moe_training_trajectory()), flush=True)


if __name__ == "__main__":
    main()
