"""On-chip numbers for the two families that had none (VERDICT r3 #6).

ViT: train ViT-Ti/4 and ViT-S/4 on CIFAR shapes under the same
data-parallel Trainer as VGG/ResNet (bf16, flash attention) — ms/step,
samples/sec, analytic MFU, plus a short loss-descent window on a
learnable synthetic set so the number is a TRAINING number, not a
forward benchmark.

MoE: LMTrainer step with a routed Switch FFN (E=8, top-2, d_ff=F)
against the FLOPs-MATCHED dense model (d_ff=2F — top-2 routing
computes two F-wide expert FFNs per token, so per-token matmul FLOPs
are equal up to the router). Reports tokens/sec for both, the MoE
utilization tax (dispatch/combine einsums + router), and the measured
drop rate / aux loss from the new fit-history metrics.

MFU accounting: FLOPs = 2*MACs, train = 3x forward, remat off; ViT
attention FLOPs counted at full (non-causal) N^2.

Measured 2026-07-31, one TPU v5e chip:
  vit_tiny  b1024: 57.6 ms/step  17.8k samples/sec  MFU 0.099
  vit_small b512:  77.8 ms/step   6.6k samples/sec  MFU 0.190
  vit_tiny descent (3 epochs, learnable synthetic): loss 2.52 -> 0.60,
  test accuracy 80.7% — a training capability, not a forward demo.
  (Low MFU is the small-model regime: d192/d384 matmuls over 65 tokens
  underfill the 128-lane MXU; the table exists to make that measured.)

  moe e8/top2 G=1:   230.1 ms  71.2k tok/s   drop 0.1%  (the negative
                     that motivated grouping: 4.2x slower than dense)
  moe e8/top2 G=16:   77.8 ms  210.5k tok/s  drop 12.7% at init
  dense d_ff 2048:    55.2 ms  297.1k tok/s  (FLOPs-matched oracle)
  GShard grouping cuts the O(N*E*C*D) dispatch by G: 2.96x step
  speedup, leaving a 1.41x routed-vs-dense tax (router + dispatch/
  combine einsums + the all-to-all-free single-chip layout). Init-time
  drop rises at per-group capacity (random router, cf 1.25); training
  balances it: the 60-step fit trajectory measured drop 8.7% -> 0.7%
  (G=1) with aux 4.62 -> 4.09.

Round 5 — ViT MXU geometry lever (vit_wide_p8: patch 8, d384, 3 heads
-> head_dim 128 = one MXU tile; FLOPs-matched to vit_tiny within 1%):
  vit_tiny    b1024: 59.8 ms  17.1k sps  MFU 0.095  (same-session)
  vit_wide_p8 b1024: 39.2 ms  26.2k sps  MFU 0.145  (1.53x at equal FLOPs)
  vit_wide_p8 b2048: 76.0 ms  26.9k sps  MFU 0.149  (saturated)
  descent (3 epochs, learnable synthetic): loss 2.76 -> 1.51,
  accuracy 35.9% vs vit_tiny's 80.7% — the honest trade: 8x8 patches
  on 32px inputs buy tile-aligned matmuls at the cost of spatial
  resolution; the lever demonstrates WHERE the tiny-ViT MFU went
  (geometry), it is not a free accuracy upgrade.

Round 5 — scatter dispatch (same chip, same session re-measurement):
  einsum  G=1:   232.5 ms   70.5k tok/s  drop 0.1%
  einsum  G=16:   81.6 ms  200.7k tok/s  drop 12.7% (init)
  scatter G=16:   87.1 ms  188.2k tok/s  drop 13.4% (init)
  scatter G=1:    79.6 ms  206.0k tok/s  drop 0.2%   <- new default
  scatter G=1 cf=1.0: 76.9 ms  213.2k tok/s  drop 3.2% (init)
  dense oracle:   55.3 ms  296.4k tok/s
Scatter is group-size-invariant, so G=1 (einsum's pathology) is its
best point: 2.9x over einsum at iso-drop, no grouping/drop trade.
The 1.44x residual vs dense is bandwidth, not FLOPs: cf 1.25 -> 1.0
deletes the whole 1.25x slot-padding FLOPs term but buys only 3.5%,
and the device profile shows the time spread across per-layer
movement/router fusions with no hot op (see benchmarks/README.md).
"""

from __future__ import annotations

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax

V5E_PEAK_FLOPS = 197e12
STEPS, WARMUP = 12, 8


def vit_flops_per_sample(d, layers, d_ff, n_tokens) -> float:
    """Per-sample forward MACs*2*3: qkv/o projections + MLP + full
    (non-causal) attention contractions, patch embed + head ignored
    (<2%)."""
    per_layer = n_tokens * (4 * d * d + 2 * d * d_ff) + 2 * n_tokens**2 * d
    return 3.0 * 2.0 * layers * per_layer


def bench_vit(model: str, batch: int) -> dict:
    from cs744_pytorch_distributed_tutorial_tpu.config import TrainConfig
    from cs744_pytorch_distributed_tutorial_tpu.data import synthetic_cifar10
    from cs744_pytorch_distributed_tutorial_tpu.parallel import make_mesh
    from cs744_pytorch_distributed_tutorial_tpu.parallel.mesh import (
        shard_global_batch,
    )
    from cs744_pytorch_distributed_tutorial_tpu.train import Trainer

    cfg = TrainConfig(
        model=model,
        # ring (explicit collectives): flash can't trace under the
        # 'auto' strategy's check_vma (see engine guard).
        sync="ring",
        num_devices=1,
        global_batch_size=batch,
        compute_dtype="bfloat16",
        synthetic_data=True,
        vit_attention="flash",
    )
    mesh = make_mesh({"data": 1})
    tr = Trainer(cfg, mesh=mesh)
    state = tr.init()
    ds = synthetic_cifar10(batch, 16, seed=0)
    x, y = shard_global_batch(mesh, ds.train_images, ds.train_labels)
    key = jax.random.key(0)
    state, m = tr.train_step(state, x, y, key)
    float(m["loss"])
    for _ in range(WARMUP):
        state, m = tr.train_step(state, x, y, key)
    float(m["loss"])
    t0 = time.perf_counter()
    for _ in range(STEPS):
        state, m = tr.train_step(state, x, y, key)
    float(m["loss"])
    dt = (time.perf_counter() - t0) / STEPS
    dims, patch = {
        "vit_tiny": ((192, 6, 768), 4),
        "vit_small": ((384, 8, 1536), 4),
        # Round-5 geometry lever: FLOPs-matched to vit_tiny (4x fewer
        # tokens x 4x the d^2 terms), head_dim 128 = one MXU tile.
        "vit_wide_p8": ((384, 6, 1536), 8),
    }[model]
    n_tokens = (32 // patch) ** 2 + 1
    flops = vit_flops_per_sample(dims[0], dims[1], dims[2], n_tokens)
    sps = batch / dt
    return {
        "metric": f"cifar10_{model}_train_samples_per_sec_per_chip",
        "ms_per_step": round(dt * 1e3, 2),
        "samples_per_sec": round(sps),
        "mfu": (
            round(sps * flops / V5E_PEAK_FLOPS, 4)
            if jax.default_backend() != "cpu" else None
        ),
        "config": f"{model}/32px/b{batch}/bf16/flash",
    }


def vit_descends(model: str = "vit_tiny") -> dict:
    """Short training window on the learnable synthetic set: the ViT
    number is a training capability, not a kernel demo."""
    from cs744_pytorch_distributed_tutorial_tpu.config import TrainConfig
    from cs744_pytorch_distributed_tutorial_tpu.train import Trainer

    cfg = TrainConfig(
        model=model,
        sync="ring",
        num_devices=1,
        global_batch_size=512,
        compute_dtype="bfloat16",
        synthetic_data=True,
        synthetic_train_size=4096,
        synthetic_test_size=1024,
        epochs=3,
        learning_rate=1e-3,
        optimizer="adamw",
        vit_attention="flash",
    )
    tr = Trainer(cfg)
    state, history = tr.fit()
    return {
        "metric": f"{model}_synthetic_descent",
        "first_loss": round(history["train_loss"][0][2], 4),
        "final_loss": round(history["train_loss"][-1][2], 4),
        "final_eval": history["eval"][-1],
    }


def _timed_lm_steps(tr, params, opt, x, y):
    """Shared LM timing protocol: compile step, WARMUP steps, then
    STEPS timed (each phase fenced by a loss fetch). Returns
    (seconds/step, last metrics)."""
    params, opt, m = tr.train_step(params, opt, x, y)
    float(m["loss"])
    for _ in range(WARMUP):
        params, opt, m = tr.train_step(params, opt, x, y)
    float(m["loss"])
    t0 = time.perf_counter()
    for _ in range(STEPS):
        params, opt, m = tr.train_step(params, opt, x, y)
    float(m["loss"])
    return (time.perf_counter() - t0) / STEPS, m


def bench_moe(batch: int = 32, seq: int = 512) -> list[dict]:
    from cs744_pytorch_distributed_tutorial_tpu.data import synthetic_tokens
    from cs744_pytorch_distributed_tutorial_tpu.parallel import make_mesh
    from cs744_pytorch_distributed_tutorial_tpu.train import LMConfig, LMTrainer

    base = dict(
        vocab_size=50304, num_layers=6, num_heads=8, d_model=512,
        max_seq_len=seq, seq_len=seq, global_batch_size=batch,
        attention_impl="flash", compute_dtype="bfloat16", use_rope=True,
    )
    rows = []
    for name, kw in (
        # top-2 of E=8 F-wide experts vs the FLOPs-matched 2F dense MLP.
        # Ungrouped (G=1) measured 4.8x slower than dense — the
        # O(N*E*C*D) dispatch at N=16k tokens; GShard grouping (G=16,
        # 1024 tokens/group) divides that cost by G.
        # moe_dispatch pinned: LMConfig's default flipped to "scatter"
        # in round 5, and these two are the einsum BASELINE rows.
        ("moe_e8_top2_g1", dict(d_ff=1024, moe_experts=8, moe_top_k=2,
                                moe_dispatch="einsum")),
        ("moe_e8_top2_g16", dict(d_ff=1024, moe_experts=8, moe_top_k=2,
                                 moe_groups=16, moe_dispatch="einsum")),
        # Round 5 (VERDICT r4 #6): scatter-add/gather token movement —
        # O(N*K*D) instead of the O(N*E*C*D) one-hot einsums, same
        # routing/drop semantics (parity-tested). Rows at the grouped
        # AND ungrouped settings: scatter's cost does not grow with the
        # group size, so G=1's per-group capacity overhead vanishes.
        ("moe_e8_top2_g16_scatter",
         dict(d_ff=1024, moe_experts=8, moe_top_k=2, moe_groups=16,
              moe_dispatch="scatter")),
        ("moe_e8_top2_g1_scatter",
         dict(d_ff=1024, moe_experts=8, moe_top_k=2,
              moe_dispatch="scatter")),
        # Capacity-floor probe: at cf=1.25 the slot padding ALONE costs
        # 1.25x vs the FLOPs-matched dense (E*C = k*cf*N slot-tokens);
        # cf=1.0 removes the padding term and isolates the router +
        # token-movement overhead.
        ("moe_e8_top2_g1_scatter_cf1",
         dict(d_ff=1024, moe_experts=8, moe_top_k=2,
              moe_dispatch="scatter", moe_capacity_factor=1.0)),
        # Dropless (late round 5): NO capacity slots — argsort by
        # expert + two ragged grouped matmuls (ops/gmm.py); expert
        # FLOPs are exactly k*N rows (the cf=1.0 scatter row's compute
        # without its drops). Both gmm backends measured.
        ("moe_e8_top2_dropless_ragged",
         dict(d_ff=1024, moe_experts=8, moe_top_k=2,
              moe_dispatch="dropless")),
        ("moe_e8_top2_dropless_pallas",
         dict(d_ff=1024, moe_experts=8, moe_top_k=2,
              moe_dispatch="dropless", moe_gmm_impl="pallas")),
        ("dense_matched", dict(d_ff=2048)),
    ):
        cfg = LMConfig(**base, **kw)
        tr = LMTrainer(cfg, mesh=make_mesh({"data": 1, "seq": 1}))
        params, opt = tr.init()
        x, y = tr.shard_batch(synthetic_tokens(batch, seq, 50304, seed=0))
        dt, m = _timed_lm_steps(tr, params, opt, x, y)
        row = {
            "metric": f"moe_vs_dense_{name}",
            "ms_per_step": round(dt * 1e3, 2),
            "tokens_per_sec": round(batch * seq / dt),
            "config": f"6L/512d/{kw.get('d_ff')}ff/b{batch}/T{seq}",
        }
        if "moe_experts" in kw:
            row["moe_drop"] = round(float(m["moe_drop"]), 4)
            row["moe_aux"] = round(float(m["moe_aux"]), 4)
        rows.append(row)
    return rows


def bench_moe_expert_sweep(batch: int = 32, seq: int = 512) -> list[dict]:
    """Where dropless pays: high expert counts. Capacity-slot compute
    scales with E*C = k*cf*N regardless of E, but the DROP RATE at
    fixed cf grows with routing imbalance, which grows with E (an
    untrained router over E=32 experts is far from uniform per group);
    covering the skew with cf costs proportional compute. Dropless
    computes exactly k*N rows at any E and any skew — this sweep
    measures both sides of that trade at E=8/32 with top-2."""
    from cs744_pytorch_distributed_tutorial_tpu.data import synthetic_tokens
    from cs744_pytorch_distributed_tutorial_tpu.parallel import make_mesh
    from cs744_pytorch_distributed_tutorial_tpu.train import LMConfig, LMTrainer

    base = dict(
        vocab_size=50304, num_layers=6, num_heads=8, d_model=512,
        d_ff=1024, max_seq_len=seq, seq_len=seq, global_batch_size=batch,
        attention_impl="flash", compute_dtype="bfloat16", use_rope=True,
        moe_top_k=2,
    )
    rows = []
    for name, kw in (
        ("e8_scatter_cf125", dict(moe_experts=8, moe_dispatch="scatter")),
        ("e8_dropless", dict(moe_experts=8, moe_dispatch="dropless")),
        ("e32_scatter_cf125", dict(moe_experts=32, moe_dispatch="scatter")),
        # cf covering the observed e32 init drop rate costs slots.
        ("e32_scatter_cf2", dict(moe_experts=32, moe_dispatch="scatter",
                                 moe_capacity_factor=2.0)),
        ("e32_dropless", dict(moe_experts=32, moe_dispatch="dropless")),
    ):
        cfg = LMConfig(**base, **kw)
        tr = LMTrainer(cfg, mesh=make_mesh({"data": 1, "seq": 1}))
        params, opt = tr.init()
        x, y = tr.shard_batch(synthetic_tokens(batch, seq, 50304, seed=0))
        dt, m = _timed_lm_steps(tr, params, opt, x, y)
        rows.append({
            "metric": f"moe_expert_sweep_{name}",
            "ms_per_step": round(dt * 1e3, 2),
            "tokens_per_sec": round(batch * seq / dt),
            "moe_drop": round(float(m["moe_drop"]), 4),
            "config": f"6L/512d/1024ff/top2/b{batch}/T{seq}",
        })
    return rows


def moe_training_trajectory() -> dict:
    """A short real fit() so drop-rate/aux-loss are shown as measured
    TRAJECTORIES (the test pins the plumbing; this pins the numbers)."""
    from cs744_pytorch_distributed_tutorial_tpu.data import synthetic_tokens
    from cs744_pytorch_distributed_tutorial_tpu.parallel import make_mesh
    from cs744_pytorch_distributed_tutorial_tpu.train import LMConfig, LMTrainer

    cfg = LMConfig(
        vocab_size=512, num_layers=4, num_heads=8, d_model=256, d_ff=512,
        max_seq_len=256, seq_len=256, global_batch_size=32,
        attention_impl="flash", compute_dtype="bfloat16", use_rope=True,
        moe_experts=8, moe_top_k=2, learning_rate=3e-4,
    )
    tr = LMTrainer(cfg, mesh=make_mesh({"data": 1, "seq": 1}))
    tokens = synthetic_tokens(256, 256, 512, seed=0)
    tr.fit(tokens, steps=60)
    h = tr.history
    return {
        "metric": "moe_fit_trajectory",
        "loss_first_last": [round(h["loss"][0], 3), round(h["loss"][-1], 3)],
        "drop_first_last": [
            round(h["moe_drop"][0], 4), round(h["moe_drop"][-1], 4),
        ],
        "aux_first_last": [
            round(h["moe_aux"][0], 4), round(h["moe_aux"][-1], 4),
        ],
    }


def main() -> None:
    which = set(sys.argv[1:]) or {"vit", "vit_descent", "moe", "moe_fit"}
    if "vit" in which:
        for model, batch in (
            ("vit_tiny", 1024), ("vit_small", 512), ("vit_wide_p8", 1024),
        ):
            print(json.dumps(bench_vit(model, batch)), flush=True)
    if "vit_descent" in which:
        for model in ("vit_tiny", "vit_wide_p8"):
            print(json.dumps(vit_descends(model)), flush=True)
    if "moe" in which:
        for row in bench_moe():
            print(json.dumps(row), flush=True)
    if "moe_sweep" in which:
        for row in bench_moe_expert_sweep():
            print(json.dumps(row), flush=True)
    if "moe_fit" in which:
        print(json.dumps(moe_training_trajectory()), flush=True)


if __name__ == "__main__":
    main()
