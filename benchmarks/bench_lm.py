"""LM training throughput on the real chip: tokens/sec, flash vs dense.

Single-chip companion to the scored CIFAR bench: a GPT-style block stack
at seq_len 2048 in bf16, comparing the Pallas flash-attention kernel
(ops/flash_attention.py) against dense attention. Run: python
benchmarks/bench_lm.py

Measured 2026-07-30 (one TPU v5e chip, this config):
  dense  92.3 ms/step  177.6k tokens/sec
  flash  89.8 ms/step  182.4k tokens/sec
Forward-only the kernel is 2.5x faster than dense (4.3 vs 10.7 ms after
retuning blocks to 512x1024 — the old 128x128 default was 2x SLOWER);
the full-step margin is small because the backward recomputes through
the dense formulation either way (the next kernel to write).
"""

from __future__ import annotations

import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax

from cs744_pytorch_distributed_tutorial_tpu.data import synthetic_tokens
from cs744_pytorch_distributed_tutorial_tpu.parallel import make_mesh
from cs744_pytorch_distributed_tutorial_tpu.train import LMConfig, LMTrainer

BATCH = 8
SEQ = 2048
STEPS = 10


def main() -> None:
    mesh = make_mesh({"data": 1, "seq": 1})
    tokens = synthetic_tokens(BATCH * 2, SEQ, 32768, seed=0)
    for impl in ("dense", "flash"):
        cfg = LMConfig(
            vocab_size=32768,
            num_layers=4,
            num_heads=8,
            d_model=512,
            d_ff=2048,
            max_seq_len=SEQ,
            seq_len=SEQ,
            global_batch_size=BATCH,
            attention_impl=impl,
            compute_dtype="bfloat16",
        )
        tr = LMTrainer(cfg, mesh=mesh)
        params, opt = tr.init()
        x, y = tr.shard_batch(tokens[:BATCH])

        params, opt, m = tr.train_step(params, opt, x, y)  # compile
        float(m["loss"])
        t0 = time.perf_counter()
        for _ in range(STEPS):
            params, opt, m = tr.train_step(params, opt, x, y)
        float(m["loss"])  # fence (see bench.py on block_until_ready)
        dt = (time.perf_counter() - t0) / STEPS
        print(
            f"{impl:6s} {dt * 1e3:8.2f} ms/step  "
            f"{BATCH * SEQ / dt:12.0f} tokens/sec"
        )


if __name__ == "__main__":
    main()
