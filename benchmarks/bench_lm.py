"""LM training throughput on the real chip: tokens/sec, flash vs dense.

Single-chip companion to the scored CIFAR bench: a GPT-style block stack
at seq_len 2048 in bf16, comparing the Pallas flash-attention kernel
(ops/flash_attention.py) against dense attention. Run: python
benchmarks/bench_lm.py

Measured 2026-07-30 (one TPU v5e chip, this config):
  round 1:  dense  91.9 ms/step  178.3k tok/s; flash 58.1 ms  282.0k (1.58x)
  round 2:  dense  80.3 ms/step  204.1k tok/s; flash 49.5 ms  330.9k (1.62x)
(round-2 numbers use the deeper warm-up below: the tunneled backend's
first ~5 executions of a large program pay multi-second deferred
initialization — without the warm-up a "step" reads seconds.)
History: the kernel started 2x SLOWER than dense (f32-cast dots +
128x128 tiles); native-dtype MXU feeds and 512x1024 blocks made the
forward 2.5x faster (4.3 vs 10.7 ms), and the Pallas FA-2 backward
(dq/dkv kernels, no [T, T] materialization) delivered the full-step
1.58x above. Parity vs dense verified on-chip at 'highest' matmul
precision (maxabs ~1e-4 grads, 5e-7 forward).
"""

from __future__ import annotations

import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from cs744_pytorch_distributed_tutorial_tpu.data import synthetic_tokens
from cs744_pytorch_distributed_tutorial_tpu.parallel import make_mesh
from cs744_pytorch_distributed_tutorial_tpu.train import LMConfig, LMTrainer

BATCH = 8
SEQ = 2048
STEPS = 10


def main() -> None:
    mesh = make_mesh({"data": 1, "seq": 1})
    tokens = synthetic_tokens(BATCH * 2, SEQ, 32768, seed=0)
    for impl in ("dense", "flash"):
        cfg = LMConfig(
            vocab_size=32768,
            num_layers=4,
            num_heads=8,
            d_model=512,
            d_ff=2048,
            max_seq_len=SEQ,
            seq_len=SEQ,
            global_batch_size=BATCH,
            attention_impl=impl,
            compute_dtype="bfloat16",
        )
        tr = LMTrainer(cfg, mesh=mesh)
        params, opt = tr.init()
        x, y = tr.shard_batch(tokens[:BATCH])

        # Warm-up: beyond the first compiled call, the tunneled backend's
        # first ~5 executions of a LARGE program pay multi-second
        # deferred-initialization costs (measured: 5.2 s/step for steps
        # 1-5, then 47 ms steady state). Warm until per-step time
        # stabilizes so the measurement is the steady state.
        params, opt, m = tr.train_step(params, opt, x, y)  # compile
        float(m["loss"])
        for _ in range(8):
            params, opt, m = tr.train_step(params, opt, x, y)
        float(m["loss"])
        t0 = time.perf_counter()
        for _ in range(STEPS):
            params, opt, m = tr.train_step(params, opt, x, y)
        float(m["loss"])  # fence (see bench.py on block_until_ready)
        dt = (time.perf_counter() - t0) / STEPS
        print(
            f"{impl:6s} {dt * 1e3:8.2f} ms/step  "
            f"{BATCH * SEQ / dt:12.0f} tokens/sec"
        )


if __name__ == "__main__":
    main()
