"""XLA:TPU compiler-option sweep for the scored ResNet-18 step.

Round 2 found one compile-option win (``xla_tpu_scoped_vmem_limit_kib=
65536``, ~7%); round 3 closed the custom-kernel route with measurements
(``ablate.py``), leaving compiler-generation settings as the remaining
scored-bench lever. This script probes candidate options one at a time
against the current baseline configuration: unknown options are reported
as unavailable (the compile raises), available ones get a measured
steps/sec. Short windows — this ranks candidates; anything that wins
here gets promoted to ``bench.py`` and re-measured at the full window.

Run: python benchmarks/sweep_flags.py

MEASURED (round 3, one v5e, batch 1024, quiet machine): the r2 baseline
options WIN — every candidate lands at or below 34,338 sps (dot-dot
fusion ties at 34,331; higher vmem budgets 98304/131072 LOSE 3-8%, so
65536 is the peak of that curve, and dropping it costs 6%). An earlier
sweep run concurrent with the CPU test suite showed four candidates
"+2-3.5%" — pure load noise, all of them regressed to baseline when
quiet. Two lessons recorded: (a) the scored step's compile-option
surface is exhausted — further gains need code, not flags; (b) never
rank compiler options on a loaded host.
"""

from __future__ import annotations

import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax

BATCH = 1024
WARMUP = 8
STEPS = 40

BASE = {"xla_tpu_scoped_vmem_limit_kib": "65536"}

# Candidates: each is (name, value) merged over BASE; None value means
# "drop the key from BASE" (measures the flag's own contribution).
CANDIDATES: list[tuple[str, dict]] = [
    ("baseline (r2 options)", {}),
    ("no scoped-vmem raise", {"xla_tpu_scoped_vmem_limit_kib": None}),
    ("vmem 98304", {"xla_tpu_scoped_vmem_limit_kib": "98304"}),
    ("vmem 131072", {"xla_tpu_scoped_vmem_limit_kib": "131072"}),
    (
        "aggressive loop fusion layout",
        {"xla_tpu_enable_aggressive_loop_fusion_layout_opt": "true"},
    ),
    ("dot-dot fusion", {"xla_tpu_dot_dot_fusion": "true"}),
    ("rwb fusion off", {"xla_tpu_rwb_fusion": "false"}),
    (
        "licm inflation 2x",
        {"xla_tpu_licm_size_inflation_ratio": "2.0"},
    ),
    (
        "vector load fusion",
        {"xla_tpu_vector_load_fusion_window": "1024"},
    ),
    (
        "multi-level nested fusion",
        {"xla_tpu_enable_multi_level_nested_loop_fusion": "true"},
    ),
    (
        "combo: nested+rwb-off",
        {
            "xla_tpu_enable_multi_level_nested_loop_fusion": "true",
            "xla_tpu_rwb_fusion": "false",
        },
    ),
    (
        "combo: nested+rwb-off+agg-layout",
        {
            "xla_tpu_enable_multi_level_nested_loop_fusion": "true",
            "xla_tpu_rwb_fusion": "false",
            "xla_tpu_enable_aggressive_loop_fusion_layout_opt": "true",
        },
    ),
    (
        "combo: all four",
        {
            "xla_tpu_enable_multi_level_nested_loop_fusion": "true",
            "xla_tpu_rwb_fusion": "false",
            "xla_tpu_enable_aggressive_loop_fusion_layout_opt": "true",
            "xla_tpu_vector_load_fusion_window": "1024",
        },
    ),
]


def build():
    from cs744_pytorch_distributed_tutorial_tpu.config import TrainConfig
    from cs744_pytorch_distributed_tutorial_tpu.data import synthetic_cifar10
    from cs744_pytorch_distributed_tutorial_tpu.parallel import make_mesh
    from cs744_pytorch_distributed_tutorial_tpu.parallel.mesh import (
        shard_global_batch,
    )
    from cs744_pytorch_distributed_tutorial_tpu.train import Trainer

    n = len(jax.devices())
    cfg = TrainConfig(
        model="resnet18",
        sync="auto",
        num_devices=n,
        global_batch_size=BATCH,
        compute_dtype="bfloat16",
        synthetic_data=True,
    )
    mesh = make_mesh({"data": n})
    trainer = Trainer(cfg, mesh=mesh)
    state = trainer.init()
    ds = synthetic_cifar10(BATCH, 16, seed=0)
    x, y = shard_global_batch(mesh, ds.train_images, ds.train_labels)
    return trainer, state, x, y, jax.random.key(cfg.seed)


def measure(trainer, state, x, y, key, options) -> float:
    fn = trainer.train_step.lower(state, x, y, key).compile(
        compiler_options=options
    )

    def fence(s):
        float(jax.tree.leaves(s.params)[0].ravel()[0])

    for _ in range(WARMUP):
        state, _ = fn(state, x, y, key)
    fence(state)
    t0 = time.perf_counter()
    for _ in range(STEPS):
        state, _ = fn(state, x, y, key)
    fence(state)
    return STEPS * BATCH / (time.perf_counter() - t0)


def main() -> None:
    trainer, state0, x, y, key = build()
    results = []
    for name, delta in CANDIDATES:
        options = dict(BASE)
        for k, v in delta.items():
            if v is None:
                options.pop(k, None)
            else:
                options[k] = v
        # Donated input: re-init per candidate so every run sees live
        # buffers.
        state = trainer.init()
        try:
            sps = measure(trainer, state, x, y, key, options)
        except Exception as e:  # unknown flag / compile failure
            print(f"{name:36s}  UNAVAILABLE ({type(e).__name__}: {str(e)[:90]})")
            continue
        results.append((sps, name))
        print(f"{name:36s}  {sps:10.1f} samples/sec")
    results.sort(reverse=True)
    print("\nranked:")
    for sps, name in results:
        print(f"  {sps:10.1f}  {name}")


if __name__ == "__main__":
    main()
