"""Ablation timings for the scored ResNet-18 step on the real chip.

Times variants of the training step to locate the bottleneck:
  full        — the scored configuration (augment + fwd/bwd + SGD)
  no_augment  — normalize only (is the one-hot crop/flip material?)
  fwd_only    — loss forward pass, no grad/update
  fwd_bwd     — value_and_grad, no optimizer update
Run on the TPU: python benchmarks/ablate.py

Measured 2026-07-30, one TPU v5e chip, batch 4096 bf16:
  aug_only        6.75 ms   (5% of the step — the one-hot MXU rewrite paid off)
  fwd_only       41.79 ms   (~28% of bf16 MXU peak: stage-1's 64-channel
                             convs half-fill the 128-wide MXU lanes, and BN
                             stats passes re-read ~0.5 GB stage-1 activations)
  fwd_bwd       123.26 ms   (backward ~2x forward, the standard ratio)
  full          127.22 ms   (optimizer ~4 ms; 32.2k sps at this batch)
  full_no_aug   125.92 ms   (augmentation nearly free after overlap)

Round-2 device-trace breakdown (jax.profiler over the tunnel works; the
per-op numbers below are device time from the trace, fwd+bwd = 117.9 ms
at batch 4096 bf16 — host-side probes are unreliable here because the
tunnel's per-dispatch overhead is 2-10 ms and variable, so time kernels
either in-graph or from the trace):
  - backward convs ~78 ms, the top block being stage-1 (4 convs x ~8 ms:
    wgrad ~5.6 via XLA's EmitAllBatchInSublanes at ~55 TF/s + dgrad ~2.4);
  - XLA lays stage-1 activations out BATCH-minor ({0,3,2,1}) so its
    forward convs get full 128-lane tiles from the batch dim — the naive
    "64 channels half-fill lanes" read was wrong for fwd, right for wgrad;
  - BatchNorm's full in-step cost is ~19.7 ms (117.9 vs 98.2 norm-free):
    HBM stat passes + backward reduces, only removable by fusing stats
    into conv epilogues (i.e. owning the convs);
  - the Pallas wgrad kernel (ops/fused_conv.py) hits 3.15 ms on stage-1
    shapes and 1.88 ms on stage-2 in isolation — at/above XLA's isolated
    emitter — but IN-graph the layout mismatch (custom calls pin dense
    row-major operands vs XLA's batch-minor choice) inserts 2x ~3.1 ms
    relayout copies per conv and the end-to-end step got SLOWER
    (117.9 -> 159.5). Hence cfg.fast_conv defaults off.
  - xla_tpu_scoped_vmem_limit_kib=65536 (v5e has 128 MiB physical VMEM
    vs the 16 MiB scoped default) lets XLA fuse deeper: step 125.6 ->
    117.3 ms; bench.py compiles with it. Fused SGD and the in-graph
    multi-step scan are each within noise of the default at this batch
    (the round-1 "scan wedges the tunnel" behavior is gone — the scan
    runs fine now, it's just not faster than per-step dispatch, whose
    overhead hides under the 117 ms step).
Round-2 follow-up experiments (both measured, both closed):
  - a LOGICAL transpose [B,H,W,C] -> [H,W,C,B] feeding a pallas call IS
    free when the producer's layout is batch-minor (verified: 0
    transpose ops, 40 bitcasts in the compiled module) — so a
    batch-minor kernel avoids the relayout copies entirely;
  - but the batch-minor wgrad formulation itself is slow: contraction
    over the batch LANES forces per-x-position dots ([576, BB] x
    [K, BB]^T with 9 sublane-concat builds per position) and measured
    13.4 ms on the stage-1 shape (23 TF/s) vs XLA's in-step 5.6 ms.
    The two constraints — dense-layout kernels pay relayout copies,
    batch-minor kernels pay lane-contraction inefficiency — bracket
    XLA's emitter as genuinely near the achievable envelope for these
    shapes on this chip generation.
Remaining unexplored lever: own the ENTIRE stem+stage1 subgraph
(fwd conv+BN-stats+ReLU and the fused backward) in a C-minor layout so
the only boundary relayouts are the stem input (tiny) and the stage-2
entry — the owned region is ~63 ms of XLA time with a ~45 ms kernel-side
ceiling estimate; high effort, and the margin would still not reach the
round-1 verdict's 45k sps target (the norm-free step alone measures
98.2 ms = 41.7k sps at batch 4096).

ROUND-3 MEASUREMENTS (2026-07-31, closing the owned-subgraph question):
  Sharper region map first (benchmarks/breakdown_r3.py, device trace of
  the exact bench step, batch 4096 bf16, vmem 64 MiB — step now 112.2 ms
  device / 35.8k sps):
    stem+stage1   54.2 ms   (region MFU ~35%: fwd conv+stat fusions
                             3.5-4.8 ms x5, wgrad+SGD fusions 3.2 x4,
                             dgrad+reduce 2.06 x4, BN-apply 2.3 x2, rest)
    stage2        23.3 ms   stage3 18.9 ms   stage4 15.2 ms
  The non-stage1 remainder (58 ms) runs at ~86% MFU — there is nothing
  left to win outside the region, and XLA's in-step stage-1 ops are
  already conv+stats/conv+SGD FUSED with no relayout copies (the copies
  only appear when a foreign-layout custom call is inserted).
  The owned-region kernel bet then requires Pallas kernels that BEAT
  those fused ops. Measured attempt (benchmarks/probe_fwd_hpair.py):
  the one formulation that breaks the 64-channel half-lane ceiling packs
  two output rows into 128 lanes via a FREE paired reshape
  [B,32,32,64]->[B,16,64,64] (K=768 full, N=128 full, 75% useful MACs,
  2.1 ms matmul floor):
    hpair fwd kernel, best block:   13.39 ms   (numerics exact vs ref)
    XLA conv isolated (same I/O):    8.48 ms   (pays boundary relayouts)
    XLA conv+stats IN-step:         ~3.5  ms   (batch-minor, fused)
  The kernel is im2col-BUILD-bound: 12 tap shifts + 6-tile lane concat
  per h-pair move ~3 MB of VPU traffic against a 1 us matmul — the same
  tax that killed the batch-minor wgrad in round 2 (13.4 ms / 23 TF/s).
  Build-free formulations were derived and all cap at <= 50% useful
  MACs (w-pair/quad K-packing: the j x dh sparsity patterns multiply),
  i.e. no better than the naive half-lane form XLA already beats.
  VERDICT-r2 #1 resolution: the ceiling is LOWER than the roadmap
  estimate — at today's 112.2 ms step, even the estimate's own 45 ms
  region ceiling gives 103 ms = 39.8k sps < 40k, and the measured
  kernel floor (~4x off XLA in-step) puts the real owned-region result
  far above that ceiling. The scored bench therefore stays on XLA's
  emitters; stage-1's ~35% region MFU is the price of 64-channel convs
  on a 128-lane MXU, not of a missing kernel. Overall step MFU 0.605
  (FLOPs = 2*MACs, bench.py accounting).
"""

from __future__ import annotations

import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp
import optax

from cs744_pytorch_distributed_tutorial_tpu.config import TrainConfig
from cs744_pytorch_distributed_tutorial_tpu.data import (
    augment_train_batch,
    eval_batch,
    synthetic_cifar10,
)
from cs744_pytorch_distributed_tutorial_tpu.models import get_model
from cs744_pytorch_distributed_tutorial_tpu.train.state import make_optimizer

BATCH = 4096
STEPS = 20


def build_full_step(batch: int = BATCH):
    """The scored train step WITHOUT buffer donation, for measurement
    loops that call it repeatedly on one state (donated inputs would be
    invalidated after the first call). Single source for ablate.py and
    breakdown_r3.py — keep in sync with ``Trainer.train_step``.

    Returns ``(full, args)`` where ``full(p, stats, opt, key, x, y)``
    performs augment + fwd/bwd + optimizer update.
    """
    cfg = TrainConfig(model="resnet18", compute_dtype="bfloat16")
    model = get_model(cfg.model, num_classes=10, dtype=jnp.bfloat16)
    tx = make_optimizer(cfg)
    ds = synthetic_cifar10(batch, 16, seed=0)
    x = jnp.asarray(ds.train_images)
    y = jnp.asarray(ds.train_labels)
    key = jax.random.key(0)
    variables = model.init(
        jax.random.key(cfg.seed), jnp.zeros((1, 32, 32, 3)), train=False
    )
    params, stats = variables["params"], variables["batch_stats"]
    opt_state = tx.init(params)

    def loss_fn(p, st, xb, yb):
        logits, mut = model.apply(
            {"params": p, "batch_stats": st}, xb, train=True,
            mutable=["batch_stats"],
        )
        return (
            optax.softmax_cross_entropy_with_integer_labels(logits, yb).mean(),
            mut,
        )

    def full(p, st, o, k, xb, yb):
        (_, mut), g = jax.value_and_grad(loss_fn, has_aux=True)(
            p, st, augment_train_batch(k, xb), yb
        )
        upd, o2 = tx.update(g, o, p)
        return optax.apply_updates(p, upd), mut["batch_stats"], o2

    return full, (params, stats, opt_state, key, x, y)


def bench(fn, *args):
    out = fn(*args)  # compile
    jax.tree.leaves(out)[0].block_until_ready()
    # Fence with a value fetch (block_until_ready is unreliable on the
    # tunneled backend — see bench.py).
    float(jax.tree.leaves(fn(*args))[0].ravel()[0])
    t0 = time.perf_counter()
    for _ in range(STEPS):
        out = fn(*args)
    float(jax.tree.leaves(out)[0].ravel()[0])
    return (time.perf_counter() - t0) / STEPS


def main():
    cfg = TrainConfig(model="resnet18", compute_dtype="bfloat16")
    model = get_model(cfg.model, num_classes=10, dtype=jnp.bfloat16)
    tx = make_optimizer(cfg)
    ds = synthetic_cifar10(BATCH, 16, seed=0)
    x = jnp.asarray(ds.train_images)
    y = jnp.asarray(ds.train_labels)
    key = jax.random.key(0)
    variables = model.init(jax.random.key(cfg.seed), jnp.zeros((1, 32, 32, 3)), train=False)
    params, stats = variables["params"], variables["batch_stats"]
    opt_state = tx.init(params)

    def loss_fn(p, st, xb, yb):
        logits, mut = model.apply(
            {"params": p, "batch_stats": st}, xb, train=True,
            mutable=["batch_stats"],
        )
        return optax.softmax_cross_entropy_with_integer_labels(logits, yb).mean(), mut

    @jax.jit
    def aug_only(k, xb):
        return augment_train_batch(k, xb)

    @jax.jit
    def fwd_only(p, st, k, xb, yb):
        return loss_fn(p, st, aug_only(k, xb), yb)[0]

    @jax.jit
    def fwd_bwd(p, st, k, xb, yb):
        (l, _), g = jax.value_and_grad(loss_fn, has_aux=True)(p, st, aug_only(k, xb), yb)
        return g

    @jax.jit
    def full(p, st, o, k, xb, yb):
        (l, mut), g = jax.value_and_grad(loss_fn, has_aux=True)(p, st, aug_only(k, xb), yb)
        upd, o2 = tx.update(g, o, p)
        return optax.apply_updates(p, upd), mut["batch_stats"], o2

    @jax.jit
    def full_no_aug(p, st, o, xb, yb):
        (l, mut), g = jax.value_and_grad(loss_fn, has_aux=True)(p, st, eval_batch(xb), yb)
        upd, o2 = tx.update(g, o, p)
        return optax.apply_updates(p, upd), mut["batch_stats"], o2

    for name, t in [
        ("aug_only", bench(aug_only, key, x)),
        ("fwd_only", bench(fwd_only, params, stats, key, x, y)),
        ("fwd_bwd", bench(fwd_bwd, params, stats, key, x, y)),
        ("full", bench(full, params, stats, opt_state, key, x, y)),
        ("full_no_aug", bench(full_no_aug, params, stats, opt_state, x, y)),
    ]:
        print(f"{name:14s} {t * 1e3:8.2f} ms  {BATCH / t:10.0f} sps")


if __name__ == "__main__":
    main()
