"""Round-3 probe: per-op device breakdown of the scored bench step.

Times the non-donating mirror of the scored train step
(``benchmarks/ablate.py::build_full_step`` — augment + fwd/bwd + SGD on
ResNet-18/CIFAR, batch 4096 bf16) compiled with bench.py's vmem option,
and prints the top device ops. This produced the round-3 region map in
``ablate.py`` (stem+stage1 54.2 ms of 112.2 at ~35% MFU; the rest at
~86%). Run on the TPU: python benchmarks/breakdown_r3.py
"""

from __future__ import annotations

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax

from bench import COMPILER_OPTIONS
from benchmarks.ablate import build_full_step
from cs744_pytorch_distributed_tutorial_tpu.utils.profiling import (
    device_op_breakdown,
)


def main() -> None:
    full, args = build_full_step()
    fn = jax.jit(full).lower(*args).compile(compiler_options=COMPILER_OPTIONS)

    # Warm past the tunnel's deferred-init window before tracing.
    out = None
    for _ in range(8):
        out = fn(*args)
    float(jax.tree.leaves(out)[0].ravel()[0])

    total, rows = device_op_breakdown(lambda: fn(*args), iters=4, top=40)
    print(f"total device ms/iter: {total:.2f}")
    for ms, name in rows:
        print(f"  {ms:8.3f} ms  {name}")


if __name__ == "__main__":
    main()
