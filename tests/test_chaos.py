"""Chaos-tested recovery (utils/chaos.py): seeded fault injection
through the full escalation ladder.

The recovery subsystem (watchdog, divergence detection, tiered restore,
re-mesh) is only trustworthy if it is EXERCISED — these tests kill runs
mid-step with the three production fault shapes and pin the strongest
recoverable property each time:

- NaN injection on the CIFAR engine recovers from the IN-MEMORY snapshot
  tier with zero filesystem reads (instrumented Checkpointer counters)
  and lands on bitwise-identical parameters.
- A real SIGTERM on the LM engine re-enters the run as a
  ``TrainingFailure`` and the resumed loss curve is bitwise equal to the
  uninterrupted run's tail.
- A device loss on a zero1 run re-meshes dp4 -> dp2
  (``parallel/elastic.py``), reshards the chunked optimizer state
  through the elastic adapt hook, and continues the SAME trajectory
  (rtol 1e-6 — chunking and reduction order are layout, not math).

The chaos-smoke CI job runs this file on CPU; docs/reliability.md is the
operator story.
"""

from __future__ import annotations

import jax
import numpy as np
import pytest
from conftest import TINY_DP4_CFG

from cs744_pytorch_distributed_tutorial_tpu.config import TrainConfig
from cs744_pytorch_distributed_tutorial_tpu.data import synthetic_tokens
from cs744_pytorch_distributed_tutorial_tpu.obs.sinks import RingSink
from cs744_pytorch_distributed_tutorial_tpu.parallel import make_mesh
from cs744_pytorch_distributed_tutorial_tpu.parallel.elastic import (
    default_remesh,
    surviving_mesh,
)
from cs744_pytorch_distributed_tutorial_tpu.train import (
    LMConfig,
    LMTrainer,
    Trainer,
)
from cs744_pytorch_distributed_tutorial_tpu.utils.chaos import (
    ChaosMonkey,
    FaultSchedule,
    SigtermFailure,
    run_chaos,
    trap_sigterm,
)
from cs744_pytorch_distributed_tutorial_tpu.utils.checkpoint import (
    Checkpointer,
)

TINY_LM = dict(
    vocab_size=32, num_layers=1, num_heads=2, d_model=16, d_ff=32,
    max_seq_len=64, seq_len=16, global_batch_size=8,
    attention_impl="dense",
)


def test_fault_schedule_validates_and_pops():
    s = FaultSchedule({3: "nan", 5: {"kind": "device_loss", "lost": [2]}})
    assert len(s) == 2
    assert s.pop(3) == {"kind": "nan"}
    assert s.pop(3) is None  # fires once
    assert len(s) == 1
    with pytest.raises(ValueError, match="fault kind"):
        FaultSchedule({1: "meteor_strike"})


def test_fault_schedule_seeded_is_reproducible():
    kw = dict(n_calls=50, rate=0.2, kinds=("nan", "sigterm"))
    a = FaultSchedule.seeded(7, **kw)
    b = FaultSchedule.seeded(7, **kw)
    assert a.faults == b.faults
    assert len(a) > 0
    assert all(1 <= idx < 50 for idx in a.faults)
    c = FaultSchedule.seeded(8, **kw)
    assert a.faults != c.faults


def test_trap_sigterm_converts_to_training_failure():
    import os
    import signal

    with trap_sigterm():
        with pytest.raises(SigtermFailure):
            os.kill(os.getpid(), signal.SIGTERM)
            # the raise lands at a bytecode boundary right after kill
            for _ in range(1000):
                pass


@pytest.mark.slow  # chaos-smoke CI runs these without the tier-1 filter
@pytest.mark.slow  # chaos-smoke CI runs these without the tier-1 filter
def test_cifar_nan_chaos_recovers_in_memory_bitwise(mesh4):
    """NaN injected mid-run, recovery from the in-memory snapshot tier
    only (no checkpoint_dir): zero filesystem restores, final params
    bitwise equal to the uninterrupted run."""
    base = dict(**TINY_DP4_CFG, sync="allreduce", log_every=1)
    clean = Trainer(TrainConfig(**base), mesh=mesh4)
    clean_state, _ = clean.fit()
    clean_params = jax.device_get(clean_state.params)

    tr = Trainer(
        TrainConfig(**base, snapshot_every=1), mesh=mesh4
    )
    assert tr.memstore is not None
    ring = RingSink()
    disk_restores_before = Checkpointer.total_restores
    state, history, restarts, monkey = run_chaos(
        tr, FaultSchedule({2: "nan"}), telemetry=ring, max_restarts=2
    )
    assert restarts == 1
    assert monkey.injected == [(2, "nan")]
    # zero-filesystem-read recovery: every restore came from host RAM
    assert Checkpointer.total_restores == disk_restores_before
    assert tr.memstore.restores >= 1
    assert int(np.asarray(state.step)) == 4
    jax.tree.map(
        lambda a, b: np.testing.assert_array_equal(
            np.asarray(a), np.asarray(b)
        ),
        clean_params,
        jax.device_get(state.params),
    )
    # the run's timeline is one event stream: injection, restart, done
    events = [
        r["event"] for r in ring.records() if r.get("kind") == "event"
    ]
    assert "chaos_inject" in events
    assert "recovery_restart" in events
    assert "recovery_complete" in events
    assert events.index("chaos_inject") < events.index("recovery_restart")


@pytest.mark.slow  # chaos-smoke CI runs these without the tier-1 filter
def test_lm_sigterm_chaos_resumes_bitwise():
    """A real SIGTERM (preemption notice) lands between steps; the
    restart resumes from the newest in-memory snapshot and the resumed
    loss curve is bitwise equal to the uninterrupted run's tail."""
    mesh = make_mesh({"data": 2, "seq": 1}, devices=jax.devices()[:2])
    tokens = synthetic_tokens(8, 16, 32, seed=0)

    clean = LMTrainer(
        LMConfig(**TINY_LM, data_parallel=2), mesh=mesh
    )
    _, _, clean_losses = clean.fit(tokens, steps=4)

    tr = LMTrainer(
        LMConfig(**TINY_LM, data_parallel=2, snapshot_every=1), mesh=mesh
    )
    disk_restores_before = Checkpointer.total_restores
    params, opt, losses, restarts, monkey = run_chaos(
        tr, FaultSchedule({2: "sigterm"}), fit_args=(tokens, 4),
        max_restarts=2,
    )
    assert restarts == 1
    assert monkey.injected == [(2, "sigterm")]
    assert Checkpointer.total_restores == disk_restores_before
    assert np.isfinite(losses).all()
    # the final fit call returns the resumed tail — bitwise equal to the
    # same steps of the clean trajectory (f32 host round-trip is exact)
    np.testing.assert_array_equal(
        np.asarray(losses), np.asarray(clean_losses[-len(losses):])
    )


@pytest.mark.slow  # chaos-smoke CI runs these without the tier-1 filter
def test_lm_device_loss_remeshes_zero1_and_continues():
    """Device loss on a dp4 zero1 run: recovery re-meshes onto the two
    survivors, the in-memory snapshot reshards (chunked moments through
    the elastic adapt hook) with zero filesystem reads, and the resumed
    dp2 trajectory matches the uninterrupted dp4 run at rtol 1e-6."""
    devices = jax.devices()[:4]
    mesh = make_mesh({"data": 4, "seq": 1}, devices=devices)
    tokens = synthetic_tokens(8, 16, 32, seed=0)

    clean = LMTrainer(
        LMConfig(**TINY_LM, data_parallel=4, zero1=True), mesh=mesh
    )
    _, _, clean_losses = clean.fit(tokens, steps=6)

    tr = LMTrainer(
        LMConfig(**TINY_LM, data_parallel=4, zero1=True, snapshot_every=1),
        mesh=mesh,
    )
    memstore = tr.memstore
    lost = [d.id for d in devices[2:]]
    disk_restores_before = Checkpointer.total_restores
    params, opt, losses, restarts, monkey = run_chaos(
        tr,
        FaultSchedule({2: {"kind": "device_loss", "lost": lost}}),
        remesh=default_remesh,
        fit_args=(tokens, 6),
        max_restarts=2,
    )
    assert restarts == 1
    assert monkey.injected == [(2, "device_loss")]
    assert Checkpointer.total_restores == disk_restores_before
    assert memstore.restores >= 1  # carried onto the replacement trainer
    # the dp2 world re-chunked the zero1 moments and continued the SAME
    # trajectory (reduction order differs across world sizes)
    np.testing.assert_allclose(
        np.asarray(losses),
        np.asarray(clean_losses[-len(losses):]),
        rtol=1e-6,
    )
    # every leaf of the recovered state lives on the 2-device world
    for leaf in jax.tree.leaves(params):
        assert {d.id for d in leaf.sharding.device_set} <= {
            d.id for d in devices[:2]
        }


@pytest.mark.slow  # chaos-smoke CI runs these without the tier-1 filter
@pytest.mark.slow  # chaos-smoke CI runs these without the tier-1 filter
def test_chaos_monkey_counter_spans_restarts(mesh4):
    """The cumulative call counter means a transient fault fires ONCE
    even though recovery replays earlier calls — total calls exceed the
    schedule's index by the replayed steps."""
    base = dict(**TINY_DP4_CFG, sync="allreduce", log_every=1)
    tr = Trainer(TrainConfig(**base, snapshot_every=1), mesh=mesh4)
    monkey = ChaosMonkey(FaultSchedule({1: "nan"}))
    state, history, restarts, monkey = run_chaos(
        tr, monkey, max_restarts=2
    )
    assert restarts == 1
    assert len(monkey.injected) == 1
    assert monkey.calls > 4  # 4-step epoch plus the replayed steps
    assert int(np.asarray(state.step)) == 4
