"""Torch <-> flax VGG checkpoint conversion (models/torch_interop.py).

The switching path for a reference user: weights trained by the torch
``_VGG`` (``master/part1/model.py``) load into this framework's flax
``VGG`` and back. Verified against ACTUAL torch (CPU build in the image):
eval-mode forward parity through the full VGG-11 stack, and exact
round-trips in both directions.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
import pytest

torch = pytest.importorskip("torch")

from cs744_pytorch_distributed_tutorial_tpu.models.torch_interop import (  # noqa: E402
    torch_state_dict_from_vgg_variables,
    vgg_variables_from_torch_state_dict,
)
from cs744_pytorch_distributed_tutorial_tpu.models.vgg import vgg11  # noqa: E402


def _reference_vgg11():
    """The reference's _VGG('VGG11') rebuilt layer-for-layer
    (master/part1/model.py:11-46) — structure only, no code reuse."""
    import torch.nn as nn

    cfg = (64, "M", 128, "M", 256, 256, "M", 512, 512, "M", 512, 512, "M")
    layers: list = []
    c_in = 3
    for entry in cfg:
        if entry == "M":
            layers.append(nn.MaxPool2d(2, 2))
        else:
            layers.append(nn.Conv2d(c_in, entry, 3, 1, 1, bias=True))
            layers.append(nn.BatchNorm2d(entry))
            layers.append(nn.ReLU(inplace=True))
            c_in = entry

    class Ref(nn.Module):
        def __init__(self):
            super().__init__()
            self.layers = nn.Sequential(*layers)
            self.fc1 = nn.Linear(512, 10)

        def forward(self, x):
            y = self.layers(x)
            return self.fc1(y.view(y.size(0), -1))

    return Ref()


@pytest.fixture(scope="module")
def tmodel():
    torch.manual_seed(7)
    m = _reference_vgg11()
    # Non-trivial running stats so eval-mode parity exercises them.
    m.train()
    with torch.no_grad():
        m(torch.randn(8, 3, 32, 32))
    m.eval()
    return m


def test_torch_to_flax_eval_parity(tmodel):
    variables = vgg_variables_from_torch_state_dict(tmodel.state_dict())
    x = np.random.default_rng(0).standard_normal((4, 32, 32, 3)).astype(
        np.float32
    )
    fy = vgg11().apply(
        {
            "params": variables["params"],
            "batch_stats": variables["batch_stats"],
        },
        jnp.asarray(x),
        train=False,
    )
    with torch.no_grad():
        ty = tmodel(torch.from_numpy(x.transpose(0, 3, 1, 2).copy()))
    np.testing.assert_allclose(
        np.asarray(fy), ty.numpy(), rtol=1e-4, atol=1e-4
    )


def test_round_trip_exact(tmodel):
    sd = tmodel.state_dict()
    variables = vgg_variables_from_torch_state_dict(sd)
    back = torch_state_dict_from_vgg_variables(variables)
    for k, v in sd.items():
        if k.endswith("num_batches_tracked"):
            continue  # no flax counterpart, regenerated as 0
        np.testing.assert_array_equal(back[k], v.numpy(), err_msg=k)
    # And the reverse direction loads cleanly into a fresh torch model.
    m2 = _reference_vgg11()
    m2.load_state_dict(
        {k: torch.as_tensor(np.asarray(v).copy()) for k, v in back.items()}
    )


def test_flax_init_exports_to_torch(tmodel):
    import jax

    variables = vgg11().init(
        jax.random.key(0), jnp.zeros((1, 32, 32, 3), jnp.float32)
    )
    sd = torch_state_dict_from_vgg_variables(variables)
    m = _reference_vgg11()
    m.load_state_dict({k: torch.as_tensor(np.asarray(v).copy()) for k, v in sd.items()})
    m.eval()
    x = np.random.default_rng(1).standard_normal((2, 32, 32, 3)).astype(
        np.float32
    )
    fy = vgg11().apply(
        {
            "params": variables["params"],
            "batch_stats": variables["batch_stats"],
        },
        jnp.asarray(x),
        train=False,
    )
    with torch.no_grad():
        ty = m(torch.from_numpy(x.transpose(0, 3, 1, 2).copy()))
    np.testing.assert_allclose(np.asarray(fy), ty.numpy(), rtol=1e-4, atol=1e-4)


def test_unknown_arch_and_wrong_head_rejected(tmodel):
    with pytest.raises(ValueError, match="unknown arch"):
        vgg_variables_from_torch_state_dict(tmodel.state_dict(), arch="vgg12")
    sd = dict(tmodel.state_dict())
    sd["fc1.weight"] = torch.zeros(10, 2048)
    with pytest.raises(ValueError, match="512-feature head"):
        vgg_variables_from_torch_state_dict(sd)


def test_bf16_state_dict_imports(tmodel):
    """ADVICE r3: _np must widen bf16/half tensors before .numpy()
    (no numpy dtype exists for them) — same contract as hf_interop."""
    import torch

    sd = {k: v.to(torch.bfloat16) if v.is_floating_point() else v
          for k, v in tmodel.state_dict().items()}
    variables = vgg_variables_from_torch_state_dict(sd)
    ref = vgg_variables_from_torch_state_dict(tmodel.state_dict())
    a = jax.tree.leaves(variables)[0]
    b = jax.tree.leaves(ref)[0]
    # bf16 rounding, not garbage: close to the fp32 import.
    np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=0.02)
