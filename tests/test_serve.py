"""Continuous-batching serving engine (serve/): the PR's contracts.

Four pins, in dependency order:

1. **Paged == dense, bitwise.** ``mode="paged_decode"`` gathers the
   slot's pages into the dense cache layout and runs the SAME
   ``decode_attention`` einsum, so per-step logits must match the dense
   cache path to the bit (float32 and int8-KV) — not approximately:
   a tolerance here would hide an off-by-one page index.
2. **Engine == make_generator, token for token** (greedy). The whole
   request lifecycle — bucketed prefill+commit, slot decode, retire —
   must reproduce batch-at-a-time generation per request.
3. **Zero retraces across slot churn.** Retire/refill/preempt change
   batch membership every which way; the fixed-shape decode step must
   never recompile post-warmup (graftlint GL002 made executable).
4. **Preemption is safe.** A pool too small for the offered load forces
   LIFO recompute preemption; every request must still complete with
   its full budget (admission guarantees the oldest always fits alone).

Pins 2 and 3 run under BOTH decode-attention implementations: the
gather+einsum reference and the Pallas paged-attention kernel
(``paged_attention_impl="kernel"``, interpret mode on CPU — kernel-level
parity lives in tests/test_paged_attention.py). Newer contracts ride the
same harness: per-request PRNG streams make preemption-recompute
output-invariant for SAMPLED requests too, tokens stream out as they
decode (``on_token`` / ``iter_tokens``, ITL measured by the loadgen),
and ``scan_layers`` models serve token-identically to unrolled ones.

Plus the host-side units (PagePool), the load generator's determinism
and telemetry, and the regress.py budget gate the CI serve-smoke job
relies on.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from cs744_pytorch_distributed_tutorial_tpu.infer import make_generator
from cs744_pytorch_distributed_tutorial_tpu.models import TransformerLM
from cs744_pytorch_distributed_tutorial_tpu.serve import (
    PagePool,
    Request,
    ServeConfig,
    ServingEngine,
    make_poisson_workload,
    run_poisson,
)

VOCAB = 61


class _ListSink:
    def __init__(self):
        self.records = []

    def emit(self, record):
        self.records.append(dict(record))


@pytest.fixture(scope="module")
def tiny_lm():
    model = TransformerLM(
        vocab_size=VOCAB,
        num_layers=2,
        num_heads=2,
        d_model=32,
        d_ff=64,
        max_seq_len=64,
        attention_impl="dense",
        use_rope=True,
    )
    params = model.init(
        jax.random.key(0), jnp.zeros((1, 4), jnp.int32)
    )["params"]
    return model, params


# ---------------------------------------------------------------- pool


def test_page_pool_reserves_trash_page():
    pool = PagePool(num_pages=8, page_size=4)
    assert pool.free_pages == 7  # page 0 reserved
    got = pool.alloc(7)
    assert 0 not in got
    assert sorted(got) == list(range(1, 8))
    with pytest.raises(RuntimeError, match="exhausted"):
        pool.alloc(1)


def test_page_pool_lifo_reuse_and_high_water():
    pool = PagePool(num_pages=8, page_size=4)
    a = pool.alloc(3)
    assert a == [1, 2, 3]
    pool.free([2])
    # the just-freed page comes back first (LIFO)
    assert pool.alloc(1) == [2]
    assert pool.high_water == 3
    pool.free([1, 2, 3])
    assert pool.allocated_pages == 0
    assert pool.high_water == 3  # high water does not recede


def test_page_pool_rejects_bad_frees():
    pool = PagePool(num_pages=8, page_size=4)
    pages = pool.alloc(2)
    pool.free(pages)
    with pytest.raises(ValueError, match="double free"):
        pool.free([pages[0]])
    with pytest.raises(ValueError, match="trash page"):
        pool.free([0])
    with pytest.raises(ValueError, match="out of range"):
        pool.free([99])
    with pytest.raises(ValueError, match="num_pages must be >= 2"):
        PagePool(num_pages=1, page_size=4)


def test_page_pool_pages_for_is_ceil():
    pool = PagePool(num_pages=8, page_size=4)
    assert [pool.pages_for(n) for n in (1, 4, 5, 8, 9)] == [1, 1, 2, 2, 3]


# ------------------------------------------------- paged/dense parity


def _commit_cache_to_pages(pages, cache, page_tables, true_len):
    """Reference host-side commit: scatter each batch row's first
    ``true_len`` dense-cache rows into that row's pages (the same
    mapping the engine's fused prefill does on device)."""

    def walk(p, c):
        if "key_pages" in p:
            out = {}
            for cname, pname in (
                ("cached_key", "key_pages"),
                ("cached_value", "value_pages"),
                ("key_scale", "key_scale_pages"),
                ("value_scale", "value_scale_pages"),
            ):
                if pname not in p:
                    continue
                pool = np.asarray(p[pname]).copy()
                rows = np.asarray(c[cname])
                page_size = pool.shape[1]
                for b in range(rows.shape[0]):
                    for i in range(true_len):
                        pool[page_tables[b, i // page_size], i % page_size] = (
                            rows[b, i]
                        )
                out[pname] = jnp.asarray(pool)
            return out
        return {k: walk(p[k], c[k]) for k in p}

    return walk(pages, cache)


@pytest.mark.parametrize("quant_kv", [False, True])
def test_paged_decode_logits_bitwise_match_dense(tiny_lm, quant_kv):
    """Per-step decode logits from the page pools must equal the dense
    cache path's EXACTLY (same einsum over a gathered view — any
    difference is a paging bug, so no tolerance)."""
    model, params = tiny_lm
    page_size, num_pages, ppr = 4, 16, 4  # ppr = pages per row
    dense = model.clone(quant_kv_cache=quant_kv)
    paged = dense.clone(page_size=page_size, num_pages=num_pages)
    B, t0, steps = 2, 6, 5
    tokens = jax.random.randint(jax.random.key(1), (B, t0 + steps), 0, VOCAB)

    # dense prefill gives both the reference cache and the rows to page
    _, variables = dense.apply(
        {"params": params}, tokens[:, :t0], mode="prefill", mutable=["cache"]
    )
    cache = variables["cache"]

    page_tables = np.asarray(
        [[1 + r * ppr + i for i in range(ppr)] for r in range(B)], np.int32
    )
    pages = paged.init(
        jax.random.key(0),
        jnp.zeros((B, 1), jnp.int32),
        mode="paged_decode",
        decode_pos=jnp.zeros((B,), jnp.int32),
        page_table=jnp.asarray(page_tables),
    )["pages"]
    pages = _commit_cache_to_pages(pages, cache, page_tables, t0)

    for pos in range(t0, t0 + steps):
        step = tokens[:, pos : pos + 1]
        dense_logits, mutated = dense.apply(
            {"params": params, "cache": cache},
            step,
            mode="decode",
            decode_pos=jnp.asarray(pos, jnp.int32),
            mutable=["cache"],
        )
        cache = mutated["cache"]
        paged_logits, mutated = paged.apply(
            {"params": params, "pages": pages},
            step,
            mode="paged_decode",
            decode_pos=jnp.full((B,), pos, jnp.int32),
            page_table=jnp.asarray(page_tables),
            mutable=["pages"],
        )
        pages = mutated["pages"]
        np.testing.assert_array_equal(
            np.asarray(paged_logits), np.asarray(dense_logits)
        )


# --------------------------------------------------- engine lifecycle


def _reference_tokens(model, params, prompt, budget):
    gen = make_generator(model, max_new_tokens=budget, temperature=0.0)
    return np.asarray(
        gen(params, np.asarray(prompt, np.int32)[None], jax.random.key(0))
    )[0].tolist()


@pytest.mark.parametrize("impl", ["gather", "kernel"])
def test_engine_greedy_matches_make_generator(tiny_lm, impl):
    """Request-level output == batch generator output, token for token,
    across different prompt lengths, budgets, and admission order —
    under both decode-attention implementations."""
    model, params = tiny_lm
    cfg = ServeConfig(num_slots=2, page_size=4, num_pages=33,
                      max_pages_per_slot=8, paged_attention_impl=impl)
    eng = ServingEngine(model, params, cfg)
    rng = np.random.default_rng(7)
    cases = [(3, 9), (7, 4), (12, 11), (5, 17), (9, 6)]
    reqs = [
        eng.submit(Request(
            prompt=rng.integers(1, VOCAB, size=plen).astype(np.int32),
            max_new_tokens=budget,
        ))
        for plen, budget in cases
    ]
    eng.run()
    assert all(r.done_time is not None for r in reqs)
    for r in reqs:
        expect = _reference_tokens(
            model, params, r.prompt, r.max_new_tokens
        )
        assert r.generated == expect, (r.req_id, r.generated, expect)


@pytest.mark.parametrize("impl", ["gather", "kernel"])
def test_engine_zero_retraces_across_slot_churn(tiny_lm, impl):
    """The fixed-shape decode step never recompiles once warm, no
    matter how membership churns (the GL002 contract, measured) — the
    Pallas kernel keeps the invariant because live length enters via
    the grid mask, never the shape."""
    from cs744_pytorch_distributed_tutorial_tpu.obs.system import (
        CompileCounter,
    )

    model, params = tiny_lm
    cfg = ServeConfig(num_slots=3, page_size=4, num_pages=33,
                      max_pages_per_slot=8, paged_attention_impl=impl)
    eng = ServingEngine(model, params, cfg)
    rng = np.random.default_rng(11)

    def burst(sizes):
        for plen, budget in sizes:
            eng.submit(Request(
                prompt=rng.integers(1, VOCAB, size=plen).astype(np.int32),
                max_new_tokens=budget,
            ))
        eng.run()

    burst([(4, 3), (8, 5)])  # warmup: compiles prefill buckets + decode
    cc = CompileCounter()
    # same buckets, wildly different membership patterns
    burst([(3, 8), (6, 2), (8, 7), (5, 3), (7, 12), (4, 2)])
    assert cc.count == 0, f"{cc.count} retraces during slot churn"
    assert len(eng._completed) == 8


def test_engine_preemption_completes_everything(tiny_lm):
    """A pool too small for the load forces LIFO recompute preemption;
    every request still finishes with its FULL budget and greedy output
    still matches the reference (recompute must be lossless)."""
    model, params = tiny_lm
    # 8 allocatable pages, slots want up to 7 each -> guaranteed fights
    cfg = ServeConfig(num_slots=3, page_size=4, num_pages=9,
                      max_pages_per_slot=7)
    eng = ServingEngine(model, params, cfg)
    rng = np.random.default_rng(13)
    cases = [(6, 18), (10, 14), (8, 16), (5, 20), (12, 12)]
    reqs = [
        eng.submit(Request(
            prompt=rng.integers(1, VOCAB, size=plen).astype(np.int32),
            max_new_tokens=budget,
        ))
        for plen, budget in cases
    ]
    eng.run()
    assert eng.stats()["preemptions"] > 0, "pool was not tight enough"
    for (plen, budget), r in zip(cases, reqs):
        assert r.output_tokens == budget, (r.req_id, r.output_tokens)
    # greedy determinism survives preemption: outputs equal the
    # no-preemption reference (recompute re-derives the same KV, so the
    # stream picks up exactly where it left off)
    for (plen, budget), r in zip(cases, reqs):
        # a preempted request's prompt absorbed its early generations;
        # the produced stream is that absorbed tail + the final tail
        produced = list(r.prompt[r.orig_prompt_len :]) + r.generated
        expect = _reference_tokens(
            model, params, r.prompt[: r.orig_prompt_len], budget
        )
        assert produced == expect, (r.req_id, produced, expect)


def test_engine_pages_recycle(tiny_lm):
    """After a drain every page is back in the pool, and high_water
    stayed within the allocatable budget."""
    model, params = tiny_lm
    cfg = ServeConfig(num_slots=2, page_size=4, num_pages=17,
                      max_pages_per_slot=8)
    eng = ServingEngine(model, params, cfg)
    rng = np.random.default_rng(17)
    for plen, budget in [(4, 6), (9, 8), (6, 10), (11, 5)]:
        eng.submit(Request(
            prompt=rng.integers(1, VOCAB, size=plen).astype(np.int32),
            max_new_tokens=budget,
        ))
    eng.run()
    assert eng.pool.allocated_pages == 0
    assert eng.pool.free_pages == cfg.num_pages - 1
    assert 0 < eng.pool.high_water <= cfg.num_pages - 1


def test_engine_eos_stops_early(tiny_lm):
    """An eos_id sampled mid-stream retires the slot before the budget
    is spent (and the emitted record reflects the short output)."""
    model, params = tiny_lm
    budget = 12
    prompt = np.asarray([1, 2, 3, 4], np.int32)
    ref = _reference_tokens(model, params, prompt, budget)
    eos = ref[3]  # force a stop 4 tokens in
    sink = _ListSink()
    cfg = ServeConfig(num_slots=2, page_size=4, num_pages=17,
                      max_pages_per_slot=8, eos_id=eos)
    eng = ServingEngine(model, params, cfg, sink=sink)
    req = eng.submit(Request(prompt=prompt, max_new_tokens=budget))
    eng.run()
    assert req.generated == ref[:4]
    recs = [r for r in sink.records if r.get("kind") == "serve"]
    assert len(recs) == 1 and recs[0]["output_tokens"] == 4


def test_engine_submit_validation(tiny_lm):
    model, params = tiny_lm
    cfg = ServeConfig(num_slots=2, page_size=4, num_pages=17,
                      max_pages_per_slot=4)
    eng = ServingEngine(model, params, cfg)
    with pytest.raises(ValueError, match="empty prompt"):
        eng.submit(Request(prompt=np.zeros((0,), np.int32), max_new_tokens=4))
    with pytest.raises(ValueError, match="max_new_tokens"):
        eng.submit(Request(prompt=np.ones((4,), np.int32), max_new_tokens=0))
    with pytest.raises(ValueError, match="exceeds max_seq_len"):
        eng.submit(Request(prompt=np.ones((60,), np.int32), max_new_tokens=8))
    # fits max_seq_len but not a slot's page-table row
    with pytest.raises(ValueError, match="caps a slot at 4 pages"):
        eng.submit(Request(prompt=np.ones((20,), np.int32), max_new_tokens=8))


def test_engine_sampled_preemption_replays_prng(tiny_lm):
    """A preempted SAMPLED request reproduces its original tokens on
    recompute: token t of request r always samples from the same
    fold_in(fold_in(root, r), t) key — slot, step count, and batch
    membership never enter the stream — so a pool-starved run with
    preemptions emits exactly what an ample-pool run emits."""
    model, params = tiny_lm
    sample = dict(temperature=0.9, top_k=20, seed=3)
    cases = [(6, 18), (10, 14), (8, 16), (5, 20), (12, 12)]

    def run(cfg):
        eng = ServingEngine(model, params, cfg)
        rng = np.random.default_rng(13)
        reqs = [
            eng.submit(Request(
                prompt=rng.integers(1, VOCAB, size=plen).astype(np.int32),
                max_new_tokens=budget,
            ))
            for plen, budget in cases
        ]
        eng.run()
        # preemption absorbs early generations into the prompt; compare
        # the full produced streams
        return eng, [
            list(r.prompt[r.orig_prompt_len:]) + r.generated for r in reqs
        ]

    tight, tight_out = run(ServeConfig(
        num_slots=3, page_size=4, num_pages=9, max_pages_per_slot=7,
        **sample,
    ))
    ample, ample_out = run(ServeConfig(
        num_slots=3, page_size=4, num_pages=33, max_pages_per_slot=8,
        **sample,
    ))
    assert tight.stats()["preemptions"] > 0, "pool was not tight enough"
    assert ample.stats()["preemptions"] == 0
    assert tight_out == ample_out


def test_engine_streams_tokens(tiny_lm):
    """Tokens surface as they decode, not at retire: the on_token
    callback sees every token in order, token_times stamps each one,
    and iter_tokens streams a request while the rest of the batch keeps
    decoding."""
    model, params = tiny_lm
    cfg = ServeConfig(num_slots=2, page_size=4, num_pages=33,
                      max_pages_per_slot=8)
    seen: list[tuple[int, int]] = []
    eng = ServingEngine(
        model, params, cfg,
        on_token=lambda r, t: seen.append((r.req_id, t)),
    )
    rng = np.random.default_rng(31)
    r0 = eng.submit(Request(
        prompt=rng.integers(1, VOCAB, size=5).astype(np.int32),
        max_new_tokens=8,
    ))
    r1 = eng.submit(Request(
        prompt=rng.integers(1, VOCAB, size=7).astype(np.int32),
        max_new_tokens=6,
    ))
    streamed = list(eng.iter_tokens(r0))
    assert streamed == r0.generated
    assert r0.done_time is not None
    eng.run()
    for r in (r0, r1):
        assert [t for rid, t in seen if rid == r.req_id] == r.generated
        assert len(r.token_times) == r.output_tokens
        assert all(
            b >= a for a, b in zip(r.token_times, r.token_times[1:])
        )


@pytest.mark.parametrize("impl", ["gather", "kernel"])
def test_engine_scan_layers_matches_unrolled(tiny_lm, impl):
    """A scan_layers model serves token-identically to the unrolled
    reference: the prefill commit scatters KV rows for ALL scanned
    layers at once (stacked pools, no unrolling) and decode runs the
    stacked step."""
    from cs744_pytorch_distributed_tutorial_tpu.models import (
        stack_block_params,
    )

    model, params = tiny_lm
    cfg = ServeConfig(num_slots=2, page_size=4, num_pages=33,
                      max_pages_per_slot=8, paged_attention_impl=impl)
    eng = ServingEngine(
        model.clone(scan_layers=True), stack_block_params(params), cfg
    )
    rng = np.random.default_rng(37)
    cases = [(5, 7), (9, 5), (3, 10)]
    reqs = [
        eng.submit(Request(
            prompt=rng.integers(1, VOCAB, size=plen).astype(np.int32),
            max_new_tokens=budget,
        ))
        for plen, budget in cases
    ]
    eng.run()
    for r in reqs:
        expect = _reference_tokens(model, params, r.prompt, r.max_new_tokens)
        assert r.generated == expect, (r.req_id, r.generated, expect)


# ------------------------------------------------------------ loadgen


def test_poisson_workload_is_seeded_and_bounded():
    mk = lambda: make_poisson_workload(
        num_requests=16, rate_rps=100.0, prompt_len=(3, 9),
        output_len=(2, 7), vocab_size=VOCAB, seed=5,
    )
    w1, w2 = mk(), mk()
    assert np.array_equal(w1.arrivals, w2.arrivals)
    assert all(np.array_equal(a, b) for a, b in zip(w1.prompts, w2.prompts))
    assert np.array_equal(w1.max_new_tokens, w2.max_new_tokens)
    assert w1.arrivals[0] == 0.0
    assert np.all(np.diff(w1.arrivals) >= 0)
    assert all(3 <= len(p) <= 9 and p.min() >= 1 for p in w1.prompts)
    assert w1.max_new_tokens.min() >= 2 and w1.max_new_tokens.max() <= 7
    with pytest.raises(ValueError, match="rate_rps"):
        make_poisson_workload(
            num_requests=1, rate_rps=0.0, prompt_len=(3, 9),
            output_len=(2, 7), vocab_size=VOCAB,
        )


def test_run_poisson_emits_summary_and_bench_twins(tiny_lm):
    """One short open-loop replay: every request completes, the summary
    record carries the serving metrics, and the bench-shaped twins
    (metric/value) land on the sink for regress.py to gate. Warmup
    requests must NOT leak into the sink or the counts."""
    model, params = tiny_lm
    sink = _ListSink()
    cfg = ServeConfig(num_slots=3, page_size=4, num_pages=33,
                      max_pages_per_slot=8)
    eng = ServingEngine(model, params, cfg, sink=sink)
    wl = make_poisson_workload(
        num_requests=6, rate_rps=500.0, prompt_len=(3, 8),
        output_len=(2, 6), vocab_size=VOCAB, seed=3,
    )
    record = run_poisson(eng, wl, sink=sink, warmup=True)
    assert record["requests"] == 6
    assert record["total_output_tokens"] == int(wl.max_new_tokens.sum())
    assert record["tokens_per_sec"] > 0
    assert record["ttft_p99_ms"] >= record["ttft_p50_ms"] >= 0
    # streamed-token gaps were measured, not derived from the mean
    assert record["itl_p99_ms"] >= record["itl_p50_ms"] >= 0
    assert record["itl_p99_ms"] > 0

    serve_recs = [r for r in sink.records if r.get("kind") == "serve"]
    assert len(serve_recs) == 6  # measured requests only, no warmup
    assert len({r["id"] for r in serve_recs}) == 6
    summaries = [r for r in sink.records if r.get("kind") == "serve_summary"]
    assert len(summaries) == 1 and summaries[0]["engine"] == "continuous"
    twins = {
        r["metric"]: r["value"]
        for r in sink.records
        if r.get("kind") == "bench"
    }
    assert twins["serve_tokens_per_sec"] == record["tokens_per_sec"]
    assert twins["serve_ttft_p99_ms"] == record["ttft_p99_ms"]
    assert twins["serve_itl_p99_ms"] == record["itl_p99_ms"]


def test_metrics_summary_renders_serve_rows(tmp_path):
    import importlib.util as ilu
    import os

    spec = ilu.spec_from_file_location(
        "metrics_summary",
        os.path.join(os.path.dirname(__file__), os.pardir, "benchmarks",
                     "metrics_summary.py"),
    )
    ms = ilu.module_from_spec(spec)
    spec.loader.exec_module(ms)
    records = [
        {"kind": "serve_summary", "engine": "continuous", "requests": 6,
         "ttft_p50_ms": 4.0, "ttft_p99_ms": 9.0, "itl_p50_ms": 2.0,
         "itl_p99_ms": 6.0, "tokens_per_sec": 310.0,
         "page_high_water": 12, "slot_occupancy": 0.8, "preemptions": 1},
        {"kind": "serve_summary", "engine": "batch", "requests": 6,
         "ttft_p50_ms": 900.0, "ttft_p99_ms": 2900.0,
         "tokens_per_sec": 40.0},
    ]
    summary = ms.summarize(records)
    assert set(summary["serve"]) == {"continuous", "batch"}
    assert summary["serve"]["continuous"]["tokens_per_sec"] == 310.0
    assert summary["serve"]["continuous"]["itl_p99_ms"] == 6.0
    assert summary["serve"]["batch"]["ttft_p99_ms"] == 2900.0
    assert summary["serve"]["batch"]["itl_p99_ms"] is None  # no streaming


# ------------------------------------------------------- regress gate


def _regress():
    import importlib.util as ilu
    import os

    spec = ilu.spec_from_file_location(
        "regress",
        os.path.join(os.path.dirname(__file__), os.pardir, "benchmarks",
                     "regress.py"),
    )
    mod = ilu.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def test_regress_generic_budgets_gate_serve_metrics():
    """The serve_smoke_budget.json idiom: baseline records with
    metric+budget arm absolute gates on the current stream — a
    throughput floor (direction min) and a latency ceiling (max)."""
    rg = _regress()
    baseline = [
        {"metric": "serve_tokens_per_sec", "value": 300.0, "budget": 40.0,
         "direction": "min"},
        {"metric": "serve_ttft_p99_ms", "value": 15.0, "budget": 1500.0,
         "direction": "max"},
    ]
    current_ok = [
        {"kind": "bench", "metric": "serve_tokens_per_sec", "value": 250.0},
        {"kind": "bench", "metric": "serve_ttft_p99_ms", "value": 12.0},
    ]
    code, verdict = rg.evaluate(
        baseline, current_ok, metric="serve_tokens_per_sec", tolerance=0.85
    )
    assert code == rg.PASS, verdict
    assert all(b["ok"] for b in verdict["budgets"])

    # p99 blows the ceiling -> REGRESSION even though throughput passes
    current_slow = [
        {"kind": "bench", "metric": "serve_tokens_per_sec", "value": 250.0},
        {"kind": "bench", "metric": "serve_ttft_p99_ms", "value": 4000.0},
    ]
    code, verdict = rg.evaluate(
        baseline, current_slow, metric="serve_tokens_per_sec", tolerance=0.85
    )
    assert code == rg.REGRESSION
    bad = {b["metric"]: b["ok"] for b in verdict["budgets"]}
    assert bad == {"serve_tokens_per_sec": True, "serve_ttft_p99_ms": False}

    # throughput under the floor -> REGRESSION via the min-direction gate
    current_weak = [
        {"kind": "bench", "metric": "serve_tokens_per_sec", "value": 260.0},
        {"kind": "bench", "metric": "serve_ttft_p99_ms", "value": 12.0},
    ]
    weak_floor = [dict(baseline[0], budget=290.0), baseline[1]]
    code, _ = rg.evaluate(
        weak_floor, current_weak, metric="serve_tokens_per_sec",
        tolerance=0.85,
    )
    assert code == rg.REGRESSION

    # an armed budget with no current values is MISSING, not a pass
    code, verdict = rg.evaluate(
        baseline,
        [{"kind": "bench", "metric": "serve_tokens_per_sec", "value": 250.0}],
        metric="serve_tokens_per_sec", tolerance=0.85,
    )
    assert code == rg.MISSING
    assert "serve_ttft_p99_ms" in verdict["error"]


# --------------------------------------------------- tensor-parallel


@pytest.mark.slow
def test_tp_engine_greedy_matches_gathered():
    """Tensor-sharded serving: the engine on a tensor=2 mesh (KV pages
    sharded over heads) must emit exactly the tokens the mesh-free
    engine emits from the same (gathered) params."""
    from cs744_pytorch_distributed_tutorial_tpu.data.text import (
        synthetic_tokens,
    )
    from cs744_pytorch_distributed_tutorial_tpu.parallel import make_mesh
    from cs744_pytorch_distributed_tutorial_tpu.train.lm import (
        LMConfig,
        LMTrainer,
    )

    mesh = make_mesh({"data": 2, "seq": 1, "tensor": 2},
                     devices=jax.devices()[:4])
    cfg = LMConfig(
        vocab_size=64, num_layers=2, num_heads=4, d_model=32, d_ff=64,
        max_seq_len=64, attention_impl="dense", global_batch_size=4,
        seq_len=16, seed=11, data_parallel=2, tensor_parallel=2,
    )
    tr = LMTrainer(cfg, mesh=mesh)
    params, opt_state = tr.init()
    toks = synthetic_tokens(8, 16, 64, seed=0)
    for s in range(2):
        x, y = tr.shard_batch(toks[s * 4 : s * 4 + 4])
        params, opt_state, _ = tr.train_step(params, opt_state, x, y)

    scfg = ServeConfig(num_slots=2, page_size=4, num_pages=33,
                      max_pages_per_slot=8)
    cases = [(4, 6), (7, 5), (5, 8)]
    rng = np.random.default_rng(23)
    prompts = [
        rng.integers(1, 64, size=plen).astype(np.int32)
        for plen, _ in cases
    ]

    def run(engine):
        reqs = [
            engine.submit(Request(prompt=p.copy(), max_new_tokens=budget))
            for p, (_, budget) in zip(prompts, cases)
        ]
        engine.run()
        return [r.generated for r in reqs]

    tp_out = run(ServingEngine(
        tr.tp_decode_model(), params, scfg,
        mesh=tr.mesh, param_specs=tr.param_specs,
    ))
    gathered_out = run(ServingEngine(
        tr.decode_model(), tr.gather_for_decode(params), scfg
    ))
    assert tp_out == gathered_out
