"""Grouped-query attention (num_kv_heads): param shapes, cache size,
decode parity, seq-parallel training."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from cs744_pytorch_distributed_tutorial_tpu.infer import make_generator
from cs744_pytorch_distributed_tutorial_tpu.models import TransformerLM

KW = dict(vocab_size=61, num_layers=2, num_heads=4, d_model=32, d_ff=64,
          max_seq_len=32, attention_impl="dense")


def test_gqa_param_and_cache_shapes():
    model = TransformerLM(**KW, num_kv_heads=2)
    toks = jnp.zeros((2, 8), jnp.int32)
    params = model.init(jax.random.key(0), toks)["params"]
    attn = params["block_0"]["attn"]
    assert attn["q"]["kernel"].shape == (32, 32)
    assert attn["k"]["kernel"].shape == (32, 16)  # 2 kv heads * head_dim 8
    assert attn["v"]["kernel"].shape == (32, 16)

    _, variables = model.apply(
        {"params": params}, toks, mode="prefill", mutable=["cache"]
    )
    ck = variables["cache"]["block_0"]["attn"]["cached_key"]
    assert ck.shape == (2, 32, 2, 8)  # kv heads cached, not query heads


@pytest.mark.parametrize("kv", [1, 2])
def test_gqa_decode_matches_full_forward(kv):
    model = TransformerLM(**KW, num_kv_heads=kv, use_rope=True)
    params = model.init(jax.random.key(0), jnp.zeros((1, 4), jnp.int32))["params"]
    tokens = jax.random.randint(jax.random.key(1), (2, 10), 0, 61)
    full = model.apply({"params": params}, tokens)

    t0 = 4
    prefill, variables = model.apply(
        {"params": params}, tokens[:, :t0], mode="prefill", mutable=["cache"]
    )
    np.testing.assert_allclose(prefill, full[:, :t0], rtol=1e-5, atol=1e-5)
    cache = variables["cache"]
    for pos in range(t0, tokens.shape[1]):
        logits, mutated = model.apply(
            {"params": params, "cache": cache},
            tokens[:, pos : pos + 1],
            mode="decode",
            decode_pos=jnp.asarray(pos, jnp.int32),
            mutable=["cache"],
        )
        cache = mutated["cache"]
        np.testing.assert_allclose(
            logits[:, 0], full[:, pos], rtol=1e-5, atol=1e-5
        )


def test_gqa_rejects_indivisible_heads():
    for bad in (3, 0, -2):
        model = TransformerLM(**KW, num_kv_heads=bad)
        with pytest.raises(ValueError, match="num_kv_heads"):
            model.init(jax.random.key(0), jnp.zeros((1, 4), jnp.int32))


@pytest.mark.slow
def test_gqa_ring_rotates_kv_width_and_matches_dense():
    """ring/ring_flash accept kv-width K/V (blocks rotate at kv heads —
    the ICI saving) and match dense attention on repeated heads, forward
    and backward."""
    from jax.sharding import PartitionSpec as P

    from cs744_pytorch_distributed_tutorial_tpu.parallel import make_mesh
    from cs744_pytorch_distributed_tutorial_tpu.parallel.ring_attention import (
        dense_attention,
        ring_attention,
        ring_flash_attention,
    )

    mesh = make_mesh({"data": 4}, devices=jax.devices()[:4])
    ks = jax.random.split(jax.random.key(7), 3)
    q = jax.random.normal(ks[0], (2, 32, 8, 16))
    k = jax.random.normal(ks[1], (2, 32, 2, 16))  # 2 kv heads
    v = jax.random.normal(ks[2], (2, 32, 2, 16))
    kw, vw = jnp.repeat(k, 4, axis=2), jnp.repeat(v, 4, axis=2)
    expected = np.asarray(dense_attention(q, kw, vw, causal=True))

    def run(fn):
        mapped = jax.shard_map(
            fn, mesh=mesh,
            in_specs=(P(None, "data"),) * 3,
            out_specs=P(None, "data"),
            check_vma=False,
        )
        return mapped

    ring = run(lambda a, b, c: ring_attention(a, b, c, "data", 4, causal=True))
    np.testing.assert_allclose(
        np.asarray(jax.jit(ring)(q, k, v)), expected, rtol=2e-5, atol=2e-5
    )
    rf = run(lambda a, b, c: ring_flash_attention(a, b, c, "data", 4, True, True))
    np.testing.assert_allclose(
        np.asarray(jax.jit(rf)(q, k, v)), expected, rtol=2e-5, atol=2e-5
    )

    # Backward: ring_flash's group-summed dk/dv vs the dense formulation.
    def dense_loss(q, k, v):
        return (
            dense_attention(
                q, jnp.repeat(k, 4, 2), jnp.repeat(v, 4, 2), causal=True
            ) ** 2
        ).sum()

    def rf_loss(q, k, v):
        return (rf(q, k, v) ** 2).sum()

    gd = jax.grad(dense_loss, argnums=(0, 1, 2))(q, k, v)
    gr = jax.jit(jax.grad(rf_loss, argnums=(0, 1, 2)))(q, k, v)
    for a, b in zip(gd, gr):
        np.testing.assert_allclose(
            np.asarray(b), np.asarray(a), rtol=5e-4, atol=5e-4
        )


@pytest.mark.parametrize("kv,inner", [(2, "dense"), (2, "flash"), (4, "dense"),
                                      (1, "dense")])
def test_gqa_ulysses_matches_dense(kv, inner):
    """Ulysses with kv-width K/V (a2a at kv width when kv%axis==0, else
    widen-first) matches dense on repeated heads."""
    from jax.sharding import PartitionSpec as P

    from cs744_pytorch_distributed_tutorial_tpu.parallel import make_mesh
    from cs744_pytorch_distributed_tutorial_tpu.parallel.ring_attention import (
        dense_attention,
        ulysses_attention,
    )

    mesh = make_mesh({"data": 2}, devices=jax.devices()[:2])
    ks = jax.random.split(jax.random.key(kv), 3)
    q = jax.random.normal(ks[0], (2, 16, 8, 8))
    k = jax.random.normal(ks[1], (2, 16, kv, 8))
    v = jax.random.normal(ks[2], (2, 16, kv, 8))
    grp = 8 // kv
    expected = np.asarray(dense_attention(
        q, jnp.repeat(k, grp, 2), jnp.repeat(v, grp, 2), causal=True
    ))
    mapped = jax.shard_map(
        lambda a, b, c: ulysses_attention(
            a, b, c, "data", 2, causal=True, inner=inner, flash_interpret=True
        ),
        mesh=mesh,
        in_specs=(P(None, "data"),) * 3,
        out_specs=P(None, "data"),
        check_vma=False,
    )
    np.testing.assert_allclose(
        np.asarray(jax.jit(mapped)(q, k, v)), expected, rtol=2e-5, atol=2e-5
    )


def test_gqa_trains_seq_parallel_and_generates():
    from cs744_pytorch_distributed_tutorial_tpu.data import synthetic_tokens
    from cs744_pytorch_distributed_tutorial_tpu.parallel import make_mesh
    from cs744_pytorch_distributed_tutorial_tpu.train import LMConfig, LMTrainer

    cfg = LMConfig(vocab_size=64, num_layers=1, num_heads=4, num_kv_heads=2,
                   d_model=32, d_ff=64, max_seq_len=32, seq_len=16,
                   global_batch_size=4, attention_impl="ring",
                   data_parallel=2, seq_parallel=2, use_rope=True)
    tr = LMTrainer(cfg, mesh=make_mesh({"data": 2, "seq": 2}))
    tokens = synthetic_tokens(8, 16, 64, seed=0)
    params, _, losses = tr.fit(tokens, steps=2)
    assert np.isfinite(losses).all()

    out = make_generator(tr.decode_model(), max_new_tokens=4, temperature=0.0)(
        jax.device_get(params), jnp.asarray(tokens[:1, :8], jnp.int32),
        jax.random.key(0),
    )
    assert out.shape == (1, 4)


# ---------------------------------------------------------------------------
# Grouped Ulysses: ragged kv_heads (kv % axis != 0) keeps kv-width ICI
# ---------------------------------------------------------------------------
@pytest.mark.parametrize("kv", [1, 2])
def test_grouped_ulysses_ragged_kv_matches_dense(kv, mesh4):
    """kv_heads not divisible by the seq axis (the MQA/GQA configs that
    previously fell back to widen-first): the grouped exchange must
    still be exact — forward AND gradients — vs dense on repeated
    heads."""
    from jax.sharding import PartitionSpec as P

    from cs744_pytorch_distributed_tutorial_tpu.parallel import make_mesh
    from cs744_pytorch_distributed_tutorial_tpu.parallel.ring_attention import (
        dense_attention,
        ulysses_attention,
    )

    mesh = make_mesh({"data": 4}, devices=jax.devices()[:4])
    ks = jax.random.split(jax.random.key(10 + kv), 3)
    q = jax.random.normal(ks[0], (2, 16, 8, 8))
    k = jax.random.normal(ks[1], (2, 16, kv, 8))
    v = jax.random.normal(ks[2], (2, 16, kv, 8))
    grp = 8 // kv

    def dense_loss(q, k, v):
        out = dense_attention(
            q, jnp.repeat(k, grp, 2), jnp.repeat(v, grp, 2), causal=True
        )
        return (out**2).sum(), out

    mapped = jax.shard_map(
        lambda a, b, c: ulysses_attention(
            a, b, c, "data", 4, causal=True, inner="dense"
        ),
        mesh=mesh,
        in_specs=(P(None, "data"),) * 3,
        out_specs=P(None, "data"),
        check_vma=False,
    )

    def uly_loss(q, k, v):
        out = mapped(q, k, v)
        return (out**2).sum(), out

    (ld, out_d), gd = jax.value_and_grad(dense_loss, argnums=(0, 1, 2),
                                         has_aux=True)(q, k, v)
    (lu, out_u), gu = jax.jit(
        jax.value_and_grad(uly_loss, argnums=(0, 1, 2), has_aux=True)
    )(q, k, v)
    np.testing.assert_allclose(np.asarray(out_u), np.asarray(out_d),
                               rtol=2e-5, atol=2e-5)
    for a, b in zip(gu, gd):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=5e-4, atol=5e-4)


def test_ulysses_kv_exchange_width_accounting():
    """The collective-bytes claim, statically: the grouped plan's
    per-device exchange width vs the widen-first H/n it replaces."""
    from cs744_pytorch_distributed_tutorial_tpu.parallel.ring_attention import (
        grouped_kv_plan,
        ulysses_kv_exchange_width,
    )

    # divisible: plain kv-width split
    assert ulysses_kv_exchange_width(8, 4, 4) == 1
    # ragged GQA 8q/2kv on a 4-axis: 1 head moved instead of widen-first's 2
    assert ulysses_kv_exchange_width(8, 2, 4) == 1 < 8 // 4
    # MQA on a 4-axis: 1 vs 2
    assert ulysses_kv_exchange_width(8, 1, 4) == 1
    # ragged 12q/6kv on a 4-axis: 2 vs 3
    assert ulysses_kv_exchange_width(12, 6, 4) == 2 < 12 // 4
    # the plan routes every device exactly the kv heads its q group needs
    idx, local, per_dev = grouped_kv_plan(8, 2, 4)
    assert per_dev == 1
    assert list(idx) == [0, 0, 1, 1]  # q pairs (0,1),(2,3)->kv0; (4,5),(6,7)->kv1
    assert local.shape == (4, 2) and (local == 0).all()
