"""Golden loss-curve test — SURVEY §4's prescribed replacement for the
reference's verification-by-eyeball.

The reference establishes cross-part equivalence only by fixed seed
(5000 everywhere: ``master/part1/part1.py:107``,
``master/part2a/part2a.py:89-90``) + manually comparing printed loss
curves. Here the part-3 configuration's first 8 step losses are pinned
against a recorded trace: any semantic regression in the model, the
augmentation RNG discipline, the gradient averaging, or the SGD update
shifts the curve and fails loudly. The gentle learning rate keeps the
trajectory non-chaotic so the tolerance absorbs compiler-version
numeric drift without masking real changes.
"""

import numpy as np
import pytest
from conftest import TINY_DP4_CFG, run_tiny_dp4_steps

# Recorded on the 8-virtual-CPU-device harness (4-device data mesh),
# tiny_cnn, sync="auto", global batch 32, synthetic CIFAR seed 5000,
# lr 0.01. Re-record ONLY for a deliberate semantic change.
GOLDEN = [3.075281, 2.268045, 2.254324, 2.11918, 2.098891, 1.907552,
          1.650272, 1.748724]


# Full engine fit — heavy compile; the curve is also pinned to the
# new-jax AD-inserted-sync path, which the compat shim reroutes.
@pytest.mark.slow
def test_part3_loss_curve_matches_golden_trace(mesh4):
    losses, _, _ = run_tiny_dp4_steps(
        "auto",
        mesh4,
        steps=len(GOLDEN),
        cfg_overrides=dict(seed=5000, learning_rate=0.01),
        data_seed=5000,
    )
    np.testing.assert_allclose(losses, GOLDEN, rtol=5e-3)


# Long-context engine golden: ring attention on a 2x4 data x seq mesh,
# AdamW lr 1e-2, synthetic cyclic tokens seed 5000. Pins the sequence-
# parallel attention, offset position embeddings, spec-aware gradient
# averaging, and the AdamW update in one curve.
GOLDEN_LM = [4.61314, 4.38864, 4.223654, 4.082678, 4.278648, 4.134741,
             4.185895, 4.089676]


@pytest.mark.slow
def test_lm_seq_parallel_loss_curve_matches_golden_trace():
    from cs744_pytorch_distributed_tutorial_tpu.data import synthetic_tokens
    from cs744_pytorch_distributed_tutorial_tpu.parallel import make_mesh
    from cs744_pytorch_distributed_tutorial_tpu.train import LMConfig, LMTrainer

    cfg = LMConfig(vocab_size=64, num_layers=2, num_heads=4, d_model=64,
                   d_ff=128, max_seq_len=256, seq_len=64, global_batch_size=8,
                   attention_impl="ring", data_parallel=2, seq_parallel=4,
                   learning_rate=1e-2, seed=5000)
    tr = LMTrainer(cfg, mesh=make_mesh({"data": 2, "seq": 4}))
    tokens = synthetic_tokens(64, cfg.seq_len, cfg.vocab_size, seed=5000)
    _, _, losses = tr.fit(tokens, steps=len(GOLDEN_LM))
    np.testing.assert_allclose(losses, GOLDEN_LM, rtol=5e-3)


def test_cifar_train_step_compiles_exactly_once(mesh4):
    """Compile-count regression gate: after the warm-up call traces and
    compiles the CIFAR train step, further steps on same-shaped inputs
    must hit the jit cache — 0 additional backend compiles. A retrace
    hazard (unstable static args, fresh wrappers, shifting shapes) shows
    up here as a nonzero steady-state count, the dynamic twin of
    graftlint's GL002."""
    import jax

    from cs744_pytorch_distributed_tutorial_tpu.config import TrainConfig
    from cs744_pytorch_distributed_tutorial_tpu.data import synthetic_cifar10
    from cs744_pytorch_distributed_tutorial_tpu.obs.system import CompileCounter
    from cs744_pytorch_distributed_tutorial_tpu.parallel.mesh import (
        shard_global_batch,
    )
    from cs744_pytorch_distributed_tutorial_tpu.train import Trainer

    warm = CompileCounter()
    cfg = TrainConfig(**TINY_DP4_CFG, sync="allreduce")
    tr = Trainer(cfg, mesh=mesh4)
    state = tr.init()
    ds = synthetic_cifar10(TINY_DP4_CFG["global_batch_size"], 8, seed=0)
    x, y = shard_global_batch(mesh4, ds.train_images, ds.train_labels)
    key = jax.random.key(0)
    state, m = tr.train_step(state, x, y, key)
    if warm.count == 0:
        pytest.skip("jax monitoring compile events unavailable")

    steady = CompileCounter()
    for _ in range(5):
        state, m = tr.train_step(state, x, y, key)
    assert np.isfinite(float(m["loss"]))
    assert steady.count == 0, (
        f"train_step triggered {steady.count} backend compile(s) after "
        "warm-up — the step is retracing"
    )
