"""Golden loss-curve test — SURVEY §4's prescribed replacement for the
reference's verification-by-eyeball.

The reference establishes cross-part equivalence only by fixed seed
(5000 everywhere: ``master/part1/part1.py:107``,
``master/part2a/part2a.py:89-90``) + manually comparing printed loss
curves. Here the part-3 configuration's first 8 step losses are pinned
against a recorded trace: any semantic regression in the model, the
augmentation RNG discipline, the gradient averaging, or the SGD update
shifts the curve and fails loudly. The gentle learning rate keeps the
trajectory non-chaotic so the tolerance absorbs compiler-version
numeric drift without masking real changes.
"""

import numpy as np
from conftest import run_tiny_dp4_steps

# Recorded on the 8-virtual-CPU-device harness (4-device data mesh),
# tiny_cnn, sync="auto", global batch 32, synthetic CIFAR seed 5000,
# lr 0.01. Re-record ONLY for a deliberate semantic change.
GOLDEN = [3.075281, 2.268045, 2.254324, 2.11918, 2.098891, 1.907552,
          1.650272, 1.748724]


def test_part3_loss_curve_matches_golden_trace(mesh4):
    losses, _, _ = run_tiny_dp4_steps(
        "auto",
        mesh4,
        steps=len(GOLDEN),
        cfg_overrides=dict(seed=5000, learning_rate=0.01),
        data_seed=5000,
    )
    np.testing.assert_allclose(losses, GOLDEN, rtol=5e-3)
