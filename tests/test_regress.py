"""benchmarks/regress.py — the perf-regression gate's pass/fail contract.

The gate is pure (``evaluate(baseline_records, current_records)``); the
CLI is I/O around it. These tests pin the contract the CI perf-smoke
job depends on: exit 0 on parity, exit 1 on a seeded >10%% regression,
exit 2 when either side has no usable values — a gate that can't find
its numbers must fail loudly, not pass vacuously.
"""

import json
import os
import sys

import pytest

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", "benchmarks"))

from regress import (  # noqa: E402
    MISSING,
    PASS,
    REGRESSION,
    evaluate,
    load_records,
    metric_values,
)
import metrics_summary  # noqa: E402

METRIC = "cifar10_resnet18_train_samples_per_sec_per_chip"


def _bench(value, **extra):
    return {"kind": "bench", "metric": METRIC, "value": value, **extra}


def test_pass_within_tolerance():
    base = [_bench(100.0)]
    code, verdict = evaluate(base, [_bench(95.0)], metric=METRIC,
                             tolerance=0.10)
    assert code == PASS
    assert verdict["throughput_ok"]
    assert verdict["baseline"] == 100.0 and verdict["current"] == 95.0


def test_seeded_regression_fails():
    """A 15% drop against a 10% tolerance must exit nonzero."""
    base = [_bench(100.0)]
    code, verdict = evaluate(base, [_bench(85.0)], metric=METRIC,
                             tolerance=0.10)
    assert code == REGRESSION
    assert not verdict["throughput_ok"]
    assert verdict["floor"] == pytest.approx(90.0)


def test_missing_metric_exits_2():
    code, verdict = evaluate([_bench(100.0)], [], metric=METRIC)
    assert code == MISSING and "error" in verdict
    code, verdict = evaluate([], [_bench(100.0)], metric=METRIC)
    assert code == MISSING and "error" in verdict


def test_baseline_is_window_median():
    """One noisy baseline run must not move the bar: the gate uses the
    median of the last ``window`` values, in stream order."""
    base = [_bench(v) for v in (500.0, 100.0, 102.0, 98.0, 101.0, 99.0)]
    code, verdict = evaluate(base, [_bench(95.0)], metric=METRIC,
                             tolerance=0.10, window=5)
    assert verdict["baseline"] == 100.0  # median of last 5, 500 aged out
    assert code == PASS


def test_bench_envelope_parsing(tmp_path):
    """The checked-in BENCH_rNN.json driver envelopes (headline record
    under "parsed") read the same as JSONL streams."""
    envelope = {
        "n": 5, "cmd": "python bench.py", "rc": 0, "tail": "...",
        "parsed": {"metric": METRIC, "value": 35330.5, "unit": "s/s/chip"},
    }
    p = tmp_path / "BENCH_r05.json"
    p.write_text(json.dumps(envelope))
    records = load_records(str(p))
    assert metric_values(records, METRIC) == [35330.5]

    jsonl = tmp_path / "metrics.jsonl"
    jsonl.write_text(
        json.dumps(_bench(34000.0)) + "\n" + json.dumps(_bench(35000.0)) + "\n"
    )
    assert metric_values(load_records(str(jsonl)), METRIC) == [
        34000.0, 35000.0,
    ]


def test_phase_gate_on_sync_exposed():
    """When both sides carry phase_summary records and a phase tolerance
    is set, a blown sync_exposed_ms fails even if throughput passes."""
    summary = {"kind": "phase_summary", "sync_exposed_ms": 2.0}
    base = [_bench(100.0), summary]
    good = [_bench(100.0), {"kind": "phase_summary", "sync_exposed_ms": 2.1}]
    bad = [_bench(100.0), {"kind": "phase_summary", "sync_exposed_ms": 9.0}]
    code, verdict = evaluate(base, good, metric=METRIC, phase_tolerance=0.5)
    assert code == PASS and verdict["sync_exposed_ok"]
    code, verdict = evaluate(base, bad, metric=METRIC, phase_tolerance=0.5)
    assert code == REGRESSION
    assert verdict["throughput_ok"] and not verdict["sync_exposed_ok"]
    # without the flag the phase records are ignored
    code, verdict = evaluate(base, bad, metric=METRIC)
    assert code == PASS and "sync_exposed_ok" not in verdict


def test_metrics_summary_phase_rows():
    """metrics_summary.summarize picks up graftscope phase records next
    to the step records it already reduces."""
    records = [
        {"kind": "step", "step": 1, "loss": 2.5, "step_time_s": 0.5},
        {"kind": "step", "step": 2, "loss": 2.0, "step_time_s": 0.1},
        {
            "kind": "phase", "phase": "grad_sync", "device_ms": 1.25,
            "wall_ms": 30.0, "clock": "device", "flops": 1e6,
            "bytes_accessed": 2e6, "comm_bytes": 8e4, "mfu": 0.1,
            "roofline": "comms",
        },
        {"kind": "phase_summary", "sync_exposed_ms": 0.75},
    ]
    s = metrics_summary.summarize(records)
    assert s["phases"]["grad_sync"]["ms"] == 1.25  # device clock wins
    assert s["phases"]["grad_sync"]["roofline"] == "comms"
    assert s["sync_exposed_ms"] == 0.75
    assert s["final_loss"] == 2.0  # step reduction unaffected


def test_metrics_summary_memory_ledger_rows():
    """metrics_summary renders graftmem memory_report.json ledgers as
    one hbm row per entrypoint, latest record per entry winning."""
    ledger = {
        "kind": "memory_ledger", "entry": "cifar", "devices": 4,
        "argument_bytes": 118332, "output_bytes": 93964,
        "temp_bytes": 2558400, "total_bytes": 2676980,
        "alias_saved_bytes": 93716, "dropped_donation_bytes": 0,
        "replicated_leaves": 0,
    }
    stale = dict(ledger, total_bytes=1)
    s = metrics_summary.summarize([stale, ledger])
    assert s["memory"]["cifar"]["total_bytes"] == 2676980
    assert s["memory"]["cifar"]["devices"] == 4
    # a replicated leaf count survives into the summary for the renderer
    leaky = dict(ledger, entry="lm", replicated_leaves=2)
    s = metrics_summary.summarize([leaky])
    assert s["memory"]["lm"]["replicated_leaves"] == 2
