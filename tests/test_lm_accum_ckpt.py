"""LM trainer: gradient accumulation + checkpoint/resume (train/lm.py).

Accumulation is a memory layout, not a different optimizer: the scanned
microbatch gradient average must reproduce the unaccumulated step's
trajectory. Resume must replay the identical remaining batch plan.
"""

import jax
import numpy as np
import pytest

from cs744_pytorch_distributed_tutorial_tpu.data import synthetic_tokens
from cs744_pytorch_distributed_tutorial_tpu.parallel import make_mesh
from cs744_pytorch_distributed_tutorial_tpu.train import LMConfig, LMTrainer

SMALL = dict(
    vocab_size=64, num_layers=2, num_heads=4, d_model=64, d_ff=128,
    max_seq_len=256, global_batch_size=8, seq_len=64, learning_rate=1e-2,
)


def _mesh24():
    return make_mesh({"data": 2, "seq": 4})


@pytest.mark.slow
def test_accum_matches_unaccumulated():
    """accum_steps=2 over the same global batch: same loss curve and final
    params as accum_steps=1 (mean of microbatch means == full-batch mean
    for equal microbatch sizes)."""
    tokens = synthetic_tokens(32, SMALL["seq_len"], SMALL["vocab_size"], seed=3)
    results = []
    for accum in (1, 2):
        cfg = LMConfig(
            **SMALL, attention_impl="ring", data_parallel=2, seq_parallel=4,
            accum_steps=accum,
        )
        tr = LMTrainer(cfg, mesh=_mesh24())
        params, _, losses = tr.fit(tokens, steps=4)
        results.append((losses, jax.device_get(params)))
    (l1, p1), (l2, p2) = results
    np.testing.assert_allclose(l1, l2, rtol=1e-5)
    # Params: microbatch summation order differs from the fused reduction,
    # and adamw's second-moment normalization amplifies those float32
    # last-bit differences — tolerance reflects numerical noise, not
    # drift (atol sized for CPU-backend reduction order, which differs
    # from TPU's).
    jax.tree.map(
        lambda a, b: np.testing.assert_allclose(a, b, rtol=5e-3, atol=1e-4),
        p1,
        p2,
    )


def test_accum_must_divide_local_batch():
    with pytest.raises(ValueError, match="accum_steps"):
        LMTrainer(
            LMConfig(
                **SMALL, attention_impl="ring", data_parallel=2, seq_parallel=4,
                accum_steps=3,  # local batch is 8/2 = 4
            ),
            mesh=_mesh24(),
        )


@pytest.mark.slow
def test_lm_checkpoint_resume_exact(tmp_path):
    """Interrupt at step 3 of 6 (drop newer checkpoints), resume: the
    recovered run must land on the uninterrupted run's exact losses."""
    tokens = synthetic_tokens(32, SMALL["seq_len"], SMALL["vocab_size"], seed=9)
    base = dict(
        **SMALL, attention_impl="ring", data_parallel=2, seq_parallel=4,
    )
    tr_full = LMTrainer(LMConfig(**base), mesh=_mesh24())
    _, _, losses_full = tr_full.fit(tokens, steps=6)

    cfg = LMConfig(
        **base, checkpoint_dir=str(tmp_path / "lm_ckpt"), checkpoint_every=1
    )
    tr_a = LMTrainer(cfg, mesh=_mesh24())
    _, _, losses_a = tr_a.fit(tokens, steps=3)  # "crash" after step 3
    np.testing.assert_allclose(losses_a, losses_full[:3], rtol=1e-6)

    tr_b = LMTrainer(cfg, mesh=_mesh24())
    _, _, losses_b = tr_b.fit(tokens, steps=6)  # resumes at step 3
    assert len(losses_b) == 3
    np.testing.assert_allclose(losses_b, losses_full[3:], rtol=1e-4)


@pytest.mark.slow
def test_lm_resume_past_end_is_noop(tmp_path):
    tokens = synthetic_tokens(16, SMALL["seq_len"], SMALL["vocab_size"], seed=1)
    cfg = LMConfig(
        **SMALL, attention_impl="ring", data_parallel=2, seq_parallel=4,
        checkpoint_dir=str(tmp_path / "lm_ckpt2"), checkpoint_every=1,
    )
    tr = LMTrainer(cfg, mesh=_mesh24())
    _, _, first = tr.fit(tokens, steps=2)
    assert len(first) == 2
    _, _, again = tr.fit(tokens, steps=2)  # already at step 2
    assert again == []
