"""graftmem memory-audit tests (TA007-TA010).

Three layers, mirroring test_trace_audit.py:

1. **Seeded fixtures** — a replicated-but-declared-sharded param, a
   partitioner-inserted reshard, a dropped donation, and budget
   regressions must each be flagged by exactly the intended rule under
   the FULL graftmem rule set.
2. **Contract tests** — budget file IO (missing file = empty budget,
   merge-on-write), suppression pragmas at the registration site, and
   the CLI exit-code/JSON/report surface including the budget-gate
   lifecycle (missing entry -> write -> pass -> regression).
3. **Clean-repo gate** — every registered entrypoint audits green
   against the checked-in ``benchmarks/memory_budget.json``.

Every fixture compiles (graftmem reads ``memory_analysis()``), so the
shapes are tiny; the clean-repo gate compiles the real entries exactly
as the trace-audit donation gate already does.
"""

from __future__ import annotations

import json
import textwrap
from pathlib import Path

import jax
import jax.numpy as jnp
import pytest
from jax.sharding import NamedSharding, PartitionSpec as P

from cs744_pytorch_distributed_tutorial_tpu.analysis.trace import (
    TracedStep,
    get_entrypoints,
    load_builtin_entrypoints,
    register_entrypoint,
)
from cs744_pytorch_distributed_tutorial_tpu.analysis.trace.memory import (
    MEMORY_RULES,
    audit_memory_entry,
    hlo_collective_counts,
    load_budget,
    main as memory_cli_main,
    measure_entry,
    run_memory_audits,
    write_budget,
)
from cs744_pytorch_distributed_tutorial_tpu.analysis.trace.registry import (
    _REGISTRY,
)

ALL_RULES = set(MEMORY_RULES)

REPO = Path(__file__).resolve().parent.parent


@pytest.fixture(autouse=True)
def _registry_guard():
    """Tests register throwaway entrypoints; restore the registry after."""
    before = dict(_REGISTRY)
    yield
    _REGISTRY.clear()
    _REGISTRY.update(before)


def entry_for(step: TracedStep, name: str):
    register_entrypoint(name, lambda: step)
    return get_entrypoints([name])[0]


def audit(step: TracedStep, rules=None, budget=None, name: str = "mem-fixture"):
    return audit_memory_entry(
        entry_for(step, name), set(rules) if rules is not None else None, budget
    )


# ------------------------------------------------------------- fixtures
def _replication_step(mesh4, shard_w: bool) -> TracedStep:
    """Elementwise step on a 4-device mesh: ``w`` is DECLARED sharded via
    sharded_param_paths but placed replicated (the TA008 seed) or
    properly sharded (the clean twin). Elementwise only, so neither the
    jaxpr nor the HLO contains collectives — TA009 stays silent."""
    sh_data = NamedSharding(mesh4, P("data"))
    sh_rep = NamedSharding(mesh4, P())
    w = jax.device_put(
        jnp.ones((64, 64), jnp.float32), sh_data if shard_w else sh_rep
    )
    x = jax.device_put(jnp.ones((8, 64), jnp.float32), sh_data)
    return TracedStep(
        name="mem-fixture",
        fn=jax.jit(lambda w, x: (w * 2.0, x + 1.0)),
        args=(w, x),
        axis_sizes={"data": 4},
        sync="zero1",
        check_donation=False,
        sharded_param_paths=("[0]",),
    )


def _reshard_step(mesh4, clean: bool) -> TracedStep:
    """Data-sharded input forced to a replicated output: the SPMD
    partitioner must insert an all-gather that no jaxpr eqn asked for
    (the TA009 seed). The clean twin keeps in/out specs aligned."""
    sh_in = NamedSharding(mesh4, P("data"))
    sh_out = sh_in if clean else NamedSharding(mesh4, P())
    x = jax.device_put(jnp.ones((8, 64), jnp.float32), sh_in)
    return TracedStep(
        name="mem-fixture",
        fn=jax.jit(lambda x: x * 2.0, in_shardings=sh_in, out_shardings=sh_out),
        args=(x,),
        axis_sizes={"data": 4},
        check_donation=False,
    )


def _donation_step(dropped: bool) -> TracedStep:
    """Donated 32x32 buffer (4096B). ``dropped=True`` uses it but returns
    nothing shape-compatible, so XLA drops the donation (the TA010 seed);
    the clean twin returns an aliasable same-shape output."""
    if dropped:
        fn = jax.jit(lambda buf, x: (buf.sum(), x * 2.0), donate_argnums=(0,))
        args = (jnp.ones((32, 32), jnp.float32), jnp.ones((8,), jnp.float32))
    else:
        fn = jax.jit(lambda buf: buf + 1.0, donate_argnums=(0,))
        args = (jnp.ones((32, 32), jnp.float32),)
    return TracedStep(
        name="mem-fixture", fn=fn, args=args, axis_sizes={}
    )


def _budget_for(ledger: dict, **overrides) -> dict:
    entry = {
        k: ledger[k]
        for k in (
            "devices",
            "argument_bytes",
            "output_bytes",
            "temp_bytes",
            "alias_bytes",
            "total_bytes",
            "dropped_donation_bytes",
        )
    }
    entry.update(overrides.pop("entry_overrides", {}))
    budget = {
        "version": 1,
        "tolerance": 0.05,
        "floor_bytes": 0,
        "entries": {ledger["entry"]: entry},
    }
    budget.update(overrides)
    return budget


# ================================================================ TA008
def test_ta008_replicated_declared_sharded_param(mesh4):
    findings, ledger = audit(_replication_step(mesh4, shard_w=False))
    assert {f.rule for f in findings} == {"TA008"}
    (f,) = findings
    assert "REPLICATED" in f.message and "[0]" in f.message
    assert "zero1" in f.message
    assert ledger["replicated_leaves"] == 1


def test_ta008_sharded_param_is_clean(mesh4):
    findings, ledger = audit(_replication_step(mesh4, shard_w=True))
    assert findings == []
    assert ledger["replicated_leaves"] == 0


def test_ta008_undeclared_replication_is_silent(mesh4):
    """Replication is only a finding when the engine PROMISED sharding:
    without sharded_param_paths the same replicated placement is fine
    (that's what plain data-parallel params look like)."""
    import dataclasses

    step = dataclasses.replace(
        _replication_step(mesh4, shard_w=False), sharded_param_paths=()
    )
    findings, _ledger = audit(step)
    assert findings == []


def test_ta008_small_leaves_exempt(mesh4):
    """Leaves under the min-bytes threshold (scalars, biases, norm
    scales) are never flagged — replicating them is the right call."""
    sh_data = NamedSharding(mesh4, P("data"))
    w = jax.device_put(jnp.ones((4, 4), jnp.float32), NamedSharding(mesh4, P()))
    x = jax.device_put(jnp.ones((8, 64), jnp.float32), sh_data)
    step = TracedStep(
        name="mem-fixture",
        fn=jax.jit(lambda w, x: (w * 2.0, x + 1.0)),
        args=(w, x),
        axis_sizes={"data": 4},
        sync="zero1",
        check_donation=False,
        sharded_param_paths=("[0]",),
    )
    findings, _ledger = audit(step)
    assert findings == []


# ================================================================ TA009
def test_ta009_partitioner_inserted_reshard(mesh4):
    findings, ledger = audit(_reshard_step(mesh4, clean=False))
    assert {f.rule for f in findings} == {"TA009"}
    (f,) = findings
    assert "all-gather" in f.message
    assert ledger["hlo_collectives"].get("all-gather", 0) >= 1


def test_ta009_aligned_specs_clean(mesh4):
    findings, ledger = audit(_reshard_step(mesh4, clean=True))
    assert findings == []
    assert ledger["hlo_collectives"] == {}


def test_hlo_collective_counts_parses_plain_and_start_forms():
    hlo = textwrap.dedent(
        """
        %ag = f32[8,64]{1,0} all-gather(f32[2,64]{1,0} %p0), replica_groups={}
        %ars = (f32[4]{0}, f32[4]{0}) all-reduce-start(f32[4]{0} %p1)
        %ard = f32[4]{0} all-reduce-done((f32[4]{0}, f32[4]{0}) %ars)
        """
    )
    counts = hlo_collective_counts(hlo)
    assert counts == {"all-gather": 1, "all-reduce": 1}


# ================================================================ TA010
def test_ta010_dropped_donation_priced():
    findings, ledger = audit(_donation_step(dropped=True))
    assert {f.rule for f in findings} == {"TA010"}
    (f,) = findings
    assert "4096B" in f.message and "dropped donation" in f.message
    assert ledger["dropped_donation_bytes"] == 4096


def test_ta010_aliased_donation_clean():
    findings, ledger = audit(_donation_step(dropped=False))
    assert findings == []
    assert ledger["dropped_donation_bytes"] == 0
    assert ledger["aliased_leaves"] == 1
    assert ledger["alias_saved_bytes"] == 4096


def test_ta010_respects_check_donation_flag():
    import dataclasses

    step = dataclasses.replace(_donation_step(dropped=True), check_donation=False)
    findings, _ledger = audit(step)
    assert findings == []


# ================================================================ TA007
def test_ta007_within_band_and_inflated_budget_pass():
    step = _donation_step(dropped=False)
    _f, ledger = audit(step, rules=set())
    # exact budget passes...
    findings, _l = audit(step, budget=_budget_for(ledger))
    assert findings == []
    # ...and so does an INFLATED one (memory went down, not up)
    roomy = _budget_for(
        ledger, entry_overrides={"total_bytes": ledger["total_bytes"] * 10}
    )
    findings, _l = audit(step, budget=roomy)
    assert findings == []


def test_ta007_regression_past_tolerance_fires():
    step = _donation_step(dropped=False)
    _f, ledger = audit(step, rules=set())
    tight = _budget_for(
        ledger,
        tolerance=0.0,
        entry_overrides={"total_bytes": ledger["total_bytes"] - 1},
    )
    findings, _l = audit(step, budget=tight)
    assert {f.rule for f in findings} == {"TA007"}
    (f,) = findings
    assert "exceeds the budget" in f.message and "--write-budget" in f.message


def test_ta007_missing_entry_fires():
    step = _donation_step(dropped=False)
    budget = {"version": 1, "tolerance": 0.05, "floor_bytes": 0, "entries": {}}
    findings, _l = audit(step, budget=budget)
    assert {f.rule for f in findings} == {"TA007"}
    assert "no HBM budget entry" in findings[0].message
    assert "--write-budget" in findings[0].message


def test_ta007_device_count_mismatch_fires():
    step = _donation_step(dropped=False)
    _f, ledger = audit(step, rules=set())
    stale = _budget_for(ledger, entry_overrides={"devices": 4})
    findings, _l = audit(step, budget=stale)
    assert {f.rule for f in findings} == {"TA007"}
    assert "not comparable" in findings[0].message


def test_ta007_skipped_without_budget():
    """budget=None (fixture runs, --no-budget) must not fire
    missing-entry findings."""
    findings, _l = audit(_donation_step(dropped=False), budget=None)
    assert findings == []


# ============================================================ budget IO
def test_load_budget_missing_file_is_empty(tmp_path):
    budget = load_budget(tmp_path / "nope.json")
    assert budget["entries"] == {}
    assert budget["tolerance"] == 0.05


def test_load_budget_malformed_raises(tmp_path):
    p = tmp_path / "bad.json"
    p.write_text(json.dumps([1, 2, 3]))
    with pytest.raises(ValueError):
        load_budget(p)


def test_write_budget_merges_existing_entries(tmp_path):
    p = tmp_path / "budget.json"
    p.write_text(
        json.dumps(
            {
                "version": 1,
                "tolerance": 0.1,
                "floor_bytes": 123,
                "entries": {"other": {"devices": 2, "total_bytes": 7}},
            }
        )
    )
    step = _donation_step(dropped=False)
    ledger = measure_entry(entry_for(step, "mem-fixture"), step)
    n = write_budget(p, [ledger])
    assert n == 2
    data = json.loads(p.read_text())
    assert sorted(data["entries"]) == ["mem-fixture", "other"]
    assert data["tolerance"] == 0.1  # preserved, not reset
    assert data["entries"]["mem-fixture"]["total_bytes"] == ledger["total_bytes"]


# ========================================================== suppressions
def test_memory_suppression_pragma_at_registration_site(tmp_path):
    """``# graftlint: disable=TA010`` on the register_entrypoint line
    silences the memory rule for that entrypoint, like GL/TA pragmas."""
    mod = tmp_path / "seeded_mem_entry.py"
    mod.write_text(
        textwrap.dedent(
            """
            import jax
            import jax.numpy as jnp
            from cs744_pytorch_distributed_tutorial_tpu.analysis.trace import (
                TracedStep,
                register_entrypoint,
            )

            def _fn(buf, x):
                return buf.sum(), x * 2.0

            def _factory():
                return TracedStep(
                    name="seeded",
                    fn=jax.jit(_fn, donate_argnums=(0,)),
                    args=(
                        jnp.ones((32, 32), jnp.float32),
                        jnp.ones((8,), jnp.float32),
                    ),
                    axis_sizes={},
                )

            register_entrypoint("mem-suppressed", _factory)  # graftlint: disable=TA010
            register_entrypoint("mem-loud", _factory)
            """
        )
    )
    code = compile(mod.read_text(), str(mod), "exec")
    exec(code, {"__name__": "seeded_mem_entry", "__file__": str(mod)})

    entries = get_entrypoints(["mem-suppressed", "mem-loud"])
    findings, suppressed, _ledgers, _sources, errors = run_memory_audits(
        entries, {"TA010"}
    )
    assert errors == []
    assert suppressed == 1
    assert len(findings) == 1
    assert "[mem-loud]" in findings[0].message


# ================================================================== CLI
def test_memory_cli_list_rules(capsys):
    assert memory_cli_main(["--list-rules"]) == 0
    out = capsys.readouterr().out
    for rid in MEMORY_RULES:
        assert rid in out


def test_memory_cli_list_entrypoints(capsys):
    assert memory_cli_main(["--list-entrypoints"]) == 0
    out = capsys.readouterr().out
    assert "cifar" in out and "lm" in out


def test_memory_cli_unknown_rule_is_usage_error(capsys):
    assert memory_cli_main(["--select", "TA999"]) == 2
    assert memory_cli_main(["--select", "GL"]) == 2  # wrong family


def test_memory_cli_unknown_entry_is_usage_error(capsys):
    assert memory_cli_main(["no-such-entry"]) == 2


def test_memory_cli_dispatch_from_analysis_main(capsys):
    """``python -m ...analysis memory`` routes to graftmem."""
    from cs744_pytorch_distributed_tutorial_tpu.analysis.cli import (
        main as analysis_main,
    )

    assert analysis_main(["memory", "--list-rules"]) == 0
    assert "TA007" in capsys.readouterr().out


def test_memory_cli_bare_family_prefix_selects_all(tmp_path, capsys):
    """``--select TA`` expands to the whole graftmem family."""
    step = _donation_step(dropped=False)
    register_entrypoint("mem-cli-fixture", lambda: step)
    rc = memory_cli_main(
        ["mem-cli-fixture", "--no-budget", "--select", "TA"]
    )
    assert rc == 0


def test_memory_cli_json_report_roundtrip(tmp_path, capsys):
    step = _donation_step(dropped=False)
    register_entrypoint("mem-cli-fixture", lambda: step)
    report = tmp_path / "memory_report.json"
    rc = memory_cli_main(
        [
            "mem-cli-fixture",
            "--no-budget",
            "--format",
            "json",
            "--report",
            str(report),
        ]
    )
    assert rc == 0
    stdout_payload = json.loads(capsys.readouterr().out)
    disk_payload = json.loads(report.read_text())
    assert stdout_payload == disk_payload
    assert disk_payload["exit_code"] == 0
    assert disk_payload["errors"] == []
    (ledger,) = disk_payload["entries"]
    assert ledger["entry"] == "mem-cli-fixture"
    assert ledger["total_bytes"] > 0
    (record,) = disk_payload["records"]
    assert record["kind"] == "memory_ledger"
    assert record["total_bytes"] == ledger["total_bytes"]


def test_memory_cli_budget_gate_lifecycle(tmp_path, capsys):
    """The CI contract end to end: gate fails on a missing budget entry,
    --write-budget records it, the gated rerun passes, a seeded
    regression fails, and --no-budget disarms the gate."""
    step = _donation_step(dropped=False)
    register_entrypoint("mem-cli-fixture", lambda: step)
    budget = tmp_path / "budget.json"

    # 1. gate armed against an absent budget file -> missing entry
    rc = memory_cli_main(["mem-cli-fixture", "--budget", str(budget)])
    assert rc == 1
    assert "no HBM budget entry" in capsys.readouterr().out

    # 2. record the budget
    rc = memory_cli_main(
        ["mem-cli-fixture", "--budget", str(budget), "--write-budget"]
    )
    assert rc == 0 and budget.is_file()
    assert "wrote 1 budget entr" in capsys.readouterr().out

    # 3. gated rerun passes
    rc = memory_cli_main(["mem-cli-fixture", "--budget", str(budget)])
    assert rc == 0

    # 4. seeded regression: deflate the recorded total, zero the band
    data = json.loads(budget.read_text())
    data["tolerance"] = 0.0
    data["floor_bytes"] = 0
    data["entries"]["mem-cli-fixture"]["total_bytes"] -= 1
    budget.write_text(json.dumps(data))
    rc = memory_cli_main(["mem-cli-fixture", "--budget", str(budget)])
    assert rc == 1
    assert "exceeds the budget" in capsys.readouterr().out

    # 5. --no-budget disarms the gate
    rc = memory_cli_main(
        ["mem-cli-fixture", "--budget", str(budget), "--no-budget"]
    )
    assert rc == 0


def test_memory_cli_malformed_budget_is_usage_error(tmp_path, capsys):
    step = _donation_step(dropped=False)
    register_entrypoint("mem-cli-fixture", lambda: step)
    bad = tmp_path / "bad.json"
    bad.write_text("[]")
    rc = memory_cli_main(["mem-cli-fixture", "--budget", str(bad)])
    assert rc == 2


# ======================================================= clean-repo gate
def test_budget_gate_smoke_cifar(devices):
    """Tier-1 smoke: the flagship entry audits clean against the REAL
    checked-in budget file (catches budget-file drift cheaply; the full
    9-entry sweep below is slow-marked and CI's audit job runs it via
    the CLI with the gate armed)."""
    load_builtin_entrypoints()
    (entry,) = get_entrypoints(["cifar"])
    budget = load_budget(REPO / "benchmarks" / "memory_budget.json")
    findings, ledger = audit_memory_entry(entry, ALL_RULES, budget)
    assert findings == []
    assert ledger["devices"] == budget["entries"]["cifar"]["devices"]


@pytest.mark.slow
def test_clean_repo_memory_audits_green(devices):
    """The acceptance gate: every registered entrypoint audits clean
    against the checked-in budget file. Compiles all nine entries, so
    it rides outside tier-1; CI's audit job runs the same gate through
    ``analysis memory``."""
    load_builtin_entrypoints()
    entries = get_entrypoints(
        ["cifar", "cifar-int8", "cifar-overlap", "cifar-overlap-zero1",
         "lm", "lm-overlap", "lm-overlap-fsdp",
         "lm-serve", "lm-serve-paged"]
    )
    budget = load_budget(REPO / "benchmarks" / "memory_budget.json")
    assert len(budget["entries"]) == 9
    findings, _suppressed, ledgers, _sources, errors = run_memory_audits(
        entries, ALL_RULES, budget
    )
    assert errors == []
    assert findings == []
    assert len(ledgers) == 9
    for lg in ledgers:
        assert lg["total_bytes"] > 0
        assert lg["devices"] == budget["entries"][lg["entry"]]["devices"]
        assert lg["replicated_leaves"] == 0
        assert lg["dropped_donation_bytes"] == 0


# =============================================================== on-TPU
@pytest.mark.tpu
@pytest.mark.skipif(
    jax.default_backend() != "tpu",
    reason="memory_stats cross-check needs a real TPU backend",
)
def test_ledger_cross_checks_live_memory_stats():
    """The static ledger must be a floor on what the device actually
    allocates: after one real step, peak bytes-in-use covers the
    compiled args+outputs+temps (docs/observability.md contract)."""
    load_builtin_entrypoints()
    (entry,) = get_entrypoints(["cifar"])
    step = entry.build()
    ledger = measure_entry(entry, step)
    out = step.fn(*step.args)
    jax.block_until_ready(out)
    stats = jax.devices()[0].memory_stats() or {}
    peak = stats.get("peak_bytes_in_use")
    if peak is None:
        pytest.skip("backend reports no peak_bytes_in_use")
    assert peak >= ledger["total_bytes"]
