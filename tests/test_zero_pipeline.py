"""ZeRO-1 on the PIPELINE engine (round 5 — the last missing family
pair, VERDICT r4 #3): optimizer state sharded over the DATA axis while
the pipe axis shards blocks (and the tensor axis their kernels).

The load-bearing property is the LM engine's: chunk-wise AdamW over
data-sharded moments — here chunked per (pipe[, tensor]) coordinate via
``Zero1Adam``'s generalized ``shard_axes`` — IS the replicated optimizer
up to float reassociation, so the trajectory must match while per-device
optimizer memory drops by the data-parallel factor on top of the
pipe/tensor sharding. The reference has no optimizer sharding at all
(full SGD replica per rank, ``master/part2a/part2a.py:127-128``).
"""

import jax
import numpy as np
import pytest

from cs744_pytorch_distributed_tutorial_tpu.parallel import make_mesh
from cs744_pytorch_distributed_tutorial_tpu.parallel.pipeline import (
    DATA_AXIS,
    PIPE_AXIS,
    PipelineLMConfig,
    PipelineLMTrainer,
)

TENSOR_AXIS = "tensor"


def _cfg(**kw) -> PipelineLMConfig:
    base = dict(
        vocab_size=64,
        num_layers=4,
        num_heads=4,
        d_model=32,
        d_ff=64,
        max_seq_len=64,
        seq_len=16,
        global_batch_size=8,
        num_microbatches=2,
        learning_rate=3e-3,
        lr_schedule="warmup_cosine",
        warmup_steps=2,
        total_steps=8,
    )
    base.update(kw)
    return PipelineLMConfig(**base)


def _mesh(data, pipe, tensor=1):
    axes = {DATA_AXIS: data, PIPE_AXIS: pipe}
    if tensor > 1:
        axes[TENSOR_AXIS] = tensor
    return make_mesh(axes, devices=jax.devices()[: data * pipe * tensor])


def _tokens(cfg, seed=0):
    rng = np.random.default_rng(seed)
    return rng.integers(
        0, cfg.vocab_size, (cfg.global_batch_size, cfg.seq_len + 1),
        dtype=np.int64,
    )


def _run(cfg, mesh, steps=6):
    tr = PipelineLMTrainer(cfg, mesh=mesh)
    params, opt = tr.init()
    tokens = _tokens(cfg)
    x, y = tr.shard_batch(tokens)
    losses = []
    for s in range(steps):
        params, opt, m = tr.train_step(params, opt, x, y, s)
        losses.append(float(m["loss"]))
    jax.block_until_ready((params, opt))
    return tr, params, opt, losses


@pytest.mark.parametrize("schedule", ["gpipe", "1f1b"])
@pytest.mark.slow
def test_pipeline_zero1_trajectory_matches_replicated(schedule):
    """dp2 x pp2: the data-sharded-moment trajectory IS the replicated
    adamw trajectory, on both the AD-derived and hand-scheduled
    backward."""
    mesh = _mesh(2, 2)
    kw = dict(data_parallel=2, pipeline_parallel=2, schedule=schedule)
    _, _, _, base = _run(_cfg(**kw), mesh)
    _, _, _, z1 = _run(_cfg(**kw, zero1=True), mesh)
    np.testing.assert_allclose(base, z1, rtol=2e-5)


@pytest.mark.parametrize("schedule", ["gpipe", "1f1b"])
@pytest.mark.slow
def test_pipeline_zero1_with_tensor_and_clip(schedule):
    """dp2 x pp2 x tp2 with grad clipping: block kernels chunk per
    (pipe, tensor) coordinate, the clip's psum spans (data, pipe,
    tensor) with replication multiplicities — trajectory still matches
    the replicated optimizer (whose clip is the spec-aware sharded
    transform). The 1f1b case additionally runs the COMPOSED
    distributed tail (per-stage head width V/(S*T)) under zero1."""
    mesh = _mesh(2, 2, 2)
    kw = dict(
        data_parallel=2, pipeline_parallel=2, tensor_parallel=2,
        grad_clip_norm=0.05, schedule=schedule,
    )
    _, _, _, base = _run(_cfg(**kw), mesh)
    _, _, _, z1 = _run(_cfg(**kw, zero1=True), mesh)
    np.testing.assert_allclose(base, z1, rtol=2e-5)
    # The clip engages: the trajectory differs from the unclipped one.
    _, _, _, unclipped = _run(
        _cfg(data_parallel=2, pipeline_parallel=2, tensor_parallel=2,
             zero1=True, schedule=schedule),
        mesh,
    )
    assert not np.allclose(z1[1:], unclipped[1:], rtol=1e-6)


@pytest.mark.slow
def test_pipeline_clip_is_pipe_count_invariant():
    """The sharded clip's norm is exact for any pipe size: pp2 and pp4
    trajectories with clipping match on the same global batch (block
    grads are per-stage locals — a local-norm clip would diverge
    between the two layouts)."""
    kw = dict(grad_clip_norm=0.05, num_layers=4)
    _, _, _, pp2 = _run(_cfg(pipeline_parallel=2, **kw), _mesh(1, 2))
    _, _, _, pp4 = _run(_cfg(pipeline_parallel=4, **kw), _mesh(1, 4))
    np.testing.assert_allclose(pp2, pp4, rtol=1e-4)


def test_pipeline_zero1_moment_layout():
    """Structure of the memory claim: block moments are [dp, S(, T),
    chunk] sharded over (data, pipe[, tensor]); replicated leaves'
    moments are [dp, chunk] over data."""
    mesh = _mesh(2, 2, 2)
    tr, params, opt, _ = _run(
        _cfg(data_parallel=2, pipeline_parallel=2, tensor_parallel=2,
             zero1=True),
        mesh, steps=1,
    )
    mu = opt["mu"]
    q = mu["blocks"]["attn"]["q"]["kernel"]
    assert q.ndim == 4 and q.shape[:3] == (2, 2, 2)
    assert tuple(q.sharding.spec)[:3] == ("data", "pipe", "tensor")
    # ln kernels inside blocks are pipe-sharded but tensor-replicated.
    ln = mu["blocks"]["ln1"]["scale"]
    assert ln.ndim == 3 and ln.shape[:2] == (2, 2)
    assert tuple(ln.sharding.spec)[:2] == ("data", "pipe")
    emb = mu["embed"]
    assert emb.ndim == 2 and emb.shape[0] == 2
    assert tuple(emb.sharding.spec)[:1] == ("data",)
    assert int(opt["count"]) == 1


@pytest.mark.slow
def test_pipeline_zero1_resume_and_elastic(tmp_path):
    """Orbax resume oracle (VERDICT r4 #3's done-criterion) plus the
    mesh-elastic re-chunk: save at dp2 x pp2, resume at dp1 x pp2 —
    trajectory matches the uninterrupted dp2 run at rtol 1e-6."""
    cfg = _cfg(
        data_parallel=2, pipeline_parallel=2, zero1=True,
        checkpoint_dir=str(tmp_path / "ck"), checkpoint_every=2,
    )
    tokens = _tokens(cfg)
    tr = PipelineLMTrainer(cfg, mesh=_mesh(2, 2))
    _, _, head = tr.fit(tokens, steps=4)
    # Same-mesh resume.
    tr2 = PipelineLMTrainer(cfg, mesh=_mesh(2, 2))
    _, _, tail = tr2.fit(tokens, steps=6)
    assert len(tail) == 2, tail
    oracle = PipelineLMTrainer(
        cfg.replace(checkpoint_dir=None), mesh=_mesh(2, 2)
    )
    _, _, full = oracle.fit(tokens, steps=6)
    np.testing.assert_allclose(head + tail, full, rtol=1e-6)

    # Elastic: fresh run saves at dp2, resumes at dp1 (re-chunked).
    cfg_e = cfg.replace(checkpoint_dir=str(tmp_path / "ck_elastic"))
    tr3 = PipelineLMTrainer(cfg_e, mesh=_mesh(2, 2))
    _, _, head_e = tr3.fit(tokens, steps=4)
    cfg_1 = cfg_e.replace(data_parallel=1)
    tr4 = PipelineLMTrainer(cfg_1, mesh=_mesh(1, 2))
    _, _, tail_e = tr4.fit(tokens, steps=6)
    assert len(tail_e) == 2, tail_e
    np.testing.assert_allclose(head_e + tail_e, full, rtol=1e-6)


def test_pipeline_zero1_rejections():
    with pytest.raises(ValueError, match="clip_norm must be > 0"):
        PipelineLMTrainer(
            _cfg(data_parallel=2, pipeline_parallel=2, zero1=True,
                 grad_clip_norm=-1.0),
            mesh=_mesh(2, 2),
        )
    with pytest.raises(ValueError, match="unknown optimizer"):
        PipelineLMTrainer(
            _cfg(data_parallel=2, pipeline_parallel=2, zero1=True,
                 optimizer="adam"),
            mesh=_mesh(2, 2),
        )
    # zero1 x expert parallelism composes since late round 5 —
    # test_pipeline_zero_expert_parallel below.


@pytest.mark.slow
def test_pipeline_zero1_lion_matches_replicated():
    """The round-5 rule family runs on the pipeline engine too: lion
    (one sharded moment) under dp2 x pp2 matches the replicated
    optax.lion trajectory."""
    mesh = _mesh(2, 2)
    kw = dict(data_parallel=2, pipeline_parallel=2, optimizer="lion",
              learning_rate=1e-3)
    _, _, _, base = _run(_cfg(**kw), mesh)
    _, _, opt, z1 = _run(_cfg(**kw, zero1=True), mesh)
    np.testing.assert_allclose(base, z1, rtol=2e-5)
    assert set(opt) == {"mu", "count"}


# ---------------------------------------------------------------------------
# ZeRO-3/FSDP on the pipeline engine (late round 5): params AND moments
# chunked over data per (pipe[, tensor]) coordinate — the N-axis
# generalization of FsdpAdam's shard/unshard pair.
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("schedule", ["gpipe", "1f1b"])
@pytest.mark.slow
def test_pipeline_fsdp_trajectory_matches_replicated(schedule):
    """dp2 x pp2: chunk-sharded params + just-in-time gather IS the
    replicated trainer — same losses, and the unsharded final params
    match the replicated run's (host_params reassembles the chunks)."""
    mesh = _mesh(2, 2)
    kw = dict(data_parallel=2, pipeline_parallel=2, schedule=schedule)
    _, p0, _, base = _run(_cfg(**kw), mesh)
    trf, pf, _, fs = _run(_cfg(**kw, fsdp=True), mesh)
    np.testing.assert_allclose(base, fs, rtol=2e-5)
    jax.tree.map(
        lambda a, b: np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=2e-4, atol=1e-5
        ),
        trf.host_params(pf),
        jax.device_get(p0),
    )


@pytest.mark.slow
def test_pipeline_fsdp_with_tensor_and_clip():
    """dp2 x pp2 x tp2 (1f1b — the composed distributed tail) with
    grad clipping: block kernels chunk per (pipe, tensor) coordinate
    ([dp, S, T, chunk] params), the exact-norm clip engages, and the
    trajectory matches the replicated run's."""
    mesh = _mesh(2, 2, 2)
    kw = dict(
        data_parallel=2, pipeline_parallel=2, tensor_parallel=2,
        grad_clip_norm=0.05, schedule="1f1b",
    )
    _, _, _, base = _run(_cfg(**kw), mesh)
    tr, params, opt, fs = _run(_cfg(**kw, fsdp=True), mesh)
    np.testing.assert_allclose(base, fs, rtol=2e-5)
    # Layout of the memory claim: block params AND moments are
    # [dp, S, T, chunk] sharded over (data, pipe, tensor).
    for tree in (params, opt["mu"]):
        q = tree["blocks"]["attn"]["q"]["kernel"]
        assert q.ndim == 4 and q.shape[:3] == (2, 2, 2)
        assert tuple(q.sharding.spec)[:3] == ("data", "pipe", "tensor")
    emb = params["embed"]
    assert emb.ndim == 2 and emb.shape[0] == 2
    # The clip engages: trajectory differs from the unclipped run.
    _, _, _, unclipped = _run(
        _cfg(data_parallel=2, pipeline_parallel=2, tensor_parallel=2,
             fsdp=True, schedule="1f1b"),
        mesh,
    )
    assert not np.allclose(fs[1:], unclipped[1:], rtol=1e-6)


@pytest.mark.slow
def test_pipeline_fsdp_resume_and_elastic(tmp_path):
    """Orbax resume oracle for chunked params: save at dp2 x pp2,
    resume at dp2 (exact layout) AND at dp1 (params + moments re-chunk
    elastically) — both match the uninterrupted run at rtol 1e-6."""
    cfg = _cfg(
        data_parallel=2, pipeline_parallel=2, fsdp=True,
        checkpoint_dir=str(tmp_path / "ck"), checkpoint_every=2,
    )
    tokens = _tokens(cfg)
    tr = PipelineLMTrainer(cfg, mesh=_mesh(2, 2))
    _, _, head = tr.fit(tokens, steps=4)
    tr2 = PipelineLMTrainer(cfg, mesh=_mesh(2, 2))
    _, _, tail = tr2.fit(tokens, steps=6)
    assert len(tail) == 2, tail
    oracle = PipelineLMTrainer(
        cfg.replace(checkpoint_dir=None), mesh=_mesh(2, 2)
    )
    _, _, full = oracle.fit(tokens, steps=6)
    np.testing.assert_allclose(head + tail, full, rtol=1e-6)

    cfg_e = cfg.replace(checkpoint_dir=str(tmp_path / "ck_elastic"))
    tr3 = PipelineLMTrainer(cfg_e, mesh=_mesh(2, 2))
    _, _, head_e = tr3.fit(tokens, steps=4)
    tr4 = PipelineLMTrainer(
        cfg_e.replace(data_parallel=1), mesh=_mesh(1, 2)
    )
    _, _, tail_e = tr4.fit(tokens, steps=6)
    assert len(tail_e) == 2, tail_e
    np.testing.assert_allclose(head_e + tail_e, full, rtol=1e-6)


@pytest.mark.slow
def test_pipeline_fsdp_lion_matches_replicated():
    """FsdpLion on the pipeline engine (params + ONE moment chunked):
    dp2 x pp2 matches the replicated optax.lion trajectory."""
    mesh = _mesh(2, 2)
    kw = dict(data_parallel=2, pipeline_parallel=2, optimizer="lion",
              learning_rate=1e-3)
    _, _, _, base = _run(_cfg(**kw), mesh)
    _, params, opt, fs = _run(_cfg(**kw, fsdp=True), mesh)
    np.testing.assert_allclose(base, fs, rtol=2e-5)
    assert set(opt) == {"mu", "count"}
    assert params["blocks"]["attn"]["q"]["kernel"].ndim == 3  # [dp,S,chunk]


def test_pipeline_fsdp_rejections():
    with pytest.raises(ValueError, match="mutually exclusive"):
        PipelineLMTrainer(
            _cfg(data_parallel=2, pipeline_parallel=2, zero1=True,
                 fsdp=True),
            mesh=_mesh(2, 2),
        )


@pytest.mark.slow
def test_pipeline_zero_expert_parallel():
    """ZeRO x EP on the pipeline engine (late round 5 — the rejection
    removed): dp2 x pp2 with experts sharded over data; expert moments
    keep natural shapes sharded like the params while everything else
    chunks; trajectory matches the replicated EP run on BOTH zero1 and
    fsdp."""
    mesh = _mesh(2, 2)
    kw = dict(
        data_parallel=2, pipeline_parallel=2, moe_experts=2,
        moe_capacity_factor=2.0, moe_expert_parallel=True,
    )
    _, _, _, base = _run(_cfg(**kw), mesh)
    _, _, opt_z, z1 = _run(_cfg(**kw, zero1=True), mesh)
    _, _, _, fs = _run(_cfg(**kw, fsdp=True), mesh)
    np.testing.assert_allclose(base, z1, rtol=2e-5)
    np.testing.assert_allclose(base, fs, rtol=2e-5)
    # expert moments: natural [L, E, D, F] block layout sharded
    # (pipe, data); replicated leaves chunk [dp, chunk].
    moe_mu = opt_z["mu"]["blocks"]["moe"]["w_in"]
    assert moe_mu.shape[:2] == (4, 2)  # [L, E] leading dims
    assert tuple(moe_mu.sharding.spec)[:2] == ("pipe", "data")
    emb_mu = opt_z["mu"]["embed"]
    assert emb_mu.ndim == 2 and emb_mu.shape[0] == 2  # [dp, chunk]


@pytest.mark.slow
def test_pipeline_zero_interleaved_schedule():
    """The ZeRO machinery is schedule-agnostic — it chunks the STORAGE
    layout, which the interleaved schedule permutes but does not
    reshape. zero1 AND fsdp on the interleaved (V=2) schedule match the
    replicated interleaved trajectory."""
    mesh = _mesh(2, 2)
    kw = dict(
        data_parallel=2, pipeline_parallel=2, schedule="interleaved",
        num_virtual_stages=2, num_microbatches=2,
    )
    _, _, _, base = _run(_cfg(**kw), mesh)
    _, _, _, z1 = _run(_cfg(**kw, zero1=True), mesh)
    _, _, _, fs = _run(_cfg(**kw, fsdp=True), mesh)
    np.testing.assert_allclose(base, z1, rtol=2e-5)
    np.testing.assert_allclose(base, fs, rtol=2e-5)


@pytest.mark.slow
def test_pipeline_dropless_moe_in_stages():
    """Dropless MoE inside pipeline stages (the ragged grouped matmuls
    trace under the scanned stage body): matches the uncapped scatter
    path — same routing, same gates, nothing drops — and rejects EP."""
    mesh = _mesh(2, 2)
    kw = dict(data_parallel=2, pipeline_parallel=2, moe_experts=4)
    # cf=4 uncaps the scatter oracle; dropless rejects non-default
    # capacity knobs (it has no capacity), so it keeps the default.
    _, _, _, cap = _run(
        _cfg(**kw, moe_dispatch="scatter", moe_capacity_factor=4.0),
        mesh, steps=3,
    )
    _, _, _, dr = _run(_cfg(**kw, moe_dispatch="dropless"), mesh, steps=3)
    np.testing.assert_allclose(cap, dr, rtol=2e-5)
    with pytest.raises(ValueError, match="dropless"):
        PipelineLMTrainer(
            _cfg(data_parallel=2, pipeline_parallel=2, moe_experts=2,
                 moe_expert_parallel=True, moe_dispatch="dropless"),
            mesh=_mesh(2, 2),
        )
