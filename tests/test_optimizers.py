"""Optimizer / LR-schedule registry (train/state.py::make_optimizer).

The reference's only recipe is fixed-LR SGD(momentum, wd)
(``master/part1/part1.py:98-99``); AdamW and cosine/warmup schedules are
capability additions behind the same TrainConfig.
"""

import jax
import numpy as np
import pytest
from conftest import TINY_DP4_CFG, run_tiny_dp4_steps

from cs744_pytorch_distributed_tutorial_tpu.config import TrainConfig
from cs744_pytorch_distributed_tutorial_tpu.train import Trainer
from cs744_pytorch_distributed_tutorial_tpu.train.state import (
    make_optimizer,
    make_schedule,
)


def test_default_is_reference_sgd():
    """The default config reproduces the reference recipe exactly — the
    torch-SGD chain at a constant lr."""
    cfg = TrainConfig()
    assert cfg.optimizer == "sgd" and cfg.lr_schedule == "constant"
    assert make_schedule(cfg) == cfg.learning_rate


def test_warmup_cosine_schedule_shape():
    cfg = TrainConfig(
        lr_schedule="warmup_cosine", warmup_steps=10, total_steps=100,
        learning_rate=0.1,
    )
    sched = make_schedule(cfg)
    assert float(sched(0)) == pytest.approx(0.0)
    assert float(sched(10)) == pytest.approx(0.1, rel=1e-5)  # peak at warmup end
    assert float(sched(55)) < 0.1  # decaying
    assert float(sched(100)) == pytest.approx(0.0, abs=1e-6)  # decayed out


def test_cosine_requires_total_steps():
    with pytest.raises(ValueError, match="total_steps"):
        make_schedule(TrainConfig(lr_schedule="cosine"))


def test_cosine_honors_warmup_steps():
    """warmup_steps applies uniformly — 'cosine' with warmup_steps>0 is the
    same schedule as 'warmup_cosine', never silently ignored."""
    a = make_schedule(
        TrainConfig(lr_schedule="cosine", warmup_steps=10, total_steps=100)
    )
    b = make_schedule(
        TrainConfig(lr_schedule="warmup_cosine", warmup_steps=10, total_steps=100)
    )
    for step in (0, 5, 10, 50, 100):
        assert float(a(step)) == float(b(step))
    assert float(a(0)) == pytest.approx(0.0)


def test_unknown_optimizer_and_schedule_rejected():
    with pytest.raises(ValueError, match="optimizer"):
        make_optimizer(TrainConfig(optimizer="adagrad"))
    with pytest.raises(ValueError, match="lr_schedule"):
        make_schedule(TrainConfig(lr_schedule="step"))


@pytest.mark.slow
def test_adamw_trains(mesh4):
    """AdamW + warmup-cosine runs the full distributed step: finite losses,
    params move, trajectory differs from SGD's."""
    cfg = TrainConfig(
        **TINY_DP4_CFG,
        sync="allreduce",
        optimizer="adamw",
        lr_schedule="warmup_cosine",
        learning_rate=1e-3,
        warmup_steps=2,
        total_steps=16,
    )
    tr = Trainer(cfg, mesh=mesh4)
    state = tr.init()
    from cs744_pytorch_distributed_tutorial_tpu.data import synthetic_cifar10
    from cs744_pytorch_distributed_tutorial_tpu.parallel.mesh import (
        shard_global_batch,
    )

    ds = synthetic_cifar10(TINY_DP4_CFG["global_batch_size"], 8, seed=0)
    x, y = shard_global_batch(mesh4, ds.train_images, ds.train_labels)
    key = jax.random.key(cfg.seed)
    losses = []
    for _ in range(4):
        state, m = tr.train_step(state, x, y, key)
        losses.append(float(m["loss"]))
    assert np.isfinite(losses).all()
    l_sgd, _, st_sgd = run_tiny_dp4_steps("allreduce", mesh4)
    p_adam = jax.tree.leaves(jax.device_get(state.params))
    p_sgd = jax.tree.leaves(jax.device_get(st_sgd.params))
    assert any(
        not np.allclose(a, b) for a, b in zip(p_adam, p_sgd)
    ), "adamw trajectory should differ from sgd's"


def test_grad_clip_bounds_update_norm():
    """With momentum/wd off, SGD's update is -lr * clipped_grad: feeding a
    gradient of huge norm must produce an update of norm exactly
    lr * clip."""
    import jax.numpy as jnp
    import optax

    cfg = TrainConfig(
        momentum=0.0, weight_decay=0.0, learning_rate=0.5, grad_clip_norm=1.0
    )
    tx = make_optimizer(cfg)
    params = {"w": jnp.zeros((4,)), "b": jnp.zeros((2,))}
    grads = {"w": jnp.full((4,), 1e6), "b": jnp.full((2,), -1e6)}
    updates, _ = tx.update(grads, tx.init(params), params)
    norm = float(optax.global_norm(updates))
    assert norm == pytest.approx(cfg.learning_rate * 1.0, rel=1e-5)

    # A small gradient passes through unclipped.
    small = {"w": jnp.full((4,), 1e-3), "b": jnp.full((2,), 1e-3)}
    updates, _ = tx.update(small, tx.init(params), params)
    np.testing.assert_allclose(
        np.asarray(updates["w"]), -cfg.learning_rate * np.asarray(small["w"]),
        rtol=1e-6,
    )

    with pytest.raises(ValueError, match="grad_clip_norm"):
        make_optimizer(TrainConfig(grad_clip_norm=-1.0))


def test_grad_clip_trains_distributed(mesh4):
    """The clipped chain runs the full distributed step and changes the
    trajectory when the bound binds."""
    losses, _, st_clip = run_tiny_dp4_steps(
        "allreduce", mesh4, cfg_overrides={"grad_clip_norm": 1e-3}
    )
    assert np.isfinite(losses).all()
    _, _, st_ref = run_tiny_dp4_steps("allreduce", mesh4)
    p_clip = jax.tree.leaves(jax.device_get(st_clip.params))
    p_ref = jax.tree.leaves(jax.device_get(st_ref.params))
    assert any(
        not np.allclose(a, b) for a, b in zip(p_clip, p_ref)
    ), "a binding clip bound should change the trajectory"


def test_lion_trains(mesh4):
    """Lion (sign momentum, half Adam's optimizer memory) runs the full
    distributed step with a trajectory distinct from SGD's."""
    losses, _, st = run_tiny_dp4_steps(
        "allreduce", mesh4,
        cfg_overrides={"optimizer": "lion", "learning_rate": 1e-4},
    )
    assert np.isfinite(losses).all()
    _, _, st_sgd = run_tiny_dp4_steps("allreduce", mesh4)
    a = jax.tree.leaves(jax.device_get(st.params))
    b = jax.tree.leaves(jax.device_get(st_sgd.params))
    assert any(not np.allclose(x, y) for x, y in zip(a, b))


def test_label_smoothing_trains_and_validates(mesh4):
    losses, _, _ = run_tiny_dp4_steps(
        "allreduce", mesh4, cfg_overrides={"label_smoothing": 0.1}
    )
    assert np.isfinite(losses).all()
    with pytest.raises(ValueError, match="label_smoothing"):
        Trainer(TrainConfig(**TINY_DP4_CFG, label_smoothing=1.5), mesh=mesh4)

    from cs744_pytorch_distributed_tutorial_tpu.train import LMConfig, LMTrainer

    with pytest.raises(ValueError, match="fused_xent"):
        LMTrainer(
            LMConfig(vocab_size=32, num_layers=1, num_heads=2, d_model=16,
                     d_ff=32, max_seq_len=32, seq_len=16, global_batch_size=4,
                     label_smoothing=0.1, fused_xent=True),
            mesh=None,
        )


def test_sharded_optimizers_reject_custom_recipe(mesh4):
    """zero1/fsdp/fused hard-code the reference SGD update; the registry
    knobs must be rejected loudly, not silently ignored."""
    for sync in ("zero1", "fsdp"):
        with pytest.raises(ValueError, match="optax path"):
            Trainer(
                TrainConfig(**TINY_DP4_CFG, sync=sync, optimizer="adamw"),
                mesh=mesh4,
            )
    with pytest.raises(ValueError, match="optax path"):
        Trainer(
            TrainConfig(
                **TINY_DP4_CFG,
                sync="allreduce",
                fused_optimizer=True,
                lr_schedule="cosine",
                total_steps=10,
            ),
            mesh=mesh4,
        )
    with pytest.raises(ValueError, match="optax path"):
        Trainer(
            TrainConfig(**TINY_DP4_CFG, sync="zero1", grad_clip_norm=1.0),
            mesh=mesh4,
        )
