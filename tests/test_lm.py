"""Long-context path: TransformerLM + LMTrainer on a 2-D (data x seq) mesh."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from cs744_pytorch_distributed_tutorial_tpu.data import synthetic_tokens
from cs744_pytorch_distributed_tutorial_tpu.models import TransformerLM
from cs744_pytorch_distributed_tutorial_tpu.parallel import make_mesh
from cs744_pytorch_distributed_tutorial_tpu.train import LMConfig, LMTrainer


SMALL = dict(
    vocab_size=64, num_layers=2, num_heads=4, d_model=64, d_ff=128,
    max_seq_len=256, global_batch_size=8, seq_len=64, learning_rate=1e-2,
)


def test_transformer_forward_shape():
    model = TransformerLM(**{k: SMALL[k] for k in
                             ("vocab_size", "num_layers", "num_heads",
                              "d_model", "d_ff", "max_seq_len")},
                          seq_axis=None)
    tokens = jnp.zeros((2, 32), jnp.int32)
    variables = model.init(jax.random.key(0), tokens)
    logits = model.apply(variables, tokens)
    assert logits.shape == (2, 32, SMALL["vocab_size"])
    assert logits.dtype == jnp.float32


@pytest.mark.parametrize("impl", ["ring", "ulysses"])
@pytest.mark.slow
def test_lm_training_learns_seq_parallel(impl):
    """data=2 x seq=4 mesh; loss on the cyclic synthetic stream must drop
    well below the uniform baseline log(vocab)."""
    mesh = make_mesh({"data": 2, "seq": 4})
    cfg = LMConfig(**SMALL, attention_impl=impl, data_parallel=2, seq_parallel=4)
    tr = LMTrainer(cfg, mesh=mesh)
    tokens = synthetic_tokens(64, cfg.seq_len, cfg.vocab_size, seed=3)
    _, _, losses = tr.fit(tokens, steps=80)
    uniform = np.log(cfg.vocab_size)
    assert losses[0] == pytest.approx(uniform, rel=0.25)  # starts near chance
    assert losses[-1] < 0.6 * uniform  # learned the cyclic structure
    assert np.isfinite(losses).all()


def test_seq_parallel_matches_single_device():
    """The sequence-parallel step must compute the same loss as the same
    model on an unsharded sequence (ring attention + offset position
    embeddings are semantically invisible)."""
    tokens = synthetic_tokens(8, 64, 64, seed=5)
    cfg1 = LMConfig(**SMALL, attention_impl="dense",
                    data_parallel=1, seq_parallel=1)
    mesh1 = make_mesh({"data": 1, "seq": 1}, devices=jax.devices()[:1])
    tr1 = LMTrainer(cfg1, mesh=mesh1)
    p1, o1 = tr1.init()
    x1, y1 = tr1.shard_batch(tokens[:4])
    m1 = tr1.eval_step(p1, x1, y1)

    cfg8 = LMConfig(**SMALL, attention_impl="ring",
                    data_parallel=2, seq_parallel=4)
    mesh8 = make_mesh({"data": 2, "seq": 4})
    tr8 = LMTrainer(cfg8, mesh=mesh8)
    p8, o8 = tr8.init()
    x8, y8 = tr8.shard_batch(tokens[:4])
    m8 = tr8.eval_step(p8, x8, y8)

    np.testing.assert_allclose(
        float(m8["loss"]), float(m1["loss"]), rtol=1e-5
    )


@pytest.mark.strict_jax
def test_lm_train_step_strict():
    """One LM train step on a data x seq mesh under leak checking and a
    transfer guard: sharding in (host_to_global) and fetching out
    (device_get) are the only transfers, and both are explicit."""
    with jax.transfer_guard("allow"):
        # One-time setup may move host constants to device; only the
        # step below must be transfer-clean.
        mesh = make_mesh({"data": 2, "seq": 2}, devices=jax.devices()[:4])
        cfg = LMConfig(**SMALL, attention_impl="ring",
                       data_parallel=2, seq_parallel=2)
        tr = LMTrainer(cfg, mesh=mesh)
        params, opt_state = tr.init()
        tokens = synthetic_tokens(8, cfg.seq_len, cfg.vocab_size, seed=9)
        x, y = tr.shard_batch(tokens[:4])
    params, opt_state, metrics = tr.train_step(params, opt_state, x, y)
    assert np.isfinite(float(jax.device_get(metrics["loss"])))


def test_lm_params_replicated_after_step():
    mesh = make_mesh({"data": 4, "seq": 2})
    cfg = LMConfig(**SMALL, attention_impl="ring",
                   data_parallel=4, seq_parallel=2)
    tr = LMTrainer(cfg, mesh=mesh)
    params, opt_state = tr.init()
    tokens = synthetic_tokens(8, cfg.seq_len, cfg.vocab_size, seed=7)
    x, y = tr.shard_batch(tokens[:4])
    params, opt_state, _ = tr.train_step(params, opt_state, x, y)
    leaf = jax.tree.leaves(params)[0]
    shards = [np.asarray(s.data) for s in leaf.addressable_shards]
    for s in shards[1:]:
        np.testing.assert_allclose(s, shards[0], rtol=1e-6)


def test_seq_len_divisibility_validated():
    with pytest.raises(ValueError, match="not divisible"):
        LMTrainer(LMConfig(**{**SMALL, "seq_len": 30},
                           data_parallel=2, seq_parallel=4),
                  mesh=make_mesh({"data": 2, "seq": 4}))


def test_seq_len_beyond_position_table_rejected():
    with pytest.raises(ValueError, match="max_seq_len"):
        LMTrainer(LMConfig(**{**SMALL, "seq_len": 512},  # max_seq_len=256
                           data_parallel=2, seq_parallel=4),
                  mesh=make_mesh({"data": 2, "seq": 4}))


def test_dense_attention_with_seq_parallel_rejected():
    with pytest.raises(ValueError, match="incompatible"):
        LMTrainer(LMConfig(**SMALL, attention_impl="dense",
                           data_parallel=2, seq_parallel=4),
                  mesh=make_mesh({"data": 2, "seq": 4}))


@pytest.mark.slow
def test_tied_embeddings_drop_lm_head_and_train():
    """tie_embeddings removes lm_head from the tree (vocab params halved),
    the tied logits equal x @ E^T, and training/generation still run."""
    kw = {k: SMALL[k] for k in ("vocab_size", "num_layers", "num_heads",
                                "d_model", "d_ff", "max_seq_len")}
    tied = TransformerLM(**kw, tie_embeddings=True)
    toks = jnp.zeros((2, 16), jnp.int32)
    params = tied.init(jax.random.key(0), toks)["params"]
    assert "lm_head" not in params
    untied = TransformerLM(**kw).init(jax.random.key(0), toks)["params"]
    assert "lm_head" in untied

    # Train end-to-end on the seq-parallel mesh + generate.
    from cs744_pytorch_distributed_tutorial_tpu.data import synthetic_tokens
    from cs744_pytorch_distributed_tutorial_tpu.infer import make_generator

    mesh = make_mesh({"data": 2, "seq": 2})
    cfg = LMConfig(**SMALL, attention_impl="ring", tie_embeddings=True,
                   data_parallel=2, seq_parallel=2)
    tr = LMTrainer(cfg, mesh=mesh)
    tokens = synthetic_tokens(16, cfg.seq_len, cfg.vocab_size, seed=9)
    p, _, losses = tr.fit(tokens, steps=2)
    assert np.isfinite(losses).all()
    out = make_generator(tr.decode_model(), max_new_tokens=3, temperature=0.0)(
        jax.device_get(p), jnp.asarray(tokens[:1, :8], jnp.int32),
        jax.random.key(0),
    )
    assert out.shape == (1, 3)


def test_evaluate_returns_perplexity():
    mesh = make_mesh({"data": 2, "seq": 2})
    cfg = LMConfig(**SMALL, attention_impl="ring",
                   data_parallel=2, seq_parallel=2)
    tr = LMTrainer(cfg, mesh=mesh)
    from cs744_pytorch_distributed_tutorial_tpu.data import synthetic_tokens

    tokens = synthetic_tokens(24, cfg.seq_len, cfg.vocab_size, seed=2)
    params, _ = tr.init()
    m = tr.evaluate(params, tokens)
    # Untrained model on ~uniform tokens: loss near log(vocab), ppl ~ vocab.
    assert m["loss"] == pytest.approx(np.log(cfg.vocab_size), rel=0.25)
    assert m["perplexity"] == pytest.approx(np.exp(m["loss"]), rel=1e-6)
    with pytest.raises(ValueError, match="at least"):
        tr.evaluate(params, tokens[:2])


@pytest.mark.slow
def test_lm_optimizer_registry():
    """LMConfig rides the shared optimizer/schedule registry: warmup-
    cosine AdamW and SGD both train; trajectories differ."""
    from cs744_pytorch_distributed_tutorial_tpu.data import synthetic_tokens

    mesh = make_mesh({"data": 2, "seq": 2})
    tokens = synthetic_tokens(8, SMALL["seq_len"], SMALL["vocab_size"], seed=12)
    params = {}
    for name, extra in [
        ("adamw", dict(lr_schedule="warmup_cosine", warmup_steps=2,
                       total_steps=8)),
        ("sgd", {}),
    ]:
        cfg = LMConfig(**SMALL, attention_impl="ring", data_parallel=2,
                       seq_parallel=2, optimizer=name, **extra)
        tr = LMTrainer(cfg, mesh=mesh)
        p, _, losses = tr.fit(tokens, steps=3)
        assert np.isfinite(losses).all(), (name, losses)
        params[name] = p
    a = jax.tree.leaves(jax.device_get(params["adamw"]))
    b = jax.tree.leaves(jax.device_get(params["sgd"]))
    assert any(not np.allclose(x, y) for x, y in zip(a, b))


@pytest.mark.slow
def test_grad_clip_changes_trajectory_and_stays_replicated():
    """Clipped AdamW runs the distributed step; a binding bound changes
    the trajectory; params remain replicated (the clip factor must be
    identical on every device)."""
    mesh = make_mesh({"data": 2, "seq": 4})
    tokens = synthetic_tokens(16, SMALL["seq_len"], SMALL["vocab_size"], seed=11)
    params = {}
    for clip in (None, 1e-4):
        cfg = LMConfig(**SMALL, attention_impl="ring",
                       data_parallel=2, seq_parallel=4, grad_clip_norm=clip)
        tr = LMTrainer(cfg, mesh=mesh)
        p, _, losses = tr.fit(tokens, steps=3)
        assert np.isfinite(losses).all()
        params[clip] = p
    leaf = jax.tree.leaves(params[1e-4])[0]
    shards = [np.asarray(s.data) for s in leaf.addressable_shards]
    for s in shards[1:]:
        np.testing.assert_allclose(s, shards[0], rtol=1e-6)
    a = jax.tree.leaves(jax.device_get(params[None]))
    b = jax.tree.leaves(jax.device_get(params[1e-4]))
    assert any(not np.allclose(x, y) for x, y in zip(a, b))


# grad_clip_norm x tensor_parallel composes since round 5 via the
# spec-aware clip (train/state.py::clip_by_global_norm_sharded);
# trajectory parity vs the single-device optax clip is pinned in
# tests/test_zero1_lm.py::test_sharded_clip_matches_single_device_optax_clip
# and the expert-parallel case in
# tests/test_moe.py::test_expert_parallel_with_grad_clip.


def test_flash_attention_lm_matches_dense_lm():
    """Single-device LM with the Pallas flash kernel == dense eval loss."""
    from cs744_pytorch_distributed_tutorial_tpu.data import synthetic_tokens as st

    tokens = st(4, 64, 64, seed=13)
    mesh = make_mesh({"data": 1, "seq": 1}, devices=jax.devices()[:1])
    losses = {}
    for impl in ("dense", "flash"):
        cfg = LMConfig(**SMALL, attention_impl=impl,
                       data_parallel=1, seq_parallel=1)
        tr = LMTrainer(cfg, mesh=mesh)
        p, _ = tr.init()
        x, y = tr.shard_batch(tokens)
        losses[impl] = float(tr.eval_step(p, x, y)["loss"])
    assert losses["flash"] == pytest.approx(losses["dense"], rel=1e-5)
