"""ZeRO-3/FSDP: params + optimizer state sharded (parallel/zero.py FsdpSGD,
sync="fsdp").

The contract: fsdp is a parameter LAYOUT, not a different optimizer. The
all_gather unshard + AD-transpose reduce-scatter must produce the same
parameter trajectory as the replicated allreduce strategy, while each
device persists only 1/axis_size of params AND momentum.
"""

import jax
import numpy as np
import pytest
from conftest import TINY_DP4_CFG, run_tiny_dp4_steps

from cs744_pytorch_distributed_tutorial_tpu.config import TrainConfig
from cs744_pytorch_distributed_tutorial_tpu.train import Trainer


def _unshard_host(shards, ref_tree):
    """Host-side inverse of FsdpSGD.shard_params: [axis_size, chunk] flat
    shards -> the original shapes of ``ref_tree``'s leaves."""
    return jax.tree.map(
        lambda sh, ref: np.asarray(sh).reshape(-1)[: ref.size].reshape(ref.shape),
        shards,
        ref_tree,
    )


def test_fsdp_matches_allreduce(mesh4):
    """Same batches, same seed: fsdp and allreduce must trace the same loss
    curve and land on the same params (all_gather + its psum_scatter
    transpose carry the same bytes and numerics as one allreduce)."""
    l_ar, _, st_ar = run_tiny_dp4_steps("allreduce", mesh4)
    l_f, _, st_f = run_tiny_dp4_steps("fsdp", mesh4)
    np.testing.assert_allclose(l_ar, l_f, rtol=1e-5)
    p_ar = jax.device_get(st_ar.params)
    p_f = _unshard_host(jax.device_get(st_f.params), p_ar)
    jax.tree.map(
        lambda a, b: np.testing.assert_allclose(a, b, rtol=1e-4, atol=1e-6),
        p_ar,
        p_f,
    )


def test_fsdp_params_and_momentum_sharded(mesh4):
    """Each device persists only its [1, chunk] shard of BOTH params and
    momentum — the memory claim of ZeRO-3."""
    _, _, state = run_tiny_dp4_steps("fsdp", mesh4, steps=1)
    for tree in (state.params, state.opt_state):
        leaves = jax.tree.leaves(tree)
        assert leaves
        for leaf in leaves:
            assert leaf.shape[0] == 4  # global leading axis == axis_size
            shard_rows = {s.data.shape[0] for s in leaf.addressable_shards}
            assert shard_rows == {1}  # one chunk row per device


def test_fsdp_uneven_param_sizes(mesh4):
    """Padding path: leaves whose size isn't divisible by axis_size (the
    10-wide head bias) still round-trip through shard/gather exactly."""
    _, _, state = run_tiny_dp4_steps("fsdp", mesh4, steps=2)
    # the 10-wide head bias shards as [4, ceil(10/4)=3]; unshard + check
    bias = np.asarray(jax.device_get(state.params["Dense_0"]["bias"]))
    assert bias.shape == (4, 3)
    flat = bias.reshape(-1)[:10]
    assert np.isfinite(flat).all()
    assert np.abs(flat).max() > 0


def test_fsdp_eval_and_fit(mesh4):
    """End-to-end fit: the eval path unshards params inside the step; loss
    and accuracy must come out finite over a tiny synthetic epoch."""
    cfg = TrainConfig(**TINY_DP4_CFG, sync="fsdp", epochs=1, log_every=2)
    tr = Trainer(cfg, mesh=mesh4)
    _, history = tr.fit()
    assert history["eval"], "no eval ran"
    ev = history["eval"][-1]
    assert np.isfinite(ev["avg_loss"])
    assert ev["count"] == TINY_DP4_CFG["synthetic_test_size"]


def test_fsdp_rejects_fused_optimizer(mesh4):
    with pytest.raises(ValueError, match="fsdp"):
        Trainer(
            TrainConfig(**TINY_DP4_CFG, sync="fsdp", fused_optimizer=True),
            mesh=mesh4,
        )


def test_fsdp_rejects_debug_sync_check(mesh4):
    """fsdp has no replicated state for the divergence monitor to compare;
    the combination is rejected loudly rather than passing vacuously."""
    with pytest.raises(ValueError, match="debug_sync_check"):
        Trainer(
            TrainConfig(**TINY_DP4_CFG, sync="fsdp", debug_sync_check=True),
            mesh=mesh4,
        )
