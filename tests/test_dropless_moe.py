"""Dropless MoE: grouped matmuls (ops/gmm.py) + dispatch_impl="dropless".

No counterpart exists in the reference (data parallelism over one dense
VGG-11 is its whole scope, SURVEY §2.3). The key properties pinned here:

- ``grouped_matmul`` computes ``out[r] = lhs[r] @ rhs[g(r)]`` under the
  contiguous-group layout for BOTH backends — XLA's ``lax.ragged_dot``
  and the Pallas gmm kernel — including empty groups, tile-unaligned row
  counts, and gradients (the Pallas backward pair is dx = gmm with
  transposed experts, dw = the tgmm kernel).
- ``dispatch_impl="dropless"`` is the capacity-free limit of the routed
  layer: it must match the scatter path exactly when capacity is large
  enough that nothing drops (same router, same gates — only the token
  movement differs), report a zero drop metric, and train.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from cs744_pytorch_distributed_tutorial_tpu.data import synthetic_tokens
from cs744_pytorch_distributed_tutorial_tpu.models import MoEFFN
from cs744_pytorch_distributed_tutorial_tpu.ops.gmm import grouped_matmul
from cs744_pytorch_distributed_tutorial_tpu.parallel import make_mesh
from cs744_pytorch_distributed_tutorial_tpu.train import LMConfig, LMTrainer

MOE = dict(
    vocab_size=64, num_layers=2, num_heads=4, d_model=64, d_ff=128,
    max_seq_len=256, global_batch_size=8, seq_len=64, learning_rate=1e-2,
    moe_experts=4,
)


def _oracle(x, w, gs):
    ids = np.repeat(np.arange(w.shape[0]), np.asarray(gs))
    return jnp.einsum("nd,ndf->nf", x, jnp.asarray(w)[ids])


@pytest.mark.parametrize(
    "m,e,gs_list",
    [
        (16, 4, [3, 5, 0, 8]),      # empty group mid-list
        (64, 3, [64, 0, 0]),        # everything in group 0
        (100, 5, [0, 30, 20, 0, 50]),  # tile-unaligned M
        (7, 2, [2, 5]),             # M smaller than one tile
    ],
)
def test_grouped_matmul_both_impls_match_oracle(m, e, gs_list):
    k, n = 8, 12
    rng = np.random.default_rng(m)
    x = jnp.array(rng.standard_normal((m, k)), jnp.float32)
    w = jnp.array(rng.standard_normal((e, k, n)), jnp.float32)
    gs = jnp.array(gs_list, jnp.int32)
    ref = _oracle(x, w, gs)
    ragged = grouped_matmul(x, w, gs, impl="ragged")
    pallas = grouped_matmul(
        x, w, gs, impl="pallas", block_m=8, block_n=8, interpret=True
    )
    # Both run the matmul at the backend's default precision; the
    # oracle's einsum may differ at bf16-level on TPU-default backends.
    np.testing.assert_allclose(ragged, ref, rtol=2e-2, atol=2e-2)
    # The two impls walk the same groups tile-by-tile — bitwise-close.
    np.testing.assert_allclose(pallas, ragged, rtol=1e-6, atol=1e-6)


def test_grouped_matmul_grads_match():
    """d/d(lhs) and d/d(rhs) agree between ragged_dot's native AD and
    the Pallas custom_vjp (dx = gmm(dout, rhsᵀ), dw = tgmm)."""
    rng = np.random.default_rng(0)
    x = jnp.array(rng.standard_normal((40, 8)), jnp.float32)
    w = jnp.array(rng.standard_normal((4, 8, 12)), jnp.float32)
    gs = jnp.array([10, 0, 25, 5], jnp.int32)

    def loss(impl):
        kw = (
            dict(impl="pallas", block_m=8, block_n=8, interpret=True)
            if impl == "pallas"
            else dict(impl="ragged")
        )
        return lambda x, w: jnp.sum(grouped_matmul(x, w, gs, **kw) ** 2)

    grx, grw = jax.grad(loss("ragged"), argnums=(0, 1))(x, w)
    gpx, gpw = jax.grad(loss("pallas"), argnums=(0, 1))(x, w)
    np.testing.assert_allclose(gpx, grx, rtol=1e-5, atol=1e-5)
    np.testing.assert_allclose(gpw, grw, rtol=1e-5, atol=1e-5)


@pytest.mark.parametrize("top_k", [1, 2])
def test_dropless_matches_uncapped_scatter(top_k):
    """With capacity high enough that nothing drops, scatter and
    dropless are the same mathematical layer (same router, same gates,
    every token computes) — outputs, aux loss and parameter gradients
    must agree; the dropless drop metric is identically zero."""
    e, d, f = 4, 8, 32
    x = jax.random.normal(jax.random.key(1), (2, 16, d), jnp.float32)
    drop = MoEFFN(
        num_experts=e, d_ff=f, top_k=top_k, dispatch_impl="dropless",
        gmm_interpret=True, gmm_block_m=8, gmm_block_n=8,
    )
    ref = MoEFFN(
        num_experts=e, d_ff=f, top_k=top_k, dispatch_impl="scatter",
        capacity_factor=float(e),  # capacity >= all tokens: zero drops
    )
    params = drop.init(jax.random.key(0), x)
    yd, md = drop.apply(params, x, mutable=["losses", "metrics"])
    yr, mr = ref.apply(params, x, mutable=["losses", "metrics"])
    np.testing.assert_allclose(yd, yr, rtol=2e-5, atol=2e-5)
    assert float(jax.tree.leaves(mr["metrics"])[0]) == 0.0  # truly uncapped
    assert float(jax.tree.leaves(md["metrics"])[0]) == 0.0
    np.testing.assert_allclose(
        jax.tree.leaves(md["losses"])[0], jax.tree.leaves(mr["losses"])[0],
        rtol=1e-6,
    )

    def loss(layer, p):
        y, _ = layer.apply(p, x, mutable=["losses", "metrics"])
        return jnp.sum(y**2)

    gd = jax.grad(lambda p: loss(drop, p))(params)
    gr = jax.grad(lambda p: loss(ref, p))(params)
    jax.tree.map(
        lambda a, b: np.testing.assert_allclose(a, b, rtol=2e-4, atol=2e-4),
        gd,
        gr,
    )


def test_dropless_pallas_matches_ragged_in_layer():
    """The two gmm backends are interchangeable inside the layer."""
    x = jax.random.normal(jax.random.key(1), (2, 16, 8), jnp.float32)
    mk = lambda impl: MoEFFN(
        num_experts=4, d_ff=32, top_k=2, dispatch_impl="dropless",
        gmm_impl=impl, gmm_interpret=True, gmm_block_m=8, gmm_block_n=8,
    )
    params = mk("ragged").init(jax.random.key(0), x)
    yr = mk("ragged").apply(params, x)
    yp = mk("pallas").apply(params, x)
    np.testing.assert_allclose(yp, yr, rtol=1e-5, atol=1e-5)


@pytest.mark.parametrize(
    "kw",
    [dict(capacity_factor=2.0), dict(num_groups=4), dict(num_groups=0)],
)
def test_dropless_rejects_capacity_knobs(kw):
    """dropless has no capacity: a tuned capacity_factor or group count
    must be rejected loudly, not silently ignored (same reject-don't-
    drop rule as the expert_axis case)."""
    x = jnp.zeros((1, 8, 8), jnp.float32)
    layer = MoEFFN(
        num_experts=4, d_ff=16, dispatch_impl="dropless", **kw
    )
    with pytest.raises(ValueError, match="dropless"):
        layer.init(jax.random.key(0), x)


def test_dropless_rejects_expert_parallel():
    layer = MoEFFN(
        num_experts=4, d_ff=16, dispatch_impl="dropless",
        expert_axis="data", expert_axis_size=2,
    )
    x = jnp.zeros((1, 8, 8))
    with pytest.raises(ValueError, match="dropless"):
        layer.init(jax.random.key(0), x)
    cfg = LMConfig(
        **MOE, attention_impl="dense", data_parallel=2,
        moe_dispatch="dropless", moe_expert_parallel=True,
    )
    mesh = make_mesh({"data": 2, "seq": 1}, devices=jax.devices()[:2])
    with pytest.raises(ValueError, match="dropless"):
        LMTrainer(cfg, mesh=mesh)


@pytest.mark.slow
def test_dropless_lm_trains():
    """A 2-device data-parallel dropless-MoE LM learns the cyclic
    synthetic stream (the end-to-end descent check the other dispatch
    impls have)."""
    mesh = make_mesh({"data": 2, "seq": 1}, devices=jax.devices()[:2])
    cfg = LMConfig(
        **MOE, attention_impl="dense", data_parallel=2, seq_parallel=1,
        moe_dispatch="dropless",
    )
    tr = LMTrainer(cfg, mesh=mesh)
    tokens = synthetic_tokens(64, cfg.seq_len, cfg.vocab_size, seed=3)
    _, _, losses = tr.fit(tokens, steps=60)
    uniform = np.log(cfg.vocab_size)
    assert losses[-1] < 0.7 * uniform
    assert np.isfinite(losses).all()
    # the drop metric surfaces as identically zero
    params, opt_state = tr.init()
    x, y = tr.shard_batch(tokens[:8])
    _, _, m = tr.train_step(params, opt_state, x, y)
    assert float(m["moe_drop"]) == 0.0


@pytest.mark.parametrize("act", ["none", "gelu"])
def test_grouped_matmul_fused_matches_unfused(act):
    """The fused-epilogue kernels (bias(+gelu) inside the gmm — the
    in-model Pallas win, benchmarks/README.md) compute exactly the
    unfused chain, forward and gradients (custom_vjp: dx/dw via the
    plain kernels, db via a K=1 tgmm segment-sum)."""
    from cs744_pytorch_distributed_tutorial_tpu.ops.gmm import (
        grouped_matmul_fused,
    )

    rng = np.random.default_rng(1)
    x = jnp.array(rng.standard_normal((24, 8)), jnp.float32)
    w = jnp.array(rng.standard_normal((4, 8, 12)), jnp.float32)
    b = jnp.array(rng.standard_normal((4, 12)), jnp.float32)
    gs = jnp.array([5, 0, 11, 8], jnp.int32)
    ids = np.repeat(np.arange(4), np.asarray(gs))

    def unfused(x, w, b):
        z = grouped_matmul(
            x, w, gs, impl="pallas", block_m=8, block_n=8, interpret=True
        ) + b[ids]
        return jax.nn.gelu(z) if act == "gelu" else z

    def fused(x, w, b):
        return grouped_matmul_fused(
            x, w, b, gs, activation=act, block_m=8, block_n=8,
            interpret=True,
        )

    np.testing.assert_allclose(
        fused(x, w, b), unfused(x, w, b), rtol=1e-5, atol=1e-5
    )
    gf = jax.grad(lambda *a: jnp.sum(fused(*a) ** 2), argnums=(0, 1, 2))(
        x, w, b
    )
    gu = jax.grad(lambda *a: jnp.sum(unfused(*a) ** 2), argnums=(0, 1, 2))(
        x, w, b
    )
    for a, c in zip(gf, gu):
        np.testing.assert_allclose(a, c, rtol=1e-4, atol=1e-4)
