"""Native batch-assembly core + prefetch pipeline."""

import time

import numpy as np
import pytest

from cs744_pytorch_distributed_tutorial_tpu.data import (
    PrefetchIterator,
    gather_rows,
    prefetch,
)
from cs744_pytorch_distributed_tutorial_tpu.native import native_available


def test_native_library_builds():
    """g++ is baked into the image; the core must actually compile here
    (graceful fallback exists for environments where it can't)."""
    assert native_available("batcher")


@pytest.mark.parametrize("dtype", [np.uint8, np.int32])
def test_gather_matches_numpy(dtype):
    rng = np.random.default_rng(0)
    arr = rng.integers(0, 200, size=(1000, 3, 5)).astype(dtype)
    idx = rng.integers(0, 1000, size=256)
    np.testing.assert_array_equal(
        gather_rows(arr, idx), np.take(arr, idx, axis=0)
    )


def test_gather_large_multithreaded_path():
    """>1 MiB payload takes the threaded branch in the C++ core."""
    rng = np.random.default_rng(1)
    arr = rng.integers(0, 255, size=(4096, 32 * 32 * 3), dtype=np.uint8)
    idx = rng.permutation(4096)
    np.testing.assert_array_equal(
        gather_rows(arr, idx), np.take(arr, idx, axis=0)
    )


def test_gather_falls_back_for_unsupported_dtype():
    arr = np.arange(20, dtype=np.float64).reshape(10, 2)
    idx = np.array([3, 1, 4])
    np.testing.assert_array_equal(
        gather_rows(arr, idx), np.take(arr, idx, axis=0)
    )


def test_prefetch_preserves_order_and_values():
    items = list(range(50))
    assert list(prefetch(iter(items), depth=4)) == items


def test_prefetch_relays_producer_exception():
    def gen():
        yield 1
        raise RuntimeError("boom")

    it = prefetch(gen(), depth=2)
    assert next(it) == 1
    with pytest.raises(RuntimeError, match="boom"):
        next(it)


def test_prefetch_depth_zero_is_passthrough():
    it = prefetch(iter([1, 2]), depth=0)
    assert not isinstance(it, PrefetchIterator)
    assert list(it) == [1, 2]


def test_prefetch_runs_ahead():
    """With depth 3 the producer stages items while the consumer sleeps."""
    produced = []

    def gen():
        for i in range(5):
            produced.append(i)
            yield i

    it = PrefetchIterator(gen(), depth=3)
    assert next(it) == 0
    deadline = time.time() + 2.0
    while len(produced) < 4 and time.time() < deadline:
        time.sleep(0.01)
    assert len(produced) >= 4  # ran ahead of the consumer
    it.close()
