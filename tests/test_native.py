"""Native batch-assembly core + prefetch pipeline."""

import time

import numpy as np
import pytest

from cs744_pytorch_distributed_tutorial_tpu.data import (
    PrefetchIterator,
    gather_rows,
    prefetch,
)
from cs744_pytorch_distributed_tutorial_tpu.native import native_available


def test_native_library_builds():
    """g++ is baked into the image; the core must actually compile here
    (graceful fallback exists for environments where it can't)."""
    assert native_available("batcher")


@pytest.mark.parametrize("dtype", [np.uint8, np.int32])
def test_gather_matches_numpy(dtype):
    rng = np.random.default_rng(0)
    arr = rng.integers(0, 200, size=(1000, 3, 5)).astype(dtype)
    idx = rng.integers(0, 1000, size=256)
    np.testing.assert_array_equal(
        gather_rows(arr, idx), np.take(arr, idx, axis=0)
    )


def test_gather_large_multithreaded_path():
    """>1 MiB payload takes the threaded branch in the C++ core."""
    rng = np.random.default_rng(1)
    arr = rng.integers(0, 255, size=(4096, 32 * 32 * 3), dtype=np.uint8)
    idx = rng.permutation(4096)
    np.testing.assert_array_equal(
        gather_rows(arr, idx), np.take(arr, idx, axis=0)
    )


def test_gather_falls_back_for_unsupported_dtype():
    arr = np.arange(20, dtype=np.float64).reshape(10, 2)
    idx = np.array([3, 1, 4])
    np.testing.assert_array_equal(
        gather_rows(arr, idx), np.take(arr, idx, axis=0)
    )


def test_native_decoder_builds_and_matches_numpy():
    """The C++ CIFAR binary decoder must compile here and agree with the
    NumPy transpose on random records."""
    from cs744_pytorch_distributed_tutorial_tpu.data.native_decode import (
        RECORD_BYTES,
        decode_cifar_records,
    )

    assert native_available("decode")
    rng = np.random.default_rng(3)
    n = 500  # > 1 MiB total: exercises the threaded path
    raw = rng.integers(0, 256, size=n * RECORD_BYTES).astype(np.uint8)
    images, labels = decode_cifar_records(raw)

    recs = raw.reshape(n, RECORD_BYTES)
    np.testing.assert_array_equal(labels, recs[:, 0].astype(np.int32))
    expect = recs[:, 1:].reshape(n, 3, 32, 32).transpose(0, 2, 3, 1)
    np.testing.assert_array_equal(images, expect)

    with pytest.raises(ValueError, match="multiple"):
        decode_cifar_records(raw[:-1])


def test_load_cifar10_reads_binary_layout(tmp_path):
    """The official binary distribution round-trips through load_cifar10
    via the native decoder."""
    from cs744_pytorch_distributed_tutorial_tpu.data import load_cifar10
    from cs744_pytorch_distributed_tutorial_tpu.data.native_decode import (
        RECORD_BYTES,
    )

    rng = np.random.default_rng(4)
    d = tmp_path / "cifar-10-batches-bin"
    d.mkdir()
    per_file = 20
    for name in [f"data_batch_{i}.bin" for i in range(1, 6)] + ["test_batch.bin"]:
        recs = rng.integers(0, 256, size=(per_file, RECORD_BYTES)).astype(np.uint8)
        recs[:, 0] = rng.integers(0, 10, size=per_file)  # valid labels
        (d / name).write_bytes(recs.tobytes())

    ds = load_cifar10(str(tmp_path), synthetic=False)
    assert not ds.synthetic
    assert ds.train_images.shape == (100, 32, 32, 3)
    assert ds.test_images.shape == (20, 32, 32, 3)
    assert ds.train_labels.dtype == np.int32
    assert ds.train_labels.max() < 10


def test_prefetch_preserves_order_and_values():
    items = list(range(50))
    assert list(prefetch(iter(items), depth=4)) == items


def test_prefetch_relays_producer_exception():
    def gen():
        yield 1
        raise RuntimeError("boom")

    it = prefetch(gen(), depth=2)
    assert next(it) == 1
    with pytest.raises(RuntimeError, match="boom"):
        next(it)


def test_prefetch_depth_zero_is_passthrough():
    it = prefetch(iter([1, 2]), depth=0)
    assert not isinstance(it, PrefetchIterator)
    assert list(it) == [1, 2]


def test_prefetch_runs_ahead():
    """With depth 3 the producer stages items while the consumer sleeps."""
    produced = []

    def gen():
        for i in range(5):
            produced.append(i)
            yield i

    it = PrefetchIterator(gen(), depth=3)
    assert next(it) == 0
    deadline = time.time() + 2.0
    while len(produced) < 4 and time.time() < deadline:
        time.sleep(0.01)
    assert len(produced) >= 4  # ran ahead of the consumer
    it.close()


def test_prefetch_terminates_after_relayed_exception():
    """Round-4 review fix: a consumer that catches the relayed exception
    and keeps reading must hit StopIteration, not block forever on the
    empty queue (the producer enqueues _STOP after the exception)."""
    def gen():
        yield 1
        raise RuntimeError("boom")

    it = prefetch(gen(), depth=2)
    assert next(it) == 1
    with pytest.raises(RuntimeError, match="boom"):
        next(it)
    with pytest.raises(StopIteration):
        next(it)  # must terminate, not hang
    with pytest.raises(StopIteration):
        next(it)  # and KEEP terminating (iterator protocol)


def test_prefetch_materializes_on_producer_thread(monkeypatch):
    """Round-4 fix: _block_ready runs ON THE PRODUCER THREAD (one-behind
    blocking; the final item fenced before _STOP) — recorded by
    monkeypatching jax.block_until_ready and asserting the calling
    thread and the fenced items."""
    import threading

    import jax
    import jax.numpy as jnp

    calls = []
    real = jax.block_until_ready

    def recording(x):
        calls.append(threading.current_thread())
        return real(x)

    monkeypatch.setattr(jax, "block_until_ready", recording)

    def gen():
        for i in range(6):
            yield jnp.arange(4) * i  # dispatched lazily

    out = list(prefetch(gen(), depth=2))
    assert len(out) == 6
    assert int(out[-1][-1]) == 15
    # Every fence ran off the main thread (the producer daemon), and
    # every item was fenced (one-behind: 6 items = 6 calls incl. the
    # final pre-_STOP fence).
    main = threading.main_thread()
    producer_calls = [t for t in calls if t is not main]
    assert len(producer_calls) >= 6, (len(calls), len(producer_calls))
