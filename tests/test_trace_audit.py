"""graftcheck trace-audit tests.

Three layers:

1. **TA003 sweep** — every ``--sync`` strategy (CIFAR) and every LM data
   -parallel mode is traced on the 8-virtual-device CPU harness and its
   collective schedule + bytes-on-wire are checked against the contract
   model in :mod:`parallel.sync` and the telemetry accounting in
   :func:`parallel.sync.sync_wire_bytes`.
2. **Seeded regressions** — hand-built step functions with an injected
   f32 upcast, a dropped donation, a giant trace constant, and a dead
   matmul must each be flagged by exactly the intended rule.
3. **Contract tests** — registry, suppressions, CLI exit codes, and the
   clean-repo gate (auditing the real registered entrypoints finds
   nothing).

Tracing uses ``jax.make_jaxpr`` only, so the sweep is cheap; only the
donation tests compile (tiny shapes).
"""

from __future__ import annotations

import json
import textwrap

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from cs744_pytorch_distributed_tutorial_tpu.analysis.trace import (
    TracedStep,
    get_entrypoints,
    load_builtin_entrypoints,
    register_entrypoint,
)
from cs744_pytorch_distributed_tutorial_tpu.analysis.trace.audits import (
    TRACE_RULES,
    audit_entry,
    run_audits,
)
from cs744_pytorch_distributed_tutorial_tpu.analysis.trace.cli import (
    main as trace_cli_main,
)
from cs744_pytorch_distributed_tutorial_tpu.analysis.trace import jaxpr_utils
from cs744_pytorch_distributed_tutorial_tpu.analysis.trace.registry import (
    _REGISTRY,
)

ALL_RULES = set(TRACE_RULES)
TRACE_ONLY = ALL_RULES - {"TA002"}  # TA002 lowers+compiles; the rest trace


@pytest.fixture(autouse=True)
def _registry_guard():
    """Tests register throwaway entrypoints; restore the registry after."""
    before = dict(_REGISTRY)
    yield
    _REGISTRY.clear()
    _REGISTRY.update(before)


def entry_for(step: TracedStep, name: str):
    register_entrypoint(name, lambda: step)
    return get_entrypoints([name])[0]


def audit(step: TracedStep, rules=TRACE_ONLY, name: str = "fixture"):
    findings, _info = audit_entry(entry_for(step, name), set(rules))
    return findings


# =================================================== TA003 schedule sweep
CIFAR_SYNCS = [
    "allreduce",
    "ring",
    "int8_allreduce",
    "zero1",
    "fsdp",
    "gather_scatter",
    "p2p_star",
    "auto",
]


@pytest.mark.parametrize("sync", CIFAR_SYNCS)
def test_ta003_cifar_schedule_matches_contract(sync, devices):
    from cs744_pytorch_distributed_tutorial_tpu.train.engine import (
        make_trace_entry,
    )

    step = make_trace_entry(sync=sync)
    closed = jax.make_jaxpr(step.fn)(*step.args)
    colls = jaxpr_utils.collect_collectives(closed, step.axis_sizes)
    counts = jaxpr_utils.schedule_counts(colls)
    assert step.expected_schedule is not None
    expected = {k: v for k, v in step.expected_schedule.items() if v}
    assert counts == expected, f"{sync}: {counts} != {expected}"

    wire = jaxpr_utils.total_wire_bytes(colls)
    assert step.expected_wire_bytes is not None
    tol = max(0.01 * step.expected_wire_bytes, 512.0)
    assert abs(wire - step.expected_wire_bytes) <= tol, (
        f"{sync}: jaxpr wire {wire} vs accounting "
        f"{step.expected_wire_bytes}"
    )


OVERLAP_CONFIGS = [
    ("allreduce", "bucket", {}),
    ("ring", "bucket", {}),
    ("int8_allreduce", "bucket+int8", {}),
    ("zero1", "bucket", {}),
    ("fsdp", "bucket", {}),
    ("zero1", "bucket+int8", {"grad_compress": "int8"}),
]


@pytest.mark.parametrize("sync,overlap,extra", OVERLAP_CONFIGS)
def test_ta003_overlapped_schedule_matches_contract(
    sync, overlap, extra, devices
):
    """The overlapped bucket schedule (--sync-overlap) keeps TA003's
    contract byte-exact: the same collective classes and wire bytes as
    the fused bucketed wire, just placed per reverse-order bucket
    (sync_units/sync_wire_bytes count the reverse layout when
    overlap=True). Covers the sharded schedules too: zero1/fsdp run the
    per-bucket psum_scatter -> chunk apply -> all_gather chain, and
    zero1+int8 swaps each scatter for the quantized allreduce
    (2 all_to_alls + 2 all_gathers per unit, plus the delta gather)."""
    from cs744_pytorch_distributed_tutorial_tpu.train.engine import (
        make_trace_entry,
    )

    step = make_trace_entry(sync=sync, sync_overlap=overlap, **extra)
    closed = jax.make_jaxpr(step.fn)(*step.args)
    colls = jaxpr_utils.collect_collectives(closed, step.axis_sizes)
    counts = jaxpr_utils.schedule_counts(colls)
    assert step.expected_schedule is not None
    expected = {k: v for k, v in step.expected_schedule.items() if v}
    assert counts == expected, f"{sync}+{overlap}: {counts} != {expected}"

    wire = jaxpr_utils.total_wire_bytes(colls)
    tol = max(0.01 * step.expected_wire_bytes, 512.0)
    assert abs(wire - step.expected_wire_bytes) <= tol, (
        f"{sync}+{overlap}: jaxpr wire {wire} vs accounting "
        f"{step.expected_wire_bytes}"
    )
    if overlap == "bucket":
        # Float wires: overlap changes WHERE the collectives sit, not
        # how many bytes move — fused and overlapped accounting agree
        # exactly. (int8 exempt: reverse bucketing regroups the
        # quantization chunks, shifting per-bucket padding slightly.)
        fused = make_trace_entry(sync=sync)
        assert step.expected_wire_bytes == fused.expected_wire_bytes


LM_OVERLAP_MODES = {
    "dp-sgd": (dict(optimizer="sgd"), "bucket"),
    "zero1": (dict(zero1=True), "bucket"),
    "fsdp": (dict(fsdp=True), "bucket"),
    "zero1-int8": (dict(zero1=True, grad_compress="int8"), "bucket+int8"),
}


@pytest.mark.parametrize("mode", sorted(LM_OVERLAP_MODES))
def test_ta003_lm_overlapped_schedule(mode, devices):
    """LM overlap sweep: pure-DP SGD plus the sharded schedules (which
    admit any registry optimizer — these trace the default AdamW)."""
    from cs744_pytorch_distributed_tutorial_tpu.train.lm import (
        make_lm_trace_entry,
    )

    kw, overlap = LM_OVERLAP_MODES[mode]
    step = make_lm_trace_entry(sync_overlap=overlap, **kw)
    closed = jax.make_jaxpr(step.fn)(*step.args)
    colls = jaxpr_utils.collect_collectives(closed, step.axis_sizes)
    counts = jaxpr_utils.schedule_counts(colls)
    expected = {k: v for k, v in step.expected_schedule.items() if v}
    assert counts == expected, f"lm-{mode}: {counts} != {expected}"
    wire = jaxpr_utils.total_wire_bytes(colls)
    tol = max(0.01 * step.expected_wire_bytes, 512.0)
    assert abs(wire - step.expected_wire_bytes) <= tol, (
        f"lm-{mode}: jaxpr wire {wire} vs accounting "
        f"{step.expected_wire_bytes}"
    )


def test_ta003_int8_wire_beats_f32(devices):
    from cs744_pytorch_distributed_tutorial_tpu.train.engine import (
        make_trace_entry,
    )

    def jaxpr_wire(sync):
        step = make_trace_entry(sync=sync)
        closed = jax.make_jaxpr(step.fn)(*step.args)
        return jaxpr_utils.total_wire_bytes(
            jaxpr_utils.collect_collectives(closed, step.axis_sizes)
        )

    f32 = jaxpr_wire("allreduce")
    int8 = jaxpr_wire("int8_allreduce")
    assert 0 < int8 < f32, (int8, f32)


LM_MODES = {
    "allreduce": {},
    "int8": {"grad_compress": "int8"},
    "zero1": {"zero1": True},
    "fsdp": {"fsdp": True},
}


@pytest.mark.parametrize("mode", sorted(LM_MODES))
def test_ta003_lm_schedule_matches_contract(mode, devices):
    from cs744_pytorch_distributed_tutorial_tpu.train.lm import (
        make_lm_trace_entry,
    )

    step = make_lm_trace_entry(**LM_MODES[mode])
    closed = jax.make_jaxpr(step.fn)(*step.args)
    colls = jaxpr_utils.collect_collectives(closed, step.axis_sizes)
    counts = jaxpr_utils.schedule_counts(colls)
    assert step.expected_schedule is not None
    expected = {k: v for k, v in step.expected_schedule.items() if v}
    assert counts == expected, f"{mode}: {counts} != {expected}"

    wire = jaxpr_utils.total_wire_bytes(colls)
    tol = max(0.01 * step.expected_wire_bytes, 512.0)
    assert abs(wire - step.expected_wire_bytes) <= tol, (
        f"{mode}: jaxpr wire {wire} vs accounting "
        f"{step.expected_wire_bytes}"
    )


def test_ta003_flags_schedule_mismatch(mesh4):
    """A step whose contract promises ring but runs allreduce is caught."""

    def psum_step(x):
        return jax.shard_map(
            lambda v: jax.lax.psum(v, "data"),
            mesh=mesh4,
            in_specs=jax.sharding.PartitionSpec("data"),
            out_specs=jax.sharding.PartitionSpec(),
        )(x)

    step = TracedStep(
        name="mismatch",
        fn=psum_step,
        args=(jnp.zeros((4, 128), jnp.float32),),
        axis_sizes={"data": 4},
        expected_schedule={"ppermute": 6},
        check_donation=False,
    )
    findings = audit(step, rules={"TA003"})
    assert [f.rule for f in findings] == ["TA003"]
    assert "ppermute" in findings[0].message


# ================================================== seeded TA001 upcast
def _bf16_block_with_f32_leak(leak: bool):
    w1 = jnp.ones((16, 16), jnp.bfloat16)
    w2 = jnp.ones((16, 16), jnp.bfloat16)

    def step(x):
        h = jnp.dot(x, w1)  # bf16 x bf16 -> bf16: fine
        if leak:
            # The forgotten-cast bug TA001 hunts: one block promotes to
            # f32 and the matmul silently runs at 4 bytes/element.
            h = jnp.dot(h.astype(jnp.float32), w2.astype(jnp.float32))
        else:
            h = jnp.dot(h, w2)
        return h.astype(jnp.float32).sum()

    return step, (jnp.ones((8, 16), jnp.bfloat16),)


def test_ta001_flags_injected_f32_upcast():
    fn, args = _bf16_block_with_f32_leak(leak=True)
    step = TracedStep(
        name="leak",
        fn=fn,
        args=args,
        axis_sizes={},
        compute_dtype="bfloat16",
        check_donation=False,
    )
    findings = audit(step)
    assert [f.rule for f in findings] == ["TA001"]
    assert "f32 dot_general" in findings[0].message


def test_ta001_clean_bf16_block():
    fn, args = _bf16_block_with_f32_leak(leak=False)
    step = TracedStep(
        name="clean",
        fn=fn,
        args=args,
        axis_sizes={},
        compute_dtype="bfloat16",
        check_donation=False,
    )
    assert audit(step) == []


def test_ta001_allowlists_loss_and_optimizer_frames():
    """f32 math inside loss/norm/optimizer code is the sanctioned
    mixed-precision pattern, not a leak."""
    w = jnp.ones((16, 16), jnp.bfloat16)

    def cross_entropy_loss(h):
        # f32 matmul, but the frame name matches the allowlist.
        return jnp.dot(h.astype(jnp.float32), jnp.eye(16)).sum()

    def step(x):
        return cross_entropy_loss(jnp.dot(x, w))

    step_t = TracedStep(
        name="allow",
        fn=step,
        args=(jnp.ones((8, 16), jnp.bfloat16),),
        axis_sizes={},
        compute_dtype="bfloat16",
        check_donation=False,
    )
    assert audit(step_t) == []


# ================================================ seeded TA002 donation
def test_ta002_flags_dropped_donation():
    """Donating a buffer the output cannot alias (shape mismatch) is a
    dropped donation — HBM holds both copies."""

    def fn(x):
        return x.sum()  # scalar out: the (8,8) donated input can't alias

    step = TracedStep(
        name="dropped",
        fn=jax.jit(fn, donate_argnums=0),
        args=(jnp.ones((8, 8), jnp.float32),),
        axis_sizes={},
    )
    findings = audit(step, rules={"TA002"})
    assert [f.rule for f in findings] == ["TA002"]
    assert "donated" in findings[0].message


def test_ta002_clean_honoured_donation():
    def fn(x):
        return x + 1.0

    step = TracedStep(
        name="honoured",
        fn=jax.jit(fn, donate_argnums=0),
        args=(jnp.ones((8, 8), jnp.float32),),
        axis_sizes={},
    )
    assert audit(step, rules={"TA002"}) == []


# =========================================== seeded TA004 trace constant
def test_ta004_flags_large_closure_constant():
    big = jnp.asarray(np.ones((512, 1024), np.float32))  # 2 MiB

    def fn(x):
        return (x @ big).sum()

    step = TracedStep(
        name="const",
        fn=fn,
        args=(jnp.ones((4, 512), jnp.float32),),
        axis_sizes={},
        check_donation=False,
    )
    findings = audit(step)
    assert [f.rule for f in findings] == ["TA004"]
    assert "2.0 MiB" in findings[0].message


def test_ta004_small_literals_are_fine():
    scale = jnp.float32(2.0)

    def fn(x):
        return (x * scale).sum()

    step = TracedStep(
        name="small",
        fn=fn,
        args=(jnp.ones((4, 4), jnp.float32),),
        axis_sizes={},
        check_donation=False,
    )
    assert audit(step) == []


# ============================================== seeded TA005 dead matmul
def test_ta005_flags_dead_matmul():
    def fn(x, w):
        dead = x @ w  # computed, never used
        del dead
        return x.sum()

    step = TracedStep(
        name="dead",
        fn=fn,
        args=(
            jnp.ones((32, 32), jnp.float32),
            jnp.ones((32, 32), jnp.float32),
        ),
        axis_sizes={},
        check_donation=False,
    )
    findings = audit(step)
    assert [f.rule for f in findings] == ["TA005"]
    assert "dot_general" in findings[0].message


def test_ta005_live_matmul_is_fine():
    def fn(x, w):
        return (x @ w).sum()

    step = TracedStep(
        name="live",
        fn=fn,
        args=(
            jnp.ones((32, 32), jnp.float32),
            jnp.ones((32, 32), jnp.float32),
        ),
        axis_sizes={},
        check_donation=False,
    )
    assert audit(step) == []


# ====================================================== registry contract
def test_registry_records_registration_site():
    def factory():
        raise AssertionError("not built by --list-entrypoints")

    register_entrypoint("site-probe", factory, tags=("test",))
    (entry,) = get_entrypoints(["site-probe"])
    assert entry.path.endswith("test_trace_audit.py")
    assert entry.line > 0
    assert entry.tags == ("test",)


def test_registry_unknown_name_lists_known():
    register_entrypoint("known-one", lambda: None)
    with pytest.raises(KeyError) as exc:
        get_entrypoints(["nope"])
    assert "known-one" in exc.value.args[0]


def test_builtin_entrypoints_load():
    load_builtin_entrypoints()
    names = {e.name for e in get_entrypoints()}
    assert {"cifar", "cifar-int8", "cifar-overlap", "cifar-overlap-zero1",
            "lm", "lm-overlap", "lm-overlap-fsdp",
            "lm-serve", "lm-serve-paged"} <= names


def test_clean_repo_audits_green(devices):
    """The acceptance gate: every registered entrypoint audits clean."""
    load_builtin_entrypoints()
    entries = get_entrypoints(
        ["cifar", "cifar-int8", "cifar-overlap", "cifar-overlap-zero1",
         "lm", "lm-overlap", "lm-overlap-fsdp"]
    )
    findings, _suppressed, summaries, _sources, errors = run_audits(
        entries, ALL_RULES
    )
    assert errors == []
    assert findings == []
    assert len(summaries) == 7
    for s in summaries:
        assert s["donation"]["donated"] == s["donation"]["aliased"]


def test_serve_entrypoints_audit_clean(devices):
    """Both serving decode steps — gather reference AND the Pallas
    paged-attention kernel — audit clean over the engine's REAL jitted
    step: TA003 finds no unexpected collectives, TA005 no dead matmuls
    (the kernel path leaves no dead dense-gather ops behind), and the
    page-pool donation contract stays fully aliased (4/4) with the
    kernel in the graph."""
    load_builtin_entrypoints()
    entries = get_entrypoints(["lm-serve", "lm-serve-paged"])
    findings, _suppressed, summaries, _sources, errors = run_audits(
        entries, ALL_RULES
    )
    assert errors == []
    assert findings == []
    assert len(summaries) == 2
    for s in summaries:
        assert s["donation"]["donated"] == 4
        assert s["donation"]["aliased"] == 4


# ========================================================== suppressions
def test_ta_suppression_pragma_at_registration_site(tmp_path):
    """``# graftlint: disable=TA001`` on the register_entrypoint line
    silences that rule for that entrypoint, exactly like GL pragmas."""
    mod = tmp_path / "seeded_entry.py"
    mod.write_text(
        textwrap.dedent(
            """
            import jax.numpy as jnp
            from cs744_pytorch_distributed_tutorial_tpu.analysis.trace import (
                TracedStep,
                register_entrypoint,
            )

            w = jnp.ones((16, 16), jnp.bfloat16)

            def _fn(x):
                h = jnp.dot(x, w)
                return jnp.dot(
                    h.astype(jnp.float32), jnp.eye(16, dtype=jnp.float32)
                ).sum()

            def _factory():
                return TracedStep(
                    name="seeded",
                    fn=_fn,
                    args=(jnp.ones((8, 16), jnp.bfloat16),),
                    axis_sizes={},
                    compute_dtype="bfloat16",
                    check_donation=False,
                )

            register_entrypoint("seeded-suppressed", _factory)  # graftlint: disable=TA001
            register_entrypoint("seeded-loud", _factory)
            """
        )
    )
    code = compile(mod.read_text(), str(mod), "exec")
    exec(code, {"__name__": "seeded_entry", "__file__": str(mod)})

    entries = get_entrypoints(["seeded-suppressed", "seeded-loud"])
    findings, suppressed, _summaries, _sources, errors = run_audits(
        entries, {"TA001"}
    )
    assert errors == []
    assert suppressed == 1
    assert len(findings) == 1
    assert "[seeded-loud]" in findings[0].message


# ================================================================== CLI
def test_cli_list_rules(capsys):
    assert trace_cli_main(["--list-rules"]) == 0
    out = capsys.readouterr().out
    for rid in TRACE_RULES:
        assert rid in out


def test_cli_list_entrypoints(capsys):
    assert trace_cli_main(["--list-entrypoints"]) == 0
    out = capsys.readouterr().out
    assert "cifar" in out and "lm" in out


def test_cli_unknown_rule_is_usage_error(capsys):
    assert trace_cli_main(["--select", "TA999"]) == 2


def test_cli_unknown_entry_is_usage_error(capsys):
    assert trace_cli_main(["no-such-entry"]) == 2


def test_cli_json_report_roundtrip(tmp_path, capsys, monkeypatch):
    monkeypatch.chdir(tmp_path)  # keep any baseline writes out of the repo
    report = tmp_path / "audit_report.json"
    rc = trace_cli_main(
        [
            "cifar",
            "--select",
            "TA003,TA004,TA005",
            "--format",
            "json",
            "--report",
            str(report),
        ]
    )
    assert rc == 0
    stdout_payload = json.loads(capsys.readouterr().out)
    disk_payload = json.loads(report.read_text())
    assert stdout_payload == disk_payload
    assert disk_payload["exit_code"] == 0
    assert disk_payload["errors"] == []
    (summary,) = disk_payload["entries"]
    assert summary["entry"] == "cifar"
    assert summary["schedule"] == {"psum": 1}


def test_cli_dispatch_from_analysis_main(capsys):
    """``python -m ...analysis trace`` routes to graftcheck."""
    from cs744_pytorch_distributed_tutorial_tpu.analysis.cli import (
        main as analysis_main,
    )

    assert analysis_main(["trace", "--list-rules"]) == 0
    assert "TA001" in capsys.readouterr().out


def _upcast_step() -> TracedStep:
    """Trace-only step with a seeded bf16->f32 matmul upcast (TA001)."""
    w = jnp.ones((16, 16), jnp.bfloat16)

    def _fn(x):
        h = jnp.dot(x, w)
        return jnp.dot(
            h.astype(jnp.float32), jnp.eye(16, dtype=jnp.float32)
        ).sum()

    return TracedStep(
        name="seeded",
        fn=_fn,
        args=(jnp.ones((8, 16), jnp.bfloat16),),
        axis_sizes={},
        compute_dtype="bfloat16",
        check_donation=False,
    )


def test_cli_write_baseline_roundtrip(tmp_path, capsys):
    """--write-baseline records current findings; a rerun against that
    baseline passes; --no-baseline surfaces them again."""
    step = _upcast_step()
    register_entrypoint("seeded-baseline", lambda: step)
    bl = tmp_path / "graftcheck_baseline.json"
    sel = ["seeded-baseline", "--select", "TA001", "--baseline", str(bl)]

    assert trace_cli_main(sel + ["--no-baseline"]) == 1  # finding is live
    capsys.readouterr()

    assert trace_cli_main(sel + ["--write-baseline"]) == 0
    assert "wrote 1 baseline entr" in capsys.readouterr().out
    assert json.loads(bl.read_text())["entries"]

    assert trace_cli_main(sel) == 0  # baselined now
    assert "1 baselined" in capsys.readouterr().out

    assert trace_cli_main(sel + ["--no-baseline"]) == 1  # still reportable


def test_checked_in_baseline_is_valid_and_empty():
    """The repo ships an EMPTY accepted-findings file: the default
    ``--baseline`` path must load and suppress nothing."""
    import pathlib

    from cs744_pytorch_distributed_tutorial_tpu.analysis import Baseline

    p = pathlib.Path(__file__).resolve().parent.parent / "graftcheck_baseline.json"
    data = json.loads(p.read_text())
    assert data == {"version": 1, "entries": []}
    assert Baseline.load(p) is not None


# ================================================ TA006 branch divergence
def _cond_entry(mesh4, sync_branch, skip_branch):
    def step(x):
        def body(v):
            return jax.lax.cond(v[0, 0] > 0, sync_branch, skip_branch, v)

        return jax.shard_map(
            body,
            mesh=mesh4,
            in_specs=jax.sharding.PartitionSpec("data"),
            out_specs=jax.sharding.PartitionSpec("data"),
        )(x)

    return TracedStep(
        name="cond-fixture",
        fn=step,
        args=(jnp.zeros((4, 128), jnp.float32),),
        axis_sizes={"data": 4},
        check_donation=False,
    )


def test_ta006_flags_divergent_cond(mesh4):
    """A cond that psums in one branch only desynchronizes the ranks."""
    step = _cond_entry(
        mesh4,
        lambda u: u + jax.lax.psum(u, "data"),
        lambda u: u * 2.0,
    )
    findings = audit(step)
    assert [f.rule for f in findings] == ["TA006"]
    assert "psum" in findings[0].message


def test_ta006_matched_branches_are_fine(mesh4):
    """Both branches lowering the same collective schedule is legal —
    every rank runs exactly one psum whichever way the predicate goes."""
    step = _cond_entry(
        mesh4,
        lambda u: u + jax.lax.psum(u, "data"),
        lambda u: u - jax.lax.psum(u, "data"),
    )
    assert audit(step) == []


def test_ta006_counts_scalar_collectives(mesh4):
    """Unlike TA003's schedule contract, TA006 must NOT drop
    scalar-payload collectives: a 4-byte psum in one branch still hangs
    the branch that skips it."""
    step = _cond_entry(
        mesh4,
        lambda u: u + jax.lax.psum(u.sum(), "data"),
        lambda u: u * 2.0,
    )
    findings = audit(step, rules={"TA006"})
    assert [f.rule for f in findings] == ["TA006"]


def test_ta006_flags_divergent_switch(mesh4):
    """lax.switch lowers to the same cond primitive; a divergent branch
    list is caught the same way."""

    def step(x):
        def body(v):
            idx = (v[0, 0] > 0).astype(jnp.int32) + (v[0, 1] > 0).astype(
                jnp.int32
            )
            return jax.lax.switch(
                idx,
                [
                    lambda u: u * 2.0,
                    lambda u: u + jax.lax.psum(u, "data"),
                    lambda u: u + jax.lax.psum(u, "data"),
                ],
                v,
            )

        return jax.shard_map(
            body,
            mesh=mesh4,
            in_specs=jax.sharding.PartitionSpec("data"),
            out_specs=jax.sharding.PartitionSpec("data"),
        )(x)

    step = TracedStep(
        name="switch-fixture",
        fn=step,
        args=(jnp.zeros((4, 128), jnp.float32),),
        axis_sizes={"data": 4},
        check_donation=False,
    )
    findings = audit(step, rules={"TA006"})
    assert [f.rule for f in findings] == ["TA006"]
    assert "3 branch" in findings[0].message or "branches" in findings[0].message
