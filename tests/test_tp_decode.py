"""Tensor-parallel decode: generation/beam on tensor-SHARDED params.

Round 1's generation required gathered full params
(``LMTrainer.decode_model``) — the one strategy-family composition hole
(docs/roadmap.md). The ``mesh=`` path added to ``make_generator`` /
``make_beam_searcher`` runs the whole sampling loop inside shard_map:
each device projects and caches its local heads, and the per-sublayer
psums keep the logits replicated. These tests pin exact token parity
against the gathered path on a tensor=2 mesh.
"""

from __future__ import annotations

import jax
import numpy as np
import pytest

# Tensor-parallel decode engines: heavy compile per case.
pytestmark = pytest.mark.slow


def _make_trainer(mesh, tensor):
    from cs744_pytorch_distributed_tutorial_tpu.train.lm import (
        LMConfig,
        LMTrainer,
    )

    cfg = LMConfig(
        vocab_size=64,
        num_layers=2,
        num_heads=4,
        d_model=32,
        d_ff=64,
        max_seq_len=64,
        attention_impl="dense",
        global_batch_size=4,
        seq_len=16,
        seed=11,
        data_parallel=2,
        tensor_parallel=tensor,
    )
    return LMTrainer(cfg, mesh=mesh)


def _trained_params(tr, steps=2):
    from cs744_pytorch_distributed_tutorial_tpu.data.text import (
        synthetic_tokens,
    )

    params, opt_state = tr.init()
    toks = synthetic_tokens(8, 16, 64, seed=0)
    for s in range(steps):
        x, y = tr.shard_batch(toks[s * 4 : s * 4 + 4])
        params, opt_state, _ = tr.train_step(params, opt_state, x, y)
    return params


@pytest.fixture(scope="module")
def tp_setup():
    from cs744_pytorch_distributed_tutorial_tpu.parallel import make_mesh

    mesh = make_mesh({"data": 2, "seq": 1, "tensor": 2},
                     devices=jax.devices()[:4])
    tr = _make_trainer(mesh, tensor=2)
    params = _trained_params(tr)
    return tr, params


def test_tp_generate_matches_gathered(tp_setup):
    """Greedy decode on tensor-sharded params must emit exactly the
    tokens the gathered-single-device path emits from the same params."""
    from cs744_pytorch_distributed_tutorial_tpu.infer import make_generator

    tr, params = tp_setup
    prompt = np.asarray(
        [[1, 2, 3, 4], [5, 6, 7, 8], [9, 10, 11, 12], [13, 14, 15, 16]],
        np.int32,
    )

    gen_tp = make_generator(
        tr.tp_decode_model(), max_new_tokens=8, temperature=0.0,
        mesh=tr.mesh, param_specs=tr.param_specs,
    )
    out_tp = np.asarray(gen_tp(params, prompt, jax.random.key(0)))

    # gathered path: one all-gather of the sharded params, then the
    # plain single-program decode
    gen_full = make_generator(
        tr.decode_model(), max_new_tokens=8, temperature=0.0
    )
    full_params = tr.gather_for_decode(params)
    out_full = np.asarray(gen_full(full_params, prompt, jax.random.key(0)))
    np.testing.assert_array_equal(out_tp, out_full)


def test_tp_generate_sampling_deterministic(tp_setup):
    """Stochastic sampling on the TP path is deterministic per key:
    every device draws from the same replicated logits, so repeated runs
    agree exactly. (Cross-path bitwise parity is pinned on the GREEDY
    test above — under sampling, psum-order float differences can
    legitimately flip near-tied draws.)"""
    from cs744_pytorch_distributed_tutorial_tpu.infer import make_generator

    tr, params = tp_setup
    prompt = np.asarray([[1, 2, 3, 4], [5, 6, 7, 8]] * 2, np.int32)
    gen_tp = make_generator(
        tr.tp_decode_model(), max_new_tokens=6, temperature=0.8, top_k=8,
        mesh=tr.mesh, param_specs=tr.param_specs,
    )
    a = np.asarray(gen_tp(params, prompt, jax.random.key(3)))
    b = np.asarray(gen_tp(params, prompt, jax.random.key(3)))
    np.testing.assert_array_equal(a, b)
    assert ((0 <= a) & (a < 64)).all()


def test_sampling_decorrelated_across_data_shards(tp_setup):
    """Identical prompts landing on DIFFERENT data shards must draw
    different random streams: the decode key is folded with the data
    axis index inside shard_map (without it, row i of every shard
    sampled identically — advisor finding, round 2)."""
    from cs744_pytorch_distributed_tutorial_tpu.infer import make_generator

    tr, params = tp_setup
    # 4 identical rows over data=2 -> rows 0,1 on shard 0, rows 2,3 on
    # shard 1. Same in-shard index + same prompt would have collided.
    prompt = np.asarray([[1, 2, 3, 4]] * 4, np.int32)
    gen_tp = make_generator(
        tr.tp_decode_model(), max_new_tokens=16, temperature=1.0,
        mesh=tr.mesh, param_specs=tr.param_specs,
    )
    out = np.asarray(gen_tp(params, prompt, jax.random.key(7)))
    # Within a shard, identical rows still share the per-shard stream
    # only through different per-row key folds inside sample_tokens —
    # the cross-shard pairs (0,2) and (1,3) are the regression surface.
    assert not np.array_equal(out[0], out[2]) or not np.array_equal(
        out[1], out[3]
    )


def test_tp_beam_matches_gathered(tp_setup):
    from cs744_pytorch_distributed_tutorial_tpu.infer import (
        make_beam_searcher,
    )

    tr, params = tp_setup
    prompt = np.asarray([[1, 2, 3, 4], [9, 10, 11, 12]] * 2, np.int32)
    beam_tp = make_beam_searcher(
        tr.tp_decode_model(), beam_size=3, max_new_tokens=5,
        mesh=tr.mesh, param_specs=tr.param_specs,
    )
    beam_full = make_beam_searcher(
        tr.decode_model(), beam_size=3, max_new_tokens=5
    )
    tok_tp, sc_tp = beam_tp(params, prompt)
    tok_full, sc_full = beam_full(tr.gather_for_decode(params), prompt)
    np.testing.assert_array_equal(np.asarray(tok_tp), np.asarray(tok_full))
    np.testing.assert_allclose(
        np.asarray(sc_tp), np.asarray(sc_full), rtol=1e-5
    )


def test_non_tp_model_rejected_without_mesh():
    """The guard rail: a tensor-parallel model without the shard_map
    path must fail with the pointer to it."""
    from cs744_pytorch_distributed_tutorial_tpu.infer import make_generator
    from cs744_pytorch_distributed_tutorial_tpu.parallel import make_mesh

    mesh = make_mesh({"data": 2, "seq": 1, "tensor": 2},
                     devices=jax.devices()[:4])
    tr = _make_trainer(mesh, tensor=2)
    with pytest.raises(ValueError, match="shard_map path"):
        make_generator(tr.tp_decode_model(), max_new_tokens=4)
