"""graftlint rule tests: one true-positive and one clean fixture per
rule, plus suppression, baseline, config, and CLI/JSON contract tests.

These run the linter on inline source strings (no jax execution), so
they are cheap enough for tier-1.
"""

from __future__ import annotations

import json
import textwrap

import pytest

from cs744_pytorch_distributed_tutorial_tpu.analysis import (
    Baseline,
    lint_paths,
    lint_source,
)
from cs744_pytorch_distributed_tutorial_tpu.analysis.cli import main as cli_main


def run(src: str, rule: str) -> list:
    findings, _ = lint_source(textwrap.dedent(src))
    return [f for f in findings if f.rule == rule]


# ---------------------------------------------------------------- GL001
def test_gl001_item_in_traced_scope():
    hits = run(
        """
        import jax
        import jax.numpy as jnp

        @jax.jit
        def f(x):
            y = jnp.sum(x)
            return y.item()
        """,
        "GL001",
    )
    assert len(hits) == 1 and ".item()" in hits[0].message


def test_gl001_branch_on_derived_traced_value():
    hits = run(
        """
        import jax
        import jax.numpy as jnp

        @jax.jit
        def f(x):
            s = jnp.sum(x)
            if s > 0:
                return s
            return -s
        """,
        "GL001",
    )
    assert len(hits) == 1 and "branching" in hits[0].message


def test_gl001_step_loop_fetch():
    hits = run(
        """
        import jax

        step = jax.jit(lambda s: s)

        def fit(state, steps):
            losses = []
            for _ in range(steps):
                state = step(state)
                losses.append(float(state))
            return losses
        """,
        "GL001",
    )
    assert len(hits) == 1 and "float()" in hits[0].message


def test_gl001_clean_branch_on_static_param():
    # A traced function branching on a plain parameter must NOT fire:
    # params may be static Python config riding alongside tracers.
    assert not run(
        """
        import jax
        import jax.numpy as jnp

        @jax.jit
        def f(x, smoothing=0.0):
            if smoothing == 0.0:
                return jnp.sum(x)
            return jnp.sum(x) * (1 - smoothing)
        """,
        "GL001",
    )


def test_gl001_clean_metadata_predicates():
    # dtype/backend introspection is host-static even though it is
    # spelled as a jax call.
    assert not run(
        """
        import jax
        import jax.numpy as jnp

        @jax.jit
        def f(x):
            if not jnp.issubdtype(x.dtype, jnp.integer):
                raise TypeError("want ints")
            if x.shape[0] % 2:
                raise ValueError("want even batch")
            return x * 2
        """,
        "GL001",
    )


# ---------------------------------------------------------------- GL002
def test_gl002_jit_in_loop():
    hits = run(
        """
        import jax

        def sweep(fns, x):
            outs = []
            for fn in fns:
                g = jax.jit(fn)
                outs.append(g(x))
            return outs
        """,
        "GL002",
    )
    assert len(hits) == 1 and "loop" in hits[0].message


def test_gl002_unhashable_static_arg():
    hits = run(
        """
        import jax

        def run(x, cfg):
            return x

        f = jax.jit(run, static_argnums=(1,))

        def use(x):
            return f(x, {"lr": 0.1})
        """,
        "GL002",
    )
    assert len(hits) == 1 and "static" in hits[0].message


def test_gl002_clean_hoisted_jit():
    assert not run(
        """
        import jax

        def run(x, cfg):
            return x

        f = jax.jit(run, static_argnums=(1,))

        def use(x):
            return f(x, ("lr", 1))
        """,
        "GL002",
    )


# ---------------------------------------------------------------- GL003
def test_gl003_read_after_donation():
    hits = run(
        """
        import jax

        step = jax.jit(lambda s: s, donate_argnums=0)

        def go(state):
            new = step(state)
            return state
        """,
        "GL003",
    )
    assert len(hits) == 1 and "donated" in hits[0].message


def test_gl003_donated_never_rebound_in_loop():
    hits = run(
        """
        import jax

        step = jax.jit(lambda s: s, donate_argnums=0)

        def go(state):
            for _ in range(3):
                out = step(state)
            return out
        """,
        "GL003",
    )
    assert len(hits) == 1 and "never rebound" in hits[0].message


def test_gl003_clean_rebinding():
    assert not run(
        """
        import jax

        step = jax.jit(lambda s: s, donate_argnums=0)

        def go(state):
            for _ in range(3):
                state = step(state)
            return state
        """,
        "GL003",
    )


# ---------------------------------------------------------------- GL004
def test_gl004_key_reuse():
    hits = run(
        """
        import jax

        def sample(key):
            a = jax.random.normal(key, (2,))
            b = jax.random.normal(key, (2,))
            return a + b
        """,
        "GL004",
    )
    assert len(hits) == 1 and "already consumed" in hits[0].message


def test_gl004_clean_split():
    assert not run(
        """
        import jax

        def sample(key):
            ka, kb = jax.random.split(key)
            a = jax.random.normal(ka, (2,))
            b = jax.random.normal(kb, (2,))
            return a + b
        """,
        "GL004",
    )


def test_gl004_subscript_reuse():
    hits = run(
        """
        import jax

        def sample(key):
            keys = jax.random.split(key, 4)
            a = jax.random.normal(keys[0], (2,))
            b = jax.random.normal(keys[0], (2,))
            return a + b
        """,
        "GL004",
    )
    assert len(hits) == 1 and "'keys[0]'" in hits[0].message


def test_gl004_clean_distinct_subscripts():
    assert not run(
        """
        import jax

        def sample(key):
            keys = jax.random.split(key, 4)
            a = jax.random.normal(keys[0], (2,))
            b = jax.random.normal(keys[1], (2,))
            return a + b
        """,
        "GL004",
    )


def test_gl004_subscript_rebind_resets_tracking():
    assert not run(
        """
        import jax

        def sample(key):
            keys = jax.random.split(key, 4)
            a = jax.random.normal(keys[0], (2,))
            keys = jax.random.split(keys[3], 4)
            b = jax.random.normal(keys[0], (2,))
            return a + b
        """,
        "GL004",
    )


def test_gl004_loop_body_reuse():
    hits = run(
        """
        import jax

        def sample(key, xs):
            out = []
            for x in xs:
                out.append(jax.random.normal(key, (2,)) + x)
            return out
        """,
        "GL004",
    )
    assert len(hits) == 1 and "inside a loop" in hits[0].message


def test_gl004_clean_loop_fold_in():
    assert not run(
        """
        import jax

        def sample(key, xs):
            out = []
            for i, x in enumerate(xs):
                k = jax.random.fold_in(key, i)
                out.append(jax.random.normal(k, (2,)) + x)
            return out
        """,
        "GL004",
    )


def test_gl004_clean_loop_carried_split():
    assert not run(
        """
        import jax

        def sample(key, xs):
            out = []
            for x in xs:
                key, sub = jax.random.split(key)
                out.append(jax.random.normal(sub, (2,)) + x)
            return out
        """,
        "GL004",
    )


# ---------------------------------------------------------------- GL005
def test_gl005_axis_drift():
    hits = run(
        """
        import jax
        from jax.sharding import Mesh

        def make(devs):
            return Mesh(devs, ("data",))

        def allsum(x):
            return jax.lax.psum(x, "model")
        """,
        "GL005",
    )
    assert len(hits) == 1 and "'model'" in hits[0].message


def test_gl005_clean_known_axis():
    assert not run(
        """
        import jax
        from jax.sharding import Mesh

        def make(devs):
            return Mesh(devs, ("data",))

        def allsum(x):
            return jax.lax.psum(x, "data")
        """,
        "GL005",
    )


# ---------------------------------------------------------------- GL006
def test_gl006_mutable_default():
    hits = run(
        """
        def collect(x, acc=[]):
            acc.append(x)
            return acc
        """,
        "GL006",
    )
    assert len(hits) == 1 and "mutable default" in hits[0].message


def test_gl006_clean_none_default():
    assert not run(
        """
        def collect(x, acc=None):
            acc = [] if acc is None else acc
            acc.append(x)
            return acc
        """,
        "GL006",
    )


# ---------------------------------------------------------------- GL007
def test_gl007_time_in_trace():
    hits = run(
        """
        import time

        import jax

        @jax.jit
        def f(x):
            t0 = time.perf_counter()
            return x + t0
        """,
        "GL007",
    )
    assert len(hits) == 1 and "trace time" in hits[0].message


def test_gl007_clean_host_timing():
    assert not run(
        """
        import time

        import jax

        @jax.jit
        def f(x):
            return x * 2

        def bench(x):
            t0 = time.perf_counter()
            f(x).block_until_ready()
            return time.perf_counter() - t0
        """,
        "GL007",
    )


# ---------------------------------------------------------------- GL008
def test_gl008_dead_import():
    hits = run(
        """
        import os
        import sys

        print(sys.argv)
        """,
        "GL008",
    )
    assert len(hits) == 1 and "'os'" in hits[0].message


def test_gl008_clean_used_and_exempt():
    assert not run(
        """
        import os
        import _side_effect_module as _sem

        print(os.sep)
        """,
        "GL008",
    )


# ---------------------------------------------------------------- GL009
def test_gl009_block_until_ready_in_step_loop():
    hits = run(
        """
        import jax

        step = jax.jit(lambda s: s)

        def fit(state, steps):
            for _ in range(steps):
                state = step(state)
                jax.block_until_ready(state)
            return state
        """,
        "GL009",
    )
    assert len(hits) == 1 and "block_until_ready" in hits[0].message


def test_gl009_method_form_and_device_get():
    hits = run(
        """
        import jax

        step = jax.jit(lambda s: s)

        def fit(state, steps):
            for _ in range(steps):
                state = step(state)
                state.block_until_ready()
                host = jax.device_get(state)
            return state
        """,
        "GL009",
    )
    assert len(hits) == 2


def test_gl009_clean_cadence_gated_and_no_jit():
    # A wait behind a cadence gate is the sanctioned telemetry pattern,
    # and a loop that drives no known jitted callable is not a step loop.
    assert not run(
        """
        import jax

        step = jax.jit(lambda s: s)

        def fit(state, steps):
            for i in range(steps):
                state = step(state)
                if i % 100 == 0:
                    jax.block_until_ready(state)
            return state

        def warm(xs):
            for x in xs:
                jax.block_until_ready(x)
        """,
        "GL009",
    )


# ---------------------------------------------------------------- GL010
def test_gl010_axis_absent_from_mesh_universe():
    hits = run(
        """
        import jax
        from jax.sharding import Mesh, PartitionSpec

        mesh = Mesh(jax.devices(), ("data",))
        SPEC = PartitionSpec("modle")
        """,
        "GL010",
    )
    assert len(hits) == 1
    assert "'modle'" in hits[0].message and "'data'" in hits[0].message


def test_gl010_duplicate_axis_flagged_without_any_mesh():
    # rank-impossible against EVERY mesh, so no declared mesh is needed
    hits = run(
        """
        from jax.sharding import PartitionSpec

        SPEC = PartitionSpec("data", "data")
        """,
        "GL010",
    )
    assert len(hits) == 1 and "twice" in hits[0].message


def test_gl010_fires_exactly_alone():
    src = """
    import jax
    from jax.sharding import Mesh, PartitionSpec

    mesh = Mesh(jax.devices(), ("data",))
    SPEC = PartitionSpec("modle")
    """
    findings, _ = lint_source(textwrap.dedent(src))
    assert {f.rule for f in findings} == {"GL010"}


def test_gl010_clean_specs_and_gated_without_mesh():
    # valid axes (incl. None placeholders) pass; and with NO mesh in the
    # module the unknown-axis check stays silent — spec literals alone
    # prove nothing about the mesh they will meet at runtime
    assert not run(
        """
        import jax
        from jax.sharding import Mesh, PartitionSpec

        mesh = Mesh(jax.devices(), ("data", "model"))
        S1 = PartitionSpec("data", "model")
        S2 = PartitionSpec(None, "data")
        """,
        "GL010",
    )
    assert not run(
        """
        from jax.sharding import PartitionSpec

        SPEC = PartitionSpec("anything")
        """,
        "GL010",
    )


# ---------------------------------------------------------- suppressions
def test_trailing_suppression_silences_same_line():
    src = textwrap.dedent(
        """
        import jax
        import jax.numpy as jnp

        @jax.jit
        def f(x):
            y = jnp.sum(x)
            return y.item()  # graftlint: disable=GL001 -- test pragma
        """
    )
    findings, suppressed = lint_source(src)
    assert not [f for f in findings if f.rule == "GL001"]
    assert suppressed == 1


def test_standalone_suppression_binds_past_comment_block():
    src = textwrap.dedent(
        """
        import jax
        import jax.numpy as jnp

        @jax.jit
        def f(x):
            y = jnp.sum(x)
            # graftlint: disable=GL001 -- pragma on first comment line
            # with a continuation comment between it and the code.
            return y.item()
        """
    )
    findings, suppressed = lint_source(src)
    assert not [f for f in findings if f.rule == "GL001"]
    assert suppressed == 1


def test_disable_file_suppresses_rule_everywhere():
    src = textwrap.dedent(
        """
        # graftlint: disable-file=GL006 -- test pragma
        def a(x, acc=[]):
            return acc

        def b(x, acc={}):
            return acc
        """
    )
    findings, suppressed = lint_source(src)
    assert not [f for f in findings if f.rule == "GL006"]
    assert suppressed == 2


def test_suppression_is_rule_specific():
    src = textwrap.dedent(
        """
        def a(x, acc=[]):  # graftlint: disable=GL001 -- wrong rule
            return acc
        """
    )
    findings, _ = lint_source(src)
    assert [f for f in findings if f.rule == "GL006"]


# -------------------------------------------------------------- baseline
BUGGY = textwrap.dedent(
    """
    import jax
    import jax.numpy as jnp

    @jax.jit
    def f(x):
        y = jnp.sum(x)
        return y.item()
    """
)


def test_baseline_silences_then_resurfaces(tmp_path):
    mod = tmp_path / "mod.py"
    mod.write_text(BUGGY)

    report = lint_paths([str(mod)])
    assert report.exit_code == 1 and len(report.findings) == 1

    entries = Baseline.fingerprints(report.findings, report.sources)
    baseline = Baseline(entries)
    report2 = lint_paths([str(mod)], baseline=baseline)
    assert report2.exit_code == 0
    assert not report2.findings and len(report2.baselined) == 1

    # Unrelated edits (line shifts) keep the baseline entry valid...
    mod.write_text("# a new leading comment\n" + BUGGY)
    report3 = lint_paths([str(mod)], baseline=baseline)
    assert report3.exit_code == 0

    # ...but touching the flagged line itself resurfaces the finding.
    mod.write_text(BUGGY.replace("return y.item()", "return  y.item()"))
    report4 = lint_paths([str(mod)], baseline=baseline)
    assert report4.exit_code == 1 and len(report4.findings) == 1


def test_baseline_round_trips_through_disk(tmp_path):
    mod = tmp_path / "mod.py"
    mod.write_text(BUGGY)
    report = lint_paths([str(mod)])
    bl_path = tmp_path / "baseline.json"
    Baseline.dump(report.findings, report.sources, bl_path)
    reloaded = Baseline.load(bl_path)
    assert lint_paths([str(mod)], baseline=reloaded).exit_code == 0


# ------------------------------------------------------------------- CLI
def test_cli_json_output_is_valid(tmp_path, capsys, monkeypatch):
    monkeypatch.chdir(tmp_path)
    mod = tmp_path / "mod.py"
    mod.write_text(BUGGY)
    rc = cli_main([str(mod), "--format=json", "--no-baseline"])
    payload = json.loads(capsys.readouterr().out)
    assert rc == 1 and payload["exit_code"] == 1
    (finding,) = payload["findings"]
    assert finding["rule"] == "GL001"
    assert {"path", "line", "col", "rule", "name", "message"} <= finding.keys()


def test_cli_clean_tree_exits_zero(tmp_path, capsys, monkeypatch):
    monkeypatch.chdir(tmp_path)
    mod = tmp_path / "ok.py"
    mod.write_text("import os\n\nprint(os.sep)\n")
    assert cli_main([str(mod), "--no-baseline"]) == 0


def test_cli_select_unknown_rule_is_usage_error(tmp_path, capsys, monkeypatch):
    monkeypatch.chdir(tmp_path)
    mod = tmp_path / "ok.py"
    mod.write_text("x = 1\n")
    assert cli_main([str(mod), "--select=GL999"]) == 2


def test_cli_syntax_error_is_a_finding_exit(tmp_path, capsys, monkeypatch):
    monkeypatch.chdir(tmp_path)
    mod = tmp_path / "bad.py"
    mod.write_text("def f(:\n")
    assert cli_main([str(mod), "--no-baseline"]) == 1


# ----------------------------------------------------------------- --fix
def fix(src: str) -> tuple[str, int]:
    from cs744_pytorch_distributed_tutorial_tpu.analysis.fix import fix_source

    return fix_source(textwrap.dedent(src), "mod.py")


def test_fix_removes_dead_import():
    new, n = fix(
        """
        import os
        import json

        print(json.dumps({}))
        """
    )
    assert n == 1
    assert "import os" not in new and "import json" in new


def test_fix_rewrites_partially_dead_from_import():
    new, n = fix(
        """
        from os.path import join, basename

        print(join("a", "b"))
        """
    )
    assert n == 1
    assert "from os.path import join" in new and "basename" not in new


def test_fix_cascades_to_fixpoint_and_is_idempotent():
    src = """
    import json
    import os

    x = json.dumps({})
    """
    new, n = fix(src)
    assert n == 1 and "import os" not in new
    again, n2 = fix(new)
    assert n2 == 0 and again == new


def test_fix_preserves_exempt_imports():
    src = """
    from __future__ import annotations

    import os as _side_effect
    import sys

    __all__ = ["sys"]
    """
    new, n = fix(src)
    assert n == 0 and new == textwrap.dedent(src)


def test_fix_skips_try_nested_imports():
    src = """
    try:
        import fancy_dep
    except ImportError:
        fancy_dep = None
    """
    new, n = fix(src)
    assert n == 0 and new == textwrap.dedent(src)


def test_fix_respects_suppression_pragma():
    src = "import os  # graftlint: disable=GL008\n"
    new, n = fix(src)
    assert n == 0 and new == src


def test_fix_handles_multiline_parenthesized_import():
    new, n = fix(
        """
        from os.path import (
            join,
            basename,
        )

        print(basename("x"))
        """
    )
    assert n == 1
    assert "from os.path import basename" in new and "join" not in new


def test_fix_paths_rewrites_in_place(tmp_path):
    from cs744_pytorch_distributed_tutorial_tpu.analysis.fix import fix_paths

    mod = tmp_path / "mod.py"
    mod.write_text("import os\nimport sys\n\nprint(sys.argv)\n")
    clean = tmp_path / "clean.py"
    clean.write_text("x = 1\n")
    files_changed, removed = fix_paths([str(tmp_path)])
    assert (files_changed, removed) == (1, 1)
    assert mod.read_text() == "import sys\n\nprint(sys.argv)\n"
    assert clean.read_text() == "x = 1\n"


def test_cli_fix_then_lints_clean(tmp_path, capsys, monkeypatch):
    monkeypatch.chdir(tmp_path)
    mod = tmp_path / "mod.py"
    mod.write_text("import os\n\nx = 1\n")
    assert cli_main([str(mod), "--fix", "--no-baseline"]) == 0
    assert "import os" not in mod.read_text()


# ----------------------------------------------- TA pragmas share the regex
def test_suppression_regex_accepts_ta_rules():
    """graftcheck findings anchor to register_entrypoint lines and reuse
    graftlint's pragma machinery, so TA ids must parse."""
    from cs744_pytorch_distributed_tutorial_tpu.analysis.core import (
        Finding,
        Suppressions,
    )

    src = "register_entrypoint('x', f)  # graftlint: disable=TA003\n"
    supp = Suppressions(src)
    ta = Finding(
        path="mod.py", line=1, col=1, rule="TA003", name="x", message="m"
    )
    gl = Finding(
        path="mod.py", line=1, col=1, rule="GL001", name="x", message="m"
    )
    assert supp.is_suppressed(ta)
    assert not supp.is_suppressed(gl)


def test_repo_tree_is_lint_clean():
    """The checked-in tree must stay clean under the checked-in config —
    the same contract the CI lint job enforces."""
    import pathlib

    repo = pathlib.Path(__file__).resolve().parent.parent
    if not (repo / "pyproject.toml").is_file():  # installed-package run
        pytest.skip("source tree not available")
    import subprocess
    import sys

    proc = subprocess.run(
        [sys.executable, "-m", "cs744_pytorch_distributed_tutorial_tpu.analysis"],
        cwd=repo,
        capture_output=True,
        text=True,
        env={
            **__import__("os").environ,
            "JAX_PLATFORMS": "cpu",
        },
    )
    assert proc.returncode == 0, proc.stdout + proc.stderr
