"""Mesh-elastic restore (parallel/elastic.py + utils/memstore.py).

The re-mesh discipline: a failed world's newest committed state restores
onto a DIFFERENT world size deterministically — replicated params
redistribute, per-replica BN stats slice/tile along their leading
device axis, zero1/fsdp chunked optimizer shards re-chunk through the
engines' elastic adapt hooks, and the data-sampler offset follows the
restored step. These tests pin the matrix through the IN-MEMORY tier
(``ReplicatedSnapshot`` handed across trainers — zero filesystem reads,
asserted via the instrumented Checkpointer counters):

- shrink and grow (dp4 <-> dp2) x zero1/fsdp on the LM engine, with the
  resumed loss curve matching the uninterrupted run at rtol 1e-6
  (chunking and reduction order are layout, not math);
- CIFAR shrink/grow carrying per-replica BN batch_stats (mechanical:
  per-replica normalization legitimately depends on the replica count,
  so the pin is a correct resume, not trajectory parity);
- ``surviving_mesh`` unit semantics (data-axis-only elasticity).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from conftest import TINY_DP4_CFG

from cs744_pytorch_distributed_tutorial_tpu.config import TrainConfig
from cs744_pytorch_distributed_tutorial_tpu.data import synthetic_tokens
from cs744_pytorch_distributed_tutorial_tpu.parallel import make_mesh
from cs744_pytorch_distributed_tutorial_tpu.parallel.elastic import (
    surviving_mesh,
)
from cs744_pytorch_distributed_tutorial_tpu.train import (
    LMConfig,
    LMTrainer,
    Trainer,
)
from cs744_pytorch_distributed_tutorial_tpu.utils.checkpoint import (
    Checkpointer,
)
from cs744_pytorch_distributed_tutorial_tpu.utils.memstore import (
    ReplicatedSnapshot,
)

TINY_LM = dict(
    vocab_size=32, num_layers=1, num_heads=2, d_model=16, d_ff=32,
    max_seq_len=64, seq_len=16, global_batch_size=8,
    attention_impl="dense",
)


# ------------------------------------------------------ surviving_mesh


def test_surviving_mesh_shrinks_data_axis_only():
    devs = jax.devices()[:8]
    mesh = make_mesh({"data": 4, "seq": 2}, devices=devs)
    lost = {devs[1].id, devs[6].id}
    new = surviving_mesh(mesh, lost)
    assert dict(new.shape) == {"data": 3, "seq": 2}
    assert {d.id for d in new.devices.flatten()}.isdisjoint(lost)


def test_surviving_mesh_rejects_nondivisible_survivors():
    mesh = make_mesh({"data": 4, "seq": 2}, devices=jax.devices()[:8])
    with pytest.raises(ValueError, match="seq/tensor"):
        surviving_mesh(mesh, [jax.devices()[0].id])  # 7 % 2 != 0


def test_surviving_mesh_rejects_total_loss():
    mesh = make_mesh({"data": 2}, devices=jax.devices()[:2])
    with pytest.raises(ValueError, match="no devices survive"):
        surviving_mesh(mesh, [d.id for d in jax.devices()[:2]])


# -------------------------------------------------- ReplicatedSnapshot


def test_replicated_snapshot_ring_retention():
    snap = ReplicatedSnapshot(max_to_keep=2)
    for step in (1, 2, 3):
        snap.save({"w": jnp.full((4,), float(step))}, step=step)
    assert snap.steps() == [2, 3]
    assert snap.latest_step() == 3
    template = {"w": jnp.zeros((4,))}
    restored = snap.restore_latest(template)
    np.testing.assert_array_equal(
        np.asarray(restored["w"]), np.full((4,), 3.0)
    )
    assert snap.saves == 3 and snap.restores == 1
    snap.clear()
    assert snap.latest_step() is None
    assert snap.restore_latest(template) is None


# --------------------------------------- LM matrix: shrink/grow x opt


@pytest.mark.slow  # chaos-smoke CI runs these without the tier-1 filter
@pytest.mark.parametrize("mode", ["zero1", "fsdp"])
@pytest.mark.parametrize("dp_save,dp_resume", [(4, 2), (2, 4)])
def test_lm_memstore_elastic_matrix(mode, dp_save, dp_resume):
    """Save at dp_save in host RAM, hand the snapshot tier to a fresh
    trainer at dp_resume: the chunked optimizer shards (and, for fsdp,
    the chunked params) re-chunk through the elastic adapt hook, no
    filesystem touched, and head+tail equals the uninterrupted dp_save
    trajectory at rtol 1e-6."""
    kw = {mode: True}
    tokens = synthetic_tokens(8, 16, 32, seed=0)
    mesh_a = make_mesh({"data": dp_save, "seq": 1},
                       devices=jax.devices()[:dp_save])
    mesh_b = make_mesh({"data": dp_resume, "seq": 1},
                       devices=jax.devices()[:dp_resume])
    tr = LMTrainer(
        LMConfig(**TINY_LM, data_parallel=dp_save, snapshot_every=2, **kw),
        mesh=mesh_a,
    )
    _, _, head = tr.fit(tokens, steps=4)

    disk_restores_before = Checkpointer.total_restores
    tr2 = LMTrainer(
        LMConfig(**TINY_LM, data_parallel=dp_resume, snapshot_every=2, **kw),
        mesh=mesh_b,
        memstore=tr.memstore,
    )
    _, _, tail = tr2.fit(tokens, steps=6)
    assert len(tail) == 2, tail
    assert Checkpointer.total_restores == disk_restores_before
    assert tr.memstore.restores >= 1

    oracle = LMTrainer(
        LMConfig(**TINY_LM, data_parallel=dp_save, **kw), mesh=mesh_a
    )
    _, _, full = oracle.fit(tokens, steps=6)
    np.testing.assert_allclose(head + tail, full, rtol=1e-6)


# ------------------------------------------- CIFAR BN-stats elasticity


@pytest.mark.slow  # chaos-smoke CI runs these without the tier-1 filter
@pytest.mark.parametrize("dp_save,dp_resume", [(4, 2), (2, 4)])
def test_cifar_memstore_elastic_bn_stats(dp_save, dp_resume, mesh4):
    """Per-replica BN batch_stats carry a leading [num_devices] axis;
    the elastic restore slices (shrink) or cyclically tiles (grow) it to
    the new world and training resumes at the recorded step."""
    base = dict(TINY_DP4_CFG, sync="allreduce", log_every=1)
    mesh_for = {
        4: mesh4,
        2: make_mesh({"data": 2}, devices=jax.devices()[:2]),
    }
    cfg_a = TrainConfig(**{**base, "num_devices": dp_save},
                        snapshot_every=1)
    tr = Trainer(cfg_a, mesh=mesh_for[dp_save])
    state, _ = tr.fit()
    assert int(np.asarray(state.step)) == 4  # one 4-step epoch

    disk_restores_before = Checkpointer.total_restores
    cfg_b = TrainConfig(**{**base, "num_devices": dp_resume},
                        snapshot_every=1, epochs=2)
    tr2 = Trainer(cfg_b, mesh=mesh_for[dp_resume], memstore=tr.memstore)
    state2, history2 = tr2.fit()
    assert Checkpointer.total_restores == disk_restores_before
    assert tr.memstore.restores >= 1
    assert int(np.asarray(state2.step)) == 8  # epoch 0 skipped, 1 trained
    for leaf in jax.tree.leaves(state2.batch_stats):
        assert leaf.shape[0] == dp_resume
    assert np.isfinite(history2["eval"][-1]["avg_loss"])
