"""ViT family: registry contract, engine training, flash-impl parity."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from cs744_pytorch_distributed_tutorial_tpu.config import TrainConfig
from cs744_pytorch_distributed_tutorial_tpu.models import ViT, get_model
from cs744_pytorch_distributed_tutorial_tpu.train import Trainer


def test_vit_forward_shape_and_no_batch_stats():
    model = get_model("vit_tiny", num_classes=10)
    x = jnp.zeros((2, 32, 32, 3))
    variables = model.init(jax.random.key(0), x, train=False)
    assert "batch_stats" not in variables
    logits = model.apply(variables, x, train=False)
    assert logits.shape == (2, 10)
    assert logits.dtype == jnp.float32


def test_vit_wide_p8_geometry():
    """The round-5 MXU geometry variant: 17 tokens of d384 at head_dim
    128, registry-constructible, per-sample FLOPs within 2% of
    vit_tiny's (so their MFU difference IS the geometry)."""
    from cs744_pytorch_distributed_tutorial_tpu.models import get_model

    m = get_model("vit_wide_p8", num_classes=10)
    assert (m.patch_size, m.d_model, m.num_heads) == (8, 384, 3)
    x = jnp.zeros((2, 32, 32, 3))
    logits = m.init(jax.random.key(0), x)
    out = m.apply(logits, x)
    assert out.shape == (2, 10)

    def flops(d, layers, d_ff, n):
        return layers * (n * (4 * d * d + 2 * d * d_ff) + 2 * n * n * d)

    tiny = flops(192, 6, 768, 65)
    wide = flops(384, 6, 1536, 17)
    assert abs(wide - tiny) / tiny < 0.02, (tiny, wide)


def test_vit_rejects_indivisible_patches():
    model = ViT(patch_size=5)
    with pytest.raises(ValueError, match="patch_size"):
        model.init(jax.random.key(0), jnp.zeros((1, 32, 32, 3)))


def test_vit_flash_matches_dense():
    """The flash kernel (interpret mode here) reproduces dense attention
    inside the classifier."""
    x = jax.random.normal(jax.random.key(1), (2, 32, 32, 3))
    dense = ViT(num_layers=2, attention_impl="dense")
    flash = ViT(num_layers=2, attention_impl="flash", flash_interpret=True)
    params = dense.init(jax.random.key(0), x)
    np.testing.assert_allclose(
        np.asarray(flash.apply(params, x)),
        np.asarray(dense.apply(params, x)),
        rtol=2e-5, atol=2e-5,
    )


@pytest.mark.slow
def test_vit_dropout_trains_and_eval_is_deterministic(mesh4):
    """dropout_rate > 0: training runs (engine supplies the rng), the
    trajectory differs from rate 0, and eval stays deterministic."""
    from cs744_pytorch_distributed_tutorial_tpu.data import synthetic_cifar10
    from cs744_pytorch_distributed_tutorial_tpu.parallel.mesh import (
        shard_global_batch,
    )

    ds = synthetic_cifar10(16, 8, seed=0)
    params = {}
    for rate in (0.0, 0.3):
        cfg = TrainConfig(model="vit_tiny", sync="auto", num_devices=4,
                          global_batch_size=16, synthetic_data=True,
                          dropout_rate=rate)
        tr = Trainer(cfg, mesh=mesh4)
        state = tr.init()
        x, y = shard_global_batch(mesh4, ds.train_images, ds.train_labels)
        for _ in range(2):
            state, m = tr.train_step(state, x, y, jax.random.key(0))
        assert np.isfinite(float(m["loss"]))
        params[rate] = state.params
        if rate > 0:
            xt, yt = shard_global_batch(mesh4, ds.test_images, ds.test_labels)
            mask = shard_global_batch(mesh4, np.ones(8, np.float32))
            e1 = tr.eval_step(state, xt, yt, mask)
            e2 = tr.eval_step(state, xt, yt, mask)
            assert float(e1["loss_sum"]) == float(e2["loss_sum"])
    a = jax.tree.leaves(jax.device_get(params[0.0]))
    b = jax.tree.leaves(jax.device_get(params[0.3]))
    assert any(not np.allclose(x_, y_) for x_, y_ in zip(a, b))

    with pytest.raises(ValueError, match="dropout"):
        Trainer(TrainConfig(model="vgg11", num_devices=4,
                            global_batch_size=16, dropout_rate=0.1,
                            synthetic_data=True), mesh=mesh4)
    for bad in (1.0, -0.5):
        with pytest.raises(ValueError, match="dropout_rate"):
            Trainer(TrainConfig(model="vit_tiny", num_devices=4,
                                global_batch_size=16, dropout_rate=bad,
                                synthetic_data=True), mesh=mesh4)


@pytest.mark.slow
def test_vit_trains_distributed(mesh4):
    """ViT under the same DP engine as VGG/ResNet: finite losses, empty
    per-replica batch_stats, eval runs."""
    cfg = TrainConfig(
        model="vit_tiny",
        sync="auto",
        num_devices=4,
        global_batch_size=16,
        synthetic_data=True,
        synthetic_train_size=64,
        synthetic_test_size=32,
        epochs=1,
        log_every=1,
    )
    tr = Trainer(cfg, mesh=mesh4)
    state, history = tr.fit()
    losses = [l for (_, _, l) in history["train_loss"]]
    assert np.isfinite(losses).all()
    assert history["eval"][-1]["count"] == 32
