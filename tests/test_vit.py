"""ViT family: registry contract, engine training, flash-impl parity."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from cs744_pytorch_distributed_tutorial_tpu.config import TrainConfig
from cs744_pytorch_distributed_tutorial_tpu.models import ViT, get_model
from cs744_pytorch_distributed_tutorial_tpu.train import Trainer


def test_vit_forward_shape_and_no_batch_stats():
    model = get_model("vit_tiny", num_classes=10)
    x = jnp.zeros((2, 32, 32, 3))
    variables = model.init(jax.random.key(0), x, train=False)
    assert "batch_stats" not in variables
    logits = model.apply(variables, x, train=False)
    assert logits.shape == (2, 10)
    assert logits.dtype == jnp.float32


def test_vit_rejects_indivisible_patches():
    model = ViT(patch_size=5)
    with pytest.raises(ValueError, match="patch_size"):
        model.init(jax.random.key(0), jnp.zeros((1, 32, 32, 3)))


def test_vit_flash_matches_dense():
    """The flash kernel (interpret mode here) reproduces dense attention
    inside the classifier."""
    x = jax.random.normal(jax.random.key(1), (2, 32, 32, 3))
    dense = ViT(num_layers=2, attention_impl="dense")
    flash = ViT(num_layers=2, attention_impl="flash", flash_interpret=True)
    params = dense.init(jax.random.key(0), x)
    np.testing.assert_allclose(
        np.asarray(flash.apply(params, x)),
        np.asarray(dense.apply(params, x)),
        rtol=2e-5, atol=2e-5,
    )


def test_vit_trains_distributed(mesh4):
    """ViT under the same DP engine as VGG/ResNet: finite losses, empty
    per-replica batch_stats, eval runs."""
    cfg = TrainConfig(
        model="vit_tiny",
        sync="auto",
        num_devices=4,
        global_batch_size=16,
        synthetic_data=True,
        synthetic_train_size=64,
        synthetic_test_size=32,
        epochs=1,
        log_every=1,
    )
    tr = Trainer(cfg, mesh=mesh4)
    state, history = tr.fit()
    losses = [l for (_, _, l) in history["train_loss"]]
    assert np.isfinite(losses).all()
    assert history["eval"][-1]["count"] == 32
