"""Pallas flash attention vs dense reference (interpret mode on CPU)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from cs744_pytorch_distributed_tutorial_tpu.ops.flash_attention import (
    flash_attention,
)
from cs744_pytorch_distributed_tutorial_tpu.parallel.ring_attention import (
    dense_attention,
)

B, T, H, D = 2, 64, 2, 16


@pytest.fixture(scope="module")
def qkv():
    ks = jax.random.split(jax.random.key(42), 3)
    mk = lambda k: jax.random.normal(k, (B, T, H, D), jnp.float32)
    return mk(ks[0]), mk(ks[1]), mk(ks[2])


@pytest.mark.parametrize("causal", [False, True])
@pytest.mark.parametrize("block", [16, 32, 64])
def test_matches_dense(qkv, causal, block):
    q, k, v = qkv
    expected = np.asarray(dense_attention(q, k, v, causal=causal))
    got = np.asarray(
        flash_attention(q, k, v, causal, block, block, True)
    )
    np.testing.assert_allclose(got, expected, rtol=2e-5, atol=2e-5)


def test_uneven_block_sizes_fall_back_to_divisors(qkv):
    q, k, v = qkv  # T=64; preferred 48 does not divide -> picks a divisor
    expected = np.asarray(dense_attention(q, k, v, causal=True))
    got = np.asarray(flash_attention(q, k, v, True, 48, 48, True))
    np.testing.assert_allclose(got, expected, rtol=2e-5, atol=2e-5)


def test_gradients_match_dense(qkv):
    q, k, v = qkv

    def loss_flash(q, k, v):
        return (flash_attention(q, k, v, True, 32, 32, True) ** 2).sum()

    def loss_dense(q, k, v):
        return (dense_attention(q, k, v, causal=True) ** 2).sum()

    g_flash = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    g_dense = jax.grad(loss_dense, argnums=(0, 1, 2))(q, k, v)
    for gf, gd in zip(g_flash, g_dense):
        np.testing.assert_allclose(
            np.asarray(gf), np.asarray(gd), rtol=1e-4, atol=1e-4
        )


@pytest.mark.parametrize("t", [24, 40, 96, 160])
def test_odd_lengths_pick_divisor_blocks(t):
    """Sequence lengths that don't divide the default 512/1024 blocks:
    _pick_block must find a working divisor, forward AND backward."""
    ks = jax.random.split(jax.random.key(t), 3)
    q, k, v = (jax.random.normal(kk, (1, t, 2, 8)) for kk in ks)
    expected = np.asarray(dense_attention(q, k, v, causal=True))
    got = np.asarray(flash_attention(q, k, v, True, interpret=True))
    np.testing.assert_allclose(got, expected, rtol=2e-5, atol=2e-5)

    g_f = jax.grad(
        lambda a: (flash_attention(a, k, v, True, interpret=True) ** 2).sum()
    )(q)
    g_d = jax.grad(
        lambda a: (dense_attention(a, k, v, causal=True) ** 2).sum()
    )(q)
    np.testing.assert_allclose(np.asarray(g_f), np.asarray(g_d),
                               rtol=1e-4, atol=1e-4)


def test_prime_length_rejected_loudly():
    """A prime T larger than the block size has no usable divisor — the
    kernel refuses instead of silently crawling one padded row per grid
    step. (Primes BELOW the block size are fine: the whole sequence is
    one block.)"""
    q = jnp.zeros((1, 1031, 2, 8))  # prime > 512
    with pytest.raises(ValueError, match="block"):
        flash_attention(q, q, q, True, interpret=True)
    small = jnp.zeros((1, 37, 2, 8))  # prime < block: single-block path
    out = flash_attention(small, small, small, True, interpret=True)
    assert out.shape == small.shape


def test_bfloat16_inputs(qkv):
    q, k, v = (a.astype(jnp.bfloat16) for a in qkv)
    expected = np.asarray(
        dense_attention(q, k, v, causal=False).astype(jnp.float32)
    )
    got = np.asarray(
        flash_attention(q, k, v, False, 32, 32, True).astype(jnp.float32)
    )
    np.testing.assert_allclose(got, expected, rtol=2e-2, atol=2e-2)
