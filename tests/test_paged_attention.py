"""Pallas paged-attention decode kernel (ops/paged_attention.py).

Four contracts, each against the gather+einsum reference that stays in
``parallel/ring_attention.py`` / ``ops/quant.py``:

1. **Parity** — float (f32/bf16 pools) and int8-KV (dequant inside the
   kernel) match the reference within the flash tolerance discipline.
   Online softmax reassociates the reduction, so this is tolerance-level
   by design, not bitwise (the gather path keeps the bitwise story).
2. **Live pages only** — pages past a slot's live length are NEVER read:
   poisoning every dead page with NaN must not change the output. This
   is the functional face of the clamped index_map (dead grid iterations
   re-point at the last live page, so no new DMA issues).
3. **Tensor-parallel** — under ``shard_map`` with pools sharded over KV
   heads (and q over query heads), per-shard kernels reproduce the
   unsharded answer: the grid derives from local shapes.
4. **Bytes scale with live tokens** — compiled ``cost_analysis``
   bytes-accessed for a decode step grows linearly with the live page
   count and is EXACTLY invariant to page-table capacity, at two pool
   geometries. The XLA CPU cost model counts operand shapes (the
   interpret-mode grid loop is counted once), so the test compiles a
   step whose operands ARE the live working set: pages allocated
   contiguously from 1, pool statically sliced to the live pages,
   ``pages_per_slot`` pruning the table — making "bytes ~ live, not
   max_seq_len" visible analytically on CPU. The same CPU cost model is
   why the un-sliced comparison still pins the gather reference's bytes
   growing with capacity while the kernel's stay flat.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from cs744_pytorch_distributed_tutorial_tpu.obs.phases import compiled_costs
from cs744_pytorch_distributed_tutorial_tpu.ops.paged_attention import (
    paged_attention,
)
from cs744_pytorch_distributed_tutorial_tpu.ops.quant import (
    paged_decode_attention_quant,
)
from cs744_pytorch_distributed_tutorial_tpu.parallel.ring_attention import (
    paged_decode_attention,
)

B, HQ, HKV, D = 3, 4, 2, 16


def _pools(key, num_pages, page_size, dtype=jnp.float32):
    kk, kv = jax.random.split(key)
    shape = (num_pages, page_size, HKV, D)
    return (
        jax.random.normal(kk, shape, jnp.float32).astype(dtype),
        jax.random.normal(kv, shape, jnp.float32).astype(dtype),
    )


def _layout(num_pages, page_size, ppr, seed=0):
    """Distinct pages per slot (shuffled — order must not matter) and
    staggered live depths, including a fresh slot at pos 0."""
    rng = np.random.default_rng(seed)
    perm = 1 + rng.permutation(num_pages - 1)[: B * ppr]
    table = jnp.asarray(perm.reshape(B, ppr), jnp.int32)
    depths = [0, page_size * (ppr - 1), ppr * page_size - 1][:B]
    pos = jnp.asarray(depths, jnp.int32)
    return table, pos


@pytest.mark.parametrize(
    "dtype,tol",
    [(jnp.float32, 2e-5), (jnp.bfloat16, 2e-2)],
    ids=["f32", "bf16"],
)
def test_kernel_matches_gather_reference(dtype, tol):
    page_size, ppr = 4, 4
    kp, vp = _pools(jax.random.key(0), 17, page_size, dtype)
    table, pos = _layout(17, page_size, ppr)
    q = jax.random.normal(jax.random.key(1), (B, 1, HQ, D), jnp.float32)
    q = q.astype(dtype)
    expected = np.asarray(
        paged_decode_attention(q, kp, vp, table, pos), jnp.float32
    )
    got = np.asarray(
        paged_attention(q, kp, vp, table, pos, interpret=True), jnp.float32
    )
    np.testing.assert_allclose(got, expected, rtol=tol, atol=tol)


def test_kernel_int8_matches_quant_reference():
    """int8 pools + per-row scale pools, dequant INSIDE the kernel —
    same algebra as decode_attention_quant (k_scale on scores, v_scale
    folded into probs)."""
    page_size, ppr, num_pages = 4, 4, 17
    ks = jax.random.split(jax.random.key(2), 4)
    shape = (num_pages, page_size, HKV, D)
    kp = jax.random.randint(ks[0], shape, -127, 128, jnp.int32).astype(
        jnp.int8
    )
    vp = jax.random.randint(ks[1], shape, -127, 128, jnp.int32).astype(
        jnp.int8
    )
    ksc = jax.random.uniform(
        ks[2], shape[:3], jnp.float32, 0.5 / 127, 1.5 / 127
    )
    vsc = jax.random.uniform(
        ks[3], shape[:3], jnp.float32, 0.5 / 127, 1.5 / 127
    )
    table, pos = _layout(num_pages, page_size, ppr, seed=1)
    q = jax.random.normal(jax.random.key(3), (B, 1, HQ, D), jnp.float32)
    expected = np.asarray(
        paged_decode_attention_quant(q, kp, vp, ksc, vsc, table, pos)
    )
    got = np.asarray(
        paged_attention(
            q, kp, vp, table, pos,
            key_scale_pages=ksc, value_scale_pages=vsc, interpret=True,
        )
    )
    np.testing.assert_allclose(got, expected, rtol=2e-5, atol=2e-5)


def test_kernel_never_reads_dead_pages():
    """Poison every page past each slot's live length (and every
    unreferenced pool page) with NaN: the output must stay finite and
    EQUAL to the clean run — the clamped index_map means dead grid
    iterations issue no new reads."""
    page_size, ppr, num_pages = 4, 4, 33
    kp, vp = _pools(jax.random.key(4), num_pages, page_size)
    table, pos = _layout(num_pages, page_size, ppr, seed=2)
    q = jax.random.normal(jax.random.key(5), (B, 1, HQ, D), jnp.float32)
    clean = np.asarray(paged_attention(q, kp, vp, table, pos, interpret=True))

    live = np.asarray(pos) // page_size + 1
    live_pages = {
        int(np.asarray(table)[b, i])
        for b in range(B)
        for i in range(int(live[b]))
    }
    dead = np.asarray([p for p in range(num_pages) if p not in live_pages])
    kp = np.asarray(kp).copy()
    vp = np.asarray(vp).copy()
    kp[dead] = np.nan
    vp[dead] = np.nan
    poisoned = np.asarray(
        paged_attention(
            q, jnp.asarray(kp), jnp.asarray(vp), table, pos, interpret=True
        )
    )
    assert np.isfinite(poisoned).all()
    np.testing.assert_array_equal(poisoned, clean)


@pytest.mark.parametrize("quant", [False, True], ids=["float", "int8"])
def test_kernel_tensor_parallel_matches_unsharded(quant):
    """Pools sharded over KV heads, q over query heads (the serving TP
    layout): per-shard grids over the LOCAL Hkv reproduce the unsharded
    kernel — no head-index plumbing needed."""
    from cs744_pytorch_distributed_tutorial_tpu.parallel import make_mesh

    page_size, ppr, num_pages = 4, 4, 17
    table, pos = _layout(num_pages, page_size, ppr, seed=3)
    q = jax.random.normal(jax.random.key(6), (B, 1, HQ, D), jnp.float32)
    shape = (num_pages, page_size, HKV, D)
    if quant:
        ks = jax.random.split(jax.random.key(7), 4)
        kp = jax.random.randint(ks[0], shape, -127, 128, jnp.int32).astype(
            jnp.int8
        )
        vp = jax.random.randint(ks[1], shape, -127, 128, jnp.int32).astype(
            jnp.int8
        )
        ksc = jax.random.uniform(
            ks[2], shape[:3], jnp.float32, 0.5 / 127, 1.5 / 127
        )
        vsc = jax.random.uniform(
            ks[3], shape[:3], jnp.float32, 0.5 / 127, 1.5 / 127
        )
        scales = (ksc, vsc)
    else:
        kp, vp = _pools(jax.random.key(7), num_pages, page_size)
        scales = ()

    def call(q, kp, vp, *scales):
        sc = (
            dict(key_scale_pages=scales[0], value_scale_pages=scales[1])
            if scales
            else {}
        )
        return paged_attention(q, kp, vp, table, pos, interpret=True, **sc)

    expected = np.asarray(call(q, kp, vp, *scales))
    mesh = make_mesh({"tensor": 2}, devices=jax.devices()[:2])
    head = P(None, None, "tensor", None)
    in_specs = (head, head, head) + (P(None, None, "tensor"),) * len(scales)
    mapped = jax.shard_map(
        call, mesh=mesh, in_specs=in_specs, out_specs=head, check_vma=False
    )
    got = np.asarray(jax.jit(mapped)(q, kp, vp, *scales))
    np.testing.assert_allclose(got, expected, rtol=2e-5, atol=2e-5)


# ------------------------------------------------ analytical bytes gate


def _kernel_step_bytes(live_pages, capacity, page_size):
    """Compiled bytes-accessed for one decode step over a LIVE working
    set: pages contiguous from 1, pool sliced to them, table pruned to
    ``pages_per_slot=live_pages`` (module docstring on why the slice is
    what makes live-scaling visible to the CPU cost model)."""
    k_live = B * live_pages + 1  # + trash page 0
    kp, vp = _pools(jax.random.key(8), k_live, page_size)
    table = np.zeros((B, capacity), np.int32)
    for b in range(B):
        table[b, :live_pages] = 1 + b * live_pages + np.arange(live_pages)
    pos = jnp.full((B,), live_pages * page_size - 1, jnp.int32)
    q = jax.random.normal(jax.random.key(9), (B, 1, HQ, D), jnp.float32)

    def step(q, kp, vp, table):
        return paged_attention(
            q, kp, vp, table, pos, interpret=True,
            pages_per_slot=live_pages,
        )

    compiled = jax.jit(step).lower(q, kp, vp, jnp.asarray(table)).compile()
    return compiled_costs(compiled)["bytes_accessed"]


@pytest.mark.parametrize("page_size", [4, 8])
def test_cost_bytes_scale_with_live_pages_not_capacity(page_size):
    """The perf claim, gated analytically: bytes per decode step grow
    LINEARLY in live pages (equal increments per extra page) and are
    EXACTLY unchanged by page-table capacity — live tokens, not
    max_seq_len, set the HBM traffic."""
    b1, b2, b4 = (
        _kernel_step_bytes(n, capacity=8, page_size=page_size)
        for n in (1, 2, 4)
    )
    assert b1 < b2 < b4
    # linear: the marginal cost of one more live page is constant
    step1, step2 = b2 - b1, (b4 - b2) / 2
    assert abs(step2 - step1) <= 0.25 * step1, (b1, b2, b4)
    # capacity invariance: a 4x wider table moves nothing
    assert b2 == _kernel_step_bytes(2, capacity=32, page_size=page_size)


def test_cost_bytes_kernel_flat_where_gather_grows():
    """Same pools, same live length, growing capacity: the gather
    reference's compiled bytes grow with the table width (it always
    materializes the dense [B, P*page_size] view); the kernel's do not."""
    page_size, num_pages = 4, 129
    kp, vp = _pools(jax.random.key(10), num_pages, page_size)
    q = jax.random.normal(jax.random.key(11), (B, 1, HQ, D), jnp.float32)
    pos = jnp.full((B,), 2 * page_size - 1, jnp.int32)  # 2 live pages

    def bytes_of(fn, capacity):
        table = np.zeros((B, capacity), np.int32)
        for b in range(B):
            table[b, :capacity] = 1 + b * capacity + np.arange(capacity)
        lowered = jax.jit(fn).lower(q, kp, vp, jnp.asarray(table))
        return compiled_costs(lowered.compile())["bytes_accessed"]

    def kernel(q, kp, vp, table):
        return paged_attention(q, kp, vp, table, pos, interpret=True)

    def gather(q, kp, vp, table):
        return paged_decode_attention(q, kp, vp, table, pos)

    g8, g32 = bytes_of(gather, 8), bytes_of(gather, 32)
    k8, k32 = bytes_of(kernel, 8), bytes_of(kernel, 32)
    assert g32 > 1.5 * g8, (g8, g32)
    assert k8 == k32, (k8, k32)


def test_validation():
    page_size, ppr, num_pages = 4, 2, 9
    kp, vp = _pools(jax.random.key(12), num_pages, page_size)
    table, pos = _layout(num_pages, page_size, ppr, seed=4)
    q = jax.random.normal(jax.random.key(13), (B, 2, HQ, D), jnp.float32)
    with pytest.raises(ValueError, match="one token at a time"):
        paged_attention(q, kp, vp, table, pos, interpret=True)
    q = q[:, :1, :3]  # 3 query heads, 2 kv heads
    with pytest.raises(ValueError, match="not a multiple"):
        paged_attention(q, kp, vp, table, pos, interpret=True)
    q = jax.random.normal(jax.random.key(14), (B, 1, HQ, D), jnp.float32)
    with pytest.raises(ValueError, match="both scale pools"):
        paged_attention(
            q, kp, vp, table, pos,
            key_scale_pages=jnp.ones(kp.shape[:3]), interpret=True,
        )
