"""graftrank rule tests (GR001–GR005): one true-positive and one clean
fixture per rule, plus the pragma-regex/EOF-pragma regressions, CLI
family selection, and the repo-tree GR gate.

Like test_lint.py these lint inline source strings — no jax execution —
so the whole file is tier-1 cheap.
"""

from __future__ import annotations

import textwrap

import pytest

from cs744_pytorch_distributed_tutorial_tpu.analysis import (
    Suppressions,
    lint_source,
)
from cs744_pytorch_distributed_tutorial_tpu.analysis.cli import main as cli_main


def run_all(src: str) -> list:
    findings, _ = lint_source(textwrap.dedent(src))
    return findings


def gr_rules(src: str) -> list[str]:
    """GR-family rule ids firing on a fixture, under the FULL rule set —
    each true-positive must be flagged by exactly its intended rule."""
    return sorted(f.rule for f in run_all(src) if f.rule.startswith("GR"))


# ---------------------------------------------------------------- GR001
GR001_TP = """
    import jax

    def sync(grads, rank):
        if rank == 0:
            return jax.lax.psum(grads, "data")
        return grads
"""


def test_gr001_rank_guarded_collective():
    assert gr_rules(GR001_TP) == ["GR001"]
    (hit,) = [f for f in run_all(GR001_TP) if f.rule == "GR001"]
    assert "psum" in hit.message


def test_gr001_coordinator_guarded_store_event():
    src = """
        import os

        def note_resume(store, step):
            coordinator = int(os.environ.get("GRAFT_COORD", "0"))
            me = int(os.environ["RANK"])
            if me == coordinator:
                store.append_event("resume", step=step)
    """
    assert gr_rules(src) == ["GR001"]


def test_gr001_taint_through_helper_return():
    """process_index() forwarded through a module-local helper still
    taints the branch at the call site."""
    src = """
        import jax

        def my_rank():
            return jax.process_index()

        def sync(grads):
            if my_rank() == 0:
                return jax.lax.psum(grads, "data")
            return grads
    """
    assert gr_rules(src) == ["GR001"]


def test_gr001_clean_same_schedule_both_sides():
    src = """
        import jax

        def sync(grads, rank):
            if rank == 0:
                return jax.lax.psum(grads, "data")
            return jax.lax.psum(grads * 1.0, "data")
    """
    assert gr_rules(src) == []


def test_gr001_clean_untainted_condition():
    src = """
        import jax

        def sync(grads, warmup):
            if warmup:
                return grads
            return jax.lax.psum(grads, "data")
    """
    assert gr_rules(src) == []


# ---------------------------------------------------------------- GR002
def test_gr002_conditional_return_skips_barrier():
    src = """
        def save(store, state, generation, rank):
            if state is None:
                return None
            store.barrier_stamp(generation, rank)
            return state
    """
    assert gr_rules(src) == ["GR002"]
    (hit,) = [f for f in run_all(src) if f.rule == "GR002"]
    assert "barrier_stamp" in hit.message


def test_gr002_conditional_raise_skips_barrier():
    src = """
        def save(store, state, generation, rank):
            if not state:
                raise ValueError("empty state")
            store.barrier_stamp(generation, rank)
    """
    assert gr_rules(src) == ["GR002"]


def test_gr002_clean_barrier_dominates_exits():
    src = """
        def save(store, state, generation, rank):
            store.barrier_stamp(generation, rank)
            if state is None:
                return None
            return state
    """
    assert gr_rules(src) == []


def test_gr002_clean_exit_and_barrier_same_branch():
    src = """
        def save(store, state, generation, rank):
            if state is not None:
                store.barrier_stamp(generation, rank)
                if not state:
                    return None
                return state
            return None
    """
    # The trailing ``return None`` is AFTER the barrier line, and the
    # inner exits share the barrier's branch — no skipped edge.
    assert gr_rules(src) == []


# ---------------------------------------------------------------- GR003
def test_gr003_store_io_under_lock():
    src = """
        import threading

        _IO_LOCK = threading.Lock()

        def emit(store, payload):
            with _IO_LOCK:
                store.append_event("evt", **payload)
    """
    assert gr_rules(src) == ["GR003"]
    (hit,) = [f for f in run_all(src) if f.rule == "GR003"]
    assert "append_event" in hit.message


def test_gr003_collective_under_lock():
    src = """
        import jax
        import threading

        class Syncer:
            def __init__(self):
                self._lock = threading.Lock()

            def sync(self, grads):
                with self._lock:
                    return jax.lax.psum(grads, "data")
    """
    assert gr_rules(src) == ["GR003"]


def test_gr003_clean_io_outside_lock():
    src = """
        import threading

        _IO_LOCK = threading.Lock()

        def emit(store, payload):
            with _IO_LOCK:
                payload = dict(payload)
            store.append_event("evt", **payload)
    """
    assert gr_rules(src) == []


# ---------------------------------------------------------------- GR004
def test_gr004_wall_clock_heartbeat_age():
    src = """
        import time

        def heartbeat_age(beat):
            now = time.time()
            return now - beat["time"]
    """
    assert gr_rules(src) == ["GR004"]
    (hit,) = [f for f in run_all(src) if f.rule == "GR004"]
    assert "monotonic" in hit.message


def test_gr004_heartbeat_age_call_without_clock():
    src = """
        def sweep(store, generation, ranks):
            return [store.heartbeat_age(generation, r) for r in ranks]
    """
    assert gr_rules(src) == ["GR004"]
    (hit,) = [f for f in run_all(src) if f.rule == "GR004"]
    assert "now_mono" in hit.message


def test_gr004_clean_monotonic_math():
    src = """
        import time

        def heartbeat_age(beat, now_mono):
            return now_mono - beat["monotonic"]

        def sweep(store, generation, ranks):
            now_mono = time.monotonic()
            return [
                store.heartbeat_age(generation, r, now_mono=now_mono)
                for r in ranks
            ]
    """
    assert gr_rules(src) == []


def test_gr004_clean_wall_delta_without_age_context():
    src = """
        import time

        def profile(t0):
            return time.time() - t0
    """
    assert gr_rules(src) == []


# ---------------------------------------------------------------- GR005
GR005_TP = """
    import threading

    class Watchdog:
        def __init__(self):
            self._lock = threading.Lock()
            self._armed_at = None
            self._thread = threading.Thread(target=self._run, daemon=True)

        def _run(self):
            while True:
                with self._lock:
                    armed = self._armed_at

        def arm(self, now):
            with self._lock:
                self._armed_at = now

        def disarm(self):
            self._armed_at = None
"""


def test_gr005_unlocked_mutation_of_thread_state():
    assert gr_rules(GR005_TP) == ["GR005"]
    (hit,) = [f for f in run_all(GR005_TP) if f.rule == "GR005"]
    assert "_armed_at" in hit.message and "_lock" in hit.message


def test_gr005_clean_when_all_mutations_locked():
    src = GR005_TP.replace(
        "def disarm(self):\n            self._armed_at = None",
        "def disarm(self):\n"
        "            with self._lock:\n"
        "                self._armed_at = None",
    )
    assert src != GR005_TP  # the rewrite actually applied
    assert gr_rules(src) == []


def test_gr005_threadsafe_containers_exempt():
    src = """
        import threading

        class Beater:
            def __init__(self):
                self._lock = threading.Lock()
                self._stop = threading.Event()
                self._count = 0
                self._thread = threading.Thread(target=self._run)

            def _run(self):
                while not self._stop.is_set():
                    with self._lock:
                        self._count += 1

            def reset(self):
                self._stop = threading.Event()
    """
    # _stop is an Event (thread-safe); only lock-guarded state counts.
    assert gr_rules(src) == []


# ------------------------------------------------- pragmas and baseline
def test_suppression_regex_accepts_gr_rules():
    src = textwrap.dedent(
        """
        import jax

        def sync(grads, rank):
            if rank == 0:  # graftlint: disable=GR001 -- demo divergence
                return jax.lax.psum(grads, "data")
            return grads
        """
    )
    findings, suppressed = lint_source(src)
    assert not [f for f in findings if f.rule == "GR001"]
    assert suppressed >= 1


def test_suppression_disable_file_gr():
    src = textwrap.dedent(
        """
        # graftlint: disable-file=GR002,GR004 -- generated fixture
        import time

        def heartbeat_age(beat):
            now = time.time()
            return now - beat["time"]
        """
    )
    findings, suppressed = lint_source(src)
    assert not [f for f in findings if f.rule.startswith("GR")]
    assert suppressed >= 1


def test_mixed_family_pragma_parses():
    sup = Suppressions("x = 1  # graftlint: disable=GL001,TA003,GR005 -- mixed\n")
    assert sup.by_line.get(1) == {"GL001", "TA003", "GR005"}


def test_eof_standalone_pragma_is_file_wide():
    """A standalone pragma with no code line after it used to bind to
    nothing; it now applies file-wide."""
    src = textwrap.dedent(
        """
        def f(x=[]):
            return x

        # graftlint: disable=GL006 -- fixture keeps the shared default
        """
    )
    sup = Suppressions(src)
    assert "GL006" in sup.file_wide
    findings, suppressed = lint_source(src)
    assert not [f for f in findings if f.rule == "GL006"]
    assert suppressed == 1


def test_standalone_pragma_still_binds_forward():
    """The forward-binding behavior is unchanged when code follows."""
    src = textwrap.dedent(
        """
        # graftlint: disable=GL006 -- fixture keeps the shared default
        def f(x=[]):
            return x

        def g(y=[]):
            return y
        """
    )
    sup = Suppressions(src)
    assert not sup.file_wide
    findings, _ = lint_source(src)
    assert [f.rule for f in findings] == ["GL006"]  # only g's default


# ------------------------------------------------------------------ CLI
def test_cli_select_gr_family_prefix(tmp_path, capsys, monkeypatch):
    bad = tmp_path / "bad.py"
    bad.write_text(textwrap.dedent(GR001_TP))
    monkeypatch.chdir(tmp_path)
    assert cli_main([str(bad), "--select", "GR", "--no-baseline"]) == 1
    out = capsys.readouterr().out
    assert "GR001" in out
    # ... and the GL-only family leaves the GR finding unselected (the
    # unused-jax GL008 finding is what remains).
    assert cli_main([str(bad), "--select", "GL006", "--no-baseline"]) == 0


def test_cli_list_rules_includes_gr(capsys):
    assert cli_main(["--list-rules"]) == 0
    out = capsys.readouterr().out
    for rid in ("GR001", "GR002", "GR003", "GR004", "GR005"):
        assert rid in out


def test_cli_disable_gr_family(tmp_path, capsys, monkeypatch):
    bad = tmp_path / "bad.py"
    bad.write_text(
        textwrap.dedent(GR001_TP).replace("import jax", "import jax.lax")
    )
    monkeypatch.chdir(tmp_path)
    assert (
        cli_main([str(bad), "--select", "GR", "--disable", "GR", "--no-baseline"])
        == 0
    )


def test_cli_unknown_rule_still_usage_error(capsys):
    assert cli_main(["x.py", "--select", "GX999"]) == 2
    assert "unknown rule" in capsys.readouterr().err


# ------------------------------------------------------- repo-tree gate
def test_repo_tree_is_gr_clean():
    """The checked-in tree must stay clean under ``--select GR`` — the
    cross-rank twin of test_lint.py::test_repo_tree_is_lint_clean."""
    import pathlib

    repo = pathlib.Path(__file__).resolve().parent.parent
    if not (repo / "pyproject.toml").is_file():  # installed-package run
        pytest.skip("source tree not available")
    import os
    import subprocess
    import sys

    proc = subprocess.run(
        [
            sys.executable,
            "-m",
            "cs744_pytorch_distributed_tutorial_tpu.analysis",
            "--select",
            "GR",
        ],
        cwd=repo,
        capture_output=True,
        text=True,
        env={**os.environ, "JAX_PLATFORMS": "cpu"},
    )
    assert proc.returncode == 0, proc.stdout + proc.stderr
