"""Checkpoint/resume (capability addition — SURVEY §5.4) and the
uneven-eval-shard fix."""

import jax
import numpy as np
import pytest

from cs744_pytorch_distributed_tutorial_tpu.config import TrainConfig
from cs744_pytorch_distributed_tutorial_tpu.data import BatchLoader, synthetic_cifar10
from cs744_pytorch_distributed_tutorial_tpu.parallel import make_mesh
from cs744_pytorch_distributed_tutorial_tpu.train import Trainer


def test_checkpoint_roundtrip(tmp_path):
    from cs744_pytorch_distributed_tutorial_tpu.utils.checkpoint import Checkpointer

    mesh = make_mesh({"data": 2}, devices=jax.devices()[:2])
    cfg = TrainConfig(model="tiny_cnn", sync="allreduce", num_devices=2,
                      global_batch_size=8)
    tr = Trainer(cfg, mesh=mesh)
    state = tr.init()
    state = state.replace(step=state.step + 7)

    ckpt = Checkpointer(str(tmp_path / "ckpt"))
    ckpt.save(state)
    restored = ckpt.restore_latest(state)
    assert int(jax.device_get(restored.step)) == 7
    for a, b in zip(jax.tree.leaves(state.params), jax.tree.leaves(restored.params)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    ckpt.close()


def test_fit_saves_and_resumes(tmp_path):
    mesh = make_mesh({"data": 2}, devices=jax.devices()[:2])
    ds = synthetic_cifar10(64, 16, seed=0)
    cfg = TrainConfig(model="tiny_cnn", sync="allreduce", num_devices=2,
                      global_batch_size=16, epochs=1, synthetic_data=True,
                      checkpoint_dir=str(tmp_path / "run"))
    tr = Trainer(cfg, mesh=mesh)
    state, _ = tr.fit(dataset=ds)
    final_step = int(jax.device_get(state.step))
    assert final_step == 4  # 64/16 batches

    # A fresh trainer on the same (completed) run restores and does NOT
    # re-train the finished epochs.
    tr2 = Trainer(cfg, mesh=mesh)
    state2, _ = tr2.fit(dataset=ds)
    assert int(jax.device_get(state2.step)) == final_step

    # Extending the epoch budget resumes from the completed epoch only.
    tr3 = Trainer(cfg.replace(epochs=2), mesh=mesh)
    state3, _ = tr3.fit(dataset=ds)
    assert int(jax.device_get(state3.step)) == final_step * 2


def test_evaluate_only_restores_and_matches(tmp_path):
    """evaluate_only reproduces the training run's final eval from the
    checkpoint alone (the --eval-only CLI path)."""
    mesh = make_mesh({"data": 2}, devices=jax.devices()[:2])
    ds = synthetic_cifar10(64, 16, seed=4)
    cfg = TrainConfig(model="tiny_cnn", sync="allreduce", num_devices=2,
                      global_batch_size=16, epochs=1, synthetic_data=True,
                      checkpoint_dir=str(tmp_path / "run"))
    tr = Trainer(cfg, mesh=mesh)
    _, history = tr.fit(dataset=ds)

    tr2 = Trainer(cfg, mesh=mesh)
    metrics = tr2.evaluate_only(dataset=ds)
    assert metrics["accuracy"] == pytest.approx(
        history["eval"][-1]["accuracy"]
    )
    assert metrics["avg_loss"] == pytest.approx(
        history["eval"][-1]["avg_loss"], rel=1e-6
    )

    with pytest.raises(FileNotFoundError, match="no checkpoint"):
        Trainer(
            cfg.replace(checkpoint_dir=str(tmp_path / "empty")), mesh=mesh
        ).evaluate_only(dataset=ds)


def test_mesh_elastic_resume(tmp_path):
    """A checkpoint written on a 4-device mesh resumes on a 2-device mesh
    (and vice versa): Orbax restores into the NEW template's shardings,
    so restart recovery is not pinned to the original world size — the
    elasticity the reference's fixed [0,1,2,3] world rules out
    (master/part2a/part2a.py:32). Per-replica BN stats are the one
    world-size-shaped leaf; resizing slices/tiles them."""
    ds = synthetic_cifar10(64, 16, seed=3)
    ckpt_dir = str(tmp_path / "elastic")
    cfg4 = TrainConfig(model="tiny_cnn", sync="allreduce", num_devices=4,
                       global_batch_size=16, epochs=1, synthetic_data=True,
                       checkpoint_dir=ckpt_dir)
    tr4 = Trainer(cfg4, mesh=make_mesh({"data": 4}, devices=jax.devices()[:4]))
    state4, _ = tr4.fit(dataset=ds)
    step4 = int(jax.device_get(state4.step))

    cfg2 = cfg4.replace(num_devices=2, epochs=2)
    tr2 = Trainer(cfg2, mesh=make_mesh({"data": 2}, devices=jax.devices()[:2]))
    state2, _ = tr2.fit(dataset=ds)
    assert int(jax.device_get(state2.step)) == 2 * step4
    leaf = jax.tree.leaves(state2.batch_stats)[0]
    assert leaf.shape[0] == 2  # per-replica axis resized to the new world


def test_eval_handles_uneven_test_set():
    """Review repro: test set size not divisible by global batch or mesh;
    every example still counted exactly once (no shard-divisibility
    crash)."""
    mesh = make_mesh({"data": 8})
    ds = synthetic_cifar10(32, 10, seed=1)  # 10 test examples, batch 8, 8 devices
    cfg = TrainConfig(model="tiny_cnn", sync="allreduce", num_devices=8,
                      global_batch_size=8, epochs=1, synthetic_data=True)
    tr = Trainer(cfg, mesh=mesh)
    state, hist = tr.fit(dataset=ds)
    assert hist["eval"][-1]["count"] == 10


def test_epoch_padded_counts_each_example_once(mesh4):
    ds = synthetic_cifar10(16, 13, seed=2)
    loader = BatchLoader(ds.test_images, ds.test_labels, 8, mesh=mesh4,
                         shuffle=False, drop_last=False)
    total = 0.0
    for x, y, mask in loader.epoch_padded(0):
        assert x.shape[0] == 8  # static shapes, always
        total += float(np.asarray(mask).sum())
    assert total == 13
