"""Pallas conv3x3 wgrad kernel vs jax.vjp reference (interpret mode).

The kernel replaces XLA's conv-backprop-filter emitter for the scored
ResNet step's hottest backward ops (``ops/fused_conv.py``); these tests
pin its numerics — both strides, k-tiling, and the full custom_vjp
(dx via XLA, dw via the kernel) — against autodiff of the XLA conv.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax import lax

from cs744_pytorch_distributed_tutorial_tpu.ops.fused_conv import (

    conv3x3,
    conv3x3_wgrad,
)

# CPU-interpret Pallas conv parity: minutes of XLA compile per case.
pytestmark = pytest.mark.slow


def _ref_wgrad(x, g, stride):
    def f(w):
        return lax.conv_general_dilated(
            x, w, (stride, stride), "SAME",
            dimension_numbers=("NHWC", "HWIO", "NHWC"),
        )

    w0 = jnp.zeros((3, 3, x.shape[-1], g.shape[-1]), x.dtype)
    return jax.vjp(f, w0)[1](g)[0]


@pytest.mark.parametrize(
    "stride,b,h,c,k,bb",
    [
        (1, 8, 8, 16, 32, 2),
        (1, 4, 16, 8, 8, 2),
        (1, 6, 8, 8, 8, 3),  # batch chunk that doesn't divide evenly -> 3
        (2, 8, 8, 16, 32, 2),
        (2, 4, 16, 8, 16, 4),
    ],
)
def test_wgrad_matches_autodiff(stride, b, h, c, k, bb):
    rng = np.random.default_rng(0)
    x = jnp.asarray(rng.standard_normal((b, h, h, c)), jnp.float32)
    g = jnp.asarray(
        rng.standard_normal((b, h // stride, h // stride, k)), jnp.float32
    )
    dw = conv3x3_wgrad(x, g, stride=stride, block_batch=bb, interpret=True)
    dw_ref = _ref_wgrad(x, g, stride)
    np.testing.assert_allclose(dw, dw_ref, rtol=1e-4, atol=1e-4)


def test_custom_vjp_full_path():
    """dx rides XLA's transposed conv, dw the Pallas kernel — both must
    match plain autodiff of the XLA conv."""
    rng = np.random.default_rng(1)
    x = jnp.asarray(rng.standard_normal((4, 8, 8, 8)), jnp.float32)
    w = jnp.asarray(rng.standard_normal((3, 3, 8, 16)) * 0.1, jnp.float32)

    def loss_ours(x, w):
        return (conv3x3(x, w, 1, True) ** 2).sum()

    def loss_ref(x, w):
        y = lax.conv_general_dilated(
            x, w, (1, 1), "SAME", dimension_numbers=("NHWC", "HWIO", "NHWC")
        )
        return (y**2).sum()

    go = jax.grad(loss_ours, argnums=(0, 1))(x, w)
    gr = jax.grad(loss_ref, argnums=(0, 1))(x, w)
    np.testing.assert_allclose(go[0], gr[0], rtol=1e-4, atol=1e-4)
    np.testing.assert_allclose(go[1], gr[1], rtol=1e-4, atol=1e-4)


def test_fast_conv_resnet_grads_match():
    """ResNet-18 with fast_conv routes wide 3x3s through the kernel; the
    full model's gradients must match the nn.Conv build (same params)."""
    from cs744_pytorch_distributed_tutorial_tpu.models.resnet import resnet18

    rng = np.random.default_rng(2)
    x = jnp.asarray(rng.standard_normal((2, 32, 32, 3)), jnp.float32)
    y = jnp.asarray(rng.integers(0, 10, 2), jnp.int32)

    ref = resnet18(num_classes=10)
    fast = resnet18(num_classes=10, fast_conv=True)
    vs = ref.init(jax.random.key(0), x, train=False)

    def loss(model, p):
        import optax

        logits, _ = model.apply(
            {"params": p, "batch_stats": vs["batch_stats"]},
            x, train=True, mutable=["batch_stats"],
        )
        return optax.softmax_cross_entropy_with_integer_labels(logits, y).mean()

    # identical param trees: fast_conv preserves nn.Conv naming
    fast_vs = fast.init(jax.random.key(0), x, train=False)
    assert jax.tree.structure(vs["params"]) == jax.tree.structure(
        fast_vs["params"]
    )

    g_ref = jax.grad(lambda p: loss(ref, p))(vs["params"])
    g_fast = jax.grad(lambda p: loss(fast, p))(vs["params"])
    jax.tree.map(
        lambda a, b: np.testing.assert_allclose(a, b, rtol=2e-4, atol=2e-4),
        g_ref, g_fast,
    )


@pytest.mark.parametrize("sync", ["auto", "allreduce"])
def test_fast_conv_engine_trajectory_parity(sync, mesh4):
    """cfg.fast_conv through the REAL engine (check_vma shard_map, both
    the framework-inserted and manual sync families) must reproduce the
    nn.Conv trajectory: the custom VJP aligns its outputs' varying axes
    with the primals (psum for replicated params under 'auto', no-op for
    the pcast-varying manual strategies)."""
    from cs744_pytorch_distributed_tutorial_tpu.config import TrainConfig
    from cs744_pytorch_distributed_tutorial_tpu.data import synthetic_cifar10
    from cs744_pytorch_distributed_tutorial_tpu.parallel.mesh import (
        shard_global_batch,
    )
    from cs744_pytorch_distributed_tutorial_tpu.train import Trainer

    losses = {}
    for fast in (False, True):
        cfg = TrainConfig(
            model="resnet18", sync=sync, num_devices=4,
            global_batch_size=16, synthetic_data=True, fast_conv=fast,
        )
        tr = Trainer(cfg, mesh=mesh4)
        state = tr.init()
        ds = synthetic_cifar10(16, 8, seed=0)
        x, y = shard_global_batch(
            mesh4, ds.train_images[:16], ds.train_labels[:16]
        )
        key = jax.random.key(cfg.seed)
        run = []
        for _ in range(2):
            state, m = tr.train_step(state, x, y, key)
            run.append(float(m["loss"]))
        losses[fast] = run
    np.testing.assert_allclose(losses[True], losses[False], rtol=1e-5)
