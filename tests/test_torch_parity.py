"""Numerical parity against torch — the reference's actual substrate.

The reference trains with ``torch.optim.SGD`` and ``nn.Conv2d``/
``nn.BatchNorm2d``/``nn.CrossEntropyLoss`` (``master/part1/part1.py:94-99``,
``master/part1/model.py:11-27``). torch (CPU) is available here, so
instead of documenting "torch semantics" we verify them directly: the
optax chain, BatchNorm convention, conv geometry, and loss must
reproduce torch's numbers on the same inputs.
"""

import numpy as np
import pytest

torch = pytest.importorskip("torch")

import jax  # noqa: E402
import jax.numpy as jnp  # noqa: E402
import optax  # noqa: E402

from cs744_pytorch_distributed_tutorial_tpu.config import TrainConfig  # noqa: E402
from cs744_pytorch_distributed_tutorial_tpu.train.state import make_optimizer  # noqa: E402


def test_sgd_update_rule_matches_torch():
    """Our optax chain (add_decayed_weights -> trace -> scale) must trace
    torch.optim.SGD(lr, momentum, weight_decay)'s parameter trajectory
    bit-for-bit-close over many steps — the reference's exact recipe
    (``master/part1/part1.py:98-99``)."""
    rng = np.random.default_rng(0)
    p0 = rng.standard_normal((7, 5)).astype(np.float32)
    grads = [rng.standard_normal((7, 5)).astype(np.float32) for _ in range(10)]

    # torch side
    tp = torch.nn.Parameter(torch.tensor(p0.copy()))
    opt = torch.optim.SGD([tp], lr=0.1, momentum=0.9, weight_decay=1e-4)
    for g in grads:
        opt.zero_grad()
        tp.grad = torch.tensor(g)
        opt.step()

    # our side
    cfg = TrainConfig(learning_rate=0.1, momentum=0.9, weight_decay=1e-4)
    tx = make_optimizer(cfg)
    params = {"w": jnp.asarray(p0)}
    opt_state = tx.init(params)
    for g in grads:
        updates, opt_state = tx.update({"w": jnp.asarray(g)}, opt_state, params)
        params = optax.apply_updates(params, updates)

    np.testing.assert_allclose(
        np.asarray(params["w"]), tp.detach().numpy(), rtol=1e-5, atol=1e-6
    )


def test_batchnorm_convention_matches_torch():
    """flax BatchNorm(momentum=0.9) == torch BatchNorm2d(momentum=0.1):
    same normalized output in train mode, same running mean. The ONE
    documented divergence: torch Bessel-corrects the variance it stores
    in running stats (n/(n-1)) while flax stores the biased batch
    variance — an O(1/n) eval-mode difference (n = 256*64 per channel at
    the reference's batch size; negligible but real, and pinned here)."""
    rng = np.random.default_rng(1)
    x = rng.standard_normal((4, 8, 8, 3)).astype(np.float32)  # NHWC
    n = 4 * 8 * 8  # elements per channel in a batch statistic

    tbn = torch.nn.BatchNorm2d(3, momentum=0.1, eps=1e-5)
    tbn.train()
    ty = tbn(torch.tensor(x.transpose(0, 3, 1, 2)))  # NCHW

    import flax.linen as nn

    fbn = nn.BatchNorm(use_running_average=False, momentum=0.9, epsilon=1e-5)
    variables = fbn.init(jax.random.key(0), x)
    fy, mut = fbn.apply(variables, x, mutable=["batch_stats"])

    np.testing.assert_allclose(
        np.asarray(fy), ty.detach().numpy().transpose(0, 2, 3, 1),
        rtol=1e-4, atol=1e-5,
    )
    np.testing.assert_allclose(
        np.asarray(mut["batch_stats"]["mean"]),
        tbn.running_mean.numpy(),
        rtol=1e-4,
        atol=1e-6,
    )
    # running_var: flax stored 0.9*1 + 0.1*biased_var; torch stored
    # 0.9*1 + 0.1*biased_var*(n/(n-1)). Undo the Bessel factor and match.
    flax_rv = np.asarray(mut["batch_stats"]["var"])
    torch_rv_debesseled = 0.9 + (tbn.running_var.numpy() - 0.9) * (n - 1) / n
    np.testing.assert_allclose(flax_rv, torch_rv_debesseled, rtol=1e-4, atol=1e-5)


def test_conv_geometry_matches_torch():
    """nn.Conv(3x3, SAME) == torch Conv2d(3x3, padding=1) — the reference's
    conv block geometry (``master/part1/model.py:19``) — on shared weights."""
    rng = np.random.default_rng(2)
    x = rng.standard_normal((2, 8, 8, 3)).astype(np.float32)
    w = rng.standard_normal((3, 3, 3, 4)).astype(np.float32) * 0.1  # HWIO
    b = rng.standard_normal((4,)).astype(np.float32) * 0.1

    tconv = torch.nn.Conv2d(3, 4, 3, padding=1)
    with torch.no_grad():
        # HWIO -> OIHW
        tconv.weight.copy_(torch.tensor(w.transpose(3, 2, 0, 1)))
        tconv.bias.copy_(torch.tensor(b))
    ty = tconv(torch.tensor(x.transpose(0, 3, 1, 2)))

    import flax.linen as nn

    conv = nn.Conv(4, (3, 3), padding="SAME", use_bias=True)
    fy = conv.apply({"params": {"kernel": jnp.asarray(w), "bias": jnp.asarray(b)}}, x)

    np.testing.assert_allclose(
        np.asarray(fy), ty.detach().numpy().transpose(0, 2, 3, 1),
        rtol=1e-4, atol=1e-5,
    )


def test_cross_entropy_matches_torch():
    """optax softmax CE with integer labels == torch CrossEntropyLoss
    (``master/part1/part1.py:94``)."""
    rng = np.random.default_rng(3)
    logits = rng.standard_normal((16, 10)).astype(np.float32)
    labels = rng.integers(0, 10, 16)

    tl = torch.nn.CrossEntropyLoss()(
        torch.tensor(logits), torch.tensor(labels, dtype=torch.long)
    )
    ol = optax.softmax_cross_entropy_with_integer_labels(
        jnp.asarray(logits), jnp.asarray(labels)
    ).mean()
    np.testing.assert_allclose(float(ol), float(tl), rtol=1e-6)


def test_label_smoothing_matches_torch():
    """_smoothed_xent == torch CrossEntropyLoss(label_smoothing=s)."""
    from cs744_pytorch_distributed_tutorial_tpu.train.engine import _smoothed_xent

    rng = np.random.default_rng(11)
    logits = rng.standard_normal((16, 10)).astype(np.float32)
    labels = rng.integers(0, 10, 16)
    for s in (0.0, 0.1, 0.3):
        tl = torch.nn.CrossEntropyLoss(label_smoothing=s)(
            torch.tensor(logits), torch.tensor(labels, dtype=torch.long)
        )
        ol = _smoothed_xent(jnp.asarray(logits), jnp.asarray(labels), s)
        np.testing.assert_allclose(float(ol), float(tl), rtol=1e-5)


def test_attention_matches_torch_sdpa():
    """Our dense causal attention == torch's canonical
    scaled_dot_product_attention(is_causal=True) on shared projection
    weights — pins the scale (1/sqrt(head_dim)), masking, and head
    reshape conventions of the LM family."""
    import torch.nn.functional as F

    from cs744_pytorch_distributed_tutorial_tpu.models.transformer import Attention

    b, t, d_model, heads = 2, 10, 32, 4
    head_dim = d_model // heads
    rng = np.random.default_rng(4)
    x = rng.standard_normal((b, t, d_model)).astype(np.float32)
    wq, wk, wv, wo = (
        (rng.standard_normal((d_model, d_model)).astype(np.float32) * 0.1)
        for _ in range(4)
    )

    attn = Attention(num_heads=heads, impl="dense", causal=True)
    params = {
        "q": {"kernel": jnp.asarray(wq)},
        "k": {"kernel": jnp.asarray(wk)},
        "v": {"kernel": jnp.asarray(wv)},
        "attn_out": {"kernel": jnp.asarray(wo)},
    }
    ours = attn.apply({"params": params}, jnp.asarray(x))

    tx = torch.tensor(x)
    # y = x @ W (flax Dense kernel convention), heads split like ours:
    # [B, T, H, Dh] -> SDPA wants [B, H, T, Dh].
    tq, tk, tv = (
        (tx @ torch.tensor(w)).reshape(b, t, heads, head_dim).transpose(1, 2)
        for w in (wq, wk, wv)
    )
    tout = F.scaled_dot_product_attention(tq, tk, tv, is_causal=True)
    tout = tout.transpose(1, 2).reshape(b, t, d_model) @ torch.tensor(wo)

    np.testing.assert_allclose(
        np.asarray(ours), tout.numpy(), rtol=1e-4, atol=1e-5
    )


def test_layernorm_and_gelu_match_torch():
    """flax LayerNorm == torch LayerNorm on shared gamma/beta, and the
    Block's GELU is the tanh approximation (flax nn.gelu's default) — the
    convention pinned so a torch port knows which variant to use."""
    import flax.linen as nn
    import torch.nn.functional as F

    rng = np.random.default_rng(5)
    x = rng.standard_normal((4, 16)).astype(np.float32)
    gamma = rng.standard_normal(16).astype(np.float32)
    beta = rng.standard_normal(16).astype(np.float32)

    fy = nn.LayerNorm().apply(
        {"params": {"scale": jnp.asarray(gamma), "bias": jnp.asarray(beta)}},
        jnp.asarray(x),
    )
    ty = F.layer_norm(
        torch.tensor(x), (16,), torch.tensor(gamma), torch.tensor(beta)
    )
    np.testing.assert_allclose(np.asarray(fy), ty.numpy(), rtol=1e-4, atol=1e-5)

    np.testing.assert_allclose(
        np.asarray(nn.gelu(jnp.asarray(x))),
        F.gelu(torch.tensor(x), approximate="tanh").numpy(),
        rtol=1e-4,
        atol=1e-6,
    )


def test_transformer_block_matches_torch_reimplementation():
    """The full pre-LN block (ln1 -> attn -> residual -> ln2 -> MLP ->
    residual) re-built op-by-op in torch from OUR trained params must
    reproduce our forward — pins the residual wiring, not just the leaf
    ops."""
    import torch.nn.functional as F

    from cs744_pytorch_distributed_tutorial_tpu.models.transformer import Block

    b, t, d_model, heads, d_ff = 2, 8, 16, 2, 48
    rng = np.random.default_rng(6)
    x = rng.standard_normal((b, t, d_model)).astype(np.float32)

    block = Block(num_heads=heads, d_ff=d_ff, impl="dense", causal=True)
    variables = block.init(jax.random.key(1), jnp.asarray(x))
    ours = np.asarray(block.apply(variables, jnp.asarray(x)))

    p = jax.tree.map(lambda a: torch.tensor(np.asarray(a)), variables["params"])
    tx_in = torch.tensor(x)

    def t_ln(v, ln):
        return F.layer_norm(v, (v.shape[-1],), ln["scale"], ln["bias"])

    h = t_ln(tx_in, p["ln1"])
    head_dim = d_model // heads
    tq, tk, tv = (
        (h @ p["attn"][k]["kernel"]).reshape(b, t, heads, head_dim).transpose(1, 2)
        for k in ("q", "k", "v")
    )
    a = F.scaled_dot_product_attention(tq, tk, tv, is_causal=True)
    a = a.transpose(1, 2).reshape(b, t, d_model) @ p["attn"]["attn_out"]["kernel"]
    mid = tx_in + a
    h = t_ln(mid, p["ln2"])
    h = h @ p["mlp_in"]["kernel"] + p["mlp_in"]["bias"]
    h = F.gelu(h, approximate="tanh")
    h = h @ p["mlp_out"]["kernel"]
    out = mid + h + p["mlp_out_bias"]

    # Tolerance sized to float32 matmul accumulation-order drift between
    # XLA and torch's CPU GEMMs (observed worst case: 1/256 elements at
    # max abs 1.94e-5, max rel 5.7e-4 — one ULP-cascade past the leaf-op
    # tolerances above; the residual wiring this test pins is insensitive
    # to it).
    np.testing.assert_allclose(ours, out.numpy(), rtol=1e-3, atol=3e-5)


def test_vgg11_param_count_matches_torch_reference_shape():
    """Our VGG-11 must have exactly the reference architecture's parameter
    count: 8 convs per the _cfg table + Linear(512, 10) head + BN
    scale/bias pairs (``master/part1/model.py:3-8,39-40``)."""
    from cs744_pytorch_distributed_tutorial_tpu.models import get_model

    model = get_model("vgg11", num_classes=10)
    variables = model.init(
        jax.random.key(0), jnp.zeros((1, 32, 32, 3), jnp.float32), train=False
    )
    n_params = sum(p.size for p in jax.tree.leaves(variables["params"]))

    # the same table built in torch
    cfg = (64, "M", 128, "M", 256, 256, "M", 512, 512, "M", 512, 512, "M")
    layers, c_in = [], 3
    for entry in cfg:
        if entry == "M":
            layers.append(torch.nn.MaxPool2d(2, 2))
        else:
            layers += [
                torch.nn.Conv2d(c_in, entry, 3, padding=1, bias=True),
                torch.nn.BatchNorm2d(entry),
                torch.nn.ReLU(inplace=True),
            ]
            c_in = entry
    tmodel = torch.nn.Sequential(*layers, torch.nn.Flatten(),
                                 torch.nn.Linear(512, 10))
    t_params = sum(p.numel() for p in tmodel.parameters())
    assert n_params == t_params


def _torch_vgg11():
    """The reference architecture in torch (built from the published
    table, as above)."""
    cfg = (64, "M", 128, "M", 256, 256, "M", 512, 512, "M", 512, 512, "M")
    layers, c_in = [], 3
    for entry in cfg:
        if entry == "M":
            layers.append(torch.nn.MaxPool2d(2, 2))
        else:
            layers += [
                torch.nn.Conv2d(c_in, entry, 3, padding=1, bias=True),
                torch.nn.BatchNorm2d(entry),
                torch.nn.ReLU(inplace=True),
            ]
            c_in = entry
    return torch.nn.Sequential(
        *layers, torch.nn.Flatten(), torch.nn.Linear(512, 10)
    )


def _copy_flax_vgg_params_to_torch(params, tmodel):
    """Load the flax init into the torch model: conv kernels HWIO->OIHW,
    dense [in,out] -> [out,in]; BN scale/bias by order."""
    convs = [m for m in tmodel if isinstance(m, torch.nn.Conv2d)]
    bns = [m for m in tmodel if isinstance(m, torch.nn.BatchNorm2d)]
    linear = [m for m in tmodel if isinstance(m, torch.nn.Linear)][0]
    with torch.no_grad():
        for i, conv in enumerate(convs):
            p = params[f"Conv_{i}"]
            conv.weight.copy_(
                torch.from_numpy(
                    np.asarray(p["kernel"]).transpose(3, 2, 0, 1).copy()
                )
            )
            conv.bias.copy_(torch.from_numpy(np.asarray(p["bias"])))
        for i, bn in enumerate(bns):
            p = params[f"BatchNorm_{i}"]
            bn.weight.copy_(torch.from_numpy(np.asarray(p["scale"])))
            bn.bias.copy_(torch.from_numpy(np.asarray(p["bias"])))
        d = params["Dense_0"]
        linear.weight.copy_(
            torch.from_numpy(np.asarray(d["kernel"]).T.copy())
        )
        linear.bias.copy_(torch.from_numpy(np.asarray(d["bias"])))


@pytest.mark.slow
def test_vgg11_loss_curve_matches_torch_trajectory(mesh4):
    """SURVEY §4's north star: loss-curve parity against the reference's
    ACTUAL torch trajectory, not just a self-recorded golden trace.

    Same init (flax params copied into torch), same data (deterministic
    normalized batches, augmentation off on both sides), same math
    (SGD 0.1/0.9/1e-4 + CE — ``master/part3/part3.py:24-48``'s loop):
    the two frameworks' per-step losses must track. The comparison runs
    the engine's single-replica semantics (part1 ==
    world-size-1 part3: ``DDP(model)`` with one rank is the bare
    model); the strategy-parity suite (test_sync_parity.py) separately
    pins part2a/2a_extra/2b/3 gradients equal to this path, closing the
    chain to every reference part."""
    from cs744_pytorch_distributed_tutorial_tpu.config import TrainConfig
    from cs744_pytorch_distributed_tutorial_tpu.data import synthetic_cifar10
    from cs744_pytorch_distributed_tutorial_tpu.data.augment import (
        CIFAR10_MEAN,
        CIFAR10_STD,
    )
    from cs744_pytorch_distributed_tutorial_tpu.parallel import make_mesh
    from cs744_pytorch_distributed_tutorial_tpu.parallel.mesh import (
        shard_global_batch,
    )
    from cs744_pytorch_distributed_tutorial_tpu.train import Trainer

    steps, batch = 10, 32
    # The reference's lr=0.1 at this small comparison batch is a chaotic
    # regime (losses spike past 20 before descending): infinitesimal
    # framework differences amplify exponentially and no tolerance is
    # meaningful. The parity claim is about the MATH (same init, data,
    # update rule), so the comparison runs the same recipe at a stable
    # lr; the reference's own operating point (batch 256, lr 0.1) is the
    # on-chip golden run (benchmarks/vgg11_golden.json).
    lr = 0.02
    ds = synthetic_cifar10(steps * batch, 8, seed=0)

    # ---- JAX side: the engine on a 1-device mesh (part1 semantics so
    # BatchNorm sees the same batch on both sides), augmentation off.
    mesh1 = make_mesh({"data": 1}, devices=jax.devices()[:1])
    cfg = TrainConfig(
        model="vgg11", sync="none", num_devices=1, global_batch_size=batch,
        synthetic_data=True, augment=False, learning_rate=lr,
    )
    tr = Trainer(cfg, mesh=mesh1)
    state = tr.init()
    key = jax.random.key(cfg.seed)
    jax_losses = []
    for s in range(steps):
        xb, yb = shard_global_batch(
            mesh1,
            ds.train_images[s * batch : (s + 1) * batch],
            ds.train_labels[s * batch : (s + 1) * batch],
        )
        state, metrics = tr.train_step(state, xb, yb, key)
        jax_losses.append(float(metrics["loss"]))

    # ---- torch side: same init, same normalized batches, same recipe.
    tmodel = _torch_vgg11()
    variables = tr.model.init(
        jax.random.key(cfg.seed), jnp.zeros((1, 32, 32, 3)), train=False
    )
    _copy_flax_vgg_params_to_torch(variables["params"], tmodel)
    # the engine's init used the same seed, so state.params == variables'
    opt = torch.optim.SGD(
        tmodel.parameters(), lr=cfg.learning_rate,
        momentum=cfg.momentum, weight_decay=cfg.weight_decay,
    )
    criterion = torch.nn.CrossEntropyLoss()
    mean = np.asarray(CIFAR10_MEAN, np.float32)
    std = np.asarray(CIFAR10_STD, np.float32)
    tmodel.train()
    torch_losses = []
    for s in range(steps):
        imgs = ds.train_images[s * batch : (s + 1) * batch]
        x = (imgs.astype(np.float32) / 255.0 - mean) / std
        xt = torch.from_numpy(x.transpose(0, 3, 1, 2).copy())
        yt = torch.from_numpy(
            ds.train_labels[s * batch : (s + 1) * batch].astype(np.int64)
        )
        opt.zero_grad()
        loss = criterion(tmodel(xt), yt)
        loss.backward()
        opt.step()
        torch_losses.append(float(loss.detach()))

    # Step-0 loss is a pure forward over identical params/data: tight.
    assert abs(jax_losses[0] - torch_losses[0]) / torch_losses[0] < 1e-3, (
        jax_losses[0], torch_losses[0],
    )
    # The curves must track through the descent phase (curve-shape
    # tolerance: SURVEY §7 hard part d — bitwise parity is not
    # meaningful across frameworks). Once the loss memorizes below 0.1,
    # run-to-run noise (torch's threaded CPU backward is not
    # deterministic) dominates the relative comparison, so those steps
    # assert only the shared destination below.
    compared = 0
    for j, t in zip(jax_losses, torch_losses):
        if t >= 0.1:
            assert abs(j - t) / t < 0.04, (jax_losses, torch_losses)
            compared += 1
    # How many steps stay above 0.1 depends on how fast the tiny subset
    # memorizes (torch's nondeterministic threaded backward can push the
    # loss under 0.1 a step or two earlier run-to-run); two tracked
    # descent steps plus the tight step-0 check above still pin the
    # trajectory.
    assert compared >= 2, (jax_losses, torch_losses)
    # and both must actually converge to the same tiny-loss regime
    assert jax_losses[-1] < 0.1 and torch_losses[-1] < 0.1, (
        jax_losses, torch_losses,
    )
