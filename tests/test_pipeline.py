"""Pipeline parallelism: schedule correctness, forward/grad parity, training.

The parity oracle is the unpipelined single-device forward on the SAME
global parameters — the property the reference could only establish by
seed + eyeball across its four parts (SURVEY §4) is here a bit-level
comparison between the pipelined and sequential executions.
"""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from cs744_pytorch_distributed_tutorial_tpu.parallel import make_mesh
from cs744_pytorch_distributed_tutorial_tpu.parallel.pipeline import (
    DATA_AXIS,
    PIPE_AXIS,
    PipelineLMConfig,
    PipelineLMTrainer,
    spmd_pipeline,
)


def make_trainer(data=1, pipe=4, layers=4, microbatches=2, batch=8, **kw):
    cfg = PipelineLMConfig(
        vocab_size=kw.pop("vocab_size", 64),
        num_layers=layers,
        num_heads=4,
        d_model=kw.pop("d_model", 32),
        d_ff=64,
        max_seq_len=64,
        data_parallel=data,
        pipeline_parallel=pipe,
        num_microbatches=microbatches,
        global_batch_size=batch,
        seq_len=16,
        **kw,
    )
    mesh = make_mesh(
        {DATA_AXIS: data, PIPE_AXIS: pipe}, devices=jax.devices()[: data * pipe]
    )
    return PipelineLMTrainer(cfg, mesh=mesh)


def tokens_for(cfg, n=None, seed=0):
    rng = np.random.default_rng(seed)
    n = cfg.global_batch_size if n is None else n
    return rng.integers(0, cfg.vocab_size, (n, cfg.seq_len + 1), dtype=np.int64)


def test_spmd_pipeline_identity_stage():
    """With identity-plus-constant stages, the schedule must deliver each
    microbatch through all S stages exactly once: out = in + S."""
    mesh = make_mesh({PIPE_AXIS: 4}, devices=jax.devices()[:4])
    m = 3
    x = jnp.arange(m * 8, dtype=jnp.float32).reshape(m, 8)

    from jax.sharding import PartitionSpec as P

    def run(mb):
        return spmd_pipeline(
            lambda _, h: h + 1.0,
            jnp.zeros((1,)),  # unused stage params
            mb,
            axis_name=PIPE_AXIS,
            num_stages=4,
            num_microbatches=m,
        )

    out = jax.jit(
        jax.shard_map(
            run, mesh=mesh, in_specs=P(), out_specs=P(), check_vma=False
        )
    )(x)
    np.testing.assert_allclose(np.asarray(out), np.asarray(x) + 4.0)


@pytest.mark.slow
def test_forward_parity_vs_sequential():
    """Pipelined forward over 4 stages == unpipelined forward, same params."""
    tr = make_trainer(data=1, pipe=4, layers=4, microbatches=4)
    params_global = tr._init_host(0)
    params, _ = tr.init(0)
    toks = tokens_for(tr.cfg)
    x = jnp.asarray(toks[:, :-1])
    got = np.asarray(tr.forward_fn(params, x))
    want = np.asarray(tr.reference_forward(params_global, x))
    np.testing.assert_allclose(got, want, atol=2e-4, rtol=2e-4)


def test_forward_invariant_to_microbatch_count():
    """Microbatching is a schedule choice, not a numerics choice."""
    outs = []
    for m in (1, 2, 4):
        tr = make_trainer(data=1, pipe=2, layers=4, microbatches=m)
        params, _ = tr.init(0)
        toks = tokens_for(tr.cfg)
        outs.append(np.asarray(tr.forward_fn(params, jnp.asarray(toks[:, :-1]))))
    np.testing.assert_allclose(outs[0], outs[1], atol=1e-5)
    np.testing.assert_allclose(outs[0], outs[2], atol=1e-5)


@pytest.mark.slow
def test_grad_parity_vs_sequential():
    """One pipelined train-step gradient == the sequential model's gradient
    (the AD-derived reverse pipeline is exact, not approximate)."""
    tr = make_trainer(data=1, pipe=4, layers=4, microbatches=2)
    params_global = tr._init_host(0)
    toks = tokens_for(tr.cfg)
    x, y = jnp.asarray(toks[:, :-1]), jnp.asarray(toks[:, 1:])

    def ref_loss(p):
        logits = tr.reference_forward(p, x)
        return optax.softmax_cross_entropy_with_integer_labels(logits, y).mean()

    want = jax.grad(ref_loss)(params_global)

    params, opt_state = tr.init(0)
    xg, yg = tr.shard_batch(toks)

    # Grab per-stage grads through a shard_map identical to the train
    # step's loss (stage-sharded block grads come back as the global
    # stacked tree via the out_specs).
    from jax.sharding import PartitionSpec as P

    def step_grads(p, tokens, targets):
        def loss_fn(pp):
            b, t = tokens.shape
            cfg = tr.cfg
            import cs744_pytorch_distributed_tutorial_tpu.parallel.pipeline as pl

            xx = tr._embed(pp, tokens)
            mb = xx.reshape(cfg.num_microbatches, b // cfg.num_microbatches, t, cfg.d_model)
            out = pl.spmd_pipeline(
                tr._stage_fn(),
                pp["blocks"],
                mb,
                axis_name=PIPE_AXIS,
                num_stages=tr.pipe_size,
                num_microbatches=cfg.num_microbatches,
            )
            logits = tr._tail(pp, out.reshape(b, t, cfg.d_model))
            return optax.softmax_cross_entropy_with_integer_labels(
                logits, targets
            ).mean()

        grads = jax.grad(loss_fn)(p)
        # The trainer's sync path, verbatim: data-average everything,
        # pipe-average replicated leaves (must be a no-op if the pipeline's
        # f-boundary replicates upstream grads correctly — this is what
        # catches a stage-0-only embed/pos gradient).
        def sync(g, spec):
            g = jax.lax.pmean(g, DATA_AXIS)
            if PIPE_AXIS not in spec:
                g = jax.lax.pmean(g, PIPE_AXIS)
            return g

        return jax.tree.map(sync, grads, tr.param_specs)

    grads = jax.jit(
        jax.shard_map(
            step_grads,
            mesh=tr.mesh,
            in_specs=(tr.param_specs, P(DATA_AXIS), P(DATA_AXIS)),
            out_specs=tr.param_specs,
            check_vma=False,
        )
    )(params, xg, yg)

    for path, g_want in jax.tree_util.tree_flatten_with_path(want)[0]:
        g_got = grads
        for k in path:
            g_got = g_got[k.key]
        np.testing.assert_allclose(
            np.asarray(g_got), np.asarray(g_want), atol=5e-4, rtol=5e-3,
            err_msg=f"grad mismatch at {path}",
        )


def test_training_reduces_loss_dp_x_pp():
    """2-way data x 4-way pipe end-to-end training makes progress."""
    tr = make_trainer(
        data=2, pipe=4, layers=4, microbatches=2, batch=16, learning_rate=3e-3
    )
    rng = np.random.default_rng(1)
    # Learnable structure: next token = (token + 1) mod vocab.
    start = rng.integers(0, tr.cfg.vocab_size, (64, 1))
    ramp = (start + np.arange(tr.cfg.seq_len + 1)) % tr.cfg.vocab_size
    _, _, losses = tr.fit(ramp.astype(np.int64), steps=50)
    assert losses[-1] < losses[0] * 0.7, losses


def test_config_validation():
    with pytest.raises(ValueError, match="num_layers"):
        make_trainer(pipe=4, layers=6)
    with pytest.raises(ValueError, match="microbatches"):
        make_trainer(data=2, pipe=2, batch=8, microbatches=3)
    with pytest.raises(ValueError, match="attention_impl"):
        make_trainer(attention_impl="ring")


@pytest.mark.slow
def test_pipeline_flash_attention_matches_dense():
    """attention_impl='flash' routes pipeline blocks through the Pallas
    kernel (interpret on CPU): same first-step loss as dense."""
    losses = {}
    for impl in ("dense", "flash"):
        tr = make_trainer(attention_impl=impl)
        toks = tokens_for(tr.cfg)
        _, _, l = tr.fit(toks, steps=1)
        losses[impl] = l[0]
    assert losses["flash"] == pytest.approx(losses["dense"], rel=1e-5)


def test_block_param_names_in_sync():
    from cs744_pytorch_distributed_tutorial_tpu.parallel.pipeline import (
        BLOCK_PARAM_NAMES,
        init_block_params,
    )

    assert set(init_block_params(jax.random.key(0), 8, 8)) == set(BLOCK_PARAM_NAMES)


# ---------------------------------------------------------------------------
# First-class promotion (round 3): real Block, cross-engine parity,
# tensor axis, checkpoint/resume, eval
# ---------------------------------------------------------------------------
@pytest.mark.slow
def test_cross_engine_parity_with_lm_trainer():
    """The pipeline runs the SAME flax Block as LMTrainer: converting a
    TransformerLM init through from_transformer_lm_params and running it
    pipelined must reproduce the LM engine's logits (float-tolerance —
    only summation order differs)."""
    from jax.sharding import NamedSharding, PartitionSpec as P
    from cs744_pytorch_distributed_tutorial_tpu.parallel.mesh import (
        host_to_global,
    )
    from cs744_pytorch_distributed_tutorial_tpu.parallel.pipeline import (
        from_transformer_lm_params,
    )
    from cs744_pytorch_distributed_tutorial_tpu.train.lm import (
        LMConfig,
        LMTrainer,
    )

    kw = dict(
        vocab_size=64, num_layers=4, num_heads=4, d_model=32, d_ff=64,
        max_seq_len=64, global_batch_size=8, seq_len=16,
    )
    lm_mesh = make_mesh(
        {"data": 1, "seq": 1, "tensor": 1}, devices=jax.devices()[:1]
    )
    lm = LMTrainer(LMConfig(attention_impl="dense", **kw), mesh=lm_mesh)
    lm_params, _ = lm.init(7)
    lm_host = jax.device_get(lm_params)

    tr = make_trainer(data=2, pipe=2, layers=4, microbatches=2, **{})
    conv = from_transformer_lm_params(lm_host, 4)
    pp_params = jax.tree.map(
        lambda x, s: host_to_global(
            jnp.asarray(x), NamedSharding(tr.mesh, s)
        ),
        conv,
        tr.param_specs,
    )
    toks = tokens_for(tr.cfg)
    x = jnp.asarray(toks[:, :-1])
    want = np.asarray(
        lm.model.apply(
            {"params": lm_params},
            jax.device_put(x, NamedSharding(lm_mesh, P())),
        )
    )
    got = np.asarray(tr.forward_fn(pp_params, x))
    np.testing.assert_allclose(got, want, atol=1e-5, rtol=1e-5)


@pytest.mark.slow
def test_dp_pp_tp_training(mesh8):
    """data x pipe x tensor on one mesh: the tensor axis shards each
    stage's q/k/v/mlp kernels (Megatron boundaries inside Block) and the
    loss matches the tensor=1 run to float tolerance."""
    from cs744_pytorch_distributed_tutorial_tpu.parallel.pipeline import (
        TENSOR_AXIS,
    )

    losses = {}
    for tensor in (1, 2):
        axes = {DATA_AXIS: 2, PIPE_AXIS: 2}
        if tensor > 1:
            axes[TENSOR_AXIS] = tensor
        cfg = PipelineLMConfig(
            vocab_size=64, num_layers=4, num_heads=4, d_model=32, d_ff=64,
            max_seq_len=64, data_parallel=2, pipeline_parallel=2,
            tensor_parallel=tensor, num_microbatches=2,
            global_batch_size=8, seq_len=16,
        )
        mesh = make_mesh(axes, devices=jax.devices()[: 4 * tensor])
        tr = PipelineLMTrainer(cfg, mesh=mesh)
        params, opt = tr.init(0)
        toks = tokens_for(cfg)
        x, y = tr.shard_batch(toks)
        for _ in range(2):
            params, opt, m = tr.train_step(params, opt, x, y)
        losses[tensor] = float(m["loss"])
    np.testing.assert_allclose(losses[2], losses[1], rtol=1e-5)


def test_vocab_sharded_head_logits_and_ce(mesh8):
    """Under tensor parallelism the LM head is vocab-sharded (the 1F1B
    per-wave tail divider): forward_fn must still assemble the exact
    full-vocab logits, and the sharded-vocab CE must equal optax's."""
    import optax
    from cs744_pytorch_distributed_tutorial_tpu.parallel.pipeline import (
        TENSOR_AXIS,
    )

    cfg = PipelineLMConfig(
        vocab_size=64, num_layers=4, num_heads=4, d_model=32, d_ff=64,
        max_seq_len=64, data_parallel=2, pipeline_parallel=2,
        tensor_parallel=2, num_microbatches=2,
        global_batch_size=8, seq_len=16, schedule="1f1b",
    )
    mesh = make_mesh(
        {DATA_AXIS: 2, PIPE_AXIS: 2, TENSOR_AXIS: 2},
        devices=jax.devices()[:8],
    )
    tr = PipelineLMTrainer(cfg, mesh=mesh)
    assert TENSOR_AXIS in tr.param_specs["head"]
    params_global = tr._init_host(0)
    params, _ = tr.init(0)
    toks = tokens_for(cfg)
    x = jnp.asarray(toks[:, :-1])
    got = np.asarray(tr.forward_fn(params, x))  # reassembled [B, T, V]
    want = np.asarray(tr.reference_forward(params_global, x))
    np.testing.assert_allclose(got, want, atol=2e-4, rtol=2e-4)

    # eval CE through _sharded_ce == full-vocab optax CE on the same
    # logits.
    y = jnp.asarray(toks[:, 1:])
    ev = float(tr.eval_step(params, *tr.shard_batch(toks))["loss"])
    ref = float(
        optax.softmax_cross_entropy_with_integer_labels(
            jnp.asarray(want), y
        ).mean()
    )
    np.testing.assert_allclose(ev, ref, rtol=1e-5)


@pytest.mark.slow
def test_pipeline_rope_gqa_flash_remat_1f1b():
    """The promoted feature set composes: RoPE + GQA + flash + remat on
    the 1F1B schedule trains and matches its own gpipe twin."""
    losses = {}
    for schedule in ("gpipe", "1f1b"):
        tr = make_trainer(
            data=2, pipe=2, layers=4, microbatches=2, batch=8,
            schedule=schedule, use_rope=True, num_kv_heads=2,
            attention_impl="flash", remat=True, remat_policy="dots",
        )
        toks = tokens_for(tr.cfg)
        x, y = tr.shard_batch(toks)
        params, opt = tr.init(0)
        params, opt, m = tr.train_step(params, opt, x, y)
        losses[schedule] = float(m["loss"])
    assert losses["1f1b"] == pytest.approx(losses["gpipe"], rel=1e-5)


@pytest.mark.slow
def test_pipeline_moe_expert_parallel():
    """ep x pp: MoE blocks with experts sharded over the data axis
    (all-to-all dispatch inside the stage function) train through BOTH
    pipeline schedules, and the hand-scheduled 1F1B backward through the
    all_to_all produces the same loss and updated params as AD of the
    GPipe forward — the riskiest composition this promotion enables."""
    results = {}
    for schedule in ("gpipe", "1f1b"):
        tr = make_trainer(
            data=2, pipe=2, layers=4, microbatches=2, batch=8,
            moe_experts=4, moe_expert_parallel=True, schedule=schedule,
        )
        toks = tokens_for(tr.cfg)
        x, y = tr.shard_batch(toks)
        params, opt = tr.init(0)
        losses = []
        for _ in range(3):
            params, opt, m = tr.train_step(params, opt, x, y)
            losses.append(float(m["loss"]))
        assert all(np.isfinite(l) for l in losses)
        assert losses[-1] < losses[0]
        results[schedule] = (losses, params)
    np.testing.assert_allclose(
        results["1f1b"][0], results["gpipe"][0], rtol=1e-5
    )
    jax.tree.map(
        lambda a, b: np.testing.assert_allclose(
            jax.device_get(a), jax.device_get(b), rtol=5e-4, atol=1e-6
        ),
        results["1f1b"][1], results["gpipe"][1],
    )


@pytest.mark.slow
def test_pipeline_optimizer_registry():
    """The shared train/state.py registry drives the pipeline engine:
    sgd/lion and a warmup-cosine schedule all step."""
    for opt, sched in (("sgd", "constant"), ("lion", "warmup_cosine")):
        tr = make_trainer(
            data=1, pipe=2, layers=2, microbatches=2,
            optimizer=opt, lr_schedule=sched, warmup_steps=2,
            total_steps=4, learning_rate=1e-3,
        )
        toks = tokens_for(tr.cfg)
        _, _, losses = tr.fit(toks, steps=2)
        assert all(np.isfinite(l) for l in losses)


@pytest.mark.slow
def test_pipeline_checkpoint_resume_bit_identical(tmp_path):
    """fit(6) in one run == fit(3) + crash + fit(6) resumed from the
    step-3 checkpoint: identical loss tail and identical final params —
    the LMTrainer resume contract, now on the pipeline engine."""
    kw = dict(
        data=2, pipe=2, layers=2, microbatches=2, batch=8,
        learning_rate=1e-3,
    )
    toks = tokens_for(make_trainer(**kw).cfg, n=32, seed=5)

    tr_full = make_trainer(**kw)
    _, _, losses_full = tr_full.fit(toks, steps=6)

    ck = str(tmp_path / "pipe_ckpt")
    tr_a = make_trainer(checkpoint_dir=ck, checkpoint_every=3, **kw)
    _, _, losses_a = tr_a.fit(toks, steps=3)
    tr_b = make_trainer(checkpoint_dir=ck, checkpoint_every=3, **kw)
    params_b, _, losses_b = tr_b.fit(toks, steps=6)
    assert len(losses_b) == 3  # resumed at step 3

    np.testing.assert_allclose(
        losses_a + losses_b, losses_full, rtol=1e-6, atol=0
    )
    # And the resumed final params must match an uninterrupted run's.
    tr_c = make_trainer(**kw)
    params_c, _, _ = tr_c.fit(toks, steps=6)
    jax.tree.map(
        lambda a, b: np.testing.assert_allclose(
            jax.device_get(a), jax.device_get(b), rtol=1e-6, atol=1e-7
        ),
        params_b, params_c,
    )


# ---------------------------------------------------------------------------
# Interleaved (virtual-stage) schedule
# ---------------------------------------------------------------------------
@pytest.mark.slow
def test_interleaved_forward_parity_and_grads():
    """V=2 virtual stages over S=2 devices: pipelined forward matches the
    unpipelined reference on the same logical params; one train step
    produces the SAME loss and (after storage->logical inverse
    permutation) the same updated block params as gpipe."""
    from cs744_pytorch_distributed_tutorial_tpu.parallel.pipeline import (
        PipelineLMConfig,
        PipelineLMTrainer,
    )

    cfg = PipelineLMConfig(
        vocab_size=64, num_layers=8, num_heads=4, d_model=32, d_ff=64,
        max_seq_len=64, data_parallel=1, pipeline_parallel=2,
        num_microbatches=4, schedule="interleaved", num_virtual_stages=2,
        global_batch_size=8, seq_len=16,
    )
    mesh = make_mesh({DATA_AXIS: 1, PIPE_AXIS: 2}, devices=jax.devices()[:2])
    tr = PipelineLMTrainer(cfg, mesh=mesh)
    params_global = tr._init_host(0)
    params, opt = tr.init(0)
    toks = tokens_for(cfg)
    x = jnp.asarray(toks[:, :-1])
    got = np.asarray(tr.forward_fn(params, x))
    want = np.asarray(tr.reference_forward(params_global, x))
    np.testing.assert_allclose(got, want, atol=2e-4, rtol=2e-4)

    xg, yg = tr.shard_batch(toks)
    p_i, _, m_i = tr.train_step(params, opt, xg, yg)

    tr_g = PipelineLMTrainer(cfg.replace(schedule="gpipe"), mesh=mesh)
    p_g, o_g = tr_g.init(0)
    p_g, _, m_g = tr_g.train_step(p_g, o_g, xg, yg)
    np.testing.assert_allclose(
        float(m_i["loss"]), float(m_g["loss"]), rtol=1e-6
    )
    jax.tree.map(
        lambda a, b: np.testing.assert_allclose(
            a, jax.device_get(b), rtol=5e-4, atol=1e-6
        ),
        tr.blocks_to_logical(p_i["blocks"]),
        p_g["blocks"],
    )


def test_interleaved_v1_degenerates_to_plain_schedule():
    """num_virtual_stages=1 must be exactly the plain spmd_pipeline
    schedule (the mixed-radix unit assignment reduces to inject-at-t)."""
    from jax.sharding import PartitionSpec as P
    from cs744_pytorch_distributed_tutorial_tpu.parallel.pipeline import (
        spmd_pipeline,
        spmd_pipeline_interleaved,
    )

    mesh = make_mesh({PIPE_AXIS: 4}, devices=jax.devices()[:4])
    m = 4
    x = jnp.arange(m * 8, dtype=jnp.float32).reshape(m, 8)
    chunks = jnp.ones((4, 1))  # 1 layer per vstage

    def run(fn, **kw):
        return jax.jit(
            jax.shard_map(
                lambda mb: fn(
                    lambda p, h: h * 2.0 + p.sum(),
                    chunks,
                    mb,
                    axis_name=PIPE_AXIS,
                    num_stages=4,
                    num_microbatches=m,
                    **kw,
                ),
                mesh=mesh, in_specs=P(), out_specs=P(), check_vma=False,
            )
        )(x)

    plain = run(spmd_pipeline)
    inter = run(spmd_pipeline_interleaved, num_chunks=1)
    np.testing.assert_allclose(np.asarray(inter), np.asarray(plain))


def test_interleaved_stats_bubble_cut():
    """The schedule's reason to exist, statically: idle chunk-ticks drop
    from (S-1)*V to S-1 — a clean 1/V bubble cut at equal busy work."""
    from cs744_pytorch_distributed_tutorial_tpu.parallel.pipeline import (
        interleaved_stats,
    )

    st = interleaved_stats(num_stages=4, num_microbatches=8, num_chunks=4)
    assert st["interleaved_idle_chunk_ticks"] == 3
    assert st["plain_idle_chunk_ticks"] == 12
    assert st["bubble_cut_factor"] == 4
    assert st["interleaved_ticks"] == 4 * 8 + 3
    assert st["bubble_fraction"] < st["plain_bubble_fraction"]
    # V=1 degenerates to the plain accounting
    st1 = interleaved_stats(num_stages=4, num_microbatches=8, num_chunks=1)
    assert st1["bubble_fraction"] == st1["plain_bubble_fraction"]


def test_interleaved_validation():
    with pytest.raises(ValueError, match="num_virtual_stages"):
        make_trainer(
            pipe=2, layers=6, schedule="interleaved", num_virtual_stages=2
        )
    with pytest.raises(ValueError, match="divisible by the pipe axis"):
        make_trainer(
            pipe=2, layers=8, microbatches=1, schedule="interleaved",
            num_virtual_stages=2,
        )


# ---------------------------------------------------------------------------
# Dropout through the pipeline schedules (round 3)
# ---------------------------------------------------------------------------
@pytest.mark.slow
def test_pipeline_dropout_gpipe_1f1b_parity():
    """Dropout masks are keyed by (step, data shard, storage layer id,
    microbatch) — derivable identically under both schedules — so gpipe
    and 1f1b must produce the SAME loss and updated params with dropout
    ON. This also proves the 1F1B backward recompute replays the exact
    forward masks (a mismatch would corrupt its gradients)."""
    results = {}
    for schedule in ("gpipe", "1f1b"):
        tr = make_trainer(
            data=2, pipe=2, layers=4, microbatches=2, batch=8,
            schedule=schedule, dropout_rate=0.3,
        )
        toks = tokens_for(tr.cfg)
        x, y = tr.shard_batch(toks)
        params, opt = tr.init(0)
        params, opt, m = tr.train_step(params, opt, x, y, step=5)
        results[schedule] = (float(m["loss"]), params)
    assert results["1f1b"][0] == pytest.approx(results["gpipe"][0], rel=1e-5)
    jax.tree.map(
        lambda a, b: np.testing.assert_allclose(
            jax.device_get(a), jax.device_get(b), rtol=5e-4, atol=1e-6
        ),
        results["1f1b"][1], results["gpipe"][1],
    )


@pytest.mark.slow
def test_pipeline_dropout_stream_properties():
    """Same (state, step) -> identical loss; different step -> different
    masks -> different loss; rate 0 reproduces the dropout-free path."""
    tr = make_trainer(
        data=1, pipe=2, layers=2, microbatches=2, dropout_rate=0.4
    )
    toks = tokens_for(tr.cfg)
    x, y = tr.shard_batch(toks)
    params, opt = tr.init(0)
    _, _, m_a = tr.train_step(params, opt, x, y, step=1)
    params2, opt2 = tr.init(0)
    _, _, m_b = tr.train_step(params2, opt2, x, y, step=1)
    assert float(m_a["loss"]) == float(m_b["loss"])  # deterministic per step
    params3, opt3 = tr.init(0)
    _, _, m_c = tr.train_step(params3, opt3, x, y, step=2)
    assert float(m_c["loss"]) != float(m_a["loss"])  # step keys the stream

    tr0 = make_trainer(
        data=1, pipe=2, layers=2, microbatches=2, dropout_rate=0.0
    )
    p0, o0 = tr0.init(0)
    _, _, m0 = tr0.train_step(p0, o0, x, y, step=1)
    p0b, o0b = tr0.init(0)
    _, _, m0b = tr0.train_step(p0b, o0b, x, y)  # step default unused
    assert float(m0["loss"]) == float(m0b["loss"])
    assert float(m0["loss"]) != float(m_a["loss"])  # dropout changes it


@pytest.mark.slow
def test_pipeline_dropout_interleaved():
    """Dropout composes with the interleaved schedule: the chunk index
    rides through chunk_fn so each (chunk, layer) keeps a distinct mask
    stream. Deterministic per (state, step); differs from rate 0."""
    kw = dict(
        data=1, pipe=2, layers=8, microbatches=2, schedule="interleaved",
        num_virtual_stages=2,
    )
    tr = make_trainer(dropout_rate=0.4, **kw)
    toks = tokens_for(tr.cfg)
    x, y = tr.shard_batch(toks)
    params, opt = tr.init(0)
    _, _, m_a = tr.train_step(params, opt, x, y, step=3)
    params2, opt2 = tr.init(0)
    _, _, m_b = tr.train_step(params2, opt2, x, y, step=3)
    assert float(m_a["loss"]) == float(m_b["loss"])

    tr0 = make_trainer(dropout_rate=0.0, **kw)
    p0, o0 = tr0.init(0)
    _, _, m0 = tr0.train_step(p0, o0, x, y, step=3)
    assert float(m0["loss"]) != float(m_a["loss"])


def test_pipeline_dropout_chunk_identity_folded():
    """The regression the old rejection guarded against: a device's V
    chunks must NOT reuse one rng stream. Calls the interleaved dropout
    chunk closure directly (pipe=1 mesh, so one device holds all
    chunks) and asserts the chunk index v — and the microbatch index —
    each change the masks."""
    from jax.sharding import PartitionSpec as P

    tr = make_trainer(
        data=1, pipe=1, layers=4, microbatches=2, schedule="interleaved",
        num_virtual_stages=2, dropout_rate=0.5,
    )
    chunk_fn = tr._stage_fn(jax.random.key(7))
    params, _ = tr.init(0)
    blocks = params["blocks"]
    c = tr.cfg.num_layers // tr.num_chunks
    toks = tokens_for(tr.cfg)
    x = jnp.asarray(toks[:, :-1])

    params_host = jax.device_get(params)
    h0 = jnp.asarray(
        params_host["embed"][np.asarray(x)]
        + params_host["pos"][: x.shape[-1]],
        tr._dtype,
    )

    def run(mb, v):
        def f(bl, h):
            chunkp = jax.tree.map(lambda a: a[:c], bl)
            return chunk_fn(chunkp, h, jnp.int32(mb), jnp.int32(v))

        return np.asarray(
            jax.jit(
                jax.shard_map(
                    f,
                    mesh=tr.mesh,
                    in_specs=(tr.param_specs["blocks"], P()),
                    out_specs=P(),
                    check_vma=False,
                )
            )(blocks, h0)
        )

    out_v0 = run(0, 0)
    out_v1 = run(0, 1)
    out_mb1 = run(1, 0)
    assert not np.array_equal(out_v0, out_v1), "chunk index not folded"
    assert not np.array_equal(out_v0, out_mb1), "microbatch index not folded"
    np.testing.assert_array_equal(out_v0, run(0, 0))  # deterministic


@pytest.mark.slow
def test_pipeline_halt_on_nonfinite():
    """The failure-detection contract shared with the other engines: a
    diverged run (lr 1e30 blows params up within a few steps) raises
    NonFiniteLossError instead of training on garbage; opting out keeps
    the old behavior."""
    from cs744_pytorch_distributed_tutorial_tpu.utils.failure import (
        NonFiniteLossError,
    )

    kw = dict(
        data=1, pipe=2, layers=2, microbatches=2, learning_rate=1e30,
    )
    tr = make_trainer(**kw)
    toks = tokens_for(tr.cfg, n=16)
    with pytest.raises(NonFiniteLossError) as exc:
        tr.fit(toks, steps=8)
    assert not np.isfinite(exc.value.loss)

    _, _, losses = make_trainer(halt_on_nonfinite=False, **kw).fit(
        toks, steps=3
    )
    assert len(losses) == 3  # ran through, divergence recorded not raised


@pytest.mark.slow
def test_pipeline_divergence_safe_checkpointing(tmp_path):
    """A checkpoint due at step k is persisted only after a LATER
    forward over its params comes back finite: when the run diverges,
    restart recovery must never find a checkpoint whose own forward is
    non-finite (the CIFAR engine's ordering, now on the pipeline)."""
    from cs744_pytorch_distributed_tutorial_tpu.utils.checkpoint import (
        Checkpointer,
    )
    from cs744_pytorch_distributed_tutorial_tpu.utils.failure import (
        NonFiniteLossError,
    )

    ck = str(tmp_path / "diverge_ckpt")
    kw = dict(
        data=1, pipe=2, layers=2, microbatches=2, learning_rate=1e30,
        checkpoint_dir=ck, checkpoint_every=1,
    )
    tr = make_trainer(**kw)
    toks = tokens_for(tr.cfg, n=16)
    with pytest.raises(NonFiniteLossError) as exc:
        tr.fit(toks, steps=8)
    diverged_at = exc.value.step

    # Every persisted checkpoint's params must produce a finite forward.
    tr2 = make_trainer(**{**kw, "learning_rate": 1e-3})
    params, opt = tr2.init()
    ckpt = Checkpointer(ck)
    restored = ckpt.restore_latest(tr2._make_state(0, params, opt))
    ckpt.close()
    if restored is not None:  # divergence at step 0 persists nothing
        assert int(jax.device_get(restored.step)) < diverged_at
        x, y = tr2.shard_batch(toks[: tr2.cfg.global_batch_size])
        ev = float(tr2.eval_step(restored.params, x, y)["loss"])
        assert np.isfinite(ev), "recovered checkpoint itself diverged"


def test_pipeline_evaluate_perplexity():
    tr = make_trainer(data=2, pipe=2, layers=2, microbatches=2)
    toks = tokens_for(tr.cfg, n=16)
    params, _ = tr.init(0)
    ev = tr.evaluate(params, toks)
    assert set(ev) == {"loss", "perplexity"}
    assert ev["perplexity"] == pytest.approx(np.exp(ev["loss"]), rel=1e-6)
    # untrained model ~ uniform: loss near log(vocab)
    assert ev["loss"] == pytest.approx(np.log(tr.cfg.vocab_size), rel=0.2)


# ---------------------------------------------------------------------------
# 1F1B schedule
# ---------------------------------------------------------------------------
def _run_one_step(schedule, mesh, m=4):
    from cs744_pytorch_distributed_tutorial_tpu.parallel.pipeline import (
        PipelineLMConfig,
        PipelineLMTrainer,
    )
    import numpy as np

    cfg = PipelineLMConfig(
        vocab_size=64, num_layers=4, num_heads=2, d_model=32, d_ff=64,
        max_seq_len=32, data_parallel=2, pipeline_parallel=2,
        num_microbatches=m, global_batch_size=8, seq_len=16,
        schedule=schedule, seed=3,
    )
    tr = PipelineLMTrainer(cfg, mesh=mesh)
    params, opt_state = tr.init()
    rng = np.random.default_rng(0)
    toks = rng.integers(0, 64, size=(8, 17), dtype=np.int32)
    x, y = tr.shard_batch(toks)
    params, opt_state, metrics = tr.train_step(params, opt_state, x, y)
    return float(metrics["loss"]), params


@pytest.mark.slow
def test_1f1b_matches_gpipe(mesh4):
    """The hand-scheduled 1F1B backward must produce the SAME loss and
    parameter update as AD of the GPipe forward — the grad-parity gate
    for the schedule swap."""
    import jax
    import numpy as np
    from cs744_pytorch_distributed_tutorial_tpu.parallel import make_mesh
    from cs744_pytorch_distributed_tutorial_tpu.parallel.pipeline import (
        PIPE_AXIS,
    )
    from cs744_pytorch_distributed_tutorial_tpu.parallel.mesh import DATA_AXIS

    mesh = make_mesh(
        {DATA_AXIS: 2, PIPE_AXIS: 2}, devices=jax.devices()[:4]
    )
    loss_g, params_g = _run_one_step("gpipe", mesh)
    loss_f, params_f = _run_one_step("1f1b", mesh)
    np.testing.assert_allclose(loss_f, loss_g, rtol=1e-5)
    jax.tree.map(
        lambda a, b: np.testing.assert_allclose(
            jax.device_get(a), jax.device_get(b), rtol=5e-4, atol=1e-6
        ),
        params_f, params_g,
    )


@pytest.mark.slow
def test_1f1b_single_stage_degenerates(mesh4):
    """S=1: no hops, every wave is fwd+bwd of the same microbatch; the
    schedule must still match gpipe exactly."""
    import jax
    import numpy as np
    from cs744_pytorch_distributed_tutorial_tpu.parallel import make_mesh
    from cs744_pytorch_distributed_tutorial_tpu.parallel.pipeline import (
        PIPE_AXIS,
        PipelineLMConfig,
        PipelineLMTrainer,
    )
    from cs744_pytorch_distributed_tutorial_tpu.parallel.mesh import DATA_AXIS

    mesh = make_mesh({DATA_AXIS: 2, PIPE_AXIS: 1}, devices=jax.devices()[:2])
    losses = {}
    for schedule in ("gpipe", "1f1b"):
        cfg = PipelineLMConfig(
            vocab_size=64, num_layers=2, num_heads=2, d_model=32, d_ff=64,
            max_seq_len=32, data_parallel=2, pipeline_parallel=1,
            num_microbatches=2, global_batch_size=8, seq_len=16,
            schedule=schedule, seed=3,
        )
        tr = PipelineLMTrainer(cfg, mesh=mesh)
        params, opt_state = tr.init()
        import numpy as np_

        toks = np_.random.default_rng(0).integers(
            0, 64, size=(8, 17), dtype=np_.int32
        )
        x, y = tr.shard_batch(toks)
        _, _, metrics = tr.train_step(params, opt_state, x, y)
        losses[schedule] = float(metrics["loss"])
    np.testing.assert_allclose(losses["1f1b"], losses["gpipe"], rtol=1e-5)


def test_1f1b_schedule_stats():
    """The memory claim, statically: the 1F1B stash is 2S-1 slots
    regardless of M, vs the GPipe path's M+S-1 saved carries."""
    from cs744_pytorch_distributed_tutorial_tpu.parallel.pipeline import (
        one_f_one_b_stats,
    )

    st = one_f_one_b_stats(num_stages=4, num_microbatches=32)
    assert st["f1b_stash_slots"] == 7
    assert st["gpipe_stash_slots"] == 35
    assert st["f1b_stash_slots"] < st["gpipe_stash_slots"]
    # tick span identical: the lockstep-SPMD 1F1B identity
    assert st["f1b_waves"] == st["gpipe_ticks"] // 2 + (4 - 1)
    assert 0 < st["bubble_fraction"] < 1


# --------------------------------------------------------------------------
# Sequence parallelism inside pipeline stages (round 4, VERDICT r3 #5)
# --------------------------------------------------------------------------
def _sp_pp_trainer(sp, pipe=2, data=1, impl="ring", schedule="gpipe", **kw):
    from cs744_pytorch_distributed_tutorial_tpu.parallel.pipeline import (
        SEQ_AXIS,
    )

    cfg = PipelineLMConfig(
        vocab_size=64,
        num_layers=4,
        num_heads=4,
        d_model=32,
        d_ff=64,
        max_seq_len=kw.pop("max_seq_len", 64),
        data_parallel=data,
        pipeline_parallel=pipe,
        seq_parallel=sp,
        attention_impl=impl,
        schedule=schedule,
        num_microbatches=2,
        global_batch_size=4 * data,
        seq_len=kw.pop("seq_len", 16),
        use_rope=kw.pop("use_rope", True),
        **kw,
    )
    axes = {DATA_AXIS: data, PIPE_AXIS: pipe}
    if sp > 1:
        axes[SEQ_AXIS] = sp
    mesh = make_mesh(axes, devices=jax.devices()[: data * pipe * max(sp, 1)])
    return PipelineLMTrainer(cfg, mesh=mesh)


@pytest.mark.parametrize("impl,schedule", [
    ("ring", "gpipe"),
    ("ring", "1f1b"),
    ("ulysses", "gpipe"),
])
@pytest.mark.slow
def test_sp_pp_loss_parity(impl, schedule):
    """sp=2 inside pp=2 reproduces the sp=1 pipeline's loss trajectory
    from the same init — the seq sharding (ring/Ulysses attention, seq-
    sharded batch, seq-axis grad/loss reduction) is exactly a layout
    change."""
    base_impl = "dense"
    tr_ref = _sp_pp_trainer(1, impl=base_impl, schedule=schedule)
    tr_sp = _sp_pp_trainer(2, impl=impl, schedule=schedule)
    toks = tokens_for(tr_ref.cfg)

    losses = {}
    for name, tr in (("ref", tr_ref), ("sp", tr_sp)):
        params, opt = tr.init(3)
        x, y = tr.shard_batch(toks)
        ls = []
        for step in range(3):
            params, opt, m = tr.train_step(params, opt, x, y, step)
            ls.append(float(m["loss"]))
        # Drain ALL device work before the next trainer launches: the
        # loss fetch fences only the loss — the param-update collectives
        # can still be in flight, and the in-process CPU rendezvous
        # deadlocks if a different-mesh program overlaps them on the
        # same device threads.
        jax.block_until_ready((params, opt))
        losses[name] = ls
    np.testing.assert_allclose(losses["ref"], losses["sp"], rtol=2e-5)


def test_sp_pp_abs_positions():
    """Non-RoPE path: the absolute position table is sliced at each seq
    shard's GLOBAL offset — forward logits match the sp=1 pipeline."""
    tr_ref = _sp_pp_trainer(1, impl="dense", use_rope=False)
    tr_sp = _sp_pp_trainer(2, impl="ring", use_rope=False)
    toks = tokens_for(tr_ref.cfg)
    x = jnp.asarray(toks[:, :-1])
    p_ref, _ = tr_ref.init(5)
    p_sp, _ = tr_sp.init(5)
    want = np.asarray(tr_ref.forward_fn(p_ref, x))
    got = np.asarray(tr_sp.forward_fn(p_sp, x))
    np.testing.assert_allclose(got, want, atol=1e-5, rtol=1e-5)


@pytest.mark.slow
def test_sp_pp_tp_composes(mesh8):
    """dp x sp x tp inside pp on one 4-D mesh: one finite training step
    (the full composition — ring attention over seq, Megatron sharding
    over tensor, stages over pipe, batch over data)."""
    from cs744_pytorch_distributed_tutorial_tpu.parallel.pipeline import (
        SEQ_AXIS, TENSOR_AXIS,
    )

    cfg = PipelineLMConfig(
        vocab_size=64, num_layers=2, num_heads=4, d_model=32, d_ff=64,
        max_seq_len=64, data_parallel=1, pipeline_parallel=2,
        seq_parallel=2, tensor_parallel=2, attention_impl="ring",
        num_microbatches=2, global_batch_size=4, seq_len=16, use_rope=True,
    )
    mesh = make_mesh({DATA_AXIS: 1, PIPE_AXIS: 2, SEQ_AXIS: 2,
                      TENSOR_AXIS: 2})
    tr = PipelineLMTrainer(cfg, mesh=mesh)
    params, opt = tr.init()
    x, y = tr.shard_batch(tokens_for(cfg))
    params, opt, m = tr.train_step(params, opt, x, y)
    assert np.isfinite(float(m["loss"]))


def test_sp_pp_validation():
    with pytest.raises(ValueError, match="incompatible with seq_parallel"):
        _sp_pp_trainer(2, impl="dense")
    with pytest.raises(ValueError, match="not divisible by seq axis"):
        _sp_pp_trainer(2, impl="ring", seq_len=15, max_seq_len=30)


# --------------------------------------------------------------------------
# 1F1B distributed tail (round 4, VERDICT r3 #7)
# --------------------------------------------------------------------------
def _dot_operand_shapes(jaxpr, out=None):
    """All dot_general operand shapes, recursing into sub-jaxprs
    (ClosedJaxpr params like pjit/scan AND raw Jaxpr params like
    shard_map's)."""
    out = [] if out is None else out

    def visit(v):
        if hasattr(v, "jaxpr"):  # ClosedJaxpr
            _dot_operand_shapes(v.jaxpr, out)
        elif hasattr(v, "eqns"):  # raw Jaxpr
            _dot_operand_shapes(v, out)
        elif isinstance(v, (list, tuple)):
            for b in v:
                visit(b)

    for eqn in jaxpr.eqns:
        if eqn.primitive.name == "dot_general":
            out.append(tuple(tuple(v.aval.shape) for v in eqn.invars))
        for v in eqn.params.values():
            visit(v)
    return out


def test_1f1b_distributed_tail_head_width():
    """tp=1 1F1B shards the per-wave tail over the pipe axis: the jaxpr
    must contain head matmuls at V/S width and NONE at full V width —
    total head FLOPs per microbatch = S * V/S = one full head matmul,
    not one per stage (the round-3 S x dead-compute tax)."""
    # vocab chosen so the head widths (192 full / 48 per slice) collide
    # with no block matmul dim (d_model 32, d_ff 64).
    d_model, vocab, pipe = 32, 192, 4
    tr = make_trainer(data=1, pipe=pipe, layers=4, microbatches=2,
                      batch=4, vocab_size=vocab, d_model=d_model)
    assert tr.cfg.schedule == "gpipe"
    tr_f = make_trainer(data=1, pipe=pipe, layers=4, microbatches=2,
                        batch=4, vocab_size=vocab, d_model=d_model,
                        schedule="1f1b")
    assert tr_f._dist_tail
    params, opt = tr_f.init()
    x, y = tr_f.shard_batch(tokens_for(tr_f.cfg))
    jaxpr = jax.make_jaxpr(
        lambda p, o, a, b: tr_f.jitted_train_step(p, o, a, b, jnp.int32(0))
    )(params, opt, x, y)
    shapes = _dot_operand_shapes(jaxpr.jaxpr)
    full = [s for s in shapes if (d_model, vocab) in s or (vocab, d_model) in s]
    sliced = [s for s in shapes if (d_model, vocab // pipe) in s]
    assert not full, f"full-vocab head dot survived: {full}"
    assert sliced, "no V/S-width head dot found — tail not sharded?"


@pytest.mark.slow
def test_1f1b_distributed_tail_composes_with_tensor_axis():
    """Round 5 (VERDICT r4 #5): with a tensor axis the per-stage tail
    width is V/(S*T), not V/T — the jaxpr must contain head matmuls at
    the joint width and none at the per-tensor-shard width, and the
    dp2 x pp2 x tp2 trajectory must match the GPipe schedule (whose
    tail is computed once, full, outside the schedule)."""
    from cs744_pytorch_distributed_tutorial_tpu.parallel.pipeline import (
        TENSOR_AXIS,
    )

    # Width pin on pipe=2 x tensor=2 (4 devices): vocab 192 -> V/T = 96
    # per tensor shard, V/(S*T) = 48 per (stage, shard).
    d_model, vocab, pipe, tensor = 32, 192, 2, 2
    cfg = PipelineLMConfig(
        vocab_size=vocab, num_layers=4, num_heads=4, d_model=d_model,
        d_ff=64, max_seq_len=64, data_parallel=1, pipeline_parallel=pipe,
        tensor_parallel=tensor, num_microbatches=2,
        global_batch_size=4, seq_len=16, schedule="1f1b",
    )
    mesh = make_mesh(
        {DATA_AXIS: 1, PIPE_AXIS: pipe, TENSOR_AXIS: tensor},
        devices=jax.devices()[: pipe * tensor],
    )
    tr = PipelineLMTrainer(cfg, mesh=mesh)
    assert tr._dist_tail
    params, opt = tr.init()
    x, y = tr.shard_batch(tokens_for(cfg))
    jaxpr = jax.make_jaxpr(
        lambda p, o, a, b: tr.jitted_train_step(p, o, a, b, jnp.int32(0))
    )(params, opt, x, y)
    shapes = _dot_operand_shapes(jaxpr.jaxpr)
    per_shard = [
        s for s in shapes
        if (d_model, vocab // tensor) in s or (vocab // tensor, d_model) in s
    ]
    joint = [s for s in shapes if (d_model, vocab // (pipe * tensor)) in s]
    assert not per_shard, f"V/T-width head dot survived: {per_shard}"
    assert joint, "no V/(S*T)-width head dot found — tail not composed?"

    # Trajectory parity vs GPipe on dp2 x pp2 x tp2 (8 devices).
    results = {}
    for schedule in ("gpipe", "1f1b"):
        cfg8 = PipelineLMConfig(
            vocab_size=64, num_layers=4, num_heads=4, d_model=32, d_ff=64,
            max_seq_len=64, data_parallel=2, pipeline_parallel=2,
            tensor_parallel=2, num_microbatches=2,
            global_batch_size=8, seq_len=16, schedule=schedule,
        )
        mesh8 = make_mesh(
            {DATA_AXIS: 2, PIPE_AXIS: 2, TENSOR_AXIS: 2},
            devices=jax.devices()[:8],
        )
        tr8 = PipelineLMTrainer(cfg8, mesh=mesh8)
        assert tr8._dist_tail == (schedule == "1f1b")
        p8, o8 = tr8.init(0)
        x8, y8 = tr8.shard_batch(tokens_for(cfg8))
        losses = []
        for s_ in range(3):
            p8, o8, m8 = tr8.train_step(p8, o8, x8, y8, s_)
            losses.append(float(m8["loss"]))
        results[schedule] = (losses, jax.device_get(p8))
    np.testing.assert_allclose(
        results["1f1b"][0], results["gpipe"][0], rtol=1e-5
    )
    jax.tree.map(
        lambda a, b: np.testing.assert_allclose(a, b, rtol=2e-4, atol=2e-6),
        results["1f1b"][1],
        results["gpipe"][1],
    )


@pytest.mark.slow
def test_1f1b_distributed_tail_fallback_when_indivisible():
    """vocab % pipe != 0 falls back to the replicated tail (correct,
    just unsharded) rather than refusing the config."""
    tr = make_trainer(data=1, pipe=4, layers=4, microbatches=2,
                      batch=4, vocab_size=66, schedule="1f1b")
    assert not tr._dist_tail
    params, opt = tr.init()
    x, y = tr.shard_batch(tokens_for(tr.cfg))
    params, opt, m = tr.train_step(params, opt, x, y)
    assert np.isfinite(float(m["loss"]))
