"""Beam search (infer/beam.py): scores, greedy equivalence, EOS handling."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from cs744_pytorch_distributed_tutorial_tpu.infer import (
    make_beam_searcher,
    make_generator,
)
from cs744_pytorch_distributed_tutorial_tpu.models import TransformerLM

VOCAB = 37


@pytest.fixture(scope="module")
def tiny_lm():
    model = TransformerLM(
        vocab_size=VOCAB,
        num_layers=2,
        num_heads=2,
        d_model=32,
        d_ff=64,
        max_seq_len=32,
        attention_impl="dense",
    )
    params = model.init(jax.random.key(0), jnp.zeros((1, 4), jnp.int32))["params"]
    return model, params


def _sequence_logprob(model, params, prompt, generated):
    """Teacher-forced log-prob of ``generated`` given ``prompt``."""
    full = jnp.concatenate([prompt, generated], axis=1)
    logits = model.apply({"params": params}, full)
    logp = jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1)
    t0 = prompt.shape[1]
    total = 0.0
    for i in range(generated.shape[1]):
        # token at position t0+i is predicted from position t0+i-1
        total += float(
            logp[jnp.arange(full.shape[0]), t0 + i - 1, full[:, t0 + i]].sum()
        )
    return total


def test_beam_1_equals_greedy(tiny_lm):
    model, params = tiny_lm
    prompt = jax.random.randint(jax.random.key(1), (2, 5), 0, VOCAB)
    greedy = make_generator(model, max_new_tokens=6, temperature=0.0)
    beam = make_beam_searcher(model, beam_size=1, max_new_tokens=6)
    g = np.asarray(greedy(params, prompt, jax.random.key(0)))
    b, _ = beam(params, prompt)
    np.testing.assert_array_equal(g, np.asarray(b))


def test_beam_score_is_model_logprob(tiny_lm):
    """The returned score must equal the teacher-forced log-prob of the
    returned sequence (no EOS involved) — pins the accumulation."""
    model, params = tiny_lm
    prompt = jax.random.randint(jax.random.key(2), (1, 5), 0, VOCAB)
    beam = make_beam_searcher(model, beam_size=3, max_new_tokens=5)
    seq, score = beam(params, prompt)
    expected = _sequence_logprob(model, params, prompt, jnp.asarray(seq))
    assert float(score[0]) == pytest.approx(expected, rel=1e-4, abs=1e-4)


def test_wider_beam_never_worse(tiny_lm):
    """Beam K's best raw score >= greedy's sequence log-prob (beam search
    explores a superset of the greedy path)."""
    model, params = tiny_lm
    prompt = jax.random.randint(jax.random.key(3), (1, 4), 0, VOCAB)
    b1 = make_beam_searcher(model, beam_size=1, max_new_tokens=6)
    b4 = make_beam_searcher(model, beam_size=4, max_new_tokens=6)
    _, s1 = b1(params, prompt)
    _, s4 = b4(params, prompt)
    assert float(s4[0]) >= float(s1[0]) - 1e-5


def test_beam_eos_pads_tail(tiny_lm):
    """A beam that emits EOS freezes its score; since every continuation
    has negative log-prob, the frozen beam must win — and its tail must
    be pad (including an out-of-vocab sentinel pad_id)."""
    model, params = tiny_lm
    prompt = jax.random.randint(jax.random.key(4), (2, 4), 0, VOCAB)
    ref = make_beam_searcher(model, beam_size=2, max_new_tokens=6)
    seq_ref, _ = ref(params, prompt)
    # EOS = row 0's FIRST token: its beam finishes immediately with the
    # single-token score, which strictly dominates any longer sequence.
    eos = int(np.asarray(seq_ref)[0, 0])

    pad = VOCAB + 3
    beam = make_beam_searcher(
        model, beam_size=2, max_new_tokens=6, eos_id=eos, pad_id=pad
    )
    seq = np.asarray(beam(params, prompt)[0])
    assert seq[0, 0] == eos, "the immediately-finished beam must win row 0"
    assert (seq[0, 1:] == pad).all()
    for row in seq:
        hits = np.flatnonzero(row == eos)
        if hits.size:
            assert (row[hits[0] + 1 :] == pad).all()


def test_beam_batch_independence(tiny_lm):
    """Each batch row's beam search is independent: searching rows
    together == searching them alone."""
    model, params = tiny_lm
    prompts = jax.random.randint(jax.random.key(5), (3, 5), 0, VOCAB)
    beam = make_beam_searcher(model, beam_size=3, max_new_tokens=4)
    joint, joint_scores = beam(params, prompts)
    for i in range(3):
        solo, solo_score = beam(params, prompts[i : i + 1])
        np.testing.assert_array_equal(np.asarray(joint)[i], np.asarray(solo)[0])
        assert float(joint_scores[i]) == pytest.approx(
            float(solo_score[0]), rel=1e-5
        )
