"""Bucketed flat gradient sync + int8 quantized all-reduce.

Three contracts pinned here (parallel/buckets.py, parallel/sync.py):

- Bucketed f32 sync is BITWISE identical to the per-leaf collectives it
  replaces: 'allreduce' pmeans a flat concatenation (elementwise — the
  layout cannot change a value), and 'ring' preserves each leaf's
  per-row chunk placement so the explicit ring's accumulation order is
  unchanged. Bucketing is a pure wire-layout optimization.
- The int8 strategies approximate the f32 mean within per-chunk
  quantization error and ship ~3.9x fewer bytes (int8 codes + one f32
  scale per 256 elements, exactly accounted by sync_bytes_per_step).
- Error feedback closes the loop: sync_grads_compressed returns the
  residual (input minus what was transmitted), and an SGD run with
  int8+EF converges to within 1% of the f32 run's final loss — the
  compressed-DP acceptance bar.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from cs744_pytorch_distributed_tutorial_tpu.ops.quant import (
    dequantize_chunked,
    quantize_chunked,
)
from cs744_pytorch_distributed_tutorial_tpu.parallel import buckets as B
from cs744_pytorch_distributed_tutorial_tpu.parallel.sync import (
    QUANT_CHUNK,
    SYNC_STRATEGIES,
    sync_grads,
    sync_grads_compressed,
)
from conftest import run_tiny_dp4_steps


def _smap(f, mesh, in_specs, out_specs):
    """shard_map across the jax.shard_map / experimental API versions."""
    if hasattr(jax, "shard_map"):
        return jax.shard_map(
            f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
            check_vma=False,
        )
    from jax.experimental.shard_map import shard_map

    return shard_map(
        f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
        check_rep=False,
    )


def _tree(seed=0):
    """Mixed shapes/dtypes: oversized leaf, odd sizes, scalar, bf16."""
    rng = np.random.RandomState(seed)
    return {
        "conv": jnp.asarray(rng.randn(3, 3, 8, 16), jnp.float32),
        "dense": {
            "w": jnp.asarray(rng.randn(257, 5), jnp.float32),
            "b": jnp.asarray(rng.randn(5), jnp.float32),
            "scale": jnp.asarray(rng.randn(), jnp.float32),
        },
        "half": jnp.asarray(rng.randn(33), jnp.bfloat16),
    }


def _stacked(tree, n=4):
    """Per-device variants: device i's leaf is (i+1)/10-scaled."""
    return jax.tree.map(
        lambda x: jnp.stack([x * (i + 1) * 0.1 for i in range(n)]), tree
    )


def _run_sync(mesh, strategy, bucket_bytes, tree):
    g = _stacked(tree)

    def f(gs):
        gl = jax.tree.map(lambda a: a[0], gs)
        return sync_grads(gl, strategy, "data", 4, bucket_bytes=bucket_bytes)

    out = jax.jit(_smap(f, mesh, (P("data"),), P()))(g)
    return jax.tree.map(np.asarray, jax.device_get(out))


# ---------------------------------------------------------------- layout
def test_bucket_layout_covers_every_element():
    tree = _tree()
    layout = B.bucket_layout(tree, 1024)
    sizes = [int(np.prod(l.shape)) or 1 for l in jax.tree.leaves(tree)]
    assert sum(s.size for s in layout.slots) == sum(sizes)
    # dtype segregation: every slot's dtype matches its bucket's.
    for s in layout.slots:
        assert s.dtype == layout.bucket_dtypes[s.bucket]


def test_bucket_layout_cached_per_structure():
    tree = _tree()
    assert B.bucket_layout(tree, 1024) is B.bucket_layout(tree, 1024)
    assert B.bucket_layout(tree, 1024) is not B.bucket_layout(tree, 2048)


def test_flatten_unflatten_roundtrip():
    for rows in (0, 4):
        tree = _tree()
        layout = B.bucket_layout(tree, 512, rows=rows)
        back = B.unflatten(B.flatten_for_sync(tree, layout), layout)
        jax.tree.map(
            lambda a, b: np.testing.assert_array_equal(
                np.asarray(a), np.asarray(b)
            ),
            tree,
            back,
        )


def test_quantize_chunked_roundtrip_error_bounded():
    x = jnp.asarray(np.random.RandomState(0).randn(4 * QUANT_CHUNK), jnp.float32)
    q, s = quantize_chunked(x, QUANT_CHUNK)
    err = np.abs(np.asarray(dequantize_chunked(q, s) - x))
    # Max error is half a quantization step per chunk.
    bound = np.repeat(np.asarray(s) / 2 * 1.0001, QUANT_CHUNK)
    assert (err <= bound).all()


# ------------------------------------------------------- bitwise parity
@pytest.mark.parametrize("strategy", ["allreduce", "ring"])
def test_bucketed_sync_bitwise_equals_per_leaf(mesh4, strategy):
    tree = _tree()
    per_leaf = _run_sync(mesh4, strategy, 0, tree)  # 0 disables bucketing
    for bucket_bytes in (512, B.DEFAULT_BUCKET_BYTES):
        bucketed = _run_sync(mesh4, strategy, bucket_bytes, tree)
        for a, b in zip(jax.tree.leaves(per_leaf), jax.tree.leaves(bucketed)):
            np.testing.assert_array_equal(a, b)


# ----------------------------------------------------------------- int8
@pytest.mark.parametrize("strategy", ["int8_allreduce", "int8_ring"])
def test_int8_strategies_close_to_f32_mean(mesh4, strategy):
    assert strategy in SYNC_STRATEGIES
    tree = _tree()
    ref = _run_sync(mesh4, "allreduce", 0, tree)
    got = _run_sync(mesh4, strategy, B.DEFAULT_BUCKET_BYTES, tree)
    for a, r in zip(jax.tree.leaves(got), jax.tree.leaves(ref)):
        a32, r32 = np.asarray(a, np.float32), np.asarray(r, np.float32)
        scale = max(np.abs(r32).max(), 1e-6)
        # Per-chunk int8: worst case ~scale/127 per quantization stage.
        np.testing.assert_allclose(a32, r32, atol=scale * 0.05, rtol=0)


def test_compressed_sync_returns_transmission_residual(mesh4):
    """new_ef == (grad + old_ef) - dequant(quant(...)): exactly what the
    wire did NOT carry this step, so mean + own residual reconstructs
    the device's pre-quantization contribution."""
    tree = _tree()
    g = _stacked(tree)
    ef0 = jax.tree.map(lambda a: jnp.zeros(a.shape, jnp.float32), g)

    def f(gs, efs):
        gl = jax.tree.map(lambda a: a[0], gs)
        el = jax.tree.map(lambda a: a[0], efs)
        mean, ef = sync_grads_compressed(gl, el, "int8_allreduce", "data", 4)
        return mean, jax.tree.map(lambda a: a[None], ef)

    mean, ef = jax.jit(
        _smap(f, mesh4, (P("data"), P("data")), (P(), P("data")))
    )(g, ef0)
    # Residuals are nonzero (quantization is lossy) but small relative
    # to the gradient scale.
    for e, orig in zip(jax.tree.leaves(ef), jax.tree.leaves(g)):
        e, orig = np.asarray(e, np.float32), np.asarray(orig, np.float32)
        assert np.abs(e).max() > 0
        assert np.abs(e).max() < np.abs(orig).max() * 0.05


# ---------------------------------------------------------------- bytes
def test_int8_bytes_on_wire_ratio():
    tree = _tree()
    f32 = B.sync_bytes_per_step(tree, "allreduce", 4)
    int8 = B.sync_bytes_per_step(tree, "int8_allreduce", 4)
    assert f32 > 0 and int8 > 0
    assert f32 / int8 >= 3.5  # acceptance bar; analytic value ~3.94
    # none / single-device ship nothing.
    assert B.sync_bytes_per_step(tree, "none", 4) == 0
    assert B.sync_bytes_per_step(tree, "allreduce", 1) == 0


# ---------------------------------------------------------- convergence
@pytest.mark.slow
def test_int8_ef_sgd_converges_like_f32(mesh4):
    """The PR's acceptance criterion: 50 SGD steps on the tiny CNN, int8
    compressed sync with error feedback vs plain f32 allreduce — final
    loss within 1%."""
    ref, _, _ = run_tiny_dp4_steps("allreduce", mesh4, steps=50)
    got, _, _ = run_tiny_dp4_steps(
        "allreduce", mesh4, steps=50, cfg_overrides={"grad_compress": "int8"}
    )
    assert got[-1] == pytest.approx(ref[-1], rel=0.01)
    # And it actually trained (loss moved meaningfully from step 0).
    assert got[-1] < got[0]


@pytest.mark.slow
def test_int8_short_run_stays_close(mesh4):
    """Fast (tier-1) version of the convergence check: 8 steps, 2%."""
    ref, _, _ = run_tiny_dp4_steps("allreduce", mesh4, steps=8)
    got, tr, state = run_tiny_dp4_steps(
        "allreduce", mesh4, steps=8, cfg_overrides={"grad_compress": "int8"}
    )
    assert got[-1] == pytest.approx(ref[-1], rel=0.02)
    # EF state exists, is per-device, and is nonzero after stepping.
    ef_leaves = jax.tree.leaves(jax.device_get(state.ef))
    assert ef_leaves and all(l.shape[0] == 4 for l in ef_leaves)
    assert any(np.abs(np.asarray(l)).max() > 0 for l in ef_leaves)


def test_int8_sync_names_route_through_compression(mesh4):
    """sync='int8_allreduce' alone (no grad_compress flag) runs the
    compressed engine path."""
    losses, tr, _ = run_tiny_dp4_steps("int8_allreduce", mesh4, steps=2)
    assert tr._compress
    assert np.isfinite(losses).all()


def test_zero1_bucketed_update_bitwise(mesh4):
    """Zero1SGD's bucketed reduce-scatter/all-gather (one collective per
    ~bucket instead of per leaf) is bitwise identical to the per-leaf
    path: column-concatenation preserves each leaf's per-row placement,
    so psum_scatter delivers the exact same shards."""
    from jax import lax

    from cs744_pytorch_distributed_tutorial_tpu.parallel.zero import Zero1SGD

    tree = _tree()
    g = _stacked(tree)

    def run(bucket_bytes):
        opt = Zero1SGD(0.1, 0.9, 1e-4, "data", 4, bucket_bytes=bucket_bytes)
        mom = opt.init(tree)

        def f(p, m, gs):
            gl = jax.tree.map(lambda a: a[0], gs)
            return opt.apply(p, m, gl)

        return jax.jit(
            _smap(f, mesh4, (P(), P("data"), P("data")), (P(), P("data")))
        )(tree, mom, g)

    p0, m0 = run(0)
    p1, m1 = run(B.DEFAULT_BUCKET_BYTES)
    for a, b in zip(jax.tree.leaves((p0, m0)), jax.tree.leaves((p1, m1))):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_compress_rejects_incompatible_sync(mesh4):
    from cs744_pytorch_distributed_tutorial_tpu.config import TrainConfig
    from cs744_pytorch_distributed_tutorial_tpu.train import Trainer

    cfg = TrainConfig(
        model="tiny_cnn", num_devices=4, global_batch_size=16,
        sync="gather_scatter", grad_compress="int8",
    )
    with pytest.raises(ValueError, match="grad_compress"):
        Trainer(cfg, mesh=mesh4)
