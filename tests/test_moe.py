"""Mixture-of-Experts FFN + expert parallelism (models/moe.py).

The key property: expert parallelism is an EXECUTION layout, not a model
change — sharding the experts over the data axis with all-to-all dispatch
must produce the same losses and the same post-step global params as
computing every expert locally on each device.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from cs744_pytorch_distributed_tutorial_tpu.data import synthetic_tokens
from cs744_pytorch_distributed_tutorial_tpu.models import MoEFFN, TransformerLM
from cs744_pytorch_distributed_tutorial_tpu.parallel import make_mesh
from cs744_pytorch_distributed_tutorial_tpu.train import LMConfig, LMTrainer

MOE = dict(
    vocab_size=64, num_layers=2, num_heads=4, d_model=64, d_ff=128,
    max_seq_len=256, global_batch_size=8, seq_len=64, learning_rate=1e-2,
    moe_experts=4, moe_capacity_factor=2.0,
)


def test_moe_ffn_shape_and_aux():
    layer = MoEFFN(num_experts=4, d_ff=32, top_k=2)
    x = jax.random.normal(jax.random.key(0), (2, 16, 24))
    variables = layer.init(jax.random.key(1), x)
    y, mut = layer.apply({"params": variables["params"]}, x, mutable=["losses"])
    assert y.shape == x.shape
    assert np.isfinite(np.asarray(y)).all()
    (aux,) = jax.tree_util.tree_leaves(mut["losses"])
    # Perfectly balanced routing gives aux = 1; any routing gives >= 1
    # up to the capacity truncation. It must at least be a finite scalar
    # of the right order.
    assert 0.5 < float(aux) < 4.0


def test_moe_capacity_overflow_drops_to_zero():
    """With capacity far below demand, most tokens are dropped — outputs
    stay finite and the dropped tokens contribute exactly zero."""
    layer = MoEFFN(num_experts=2, d_ff=16, top_k=1, capacity_factor=0.1)
    x = jax.random.normal(jax.random.key(0), (1, 64, 8))
    variables = layer.init(jax.random.key(1), x)
    y = layer.apply(variables, x)
    n_zero = int((np.abs(np.asarray(y)).sum(-1) == 0.0).sum())
    assert n_zero >= 32  # far more tokens than slots -> many exact zeros
    assert np.isfinite(np.asarray(y)).all()


@pytest.mark.parametrize("top_k", [1, 2])
@pytest.mark.slow
def test_moe_lm_trains(top_k):
    """A 2-device data-parallel MoE LM (experts local) learns the cyclic
    synthetic stream."""
    mesh = make_mesh({"data": 2, "seq": 1}, devices=jax.devices()[:2])
    cfg = LMConfig(**MOE, moe_top_k=top_k, attention_impl="dense",
                   data_parallel=2, seq_parallel=1)
    tr = LMTrainer(cfg, mesh=mesh)
    tokens = synthetic_tokens(64, cfg.seq_len, cfg.vocab_size, seed=3)
    _, _, losses = tr.fit(tokens, steps=60)
    uniform = np.log(cfg.vocab_size)
    assert losses[-1] < 0.7 * uniform
    assert np.isfinite(losses).all()


@pytest.mark.slow
def test_expert_parallel_matches_local_experts():
    """EP over the data axis (all-to-all dispatch, sharded expert params)
    must match the identical model with every expert computed locally:
    same per-step losses, same post-step global params."""
    mesh = make_mesh({"data": 4, "seq": 1}, devices=jax.devices()[:4])
    tokens = synthetic_tokens(32, MOE["seq_len"], MOE["vocab_size"], seed=7)
    results = []
    for ep in (False, True):
        cfg = LMConfig(**MOE, attention_impl="dense", data_parallel=4,
                       seq_parallel=1, moe_expert_parallel=ep)
        tr = LMTrainer(cfg, mesh=mesh)
        params, opt_state = tr.init()
        losses = []
        for step in range(3):
            x, y = tr.shard_batch(tokens[step * 8 : step * 8 + 8])
            params, opt_state, m = tr.train_step(params, opt_state, x, y)
            losses.append(float(m["loss"]))
        results.append((losses, jax.device_get(params)))
    (l0, p0), (l1, p1) = results
    np.testing.assert_allclose(l0, l1, rtol=1e-5)
    # atol covers adamw-amplified reassociation noise: the scatter
    # dispatch (round-5 default) sums token rows in a different order
    # on the EP vs local path — a handful of elements land ~5e-5 apart
    # after 3 optimizer steps.
    jax.tree.map(
        lambda a, b: np.testing.assert_allclose(a, b, rtol=2e-4, atol=1e-4),
        p0,
        p1,
    )


@pytest.mark.parametrize("top_k,groups", [(1, 1), (2, 2)])
def test_scatter_dispatch_matches_einsum(top_k, groups):
    """The scatter-add/gather token movement (round 5) is numerically
    the einsum dispatch: same routing, priority, capacity and drops —
    outputs AND gradients (w.r.t. inputs and params) match to float
    tolerance."""
    x = jax.random.normal(jax.random.key(0), (2, 32, 24))

    def build(impl):
        return MoEFFN(
            num_experts=4, d_ff=32, top_k=top_k, num_groups=groups,
            capacity_factor=1.25, dispatch_impl=impl,
        )

    params = build("einsum").init(jax.random.key(1), x)["params"]

    outs, grads = {}, {}
    for impl in ("einsum", "scatter"):
        layer = build(impl)

        def loss(p, xx):
            y, _ = layer.apply(
                {"params": p}, xx, mutable=["losses", "metrics"]
            )
            return (y * jnp.sin(jnp.arange(y.size).reshape(y.shape))).sum()

        outs[impl] = layer.apply(
            {"params": params}, x, mutable=["losses", "metrics"]
        )[0]
        grads[impl] = jax.grad(loss, argnums=(0, 1))(params, x)
    np.testing.assert_allclose(
        np.asarray(outs["einsum"]), np.asarray(outs["scatter"]),
        rtol=1e-5, atol=1e-6,
    )
    jax.tree.map(
        lambda a, b: np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=1e-5, atol=1e-6
        ),
        grads["einsum"],
        grads["scatter"],
    )


@pytest.mark.slow
def test_scatter_dispatch_trains_and_composes_with_ep():
    """Trajectory parity einsum vs scatter through the LM engine, and
    scatter under expert parallelism (the all-to-all sees identical
    slot blocks either way)."""
    mesh = make_mesh({"data": 4, "seq": 1}, devices=jax.devices()[:4])
    tokens = synthetic_tokens(32, MOE["seq_len"], MOE["vocab_size"], seed=7)

    def run(dispatch, ep):
        cfg = LMConfig(**MOE, attention_impl="dense", data_parallel=4,
                       seq_parallel=1, moe_dispatch=dispatch,
                       moe_expert_parallel=ep)
        tr = LMTrainer(cfg, mesh=mesh)
        params, opt_state = tr.init()
        losses = []
        for step in range(3):
            x, y = tr.shard_batch(tokens[step * 8 : step * 8 + 8])
            params, opt_state, m = tr.train_step(params, opt_state, x, y)
            losses.append(float(m["loss"]))
        return losses

    base = run("einsum", ep=False)
    np.testing.assert_allclose(base, run("scatter", ep=False), rtol=1e-5)
    np.testing.assert_allclose(base, run("scatter", ep=True), rtol=1e-5)


@pytest.mark.slow
def test_expert_parallel_with_grad_clip():
    """grad_clip_norm under EP (round 5): the spec-aware clip psums
    each expert-sharded leaf's squared-sum over the data axis, so the
    EP trajectory with clipping still matches local experts clipped by
    plain optax (same global norm), and the clip demonstrably engages."""
    mesh = make_mesh({"data": 4, "seq": 1}, devices=jax.devices()[:4])
    tokens = synthetic_tokens(32, MOE["seq_len"], MOE["vocab_size"], seed=7)

    def run(ep, clip):
        cfg = LMConfig(**MOE, attention_impl="dense", data_parallel=4,
                       seq_parallel=1, moe_expert_parallel=ep,
                       grad_clip_norm=clip)
        tr = LMTrainer(cfg, mesh=mesh)
        params, opt_state = tr.init()
        losses = []
        for step in range(3):
            x, y = tr.shard_batch(tokens[step * 8 : step * 8 + 8])
            params, opt_state, m = tr.train_step(params, opt_state, x, y)
            losses.append(float(m["loss"]))
        return losses

    base = run(ep=False, clip=0.05)
    ep_clipped = run(ep=True, clip=0.05)
    np.testing.assert_allclose(base, ep_clipped, rtol=1e-5)
    unclipped = run(ep=True, clip=None)
    assert not np.allclose(ep_clipped[1:], unclipped[1:], rtol=1e-6), (
        "clip_norm=0.05 must actually change the EP trajectory"
    )


@pytest.mark.slow
def test_expert_parallel_with_seq_parallel():
    """EP composes with sequence parallelism on a data x seq mesh: the
    2x2 EP run must match the same model with local experts."""
    mesh = make_mesh({"data": 2, "seq": 2}, devices=jax.devices()[:4])
    tokens = synthetic_tokens(32, MOE["seq_len"], MOE["vocab_size"], seed=9)
    results = []
    for ep in (False, True):
        cfg = LMConfig(**MOE, attention_impl="ring", data_parallel=2,
                       seq_parallel=2, moe_expert_parallel=ep)
        tr = LMTrainer(cfg, mesh=mesh)
        params, opt_state = tr.init()
        for step in range(2):
            x, y = tr.shard_batch(tokens[step * 8 : step * 8 + 8])
            params, opt_state, m = tr.train_step(params, opt_state, x, y)
        results.append((float(m["loss"]), jax.device_get(params)))
    (l0, p0), (l1, p1) = results
    assert l0 == pytest.approx(l1, rel=1e-5)
    # atol 2e-4: Adam normalizes tiny einsum-reordering differences up to
    # ~lr-sized param deltas on near-tied routing decisions.
    jax.tree.map(
        lambda a, b: np.testing.assert_allclose(a, b, rtol=2e-4, atol=2e-4),
        p0,
        p1,
    )


@pytest.mark.slow
def test_expert_parallel_with_tensor_parallel():
    """EP composes with tensor parallelism on a data x tensor mesh:
    experts compute replicated over the tensor axis (Megatron shards the
    attention around them) and must match the local-experts run."""
    mesh = make_mesh({"data": 2, "seq": 1, "tensor": 2}, devices=jax.devices()[:4])
    tokens = synthetic_tokens(32, MOE["seq_len"], MOE["vocab_size"], seed=11)
    results = []
    for ep in (False, True):
        cfg = LMConfig(**MOE, attention_impl="dense", data_parallel=2,
                       seq_parallel=1, tensor_parallel=2,
                       moe_expert_parallel=ep)
        tr = LMTrainer(cfg, mesh=mesh)
        params, opt_state = tr.init()
        for step in range(2):
            x, y = tr.shard_batch(tokens[step * 8 : step * 8 + 8])
            params, opt_state, m = tr.train_step(params, opt_state, x, y)
        results.append((float(m["loss"]), jax.device_get(params)))
    (l0, p0), (l1, p1) = results
    assert l0 == pytest.approx(l1, rel=1e-5)
    jax.tree.map(
        lambda a, b: np.testing.assert_allclose(a, b, rtol=2e-4, atol=2e-4),
        p0,
        p1,
    )


def test_moe_param_shapes_global_vs_local():
    """Host init produces GLOBAL expert shapes; the EP partition specs
    shard the leading expert dim over the data axis."""
    mesh = make_mesh({"data": 4, "seq": 1}, devices=jax.devices()[:4])
    cfg = LMConfig(**MOE, attention_impl="dense", data_parallel=4,
                   moe_expert_parallel=True)
    tr = LMTrainer(cfg, mesh=mesh)
    params, _ = tr.init()
    w_in = params["block_0"]["moe"]["w_in"]
    assert w_in.shape == (4, MOE["d_model"], MOE["d_ff"])  # global
    # sharded over data: each device holds 1 expert
    shard_shapes = {s.data.shape for s in w_in.addressable_shards}
    assert shard_shapes == {(1, MOE["d_model"], MOE["d_ff"])}
    router = params["block_0"]["moe"]["router"]["kernel"]
    assert {s.data.shape for s in router.addressable_shards} == {
        router.shape
    }  # replicated


def test_moe_metrics_surfaced_in_fit_history():
    """VERDICT r3 #6: the router's load-balance aux term AND the
    capacity-overflow drop rate must be observable — per-step in the
    train metrics and accumulated in trainer.history."""
    from cs744_pytorch_distributed_tutorial_tpu.data import synthetic_tokens
    from cs744_pytorch_distributed_tutorial_tpu.parallel import make_mesh
    from cs744_pytorch_distributed_tutorial_tpu.train import LMConfig, LMTrainer

    cfg = LMConfig(
        vocab_size=64, num_layers=2, num_heads=4, d_model=32, d_ff=64,
        max_seq_len=64, seq_len=16, global_batch_size=4,
        attention_impl="dense", moe_experts=4,
        # Tight capacity so drops actually happen and the rate is
        # meaningfully nonzero.
        moe_capacity_factor=0.5,
    )
    tr = LMTrainer(cfg, mesh=make_mesh({"data": 1, "seq": 1},
                                       devices=jax.devices()[:1]))
    tokens = synthetic_tokens(8, 16, 64, seed=0)
    params, opt = tr.init()
    x, y = tr.shard_batch(tokens[:4])
    params, opt, m = tr.train_step(params, opt, x, y)
    # The obs/ telemetry PR widened the metrics dict: global grad/param
    # norms always (non-ZeRO layouts) + the router's load entropy.
    moe_keys = {"loss", "moe_aux", "moe_drop", "moe_load_entropy",
                "grad_norm", "param_norm"}
    assert set(m) == moe_keys
    aux, drop = float(m["moe_aux"]), float(m["moe_drop"])
    assert np.isfinite(aux) and aux > 0.0
    assert 0.0 < drop < 1.0, drop  # capacity 0.5 must drop something
    assert 0.0 <= float(m["moe_load_entropy"]) <= 1.0

    tr.fit(tokens, steps=3)
    assert set(tr.history) == moe_keys
    assert len(tr.history["moe_drop"]) == 3
    assert all(0.0 <= d <= 1.0 for d in tr.history["moe_drop"])
    assert all(0.0 <= e <= 1.0 for e in tr.history["moe_load_entropy"])

    # Dense models keep the non-MoE metrics shape — no silent key creep.
    dense = LMTrainer(cfg.replace(moe_experts=0),
                      mesh=make_mesh({"data": 1, "seq": 1},
                                     devices=jax.devices()[:1]))
    p2, o2 = dense.init()
    _, _, m2 = dense.train_step(p2, o2, x, y)
    assert set(m2) == {"loss", "grad_norm", "param_norm"}


def test_moe_token_groups():
    """Token grouping (GShard dispatch-cost lever): with capacity slack
    (cf large enough that nothing drops in either layout) grouping is a
    pure dispatch reorganization — outputs match the G=1 path; with
    tight capacity the semantics legitimately differ (capacity is per
    group) but stay finite and within [0,1] drop rate. Auto mode (0)
    picks ~1024-token groups."""
    from cs744_pytorch_distributed_tutorial_tpu.models.moe import MoEFFN

    x = jax.random.normal(jax.random.key(0), (4, 64, 32))  # N=256
    kw = dict(num_experts=4, d_ff=64, top_k=2, capacity_factor=4.0)
    m1 = MoEFFN(**kw, num_groups=1)
    params = m1.init(jax.random.key(1), x)["params"]
    y1 = m1.apply({"params": params}, x)
    m4 = MoEFFN(**kw, num_groups=4)
    y4 = m4.apply({"params": params}, x)  # same params: grouping is
    np.testing.assert_allclose(                  # not a param change
        np.asarray(y1), np.asarray(y4), rtol=2e-5, atol=2e-5
    )

    # Auto grouping resolves to a divisor of N.
    m0 = MoEFFN(**kw, num_groups=0)
    y0 = m0.apply({"params": params}, x)
    assert np.isfinite(np.asarray(y0)).all()

    # Non-divisor requests degrade to the largest divisor <= requested
    # (decode calls N as small as 1 token through train-configured
    # groups); the output stays finite and the extreme g=N degenerates
    # to per-token groups without error.
    m3 = MoEFFN(**kw, num_groups=3)  # 3 -> effective 2 for N=256? no:
    y3 = m3.apply({"params": params}, x)  # largest divisor of 256 <= 3 = 2
    assert np.isfinite(np.asarray(y3)).all()
    single = MoEFFN(**kw, num_groups=1)
    y_one_tok = single.apply(
        {"params": params}, x[:1, :1, :]
    )  # N=1: any group request must degrade to 1
    assert y_one_tok.shape == (1, 1, 32)
