"""ZeRO-1 AdamW on the LM engine (parallel/zero.py::Zero1Adam,
LMConfig.zero1 — round 4).

The round-3 ZeRO story lived on the CIFAR engine (SGD) and, since early
round 4, as dryrun scaffolding over raw LM params; this makes it a
first-class LM trainer feature with the optimizer LM users actually
run. The load-bearing property: chunk-wise AdamW over data-sharded
moments is EXACTLY the replicated optimizer up to float reassociation —
the trajectory must match — while the moment arrays per device shrink
by the data-parallel factor.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from cs744_pytorch_distributed_tutorial_tpu.data import synthetic_tokens
from cs744_pytorch_distributed_tutorial_tpu.parallel import make_mesh
from cs744_pytorch_distributed_tutorial_tpu.train import LMConfig, LMTrainer


def _cfg(**kw) -> LMConfig:
    base = dict(
        vocab_size=64,
        num_layers=2,
        num_heads=4,
        d_model=32,
        d_ff=64,
        max_seq_len=64,
        seq_len=16,
        global_batch_size=8,
        attention_impl="dense",
        use_rope=True,
        learning_rate=3e-3,
        lr_schedule="warmup_cosine",
        warmup_steps=2,
        total_steps=8,
        optimizer="adamw",
    )
    base.update(kw)
    return LMConfig(**base)


def _run(cfg, mesh, steps=6):
    tr = LMTrainer(cfg, mesh=mesh)
    params, opt = tr.init()
    tokens = synthetic_tokens(8, 16, 64, seed=0)
    x, y = tr.shard_batch(tokens)
    losses = []
    for s in range(steps):
        params, opt, m = tr.train_step(params, opt, x, y, s)
        losses.append(float(m["loss"]))
    jax.block_until_ready((params, opt))
    return tr, params, opt, losses


@pytest.mark.slow
def test_zero1_trajectory_matches_replicated_adamw():
    """dp=4: the sharded-moment trajectory IS the replicated adamw
    trajectory (same schedule, bias correction, decoupled decay)."""
    mesh = make_mesh({"data": 4, "seq": 1}, devices=jax.devices()[:4])
    _, _, _, base = _run(_cfg(data_parallel=4), mesh)
    _, _, _, z1 = _run(_cfg(data_parallel=4, zero1=True), mesh)
    np.testing.assert_allclose(base, z1, rtol=2e-5)


@pytest.mark.slow
def test_zero1_composes_with_seq_and_scan_and_accum():
    """dp2 x sp2 with scan_layers and accumulation: the seq pmean runs
    on the chunk, scan-stacked leaves chunk like any other, and the
    accumulated raw grads feed the scatter — trajectory still matches
    the replicated optimizer."""
    mesh = make_mesh({"data": 2, "seq": 2}, devices=jax.devices()[:4])
    kw = dict(
        data_parallel=2, seq_parallel=2, attention_impl="ring",
        scan_layers=True, accum_steps=2,
    )
    _, _, _, base = _run(_cfg(**kw), mesh)
    _, _, _, z1 = _run(_cfg(**kw, zero1=True), mesh)
    np.testing.assert_allclose(base, z1, rtol=2e-5)


def test_zero1_moments_are_sharded():
    """The memory claim, structurally: every moment leaf is a global
    [dp, chunk] array sharded over the data axis (per-device bytes =
    leaf/dp), not a replicated param-shaped copy."""
    from jax.sharding import PartitionSpec as P

    mesh = make_mesh({"data": 4, "seq": 1}, devices=jax.devices()[:4])
    tr, params, opt, _ = _run(_cfg(data_parallel=4, zero1=True), mesh, steps=1)
    for coll in ("mu", "nu"):
        for leaf, p in zip(
            jax.tree.leaves(opt[coll]), jax.tree.leaves(params)
        ):
            assert leaf.shape[0] == 4
            assert leaf.shape[0] * leaf.shape[1] >= p.size
            # Normalize trailing Nones (P('data') == P('data', None)).
            assert tuple(leaf.sharding.spec)[:1] == ("data",)
    assert int(opt["count"]) == 1


def test_zero1_rejections():
    """What remains rejected after the round-5 compositions: unknown
    optimizer strings (friendly error, not a KeyError). Expert
    parallelism composes since late round 5 —
    test_zero_expert_parallel_trajectory_matches_replicated."""
    mesh = make_mesh({"data": 2, "seq": 1}, devices=jax.devices()[:2])
    with pytest.raises(ValueError, match="unknown optimizer"):
        LMTrainer(_cfg(data_parallel=2, zero1=True, optimizer="adam"),
                  mesh=mesh)


@pytest.mark.parametrize("opt", ["lion", "sgd"])
@pytest.mark.slow
def test_zero1_lion_sgd_trajectory_matches_replicated(opt):
    """Round 5: zero1 carries all three registry rules chunk-wise —
    lion (ONE sharded moment: Lion's halved state stacks with the
    ZeRO sharding) and torch-chain sgd match their replicated optax
    trajectories, here composed with tp2 + clipping so the chunk
    layout and the exact-norm clip run under the non-adamw rules
    too."""
    mesh = make_mesh({"data": 2, "seq": 1, "tensor": 2},
                     devices=jax.devices()[:4])
    kw = dict(data_parallel=2, tensor_parallel=2, optimizer=opt,
              grad_clip_norm=0.05, learning_rate=1e-3)
    _, _, _, base = _run(_cfg(**kw), mesh)
    tr, _, z_opt, z1 = _run(_cfg(**kw, zero1=True), mesh)
    np.testing.assert_allclose(base, z1, rtol=2e-5)
    # Single-moment rules carry ONE sharded collection, not two.
    assert set(z_opt) == {"mu", "count"}


@pytest.mark.parametrize("opt", ["lion", "sgd"])
@pytest.mark.slow
def test_fsdp_lion_sgd_trajectory_matches_replicated(opt):
    """FSDP runs the same rule family (MRO composition FsdpLion /
    FsdpSgdLM): chunked params + single-moment state still match the
    replicated optax trajectory, and decode unshards."""
    mesh = make_mesh({"data": 2, "seq": 1}, devices=jax.devices()[:2])
    kw = dict(data_parallel=2, optimizer=opt, learning_rate=1e-3)
    _, _, _, base = _run(_cfg(**kw), mesh)
    tr, params, f_opt, f = _run(_cfg(**kw, fsdp=True), mesh)
    np.testing.assert_allclose(base, f, rtol=2e-5)
    assert set(f_opt) == {"mu", "count"}
    host = tr.gather_for_decode(params)
    toks = jnp.asarray(
        synthetic_tokens(2, 16, 64, seed=3)[:, :16], jnp.int32
    )
    logits = tr.decode_model().apply({"params": host}, toks)
    assert np.isfinite(np.asarray(logits)).all()


# --------------------------------------------------------------------------
# ZeRO x tensor parallelism + global-norm clipping (round 5)
# --------------------------------------------------------------------------
@pytest.mark.slow
def test_zero1_tp_trajectory_matches_replicated():
    """dp2 x tp2: tensor-sharded leaves chunk their LOCAL shard per
    (data, tensor) coordinate — the trajectory still IS the replicated
    optimizer's on the same mesh (VERDICT r4 #1's done-criterion)."""
    mesh = make_mesh({"data": 2, "seq": 1, "tensor": 2},
                     devices=jax.devices()[:4])
    kw = dict(data_parallel=2, tensor_parallel=2)
    _, _, _, base = _run(_cfg(**kw), mesh)
    _, _, _, z1 = _run(_cfg(**kw, zero1=True), mesh)
    np.testing.assert_allclose(base, z1, rtol=2e-5)


def test_zero1_tp_moment_layout():
    """Tensor-sharded leaves' moments are [dp, tp, chunk] sharded over
    (data, tensor); replicated leaves keep [dp, chunk] over data —
    per-device optimizer bytes = local_leaf/dp either way."""
    mesh = make_mesh({"data": 2, "seq": 1, "tensor": 2},
                     devices=jax.devices()[:4])
    tr, params, opt, _ = _run(
        _cfg(data_parallel=2, tensor_parallel=2, zero1=True), mesh, steps=1
    )
    mu = opt["mu"]
    q = mu["block_0"]["attn"]["q"]["kernel"]
    assert q.ndim == 3 and q.shape[:2] == (2, 2)
    assert tuple(q.sharding.spec)[:2] == ("data", "tensor")
    ln = mu["ln_f"]["scale"]
    assert ln.ndim == 2 and ln.shape[0] == 2
    assert tuple(ln.sharding.spec)[:1] == ("data",)


@pytest.mark.slow
def test_zero_clip_matches_replicated_clip():
    """zero1 + grad_clip_norm: the chunked path computes the EXACT
    global norm (one psum of per-chunk squared sums) — trajectory
    parity vs replicated adamw+clip (VERDICT r4 #2's done-criterion),
    and the clip demonstrably engages (differs from unclipped)."""
    mesh = make_mesh({"data": 4, "seq": 1}, devices=jax.devices()[:4])
    kw = dict(data_parallel=4, grad_clip_norm=0.05)
    _, _, _, base = _run(_cfg(**kw), mesh)
    _, _, _, z1 = _run(_cfg(**kw, zero1=True), mesh)
    np.testing.assert_allclose(base, z1, rtol=2e-5)
    _, _, _, unclipped = _run(_cfg(data_parallel=4, zero1=True), mesh)
    assert not np.allclose(z1[1:], unclipped[1:], rtol=1e-6), (
        "clip_norm=0.05 must actually change the trajectory"
    )


@pytest.mark.slow
def test_fsdp_tp_trajectory_and_decode():
    """dp2 x tp2 FSDP: chunked-per-(data,tensor) params gather to the
    LOCAL tensor shard inside the step; trajectory matches the
    replicated optimizer, clip composes, and unshard_host reassembles
    tensor-sharded leaves for decode (logit parity vs the replicated
    run)."""
    mesh = make_mesh({"data": 2, "seq": 1, "tensor": 2},
                     devices=jax.devices()[:4])
    kw = dict(data_parallel=2, tensor_parallel=2)
    _, _, _, base = _run(_cfg(**kw), mesh)
    tr_f, params_f, _, f = _run(_cfg(**kw, fsdp=True), mesh)
    np.testing.assert_allclose(base, f, rtol=2e-5)

    _, _, _, base_c = _run(_cfg(**kw, grad_clip_norm=0.05), mesh)
    _, _, _, f_c = _run(_cfg(**kw, fsdp=True, grad_clip_norm=0.05), mesh)
    np.testing.assert_allclose(base_c, f_c, rtol=2e-5)

    tr_b, params_b, _, _ = _run(_cfg(**kw), mesh, steps=6)
    host = tr_f.gather_for_decode(params_f)
    toks = jnp.asarray(
        synthetic_tokens(2, 16, 64, seed=3)[:, :16], jnp.int32
    )
    got = tr_f.decode_model().apply({"params": host}, toks)
    want = tr_b.decode_model().apply(
        {"params": tr_b.gather_for_decode(params_b)}, toks
    )
    np.testing.assert_allclose(
        np.asarray(got), np.asarray(want), rtol=2e-4, atol=2e-4
    )


@pytest.mark.slow
def test_zero_full_matrix_dp_sp_tp():
    """The whole composition at once — dp2 x sp2 x tp2 with ring
    attention, scan_layers, accumulation AND clipping, zero1 vs the
    replicated optimizer on the same 8-device mesh. Every chunk-layout
    branch (scanned tensor-sharded leaves chunk locally, seq pmean on
    chunks, clip psum over (data, tensor)) fires in one trajectory."""
    mesh = make_mesh({"data": 2, "seq": 2, "tensor": 2},
                     devices=jax.devices()[:8])
    kw = dict(
        data_parallel=2, seq_parallel=2, tensor_parallel=2,
        attention_impl="ring", scan_layers=True, accum_steps=2,
        grad_clip_norm=0.05,
    )
    _, _, _, base = _run(_cfg(**kw), mesh)
    _, _, _, z1 = _run(_cfg(**kw, zero1=True), mesh)
    np.testing.assert_allclose(base, z1, rtol=2e-5)
    _, _, _, f = _run(_cfg(**kw, fsdp=True), mesh)
    np.testing.assert_allclose(base, f, rtol=2e-5)


@pytest.mark.parametrize("dp_save,dp_resume", [(4, 2), (2, 4)])
@pytest.mark.slow
def test_zero1_elastic_resume(tmp_path, dp_save, dp_resume):
    """Mesh-elastic ZeRO resume (VERDICT r4 #4): save at dp_save,
    resume at dp_resume — the restore re-chunks [dp_old, c_old] flat
    state to [dp_new, c_new] and the trajectory matches the
    UNINTERRUPTED dp_save run at rtol 1e-6 (chunking is layout, not
    math)."""
    tokens = synthetic_tokens(8, 16, 64, seed=0)
    mesh_a = make_mesh({"data": dp_save, "seq": 1},
                       devices=jax.devices()[:dp_save])
    mesh_b = make_mesh({"data": dp_resume, "seq": 1},
                       devices=jax.devices()[:dp_resume])
    ckdir = str(tmp_path / "ck")
    tr = LMTrainer(
        _cfg(data_parallel=dp_save, zero1=True, checkpoint_dir=ckdir,
             checkpoint_every=2),
        mesh=mesh_a,
    )
    _, _, head = tr.fit(tokens, steps=4)
    tr2 = LMTrainer(
        _cfg(data_parallel=dp_resume, zero1=True, checkpoint_dir=ckdir,
             checkpoint_every=2),
        mesh=mesh_b,
    )
    _, _, tail = tr2.fit(tokens, steps=6)
    assert len(tail) == 2, tail
    oracle = LMTrainer(_cfg(data_parallel=dp_save, zero1=True), mesh=mesh_a)
    _, _, full = oracle.fit(tokens, steps=6)
    np.testing.assert_allclose(head + tail, full, rtol=1e-6)


@pytest.mark.slow
def test_elastic_resume_rejects_model_shape_change(tmp_path):
    """The elastic re-chunk only bends over data_parallel: resuming a
    zero1 checkpoint with a CHANGED model shape (stale flat chunks)
    must fail loudly, not silently slice old state."""
    tokens = synthetic_tokens(8, 16, 64, seed=0)
    mesh = make_mesh({"data": 2, "seq": 1}, devices=jax.devices()[:2])
    ckdir = str(tmp_path / "ck")
    tr = LMTrainer(
        _cfg(data_parallel=2, zero1=True, checkpoint_dir=ckdir,
             checkpoint_every=2),
        mesh=mesh,
    )
    tr.fit(tokens, steps=2)
    bigger = LMTrainer(
        _cfg(data_parallel=2, zero1=True, d_ff=128,
             checkpoint_dir=ckdir),
        mesh=mesh,
    )
    with pytest.raises(ValueError, match="model shape|cannot adapt"):
        bigger.fit(tokens, steps=4)


@pytest.mark.slow
def test_fsdp_elastic_resume_with_tp(tmp_path):
    """FSDP chunked PARAMS re-chunk too, and the tensor coordinate
    (middle axis) rides along untouched: save on dp2 x tp2, resume on
    dp4 x tp2 (8 devices) — trajectory matches the uninterrupted run."""
    tokens = synthetic_tokens(8, 16, 64, seed=0)
    mesh_a = make_mesh({"data": 2, "seq": 1, "tensor": 2},
                       devices=jax.devices()[:4])
    mesh_b = make_mesh({"data": 4, "seq": 1, "tensor": 2},
                       devices=jax.devices()[:8])
    ckdir = str(tmp_path / "ck")
    kw = dict(tensor_parallel=2, fsdp=True, checkpoint_dir=ckdir,
              checkpoint_every=2)
    tr = LMTrainer(_cfg(data_parallel=2, **kw), mesh=mesh_a)
    _, _, head = tr.fit(tokens, steps=4)
    tr2 = LMTrainer(_cfg(data_parallel=4, **kw), mesh=mesh_b)
    _, _, tail = tr2.fit(tokens, steps=6)
    assert len(tail) == 2, tail
    oracle = LMTrainer(
        _cfg(data_parallel=2, tensor_parallel=2, fsdp=True), mesh=mesh_a
    )
    _, _, full = oracle.fit(tokens, steps=6)
    np.testing.assert_allclose(head + tail, full, rtol=1e-6)


@pytest.mark.slow
def test_sharded_clip_matches_single_device_optax_clip():
    """The replicated-optimizer path under TP now clips via the
    spec-aware transform (train/state.py::clip_by_global_norm_sharded):
    dp2 x tp2 + clip matches the single-device optax.clip trajectory
    (same global batch), closing the old clip x TP rejection."""
    mesh1 = make_mesh({"data": 1, "seq": 1}, devices=jax.devices()[:1])
    mesh = make_mesh({"data": 2, "seq": 1, "tensor": 2},
                     devices=jax.devices()[:4])
    _, _, _, base = _run(_cfg(grad_clip_norm=0.05), mesh1)
    _, _, _, tp = _run(
        _cfg(data_parallel=2, tensor_parallel=2, grad_clip_norm=0.05), mesh
    )
    np.testing.assert_allclose(base, tp, rtol=1e-4)


@pytest.mark.slow
def test_zero1_checkpoint_resume(tmp_path):
    """Orbax save/restore round-trips the chunked state: an interrupted
    zero1 run resumes to the identical trajectory."""
    mesh = make_mesh({"data": 2, "seq": 1}, devices=jax.devices()[:2])
    cfg = _cfg(
        data_parallel=2, zero1=True,
        checkpoint_dir=str(tmp_path / "ck"), checkpoint_every=2,
    )
    tokens = synthetic_tokens(8, 16, 64, seed=0)
    tr = LMTrainer(cfg, mesh=mesh)
    _, _, head = tr.fit(tokens, steps=4)
    tr2 = LMTrainer(cfg, mesh=mesh)
    # Fresh trainer, same dir: restores the step-4 checkpoint and
    # replays only steps 4-5.
    _, _, tail = tr2.fit(tokens, steps=6)
    assert len(tail) == 2, tail
    # Oracle: one uninterrupted 6-step run (no checkpointing).
    oracle = LMTrainer(cfg.replace(checkpoint_dir=None), mesh=mesh)
    _, _, full = oracle.fit(tokens, steps=6)
    np.testing.assert_allclose(head + tail, full, rtol=1e-6)


# --------------------------------------------------------------------------
# ZeRO-3 / FSDP (FsdpAdam, LMConfig.fsdp)
# --------------------------------------------------------------------------
@pytest.mark.slow
def test_fsdp_trajectory_matches_replicated_adamw():
    """dp=4: gather-just-in-time + chunk AdamW IS the replicated
    trajectory (the unshard/scatter pair is numerically transparent)."""
    mesh = make_mesh({"data": 4, "seq": 1}, devices=jax.devices()[:4])
    _, _, _, base = _run(_cfg(data_parallel=4), mesh)
    _, _, _, f = _run(_cfg(data_parallel=4, fsdp=True), mesh)
    np.testing.assert_allclose(base, f, rtol=2e-5)


@pytest.mark.slow
def test_fsdp_params_are_sharded_and_decode_roundtrips():
    """Params persist as [dp, chunk] data-sharded arrays; the decode
    path unshards them to logits that match the replicated run's."""
    mesh = make_mesh({"data": 2, "seq": 1}, devices=jax.devices()[:2])
    tr, params, opt, _ = _run(_cfg(data_parallel=2, fsdp=True), mesh,
                              steps=2)
    for leaf in jax.tree.leaves(params):
        assert leaf.ndim == 2 and leaf.shape[0] == 2
        assert tuple(leaf.sharding.spec)[:1] == ("data",)
    for coll in ("mu", "nu"):
        for leaf in jax.tree.leaves(opt[coll]):
            assert leaf.shape[0] == 2

    # The replicated oracle reaches the same params after 2 steps;
    # unsharded decode logits must match its logits.
    tr_b, params_b, _, _ = _run(_cfg(data_parallel=2), mesh, steps=2)
    host = tr.gather_for_decode(params)
    toks = jnp.asarray(
        synthetic_tokens(2, 16, 64, seed=3)[:, :16], jnp.int32
    )
    got = tr.decode_model().apply({"params": host}, toks)
    want = tr_b.decode_model().apply(
        {"params": jax.device_get(params_b)}, toks
    )
    np.testing.assert_allclose(
        np.asarray(got), np.asarray(want), rtol=2e-4, atol=2e-4
    )


@pytest.mark.slow
def test_fsdp_composes_with_seq_scan_accum_and_resumes(tmp_path):
    """dp2 x sp2 + scan_layers + accumulation, with an interrupted run
    resuming mid-trajectory — all on chunked params."""
    mesh = make_mesh({"data": 2, "seq": 2}, devices=jax.devices()[:4])
    kw = dict(
        data_parallel=2, seq_parallel=2, attention_impl="ring",
        scan_layers=True, accum_steps=2, fsdp=True,
    )
    _, _, _, base = _run(
        _cfg(**{**kw, "fsdp": False}), mesh
    )
    _, _, _, f = _run(_cfg(**kw), mesh)
    np.testing.assert_allclose(base, f, rtol=2e-5)

    cfg = _cfg(**kw, checkpoint_dir=str(tmp_path / "ck"),
               checkpoint_every=2)
    tokens = synthetic_tokens(8, 16, 64, seed=0)
    tr = LMTrainer(cfg, mesh=mesh)
    _, _, head = tr.fit(tokens, steps=4)
    tr2 = LMTrainer(cfg, mesh=mesh)
    _, _, tail = tr2.fit(tokens, steps=6)
    assert len(tail) == 2
    oracle = LMTrainer(cfg.replace(checkpoint_dir=None), mesh=mesh)
    _, _, full = oracle.fit(tokens, steps=6)
    np.testing.assert_allclose(head + tail, full, rtol=1e-6)


def test_fsdp_zero1_mutually_exclusive():
    mesh = make_mesh({"data": 2, "seq": 1}, devices=jax.devices()[:2])
    with pytest.raises(ValueError, match="mutually exclusive"):
        LMTrainer(_cfg(data_parallel=2, zero1=True, fsdp=True), mesh=mesh)


# ---------------------------------------------------------------------------
# ZeRO x expert parallelism (late round 5 — the last ZeRO rejection
# removed): EP-over-DP expert leaves are ALREADY data-sharded, so their
# optimizer state stays local at natural shapes (memory divided by
# construction, zero collectives in their update); everything else
# chunks as before.
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("mode", ["zero1", "fsdp"])
@pytest.mark.slow
def test_zero_expert_parallel_trajectory_matches_replicated(mode):
    """dp4 + EP(moe) + clip: the mixed layout (chunked replicated
    leaves, natural-local expert leaves) IS the replicated optimizer —
    including the exact global-norm clip spanning both leaf kinds."""
    mesh = make_mesh({"data": 4, "seq": 1}, devices=jax.devices()[:4])
    kw = dict(
        data_parallel=4, moe_experts=4, moe_capacity_factor=2.0,
        moe_expert_parallel=True, grad_clip_norm=0.05,
    )
    _, _, _, base = _run(_cfg(**kw), mesh)
    tr, params, opt, z = _run(
        _cfg(**kw, zero1=(mode == "zero1"), fsdp=(mode == "fsdp")), mesh
    )
    np.testing.assert_allclose(base, z, rtol=2e-5)
    # Layout of the memory claim: expert moments keep the PARAM's
    # natural shape sharded over data; replicated leaves chunk
    # [dp, chunk]; fsdp expert PARAMS stay natural too.
    moe_mu = opt["mu"]["block_0"]["moe"]["w_in"]
    assert moe_mu.ndim == 3 and moe_mu.shape[0] == 4  # [E, D, F]
    assert tuple(moe_mu.sharding.spec)[:1] == ("data",)
    ln_mu = opt["mu"]["ln_f"]["scale"]
    assert ln_mu.ndim == 2 and ln_mu.shape[0] == 4  # [dp, chunk]
    if mode == "fsdp":
        moe_p = params["block_0"]["moe"]["w_in"]
        assert moe_p.ndim == 3 and moe_p.shape[0] == 4
        # decode unshard reassembles global expert arrays
        host = tr.gather_for_decode(params)
        assert host["block_0"]["moe"]["w_in"].shape == (4, 32, 64)


@pytest.mark.slow
def test_zero1_expert_parallel_resume(tmp_path):
    """Mixed-layout checkpoint resume under zero1+EP. Same-dp resume is
    EXACT (chunked leaves plus natural expert moments restore placed on
    their shardings — the restore-placement fix this test pinned: an
    uncommitted host leaf let jit's donation pairing alias a chunked
    input to a different-sharded output and crash). Cross-dp elastic
    resume is exercised only MECHANICALLY: EP computes capacity from
    LOCAL token counts, so changing dp changes routing semantics and
    the trajectory legitimately diverges from the saved-dp oracle —
    the assertion is that the re-chunk/re-shard restore runs and
    training continues finite."""
    kw = dict(
        moe_experts=4, moe_capacity_factor=2.0, moe_expert_parallel=True,
        zero1=True, checkpoint_dir=str(tmp_path / "ck"),
        checkpoint_every=2,
    )
    mesh4 = make_mesh({"data": 4, "seq": 1}, devices=jax.devices()[:4])
    tokens = synthetic_tokens(8, 16, 64, seed=0)
    tr = LMTrainer(_cfg(data_parallel=4, **kw), mesh=mesh4)
    _, _, head = tr.fit(tokens, steps=4)
    oracle = LMTrainer(
        _cfg(data_parallel=4, **{**kw, "checkpoint_dir": None}), mesh=mesh4
    )
    _, _, full = oracle.fit(tokens, steps=6)
    # Exact same-dp resume.
    tr_same = LMTrainer(_cfg(data_parallel=4, **kw), mesh=mesh4)
    _, _, tail_same = tr_same.fit(tokens, steps=6)
    assert len(tail_same) == 2, tail_same
    np.testing.assert_allclose(head + tail_same, full, rtol=1e-6)
    # Mechanical cross-dp restore (different routing semantics) — from
    # a fresh step-4 save (the run above already saved its step 6).
    kw_e = {**kw, "checkpoint_dir": str(tmp_path / "ck_elastic")}
    tr_h = LMTrainer(_cfg(data_parallel=4, **kw_e), mesh=mesh4)
    tr_h.fit(tokens, steps=4)
    mesh2 = make_mesh({"data": 2, "seq": 1}, devices=jax.devices()[:2])
    tr2 = LMTrainer(_cfg(data_parallel=2, **kw_e), mesh=mesh2)
    _, _, tail = tr2.fit(tokens, steps=6)
    assert len(tail) == 2 and np.isfinite(tail).all(), tail
