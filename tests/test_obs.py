"""The obs/ telemetry subsystem: sinks, manifest, helpers, and e2e
runs of both engines writing real metric streams.

CPU-only (conftest forces 8 virtual devices); the e2e tests exercise
the same `--metrics-dir` path a TPU run uses.
"""

import json
import logging
import math
import time

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from cs744_pytorch_distributed_tutorial_tpu.obs.metrics import (
    Telemetry,
    expert_load_entropy,
    speculative_accept_rate,
    tree_l2_norm,
)
from cs744_pytorch_distributed_tutorial_tpu.obs.run_manifest import (
    read_manifest,
    write_manifest,
)
from cs744_pytorch_distributed_tutorial_tpu.obs.sinks import (
    CsvSink,
    JsonlSink,
    RingSink,
    sanitize,
)


# ---------------------------------------------------------------------------
# Sinks
# ---------------------------------------------------------------------------


def test_jsonl_round_trip_sanitizes_nonfinite(tmp_path):
    path = str(tmp_path / "m.jsonl")
    sink = JsonlSink(path)
    sink.emit({"kind": "step", "step": 0, "loss": 1.5})
    sink.emit({"kind": "step", "step": 1, "loss": float("nan"),
               "extra": float("inf")})
    sink.emit({"kind": "step", "step": 2, "loss": jnp.float32(0.25)})
    sink.close()
    recs = [json.loads(line) for line in open(path)]
    assert [r["step"] for r in recs] == [0, 1, 2]
    assert recs[0]["loss"] == 1.5
    # NaN/inf must land as JSON null, not corrupt the stream.
    assert recs[1]["loss"] is None and recs[1]["extra"] is None
    # jax 0-d scalars coerce to plain floats.
    assert recs[2]["loss"] == 0.25


def test_csv_header_frozen_at_first_record(tmp_path):
    path = str(tmp_path / "m.csv")
    sink = CsvSink(path)
    sink.emit({"step": 0, "loss": 1.0})
    sink.emit({"step": 1, "loss": 2.0, "surprise": 9.9})  # extra key dropped
    sink.emit({"step": 2})  # missing key -> empty cell
    sink.close()
    lines = open(path).read().splitlines()
    assert lines[0] == "step,loss"
    assert lines[1] == "0,1.0"
    assert lines[2] == "1,2.0"  # 'surprise' did not widen the file
    assert lines[3] == "2,"


def test_ring_evicts_oldest():
    ring = RingSink(capacity=3)
    for i in range(5):
        ring.emit({"step": i})
    assert len(ring) == 3
    assert [r["step"] for r in ring.records()] == [2, 3, 4]
    assert [r["step"] for r in ring.tail(2)] == [3, 4]


def test_sanitize_stringifies_unknown_objects():
    out = sanitize({"a": object(), "b": None, "c": True})
    assert isinstance(out["a"], str)
    assert out["b"] is None and out["c"] is True


# ---------------------------------------------------------------------------
# Manifest
# ---------------------------------------------------------------------------


def test_manifest_write_read(tmp_path):
    from cs744_pytorch_distributed_tutorial_tpu.config import TrainConfig
    from cs744_pytorch_distributed_tutorial_tpu.parallel import make_mesh

    cfg = TrainConfig(num_devices=2, synthetic_data=True)
    mesh = make_mesh({"data": 2})
    path = write_manifest(str(tmp_path), config=cfg, mesh=mesh, extra_key=7)
    man = read_manifest(str(tmp_path))  # dir or file path both accepted
    assert man == read_manifest(path)
    assert man["kind"] == "manifest"
    assert man["mesh"] == {"data": 2}
    assert man["config"]["num_devices"] == 2
    assert man["device_count"] == jax.device_count()
    assert man["jax_version"] == jax.__version__
    assert man["extra_key"] == 7


# ---------------------------------------------------------------------------
# In-graph / host helpers
# ---------------------------------------------------------------------------


def test_tree_l2_norm_matches_numpy():
    tree = {"a": jnp.arange(4, dtype=jnp.float32),
            "b": {"c": jnp.full((2, 2), 2.0)}}
    flat = np.concatenate([np.arange(4, dtype=np.float32), np.full(4, 2.0)])
    assert float(tree_l2_norm(tree)) == pytest.approx(
        float(np.linalg.norm(flat)), rel=1e-6
    )


def test_expert_load_entropy_bounds():
    uniform = jnp.full((8,), 1.0 / 8)
    collapsed = jnp.array([1.0] + [0.0] * 7)
    assert float(expert_load_entropy(uniform)) == pytest.approx(1.0, abs=1e-5)
    assert float(expert_load_entropy(collapsed)) == pytest.approx(0.0, abs=1e-4)
    assert float(expert_load_entropy(jnp.ones((1,)))) == 1.0  # degenerate E=1


def test_speculative_accept_rate():
    # 64 tokens from 16 calls at k=4: (64/16 - 1)/4 = 0.75
    assert speculative_accept_rate(64, 16, 4) == pytest.approx(0.75)
    # every call accepted everything -> clamped to 1.0
    assert speculative_accept_rate(100, 10, 4) == 1.0
    assert speculative_accept_rate(10, 0, 4) is None
    assert speculative_accept_rate(10, 10, 0) is None


def test_telemetry_amortized_step_time_and_ring(tmp_path):
    t = Telemetry(str(tmp_path), every=2, run="unit")
    assert t.due(0) and not t.due(1) and t.due(2)
    t.emit_step(0, loss=1.0)
    time.sleep(0.02)
    t.emit_step(2, loss=0.5)
    t.close()
    recs = [json.loads(line) for line in open(str(tmp_path / "metrics.jsonl"))]
    steps = [r for r in recs if r["kind"] == "step"]
    assert steps[0]["step_time_s"] is None  # nothing to amortize over yet
    # 2 steps elapsed between emissions -> per-step time is half the gap.
    assert 0.005 < steps[1]["step_time_s"] < 10.0
    assert len(t.ring) >= 2  # the ring mirrors every record


# ---------------------------------------------------------------------------
# Satellite: per-record [proc i/n] prefix
# ---------------------------------------------------------------------------


def test_logger_prefix_computed_per_record(monkeypatch):
    import io

    from cs744_pytorch_distributed_tutorial_tpu.utils.logging import get_logger

    logger = get_logger("cs744_tpu_obs_prefix_test")
    stream = io.StringIO()
    handler = logger.handlers[0]
    old_stream = handler.stream
    handler.stream = stream
    try:
        logger.info("single")
        # "jax.distributed initializes" AFTER the logger exists — the
        # prefix must pick up the new world size on the next record.
        monkeypatch.setattr(jax, "process_count", lambda: 4)
        monkeypatch.setattr(jax, "process_index", lambda: 2)
        logger.info("multi")
    finally:
        handler.stream = old_stream
    lines = stream.getvalue().splitlines()
    assert lines[0] == "single"
    assert lines[1] == "[proc 2/4] multi"


# ---------------------------------------------------------------------------
# Satellite: watchdog flushes the metric ring on firing
# ---------------------------------------------------------------------------


def test_watchdog_flushes_metric_ring():
    from cs744_pytorch_distributed_tutorial_tpu.utils.failure import (
        StepWatchdog,
    )
    from cs744_pytorch_distributed_tutorial_tpu.utils.logging import get_logger

    ring = RingSink(capacity=8)
    for i in range(3):
        ring.emit({"kind": "step", "step": i, "loss": 1.0 / (i + 1)})

    records: list[logging.LogRecord] = []

    class Capture(logging.Handler):
        def emit(self, record):
            records.append(record)

    logger = get_logger()
    cap = Capture()
    logger.addHandler(cap)
    try:
        wd = StepWatchdog(timeout_s=0.1, dump_stacks=False, metric_ring=ring)
        wd.arm()
        time.sleep(0.4)
        wd.disarm()
        wd.close()
    finally:
        logger.removeHandler(cap)
    assert wd.fired == 1
    text = "\n".join(r.getMessage() for r in records)
    assert "last 3 metric records" in text
    # The actual records appear in the report, parseable.
    assert '"step": 2' in text and '"loss"' in text


# ---------------------------------------------------------------------------
# E2E: CIFAR engine via the CLI
# ---------------------------------------------------------------------------


def _run_cifar_cli(metrics_dir, extra=()):
    from cs744_pytorch_distributed_tutorial_tpu.cli import main

    rc = main([
        "--sync", "allreduce", "--model", "tiny_cnn", "--num-devices", "2",
        "--global-batch-size", "16", "--epochs", "1", "--synthetic-data",
        "--synthetic-train-size", "80", "--synthetic-test-size", "16",
        "--log-every", "1", "--metrics-dir", str(metrics_dir), *extra,
    ])
    assert rc == 0
    path = metrics_dir / "metrics.jsonl"
    return [json.loads(line) for line in open(path)]


def test_cifar_cli_writes_manifest_and_step_stream(tmp_path):
    recs = _run_cifar_cli(tmp_path / "run")

    man = read_manifest(str(tmp_path / "run"))
    assert man["run"] == "cifar"
    assert man["config"]["model"] == "tiny_cnn"
    assert man["mesh"] == {"data": 2}
    assert man["grad_sync_bytes_per_step"] > 0

    steps = [r for r in recs if r["kind"] == "step"]
    assert len(steps) == 5  # 80 samples / batch 16, 1 epoch
    indices = [r["step"] for r in steps]
    assert indices == sorted(indices) and len(set(indices)) == len(indices)
    for r in steps:
        assert math.isfinite(r["loss"])
        assert math.isfinite(r["grad_norm"]) and r["grad_norm"] > 0
        assert math.isfinite(r["param_norm"]) and r["param_norm"] > 0
        assert r["grad_sync_bytes"] > 0
        assert r["lr"] > 0
    # step_time_s is amortized: null first, positive after.
    assert steps[0]["step_time_s"] is None
    assert all(s["step_time_s"] > 0 for s in steps[1:])
    # the epoch boundary feeds the DivergenceMonitor verdict + eval in.
    events = {r["event"] for r in recs if r["kind"] == "event"}
    assert "eval" in events


def test_int8_compression_shrinks_recorded_wire_bytes(tmp_path):
    f32 = _run_cifar_cli(tmp_path / "f32")
    int8 = _run_cifar_cli(tmp_path / "int8", extra=["--grad-compress", "int8"])
    f32_bytes = next(r["grad_sync_bytes"] for r in f32 if r["kind"] == "step")
    int8_bytes = next(
        r["grad_sync_bytes"] for r in int8 if r["kind"] == "step"
    )
    assert 0 < int8_bytes < f32_bytes
    # int8 payload + per-chunk f32 scales ≈ 3.9x smaller than f32.
    assert f32_bytes / int8_bytes > 3.0


# ---------------------------------------------------------------------------
# E2E: LM engine
# ---------------------------------------------------------------------------


def test_lm_fit_emits_metrics(tmp_path):
    from cs744_pytorch_distributed_tutorial_tpu.train.lm import (
        LMConfig,
        LMTrainer,
    )

    cfg = LMConfig(
        vocab_size=64, num_layers=1, num_heads=2, d_model=32, d_ff=64,
        max_seq_len=32, attention_impl="dense", data_parallel=2,
        global_batch_size=4, seq_len=16,
        metrics_dir=str(tmp_path), metrics_every=1,
    )
    tokens = np.random.default_rng(0).integers(
        0, 64, size=(16, 17), dtype=np.int32
    )
    LMTrainer(cfg).fit(tokens, steps=3)

    man = read_manifest(str(tmp_path))
    assert man["run"] == "lm" and man["n_params"] > 0
    steps = [
        json.loads(line)
        for line in open(str(tmp_path / "metrics.jsonl"))
    ]
    steps = [r for r in steps if r["kind"] == "step"]
    assert [r["step"] for r in steps] == [0, 1, 2]
    for r in steps:
        assert math.isfinite(r["loss"])
        assert math.isfinite(r["grad_norm"]) and r["grad_norm"] > 0
        assert r["grad_sync_bytes"] == man["grad_sync_bytes_per_step"] > 0


def test_lm_cli_rejects_metrics_dir_on_pipeline(tmp_path):
    from cs744_pytorch_distributed_tutorial_tpu.lm_cli import main

    with pytest.raises(SystemExit, match="metrics-dir"):
        main([
            "--pipeline-parallel", "2", "--steps", "1",
            "--metrics-dir", str(tmp_path),
        ])


# ---------------------------------------------------------------------------
# benchmarks/metrics_summary.py
# ---------------------------------------------------------------------------


def test_metrics_summary_tabulates(tmp_path):
    import pathlib
    import sys

    sys.path.insert(
        0, str(pathlib.Path(__file__).resolve().parent.parent / "benchmarks")
    )
    try:
        from metrics_summary import load_records, summarize
    finally:
        sys.path.pop(0)

    path = tmp_path / "m.jsonl"
    recs = [
        {"kind": "manifest"},
        {"kind": "step", "step": 0, "loss": 2.0, "step_time_s": None,
         "grad_sync_bytes": 100},
        {"kind": "step", "step": 1, "loss": 1.0, "step_time_s": 9.0,
         "grad_sync_bytes": 100, "mfu": 0.4},
        {"kind": "step", "step": 2, "loss": 1.5, "step_time_s": 0.5,
         "grad_sync_bytes": 100, "mfu": 0.6},
        {"kind": "event", "event": "eval"},
    ]
    path.write_text("\n".join(json.dumps(r) for r in recs) + "\n")
    s = summarize(load_records(str(path)))
    assert s["step_records"] == 3
    assert s["step_range"] == (0, 2)
    # first recorded step time (9.0, the compile step) is excluded.
    assert s["mean_step_time_s"] == pytest.approx(0.5)
    assert s["final_loss"] == 1.5 and s["best_loss"] == 1.0
    assert s["mean_mfu"] == pytest.approx(0.5)
    assert s["total_grad_sync_bytes"] == 300
    assert s["events"] == ["eval"]
