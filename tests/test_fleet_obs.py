"""graftfleet: cross-process timeline aggregation + incident audit
(``obs/fleet.py`` and the ``fleet-report`` CLI).

The units run on synthetic stores built with the same primitives a real
run uses (``RendezvousStore`` files + ``FleetStamper`` streams) but
with hand-picked clocks, so the alignment math is checked against known
answers — including ranks whose monotonic origins differ by hours and
whose wall clocks are skewed by seconds.

The slow test is the Issue-17 acceptance scenario end to end: a
4-process ``launch_local`` with a seeded 150 ms straggler on rank 3 AND
a coordinator SIGKILL at step 3. ``fleet-report --check`` must exit 0,
the merged Perfetto trace must show one lane per process across both
generations with the kill/death/re-election/re-exec instants in causal
order, and the skew attribution must pin rank 3 on every post-warmup
step.
"""

from __future__ import annotations

import json
import os
import socket
import subprocess
import sys

import pytest

from cs744_pytorch_distributed_tutorial_tpu.obs.fleet import (
    ClockAligner,
    FleetStamper,
    collective_skew,
    fleet_check,
    load_fleet_dir,
    merge_timeline,
    render_fleet_report,
    write_fleet_artifacts,
)
from cs744_pytorch_distributed_tutorial_tpu.parallel.multihost import (
    RendezvousStore,
)

# ------------------------------------------------ synthetic store tools
T0 = 1_700_000_000.0  # global barrier-release instant (reference time)

# Per-rank clock frames: rank 0 is the reference (zero wall offset);
# rank 1's wall clock runs 0.25 s fast; rank 2's runs 3 s slow. The
# monotonic origins are wildly different on purpose — alignment must
# come from the barrier anchors, not from the raw values.
_OFF = {0: 0.0, 1: 0.25, 2: -3.0}
_MONO0 = {0: 100.0, 1: 50_000.0, 2: 7.5}


def _pair(rank: int, t: float) -> tuple[float, float]:
    """Rank-local (wall, mono) for global instant ``t``."""
    return t + _OFF[rank], _MONO0[rank] + (t - T0)


def _write_json(path: str, payload: dict) -> None:
    with open(path, "w", encoding="utf-8") as f:
        json.dump(payload, f)


def _anchor(root: str, gen: int, rank: int, t: float = T0) -> None:
    wall, mono = _pair(rank, t)
    _write_json(
        os.path.join(root, f"sync_g{gen:06d}_r{rank}.json"),
        {
            "generation": gen,
            "global_rank": rank,
            "wall": wall,
            "mono": mono,
            "host": f"host{rank}",
        },
    )


def _event_line(root: str, event: str, t: float, **fields) -> None:
    with open(os.path.join(root, "events.jsonl"), "a", encoding="utf-8") as f:
        f.write(
            json.dumps({"kind": "event", "event": event, "time": t, **fields})
            + "\n"
        )


def _synthetic_store(root: str, *, steps: int = 4, stall_s: float = 0.1):
    """One generation, 3 ranks, rank 2 seeded ``stall_s`` late at every
    sync_enter from step 1 on (step 0 is the compile warmup)."""
    store = RendezvousStore(root)
    store.write_world(
        {"generation": 0, "ranks": [0, 1, 2], "world_size": 3,
         "coordinator_rank": 0}
    )
    _event_line(
        root, "generation_start", T0, generation=0, world_size=3,
        ranks=[0, 1, 2],
    )
    for rank in (0, 1, 2):
        _anchor(root, 0, rank)
        with FleetStamper(root, 0, rank) as stamper:
            for step in range(steps):
                enter = T0 + 1.0 + step  # one step per second
                stall = stall_s if rank == 2 and step >= 1 else 0.0
                arrive = enter + 0.01 + stall
                # everyone leaves the collective when the straggler
                # arrives (plus wire time)
                leave = enter + 0.01 + (stall_s if step >= 1 else 0.0) + 0.005
                stamper.stamp_step(
                    step,
                    step_enter=_pair(rank, enter),
                    sync_enter=_pair(rank, arrive),
                    sync_exit=_pair(rank, leave),
                    step_exit=_pair(rank, leave + 0.001),
                )
    return store


# -------------------------------------------------------------- aligner
def test_clock_aligner_maps_skewed_frames_to_one_timeline():
    anchors = {
        0: {
            0: {"wall": T0, "mono": 100.0},
            1: {"wall": T0 + 0.25, "mono": 50_000.0},
        }
    }
    al = ClockAligner(anchors)
    assert al.reference_rank(0) == 0
    assert al.wall_offset(0, 1) == pytest.approx(0.25)
    # The same global instant T0+1, seen from each rank's own clocks,
    # aligns to the same reference time via the monotonic path:
    assert al.aligned(0, 0, mono=101.0) == pytest.approx(T0 + 1.0)
    assert al.aligned(0, 1, mono=50_001.0) == pytest.approx(T0 + 1.0)
    # Wall fallback (no mono recorded) subtracts the anchor offset:
    assert al.aligned(0, 1, wall=T0 + 1.25) == pytest.approx(T0 + 1.0)
    # Monotonic wins over a lying wall stamp when both are present:
    assert al.aligned(0, 1, wall=T0 + 999.0, mono=50_001.0) == pytest.approx(
        T0 + 1.0
    )
    # Unanchored (gen, rank) passes wall through and is tracked:
    assert al.aligned(0, 7, wall=123.0) == 123.0
    assert (0, 7) in al.unanchored


# ------------------------------------------------- stamper + ingestion
def test_fleet_stamper_round_trips_through_load_fleet_dir(tmp_path):
    root = str(tmp_path / "store")
    _synthetic_store(root, steps=2)
    data = load_fleet_dir(root)
    assert data.generations == [0]
    assert data.ranks == [0, 1, 2]
    stamps = [s for s in data.stamps if s.get("kind") == "fleet_stamp"]
    assert len(stamps) == 6  # 3 ranks x 2 steps
    rec = stamps[0]
    for key in ("step_enter", "sync_enter", "sync_exit", "step_exit"):
        assert isinstance(rec[f"{key}_wall"], float)
        assert isinstance(rec[f"{key}_mono"], float)
    assert set(data.barrier_stamps[0]) == {0, 1, 2}
    assert data.torn_lines == {}


def test_collective_skew_pins_seeded_straggler(tmp_path):
    root = str(tmp_path / "store")
    _synthetic_store(root, steps=4, stall_s=0.1)
    data = load_fleet_dir(root)
    rows = collective_skew(data)
    assert [r["step"] for r in rows] == [0, 1, 2, 3]
    assert rows[0]["warmup"] and not any(r["warmup"] for r in rows[1:])
    for row in rows[1:]:
        assert row["straggler"] == 2
        assert row["skew_ms"] == pytest.approx(100.0, abs=1.0)
        # early ranks are charged the wait; the straggler waits ~0
        assert row["collective_wait_ms"]["0"] == pytest.approx(100.0, abs=1.0)
        assert row["collective_wait_ms"]["2"] == pytest.approx(0.0, abs=1.0)
        assert row["full_coverage"]
    # and the audit finds nothing wrong with a healthy run
    assert fleet_check(data) == []


def test_merge_timeline_lane_per_process(tmp_path):
    root = str(tmp_path / "store")
    _synthetic_store(root, steps=2)
    data = load_fleet_dir(root)
    trace = merge_timeline(data, skew=collective_skew(data))
    events = trace["traceEvents"]
    lanes = {
        e["args"]["name"]
        for e in events
        if e.get("ph") == "M" and e.get("name") == "process_name"
    }
    assert lanes == {"fleet", "rank 0", "rank 1", "rank 2"}
    steps = [e for e in events if e.get("cat") == "step"]
    assert {e["pid"] for e in steps} == {1, 2, 3}
    gen_track = [e for e in events if e.get("cat") == "generation"]
    assert [e["args"]["generation"] for e in gen_track] == [0]
    # the collective spans of one step start at aligned arrival: the
    # straggler's span must start last on step 1
    coll = {
        e["pid"]: e["ts"]
        for e in events
        if e.get("cat") == "collective" and e["args"]["step"] == 1
    }
    assert max(coll, key=coll.get) == 3  # pid 3 == rank 2
    # rendered report names the straggler too
    text = render_fleet_report(
        data, collective_skew(data), [], ClockAligner(data.barrier_stamps)
    )
    assert "r2" in text


# ---------------------------------------------------------------- audit
def test_fleet_check_flags_orphan_generation(tmp_path):
    root = str(tmp_path / "orphan")
    store = RendezvousStore(root)
    # generation 1 appears with no parent world and no re-election
    store.write_world(
        {"generation": 1, "ranks": [0, 1], "world_size": 2,
         "coordinator_rank": 0}
    )
    problems = fleet_check(load_fleet_dir(root))
    assert any("orphan generation 1" in p and "parent" in p
               for p in problems)
    assert any("no re-election" in p for p in problems)


def _two_generation_store(root: str) -> RendezvousStore:
    """g0=[0,1] -> rank 1 dies at T0+2 -> g1=[0]; causally ordered."""
    store = RendezvousStore(root)
    store.write_world(
        {"generation": 0, "ranks": [0, 1], "world_size": 2,
         "coordinator_rank": 0}
    )
    store.write_world(
        {"generation": 1, "ranks": [0], "world_size": 1,
         "coordinator_rank": 0}
    )
    _event_line(root, "generation_start", T0, generation=0, world_size=2,
                ranks=[0, 1])
    _event_line(root, "worker_death", T0 + 2.0, generation=0, dead_rank=1,
                reason="sigkill")
    _write_json(
        os.path.join(root, "dead_g000000.json"),
        {"generation": 0, "dead": [1], "time": T0 + 2.05},
    )
    _event_line(root, "reelection", T0 + 2.1, parent_generation=0,
                generation=1, survivors=[0], dead=[1], coordinator_rank=0)
    _event_line(root, "generation_start", T0 + 2.2, generation=1,
                world_size=1, ranks=[0])
    _anchor(root, 0, 0)
    _anchor(root, 0, 1)
    _anchor(root, 1, 0, T0 + 2.3)
    return store


def test_fleet_check_passes_consistent_two_generation_run(tmp_path):
    root = str(tmp_path / "ok")
    _two_generation_store(root)
    with FleetStamper(root, 0, 0) as stamper:
        stamper.stamp_step(
            0,
            step_enter=_pair(0, T0 + 1.0),
            sync_enter=_pair(0, T0 + 1.01),
            sync_exit=_pair(0, T0 + 1.02),
            step_exit=_pair(0, T0 + 1.03),
        )
    assert fleet_check(load_fleet_dir(root)) == []


def test_fleet_check_flags_seal_crossing_step(tmp_path):
    root = str(tmp_path / "seal")
    _two_generation_store(root)
    # rank 0 claims a g0 step that EXITS 4 s after g1 started: a step
    # completed in a world that no longer existed.
    with FleetStamper(root, 0, 0) as stamper:
        stamper.stamp_step(
            2,
            step_enter=_pair(0, T0 + 1.0),
            sync_enter=_pair(0, T0 + 1.01),
            sync_exit=_pair(0, T0 + 6.0),
            step_exit=_pair(0, T0 + 6.2),
        )
    problems = fleet_check(load_fleet_dir(root))
    assert any("crosses the generation seal" in p for p in problems)


def test_fleet_check_flags_out_of_order_stamp(tmp_path):
    root = str(tmp_path / "disorder")
    _synthetic_store(root, steps=1)
    with FleetStamper(root, 0, 0) as stamper:
        stamper.stamp_step(
            9,
            step_enter=_pair(0, T0 + 9.0),
            sync_enter=_pair(0, T0 + 8.0),  # before step_enter
            sync_exit=_pair(0, T0 + 9.1),
            step_exit=_pair(0, T0 + 9.2),
        )
    problems = fleet_check(load_fleet_dir(root))
    assert any("out of order" in p for p in problems)


# --------------------------------------------- store durability fixes
def test_append_event_single_line_and_torn_tail_tolerated(tmp_path):
    store = RendezvousStore(str(tmp_path / "store"))
    store.append_event("alpha", n=1)
    store.append_event("beta", n=2)
    # every intact record is one line and carries the monotonic stamp
    events, torn = store.events_with_torn()
    assert [e["event"] for e in events] == ["alpha", "beta"]
    assert torn == 0
    assert all(isinstance(e.get("monotonic"), float) for e in events)
    # a writer SIGKILLed mid-append leaves a torn tail: reader skips it
    with open(store.events_path, "a", encoding="utf-8") as f:
        f.write('{"kind": "event", "event": "gam')
    events, torn = store.events_with_torn()
    assert [e["event"] for e in events] == ["alpha", "beta"]
    assert torn == 1
    assert store.events() == events  # plain reader unaffected
    # the fleet loader counts it per source file
    data = load_fleet_dir(store.root)
    assert sum(data.torn_lines.values()) == 1


def test_heartbeat_age_prefers_monotonic_on_same_host(tmp_path):
    store = RendezvousStore(str(tmp_path / "store"))
    store.heartbeat(0, 0, step=3)
    with open(store._hb_path(0, 0), encoding="utf-8") as f:
        rec = json.load(f)
    assert rec["host"] == socket.gethostname()
    # monotonic path: age is the mono delta, immune to wall steps
    age = store.heartbeat_age(0, 0, now_mono=rec["monotonic"] + 5.0)
    assert age == pytest.approx(5.0, abs=0.01)
    # explicit `now` forces the wall path (tests pin time that way)
    age = store.heartbeat_age(0, 0, now=rec["time"] + 7.0)
    assert age == pytest.approx(7.0, abs=0.01)
    # a beat from another host cannot use this host's monotonic clock
    rec["host"] = "somewhere-else"
    rec["time"] = rec["time"] - 11.0
    with open(store._hb_path(0, 0), "w", encoding="utf-8") as f:
        json.dump(rec, f)
    age = store.heartbeat_age(0, 0)
    assert age == pytest.approx(11.0, abs=2.0)


# ------------------------------------------------------------ CLI + e2e
def _cli(args, **kw):
    env = {**os.environ, "PYTHONPATH": _repo_root(), "JAX_PLATFORMS": "cpu",
           "XLA_FLAGS": "", "PALLAS_AXON_POOL_IPS": ""}
    return subprocess.run(
        [sys.executable, "-m", "cs744_pytorch_distributed_tutorial_tpu.obs",
         *args],
        env=env, capture_output=True, text=True, timeout=kw.pop("timeout", 120),
    )


def _repo_root() -> str:
    return os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def test_fleet_report_cli_check_gates_on_problems(tmp_path):
    ok_root = str(tmp_path / "ok")
    _synthetic_store(ok_root, steps=2)
    proc = _cli(["fleet-report", ok_root, "--check"])
    assert proc.returncode == 0, proc.stderr
    assert "fleet check: OK" in proc.stdout
    assert os.path.exists(os.path.join(ok_root, "fleet_trace.json"))
    assert os.path.exists(os.path.join(ok_root, "fleet_report.json"))

    bad_root = str(tmp_path / "bad")
    store = RendezvousStore(bad_root)
    store.write_world(
        {"generation": 1, "ranks": [0], "world_size": 1,
         "coordinator_rank": 0}
    )
    proc = _cli(["fleet-report", bad_root, "--check", "--no-artifacts"])
    assert proc.returncode == 1
    assert "orphan generation" in proc.stderr
    assert not os.path.exists(os.path.join(bad_root, "fleet_trace.json"))


def _store_root(tmp_path, name):
    """CI artifact hook: multihost-smoke sets GRAFT_ELASTIC_TEST_STORE
    so the run dir (including fleet artifacts) lands in an uploaded
    directory."""
    base = os.environ.get("GRAFT_ELASTIC_TEST_STORE")
    if base:
        return os.path.join(base, name)
    return str(tmp_path / name)


@pytest.mark.slow  # multihost-smoke CI runs these without the tier-1 filter
def test_fleet_report_on_coordinator_kill_with_seeded_straggler(tmp_path):
    """Issue-17 acceptance: 4 processes, rank 3 stalled 150 ms per step,
    coordinator (rank 0) SIGKILLed at step 3. The audit must pass, the
    merged trace must carry every process across both generations with
    the incident instants in causal order, and the attribution must name
    rank 3 the straggler on every post-warmup step."""
    store_root = _store_root(tmp_path, "fleet_kill")
    repo = _repo_root()
    env = {
        **os.environ,
        "JAX_PLATFORMS": "cpu",
        "XLA_FLAGS": "",  # one CPU device per worker
        "PALLAS_AXON_POOL_IPS": "",
        "PYTHONPATH": repo,
    }
    proc = subprocess.run(
        [
            sys.executable, "-m",
            "cs744_pytorch_distributed_tutorial_tpu.launch",
            "--nprocs", "4", "--store", store_root,
            "--steps", "7", "--kill", "3:0", "--slow", "3:150",
            "--collective-deadline-s", "6",
        ],
        env=env, capture_output=True, text=True, timeout=480,
    )
    assert proc.returncode == 0, (
        f"supervisor failed rc={proc.returncode}\n"
        f"stdout:\n{proc.stdout}\nstderr:\n{proc.stderr}"
    )
    # the supervisor already merged the artifacts at exit
    assert os.path.exists(os.path.join(store_root, "fleet_trace.json"))

    cli = _cli(["fleet-report", store_root, "--check"], timeout=180)
    assert cli.returncode == 0, (
        f"fleet check failed\nstdout:\n{cli.stdout}\nstderr:\n{cli.stderr}"
    )
    assert "fleet check: OK" in cli.stdout

    with open(os.path.join(store_root, "fleet_trace.json")) as f:
        events = json.load(f)["traceEvents"]
    lanes = {
        e["args"]["name"]
        for e in events
        if e.get("ph") == "M" and e.get("name") == "process_name"
    }
    assert lanes == {"fleet", "rank 0", "rank 1", "rank 2", "rank 3"}
    # generation track: g0 then g1 on the fleet lane
    gen_track = [e for e in events if e.get("cat") == "generation"]
    assert [e["args"]["generation"] for e in gen_track] == [0, 1]
    # every survivor's lane continues into generation 1; the victim's
    # stops at generation 0
    gens_by_pid: dict[int, set] = {}
    for e in events:
        if e.get("cat") == "step":
            gens_by_pid.setdefault(e["pid"], set()).add(
                e["args"]["generation"]
            )
    assert gens_by_pid[1] == {0}  # rank 0 (killed)
    for pid in (2, 3, 4):  # ranks 1-3 survive into g1
        assert gens_by_pid[pid] == {0, 1}, gens_by_pid

    def first_instant(prefix):
        ts = [
            e["ts"] for e in events
            if e.get("ph") == "i" and e["name"].startswith(prefix)
        ]
        assert ts, f"no instant named {prefix!r}"
        return min(ts)

    kill = first_instant("chaos process_kill")
    death = first_instant("death r0")
    note = first_instant("death note g0")
    reelect = first_instant("re-election g0->g1")
    reexec = first_instant("re-exec g1")
    assert kill <= death <= note <= reelect <= reexec

    with open(os.path.join(store_root, "fleet_report.json")) as f:
        report = json.load(f)
    assert report["problems"] == []
    assert report["generations"] == [0, 1]
    assert report["ranks"] == [0, 1, 2, 3]
    skew = [
        r for r in report["records"]
        if r.get("kind") == "fleet_skew" and not r.get("warmup")
    ]
    assert len(skew) >= 4  # 7 steps attributed minus one warmup per gen
    for row in skew:
        assert row["straggler"] == 3, row
        # the stall dominates the spread; the straggler itself waits
        # the least inside the collective
        waits = row["collective_wait_ms"]
        assert min(waits, key=waits.get) == "3"
        assert row["skew_ms"] > 50.0
