"""scan_layers: the stacked-layer TransformerLM is numerically the
unrolled one (models/transformer.py::TransformerLM.scan_layers).

No counterpart in the reference (conv VGG-11 only,
``master/part1/model.py:30-46``) — this is compile-scalability
infrastructure: the scanned program is one block body + a loop instead
of L inlined bodies, which is what lets deep/big-batch GPT-2 configs
compile (the round-3 b32 remote-compile wall, benchmarks/README.md).
These tests pin that the layout change is EXACTLY a layout change:
logits, grads, the training step, remat, dropout keying, decode with a
KV cache, and tensor-parallel sharding all agree with the unrolled
path.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from cs744_pytorch_distributed_tutorial_tpu.models import TransformerLM
from cs744_pytorch_distributed_tutorial_tpu.models.transformer import (
    lm_param_specs,
    stack_block_params,
    unstack_block_params,
)

L = 3


def _lm(**kw) -> TransformerLM:
    base = dict(
        vocab_size=128,
        num_layers=L,
        num_heads=4,
        d_model=64,
        d_ff=128,
        max_seq_len=64,
        dtype=jnp.float32,
        attention_impl="dense",
        use_rope=True,
        flash_interpret=True,
    )
    base.update(kw)
    return TransformerLM(**base)


@pytest.fixture(scope="module")
def unrolled_params():
    m = _lm()
    toks = jnp.zeros((2, 16), jnp.int32)
    return m.init(jax.random.key(0), toks)["params"]


def test_forward_logit_parity(unrolled_params):
    toks = jax.random.randint(jax.random.key(1), (2, 16), 0, 128)
    out_u = _lm().apply({"params": unrolled_params}, toks)
    stacked = stack_block_params(unrolled_params, L)
    out_s = _lm(scan_layers=True).apply({"params": stacked}, toks)
    np.testing.assert_allclose(
        np.asarray(out_u), np.asarray(out_s), rtol=1e-6, atol=1e-5
    )


def test_stack_unstack_roundtrip(unrolled_params):
    stacked = stack_block_params(unrolled_params, L)
    back = unstack_block_params(stacked)
    jax.tree.map(
        lambda a, b: np.testing.assert_array_equal(
            np.asarray(a), np.asarray(b)
        ),
        unrolled_params,
        back,
    )


def test_grad_parity(unrolled_params):
    toks = jax.random.randint(jax.random.key(2), (2, 16), 0, 128)
    tgts = jax.random.randint(jax.random.key(3), (2, 16), 0, 128)

    def loss(model, p):
        import optax

        logits = model.apply({"params": p}, toks)
        return optax.softmax_cross_entropy_with_integer_labels(
            logits, tgts
        ).mean()

    g_u = jax.grad(lambda p: loss(_lm(), p))(unrolled_params)
    stacked = stack_block_params(unrolled_params, L)
    g_s = jax.grad(lambda p: loss(_lm(scan_layers=True), p))(stacked)
    g_u_stacked = stack_block_params(g_u, L)
    jax.tree.map(
        lambda a, b: np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=1e-5, atol=1e-5
        ),
        g_u_stacked,
        g_s,
    )


def test_remat_scan_parity(unrolled_params):
    """remat composes with scan_layers: the scanned body is checkpointed
    per layer (scan-over-remat), numerics unchanged."""
    toks = jax.random.randint(jax.random.key(4), (2, 16), 0, 128)
    out_u = _lm().apply({"params": unrolled_params}, toks)
    stacked = stack_block_params(unrolled_params, L)
    m_rs = _lm(scan_layers=True, remat=True, remat_policy="dots")
    out_s = m_rs.apply({"params": stacked}, toks)
    np.testing.assert_allclose(
        np.asarray(out_u), np.asarray(out_s), rtol=1e-6, atol=1e-5
    )
    g = jax.grad(lambda p: m_rs.apply({"params": p}, toks).sum())(stacked)
    assert all(
        bool(jnp.all(jnp.isfinite(leaf))) for leaf in jax.tree.leaves(g)
    )


def test_decode_cache_parity(unrolled_params):
    """Cached prefill+decode through the scanned stack matches the
    teacher-forced forward at every generated position (the cache gets a
    leading [L] axis; reads/writes must hit the right layer's slice)."""
    stacked = stack_block_params(unrolled_params, L)
    m = _lm(scan_layers=True)
    toks = jax.random.randint(jax.random.key(5), (2, 24), 0, 128)
    full = m.apply({"params": stacked}, toks)

    prompt = toks[:, :16]
    cache = m.init(jax.random.key(0), prompt, mode="prefill")["cache"]
    logits, mut = m.apply(
        {"params": stacked, "cache": cache}, prompt, mode="prefill",
        mutable=["cache"],
    )
    np.testing.assert_allclose(
        np.asarray(logits), np.asarray(full[:, :16]), rtol=1e-5, atol=1e-5
    )
    cache = mut["cache"]
    for pos in range(16, 24):
        step_logits, mut = m.apply(
            {"params": stacked, "cache": cache},
            toks[:, pos : pos + 1],
            mode="decode",
            decode_pos=jnp.int32(pos),
            mutable=["cache"],
        )
        cache = mut["cache"]
        np.testing.assert_allclose(
            np.asarray(step_logits[:, 0]),
            np.asarray(full[:, pos]),
            rtol=1e-4,
            atol=1e-4,
        )


def test_dropout_runs_and_differs_per_layer():
    """split_rngs gives each scanned layer its own dropout stream: the
    zero patterns the per-layer Dropout modules apply must DIFFER across
    layers (a regression to a shared rng would correlate them exactly).
    Pinned via captured intermediates — under nn.scan each submodule's
    outputs stack along the leading layer axis."""
    m = _lm(scan_layers=True, dropout_rate=0.5)
    toks = jnp.zeros((2, 16), jnp.int32)
    params = m.init(jax.random.key(0), toks)["params"]
    out, state = m.apply(
        {"params": params},
        toks,
        deterministic=False,
        rngs={"dropout": jax.random.key(7)},
        capture_intermediates=lambda mdl, _: mdl.name == "attn_drop",
    )
    assert bool(jnp.all(jnp.isfinite(out)))
    (dropped,) = jax.tree.leaves(state["intermediates"])
    assert dropped.shape[0] == L  # stacked per layer
    masks = np.asarray(dropped == 0.0).reshape(L, -1)
    for i in range(1, L):
        assert (masks[0] != masks[i]).any(), (
            f"layer 0 and layer {i} drew identical dropout masks — "
            "split_rngs regressed"
        )


def test_moe_scan_rejected():
    m = _lm(scan_layers=True, num_experts=4)
    toks = jnp.zeros((2, 16), jnp.int32)
    with pytest.raises(ValueError, match="scan_layers does not compose"):
        m.init(jax.random.key(0), toks)


def test_param_specs_scanned_layout(unrolled_params):
    """Tensor-axis specs shift one dim right for stacked leaves; the
    layer dim stays unsharded."""
    from jax.sharding import PartitionSpec as P

    stacked = stack_block_params(unrolled_params, L)
    specs = lm_param_specs(stacked, "tensor")
    blk = specs["blocks"]
    assert blk["attn"]["q"]["kernel"] == P(None, None, "tensor")
    assert blk["attn"]["attn_out"]["kernel"] == P(None, "tensor", None)
    assert blk["mlp_in"]["kernel"] == P(None, None, "tensor")
    assert blk["mlp_in"]["bias"] == P(None, "tensor")
    assert blk["mlp_out"]["kernel"] == P(None, "tensor", None)
    assert specs["tok_embed"]["embedding"] == P()


@pytest.mark.slow
def test_trainer_scan_layers_loss_parity(mesh8):
    """LMTrainer(scan_layers=True) takes the stacked version of the
    unrolled trainer's params to the SAME loss — the full shard_map
    train path (dp2 x tp2, grad sync, optimizer) is layout-invariant."""
    from cs744_pytorch_distributed_tutorial_tpu.data import synthetic_tokens
    from cs744_pytorch_distributed_tutorial_tpu.parallel import make_mesh
    from cs744_pytorch_distributed_tutorial_tpu.train import LMConfig, LMTrainer

    mesh = make_mesh(
        {"data": 2, "seq": 1, "tensor": 2}, devices=jax.devices()[:4]
    )
    cfg = LMConfig(
        vocab_size=128,
        num_layers=L,
        num_heads=4,
        d_model=64,
        d_ff=128,
        max_seq_len=64,
        seq_len=32,
        global_batch_size=4,
        attention_impl="dense",
        data_parallel=2,
        tensor_parallel=2,
        use_rope=True,
    )
    tr_u = LMTrainer(cfg, mesh=mesh)
    tr_s = LMTrainer(cfg.replace(scan_layers=True), mesh=mesh)
    tokens = synthetic_tokens(4, 32, 128, seed=0)
    x, y = tr_u.shard_batch(tokens)

    params_u, opt_u = tr_u.init()
    host_u = jax.tree.map(np.asarray, jax.device_get(params_u))
    stacked = stack_block_params(host_u, L)
    from jax.sharding import NamedSharding

    from cs744_pytorch_distributed_tutorial_tpu.parallel.mesh import (
        host_to_global,
    )

    params_s = jax.tree.map(
        lambda p, s: host_to_global(p, NamedSharding(mesh, s)),
        stacked,
        tr_s.param_specs,
    )
    opt_s = jax.tree.map(
        lambda o, s: host_to_global(np.asarray(o), NamedSharding(mesh, s)),
        jax.device_get(tr_s.tx.init(stacked)),
        tr_s.opt_specs,
    )

    losses_u, losses_s = [], []
    for step in range(3):
        params_u, opt_u, m_u = tr_u.train_step(params_u, opt_u, x, y, step)
        params_s, opt_s, m_s = tr_s.train_step(params_s, opt_s, x, y, step)
        losses_u.append(float(m_u["loss"]))
        losses_s.append(float(m_s["loss"]))
    np.testing.assert_allclose(losses_u, losses_s, rtol=2e-5)
