"""Failure detection + recovery (utils/failure.py, SURVEY §5.3).

The reference has no failure story — a dead rank hangs its Gloo
collectives with no retry. These tests exercise the three replacement
pieces with injected faults: the hang watchdog, non-finite-loss
detection inside ``Trainer.fit``, and the checkpoint/restart recovery
loop.
"""

import time

import jax.numpy as jnp
import numpy as np
import pytest
from conftest import TINY_DP4_CFG

from cs744_pytorch_distributed_tutorial_tpu.config import TrainConfig
from cs744_pytorch_distributed_tutorial_tpu.train import Trainer
from cs744_pytorch_distributed_tutorial_tpu.utils.failure import (
    NonFiniteLossError,
    StepWatchdog,
    TrainingFailure,
    run_with_recovery,
)


def test_watchdog_fires_on_hang():
    hangs = []
    wd = StepWatchdog(timeout_s=0.15, on_hang=hangs.append, dump_stacks=False)
    wd.arm()
    time.sleep(0.5)  # the "hung step"
    wd.disarm()
    wd.close()
    assert wd.fired == 1
    assert len(hangs) == 1 and hangs[0] >= 0.15  # actual elapsed time


def test_watchdog_quiet_on_fast_steps():
    wd = StepWatchdog(timeout_s=0.3, dump_stacks=False)
    for _ in range(5):
        with wd.watch():
            time.sleep(0.01)
    time.sleep(0.5)  # well past the timeout — but every section disarmed
    wd.close()
    assert wd.fired == 0


def _nan_injecting(trainer, fail_at_call: int, transient: bool):
    """Wrap trainer.train_step to return a NaN loss. ``transient``: NaN
    exactly once, on the Nth call (a flaky-chip analog). Persistent: NaN
    on every call from the Nth on (deterministic divergence — replays
    identically after each restart)."""
    orig = trainer.train_step
    calls = {"n": 0, "injected": False}

    def step(*args):
        state, metrics = orig(*args)
        calls["n"] += 1
        fire = (
            calls["n"] == fail_at_call and not calls["injected"]
            if transient
            else calls["n"] >= fail_at_call
        )
        if fire:
            calls["injected"] = True
            metrics = dict(metrics, loss=jnp.float32(float("nan")))
        return state, metrics

    trainer.train_step = step
    return calls


def test_fit_raises_on_nonfinite_loss(mesh4):
    cfg = TrainConfig(**TINY_DP4_CFG, sync="allreduce", log_every=1)
    tr = Trainer(cfg, mesh=mesh4)
    _nan_injecting(tr, fail_at_call=2, transient=False)
    with pytest.raises(NonFiniteLossError) as ei:
        tr.fit()
    assert ei.value.step == 1  # 0-indexed: the second step diverged


def test_run_with_recovery_restarts_then_succeeds(mesh4, tmp_path):
    """A transient fault (NaN once, clean on replay) recovers with exactly
    one restart, resuming MID-epoch from the newest checkpoint — already-
    applied batches are skipped, not double-applied, so the recovered run
    lands on the identical parameters of an uninterrupted run."""
    import jax

    base = dict(**TINY_DP4_CFG, sync="allreduce", log_every=1)
    clean = Trainer(TrainConfig(**base), mesh=mesh4)
    clean_state, _ = clean.fit()
    clean_params = jax.device_get(clean_state.params)

    cfg = TrainConfig(
        **base,
        checkpoint_dir=str(tmp_path / "ckpt"),
        checkpoint_every=1,
    )
    tr = Trainer(cfg, mesh=mesh4)
    calls = _nan_injecting(tr, fail_at_call=3, transient=True)
    state, history, restarts = run_with_recovery(tr, max_restarts=2)
    assert restarts == 1
    assert calls["injected"]
    assert np.isfinite(history["eval"][-1]["avg_loss"])
    # exact resume: step count matches the uninterrupted epoch (4 batches),
    # and params match the clean trajectory bit-for-bit
    assert int(jnp.asarray(state.step)) == 4  # 128/32 = 4 steps per epoch
    jax.tree.map(
        lambda a, b: np.testing.assert_array_equal(np.asarray(a), np.asarray(b)),
        clean_params,
        jax.device_get(state.params),
    )


def test_run_with_recovery_gives_up_on_persistent_failure(mesh4, tmp_path):
    """Deterministic divergence replays identically; after max_restarts the
    failure propagates instead of looping forever."""
    cfg = TrainConfig(
        **TINY_DP4_CFG,
        sync="allreduce",
        log_every=1,
        checkpoint_dir=str(tmp_path / "ckpt"),
        checkpoint_every=1,
    )
    tr = Trainer(cfg, mesh=mesh4)
    _nan_injecting(tr, fail_at_call=2, transient=False)
    with pytest.raises(NonFiniteLossError):
        run_with_recovery(tr, max_restarts=1)


def test_run_with_recovery_requires_checkpoint_dir(mesh4):
    tr = Trainer(TrainConfig(**TINY_DP4_CFG, sync="allreduce"), mesh=mesh4)
    with pytest.raises(ValueError, match="checkpoint_dir"):
        run_with_recovery(tr)


def test_training_failure_is_runtime_error():
    assert issubclass(NonFiniteLossError, TrainingFailure)
    assert issubclass(TrainingFailure, RuntimeError)


def test_hang_action_validated(mesh4):
    with pytest.raises(ValueError, match="hang_action"):
        Trainer(
            TrainConfig(**TINY_DP4_CFG, sync="allreduce", hang_action="explode"),
            mesh=mesh4,
        )


def test_halt_on_nonfinite_can_be_disabled(mesh4):
    """With halt_on_nonfinite=False (CLI --no-halt-on-nonfinite) the run
    observes the NaN and keeps training — the reference's behavior."""
    cfg = TrainConfig(
        **TINY_DP4_CFG, sync="allreduce", log_every=1, halt_on_nonfinite=False
    )
    tr = Trainer(cfg, mesh=mesh4)
    _nan_injecting(tr, fail_at_call=2, transient=True)
    state, history = tr.fit()  # completes despite the injected NaN
    assert int(jnp.asarray(state.step)) == 4
    assert any(not np.isfinite(l) for _, _, l in history["train_loss"])
