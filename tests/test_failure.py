"""Failure detection + recovery (utils/failure.py, SURVEY §5.3).

The reference has no failure story — a dead rank hangs its Gloo
collectives with no retry. These tests exercise the three replacement
pieces with injected faults: the hang watchdog, non-finite-loss
detection inside ``Trainer.fit``, and the checkpoint/restart recovery
loop.
"""

import time

import jax.numpy as jnp
import numpy as np
import pytest
from conftest import TINY_DP4_CFG

from cs744_pytorch_distributed_tutorial_tpu.config import TrainConfig
from cs744_pytorch_distributed_tutorial_tpu.train import Trainer
from cs744_pytorch_distributed_tutorial_tpu.utils.failure import (
    NonFiniteLossError,
    StepWatchdog,
    TrainingFailure,
    run_with_recovery,
)


def test_watchdog_fires_on_hang():
    hangs = []
    wd = StepWatchdog(timeout_s=0.15, on_hang=hangs.append, dump_stacks=False)
    wd.arm()
    time.sleep(0.5)  # the "hung step"
    wd.disarm()
    wd.close()
    assert wd.fired == 1
    assert len(hangs) == 1 and hangs[0] >= 0.15  # actual elapsed time


def test_watchdog_quiet_on_fast_steps():
    wd = StepWatchdog(timeout_s=0.3, dump_stacks=False)
    for _ in range(5):
        with wd.watch():
            time.sleep(0.01)
    time.sleep(0.5)  # well past the timeout — but every section disarmed
    wd.close()
    assert wd.fired == 0


def test_watchdog_escalation_ladder():
    """escalation=("warn","dump","abort"): a persistently wedged section
    climbs the ladder on its own — fire #1 warns (no callback), #2 dumps,
    #3 aborts (callback fires) — with no help from the blocked training
    thread."""
    hangs = []
    wd = StepWatchdog(
        timeout_s=0.1,
        on_hang=hangs.append,
        dump_stacks=False,
        escalation=("warn", "dump", "abort"),
    )
    wd.arm()
    deadline = time.monotonic() + 5.0
    while wd.fired < 3 and time.monotonic() < deadline:
        time.sleep(0.02)
    wd.disarm()
    wd.close()
    assert wd.fired == 3
    assert wd.last_stage == "abort"
    assert len(hangs) == 1  # only the "abort" rung runs the callback


def test_watchdog_escalation_rejects_unknown_stage():
    with pytest.raises(ValueError, match="escalation stages"):
        StepWatchdog(timeout_s=1.0, escalation=("warn", "explode"))


def test_watchdog_rearm_during_fire_cannot_double_fire():
    """A callback that re-arms DURING an in-flight _fire (the lock is
    re-entrant) starts a new section; the expired section still fires
    exactly once, and a prompt disarm cancels the new section."""
    wd = None
    fires = []

    def rearm_on_hang(elapsed):
        fires.append(elapsed)
        wd.arm(10.0)  # new section with a far deadline

    wd = StepWatchdog(
        timeout_s=0.1, on_hang=rearm_on_hang, dump_stacks=False
    )
    wd.arm()
    deadline = time.monotonic() + 5.0
    while wd.fired < 1 and time.monotonic() < deadline:
        time.sleep(0.02)
    time.sleep(0.3)  # old section's deadline long gone — must not refire
    wd.disarm()  # cancels the callback's 10s section
    wd.close()
    assert wd.fired == 1
    assert len(fires) == 1


def _nan_injecting(trainer, fail_at_call: int, transient: bool):
    """Wrap trainer.train_step to return a NaN loss. ``transient``: NaN
    exactly once, on the Nth call (a flaky-chip analog). Persistent: NaN
    on every call from the Nth on (deterministic divergence — replays
    identically after each restart)."""
    orig = trainer.train_step
    calls = {"n": 0, "injected": False}

    def step(*args):
        state, metrics = orig(*args)
        calls["n"] += 1
        fire = (
            calls["n"] == fail_at_call and not calls["injected"]
            if transient
            else calls["n"] >= fail_at_call
        )
        if fire:
            calls["injected"] = True
            metrics = dict(metrics, loss=jnp.float32(float("nan")))
        return state, metrics

    trainer.train_step = step
    return calls


def test_fit_raises_on_nonfinite_loss(mesh4):
    cfg = TrainConfig(**TINY_DP4_CFG, sync="allreduce", log_every=1)
    tr = Trainer(cfg, mesh=mesh4)
    _nan_injecting(tr, fail_at_call=2, transient=False)
    with pytest.raises(NonFiniteLossError) as ei:
        tr.fit()
    assert ei.value.step == 1  # 0-indexed: the second step diverged


def test_run_with_recovery_restarts_then_succeeds(mesh4, tmp_path):
    """A transient fault (NaN once, clean on replay) recovers with exactly
    one restart, resuming MID-epoch from the newest checkpoint — already-
    applied batches are skipped, not double-applied, so the recovered run
    lands on the identical parameters of an uninterrupted run."""
    import jax

    base = dict(**TINY_DP4_CFG, sync="allreduce", log_every=1)
    clean = Trainer(TrainConfig(**base), mesh=mesh4)
    clean_state, _ = clean.fit()
    clean_params = jax.device_get(clean_state.params)

    cfg = TrainConfig(
        **base,
        checkpoint_dir=str(tmp_path / "ckpt"),
        checkpoint_every=1,
    )
    tr = Trainer(cfg, mesh=mesh4)
    calls = _nan_injecting(tr, fail_at_call=3, transient=True)
    state, history, restarts = run_with_recovery(tr, max_restarts=2)
    assert restarts == 1
    assert calls["injected"]
    assert np.isfinite(history["eval"][-1]["avg_loss"])
    # exact resume: step count matches the uninterrupted epoch (4 batches),
    # and params match the clean trajectory bit-for-bit
    assert int(jnp.asarray(state.step)) == 4  # 128/32 = 4 steps per epoch
    jax.tree.map(
        lambda a, b: np.testing.assert_array_equal(np.asarray(a), np.asarray(b)),
        clean_params,
        jax.device_get(state.params),
    )


def test_run_with_recovery_gives_up_on_persistent_failure(mesh4, tmp_path):
    """Deterministic divergence replays identically; after max_restarts the
    failure propagates instead of looping forever."""
    cfg = TrainConfig(
        **TINY_DP4_CFG,
        sync="allreduce",
        log_every=1,
        checkpoint_dir=str(tmp_path / "ckpt"),
        checkpoint_every=1,
    )
    tr = Trainer(cfg, mesh=mesh4)
    _nan_injecting(tr, fail_at_call=2, transient=False)
    with pytest.raises(NonFiniteLossError):
        run_with_recovery(tr, max_restarts=1)


def test_run_with_recovery_requires_checkpoint_dir(mesh4):
    tr = Trainer(TrainConfig(**TINY_DP4_CFG, sync="allreduce"), mesh=mesh4)
    with pytest.raises(ValueError, match="checkpoint_dir"):
        run_with_recovery(tr)


def test_training_failure_is_runtime_error():
    assert issubclass(NonFiniteLossError, TrainingFailure)
    assert issubclass(TrainingFailure, RuntimeError)


def test_hang_action_validated(mesh4):
    with pytest.raises(ValueError, match="hang_action"):
        Trainer(
            TrainConfig(**TINY_DP4_CFG, sync="allreduce", hang_action="explode"),
            mesh=mesh4,
        )


@pytest.mark.slow  # chaos-smoke CI runs these without the tier-1 filter
def test_run_with_recovery_backoff_and_events(mesh4, tmp_path):
    """Exponential backoff between restarts (injectable sleep) and the
    per-transition kind:"event" telemetry: one recovery_restart per
    attempt carrying tier/backoff, recovery_giveup when exhausted."""
    from cs744_pytorch_distributed_tutorial_tpu.obs.sinks import RingSink

    cfg = TrainConfig(
        **TINY_DP4_CFG,
        sync="allreduce",
        log_every=1,
        checkpoint_dir=str(tmp_path / "ckpt"),
        checkpoint_every=1,
    )
    tr = Trainer(cfg, mesh=mesh4)
    _nan_injecting(tr, fail_at_call=2, transient=False)
    sleeps = []
    ring = RingSink()
    with pytest.raises(NonFiniteLossError):
        run_with_recovery(
            tr,
            max_restarts=2,
            backoff_s=0.5,
            sleep=sleeps.append,
            telemetry=ring,
        )
    assert sleeps == [0.5, 1.0]  # backoff_s * 2^(n-1)
    events = [r for r in ring.records() if r.get("kind") == "event"]
    restarts = [e for e in events if e["event"] == "recovery_restart"]
    assert [e["restart"] for e in restarts] == [1, 2]
    assert [e["backoff_s"] for e in restarts] == [0.5, 1.0]
    assert all(e["tier"] == "restart" for e in restarts)
    giveups = [e for e in events if e["event"] == "recovery_giveup"]
    assert len(giveups) == 1 and giveups[0]["restarts"] == 2


@pytest.mark.slow  # chaos-smoke CI runs these without the tier-1 filter
@pytest.mark.slow  # chaos-smoke CI runs these without the tier-1 filter
def test_lm_recovery_from_memory_snapshot_zero_disk_reads():
    """The in-memory snapshot tier alone (no checkpoint_dir) recovers an
    LMTrainer run — and the recovery performs ZERO filesystem restores,
    asserted through the instrumented Checkpointer counters."""
    from cs744_pytorch_distributed_tutorial_tpu.data import synthetic_tokens
    from cs744_pytorch_distributed_tutorial_tpu.parallel import make_mesh
    from cs744_pytorch_distributed_tutorial_tpu.train import (
        LMConfig,
        LMTrainer,
    )
    from cs744_pytorch_distributed_tutorial_tpu.utils.checkpoint import (
        Checkpointer,
    )

    mesh = make_mesh({"data": 2, "seq": 2})
    tr = LMTrainer(
        LMConfig(
            vocab_size=32, num_layers=1, num_heads=2, d_model=16, d_ff=32,
            max_seq_len=64, seq_len=16, global_batch_size=4,
            attention_impl="ring", data_parallel=2, seq_parallel=2,
            snapshot_every=1,
        ),
        mesh=mesh,
    )
    assert tr.memstore is not None  # built lazily from snapshot_every
    real = tr.train_step
    calls = {"n": 0}

    def flaky(params, opt_state, x, y, step=0):
        p, o, m = real(params, opt_state, x, y, step)
        calls["n"] += 1
        if calls["n"] == 3:  # transient: fails once, clean on replay
            m = dict(m, loss=jnp.float32(float("inf")))
        return p, o, m

    tr.train_step = flaky
    tokens = synthetic_tokens(8, 16, 32, seed=0)
    disk_restores_before = Checkpointer.total_restores
    params, opt, losses, restarts = run_with_recovery(
        tr, fit_args=(tokens, 4), max_restarts=2
    )
    assert restarts == 1
    assert np.isfinite(losses).all()
    assert Checkpointer.total_restores == disk_restores_before
    assert tr.memstore.restores >= 1


def test_halt_on_nonfinite_can_be_disabled(mesh4):
    """With halt_on_nonfinite=False (CLI --no-halt-on-nonfinite) the run
    observes the NaN and keeps training — the reference's behavior."""
    cfg = TrainConfig(
        **TINY_DP4_CFG, sync="allreduce", log_every=1, halt_on_nonfinite=False
    )
    tr = Trainer(cfg, mesh=mesh4)
    _nan_injecting(tr, fail_at_call=2, transient=True)
    state, history = tr.fit()  # completes despite the injected NaN
    assert int(jnp.asarray(state.step)) == 4
    assert any(not np.isfinite(l) for _, _, l in history["train_loss"])


# ---------------------------------------------------- restart jitter
class _AlwaysFailingTrainer:
    """Minimal run_with_recovery surface: restartable (checkpoint_dir
    set) but every fit attempt fails — isolates the backoff schedule."""

    class cfg:
        checkpoint_dir = "unused"

    memstore = None

    def fit(self, *a, **k):
        raise NonFiniteLossError(step=0, loss=float("nan"))


def _backoff_sequence(restarts, **kwargs):
    sleeps = []
    with pytest.raises(NonFiniteLossError):
        run_with_recovery(
            _AlwaysFailingTrainer(),
            max_restarts=restarts,
            backoff_s=0.5,
            sleep=sleeps.append,
            **kwargs,
        )
    return sleeps


def test_backoff_jitter_defaults_off():
    """backoff_jitter is strictly opt-in: the default schedule stays the
    bit-exact deterministic exponential."""
    assert _backoff_sequence(2) == [0.5, 1.0]
    assert _backoff_sequence(2, backoff_jitter="none") == [0.5, 1.0]


def test_backoff_jitter_invalid_value_rejected():
    with pytest.raises(ValueError, match="backoff_jitter"):
        run_with_recovery(
            _AlwaysFailingTrainer(), backoff_jitter="thundering-herd"
        )


def test_decorrelated_jitter_bounds_and_injected_rng():
    """Decorrelated jitter (AWS shape): attempt n draws
    uniform(base, prev * 3) capped at max_backoff_s — every delay stays
    within [base, cap], and an injected rng makes the draw exact."""
    rng = np.random.default_rng(123)
    sleeps = _backoff_sequence(
        6, backoff_jitter="decorrelated", jitter_rng=rng,
        max_backoff_s=3.0,
    )
    assert len(sleeps) == 6
    assert all(0.5 <= s <= 3.0 for s in sleeps)

    expect_rng = np.random.default_rng(123)
    prev = 0.5
    for got in sleeps:
        want = min(float(expect_rng.uniform(0.5, max(0.5, prev * 3.0))), 3.0)
        assert got == want
        prev = want


def test_decorrelated_jitter_seeded_per_rank_identity():
    """The stream is seeded by (jitter_seed, process_id, generation):
    same identity -> reproducible; different rank or generation ->
    decorrelated (survivors don't restart in lockstep)."""
    from cs744_pytorch_distributed_tutorial_tpu.parallel.multihost import (
        reset_runtime_labels,
        set_runtime_labels,
    )

    def seq(process_id, generation):
        set_runtime_labels(
            process_id=process_id, process_count=4,
            generation=generation, global_rank=process_id,
        )
        try:
            return _backoff_sequence(
                4, backoff_jitter="decorrelated", jitter_seed=42
            )
        finally:
            reset_runtime_labels()

    assert seq(0, 0) == seq(0, 0)  # reproducible for one identity
    assert seq(0, 0) != seq(1, 0)  # ranks decorrelate
    assert seq(1, 0) != seq(1, 1)  # generations decorrelate
