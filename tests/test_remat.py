"""Rematerialization (jax.checkpoint): the HBM-for-FLOPs trade.

Remat must be numerically invisible — the backward pass recomputes block
activations instead of loading stored ones, so losses and parameter
trajectories must match the unremat'ed run exactly. Verified for both
LM engines (seq-parallel LMTrainer and the pipelined trainer).
"""

import numpy as np
import pytest

from cs744_pytorch_distributed_tutorial_tpu.data import synthetic_tokens
from cs744_pytorch_distributed_tutorial_tpu.parallel import make_mesh
from cs744_pytorch_distributed_tutorial_tpu.parallel.pipeline import (
    PipelineLMConfig,
    PipelineLMTrainer,
)
from cs744_pytorch_distributed_tutorial_tpu.train import LMConfig, LMTrainer

# LM remat-vs-unremat fit pairs: heavy compile.
pytestmark = pytest.mark.slow

SMALL = dict(
    vocab_size=64, num_layers=2, num_heads=4, d_model=64, d_ff=128,
    max_seq_len=256, global_batch_size=8, seq_len=64, learning_rate=1e-2,
)


def test_lm_remat_matches_unremat():
    tokens = synthetic_tokens(32, SMALL["seq_len"], SMALL["vocab_size"], seed=4)
    losses = {}
    for remat in (False, True):
        cfg = LMConfig(
            **SMALL, attention_impl="ring", data_parallel=2, seq_parallel=4,
            remat=remat,
        )
        tr = LMTrainer(cfg, mesh=make_mesh({"data": 2, "seq": 4}))
        _, _, losses[remat] = tr.fit(tokens, steps=4)
    np.testing.assert_allclose(losses[False], losses[True], rtol=1e-6)


def test_lm_remat_dots_policy_matches():
    """remat_policy='dots' (keep matmul outputs, recompute elementwise)
    is likewise numerically invisible."""
    tokens = synthetic_tokens(16, SMALL["seq_len"], SMALL["vocab_size"], seed=6)
    losses = {}
    for policy in ("none", "dots"):
        cfg = LMConfig(
            **SMALL, attention_impl="ring", data_parallel=2, seq_parallel=4,
            remat=True, remat_policy=policy,
        )
        tr = LMTrainer(cfg, mesh=make_mesh({"data": 2, "seq": 4}))
        _, _, losses[policy] = tr.fit(tokens, steps=3)
    np.testing.assert_allclose(losses["none"], losses["dots"], rtol=1e-6)

    import pytest

    from cs744_pytorch_distributed_tutorial_tpu.models.transformer import (
        resolve_remat_policy,
    )

    with pytest.raises(ValueError, match="remat_policy"):
        resolve_remat_policy("everything")


def test_pipeline_remat_matches_unremat():
    tokens = synthetic_tokens(32, 16, 64, seed=5)
    losses = {}
    for remat in (False, True):
        cfg = PipelineLMConfig(
            vocab_size=64, num_layers=4, num_heads=4, d_model=32, d_ff=64,
            max_seq_len=64, data_parallel=2, pipeline_parallel=4,
            num_microbatches=2, global_batch_size=8, seq_len=16, remat=remat,
        )
        tr = PipelineLMTrainer(
            cfg, mesh=make_mesh({"data": 2, "pipe": 4})
        )
        _, _, losses[remat] = tr.fit(tokens, steps=3)
    np.testing.assert_allclose(losses[False], losses[True], rtol=1e-6)
