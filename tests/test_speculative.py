"""Speculative decoding (infer/speculative.py).

The load-bearing property: greedy speculative output is BIT-IDENTICAL
to plain greedy decoding of the target alone, for ANY draft — a random
draft (worst case, near-zero acceptance) and the target itself as draft
(acceptance 1) must both reproduce ``make_generator(temperature=0)``
exactly. Plus chunked-decode logit parity (the ``decode_attention``
T>1 path the verifier rides) and guard-rail rejections.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from cs744_pytorch_distributed_tutorial_tpu.infer import make_generator
from cs744_pytorch_distributed_tutorial_tpu.infer.speculative import (
    make_speculative_generator,
)
from cs744_pytorch_distributed_tutorial_tpu.models import TransformerLM


def _model(layers=2, seed_dims=True, **kw) -> TransformerLM:
    base = dict(
        vocab_size=64,
        num_layers=layers,
        num_heads=4,
        num_kv_heads=2,
        d_model=64,
        d_ff=128,
        max_seq_len=64,
        dtype=jnp.float32,
        attention_impl="dense",
        use_rope=True,
        flash_interpret=True,
    )
    base.update(kw)
    return TransformerLM(**base)


@pytest.fixture(scope="module")
def setup():
    target = _model(2)
    draft = _model(1)
    prompt = jax.random.randint(jax.random.key(0), (1, 8), 0, 64)
    tp = target.init(jax.random.key(1), prompt)["params"]
    dp = draft.init(jax.random.key(2), prompt)["params"]
    plain = make_generator(target, max_new_tokens=12, temperature=0.0)
    want = np.asarray(plain(tp, prompt, jax.random.key(3)))
    return target, draft, prompt, tp, dp, want


def test_chunked_decode_matches_teacher_forcing():
    """mode='decode' with T>1 (the verification pass) must reproduce the
    full teacher-forced forward at every chunk row."""
    model = _model(2)
    tokens = jax.random.randint(jax.random.key(4), (1, 16), 0, 64)
    params = model.init(jax.random.key(5), tokens)["params"]
    full = np.asarray(model.apply({"params": params}, tokens))
    # Prefill the first 8, then feed positions 8..15 as ONE chunk.
    _, vars_ = model.apply(
        {"params": params}, tokens[:, :8], mode="prefill", mutable=["cache"]
    )
    chunk_logits, _ = model.apply(
        {"params": params, "cache": vars_["cache"]},
        tokens[:, 8:],
        mode="decode",
        decode_pos=jnp.asarray(8, jnp.int32),
        mutable=["cache"],
    )
    np.testing.assert_allclose(
        np.asarray(chunk_logits), full[:, 8:], rtol=2e-5, atol=2e-5
    )


def test_exact_parity_with_random_draft(setup):
    target, draft, prompt, tp, dp, want = setup
    spec = make_speculative_generator(
        target, draft, max_new_tokens=12, k=3
    )
    got = np.asarray(spec(tp, dp, prompt))
    np.testing.assert_array_equal(got, want)


def test_exact_parity_with_self_draft(setup):
    """Target as its own draft: acceptance is 1 by construction and the
    output must still be exactly plain greedy."""
    target, _, prompt, tp, _, want = setup
    spec = make_speculative_generator(
        target, target, max_new_tokens=12, k=4
    )
    got = np.asarray(spec(tp, tp, prompt))
    np.testing.assert_array_equal(got, want)


@pytest.mark.parametrize("k", [1, 2, 5])
def test_exact_parity_across_k(setup, k):
    target, draft, prompt, tp, dp, want = setup
    spec = make_speculative_generator(target, draft, max_new_tokens=12, k=k)
    np.testing.assert_array_equal(np.asarray(spec(tp, dp, prompt)), want)


def test_eos_masks_tail(setup):
    target, draft, prompt, tp, dp, want = setup
    eos = int(want[0, 4])  # force an 'EOS' at a known emitted position
    spec = make_speculative_generator(
        target, draft, max_new_tokens=12, k=3, eos_id=eos, pad_id=0
    )
    got = np.asarray(spec(tp, dp, prompt))[0]
    first = int(np.argmax(got == eos))
    assert got[first] == eos
    assert (got[first + 1 :] == 0).all()


def test_guard_rails(setup):
    target, draft, prompt, tp, dp, _ = setup
    with pytest.raises(ValueError, match="k must be"):
        make_speculative_generator(target, draft, max_new_tokens=4, k=0)
    with pytest.raises(ValueError, match="vocab"):
        make_speculative_generator(
            target, draft.clone(vocab_size=32), max_new_tokens=4
        )
    spec = make_speculative_generator(target, draft, max_new_tokens=4, k=2)
    with pytest.raises(ValueError, match="batch-1"):
        spec(tp, dp, jnp.zeros((2, 8), jnp.int32))
    with pytest.raises(ValueError, match="exceeds"):
        make_speculative_generator(target, draft, max_new_tokens=60, k=4)(
            tp, dp, prompt
        )


def test_stats_counts_target_calls(setup):
    target, draft, prompt, tp, dp, want = setup
    spec = make_speculative_generator(
        target, target, max_new_tokens=12, k=3, return_stats=True
    )
    toks, iters = spec(tp, tp, prompt)
    np.testing.assert_array_equal(np.asarray(toks), want)
    # Self-draft accepts all k proposals every call (each call emits
    # k+1 = 4 tokens past the free prefill token): ceil(11/4) = 3.
    # This pins the draft-cache completeness fix — the missing pos+k
    # row used to cost an extra call here.
    assert int(iters) == 3, int(iters)
    # A (worst-case) random draft can never need more than one call per
    # emitted token after the free prefill token.
    specr = make_speculative_generator(
        target, draft, max_new_tokens=12, k=3, return_stats=True
    )
    _, iters_r = specr(tp, dp, prompt)
    assert 3 <= int(iters_r) <= 11


# --------------------------------------------------------------------------
# Rejection-sampling mode (round 4, VERDICT r3 #3b)
# --------------------------------------------------------------------------
def _chi2_threshold(df: int, z: float = 3.09) -> float:
    """Wilson-Hilferty chi-square quantile approximation (z=3.09 ~
    alpha 0.001)."""
    a = 2.0 / (9.0 * df)
    return df * (1.0 - a + z * (a ** 0.5)) ** 3


def test_sampling_speculative_distribution_exact():
    """The emitted (t1, t2) pair distribution must equal sampling the
    TARGET alone: chi-square of N vmapped generations against the
    analytic p(t1) * p(t2 | t1) on a V=8 vocab, alpha=0.001. This
    exercises prefill sampling, probabilistic accept/reject against a
    DIFFERENT draft, and the residual distribution — any bias in any of
    them shifts cell counts."""
    vocab, temp, n_samples = 8, 1.3, 4000
    target = _model(1, vocab_size=vocab, d_model=32, d_ff=64, num_heads=2,
                    num_kv_heads=2, max_seq_len=32)
    draft = _model(1, vocab_size=vocab, d_model=16, d_ff=32, num_heads=2,
                   num_kv_heads=2, max_seq_len=32)
    prompt = jnp.asarray([[1, 5, 2, 7]], jnp.int32)
    tp = target.init(jax.random.key(10), prompt)["params"]
    dp = draft.init(jax.random.key(11), prompt)["params"]

    # Analytic target distribution at the shared temperature.
    logits = target.apply({"params": tp}, prompt)
    p1 = jax.nn.softmax(logits[0, -1].astype(jnp.float32) / temp)
    p2 = np.zeros((vocab, vocab))
    for t1 in range(vocab):
        ext = jnp.concatenate(
            [prompt, jnp.asarray([[t1]], jnp.int32)], axis=1
        )
        lg = target.apply({"params": tp}, ext)
        p2[t1] = np.asarray(
            jax.nn.softmax(lg[0, -1].astype(jnp.float32) / temp)
        )
    joint = np.asarray(p1)[:, None] * p2  # [V, V]

    gen = make_speculative_generator(
        target, draft, max_new_tokens=2, k=2, temperature=temp,
    )
    keys = jax.random.split(jax.random.key(42), n_samples)
    outs = jax.vmap(lambda key: gen(tp, dp, prompt, key))(keys)
    outs = np.asarray(outs)[:, 0, :]  # [N, 2]

    counts = np.zeros((vocab, vocab))
    np.add.at(counts, (outs[:, 0], outs[:, 1]), 1)

    # Pool cells with tiny expectation (chi-square validity).
    exp = joint.ravel() * n_samples
    obs = counts.ravel()
    big = exp >= 5.0
    obs_b = np.append(obs[big], obs[~big].sum())
    exp_b = np.append(exp[big], exp[~big].sum())
    keep = exp_b > 0
    chi2 = float((((obs_b - exp_b) ** 2) / np.where(keep, exp_b, 1.0))[keep].sum())
    df = int(keep.sum()) - 1
    assert chi2 < _chi2_threshold(df), (chi2, _chi2_threshold(df), df)


def test_sampling_speculative_rejections_happen(setup):
    """With a DIFFERENT draft the accept test must actually reject
    sometimes (otherwise the distribution test above only covered the
    all-accept path): realized acceptance strictly below 1."""
    target, draft, prompt, tp, dp, _ = setup
    gen = make_speculative_generator(
        target, draft, max_new_tokens=24, k=4, temperature=1.0,
        return_stats=True,
    )
    toks, iters = gen(tp, dp, prompt, jax.random.key(0))
    acc = (24 / float(iters) - 1.0) / 4
    assert 0.0 <= acc < 0.95, acc
    assert toks.shape == (1, 24)


def test_sampling_speculative_self_draft_accepts(setup):
    """target-as-draft: p == q, the accept ratio is 1, every window
    fully accepts — iters == ceil((max_new_tokens-1) / (k+1))."""
    target, _, prompt, tp, _, _ = setup
    gen = make_speculative_generator(
        target, target, max_new_tokens=16, k=3, temperature=0.8,
        return_stats=True,
    )
    toks, iters = gen(tp, tp, prompt, jax.random.key(1))
    assert int(iters) == -(-(16 - 1) // 4), int(iters)
