"""Rotary position embeddings: relative-shift property, sequence-parallel
exactness, cached-decode parity — the three ways RoPE positions can go
wrong."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from cs744_pytorch_distributed_tutorial_tpu.models import TransformerLM
from cs744_pytorch_distributed_tutorial_tpu.models.transformer import apply_rope

KW = dict(vocab_size=64, num_layers=2, num_heads=4, d_model=64, d_ff=128,
          max_seq_len=256)


def test_rope_rotation_preserves_norm_and_relativity():
    rng = np.random.default_rng(0)
    q = jnp.asarray(rng.standard_normal((1, 8, 2, 16)).astype(np.float32))
    k = jnp.asarray(rng.standard_normal((1, 8, 2, 16)).astype(np.float32))
    pos = jnp.arange(8)

    rq, rk = apply_rope(q, pos), apply_rope(k, pos)
    np.testing.assert_allclose(  # rotation: norms unchanged
        np.linalg.norm(np.asarray(rq), axis=-1),
        np.linalg.norm(np.asarray(q), axis=-1),
        rtol=1e-5,
    )
    # Relative property: scores depend only on position DIFFERENCES —
    # shifting every position by a constant leaves q_i . k_j unchanged.
    rq2, rk2 = apply_rope(q, pos + 57), apply_rope(k, pos + 57)
    s1 = jnp.einsum("bqhd,bkhd->bhqk", rq, rk)
    s2 = jnp.einsum("bqhd,bkhd->bhqk", rq2, rk2)
    np.testing.assert_allclose(np.asarray(s1), np.asarray(s2), rtol=1e-4,
                               atol=1e-4)

    with pytest.raises(ValueError, match="even"):
        apply_rope(jnp.zeros((1, 4, 2, 15)), jnp.arange(4))


def test_rope_drops_pos_embed_param():
    toks = jnp.zeros((1, 8), jnp.int32)
    with_rope = TransformerLM(**KW, use_rope=True).init(jax.random.key(0), toks)
    without = TransformerLM(**KW).init(jax.random.key(0), toks)
    assert "pos_embed" not in with_rope["params"]
    assert "pos_embed" in without["params"]


def test_rope_seq_parallel_matches_single_device():
    """Sharded q/k rotate by GLOBAL positions: the ring step's loss equals
    the unsharded model's."""
    from cs744_pytorch_distributed_tutorial_tpu.data import synthetic_tokens
    from cs744_pytorch_distributed_tutorial_tpu.parallel import make_mesh
    from cs744_pytorch_distributed_tutorial_tpu.train import LMConfig, LMTrainer

    base = dict(vocab_size=64, num_layers=2, num_heads=4, d_model=64,
                d_ff=128, max_seq_len=256, global_batch_size=4, seq_len=64,
                use_rope=True)
    tokens = synthetic_tokens(4, 64, 64, seed=5)

    cfg1 = LMConfig(**base, attention_impl="dense",
                    data_parallel=1, seq_parallel=1)
    tr1 = LMTrainer(cfg1, mesh=make_mesh({"data": 1, "seq": 1},
                                         devices=jax.devices()[:1]))
    p1, _ = tr1.init()
    x1, y1 = tr1.shard_batch(tokens)
    l1 = float(tr1.eval_step(p1, x1, y1)["loss"])

    cfg8 = LMConfig(**base, attention_impl="ring",
                    data_parallel=2, seq_parallel=4)
    tr8 = LMTrainer(cfg8, mesh=make_mesh({"data": 2, "seq": 4}))
    p8, _ = tr8.init()
    x8, y8 = tr8.shard_batch(tokens)
    l8 = float(tr8.eval_step(p8, x8, y8)["loss"])
    assert l8 == pytest.approx(l1, rel=1e-5)


def test_rope_cached_decode_matches_full_forward():
    """Decode rotates the new token's q/k by its cache position: cached
    logits must equal teacher forcing."""
    model = TransformerLM(vocab_size=61, num_layers=2, num_heads=2,
                          d_model=32, d_ff=64, max_seq_len=32,
                          attention_impl="dense", use_rope=True)
    params = model.init(jax.random.key(0), jnp.zeros((1, 4), jnp.int32))["params"]
    tokens = jax.random.randint(jax.random.key(1), (2, 12), 0, 61)
    full = model.apply({"params": params}, tokens)

    t0 = 5
    prefill, variables = model.apply(
        {"params": params}, tokens[:, :t0], mode="prefill", mutable=["cache"]
    )
    np.testing.assert_allclose(prefill, full[:, :t0], rtol=1e-5, atol=1e-5)
    cache = variables["cache"]
    for pos in range(t0, tokens.shape[1]):
        logits, mutated = model.apply(
            {"params": params, "cache": cache},
            tokens[:, pos : pos + 1],
            mode="decode",
            decode_pos=jnp.asarray(pos, jnp.int32),
            mutable=["cache"],
        )
        cache = mutated["cache"]
        np.testing.assert_allclose(
            logits[:, 0], full[:, pos], rtol=1e-5, atol=1e-5
        )


def test_rope_generation_end_to_end():
    from cs744_pytorch_distributed_tutorial_tpu.infer import make_generator

    model = TransformerLM(vocab_size=61, num_layers=1, num_heads=2,
                          d_model=32, d_ff=64, max_seq_len=32,
                          attention_impl="dense", use_rope=True,
                          tie_embeddings=True)
    params = model.init(jax.random.key(0), jnp.zeros((1, 4), jnp.int32))["params"]
    prompt = jax.random.randint(jax.random.key(2), (2, 6), 0, 61)
    out = make_generator(model, max_new_tokens=5, temperature=0.0)(
        params, prompt, jax.random.key(3)
    )
    # Greedy must equal the naive grow-and-rerun loop.
    seq = prompt
    for _ in range(5):
        nxt = jnp.argmax(model.apply({"params": params}, seq)[:, -1], -1)
        seq = jnp.concatenate([seq, nxt[:, None]], axis=1)
    np.testing.assert_array_equal(np.asarray(out), np.asarray(seq[:, 6:]))
