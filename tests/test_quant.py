"""Weight-only int8 decode path (ops/quant.py).

The reference never runs quantized inference (its eval loop is float,
``master/part1/part1.py:47-62``) — this is a framework capability test:
kernel-vs-oracle exactness, quantization error bounds, the param-tree
transform, and end-to-end cached generation on the quantized model.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from cs744_pytorch_distributed_tutorial_tpu.models import TransformerLM
from cs744_pytorch_distributed_tutorial_tpu.ops.quant import (
    int8_matmul,
    int8_matmul_ref,
    quantize_int8,
    quantize_lm_params,
)


def test_quantize_int8_roundtrip_error():
    w = jax.random.normal(jax.random.key(0), (256, 512), jnp.float32)
    q, scale = quantize_int8(w)
    assert q.dtype == jnp.int8 and scale.shape == (512,)
    deq = q.astype(jnp.float32) * scale[None, :]
    # Symmetric per-channel: error is at most half a step (scale/2) up
    # to f32 rounding — w/scale can land within an ULP of a .5 boundary
    # and round() the "wrong" way, overshooting half a step by O(1e-6)
    # relative (observed: one element in 128k at 5.6e-6 of its scale).
    err = np.abs(np.asarray(deq - w))
    assert (err <= np.asarray(scale)[None, :] * (0.5 + 1e-5) + 1e-7).all()
    # Codes stay in the symmetric range.
    assert int(jnp.max(q)) <= 127 and int(jnp.min(q)) >= -127


def test_quantize_int8_zero_column():
    w = jnp.zeros((64, 128), jnp.float32)
    q, scale = quantize_int8(w)
    assert (np.asarray(q) == 0).all()
    assert (np.asarray(scale) == 1.0).all()


@pytest.mark.parametrize("m,k,n", [(16, 256, 512), (100, 128, 300), (1, 512, 1000)])
def test_int8_matmul_matches_ref(m, k, n):
    kx, kw = jax.random.split(jax.random.key(1))
    x = jax.random.normal(kx, (m, k), jnp.float32).astype(jnp.bfloat16)
    q, scale = quantize_int8(jax.random.normal(kw, (k, n), jnp.float32))
    got = int8_matmul(x, q, scale, interpret=True)
    want = int8_matmul_ref(x, q, scale)
    np.testing.assert_allclose(
        np.asarray(got, np.float32), np.asarray(want, np.float32),
        rtol=2e-2, atol=2e-2,
    )


def test_int8_matmul_leading_dims():
    kx, kw = jax.random.split(jax.random.key(2))
    x = jax.random.normal(kx, (2, 3, 128), jnp.float32)
    q, scale = quantize_int8(jax.random.normal(kw, (128, 256), jnp.float32))
    got = int8_matmul(x, q, scale, interpret=True)
    assert got.shape == (2, 3, 256)
    want = int8_matmul_ref(x, q, scale)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), rtol=1e-5)


def test_int8_matmul_unaligned_k_falls_back():
    # K=96 is not lane-aligned: the wrapper must route to the XLA
    # reference path rather than fail to tile.
    kx, kw = jax.random.split(jax.random.key(3))
    x = jax.random.normal(kx, (4, 96), jnp.float32)
    q, scale = quantize_int8(jax.random.normal(kw, (96, 64), jnp.float32))
    got = int8_matmul(x, q, scale, interpret=True)
    want = int8_matmul_ref(x, q, scale)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), rtol=1e-5)


def _small_lm(quant: bool) -> TransformerLM:
    return TransformerLM(
        vocab_size=512,
        num_layers=2,
        num_heads=4,
        num_kv_heads=2,
        d_model=128,
        d_ff=256,
        max_seq_len=64,
        dtype=jnp.float32,
        attention_impl="dense",
        use_rope=True,
        quant_dense=quant,
        flash_interpret=True,
    )


def test_quantize_lm_params_tree_shape():
    model = _small_lm(False)
    params = model.init(jax.random.key(0), jnp.zeros((1, 8), jnp.int32))["params"]
    qparams = quantize_lm_params(params)
    blk = qparams["block_0"]
    for mod in ("q", "k", "v", "attn_out"):
        assert blk["attn"][mod]["qkernel"].dtype == jnp.int8
        assert blk["attn"][mod]["scale"].dtype == jnp.float32
        assert "kernel" not in blk["attn"][mod]
    assert blk["mlp_in"]["qkernel"].dtype == jnp.int8
    assert "bias" in blk["mlp_in"]  # bias rides along unquantized
    assert qparams["lm_head"]["qkernel"].dtype == jnp.int8
    # Embeddings / layernorms untouched.
    assert qparams["tok_embed"]["embedding"].dtype == params["tok_embed"][
        "embedding"
    ].dtype
    assert "scale" in qparams["ln_f"] or "bias" in qparams["ln_f"]
    # The quantized tree is exactly what a quant_dense clone expects.
    qmodel = _small_lm(True)
    ref = qmodel.init(jax.random.key(0), jnp.zeros((1, 8), jnp.int32))["params"]
    assert jax.tree_util.tree_structure(ref) == jax.tree_util.tree_structure(
        qparams
    )


def test_quantized_forward_logits_close():
    model = _small_lm(False)
    tokens = jax.random.randint(jax.random.key(4), (2, 16), 0, 512)
    params = model.init(jax.random.key(0), tokens)["params"]
    logits = model.apply({"params": params}, tokens)
    qlogits = _small_lm(True).apply(
        {"params": quantize_lm_params(params)}, tokens
    )
    # Per-channel int8 keeps logits within a small relative envelope
    # (random init is the worst case — no large-margin structure for the
    # rounding noise to hide under).
    denom = np.maximum(np.abs(np.asarray(logits)), 1.0)
    rel = np.abs(np.asarray(qlogits) - np.asarray(logits)) / denom
    assert rel.max() < 0.1, rel.max()
    # Mean envelope: 1.5% — the random-init worst case sits right at 1%
    # (observed 0.0107 on this backend/jax version; dot-product rounding
    # order moves it a few 1e-4), so 1% left no noise margin.
    assert rel.mean() < 0.015, rel.mean()


def test_head_only_scope():
    from cs744_pytorch_distributed_tutorial_tpu.ops.quant import QUANT_HEAD_ONLY

    model = _small_lm(False)
    params = model.init(jax.random.key(0), jnp.zeros((1, 8), jnp.int32))["params"]
    qparams = quantize_lm_params(params, QUANT_HEAD_ONLY)
    # Only the head converts; per-layer projections keep float kernels.
    assert qparams["lm_head"]["qkernel"].dtype == jnp.int8
    assert "kernel" in qparams["block_0"]["attn"]["q"]
    qmodel = _small_lm(False).clone(
        quant_dense=True, quant_modules=QUANT_HEAD_ONLY
    )
    ref = qmodel.init(jax.random.key(0), jnp.zeros((1, 8), jnp.int32))["params"]
    assert jax.tree_util.tree_structure(ref) == jax.tree_util.tree_structure(
        qparams
    )
    tokens = jax.random.randint(jax.random.key(7), (2, 16), 0, 512)
    logits = model.apply({"params": params}, tokens)
    qlogits = qmodel.apply({"params": qparams}, tokens)
    denom = np.maximum(np.abs(np.asarray(logits)), 1.0)
    rel = np.abs(np.asarray(qlogits) - np.asarray(logits)) / denom
    # One quantized matmul's worth of noise — tighter than the all-module
    # envelope in test_quantized_forward_logits_close.
    assert rel.max() < 0.05, rel.max()


def test_unknown_quant_module_rejected():
    import pytest

    model = _small_lm(False)
    params = model.init(jax.random.key(0), jnp.zeros((1, 8), jnp.int32))["params"]
    with pytest.raises(ValueError, match="unknown quant modules"):
        quantize_lm_params(params, ("lm_head", "tok_embed"))


def test_quantized_generation_runs_and_tracks_float():
    from cs744_pytorch_distributed_tutorial_tpu.infer import make_generator

    model = _small_lm(False)
    prompt = jax.random.randint(jax.random.key(5), (2, 8), 0, 512)
    params = model.init(jax.random.key(0), prompt)["params"]
    gen = make_generator(model, max_new_tokens=8, temperature=0.0)
    qgen = make_generator(_small_lm(True), max_new_tokens=8, temperature=0.0)
    out = np.asarray(gen(params, prompt, jax.random.key(6)))
    qout = np.asarray(
        qgen(quantize_lm_params(params), prompt, jax.random.key(6))
    )
    assert qout.shape == out.shape
    # Greedy decode on a random-init model is a worst case for argmax
    # stability (near-uniform logits) — require agreement on most steps,
    # not all.
    agree = (out == qout).mean()
    assert agree >= 0.5, (agree, out, qout)


def test_quantize_kv_roundtrip_error():
    from cs744_pytorch_distributed_tutorial_tpu.ops.quant import quantize_kv

    x = jax.random.normal(jax.random.key(8), (2, 16, 4, 64), jnp.float32)
    q, scale = quantize_kv(x)
    assert q.dtype == jnp.int8 and scale.shape == (2, 16, 4)
    deq = q.astype(jnp.float32) * np.asarray(scale)[..., None]
    err = np.abs(np.asarray(deq) - np.asarray(x))
    # Per-row symmetric: error bounded by half a step of that row's scale.
    assert (err <= np.asarray(scale)[..., None] * 0.5 + 1e-7).all()


def test_decode_attention_quant_tracks_float():
    from cs744_pytorch_distributed_tutorial_tpu.ops.quant import (
        decode_attention_quant,
        quantize_kv,
    )
    from cs744_pytorch_distributed_tutorial_tpu.parallel.ring_attention import (
        decode_attention,
    )

    kq, kk, kv_ = jax.random.split(jax.random.key(9), 3)
    b, L, hq, hkv, d = 2, 32, 8, 2, 64
    q = jax.random.normal(kq, (b, 1, hq, d), jnp.float32)
    k = jax.random.normal(kk, (b, L, hkv, d), jnp.float32)
    v = jax.random.normal(kv_, (b, L, hkv, d), jnp.float32)
    pos = jnp.asarray(20, jnp.int32)
    want = np.asarray(decode_attention(q, k, v, pos))
    kq8, ks = quantize_kv(k)
    vq8, vs = quantize_kv(v)
    got = np.asarray(decode_attention_quant(q, kq8, vq8, ks, vs, pos))
    # Int8 KV noise stays small relative to the attention output scale.
    denom = np.maximum(np.abs(want), 0.1)
    assert (np.abs(got - want) / denom).mean() < 0.02
    # Masked region must not leak: positions > pos get exactly 0 weight,
    # so perturbing them changes nothing.
    vq8_b = vq8.at[:, 25:].set(127)
    got2 = np.asarray(decode_attention_quant(q, kq8, vq8_b, ks, vs, pos))
    np.testing.assert_array_equal(got, got2)


def test_quant_kv_cache_generation_tracks_float():
    from cs744_pytorch_distributed_tutorial_tpu.infer import make_generator

    model = _small_lm(False)
    prompt = jax.random.randint(jax.random.key(10), (2, 8), 0, 512)
    params = model.init(jax.random.key(0), prompt)["params"]
    gen = make_generator(model, max_new_tokens=8, temperature=0.0)
    qgen = make_generator(
        model.clone(quant_kv_cache=True), max_new_tokens=8, temperature=0.0
    )
    out = np.asarray(gen(params, prompt, jax.random.key(6)))
    qout = np.asarray(qgen(params, prompt, jax.random.key(6)))
    assert qout.shape == out.shape
    assert (out == qout).mean() >= 0.5, (out, qout)


def test_quant_kv_cache_beam_runs():
    from cs744_pytorch_distributed_tutorial_tpu.infer import make_beam_searcher

    model = _small_lm(False).clone(quant_kv_cache=True)
    prompt = jax.random.randint(jax.random.key(11), (1, 6), 0, 512)
    params = model.init(jax.random.key(0), prompt)["params"]
    search = make_beam_searcher(model, beam_size=2, max_new_tokens=4)
    out, scores = search(params, prompt)
    assert out.shape == (1, 4) and np.isfinite(np.asarray(scores)).all()


@pytest.mark.slow
def test_quantized_eval_loss_close_after_training():
    """Quality evidence on a TRAINED model (random-init logit noise says
    little about deployment): int8-all quantization moves held-out
    cross-entropy by under 2% relative."""
    from cs744_pytorch_distributed_tutorial_tpu.data import synthetic_tokens
    from cs744_pytorch_distributed_tutorial_tpu.ops.quant import QUANT_MODULES
    from cs744_pytorch_distributed_tutorial_tpu.train import LMConfig, LMTrainer

    cfg = LMConfig(
        vocab_size=64,
        num_layers=2,
        num_heads=4,
        d_model=128,  # lane-aligned: the real kernel path (interpret)
        d_ff=256,
        max_seq_len=64,
        seq_len=32,
        attention_impl="dense",
        global_batch_size=8,
        learning_rate=3e-3,
        use_rope=True,
    )
    tr = LMTrainer(cfg)
    tokens = synthetic_tokens(64, 32, 64, seed=0)
    params, _, losses = tr.fit(tokens[:48], 40)
    assert losses[-1] < losses[0]
    host = tr.gather_for_decode(params)
    heldout = jnp.asarray(tokens[48:, :32], jnp.int32)
    targets = jnp.asarray(tokens[48:, 1:33], jnp.int32)

    def ce(model, p):
        logits = model.apply({"params": p}, heldout)
        logp = jax.nn.log_softmax(logits, axis=-1)
        return float(
            -jnp.take_along_axis(logp, targets[..., None], axis=-1).mean()
        )

    fp = ce(tr.decode_model(), host)
    mods = tuple(sorted(QUANT_MODULES))
    q8 = ce(
        tr.quantized_decode_model("all"),
        quantize_lm_params(host, mods),
    )
    assert abs(q8 - fp) < 0.02 * max(fp, 1.0), (fp, q8)


def test_tied_embeddings_kv_only_decode_model():
    """ADVICE r3: tie_embeddings + modules='head' used to raise even with
    kv_cache=True — while the error message recommended kv_cache=True.
    The KV-only request is legitimate (the weight scope degrades to a
    no-op pass-through): it must return a cache-quantized float-weight
    model, and still raise without the cache."""
    import pytest

    from cs744_pytorch_distributed_tutorial_tpu.train import LMConfig, LMTrainer

    cfg = LMConfig(
        vocab_size=64, num_layers=1, num_heads=2, d_model=32, d_ff=64,
        max_seq_len=64, seq_len=32, global_batch_size=4,
        attention_impl="dense", tie_embeddings=True,
    )
    tr = LMTrainer(cfg)
    m = tr.quantized_decode_model("head", kv_cache=True)
    assert m.quant_kv_cache and not m.quant_dense
    with pytest.raises(ValueError, match="no-op with tied embeddings"):
        tr.quantized_decode_model("head", kv_cache=False)
