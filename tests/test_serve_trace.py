"""graftserve (obs/serve_trace.py): the serving observability contracts.

What this file pins, in dependency order:

1. **Spans are consistent.** Every request's lifecycle closes — queue ->
   admission -> decode_run -> retire — with no orphan, unclosed, or
   overlapping spans, INCLUDING under LIFO recompute preemption and
   kill/resume replay (the two paths that re-open queue spans and
   re-admit under a different kind).
2. **Span arithmetic reconciles with the recorded metrics.** The tracer
   stores the engine's own clock stamps, so queue+prefill span sums
   equal the recorded TTFT exactly — ``reconcile`` is the CI gate's
   second half.
3. **The Chrome/Perfetto export is structurally valid.** X events carry
   durations on slot lanes, queue waits are paired async b/e events,
   counter tracks sample the pool.
4. **Windowed SLO percentiles agree with the post-hoc summary.** The
   tracer's reservoirs are fed the same floats ``loadgen._summarize``
   diffs, so the final window's p50/p99 match the ``serve_summary``.
5. **Tracing is free.** The decode CompileCounter stays at zero
   post-warmup with the tracer attached (GL002 stays executable), and
   ``profile_serve_programs`` — which DOES compile — leaves the live
   engine's state intact despite the donated pages argument.

Plus the serve-report CLI exit codes, the flight-recorder serve tail,
and the metrics_summary serve_window/serve_phase rows.
"""

from __future__ import annotations

import json

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from cs744_pytorch_distributed_tutorial_tpu.models import TransformerLM
from cs744_pytorch_distributed_tutorial_tpu.obs.serve_trace import (
    PREFILL_KINDS,
    ServeTracer,
    check_spans,
    load_trace_dir,
    profile_serve_programs,
    reconcile,
)
from cs744_pytorch_distributed_tutorial_tpu.serve import (
    Request,
    ServeConfig,
    ServingEngine,
    make_poisson_workload,
    run_poisson,
)

VOCAB = 61


class _ListSink:
    def __init__(self):
        self.records = []

    def emit(self, record):
        self.records.append(dict(record))


@pytest.fixture(scope="module")
def tiny_lm():
    model = TransformerLM(
        vocab_size=VOCAB,
        num_layers=2,
        num_heads=2,
        d_model=32,
        d_ff=64,
        max_seq_len=64,
        attention_impl="dense",
        use_rope=True,
    )
    params = model.init(
        jax.random.key(0), jnp.zeros((1, 4), jnp.int32)
    )["params"]
    return model, params


def _submit(eng, cases, data_seed=13):
    rng = np.random.default_rng(data_seed)
    return [
        eng.submit(Request(
            prompt=rng.integers(1, VOCAB, size=plen).astype(np.int32),
            max_new_tokens=budget,
        ))
        for plen, budget in cases
    ]


# Pool sized to force LIFO recompute preemption (mirrors
# test_engine_preemption_completes_everything).
TIGHT = dict(num_slots=3, page_size=4, num_pages=9, max_pages_per_slot=7)
TIGHT_CASES = [(6, 18), (10, 14), (8, 16), (5, 20), (12, 12)]


def test_spans_close_and_reconcile_under_preemption(tiny_lm):
    """A preemption-heavy run produces a fully consistent span set whose
    queue+prefill sums reconcile with the engine-recorded TTFTs — the
    exact audit CI's serve-smoke gate runs."""
    model, params = tiny_lm
    tracer = ServeTracer(TIGHT["num_slots"])
    eng = ServingEngine(
        model, params, ServeConfig(**TIGHT), tracer=tracer
    )
    _submit(eng, TIGHT_CASES)
    eng.run()
    assert eng.stats()["preemptions"] > 0, "pool was not tight enough"

    spans = tracer.all_spans()
    assert check_spans(spans) == []
    assert reconcile(spans, tracer.requests) == []
    names = {s["name"] for s in spans}
    assert "recompute" in names  # preemptions re-admit under a new kind
    preempts = [s for s in spans if s["name"] == "preempt"]
    assert len(preempts) == eng.stats()["preemptions"]
    retires = [s for s in spans if s["name"] == "retire"]
    assert len(retires) == len(TIGHT_CASES)
    assert len(tracer.requests) == len(TIGHT_CASES)
    # queue and admission tile exactly: same float at the boundary
    by_req = {}
    for s in spans:
        by_req.setdefault(s["req"], []).append(s)
    for rid, sps in by_req.items():
        queues = sorted(
            (s for s in sps if s["name"] == "queue"), key=lambda s: s["t0"]
        )
        admits = sorted(
            (s for s in sps if s["name"] in PREFILL_KINDS),
            key=lambda s: s["t0"],
        )
        assert len(queues) == len(admits), rid
        for q, a in zip(queues, admits):
            assert q["t1"] == a["t0"], rid


@pytest.mark.slow  # serve-smoke CI runs this file without the tier-1 filter
def test_spans_close_across_kill_resume(tiny_lm):
    """Kill mid-decode, resume on a fresh engine with its own tracer:
    the fresh timeline is consistent, in-flight requests re-admit as
    resume-replay spans with the replayed token count, and their request
    records carry the recovered flag (reconcile skips them — their
    arrival stamps belong to the dead process's clock epoch)."""
    model, params = tiny_lm
    cfg = ServeConfig(num_slots=2, page_size=4, num_pages=33,
                      max_pages_per_slot=8, seed=3)
    victim = ServingEngine(model, params, cfg)
    _submit(victim, [(3, 9), (7, 4), (12, 11), (5, 17)], data_seed=7)
    for _ in range(5):
        victim.step()
    assert victim.busy
    snap = victim.snapshot()
    in_flight = sum(1 for rec in snap.requests if rec["in_flight"])
    assert in_flight > 0
    del victim

    tracer = ServeTracer(cfg.num_slots)
    fresh = ServingEngine(model, params, cfg, tracer=tracer)
    fresh.resume(snap)
    fresh.run()

    spans = tracer.all_spans()
    assert check_spans(spans) == []
    assert reconcile(spans, tracer.requests) == []
    replays = [s for s in spans if s["name"] == "resume-replay"]
    assert len(replays) == in_flight
    assert all(s.get("replayed", 0) > 0 for s in replays)
    recovered = [r for r in tracer.requests if r["recovered"]]
    assert len(recovered) == len(snap.requests)


def test_tracer_rejects_mismatched_slot_count(tiny_lm):
    model, params = tiny_lm
    cfg = ServeConfig(num_slots=2, page_size=4, num_pages=17,
                      max_pages_per_slot=8)
    with pytest.raises(ValueError, match="slots"):
        ServingEngine(model, params, cfg, tracer=ServeTracer(4))


def test_chrome_trace_is_structurally_valid(tiny_lm):
    """The export is JSON-serializable trace-event format: slot-lane X
    events with durations, paired async b/e queue events, instants,
    metadata naming every lane, and pool counter samples."""
    model, params = tiny_lm
    tracer = ServeTracer(TIGHT["num_slots"])
    eng = ServingEngine(
        model, params, ServeConfig(**TIGHT), tracer=tracer
    )
    _submit(eng, TIGHT_CASES)
    eng.run()

    trace = json.loads(json.dumps(tracer.to_chrome_trace()))
    events = trace["traceEvents"]
    assert trace["displayTimeUnit"] == "ms"

    meta = [e for e in events if e["ph"] == "M"]
    lane_names = {e["args"]["name"] for e in meta
                  if e["name"] == "thread_name"}
    assert "queue" in lane_names
    for s in range(TIGHT["num_slots"]):
        assert f"slot {s}" in lane_names

    xs = [e for e in events if e["ph"] == "X"]
    assert xs
    for e in xs:
        assert e["dur"] > 0
        assert 1 <= e["tid"] <= TIGHT["num_slots"]
        assert e["ts"] >= 0

    begins = [e for e in events if e["ph"] == "b"]
    ends = [e for e in events if e["ph"] == "e"]
    assert begins and sorted(e["id"] for e in begins) == sorted(
        e["id"] for e in ends
    )

    counters = {e["name"] for e in events if e["ph"] == "C"}
    assert {"kv_pages", "slots_active", "queue_depth"} <= counters
    instants = [e for e in events if e["ph"] == "i"]
    assert any(e["name"].startswith("retire") for e in instants)
    assert any(e["name"].startswith("preempt") for e in instants)


def test_windowed_percentiles_match_posthoc_summary(tiny_lm):
    """The tracer's TTFT/ITL reservoirs are fed the same floats
    ``loadgen._summarize`` percentiles, with the same resume-boundary
    exclusion — so the final flushed window agrees with the post-hoc
    serve_summary record."""
    model, params = tiny_lm
    cfg = ServeConfig(num_slots=4, page_size=4, num_pages=33,
                      max_pages_per_slot=8)
    tracer = ServeTracer(cfg.num_slots, window_every_s=0.05)
    sink = _ListSink()
    eng = ServingEngine(model, params, cfg, sink=sink, tracer=tracer)
    wl = make_poisson_workload(
        num_requests=12, rate_rps=200.0, prompt_len=(3, 10),
        output_len=(4, 12), vocab_size=VOCAB, seed=5,
    )
    summary = run_poisson(eng, wl, sink=sink)

    assert tracer.windows, "no serve_window flushed"
    last = tracer.windows[-1]
    assert last["ttft_samples"] == len(wl)
    assert last["ttft_p50_ms"] == pytest.approx(
        summary["ttft_p50_ms"], abs=0.01
    )
    assert last["ttft_p99_ms"] == pytest.approx(
        summary["ttft_p99_ms"], abs=0.01
    )
    assert last["itl_p50_ms"] == pytest.approx(
        summary["itl_p50_ms"], abs=0.01
    )
    assert last["itl_p99_ms"] == pytest.approx(
        summary["itl_p99_ms"], abs=0.01
    )
    # the window stream reached the sink (flat records, sink-safe)
    emitted = [r for r in sink.records if r.get("kind") == "serve_window"]
    assert len(emitted) == len(tracer.windows)
    for rec in emitted:
        for v in rec.values():
            assert v is None or isinstance(v, (bool, int, float, str))
    # cadence: every window but the final drain flush spans >= the
    # configured interval
    for w in tracer.windows[:-1]:
        assert w["window_s"] >= tracer.window_every_s
    # per-bucket admission counts total one per admission (first
    # prefill per request + one recompute per preemption)
    admits = sum(
        v for w in tracer.windows for k, v in w.items()
        if k.startswith("prefill_bucket_")
    )
    assert admits == len(wl) + summary["preemptions"]


def test_zero_retraces_with_tracing_on(tiny_lm):
    """The tracer is pure host-side bookkeeping: the decode step still
    never recompiles across slot churn once warm (the GL002 contract
    must survive observability)."""
    from cs744_pytorch_distributed_tutorial_tpu.obs.system import (
        CompileCounter,
    )

    model, params = tiny_lm
    cfg = ServeConfig(num_slots=3, page_size=4, num_pages=33,
                      max_pages_per_slot=8)
    tracer = ServeTracer(cfg.num_slots, window_every_s=0.01)
    eng = ServingEngine(
        model, params, cfg, sink=_ListSink(), tracer=tracer
    )
    rng = np.random.default_rng(11)

    def burst(sizes):
        for plen, budget in sizes:
            eng.submit(Request(
                prompt=rng.integers(1, VOCAB, size=plen).astype(np.int32),
                max_new_tokens=budget,
            ))
        eng.run()

    burst([(4, 3), (8, 5)])  # warmup: compiles prefill buckets + decode
    cc = CompileCounter()
    burst([(3, 8), (6, 2), (8, 7), (5, 3), (7, 12), (4, 2)])
    assert cc.count == 0, f"{cc.count} retraces with tracing on"
    assert check_spans(tracer.all_spans(), require_retired=False) == []


def test_check_spans_catches_synthetic_corruption():
    """The audit actually fires: unclosed spans, overlaps, missing
    queue provenance, orphans, and double retires all surface."""
    ok = [
        {"name": "queue", "req": 1, "slot": None, "t0": 0.0, "t1": 1.0},
        {"name": "prefill", "req": 1, "slot": 0, "bucket": 8,
         "t0": 1.0, "t1": 2.0},
        {"name": "decode_run", "req": 1, "slot": 0, "t0": 2.0, "t1": 3.0,
         "tokens": 4},
        {"name": "retire", "req": 1, "slot": 0, "t0": 3.0, "t1": 3.0},
    ]
    assert check_spans(ok) == []

    unclosed = [dict(ok[0], t1=None)] + ok[1:]
    assert any("unclosed" in p for p in check_spans(unclosed))

    overlap = ok[:2] + [
        {"name": "decode_run", "req": 1, "slot": 0, "t0": 1.5, "t1": 3.0,
         "tokens": 4},
        ok[3],
    ]
    assert any("overlap" in p for p in check_spans(overlap))

    no_queue = ok[1:]
    problems = check_spans(no_queue)
    assert any("queue" in p for p in problems)

    orphan = ok[:3]
    assert any("never retired" in p for p in check_spans(orphan))
    assert check_spans(orphan, require_retired=False) == []

    twice = ok + [dict(ok[3])]
    assert any("retire instants" in p for p in check_spans(twice))

    backwards = [dict(ok[0], t0=1.0, t1=0.0)] + ok[1:]
    assert any("ends before" in p for p in check_spans(backwards))


def test_reconcile_catches_ttft_drift():
    spans = [
        {"name": "queue", "req": 0, "slot": None, "t0": 0.0, "t1": 0.010},
        {"name": "prefill", "req": 0, "slot": 0, "bucket": 8,
         "t0": 0.010, "t1": 0.020},
    ]
    good = [{"req": 0, "tokens": 4, "preemptions": 0, "recovered": False,
             "ttft_ms": 20.0}]
    assert reconcile(spans, good) == []
    drifted = [dict(good[0], ttft_ms=35.0)]
    assert any("TTFT" in p for p in reconcile(spans, drifted))
    # recovered requests are exempt: cross-epoch stamps can't reconcile
    assert reconcile(spans, [dict(drifted[0], recovered=True)]) == []


@pytest.mark.slow  # serve-smoke CI runs this file without the tier-1 filter
def test_profile_serve_programs_attributes_and_preserves_state(tiny_lm):
    """Serve-side graftscope: one serve_phase record per program with
    flops/bytes/roofline, a summary with decode_host_exposed_ms, and —
    despite the donated pages argument — the live engine still serves
    correctly afterwards."""
    model, params = tiny_lm
    cfg = ServeConfig(num_slots=2, page_size=4, num_pages=17,
                      max_pages_per_slot=8)
    eng = ServingEngine(model, params, cfg)
    reqs = _submit(eng, [(4, 6), (9, 5)], data_seed=17)
    eng.run()
    expect = [list(r.generated) for r in reqs]

    records = profile_serve_programs(eng, iters=2)
    phases = [r for r in records if r["kind"] == "serve_phase"]
    names = {r["phase"] for r in phases}
    assert "decode" in names
    assert names == {"decode"} | {
        f"prefill[bucket={b}]" for b in eng._prefill_cache
    }
    for r in phases:
        assert r["flops"] is None or r["flops"] >= 0
        assert r["clock"] in ("device", "wall")
        assert r["wall_ms"] > 0
        assert r["roofline"] in ("compute", "memory", "comms", "unknown")
    summaries = [r for r in records if r["kind"] == "serve_phase_summary"]
    assert len(summaries) == 1
    s = summaries[0]
    assert s["decode_steps_observed"] > 0
    assert s["decode_host_exposed_ms"] >= 0
    assert s["decode_host_ms"] >= s["decode_host_exposed_ms"]

    # donation safety: the profiled copies absorbed the donations; the
    # engine's own pools still produce identical streams
    again = _submit(eng, [(4, 6), (9, 5)], data_seed=17)
    eng.run()
    assert [list(r.generated) for r in again] == expect


def test_write_and_serve_report_cli(tiny_lm, tmp_path, capsys):
    """tracer.write() + the obs serve-report subcommand: a clean trace
    passes --check (exit 0); a corrupted span file fails (exit 1)."""
    from cs744_pytorch_distributed_tutorial_tpu.obs.__main__ import main

    model, params = tiny_lm
    tracer = ServeTracer(TIGHT["num_slots"], window_every_s=0.01)
    eng = ServingEngine(
        model, params, ServeConfig(**TIGHT), sink=_ListSink(),
        tracer=tracer,
    )
    _submit(eng, TIGHT_CASES)
    eng.run()
    eng.finalize_trace()
    good = tmp_path / "trace"
    paths = tracer.write(str(good))
    with open(paths["trace"], encoding="utf-8") as f:
        assert json.load(f)["traceEvents"]

    data = load_trace_dir(str(good))
    assert data["spans"] and data["requests"] and data["windows"]
    assert main(["serve-report", str(good), "--check"]) == 0
    out = capsys.readouterr().out
    assert "serve-trace check: OK" in out
    assert "span kinds" in out

    # corrupt: drop every retire span -> orphan lifecycles
    spans_file = good / "serve_spans.jsonl"
    rows = [json.loads(line) for line in
            spans_file.read_text().splitlines() if line.strip()]
    spans_file.write_text("\n".join(
        json.dumps(r) for r in rows if r["name"] != "retire"
    ) + "\n")
    assert main(["serve-report", str(good), "--check"]) == 1
    assert "never retired" in capsys.readouterr().err

    with pytest.raises(FileNotFoundError):
        load_trace_dir(str(tmp_path / "empty"))


def test_flight_recorder_dumps_serve_tail(tiny_lm):
    """make_flight_recorder(): a dump carries the scheduler header
    (queue depth, pool counters) and replays the serve event ring as
    flight_serve records through the engine's own sink."""
    model, params = tiny_lm
    sink = _ListSink()
    cfg = ServeConfig(**TIGHT)
    eng = ServingEngine(model, params, cfg, sink=sink)
    _submit(eng, TIGHT_CASES)
    eng.run()
    fr = eng.make_flight_recorder(hbm=False)
    fr.dump("test")

    dumps = [r for r in sink.records
             if r.get("kind") == "event" and r.get("event") == "flight_dump"]
    assert len(dumps) == 1
    header = dumps[0]
    assert header["reason"] == "test"
    assert header["queue_depth"] == 0
    assert header["preemptions"] == eng.stats()["preemptions"]
    assert header["page_high_water"] == eng.pool.high_water
    assert header["page_churn"] > 0
    assert header["trash_rows_written"] > 0
    tails = [r for r in sink.records if r.get("event") == "flight_serve"]
    assert tails
    # ring records re-keyed: engine "event" -> "serve_event", no "kind"
    # collision with the wrapper
    assert all("serve_event" in r for r in tails)
    assert any(r["serve_event"] == "request" for r in tails)


def test_pool_counts_churn(tiny_lm):
    """PagePool cumulative alloc/free counters feed page_churn; a
    drained run's allocs equal its frees."""
    model, params = tiny_lm
    cfg = ServeConfig(num_slots=2, page_size=4, num_pages=17,
                      max_pages_per_slot=8)
    eng = ServingEngine(model, params, cfg)
    _submit(eng, [(4, 6), (9, 8), (6, 10)], data_seed=17)
    eng.run()
    assert eng.pool.total_allocs > 0
    assert eng.pool.total_allocs == eng.pool.total_frees
    stats = eng.stats()
    assert stats["page_churn"] == (
        eng.pool.total_allocs + eng.pool.total_frees
    )
    assert stats["trash_rows_written"] == eng._trash_rows > 0


def test_metrics_summary_renders_serve_window_rows(tmp_path, capsys):
    """summarize() aggregates serve_window records and serve_phase rows
    next to the existing serve rows, and main() renders them."""
    import importlib.util
    from pathlib import Path

    spec = importlib.util.spec_from_file_location(
        "metrics_summary",
        Path(__file__).resolve().parents[1]
        / "benchmarks" / "metrics_summary.py",
    )
    ms = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(ms)

    records = [
        {"kind": "serve_window", "t_s": 0.25, "window_s": 0.25,
         "ttft_p99_ms": 12.0, "itl_p99_ms": 4.0, "live_pages": 30,
         "queue_depth_max": 5, "preempt_rate_per_s": 8.0},
        {"kind": "serve_window", "t_s": 0.5, "window_s": 0.25,
         "ttft_p99_ms": 9.0, "itl_p99_ms": 3.0, "live_pages": 12,
         "queue_depth_max": 1, "preempt_rate_per_s": 0.0},
        {"kind": "serve_phase", "phase": "decode", "clock": "wall",
         "wall_ms": 1.5, "flops": 1e6, "bytes_accessed": 2e6,
         "roofline": "memory"},
        {"kind": "serve_phase_summary", "decode_host_exposed_ms": 0.4},
    ]
    summary = ms.summarize(records)
    sw = summary["serve_windows"]
    assert sw["count"] == 2
    assert sw["span_s"] == 0.5
    assert sw["ttft_p99_ms_last"] == 9.0
    assert sw["ttft_p99_ms_max"] == 12.0
    assert sw["itl_p99_ms_last"] == 3.0
    assert sw["live_pages_peak"] == 30
    assert sw["queue_depth_max"] == 5
    assert sw["preempt_rate_per_s_max"] == 8.0
    assert summary["serve_decode_host_exposed_ms"] == 0.4
    assert summary["phases"]["serve decode"]["ms"] == 1.5

    path = tmp_path / "metrics.jsonl"
    path.write_text("\n".join(json.dumps(r) for r in records) + "\n")
    assert ms.main([str(path)]) == 0
    out = capsys.readouterr().out
    assert "serve windows" in out
    assert "serve decode host exposed" in out
    assert "phase serve decode" in out

    # absent records -> no rows, no crash
    empty = ms.summarize([{"kind": "step", "loss": 1.0}])
    assert empty["serve_windows"] is None
    assert empty["serve_decode_host_exposed_ms"] is None
