"""Gradient-sync strategy parity.

The reference's central (implicit) property: part2a, part2a_extra, part2b
and part3 compute the SAME update — four mechanisms, one semantics —
which it establishes only by fixed seed + eyeballing loss curves
(SURVEY §4). Here it is a real test: from identical init and an identical
global batch, one train step under every strategy must produce identical
parameters.
"""

import jax
import numpy as np
import pytest

from cs744_pytorch_distributed_tutorial_tpu.config import TrainConfig
from cs744_pytorch_distributed_tutorial_tpu.data import synthetic_cifar10
from cs744_pytorch_distributed_tutorial_tpu.parallel import make_mesh
from cs744_pytorch_distributed_tutorial_tpu.train import Trainer

STRATEGIES = [
    "allreduce",
    "gather_scatter",
    "p2p_star",
    "ring",
    "auto",
    "zero1",
    "fsdp",
]


def _one_step_params(strategy, mesh, batch):
    cfg = TrainConfig(
        model="tiny_cnn",
        sync=strategy,
        num_devices=4,
        global_batch_size=16,
        seed=5000,
    )
    tr = Trainer(cfg, mesh=mesh)
    state = tr.init()
    x, y = batch
    from cs744_pytorch_distributed_tutorial_tpu.parallel.mesh import shard_global_batch

    gx, gy = shard_global_batch(mesh, x, y)
    key = jax.random.key(cfg.seed)
    new_state, metrics = tr.train_step(state, gx, gy, key)
    params = jax.device_get(new_state.params)
    if strategy == "fsdp":
        # fsdp persists [axis_size, chunk] flat shards; unshard host-side
        # to the original shapes so the matrix compares like with like.
        import jax.numpy as jnp

        sample = jnp.zeros((1, cfg.image_size, cfg.image_size, 3), jnp.float32)
        shapes = jax.eval_shape(
            lambda: tr.model.init(jax.random.key(0), sample, train=False)
        )["params"]
        params = jax.tree.map(
            lambda sh, ref: np.asarray(sh).reshape(-1)[
                : int(np.prod(ref.shape))
            ].reshape(ref.shape),
            params,
            shapes,
        )
    return (
        jax.tree.map(np.asarray, params),
        float(metrics["loss"]),
    )


@pytest.fixture(scope="module")
def batch():
    ds = synthetic_cifar10(64, 16, seed=3)
    return ds.train_images[:16], ds.train_labels[:16]


@pytest.fixture(scope="module")
def results(batch):
    mesh = make_mesh({"data": 4}, devices=jax.devices()[:4])
    return {s: _one_step_params(s, mesh, batch) for s in STRATEGIES}


@pytest.mark.parametrize("strategy", STRATEGIES[1:])
def test_strategies_match_allreduce(results, strategy):
    ref_params, ref_loss = results["allreduce"]
    got_params, got_loss = results[strategy]
    assert got_loss == pytest.approx(ref_loss, rel=1e-6)
    ref_leaves = jax.tree.leaves(ref_params)
    got_leaves = jax.tree.leaves(got_params)
    assert len(ref_leaves) == len(got_leaves)
    for r, g in zip(ref_leaves, got_leaves):
        np.testing.assert_allclose(g, r, rtol=1e-5, atol=1e-6)


def test_sync_actually_replicates_params(results):
    """After one synced step, every replica's params must agree (DDP's
    broadcast-at-construction + identical-updates invariant)."""
    params, _ = results["p2p_star"]
    # Values came back as a single global (replicated) array; a second
    # step from them must not diverge — run two more steps under star.
    # (Replication is structurally guaranteed by out_specs=P(); this
    # checks the star's mean really is the global mean on every replica
    # by comparing against gather_scatter.)
    ref, _ = results["gather_scatter"]
    for r, g in zip(jax.tree.leaves(ref), jax.tree.leaves(params)):
        np.testing.assert_allclose(g, r, rtol=1e-5, atol=1e-6)


# ------------------------------------------------------- overlapped schedule
def _run_steps(mesh, batch, steps, **cfg_kw):
    """Final params + per-step losses for a tiny_cnn run on 4 devices."""
    from cs744_pytorch_distributed_tutorial_tpu.parallel.mesh import (
        shard_global_batch,
    )

    cfg = TrainConfig(
        model="tiny_cnn", num_devices=4, global_batch_size=16, seed=5000,
        **cfg_kw,
    )
    tr = Trainer(cfg, mesh=mesh)
    state = tr.init()
    gx, gy = shard_global_batch(mesh, *batch)
    key = jax.random.key(cfg.seed)
    losses = []
    for _ in range(steps):
        state, metrics = tr.train_step(state, gx, gy, key)
        losses.append(float(metrics["loss"]))
    return jax.tree.map(np.asarray, jax.device_get(state.params)), losses


@pytest.mark.parametrize("strategy", ["allreduce", "ring"])
def test_overlap_bitwise_vs_fused(mesh4, batch, strategy):
    """The overlapped bucket schedule (--sync-overlap bucket) reorders
    WHEN each bucket syncs and applies, not WHAT is computed: for the
    float wires the reverse-bucket mean and per-bucket SGD apply are the
    same f32 operations on the same operands, so parity is bitwise —
    any drift means the schedule changed the math."""
    fused_p, fused_l = _run_steps(mesh4, batch, 3, sync=strategy)
    ov_p, ov_l = _run_steps(
        mesh4, batch, 3, sync=strategy, sync_overlap="bucket"
    )
    assert fused_l == ov_l
    for r, g in zip(jax.tree.leaves(fused_p), jax.tree.leaves(ov_p)):
        np.testing.assert_array_equal(g, r)


@pytest.mark.slow
def test_overlap_int8_ef_trajectory(mesh4):
    """int8+EF overlap is NOT bitwise vs fused int8 — the reverse bucket
    layout regroups the quantization chunks — but error feedback keeps
    the trajectories together: over 50 steps the mean per-step relative
    loss gap stays under 1% (the compression suite's tolerance class;
    measured 0.66%). The mean is the stable statistic — single-step
    losses on this chaotic repeated-batch config oscillate ~10%, so a
    final-step bar would gate on noise, not on the schedule."""
    from conftest import run_tiny_dp4_steps

    fused_l, _, _ = run_tiny_dp4_steps(
        "allreduce", mesh4, steps=50, cfg_overrides={"grad_compress": "int8"}
    )
    ov_l, _, _ = run_tiny_dp4_steps(
        "allreduce", mesh4, steps=50,
        cfg_overrides={
            "grad_compress": "int8", "sync_overlap": "bucket+int8",
        },
    )
    rels = [abs(a - b) / max(abs(a), 1.0) for a, b in zip(fused_l, ov_l)]
    assert sum(rels) / len(rels) <= 0.01, (max(rels), sum(rels) / len(rels))
    assert ov_l[-1] < ov_l[0]  # and it actually trained


def test_overlap_int8_short_run_stays_close(mesh4):
    """Fast (tier-1) version of the int8 overlap check: 8 steps, 2% —
    the same bar as the fused int8-vs-f32 short-run test (measured
    final-loss gap: 6e-5)."""
    from conftest import run_tiny_dp4_steps

    fused_l, _, _ = run_tiny_dp4_steps(
        "allreduce", mesh4, steps=8, cfg_overrides={"grad_compress": "int8"}
    )
    ov_l, _, _ = run_tiny_dp4_steps(
        "allreduce", mesh4, steps=8,
        cfg_overrides={
            "grad_compress": "int8", "sync_overlap": "bucket+int8",
        },
    )
    assert ov_l[-1] == pytest.approx(fused_l[-1], rel=0.02)


@pytest.mark.parametrize("strategy", ["zero1", "fsdp"])
def test_overlap_rejects_sharded_optimizer(mesh4, strategy):
    # Sharded-optimizer strategies interleave sync with their own
    # gather/scatter schedule — per-bucket apply is not bitwise-sound
    # there, so the engine must refuse rather than silently drift.
    cfg = TrainConfig(
        model="tiny_cnn", sync=strategy, sync_overlap="bucket",
        num_devices=4, global_batch_size=16,
    )
    with pytest.raises(ValueError, match="sync_overlap"):
        Trainer(cfg, mesh=mesh4)


def test_none_requires_single_device():
    mesh = make_mesh({"data": 4}, devices=jax.devices()[:4])
    cfg = TrainConfig(model="tiny_cnn", sync="none", num_devices=4,
                      global_batch_size=16)
    with pytest.raises(ValueError):
        Trainer(cfg, mesh=mesh)


def test_unknown_strategy_rejected():
    from cs744_pytorch_distributed_tutorial_tpu.parallel.sync import get_sync

    with pytest.raises(ValueError):
        get_sync("nccl")
