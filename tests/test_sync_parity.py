"""Gradient-sync strategy parity.

The reference's central (implicit) property: part2a, part2a_extra, part2b
and part3 compute the SAME update — four mechanisms, one semantics —
which it establishes only by fixed seed + eyeballing loss curves
(SURVEY §4). Here it is a real test: from identical init and an identical
global batch, one train step under every strategy must produce identical
parameters.
"""

import jax
import numpy as np
import pytest

from cs744_pytorch_distributed_tutorial_tpu.config import TrainConfig
from cs744_pytorch_distributed_tutorial_tpu.data import synthetic_cifar10
from cs744_pytorch_distributed_tutorial_tpu.parallel import make_mesh
from cs744_pytorch_distributed_tutorial_tpu.train import Trainer

STRATEGIES = [
    "allreduce",
    "gather_scatter",
    "p2p_star",
    "ring",
    "auto",
    "zero1",
    "fsdp",
]


def _one_step_params(strategy, mesh, batch):
    cfg = TrainConfig(
        model="tiny_cnn",
        sync=strategy,
        num_devices=4,
        global_batch_size=16,
        seed=5000,
    )
    tr = Trainer(cfg, mesh=mesh)
    state = tr.init()
    x, y = batch
    from cs744_pytorch_distributed_tutorial_tpu.parallel.mesh import shard_global_batch

    gx, gy = shard_global_batch(mesh, x, y)
    key = jax.random.key(cfg.seed)
    new_state, metrics = tr.train_step(state, gx, gy, key)
    params = jax.device_get(new_state.params)
    if strategy == "fsdp":
        # fsdp persists [axis_size, chunk] flat shards; unshard host-side
        # to the original shapes so the matrix compares like with like.
        import jax.numpy as jnp

        sample = jnp.zeros((1, cfg.image_size, cfg.image_size, 3), jnp.float32)
        shapes = jax.eval_shape(
            lambda: tr.model.init(jax.random.key(0), sample, train=False)
        )["params"]
        params = jax.tree.map(
            lambda sh, ref: np.asarray(sh).reshape(-1)[
                : int(np.prod(ref.shape))
            ].reshape(ref.shape),
            params,
            shapes,
        )
    return (
        jax.tree.map(np.asarray, params),
        float(metrics["loss"]),
    )


@pytest.fixture(scope="module")
def batch():
    ds = synthetic_cifar10(64, 16, seed=3)
    return ds.train_images[:16], ds.train_labels[:16]


@pytest.fixture(scope="module")
def results(batch):
    mesh = make_mesh({"data": 4}, devices=jax.devices()[:4])
    return {s: _one_step_params(s, mesh, batch) for s in STRATEGIES}


@pytest.mark.parametrize("strategy", STRATEGIES[1:])
def test_strategies_match_allreduce(results, strategy):
    ref_params, ref_loss = results["allreduce"]
    got_params, got_loss = results[strategy]
    assert got_loss == pytest.approx(ref_loss, rel=1e-6)
    ref_leaves = jax.tree.leaves(ref_params)
    got_leaves = jax.tree.leaves(got_params)
    assert len(ref_leaves) == len(got_leaves)
    for r, g in zip(ref_leaves, got_leaves):
        np.testing.assert_allclose(g, r, rtol=1e-5, atol=1e-6)


def test_sync_actually_replicates_params(results):
    """After one synced step, every replica's params must agree (DDP's
    broadcast-at-construction + identical-updates invariant)."""
    params, _ = results["p2p_star"]
    # Values came back as a single global (replicated) array; a second
    # step from them must not diverge — run two more steps under star.
    # (Replication is structurally guaranteed by out_specs=P(); this
    # checks the star's mean really is the global mean on every replica
    # by comparing against gather_scatter.)
    ref, _ = results["gather_scatter"]
    for r, g in zip(jax.tree.leaves(ref), jax.tree.leaves(params)):
        np.testing.assert_allclose(g, r, rtol=1e-5, atol=1e-6)


# ------------------------------------------------------- overlapped schedule
def _run_steps(mesh, batch, steps, **cfg_kw):
    """Final params + per-step losses for a tiny_cnn run on 4 devices."""
    from cs744_pytorch_distributed_tutorial_tpu.parallel.mesh import (
        shard_global_batch,
    )

    cfg = TrainConfig(
        model="tiny_cnn", num_devices=4, global_batch_size=16, seed=5000,
        **cfg_kw,
    )
    tr = Trainer(cfg, mesh=mesh)
    state = tr.init()
    gx, gy = shard_global_batch(mesh, *batch)
    key = jax.random.key(cfg.seed)
    losses = []
    for _ in range(steps):
        state, metrics = tr.train_step(state, gx, gy, key)
        losses.append(float(metrics["loss"]))
    return jax.tree.map(np.asarray, jax.device_get(state.params)), losses


@pytest.mark.parametrize("strategy", ["allreduce", "ring"])
def test_overlap_bitwise_vs_fused(mesh4, batch, strategy):
    """The overlapped bucket schedule (--sync-overlap bucket) reorders
    WHEN each bucket syncs and applies, not WHAT is computed: for the
    float wires the reverse-bucket mean and per-bucket SGD apply are the
    same f32 operations on the same operands, so parity is bitwise —
    any drift means the schedule changed the math."""
    fused_p, fused_l = _run_steps(mesh4, batch, 3, sync=strategy)
    ov_p, ov_l = _run_steps(
        mesh4, batch, 3, sync=strategy, sync_overlap="bucket"
    )
    assert fused_l == ov_l
    for r, g in zip(jax.tree.leaves(fused_p), jax.tree.leaves(ov_p)):
        np.testing.assert_array_equal(g, r)


@pytest.mark.slow
def test_overlap_int8_ef_trajectory(mesh4):
    """int8+EF overlap is NOT bitwise vs fused int8 — the reverse bucket
    layout regroups the quantization chunks — but error feedback keeps
    the trajectories together: over 50 steps the mean per-step relative
    loss gap stays under 1% (the compression suite's tolerance class;
    measured 0.66%). The mean is the stable statistic — single-step
    losses on this chaotic repeated-batch config oscillate ~10%, so a
    final-step bar would gate on noise, not on the schedule."""
    from conftest import run_tiny_dp4_steps

    fused_l, _, _ = run_tiny_dp4_steps(
        "allreduce", mesh4, steps=50, cfg_overrides={"grad_compress": "int8"}
    )
    ov_l, _, _ = run_tiny_dp4_steps(
        "allreduce", mesh4, steps=50,
        cfg_overrides={
            "grad_compress": "int8", "sync_overlap": "bucket+int8",
        },
    )
    rels = [abs(a - b) / max(abs(a), 1.0) for a, b in zip(fused_l, ov_l)]
    assert sum(rels) / len(rels) <= 0.01, (max(rels), sum(rels) / len(rels))
    assert ov_l[-1] < ov_l[0]  # and it actually trained


def test_overlap_int8_short_run_stays_close(mesh4):
    """Fast (tier-1) version of the int8 overlap check: 8 steps, 2% —
    the same bar as the fused int8-vs-f32 short-run test (measured
    final-loss gap: 6e-5)."""
    from conftest import run_tiny_dp4_steps

    fused_l, _, _ = run_tiny_dp4_steps(
        "allreduce", mesh4, steps=8, cfg_overrides={"grad_compress": "int8"}
    )
    ov_l, _, _ = run_tiny_dp4_steps(
        "allreduce", mesh4, steps=8,
        cfg_overrides={
            "grad_compress": "int8", "sync_overlap": "bucket+int8",
        },
    )
    assert ov_l[-1] == pytest.approx(fused_l[-1], rel=0.02)


@pytest.mark.parametrize("strategy", ["zero1", "fsdp"])
def test_overlap_sharded_bitwise_vs_fused(mesh4, batch, strategy):
    """zero1/fsdp overlap (reverse-bucket psum_scatter -> per-shard
    apply -> all_gather, parallel/zero.py) changes only bucket
    ASSIGNMENT: every collective stays column-elementwise on the same
    per-leaf [axis_size, chunk] blocks and the chunk rules are
    elementwise, so the float path is bitwise vs the fused schedule.
    (fsdp params persist as flat shards on both sides — same layout,
    so the leaves compare directly.)"""
    fused_p, fused_l = _run_steps(mesh4, batch, 3, sync=strategy)
    ov_p, ov_l = _run_steps(
        mesh4, batch, 3, sync=strategy, sync_overlap="bucket"
    )
    assert fused_l == ov_l
    for r, g in zip(jax.tree.leaves(fused_p), jax.tree.leaves(ov_p)):
        np.testing.assert_array_equal(g, r)


@pytest.mark.parametrize(
    "strategy",
    ["zero1", pytest.param("allreduce", marks=pytest.mark.slow)],
)
def test_overlap_accum_final_microstep(mesh4, batch, strategy):
    """accum_steps>1 composes with overlap: intermediate micro-steps
    stay local adds and only the FINAL micro-step's sync+apply runs the
    bucket schedule. zero1 syncs once per step either way, so it stays
    bitwise. Fused pure-DP allreduce syncs per micro-step (mean of
    means) while overlap syncs the accumulated sum once — equal up to
    f32 reassociation, so the parity-suite allclose bar applies."""
    fused_p, fused_l = _run_steps(
        mesh4, batch, 2, sync=strategy, accum_steps=2
    )
    ov_p, ov_l = _run_steps(
        mesh4, batch, 2, sync=strategy, accum_steps=2, sync_overlap="bucket"
    )
    if strategy == "zero1":
        assert fused_l == ov_l
        for r, g in zip(jax.tree.leaves(fused_p), jax.tree.leaves(ov_p)):
            np.testing.assert_array_equal(g, r)
    else:
        for a, b in zip(fused_l, ov_l):
            assert b == pytest.approx(a, rel=1e-5)
        for r, g in zip(jax.tree.leaves(fused_p), jax.tree.leaves(ov_p)):
            np.testing.assert_allclose(g, r, rtol=1e-5, atol=1e-6)


@pytest.mark.slow
def test_overlap_zero1_int8_short_run_stays_close(mesh4):
    """zero1 + bucket+int8: the quantized wire replaces each bucket's
    psum_scatter; error feedback keeps the trajectory on the float
    zero1 run — 8 steps, the compression suite's 2% short-run bar
    (measured ~2e-4). Tier-1 still exercises this wire end-to-end via
    test_profiling's zero1-int8 segmented parity and the zero-retrace
    sweep; the trajectory bars live in the slow tier."""
    from conftest import run_tiny_dp4_steps

    fused_l, _, _ = run_tiny_dp4_steps("zero1", mesh4, steps=8)
    ov_l, _, _ = run_tiny_dp4_steps(
        "zero1", mesh4, steps=8,
        cfg_overrides={
            "grad_compress": "int8", "sync_overlap": "bucket+int8",
        },
    )
    assert ov_l[-1] == pytest.approx(fused_l[-1], rel=0.02)


@pytest.mark.slow
def test_overlap_zero1_int8_trajectory(mesh4):
    """50-step bar for the zero1 int8 wire vs float zero1: mean
    per-step relative loss gap <= 1% (same statistic as the pure-DP
    int8 overlap bar; measured ~2e-4)."""
    from conftest import run_tiny_dp4_steps

    fused_l, _, _ = run_tiny_dp4_steps("zero1", mesh4, steps=50)
    ov_l, _, _ = run_tiny_dp4_steps(
        "zero1", mesh4, steps=50,
        cfg_overrides={
            "grad_compress": "int8", "sync_overlap": "bucket+int8",
        },
    )
    rels = [abs(a - b) / max(abs(a), 1.0) for a, b in zip(fused_l, ov_l)]
    assert sum(rels) / len(rels) <= 0.01, (max(rels), sum(rels) / len(rels))
    assert ov_l[-1] < ov_l[0]  # and it actually trained


def test_overlap_int8_rejects_fsdp(mesh4):
    # fsdp has no separate gradient wire to quantize — its reduction IS
    # the AD transpose of the param all_gather — so the engine must
    # refuse int8 there and point at the zero1 schedule instead.
    cfg = TrainConfig(
        model="tiny_cnn", sync="fsdp", grad_compress="int8",
        num_devices=4, global_batch_size=16,
    )
    with pytest.raises(ValueError, match="fsdp"):
        Trainer(cfg, mesh=mesh4)


@pytest.mark.parametrize(
    "cfg_kw",
    [
        dict(sync="zero1", sync_overlap="bucket"),
        pytest.param(
            dict(sync="fsdp", sync_overlap="bucket"),
            marks=pytest.mark.slow,
        ),
        pytest.param(
            dict(
                sync="zero1", grad_compress="int8",
                sync_overlap="bucket+int8",
            ),
            marks=pytest.mark.slow,
        ),
    ],
    ids=["zero1-bucket", "fsdp-bucket", "zero1-int8"],
)
def test_overlap_modes_zero_retrace(mesh4, batch, cfg_kw):
    """Each overlapped sharded mode compiles ONCE: steady-state steps
    must not retrace (the per-bucket python loops run at trace time —
    any shape/layout instability would show up as a recompile)."""
    from cs744_pytorch_distributed_tutorial_tpu.obs.system import (
        CompileCounter,
    )
    from cs744_pytorch_distributed_tutorial_tpu.parallel.mesh import (
        shard_global_batch,
    )

    cfg = TrainConfig(
        model="tiny_cnn", num_devices=4, global_batch_size=16, seed=5000,
        **cfg_kw,
    )
    tr = Trainer(cfg, mesh=mesh4)
    state = tr.init()
    gx, gy = shard_global_batch(mesh4, *batch)
    key = jax.random.key(cfg.seed)
    warm = CompileCounter()
    state, _ = tr.train_step(state, gx, gy, key)
    if warm.count == 0:
        pytest.skip("jax monitoring compile events unavailable")
    steady = CompileCounter()
    for _ in range(3):
        state, m = tr.train_step(state, gx, gy, key)
    assert np.isfinite(float(m["loss"]))
    assert steady.count == 0, (
        f"overlapped step triggered {steady.count} backend compile(s) "
        "after warm-up — the bucket schedule is retracing"
    )


# --------------------------------------------------- LM overlapped schedule
def _lm_run(mesh, steps=4, **kw):
    """Final params + per-step losses for a tiny LM run on dp=4."""
    from cs744_pytorch_distributed_tutorial_tpu.data import synthetic_tokens
    from cs744_pytorch_distributed_tutorial_tpu.train import (
        LMConfig,
        LMTrainer,
    )

    base = dict(
        vocab_size=64, num_layers=2, num_heads=4, d_model=32, d_ff=64,
        max_seq_len=64, seq_len=16, global_batch_size=8,
        attention_impl="dense", use_rope=True, learning_rate=3e-3,
        optimizer="sgd", lr_schedule="constant", data_parallel=4,
    )
    base.update(kw)
    cfg = LMConfig(**base)
    tr = LMTrainer(cfg, mesh=mesh)
    params, opt = tr.init()
    tokens = synthetic_tokens(8, 16, 64, seed=0)
    x, y = tr.shard_batch(tokens)
    losses = []
    for s in range(steps):
        params, opt, m = tr.train_step(params, opt, x, y, s)
        losses.append(float(m["loss"]))
    return jax.tree.map(np.asarray, jax.device_get(params)), losses


@pytest.fixture(scope="module")
def lm_mesh4():
    return make_mesh({"data": 4, "seq": 1}, devices=jax.devices()[:4])


@pytest.mark.slow
@pytest.mark.parametrize("shard", ["zero1", "fsdp"])
def test_lm_overlap_sharded_bitwise_vs_fused(lm_mesh4, shard):
    """The LM engine's zero1/fsdp overlap is the same bucket-assignment-
    only change as CIFAR's: float SGD parity is bitwise. Slow tier —
    tier-1 pins the same property on the CIFAR engine
    (test_overlap_sharded_bitwise_vs_fused) and the LM schedules' wire
    accounting via the TA003 rows in test_trace_audit.py."""
    kw = {"zero1": True} if shard == "zero1" else {"fsdp": True}
    fused_p, fused_l = _lm_run(lm_mesh4, **kw)
    ov_p, ov_l = _lm_run(lm_mesh4, sync_overlap="bucket", **kw)
    assert fused_l == ov_l
    for r, g in zip(jax.tree.leaves(fused_p), jax.tree.leaves(ov_p)):
        np.testing.assert_array_equal(g, r)


@pytest.mark.slow
def test_lm_overlap_zero1_adamw_short_run(lm_mesh4):
    """AdamW under overlap hoists the schedule/bias-correction step
    scalars once and applies the chunk rule per bucket — float
    reassociation only, so 6 steps stay within the zero1-vs-replicated
    AdamW suite's rtol. Slow tier with the 50-step bar below: tier-1
    keeps the bitwise SGD sweep, which pins the same bucket schedule."""
    kw = dict(
        optimizer="adamw", lr_schedule="warmup_cosine", warmup_steps=2,
        total_steps=8, zero1=True,
    )
    _, fused_l = _lm_run(lm_mesh4, steps=6, **kw)
    _, ov_l = _lm_run(lm_mesh4, steps=6, sync_overlap="bucket", **kw)
    np.testing.assert_allclose(fused_l, ov_l, rtol=2e-5)


@pytest.mark.slow
def test_lm_overlap_zero1_adamw_trajectory(lm_mesh4):
    """The ISSUE's 50-step bar: overlapped zero1 AdamW holds a <=1%
    mean per-step relative loss gap vs the fused schedule (measured
    ~1e-5)."""
    kw = dict(
        optimizer="adamw", lr_schedule="warmup_cosine", warmup_steps=2,
        total_steps=50, zero1=True,
    )
    _, fused_l = _lm_run(lm_mesh4, steps=50, **kw)
    _, ov_l = _lm_run(lm_mesh4, steps=50, sync_overlap="bucket", **kw)
    rels = [abs(a - b) / max(abs(a), 1.0) for a, b in zip(fused_l, ov_l)]
    assert sum(rels) / len(rels) <= 0.01, (max(rels), sum(rels) / len(rels))
    assert ov_l[-1] < ov_l[0]


@pytest.mark.slow
def test_lm_overlap_zero1_accum_bitwise(lm_mesh4):
    """LM zero1 + accumulation: the accumulated grads feed ONE scatter
    under both schedules, so overlap stays bitwise even with
    accum_steps=2. Slow tier — tier-1 covers accum composition via the
    CIFAR zero1 variant of test_overlap_accum_final_microstep."""
    kw = dict(zero1=True, accum_steps=2)
    fused_p, fused_l = _lm_run(lm_mesh4, steps=2, **kw)
    ov_p, ov_l = _lm_run(lm_mesh4, steps=2, sync_overlap="bucket", **kw)
    assert fused_l == ov_l
    for r, g in zip(jax.tree.leaves(fused_p), jax.tree.leaves(ov_p)):
        np.testing.assert_array_equal(g, r)


def test_none_requires_single_device():
    mesh = make_mesh({"data": 4}, devices=jax.devices()[:4])
    cfg = TrainConfig(model="tiny_cnn", sync="none", num_devices=4,
                      global_batch_size=16)
    with pytest.raises(ValueError):
        Trainer(cfg, mesh=mesh)


def test_unknown_strategy_rejected():
    from cs744_pytorch_distributed_tutorial_tpu.parallel.sync import get_sync

    with pytest.raises(ValueError):
        get_sync("nccl")
