"""True multi-process rendezvous: two OS processes join through
``parallel.mesh.initialize`` (the ``init_process`` mirror,
``master/part2a/part2a.py:80-85``) and run a cross-process psum over a
global array assembled with ``local_to_global_batch`` — the reference's
4-CloudLab-node flow, on one machine. Every other test simulates
multi-device single-process; this one exercises the actual coordination
service + cross-process collective path."""

import os
import socket
import subprocess
import sys

import pytest

# Spawns whole multi-process jax clusters; ~10s+ per case.
pytestmark = pytest.mark.slow

_WORKER = r"""
import os, sys
os.environ["PALLAS_AXON_POOL_IPS"] = ""
os.environ["JAX_PLATFORMS"] = "cpu"
import jax
jax.config.update("jax_platforms", "cpu")
import numpy as np

sys.path.insert(0, {repo!r})
from cs744_pytorch_distributed_tutorial_tpu.parallel import make_mesh
from cs744_pytorch_distributed_tutorial_tpu.parallel.mesh import (
    initialize, local_to_global_batch,
)

rank = int(sys.argv[1])
initialize({coord!r}, 2, rank)  # the init_process mirror
assert jax.process_count() == 2
devices = jax.devices()
assert len(devices) == 2, devices

mesh = make_mesh({{"data": 2}}, devices=devices)
# Each process contributes ITS shard of the global batch (the
# DistributedSampler analog across hosts).
local = np.full((2, 4), float(rank + 1), np.float32)
global_batch = local_to_global_batch(mesh, local)
assert global_batch.shape == (4, 4)

from jax.sharding import NamedSharding, PartitionSpec as P

@jax.jit
def global_sum(x):
    return jax.lax.with_sharding_constraint(
        x, NamedSharding(mesh, P())
    ).sum()

total = float(global_sum(global_batch))
# rows: two of 1.0 (rank 0) + two of 2.0 (rank 1), 4 columns each
assert total == 2 * 4 * 1.0 + 2 * 4 * 2.0, total
print(f"rank {{rank}} ok total={{total}}")
"""


_LOADER_WORKER = r"""
import os, sys
os.environ["PALLAS_AXON_POOL_IPS"] = ""
os.environ["JAX_PLATFORMS"] = "cpu"
import jax
jax.config.update("jax_platforms", "cpu")
import numpy as np

sys.path.insert(0, {repo!r})
from cs744_pytorch_distributed_tutorial_tpu.data import BatchLoader
from cs744_pytorch_distributed_tutorial_tpu.parallel import make_mesh
from cs744_pytorch_distributed_tutorial_tpu.parallel.mesh import initialize

rank = int(sys.argv[1])
initialize({coord!r}, 2, rank)
mesh = make_mesh({{"data": 2}}, devices=jax.devices())

# Identical host data on both processes; the loader's multi-host branch
# has each process contribute only its contiguous slice.
images = np.arange(8 * 2 * 2 * 3, dtype=np.uint8).reshape(8, 2, 2, 3)
labels = np.arange(8, dtype=np.int32)
loader = BatchLoader(images, labels, 4, mesh=mesh, shuffle=True, seed=3)

from jax.sharding import NamedSharding, PartitionSpec as P

@jax.jit
def reduce_sum(x, y):
    rep = NamedSharding(mesh, P())
    return (
        jax.lax.with_sharding_constraint(x, rep).astype(np.float32).sum()
        + jax.lax.with_sharding_constraint(y, rep).sum()
    )

totals = [float(reduce_sum(x, y)) for x, y in loader.epoch(0)]

# Reference: the same deterministic plan computed host-side.
from cs744_pytorch_distributed_tutorial_tpu.data.sampler import (
    epoch_permutation,
)
order = epoch_permutation(8, 3, 0, True)
expect = [
    float(images[order[b*4:(b+1)*4]].astype(np.float32).sum()
          + labels[order[b*4:(b+1)*4]].sum())
    for b in range(2)
]
assert totals == expect, (totals, expect)
print(f"rank {{rank}} loader ok {{totals}}")
"""


def _run_pair(script_template, tmp_path, repo, marker, extra_args=()):
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]
    script = script_template.format(repo=repo, coord=f"127.0.0.1:{port}")
    env = {
        **os.environ,
        "PALLAS_AXON_POOL_IPS": "",
        "JAX_PLATFORMS": "cpu",
        "XLA_FLAGS": "",  # exactly one CPU device per process
    }
    procs = [
        subprocess.Popen(
            [sys.executable, "-c", script, str(rank), *map(str, extra_args)],
            env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
            text=True, cwd=str(tmp_path),
        )
        for rank in (0, 1)
    ]
    outs = []
    try:
        for p in procs:
            out, _ = p.communicate(timeout=240)
            outs.append(out)
    except subprocess.TimeoutExpired:
        for p in procs:
            p.kill()
        pytest.fail(f"multi-process run hung; partial output: {outs}")
    for rank, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, f"rank {rank} failed:\n{out}"
        assert f"rank {rank} {marker}" in out
    return outs


_FIT_WORKER = r"""
import os, sys
os.environ["PALLAS_AXON_POOL_IPS"] = ""
os.environ["JAX_PLATFORMS"] = "cpu"
import jax
jax.config.update("jax_platforms", "cpu")
sys.path.insert(0, {repo!r})
from cs744_pytorch_distributed_tutorial_tpu.config import TrainConfig
from cs744_pytorch_distributed_tutorial_tpu.data import synthetic_cifar10
from cs744_pytorch_distributed_tutorial_tpu.parallel import make_mesh
from cs744_pytorch_distributed_tutorial_tpu.parallel.mesh import initialize
from cs744_pytorch_distributed_tutorial_tpu.train import Trainer

rank = int(sys.argv[1])
initialize({coord!r}, 2, rank)
mesh = make_mesh({{"data": 2}}, devices=jax.devices())
cfg = TrainConfig(model="tiny_cnn", sync="allreduce", num_devices=2,
                  global_batch_size=8, synthetic_data=True,
                  synthetic_train_size=32, synthetic_test_size=16, epochs=1)
tr = Trainer(cfg, mesh=mesh)
state, hist = tr.fit(dataset=synthetic_cifar10(32, 16, seed=0))
loss = hist["train_loss"][-1][2]
acc = hist["eval"][-1]["accuracy"]
print(f"rank {{rank}} fit ok loss={{loss:.6f}} acc={{acc:.4f}}")
"""


_RESUME_WORKER = r"""
import os, sys
os.environ["PALLAS_AXON_POOL_IPS"] = ""
os.environ["JAX_PLATFORMS"] = "cpu"
import jax
jax.config.update("jax_platforms", "cpu")
import numpy as np
sys.path.insert(0, {repo!r})
from cs744_pytorch_distributed_tutorial_tpu.config import TrainConfig
from cs744_pytorch_distributed_tutorial_tpu.data import synthetic_cifar10
from cs744_pytorch_distributed_tutorial_tpu.parallel import make_mesh
from cs744_pytorch_distributed_tutorial_tpu.parallel.mesh import (
    initialize, shard_global_batch,
)
from cs744_pytorch_distributed_tutorial_tpu.train import Trainer
from cs744_pytorch_distributed_tutorial_tpu.utils.checkpoint import Checkpointer

rank = int(sys.argv[1])
ckdir = "__CKDIR__"
initialize({coord!r}, 2, rank)
mesh = make_mesh({{"data": 2}}, devices=jax.devices())
# zero1: the optimizer momentum shards over the data axis, so the
# checkpointed opt_state leaves SPAN both processes — exactly the
# sharding family whose restore->place_state path used to crash in
# host_to_global's np.asarray fallback.
cfg = TrainConfig(model="tiny_cnn", sync="zero1", num_devices=2,
                  global_batch_size=8, synthetic_data=True,
                  synthetic_train_size=32, synthetic_test_size=16)
tr = Trainer(cfg, mesh=mesh)
state = tr.init()
ds = synthetic_cifar10(8, 8, seed=0)
x, y = shard_global_batch(mesh, ds.train_images[:8], ds.train_labels[:8])
key = jax.random.key(cfg.seed)
for _ in range(3):
    state, m = tr.train_step(state, x, y, key)

ckpt = Checkpointer(ckdir)
ckpt.save(state, wait=True)

# Uninterrupted continuation = the reference trajectory.
ref = state
for _ in range(2):
    ref, mref = tr.train_step(ref, x, y, key)
ref_loss = float(mref["loss"])

# "Restart": a fresh Trainer restores the checkpoint and resumes.
tr2 = Trainer(cfg, mesh=mesh)
template = tr2.init()
ckpt2 = Checkpointer(ckdir)
restored = ckpt2.restore_latest(template)
assert restored is not None
assert int(jax.device_get(restored.step)) == 3
st2 = tr2.place_state(restored)  # the multi-host placement path
for _ in range(2):
    st2, m2 = tr2.train_step(st2, x, y, key)
loss2 = float(m2["loss"])
assert loss2 == ref_loss, (loss2, ref_loss)
# params are replicated under zero1: compare resumed vs uninterrupted.
pa = jax.device_get(jax.tree.leaves(ref.params)[0])
pb = jax.device_get(jax.tree.leaves(st2.params)[0])
np.testing.assert_array_equal(pa, pb)
ckpt.close(); ckpt2.close()
print(f"rank {{rank}} resume ok loss={{loss2:.6f}}")
"""


def test_two_process_checkpoint_save_restore_resume(tmp_path):
    """Multi-host checkpointing: both processes save sharded (zero1)
    state into one Orbax directory, a fresh trainer restores it, and the
    resumed trajectory is bit-identical to the uninterrupted one on both
    ranks — the save->kill->restore->resume flow of SURVEY §5.4 at real
    process scope."""
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    ckdir = str(tmp_path / "ckpt")
    script_template = _RESUME_WORKER.replace("__CKDIR__", ckdir)
    outs = _run_pair(script_template, tmp_path, repo, "resume ok")
    vals = [o.strip().splitlines()[-1].split("ok ", 1)[1] for o in outs]
    assert vals[0] == vals[1], vals


def test_full_trainer_fit_across_two_processes(tmp_path):
    """The reference's whole multi-node flow — rendezvous, sharded data,
    allreduce training, psum eval aggregation — over a REAL process
    boundary; both ranks report identical loss and accuracy."""
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    outs = _run_pair(_FIT_WORKER, tmp_path, repo, "fit ok")
    vals = [o.strip().splitlines()[-1].split("ok ", 1)[1] for o in outs]
    assert vals[0] == vals[1], vals  # bit-identical metrics on both ranks


def test_batchloader_multi_host_branch(tmp_path):
    """BatchLoader's process-local contribution path, exercised across a
    REAL process boundary: both ranks see the full deterministic batch
    stream as global arrays."""
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    _run_pair(_LOADER_WORKER, tmp_path, repo, "loader ok")


def test_two_process_rendezvous_and_cross_process_reduction(tmp_path):
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    with socket.socket() as s:  # free port for the coordination service
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]
    coord = f"127.0.0.1:{port}"
    script = _WORKER.format(repo=repo, coord=coord)

    env = {
        **os.environ,
        "PALLAS_AXON_POOL_IPS": "",
        "JAX_PLATFORMS": "cpu",
        "XLA_FLAGS": "",  # exactly one CPU device per process
    }
    procs = [
        subprocess.Popen(
            [sys.executable, "-c", script, str(rank)],
            env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
            text=True, cwd=str(tmp_path),
        )
        for rank in (0, 1)
    ]
    outs = []
    try:
        for p in procs:
            out, _ = p.communicate(timeout=240)
            outs.append(out)
    except subprocess.TimeoutExpired:
        for p in procs:
            p.kill()
        pytest.fail(f"multi-process rendezvous hung; partial output: {outs}")
    for rank, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, f"rank {rank} failed:\n{out}"
        assert f"rank {rank} ok" in out


_PIPELINE_WORKER = r"""
import os, sys
os.environ["PALLAS_AXON_POOL_IPS"] = ""
os.environ["JAX_PLATFORMS"] = "cpu"
import jax
jax.config.update("jax_platforms", "cpu")
import numpy as np

sys.path.insert(0, {repo!r})
from cs744_pytorch_distributed_tutorial_tpu.parallel import make_mesh
from cs744_pytorch_distributed_tutorial_tpu.parallel.mesh import initialize
from cs744_pytorch_distributed_tutorial_tpu.parallel.pipeline import (
    PipelineLMConfig, PipelineLMTrainer,
)

rank = int(sys.argv[1])
initialize({coord!r}, 2, rank)
# One device per process -> the PIPE axis spans the process boundary:
# every stage hop (forward ppermute, 1F1B reverse ppermute) is a real
# cross-process transfer, the reference's multi-node p2p flow
# (master/part2a/part2a_extra.py) doing pipeline work.
mesh = make_mesh({{"data": 1, "pipe": 2}}, devices=jax.devices())
cfg = PipelineLMConfig(
    vocab_size=64, num_layers=2, num_heads=2, d_model=32, d_ff=64,
    max_seq_len=32, data_parallel=1, pipeline_parallel=2,
    num_microbatches=2, global_batch_size=4, seq_len=16,
    schedule="1f1b", seed=5,
)
tr = PipelineLMTrainer(cfg, mesh=mesh)
params, opt = tr.init()
toks = np.random.default_rng(0).integers(0, 64, (4, 17), dtype=np.int64)
x, y = tr.shard_batch(toks)
losses = []
for s in range(3):
    params, opt, m = tr.train_step(params, opt, x, y, s)
    losses.append(round(float(m["loss"]), 8))
assert all(np.isfinite(losses)), losses
assert losses[-1] < losses[0], losses
print(f"rank {{rank}} pipeline ok losses={{losses}}")
"""


def test_pipeline_stages_across_two_processes(tmp_path):
    """The pipeline engine's stage hops crossing a REAL process
    boundary: pipe=2 over two single-device processes, 1F1B schedule —
    forward and reverse ppermutes ride the inter-process transport, and
    both ranks observe identical losses."""
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    outs = _run_pair(_PIPELINE_WORKER, tmp_path, repo, "pipeline ok")
    loss_lines = [
        next(l for l in out.splitlines() if "losses=" in l) for out in outs
    ]
    assert loss_lines[0].split("losses=")[1] == loss_lines[1].split(
        "losses="
    )[1], loss_lines


_RING_SEQ_WORKER = r"""
import os, sys
os.environ["PALLAS_AXON_POOL_IPS"] = ""
os.environ["JAX_PLATFORMS"] = "cpu"
import jax
jax.config.update("jax_platforms", "cpu")
import numpy as np

sys.path.insert(0, {repo!r})
from cs744_pytorch_distributed_tutorial_tpu.parallel import make_mesh
from cs744_pytorch_distributed_tutorial_tpu.parallel.mesh import initialize
from cs744_pytorch_distributed_tutorial_tpu.train import LMConfig, LMTrainer

rank = int(sys.argv[1])
initialize({coord!r}, 2, rank)
# One device per process -> the SEQ axis spans the process boundary:
# every ring-attention hop (forward K/V rotation AND its AD-transposed
# reverse ring in backward) is a real cross-process transfer — the
# long-context analog of the reference's multi-node p2p flow.
mesh = make_mesh({{"data": 1, "seq": 2}}, devices=jax.devices())
cfg = LMConfig(
    vocab_size=64, num_layers=2, num_heads=4, d_model=32, d_ff=64,
    max_seq_len=64, attention_impl="ring", data_parallel=1,
    seq_parallel=2, global_batch_size=4, seq_len=16, use_rope=True,
    seed=5,
)
tr = LMTrainer(cfg, mesh=mesh)
params, opt = tr.init()
toks = np.random.default_rng(0).integers(0, 64, (4, 17), dtype=np.int64)
x, y = tr.shard_batch(toks)
losses = []
for s in range(3):
    params, opt, m = tr.train_step(params, opt, x, y, s)
    losses.append(round(float(m["loss"]), 8))
assert all(np.isfinite(losses)), losses
assert losses[-1] < losses[0], losses
print(f"rank {{rank}} ringseq ok losses={{losses}}")
"""


def test_ring_attention_across_two_processes(tmp_path):
    """Sequence-parallel ring attention crossing a REAL process
    boundary: seq=2 over two single-device processes — the ring's
    ppermute hops (and their reverse-ring transposes in backward) ride
    the inter-process transport; both ranks observe identical losses,
    and those losses match a single-process dense-attention run of the
    same config (the ring is exactly a layout change)."""
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    outs = _run_pair(_RING_SEQ_WORKER, tmp_path, repo, "ringseq ok")
    loss_lines = [
        next(l for l in out.splitlines() if "losses=" in l) for out in outs
    ]
    assert loss_lines[0].split("losses=")[1] == loss_lines[1].split(
        "losses="
    )[1], loss_lines

    # Single-process oracle: same config at seq_parallel=1 / dense.
    import jax
    import numpy as np

    from cs744_pytorch_distributed_tutorial_tpu.parallel import make_mesh
    from cs744_pytorch_distributed_tutorial_tpu.train import (
        LMConfig,
        LMTrainer,
    )

    cfg = LMConfig(
        vocab_size=64, num_layers=2, num_heads=4, d_model=32, d_ff=64,
        max_seq_len=64, attention_impl="dense", data_parallel=1,
        seq_parallel=1, global_batch_size=4, seq_len=16, use_rope=True,
        seed=5,
    )
    tr = LMTrainer(
        cfg,
        mesh=make_mesh({"data": 1, "seq": 1}, devices=jax.devices()[:1]),
    )
    params, opt = tr.init()
    toks = np.random.default_rng(0).integers(0, 64, (4, 17), dtype=np.int64)
    x, y = tr.shard_batch(toks)
    want = []
    for s in range(3):
        params, opt, m = tr.train_step(params, opt, x, y, s)
        want.append(float(m["loss"]))
    import ast

    got = ast.literal_eval(loss_lines[0].split("losses=")[1])
    np.testing.assert_allclose(got, want, rtol=2e-5)


_STEP_PARITY_WORKER = r"""
import os, sys
os.environ["PALLAS_AXON_POOL_IPS"] = ""
os.environ["JAX_PLATFORMS"] = "cpu"
import jax
jax.config.update("jax_platforms", "cpu")
import numpy as np
sys.path.insert(0, {repo!r})
from cs744_pytorch_distributed_tutorial_tpu.config import TrainConfig
from cs744_pytorch_distributed_tutorial_tpu.data import synthetic_cifar10
from cs744_pytorch_distributed_tutorial_tpu.parallel import make_mesh
from cs744_pytorch_distributed_tutorial_tpu.parallel.mesh import (
    initialize, shard_global_batch,
)
from cs744_pytorch_distributed_tutorial_tpu.train import Trainer

rank = int(sys.argv[1])
initialize({coord!r}, 2, rank)
mesh = make_mesh({{"data": 2}}, devices=jax.devices())
cfg = TrainConfig(model="tiny_cnn", sync="allreduce", sync_bn=True,
                  augment=False, num_devices=2, global_batch_size=8,
                  synthetic_data=True, synthetic_train_size=8,
                  synthetic_test_size=8, seed=0)
tr = Trainer(cfg, mesh=mesh)
state = tr.init()
ds = synthetic_cifar10(8, 8, seed=0)
x, y = shard_global_batch(mesh, ds.train_images, ds.train_labels)
key = jax.random.key(cfg.seed)
losses = []
for _ in range(3):
    state, m = tr.train_step(state, x, y, key)
    losses.append(round(float(jax.device_get(m["loss"])), 8))
print(f"rank {{rank}} stepparity ok losses={{losses}}")
"""


def test_train_step_psum_parity_across_two_processes(tmp_path):
    """The elastic demo worker's exact step recipe (tiny-CNN allreduce,
    sync_bn, fixed batch, trainer-folded PRNG) over a REAL process
    boundary: the grad psum and BN-stat psum cross the inter-process
    transport, both ranks observe identical losses, and the trajectory
    matches a single-process 2-virtual-device oracle — the parity claim
    the graftelastic e2e builds on, isolated from the launcher."""
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    outs = _run_pair(_STEP_PARITY_WORKER, tmp_path, repo, "stepparity ok")
    loss_lines = [
        next(l for l in out.splitlines() if "losses=" in l) for out in outs
    ]
    assert loss_lines[0].split("losses=")[1] == loss_lines[1].split(
        "losses="
    )[1], loss_lines

    import ast

    import jax
    import numpy as np

    from cs744_pytorch_distributed_tutorial_tpu.config import TrainConfig
    from cs744_pytorch_distributed_tutorial_tpu.data import synthetic_cifar10
    from cs744_pytorch_distributed_tutorial_tpu.parallel import make_mesh
    from cs744_pytorch_distributed_tutorial_tpu.parallel.mesh import (
        shard_global_batch,
    )
    from cs744_pytorch_distributed_tutorial_tpu.train import Trainer

    cfg = TrainConfig(model="tiny_cnn", sync="allreduce", sync_bn=True,
                      augment=False, num_devices=2, global_batch_size=8,
                      synthetic_data=True, synthetic_train_size=8,
                      synthetic_test_size=8, seed=0)
    mesh = make_mesh({"data": 2}, devices=jax.devices()[:2])
    tr = Trainer(cfg, mesh=mesh)
    state = tr.init()
    ds = synthetic_cifar10(8, 8, seed=0)
    x, y = shard_global_batch(mesh, ds.train_images, ds.train_labels)
    key = jax.random.key(cfg.seed)
    oracle = []
    for _ in range(3):
        state, m = tr.train_step(state, x, y, key)
        oracle.append(float(jax.device_get(m["loss"])))
    got = ast.literal_eval(loss_lines[0].split("losses=")[1])
    np.testing.assert_allclose(got, oracle, rtol=2e-5)


_ZERO_WORKER = r"""
import os, sys
os.environ["PALLAS_AXON_POOL_IPS"] = ""
os.environ["JAX_PLATFORMS"] = "cpu"
import jax
jax.config.update("jax_platforms", "cpu")
import numpy as np
sys.path.insert(0, {repo!r})
from cs744_pytorch_distributed_tutorial_tpu.data import synthetic_tokens
from cs744_pytorch_distributed_tutorial_tpu.parallel import make_mesh
from cs744_pytorch_distributed_tutorial_tpu.parallel.mesh import initialize
from cs744_pytorch_distributed_tutorial_tpu.train import LMConfig, LMTrainer

rank = int(sys.argv[1])
mode = sys.argv[2]  # "zero1" | "fsdp"
assert mode in ("zero1", "fsdp"), mode  # typo'd mode would pass trivially
initialize({coord!r}, 2, rank)
mesh = make_mesh({{"data": 2, "seq": 1}}, devices=jax.devices())
cfg = LMConfig(
    vocab_size=64, num_layers=2, num_heads=4, d_model=32, d_ff=64,
    max_seq_len=64, attention_impl="dense", data_parallel=2,
    seq_parallel=1, global_batch_size=4, seq_len=16, use_rope=True,
    seed=5, zero1=(mode == "zero1"), fsdp=(mode == "fsdp"),
)
tr = LMTrainer(cfg, mesh=mesh)
params, opt = tr.init()
tokens = synthetic_tokens(16, cfg.seq_len, cfg.vocab_size, seed=11)
losses = []
for s in range(3):
    x, y = tr.shard_batch(tokens[s * 4 : s * 4 + 4])
    params, opt, m = tr.train_step(params, opt, x, y)
    losses.append(round(float(m["loss"]), 6))
print(f"rank {{rank}} zerolm ok losses={{losses}}")
"""


@pytest.mark.parametrize("mode", ["zero1", "fsdp"])
def test_zero_sharded_optimizer_across_two_processes(mode, tmp_path):
    """ZeRO's collective pair crossing a REAL process boundary: with
    dp=2 spanning two single-device processes, every per-leaf
    psum_scatter (mean-grad chunking) and all_gather (delta/param
    unshard) rides the inter-process transport — the fourth kind of
    2-real-process evidence (after DP metrics, pipeline hops, ring
    attention). Both ranks observe identical losses, and the
    trajectory matches the REPLICATED-optimizer single-process oracle
    on a 2-virtual-device mesh (the ZeRO identity, now over the real
    transport)."""
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    outs = _run_pair(_ZERO_WORKER, tmp_path, repo, "zerolm ok",
                     extra_args=[mode])
    loss_lines = [
        next(l for l in out.splitlines() if "losses=" in l) for out in outs
    ]
    assert loss_lines[0].split("losses=")[1] == loss_lines[1].split(
        "losses="
    )[1], loss_lines

    import ast

    import jax
    import numpy as np

    from cs744_pytorch_distributed_tutorial_tpu.data import synthetic_tokens
    from cs744_pytorch_distributed_tutorial_tpu.parallel import make_mesh
    from cs744_pytorch_distributed_tutorial_tpu.train import (
        LMConfig,
        LMTrainer,
    )

    cfg = LMConfig(
        vocab_size=64, num_layers=2, num_heads=4, d_model=32, d_ff=64,
        max_seq_len=64, attention_impl="dense", data_parallel=2,
        seq_parallel=1, global_batch_size=4, seq_len=16, use_rope=True,
        seed=5,
    )
    mesh = make_mesh({"data": 2, "seq": 1}, devices=jax.devices()[:2])
    tr = LMTrainer(cfg, mesh=mesh)
    params, opt = tr.init()
    tokens = synthetic_tokens(16, cfg.seq_len, cfg.vocab_size, seed=11)
    oracle = []
    for s in range(3):
        x, y = tr.shard_batch(tokens[s * 4 : s * 4 + 4])
        params, opt, m = tr.train_step(params, opt, x, y)
        oracle.append(float(m["loss"]))
    got = ast.literal_eval(loss_lines[0].split("losses=")[1])
    np.testing.assert_allclose(got, oracle, rtol=2e-5)
