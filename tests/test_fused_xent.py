"""Pallas fused softmax-CE (ops/fused_xent.py) against optax."""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from cs744_pytorch_distributed_tutorial_tpu.ops.fused_xent import (
    fused_cross_entropy,
)

# CPU-interpret Pallas xent kernels: heavy compile.
pytestmark = pytest.mark.slow


@pytest.mark.parametrize(
    "n,v",
    [
        (8, 128),       # exact tiles
        (256, 512),     # one row block, one vocab block
        (300, 1000),    # ragged both ways -> padding path
        (5, 50),        # tiny, heavily padded
    ],
)
def test_matches_optax_forward(n, v):
    rng = np.random.default_rng(n * 31 + v)
    logits = jnp.asarray(rng.standard_normal((n, v)).astype(np.float32) * 4)
    labels = jnp.asarray(rng.integers(0, v, n).astype(np.int32))
    ours = fused_cross_entropy(logits, labels, interpret=True)
    ref = optax.softmax_cross_entropy_with_integer_labels(logits, labels)
    np.testing.assert_allclose(np.asarray(ours), np.asarray(ref), rtol=1e-5, atol=1e-5)


def test_matches_optax_grad():
    rng = np.random.default_rng(7)
    logits = jnp.asarray(rng.standard_normal((48, 300)).astype(np.float32))
    labels = jnp.asarray(rng.integers(0, 300, 48).astype(np.int32))

    g_ours = jax.grad(
        lambda l: fused_cross_entropy(l, labels, interpret=True).mean()
    )(logits)
    g_ref = jax.grad(
        lambda l: optax.softmax_cross_entropy_with_integer_labels(l, labels).mean()
    )(logits)
    np.testing.assert_allclose(
        np.asarray(g_ours), np.asarray(g_ref), rtol=1e-5, atol=1e-6
    )


def test_bfloat16_logits_float32_accumulation():
    rng = np.random.default_rng(9)
    logits32 = rng.standard_normal((32, 256)).astype(np.float32)
    labels = jnp.asarray(rng.integers(0, 256, 32).astype(np.int32))
    ours = fused_cross_entropy(
        jnp.asarray(logits32, jnp.bfloat16), labels, interpret=True
    )
    ref = optax.softmax_cross_entropy_with_integer_labels(
        jnp.asarray(logits32, jnp.bfloat16).astype(jnp.float32), labels
    )
    assert ours.dtype == jnp.float32
    np.testing.assert_allclose(np.asarray(ours), np.asarray(ref), rtol=1e-3, atol=1e-3)


def test_extreme_logits_stable():
    """Online-softmax must survive large-magnitude logits (no inf/nan)."""
    logits = jnp.asarray([[1e4, -1e4, 0.0, 500.0] * 32] * 8, jnp.float32)
    labels = jnp.zeros((8,), jnp.int32)
    out = fused_cross_entropy(logits, labels, interpret=True)
    assert np.isfinite(np.asarray(out)).all()


def test_lm_trainer_fused_xent_matches_dense():
    """One LMTrainer eval/train step with fused_xent=True reproduces the
    unfused loss on the same params/batch."""
    from cs744_pytorch_distributed_tutorial_tpu.data import synthetic_tokens
    from cs744_pytorch_distributed_tutorial_tpu.parallel import make_mesh
    from cs744_pytorch_distributed_tutorial_tpu.train import LMConfig, LMTrainer

    kw = dict(vocab_size=64, num_layers=1, num_heads=2, d_model=32, d_ff=64,
              max_seq_len=64, seq_len=32, global_batch_size=4,
              attention_impl="ring", data_parallel=2, seq_parallel=2)
    tokens = synthetic_tokens(8, 32, 64, seed=1)
    mesh = make_mesh({"data": 2, "seq": 2})
    losses = {}
    for fused in (False, True):
        tr = LMTrainer(LMConfig(**kw, fused_xent=fused), mesh=mesh)
        p, o = tr.init()
        x, y = tr.shard_batch(tokens[:4])
        _, _, m = tr.train_step(p, o, x, y)
        losses[fused] = float(m["loss"])
    assert losses[True] == pytest.approx(losses[False], rel=1e-5)


def test_one_pass_backward_ragged_and_bf16():
    """The round-2 one-pass backward (tile kernel from the saved row
    logsumexp): padded/ragged shapes and bf16 logits must match optax's
    gradient — nothing of [N, V] shape besides the cotangent itself."""
    import optax

    rng = np.random.default_rng(4)
    for n, v, dtype in [(13, 77, jnp.float32), (32, 200, jnp.bfloat16)]:
        logits = jnp.asarray(rng.standard_normal((n, v)), dtype)
        labels = jnp.asarray(rng.integers(0, v, n), jnp.int32)

        g_ours = jax.grad(
            lambda l: fused_cross_entropy(
                l, labels, 8, 128, True
            ).sum()
        )(logits)
        g_ref = jax.grad(
            lambda l: optax.softmax_cross_entropy_with_integer_labels(
                l.astype(jnp.float32), labels
            ).sum()
        )(logits.astype(jnp.float32))
        np.testing.assert_allclose(
            np.asarray(g_ours, np.float32), np.asarray(g_ref),
            rtol=2e-2 if dtype == jnp.bfloat16 else 1e-5,
            atol=2e-2 if dtype == jnp.bfloat16 else 1e-6,
        )
