"""Pallas fused SGD kernel vs the optax reference chain (interpret mode)."""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from cs744_pytorch_distributed_tutorial_tpu.config import TrainConfig
from cs744_pytorch_distributed_tutorial_tpu.ops.fused_sgd import FusedSGD
from cs744_pytorch_distributed_tutorial_tpu.train.state import make_optimizer

# CPU-interpret Pallas fused-SGD kernels: heavy compile.
pytestmark = pytest.mark.slow

LR, MU, WD = 0.1, 0.9, 1e-4


def _random_tree(key):
    k = jax.random.split(key, 4)
    return {
        "conv": {"kernel": jax.random.normal(k[0], (3, 3, 3, 64)),
                 "bias": jax.random.normal(k[1], (64,))},
        "dense": {"kernel": jax.random.normal(k[2], (512, 10)),
                  "bias": jax.random.normal(k[3], (10,))},
    }


def test_matches_optax_chain_over_steps():
    cfg = TrainConfig(learning_rate=LR, momentum=MU, weight_decay=WD)
    ref_tx = make_optimizer(cfg)
    fused = FusedSGD(LR, MU, WD, interpret=True)

    params = _random_tree(jax.random.key(0))
    ref_params = params
    ref_opt = ref_tx.init(params)
    mom = fused.init(params)

    for step in range(3):
        grads = _random_tree(jax.random.key(100 + step))
        updates, ref_opt = ref_tx.update(grads, ref_opt, ref_params)
        ref_params = optax.apply_updates(ref_params, updates)
        params, mom = fused.apply(params, mom, grads)

    for a, b in zip(jax.tree.leaves(params), jax.tree.leaves(ref_params)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=1e-6, atol=1e-7)


@pytest.mark.parametrize("shape", [(1,), (7,), (128,), (1000,), (8, 128), (3, 5, 7)])
def test_odd_shapes(shape):
    """Padding to (rows, 128) lanes must not corrupt any element."""
    fused = FusedSGD(LR, MU, WD, interpret=True)
    p = jnp.arange(np.prod(shape), dtype=jnp.float32).reshape(shape)
    m = jnp.ones(shape, jnp.float32)
    g = jnp.full(shape, 0.5, jnp.float32)
    new_p, new_m = fused.apply(p, m, g)
    g_eff = 0.5 + WD * p
    want_m = MU * 1.0 + g_eff
    want_p = p - LR * want_m
    np.testing.assert_allclose(np.asarray(new_m), np.asarray(want_m), rtol=1e-6)
    np.testing.assert_allclose(np.asarray(new_p), np.asarray(want_p), rtol=1e-6)


def test_trainer_with_fused_optimizer_learns():
    from cs744_pytorch_distributed_tutorial_tpu.data import synthetic_cifar10
    from cs744_pytorch_distributed_tutorial_tpu.parallel import make_mesh
    from cs744_pytorch_distributed_tutorial_tpu.train import Trainer

    mesh = make_mesh({"data": 2}, devices=jax.devices()[:2])
    ds = synthetic_cifar10(512, 64, seed=11)
    cfg = TrainConfig(model="tiny_cnn", sync="allreduce", num_devices=2,
                      global_batch_size=64, learning_rate=0.02, epochs=3,
                      synthetic_data=True, fused_optimizer=True, log_every=4)
    tr = Trainer(cfg, mesh=mesh)
    state, hist = tr.fit(dataset=ds)
    losses = [l for (_, _, l) in hist["train_loss"]]
    assert losses[-1] < losses[0]
    assert hist["eval"][-1]["accuracy"] > 0.3
