"""KV-cache generation (infer/generate.py) against the full forward pass.

The correctness anchor: cached prefill+decode must produce the same
logits as teacher-forcing the full sequence through the model — the
decode path shares parameters but not code with the training path, so
this pins the cache indexing, masking, and position handling.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from cs744_pytorch_distributed_tutorial_tpu.infer import make_generator, sample_tokens
from cs744_pytorch_distributed_tutorial_tpu.models import TransformerLM

VOCAB = 61


@pytest.fixture(scope="module")
def tiny_lm():
    model = TransformerLM(
        vocab_size=VOCAB,
        num_layers=2,
        num_heads=2,
        d_model=32,
        d_ff=64,
        max_seq_len=32,
        attention_impl="dense",
    )
    toks = jnp.zeros((1, 4), jnp.int32)
    params = model.init(jax.random.key(0), toks)["params"]
    return model, params


def test_decode_logits_match_full_forward(tiny_lm):
    model, params = tiny_lm
    tokens = jax.random.randint(jax.random.key(1), (2, 12), 0, VOCAB)
    full_logits = model.apply({"params": params}, tokens)

    t0 = 5
    prefill_logits, variables = model.apply(
        {"params": params}, tokens[:, :t0], mode="prefill", mutable=["cache"]
    )
    np.testing.assert_allclose(
        prefill_logits, full_logits[:, :t0], rtol=1e-5, atol=1e-5
    )

    cache = variables["cache"]
    for pos in range(t0, tokens.shape[1]):
        step_logits, mutated = model.apply(
            {"params": params, "cache": cache},
            tokens[:, pos : pos + 1],
            mode="decode",
            decode_pos=jnp.asarray(pos, jnp.int32),
            mutable=["cache"],
        )
        cache = mutated["cache"]
        np.testing.assert_allclose(
            step_logits[:, 0], full_logits[:, pos], rtol=1e-5, atol=1e-5
        )


def test_greedy_generation_matches_naive_loop(tiny_lm):
    model, params = tiny_lm
    prompt = jax.random.randint(jax.random.key(2), (2, 6), 0, VOCAB)
    n_new = 8

    generate = make_generator(model, max_new_tokens=n_new, temperature=0.0)
    fast = generate(params, prompt, jax.random.key(3))

    # Naive: re-run the FULL forward pass on the growing sequence each step.
    seq = prompt
    naive = []
    for _ in range(n_new):
        logits = model.apply({"params": params}, seq)
        nxt = jnp.argmax(logits[:, -1], axis=-1)
        naive.append(nxt)
        seq = jnp.concatenate([seq, nxt[:, None]], axis=1)
    np.testing.assert_array_equal(np.asarray(fast), np.stack(naive, axis=1))


def test_top_k_1_equals_greedy(tiny_lm):
    model, params = tiny_lm
    prompt = jax.random.randint(jax.random.key(4), (2, 4), 0, VOCAB)
    greedy = make_generator(model, max_new_tokens=5, temperature=0.0)
    topk1 = make_generator(model, max_new_tokens=5, temperature=0.7, top_k=1)
    np.testing.assert_array_equal(
        np.asarray(greedy(params, prompt, jax.random.key(5))),
        np.asarray(topk1(params, prompt, jax.random.key(6))),
    )


def test_sampling_is_reproducible_and_in_vocab(tiny_lm):
    model, params = tiny_lm
    prompt = jax.random.randint(jax.random.key(7), (3, 4), 0, VOCAB)
    generate = make_generator(
        model, max_new_tokens=6, temperature=0.9, top_k=20, top_p=0.95
    )
    a = generate(params, prompt, jax.random.key(8))
    b = generate(params, prompt, jax.random.key(8))
    np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    assert a.shape == (3, 6)
    assert (np.asarray(a) >= 0).all() and (np.asarray(a) < VOCAB).all()


def test_eos_rows_pad_after_stop(tiny_lm):
    model, params = tiny_lm
    prompt = jax.random.randint(jax.random.key(9), (2, 4), 0, VOCAB)
    ref = make_generator(model, max_new_tokens=6, temperature=0.0)
    first = np.asarray(ref(params, prompt, jax.random.key(0)))[:, 0]
    eos = int(first[0])  # make row 0's very first token the EOS

    pad = VOCAB + 7  # out-of-vocab sentinel so padding is unmistakable
    gen = make_generator(
        model, max_new_tokens=6, temperature=0.0, eos_id=eos, pad_id=pad
    )
    out = np.asarray(gen(params, prompt, jax.random.key(0)))
    for row in out:
        hits = np.flatnonzero(row == eos)
        if hits.size:
            assert (row[hits[0] + 1 :] == pad).all()
        else:
            assert (row != pad).all()


def test_sample_tokens_top_p_keeps_top_token():
    # One dominant logit: top_p tiny must still sample it.
    logits = jnp.array([[0.0, 10.0, 0.0, 0.0]])
    tok = sample_tokens(logits, jax.random.key(0), temperature=1.0, top_p=0.01)
    assert int(tok[0]) == 1


def test_generation_rejects_overlong_request(tiny_lm):
    model, params = tiny_lm
    prompt = jnp.zeros((1, 30), jnp.int32)
    generate = make_generator(model, max_new_tokens=5, temperature=0.0)
    with pytest.raises(ValueError, match="max_seq_len"):
        generate(params, prompt, jax.random.key(0))


@pytest.mark.slow
def test_decode_model_generates_from_seq_parallel_training():
    """The full user journey: train on a data x seq mesh with ring
    attention, then generate from the SAME params via
    ``LMTrainer.decode_model()`` — and the decode logits agree with the
    trainer's own (sequence-parallel) forward pass."""
    from cs744_pytorch_distributed_tutorial_tpu.data import synthetic_tokens
    from cs744_pytorch_distributed_tutorial_tpu.parallel import make_mesh
    from cs744_pytorch_distributed_tutorial_tpu.train import LMConfig, LMTrainer

    cfg = LMConfig(
        vocab_size=VOCAB, num_layers=2, num_heads=2, d_model=32, d_ff=64,
        max_seq_len=32, seq_len=16, global_batch_size=4,
        attention_impl="ring", data_parallel=2, seq_parallel=2,
    )
    tr = LMTrainer(cfg, mesh=make_mesh({"data": 2, "seq": 2}))
    tokens = synthetic_tokens(16, cfg.seq_len, VOCAB, seed=0)
    params, _, losses = tr.fit(tokens, steps=2)
    assert np.isfinite(losses).all()

    decode = tr.decode_model()
    prompt = jnp.asarray(tokens[:2, :8], jnp.int32)
    generate = make_generator(decode, max_new_tokens=6, temperature=0.0)
    out = generate(params, prompt, jax.random.key(0))
    assert out.shape == (2, 6)

    # Cross-check the first generated token against the model's plain
    # forward pass on the prompt (greedy = argmax of the last position).
    full_logits = decode.apply({"params": jax.device_get(params)}, prompt)
    np.testing.assert_array_equal(
        np.asarray(out[:, 0]), np.asarray(jnp.argmax(full_logits[:, -1], -1))
    )


def test_generation_with_bfloat16_and_remat_variants():
    """Decode works for the bf16 compute path and ignores remat."""
    model = TransformerLM(
        vocab_size=VOCAB,
        num_layers=1,
        num_heads=2,
        d_model=16,
        d_ff=32,
        max_seq_len=16,
        attention_impl="dense",
        dtype=jnp.bfloat16,
        remat=True,
    )
    toks = jnp.zeros((1, 4), jnp.int32)
    params = model.init(jax.random.key(0), toks)["params"]
    prompt = jax.random.randint(jax.random.key(1), (2, 4), 0, VOCAB)
    out = make_generator(model, max_new_tokens=4, temperature=0.0)(
        params, prompt, jax.random.key(2)
    )
    assert out.shape == (2, 4)


@pytest.mark.parametrize("dispatch", ["scatter", "dropless"])
def test_moe_decode_logits_match_full_forward(dispatch):
    """The routed-FFN decode path: cached prefill+decode on a MoE LM
    must reproduce the full forward's logits. At decode the token
    routes ALONE (N=1, so top-k experts each see one row) — parity
    with the batched forward requires either capacity high enough that
    the forward dropped nothing (scatter, cf=4) or the dropless path,
    where nothing can drop by construction. Routing is data-dependent,
    so this also pins that the ragged/slot machinery traces at N=1."""
    # cf=4 uncaps the scatter forward; dropless rejects non-default
    # capacity knobs (nothing can drop by construction).
    cap_kw = {"moe_capacity_factor": 4.0} if dispatch == "scatter" else {}
    model = TransformerLM(
        vocab_size=VOCAB, num_layers=2, num_heads=2, d_model=32, d_ff=64,
        max_seq_len=32, attention_impl="dense", num_experts=4,
        moe_top_k=2, moe_dispatch=dispatch, **cap_kw,
    )
    toks0 = jnp.zeros((1, 4), jnp.int32)
    params = model.init(jax.random.key(0), toks0)["params"]
    tokens = jax.random.randint(jax.random.key(1), (2, 10), 0, VOCAB)
    full_logits = model.apply({"params": params}, tokens)

    t0 = 4
    prefill_logits, variables = model.apply(
        {"params": params}, tokens[:, :t0], mode="prefill", mutable=["cache"]
    )
    np.testing.assert_allclose(
        prefill_logits, full_logits[:, :t0], rtol=1e-5, atol=1e-5
    )
    cache = variables["cache"]
    for pos in range(t0, tokens.shape[1]):
        step_logits, mutated = model.apply(
            {"params": params, "cache": cache},
            tokens[:, pos : pos + 1],
            mode="decode",
            decode_pos=jnp.asarray(pos, jnp.int32),
            mutable=["cache"],
        )
        cache = mutated["cache"]
        np.testing.assert_allclose(
            step_logits[:, 0], full_logits[:, pos], rtol=1e-5, atol=1e-5
        )
    # And the jitted generator loop runs end-to-end on the MoE model.
    gen = make_generator(model, max_new_tokens=4, temperature=0.0)
    out = gen(params, tokens[:, :t0], jax.random.key(2))
    assert out.shape == (2, 4)
