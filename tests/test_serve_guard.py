"""graftguard (serve/guard.py): chaos-tested serving contracts.

Five pin groups:

1. **Pool accounting is un-corruptible.** ``PagePool.free`` rejects
   double frees (the silent-corruption bug class behind leaked pages),
   and ``check_invariants`` — called under ``__debug__`` at every
   retire/preempt/expiry — proves free ∪ live partitions the pool.
2. **Deadlines resolve terminally.** ``deadline_s`` / ``max_queue_s``
   expiry retires a request as ``timed_out`` — active slots free their
   pages immediately, queued requests resolve with honestly-absent
   latency fields — under an injected fake clock, so the sweeps are
   deterministic. The nasty interleaving is pinned: a preemption victim
   whose deadline lapses while it waits at the queue FRONT.
3. **Shedding is deterministic and non-destructive.** The bounded queue
   rejects with machine-readable ``serve_shed`` events (identical
   sequences on identical seeded traces); ``degrade`` trims budgets
   under pool pressure and the trimmed output is a bitwise PREFIX of
   the untrimmed oracle (greedy AND sampled — the per-request PRNG
   streams make the trim invisible to the tokens that survive).
4. **Zero retraces survive the guard.** All guard work is host-side;
   the CompileCounter proves admission control, shedding, and expiry
   never touch the fixed-shape decode step (GL002).
5. **Crashes never reach the client.** ``ServeChaosMonkey`` faults
   (``decode_nan`` / ``slow_step`` / ``engine_crash``) drive
   ``run_serve_with_recovery``'s snapshot→restart→replay ladder; the
   overloaded chaos e2e must end with every request terminally
   resolved, zero leaked pages, and admitted outputs token-identical
   to an uninterrupted oracle run.

The chaos-smoke CI job runs this file without the tier-1 ``slow``
filter; docs/reliability.md ("Serving under failure and overload") is
the operator story.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from cs744_pytorch_distributed_tutorial_tpu.models import TransformerLM
from cs744_pytorch_distributed_tutorial_tpu.serve import (
    GuardConfig,
    PagePool,
    Request,
    ServeConfig,
    ServeGuard,
    ServingEngine,
    make_poisson_workload,
    run_poisson,
    run_serve_with_recovery,
)
from cs744_pytorch_distributed_tutorial_tpu.utils.chaos import (
    FaultSchedule,
    ServeChaosMonkey,
)
from cs744_pytorch_distributed_tutorial_tpu.utils.failure import (
    DecodeNanError,
    EngineCrashError,
)

VOCAB = 61


class _ListSink:
    def __init__(self):
        self.records = []

    def emit(self, record):
        self.records.append(dict(record))


class _Clock:
    """Injectable monotonic clock: guard sweeps become deterministic."""

    def __init__(self, t: float = 100.0):
        self.t = float(t)

    def __call__(self) -> float:
        return self.t

    def advance(self, dt: float) -> None:
        self.t += float(dt)


@pytest.fixture(scope="module")
def tiny_lm():
    model = TransformerLM(
        vocab_size=VOCAB,
        num_layers=2,
        num_heads=2,
        d_model=32,
        d_ff=64,
        max_seq_len=64,
        attention_impl="dense",
        use_rope=True,
    )
    params = model.init(
        jax.random.key(0), jnp.zeros((1, 4), jnp.int32)
    )["params"]
    return model, params


def _cfg(**kw):
    base = dict(num_slots=2, page_size=4, num_pages=33, max_pages_per_slot=8)
    base.update(kw)
    return ServeConfig(**base)


def _prompt(rng, n):
    return rng.integers(1, VOCAB, size=n).astype(np.int32)


# ---------------------------------------------------------------------------
# PagePool hardening (double-free + invariants)
# ---------------------------------------------------------------------------


def test_pool_rejects_double_free():
    pool = PagePool(num_pages=9, page_size=4)
    pages = pool.alloc(3)
    pool.free(pages)
    with pytest.raises(ValueError, match="double free"):
        pool.free([pages[0]])
    assert pool.check_invariants()


def test_pool_rejects_duplicate_pages_in_one_free():
    pool = PagePool(num_pages=9, page_size=4)
    a = pool.alloc(2)
    with pytest.raises(ValueError, match="double free"):
        pool.free([a[0], a[0]])
    # the rejected call must not have mutated anything
    assert pool.allocated_pages == 2
    assert pool.check_invariants()
    pool.free(a)
    assert pool.free_pages == 8
    assert pool.check_invariants()


def test_pool_check_invariants_catches_corruption():
    pool = PagePool(num_pages=9, page_size=4)
    pool.alloc(2)
    pool._free.append(pool._free[0])  # a double-free that slipped through
    with pytest.raises(AssertionError):
        pool.check_invariants()


# ---------------------------------------------------------------------------
# Guard config + admission control
# ---------------------------------------------------------------------------


def test_guard_config_validates():
    with pytest.raises(ValueError, match="shed_policy"):
        GuardConfig(shed_policy="drop")
    with pytest.raises(ValueError, match="degrade_floor"):
        GuardConfig(degrade_floor=0)
    with pytest.raises(ValueError, match="pressure_free_frac"):
        GuardConfig(pressure_free_frac=1.5)


def test_queue_full_sheds_terminally(tiny_lm):
    model, params = tiny_lm
    sink = _ListSink()
    eng = ServingEngine(
        model, params, _cfg(), sink=sink, clock=_Clock(),
        guard=ServeGuard(cfg=GuardConfig(max_queue_depth=2)),
    )
    rng = np.random.default_rng(3)
    reqs = [
        eng.submit(Request(prompt=_prompt(rng, 4), max_new_tokens=4))
        for _ in range(5)
    ]
    shed = [r for r in reqs if r.terminal_status == "rejected"]
    assert [r.req_id for r in shed] == [2, 3, 4]
    assert len(eng._queue) == 2
    assert all(
        r.done_time is not None and r.output_tokens == 0 for r in shed
    )
    evs = [e for e in sink.records if e.get("kind") == "serve_shed"]
    assert [(e["id"], e["reason"], e["terminal"]) for e in evs] == [
        (2, "queue_full", True), (3, "queue_full", True),
        (4, "queue_full", True),
    ]
    assert eng.guard.shed_counts == {"queue_full": 3}
    while eng.busy:
        eng.step()
    assert eng.stats()["shed_requests"] == 3
    shed_ids = {r.req_id for r in shed}
    assert all(
        r.terminal_status == "completed"
        for r in reqs if r.req_id not in shed_ids
    )
    # every submission resolved exactly once
    assert sorted(r.req_id for r in eng._completed) == [0, 1, 2, 3, 4]


@pytest.mark.slow  # chaos-smoke CI runs these without the tier-1 filter
@pytest.mark.parametrize(
    "sample",
    [dict(), dict(temperature=0.9, top_k=20)],
    ids=["greedy", "sampled"],
)
def test_degrade_trim_output_is_oracle_prefix(tiny_lm, sample):
    """A degrade-trimmed request's stream is a bitwise PREFIX of its
    untrimmed oracle output — greedy trivially, sampled because the
    per-request PRNG streams key on (req_id, absolute token index)."""
    model, params = tiny_lm
    rng = np.random.default_rng(5)
    filler_prompt = _prompt(rng, 8)
    prompt = _prompt(rng, 6)

    oracle = ServingEngine(model, params, _cfg(**sample))
    oracle.submit(Request(prompt=filler_prompt.copy(), max_new_tokens=8))
    o = oracle.submit(Request(prompt=prompt.copy(), max_new_tokens=20))
    oracle.run()

    sink = _ListSink()
    guard = ServeGuard(cfg=GuardConfig(
        shed_policy="degrade", degrade_floor=6, pressure_free_frac=1.0,
    ))
    eng = ServingEngine(model, params, _cfg(**sample), sink=sink, guard=guard)
    # pool is unpressured while empty; the filler's pages trip the
    # (deliberately hair-trigger) pressure threshold for the next admit
    eng.submit(Request(prompt=filler_prompt.copy(), max_new_tokens=8))
    eng.step()
    r = eng.submit(Request(prompt=prompt.copy(), max_new_tokens=20))
    assert r.max_new_tokens == 6, "degrade did not trim at admission"
    assert r.orig_max_new_tokens == 6, "trim must precede budget record"
    eng.run()
    assert r.terminal_status == "completed"
    assert r.generated == o.generated[:6]
    trims = [e for e in sink.records if e.get("kind") == "serve_shed"]
    assert [(e["reason"], e["terminal"], e["tokens_shed"])
            for e in trims] == [("degrade_trim", False, 14)]
    assert eng.guard.shed_counts == {"degrade_trim": 1}


@pytest.mark.slow  # chaos-smoke CI runs these without the tier-1 filter
def test_shed_events_deterministic_on_seeded_trace(tiny_lm):
    """Two runs of the same seeded overload trace under a fake clock
    produce IDENTICAL serve_shed and timed_out event sequences."""
    model, params = tiny_lm

    def run_once():
        clock = _Clock()
        sink = _ListSink()
        eng = ServingEngine(
            model, params, _cfg(), sink=sink, clock=clock,
            guard=ServeGuard(cfg=GuardConfig(
                max_queue_depth=2, deadline_s=3.0,
            )),
        )
        rng = np.random.default_rng(9)
        sizes = rng.integers(4, 9, size=(10, 2))
        for k, (plen, budget) in enumerate(sizes):
            eng.submit(Request(
                prompt=_prompt(rng, int(plen)),
                max_new_tokens=int(budget),
            ))
            if k % 3 == 2:
                eng.step()
                clock.advance(0.5)
        while eng.busy:
            eng.step()
            clock.advance(0.5)
        sheds = [
            (e["id"], e["reason"], e["terminal"])
            for e in sink.records if e.get("kind") == "serve_shed"
        ]
        expiries = [
            (e["id"], e["reason"], e["queued"])
            for e in sink.records
            if e.get("kind") == "serve" and e.get("event") == "timed_out"
        ]
        return sheds, expiries

    first, second = run_once(), run_once()
    assert first == second
    assert first[0], "trace was not overloaded enough to shed"
    assert first[1], "trace was not slow enough to expire deadlines"


# ---------------------------------------------------------------------------
# Deadlines + expiry (fake clock)
# ---------------------------------------------------------------------------


def test_deadline_expires_active_slot_and_frees_pages(tiny_lm):
    model, params = tiny_lm
    clock = _Clock()
    sink = _ListSink()
    eng = ServingEngine(
        model, params, _cfg(), sink=sink, clock=clock,
        guard=ServeGuard(cfg=GuardConfig(deadline_s=10.0)),
    )
    rng = np.random.default_rng(0)
    r = eng.submit(Request(prompt=_prompt(rng, 6), max_new_tokens=20))
    for _ in range(3):
        eng.step()
    assert r.first_token_time is not None and r.done_time is None
    clock.advance(11.0)
    eng.step()
    assert r.terminal_status == "timed_out"
    assert r.done_time is not None
    # pages reclaimed immediately, pool partition intact
    assert eng.pool.free_pages == eng.pool.num_pages - 1
    assert eng.pool.check_invariants()
    evs = [
        e for e in sink.records
        if e.get("kind") == "serve" and e.get("event") == "timed_out"
    ]
    assert [(e["id"], e["reason"], e["queued"]) for e in evs] == [
        (r.req_id, "deadline", False)
    ]
    assert eng.stats()["timed_out_requests"] == 1
    # tokens surfaced before expiry were delivered, and the request
    # record carries real latency fields
    rec = [
        e for e in sink.records
        if e.get("kind") == "serve" and e.get("event") == "request"
    ][0]
    assert rec["status"] == "timed_out" and rec["ttft_ms"] is not None


def test_queue_wait_expires_queued_request(tiny_lm):
    model, params = tiny_lm
    clock = _Clock()
    sink = _ListSink()
    eng = ServingEngine(
        model, params, _cfg(num_slots=1), sink=sink, clock=clock,
        guard=ServeGuard(cfg=GuardConfig(max_queue_s=5.0)),
    )
    rng = np.random.default_rng(1)
    first = eng.submit(Request(prompt=_prompt(rng, 6), max_new_tokens=24))
    eng.step()  # first owns the only slot
    waiting = eng.submit(Request(prompt=_prompt(rng, 6), max_new_tokens=8))
    clock.advance(6.0)
    eng.step()
    assert waiting.terminal_status == "timed_out"
    assert waiting.first_token_time is None
    assert waiting.output_tokens == 0
    rec = [
        e for e in sink.records
        if e.get("kind") == "serve" and e.get("event") == "request"
        and e["id"] == waiting.req_id
    ]
    # never produced a token: latency fields honestly absent, not zero
    assert rec[0]["ttft_ms"] is None
    assert rec[0]["decode_ms_per_token"] is None
    evs = [
        e for e in sink.records
        if e.get("kind") == "serve" and e.get("event") == "timed_out"
    ]
    assert [(e["id"], e["reason"], e["queued"]) for e in evs] == [
        (waiting.req_id, "queue_wait", True)
    ]
    # max_queue_s does NOT bound the request that already started
    while eng.busy:
        eng.step()
    assert first.terminal_status == "completed"
    assert first.output_tokens == 24
    assert eng.pool.check_invariants()


@pytest.mark.slow  # chaos-smoke CI runs these without the tier-1 filter
def test_preempted_victim_expires_at_queue_front(tiny_lm):
    """The nasty interleaving: a LIFO-preempted victim waits at the
    queue FRONT with its pages already freed; its deadline lapses
    before re-admission. Expiry must resolve it terminally without
    touching the pool again, and the drain must leak nothing."""
    model, params = tiny_lm
    clock = _Clock()
    sink = _ListSink()
    # 8 allocatable pages, slots want up to 7 each -> guaranteed fights
    cfg = _cfg(num_slots=3, num_pages=9, max_pages_per_slot=7)
    eng = ServingEngine(
        model, params, cfg, sink=sink, clock=clock, guard=ServeGuard(),
    )
    rng = np.random.default_rng(13)
    cases = [(6, 18), (10, 14), (8, 16), (5, 20), (12, 12)]
    reqs = [
        eng.submit(Request(
            prompt=_prompt(rng, plen), max_new_tokens=budget,
        ))
        for plen, budget in cases
    ]
    victim = None
    while eng.busy:
        eng.step()
        if eng._queue and eng._queue[0].preemptions > 0:
            victim = eng._queue[0]  # LIFO re-queue = front of the line
            break
    assert victim is not None, "pool was not tight enough to preempt"
    victim.deadline_s = 1.0
    clock.advance(2.0)  # arrival was >= 2s ago on the fake clock
    while eng.busy:
        eng.step()
    assert victim.terminal_status == "timed_out"
    survivors = [r for r in reqs if r is not victim]
    for r in survivors:
        assert r.terminal_status == "completed", r.req_id
        # budget compares against the ORIGINAL grant: preemption folds
        # generated tokens into the prompt and decrements max_new_tokens
        assert r.output_tokens == r.orig_max_new_tokens
    # zero leaked pages after the drain, partition intact
    assert eng.pool.free_pages == eng.pool.num_pages - 1
    assert eng.pool.check_invariants()
    assert eng.stats()["timed_out_requests"] == 1
    assert len(eng._completed) == len(cases)


# ---------------------------------------------------------------------------
# Zero retraces with the guard enabled (GL002 under guardrails)
# ---------------------------------------------------------------------------


def test_zero_retraces_with_guard_enabled(tiny_lm):
    """Admission control, queue-full shedding, AND deadline expiry are
    pure host work: the warmed decode step must not retrace while all
    three fire."""
    from cs744_pytorch_distributed_tutorial_tpu.obs.system import (
        CompileCounter,
    )

    model, params = tiny_lm
    clock = _Clock()
    guard = ServeGuard(cfg=GuardConfig(
        deadline_s=30.0, max_queue_s=20.0, max_queue_depth=4,
        shed_policy="degrade", degrade_floor=4, pressure_free_frac=0.3,
    ))
    eng = ServingEngine(
        model, params, _cfg(num_slots=3), guard=guard, clock=clock,
    )
    rng = np.random.default_rng(11)

    def burst(sizes):
        for plen, budget in sizes:
            eng.submit(Request(
                prompt=_prompt(rng, plen), max_new_tokens=budget,
            ))
        while eng.busy:
            eng.step()
            clock.advance(0.2)

    burst([(4, 3), (8, 5)])  # warmup: compiles prefill buckets + decode
    cc = CompileCounter()
    # churn + queue_full sheds (6 submissions against depth 4)
    burst([(3, 8), (6, 2), (8, 7), (5, 3), (7, 12), (4, 2)])
    assert guard.shed_counts.get("queue_full", 0) >= 1
    # deadline expiry of an active slot, still inside the counter
    r = eng.submit(Request(prompt=_prompt(rng, 5), max_new_tokens=12))
    eng.step()
    clock.advance(31.0)
    eng.step()
    assert r.terminal_status == "timed_out"
    assert cc.count == 0, f"{cc.count} retraces with guard enabled"


# ---------------------------------------------------------------------------
# Serve chaos kinds (unit level)
# ---------------------------------------------------------------------------


def test_chaos_decode_nan_raises_and_fires_once(tiny_lm):
    model, params = tiny_lm
    eng = ServingEngine(model, params, _cfg())
    monkey = ServeChaosMonkey(FaultSchedule({2: "decode_nan"}))
    monkey.install(eng)
    rng = np.random.default_rng(17)
    eng.submit(Request(prompt=_prompt(rng, 4), max_new_tokens=8))
    with pytest.raises(DecodeNanError):
        while eng.busy:
            eng.step()
    # fire-once: the popped fault is gone, a reinstall can't re-fire it
    assert 2 not in monkey.schedule.faults


@pytest.mark.slow  # chaos-smoke CI runs these without the tier-1 filter
def test_chaos_engine_crash_is_snapshot_consistent(tiny_lm):
    """engine_crash raises BEFORE the step runs, so snapshot() on the
    dead engine resumes token-identically on a fresh one — with the
    monkey re-installed (its counter spans restarts, nothing
    re-fires)."""
    model, params = tiny_lm
    rng = np.random.default_rng(19)
    prompt = _prompt(rng, 5)

    oracle = ServingEngine(model, params, _cfg())
    o = oracle.submit(Request(prompt=prompt.copy(), max_new_tokens=8))
    oracle.run()

    eng = ServingEngine(model, params, _cfg())
    monkey = ServeChaosMonkey(FaultSchedule({3: "engine_crash"}))
    monkey.install(eng)
    r = eng.submit(Request(prompt=prompt.copy(), max_new_tokens=8))
    with pytest.raises(EngineCrashError):
        while eng.busy:
            eng.step()
    snap = eng.snapshot()
    eng2 = ServingEngine(model, params, _cfg())
    monkey.install(eng2)
    eng2.resume(snap)
    while eng2.busy:
        eng2.step()
    done = {q.req_id: q for q in eng2._completed}
    rec = done[r.req_id]
    assert rec.recovered and rec.terminal_status == "recovered"
    produced = list(rec.prompt[rec.orig_prompt_len:]) + list(rec.generated)
    assert produced == o.generated


@pytest.mark.slow  # chaos-smoke CI runs these without the tier-1 filter
def test_chaos_slow_step_stalls_via_injectable_sleep(tiny_lm):
    model, params = tiny_lm
    stalls = []
    eng = ServingEngine(model, params, _cfg())
    monkey = ServeChaosMonkey(
        FaultSchedule({1: {"kind": "slow_step", "stall_s": 0.25}}),
        sleep=stalls.append,
    )
    monkey.install(eng)
    rng = np.random.default_rng(23)
    r = eng.submit(Request(prompt=_prompt(rng, 4), max_new_tokens=6))
    while eng.busy:
        eng.step()
    assert stalls == [0.25]  # stalled exactly once, injectably
    assert r.terminal_status == "completed"  # slow_step is non-fatal
    assert r.output_tokens == 6


# ---------------------------------------------------------------------------
# Tracer: shed/timeout lifecycles audit clean
# ---------------------------------------------------------------------------


def test_tracer_shed_and_timeout_lifecycles_audit_clean(tiny_lm):
    from cs744_pytorch_distributed_tutorial_tpu.obs.serve_trace import (
        ServeTracer,
        check_spans,
        reconcile,
    )

    model, params = tiny_lm
    clock = _Clock()
    tracer = ServeTracer(1)
    eng = ServingEngine(
        model, params, _cfg(num_slots=1), clock=clock, tracer=tracer,
        guard=ServeGuard(cfg=GuardConfig(
            max_queue_depth=1, max_queue_s=2.0,
        )),
    )
    rng = np.random.default_rng(29)
    a = eng.submit(Request(prompt=_prompt(rng, 4), max_new_tokens=6))
    eng.step()  # a takes the only slot
    b = eng.submit(Request(prompt=_prompt(rng, 4), max_new_tokens=6))
    c = eng.submit(Request(prompt=_prompt(rng, 4), max_new_tokens=6))
    assert c.terminal_status == "rejected"  # bounded queue shed it
    clock.advance(3.0)
    eng.step()  # b expires while queued (never admitted)
    while eng.busy:
        eng.step()
        clock.advance(0.1)
    assert b.terminal_status == "timed_out"
    assert a.terminal_status == "completed"
    eng.finalize_trace()
    assert check_spans(tracer.spans) == []
    assert reconcile(tracer.spans, tracer.requests) == []
    sheds = [s for s in tracer.spans if s["name"] == "shed"]
    assert [(s["req"], s["reason"]) for s in sheds] == [
        (c.req_id, "queue_full")
    ]
    recs = {r["req"]: r for r in tracer.requests}
    assert recs[b.req_id]["status"] == "timed_out"
    assert recs[c.req_id]["status"] == "rejected"
    assert "status" not in recs[a.req_id]


# ---------------------------------------------------------------------------
# Loadgen terminal-status accounting
# ---------------------------------------------------------------------------


def test_loadgen_counts_terminal_statuses(tiny_lm):
    model, params = tiny_lm
    sink = _ListSink()
    eng = ServingEngine(
        model, params, _cfg(), sink=sink,
        guard=ServeGuard(cfg=GuardConfig(max_queue_depth=2)),
    )
    wl = make_poisson_workload(
        num_requests=10, rate_rps=5000.0, prompt_len=(4, 8),
        output_len=(4, 8), vocab_size=VOCAB, seed=2,
    )
    rec = run_poisson(eng, wl, sink=sink)
    # every submitted request reached exactly one terminal status
    assert (
        rec["completed"] + rec["rejected"]
        + rec["timed_out"] + rec["recovered"] == 10
    )
    assert rec["rejected"] >= 1, "the bounded queue never bit"
    twins = {
        r["metric"]: r["value"]
        for r in sink.records if r.get("kind") == "bench"
    }
    assert twins["serve_rejected"] == rec["rejected"]
    assert twins["serve_timed_out"] == rec["timed_out"]


# ---------------------------------------------------------------------------
# Supervised recovery (chaos-smoke tier: slow)
# ---------------------------------------------------------------------------


@pytest.mark.slow  # chaos-smoke CI runs these without the tier-1 filter
@pytest.mark.parametrize(
    "sample",
    [dict(), dict(temperature=0.9, top_k=20)],
    ids=["greedy", "sampled"],
)
def test_overload_chaos_streams_token_identical_to_oracle(tiny_lm, sample):
    """The acceptance e2e: Poisson arrivals well past sustainable rate,
    decode_nan AND engine_crash injected mid-run. The supervised loop
    must finish with zero crashes surfacing, every request terminally
    resolved, zero leaked pages, and every delivered stream
    token-identical to an uninterrupted oracle run — greedy bitwise,
    sampled via the per-request PRNG streams."""
    model, params = tiny_lm
    cfg = _cfg(num_slots=3, **sample)
    wl = make_poisson_workload(
        num_requests=16, rate_rps=200.0, prompt_len=(4, 10),
        output_len=(4, 10), vocab_size=VOCAB, seed=21,
    )
    oracle = ServingEngine(model, params, cfg)
    orc = [
        oracle.submit(Request(prompt=p.copy(), max_new_tokens=int(m)))
        for p, m in zip(wl.prompts, wl.max_new_tokens)
    ]
    oracle.run()
    expect = {
        r.req_id: list(r.prompt[r.orig_prompt_len:]) + list(r.generated)
        for r in orc
    }

    sink = _ListSink()
    # bounded queue that never trips: req_ids stay aligned with the
    # oracle so the PRNG streams match; overload pressure comes from
    # the arrival rate alone
    guard = ServeGuard(cfg=GuardConfig(max_queue_depth=64))
    monkey = ServeChaosMonkey(
        FaultSchedule({5: "decode_nan", 12: "engine_crash"}),
        telemetry=sink,
    )
    engines = []

    def make_engine():
        eng = ServingEngine(model, params, cfg, sink=sink, guard=guard)
        engines.append(eng)
        return eng

    rec = run_serve_with_recovery(
        make_engine, wl, monkey=monkey, max_restarts=4,
        telemetry=sink, sink=sink,
    )
    assert rec["restarts"] == 2
    assert rec["requests"] == 16
    assert rec["rejected"] == 0 and rec["timed_out"] == 0
    assert rec["completed"] + rec["recovered"] == 16
    done = {r.req_id: r for e in engines for r in e._completed}
    assert sorted(done) == list(range(16))
    for rid, r in done.items():
        produced = (
            list(r.prompt[r.orig_prompt_len:]) + list(r.generated)
        )
        assert produced == expect[rid], rid
    # zero leaked pages on the surviving engine
    assert engines[-1].pool.free_pages == engines[-1].pool.num_pages - 1
    assert engines[-1].pool.check_invariants()
    events = [
        e.get("event") for e in sink.records if e.get("kind") == "event"
    ]
    assert events.count("recovery_restart") == 2
    assert "recovery_complete" in events
    assert "recovery_giveup" not in events


@pytest.mark.slow  # chaos-smoke CI runs these without the tier-1 filter
def test_hung_step_watchdog_triggers_restart(tiny_lm):
    """A wedged decode step (slow_step stall well past step_timeout_s)
    climbs the watchdog's warn→dump→abort ladder; the supervisor turns
    the abort into HungStepError and restarts the engine."""
    model, params = tiny_lm
    sink = _ListSink()
    wl = make_poisson_workload(
        num_requests=4, rate_rps=50.0, prompt_len=(4, 8),
        output_len=(4, 6), vocab_size=VOCAB, seed=3,
    )
    # abort fires at 3x step_timeout_s (warn -> dump -> abort), so the
    # stall must exceed 6s — and the timeout must be generous enough
    # that the replacement engine's inline recompile (honest recovery
    # downtime, on the clock) can never exhaust the ladder by itself
    monkey = ServeChaosMonkey(
        FaultSchedule({2: {"kind": "slow_step", "stall_s": 7.0}}),
        telemetry=sink,
    )
    rec = run_serve_with_recovery(
        lambda: ServingEngine(model, params, _cfg(), sink=sink),
        wl, monkey=monkey, max_restarts=2, step_timeout_s=2.0,
        telemetry=sink, sink=sink,
    )
    assert rec["restarts"] == 1
    assert rec["completed"] + rec["recovered"] == 4
    restart = [
        e for e in sink.records
        if e.get("kind") == "event" and e.get("event") == "recovery_restart"
    ]
    assert len(restart) == 1
    assert "HungStepError" in restart[0]["failure"]


@pytest.mark.slow  # chaos-smoke CI runs these without the tier-1 filter
def test_recovery_giveup_emits_traceback(tiny_lm):
    model, params = tiny_lm
    sink = _ListSink()
    wl = make_poisson_workload(
        num_requests=2, rate_rps=100.0, prompt_len=(4, 6),
        output_len=(3, 5), vocab_size=VOCAB, seed=31,
    )
    monkey = ServeChaosMonkey(
        FaultSchedule({0: "decode_nan"}), telemetry=sink,
    )
    with pytest.raises(DecodeNanError):
        run_serve_with_recovery(
            lambda: ServingEngine(model, params, _cfg(), sink=sink),
            wl, monkey=monkey, max_restarts=0, telemetry=sink, sink=sink,
        )
    give = [
        e for e in sink.records
        if e.get("kind") == "event" and e.get("event") == "recovery_giveup"
    ]
    assert len(give) == 1
    assert give[0]["restarts"] == 0
    tb = give[0]["traceback"]
    assert tb.startswith("Traceback")
    assert "DecodeNanError" in tb.strip().splitlines()[-1]


# ---------------------------------------------------------------------------
# metrics_summary: giveup traceback tail + shed aggregation
# ---------------------------------------------------------------------------


def _load_metrics_summary():
    import importlib.util
    import os

    spec = importlib.util.spec_from_file_location(
        "metrics_summary",
        os.path.join(os.path.dirname(__file__), "..", "benchmarks",
                     "metrics_summary.py"),
    )
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def test_metrics_summary_giveup_traceback_and_shed_rows():
    ms = _load_metrics_summary()
    records = [
        {"kind": "event", "event": "recovery_giveup", "process_id": 0,
         "generation": 0, "restarts": 2,
         "traceback": ("Traceback (most recent call last):\n"
                       "  ...\n"
                       "DecodeNanError: decode step 5 produced "
                       "out-of-vocab tokens\n")},
        {"kind": "serve_shed", "reason": "queue_full", "terminal": True},
        {"kind": "serve_shed", "reason": "queue_full", "terminal": True},
        {"kind": "serve_shed", "reason": "degrade_trim",
         "terminal": False},
        {"kind": "serve_summary", "engine": "continuous", "requests": 4,
         "completed": 1, "rejected": 2, "timed_out": 1, "recovered": 0,
         "restarts": 2, "tokens_per_sec": 1.0, "ttft_p50_ms": 1.0,
         "ttft_p99_ms": 2.0},
    ]
    s = ms.summarize(records)
    assert s["chaos_events"]["recovery_giveup"]["traceback_tail"] == (
        "DecodeNanError: decode step 5 produced out-of-vocab tokens"
    )
    assert s["serve_shed"] == {"queue_full": 2, "degrade_trim": 1}
    assert s["serve_shed_terminal"] == 2
    row = s["serve"]["continuous"]
    assert (row["completed"], row["rejected"], row["timed_out"],
            row["restarts"]) == (1, 2, 1, 2)
