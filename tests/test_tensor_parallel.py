"""Tensor parallelism: Megatron-style sharded sublayers must be invariant
to the tensor-axis size — same global params, same function.

No counterpart exists in the reference (data parallelism only, SURVEY
§2.3); this is the beyond-parity capability stack: column/row-parallel
kernels (``models/transformer.py``), f/g boundary collectives
(``parallel/tensor.py``), spec-aware gradient sync (``train/lm.py``).
"""

import jax
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from cs744_pytorch_distributed_tutorial_tpu.parallel import make_mesh
from cs744_pytorch_distributed_tutorial_tpu.train import LMConfig, LMTrainer

SMALL = dict(
    vocab_size=64,
    num_layers=2,
    num_heads=4,
    d_model=32,
    d_ff=64,
    max_seq_len=64,
    seq_len=16,
    global_batch_size=4,
    seed=3,
)


def _tokens(n=4, t=17, seed=0):
    return np.random.default_rng(seed).integers(0, 64, (n, t)).astype(np.int32)


def _global(tree):
    return jax.tree.map(lambda a: np.asarray(jax.device_get(a)), tree)


@pytest.mark.parametrize("tp", [2, 4])
@pytest.mark.slow
def test_tp_loss_matches_single_device(tp):
    toks = _tokens()
    cfg1 = LMConfig(**SMALL, attention_impl="dense")
    tr1 = LMTrainer(
        cfg1, mesh=make_mesh({"data": 1, "seq": 1}, devices=jax.devices()[:1])
    )
    cfg_tp = LMConfig(**SMALL, attention_impl="dense", tensor_parallel=tp)
    tr_tp = LMTrainer(
        cfg_tp,
        mesh=make_mesh(
            {"data": 1, "seq": 1, "tensor": tp}, devices=jax.devices()[:tp]
        ),
    )

    p1, _ = tr1.init()
    ptp, _ = tr_tp.init()
    # identical global params regardless of tp (init is tp-agnostic)
    jax.tree.map(
        np.testing.assert_array_equal, _global(p1), _global(ptp)
    )

    x1, y1 = tr1.shard_batch(toks)
    xtp, ytp = tr_tp.shard_batch(toks)
    l1 = float(tr1.eval_step(p1, x1, y1)["loss"])
    ltp = float(tr_tp.eval_step(ptp, xtp, ytp)["loss"])
    assert np.isclose(l1, ltp, rtol=1e-5), (l1, ltp)


@pytest.mark.slow
def test_tp_train_step_matches_single_device():
    toks = _tokens(seed=1)
    cfg1 = LMConfig(**SMALL, attention_impl="dense")
    tr1 = LMTrainer(
        cfg1, mesh=make_mesh({"data": 1, "seq": 1}, devices=jax.devices()[:1])
    )
    cfg_tp = LMConfig(**SMALL, attention_impl="dense", tensor_parallel=4)
    tr_tp = LMTrainer(
        cfg_tp,
        mesh=make_mesh(
            {"data": 1, "seq": 1, "tensor": 4}, devices=jax.devices()[:4]
        ),
    )
    p1, o1 = tr1.init()
    ptp, otp = tr_tp.init()
    x1, y1 = tr1.shard_batch(toks)
    xtp, ytp = tr_tp.shard_batch(toks)
    for _ in range(2):
        p1, o1, m1 = tr1.train_step(p1, o1, x1, y1)
        ptp, otp, mtp = tr_tp.train_step(ptp, otp, xtp, ytp)
    assert np.isclose(float(m1["loss"]), float(mtp["loss"]), rtol=1e-4)
    jax.tree.map(
        lambda a, b: np.testing.assert_allclose(a, b, rtol=2e-4, atol=2e-6),
        _global(p1),
        _global(ptp),
    )


def test_tp_params_are_actually_sharded():
    cfg = LMConfig(**SMALL, attention_impl="dense", tensor_parallel=4)
    tr = LMTrainer(
        cfg,
        mesh=make_mesh(
            {"data": 1, "seq": 1, "tensor": 4}, devices=jax.devices()[:4]
        ),
    )
    params, opt_state = tr.init()
    blk = params["block_0"]
    # column-parallel: output features split 4 ways on one device
    q = blk["attn"]["q"]["kernel"]
    assert q.shape == (32, 32)
    assert q.sharding.spec == P(None, "tensor")
    local = q.addressable_shards[0].data
    assert local.shape == (32, 8)
    # row-parallel: input features split
    mo = blk["mlp_out"]["kernel"]
    assert mo.sharding.spec == P("tensor", None)
    assert mo.addressable_shards[0].data.shape == (16, 32)
    # optimizer moments follow the param layout
    mu_q = opt_state[0].mu["block_0"]["attn"]["q"]["kernel"]
    assert mu_q.addressable_shards[0].data.shape == (32, 8)
    # replicated leaves stay replicated
    assert params["ln_f"]["scale"].sharding.spec == P()


@pytest.mark.slow
def test_tp_composes_with_ring_and_data_and_seq_axes():
    cfg = LMConfig(
        **SMALL,
        attention_impl="ring",
        data_parallel=2,
        seq_parallel=2,
        tensor_parallel=2,
    )
    tr = LMTrainer(cfg)  # builds the {data:2, seq:2, tensor:2} mesh
    params, opt_state, losses = tr.fit(_tokens(n=16, t=17, seed=2), steps=4)
    assert all(np.isfinite(l) for l in losses)
    # training moves the loss (sanity that grads are nonzero and synced)
    assert losses[-1] != losses[0]


@pytest.mark.slow
def test_tp_composes_with_ulysses():
    cfg = LMConfig(
        **SMALL,
        attention_impl="ulysses",
        data_parallel=2,
        seq_parallel=2,
        tensor_parallel=2,
    )
    tr = LMTrainer(cfg)
    params, opt_state, losses = tr.fit(_tokens(n=16, t=17, seed=4), steps=2)
    assert all(np.isfinite(l) for l in losses)


def test_tp_validation():
    with pytest.raises(ValueError, match="num_heads"):
        LMTrainer(
            LMConfig(**{**SMALL, "num_heads": 6}, tensor_parallel=4),
            mesh=make_mesh(
                {"data": 1, "seq": 1, "tensor": 4}, devices=jax.devices()[:4]
            ),
        )
