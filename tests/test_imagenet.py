"""ImageNet-scale path: 7x7/stride-2 ResNet stem, synthetic data at any
resolution/class count, end-to-end DP training (the BASELINE.md
"ResNet-50 / ImageNet DDP scale-out" target, exercised at CI scale)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from cs744_pytorch_distributed_tutorial_tpu.config import TrainConfig
from cs744_pytorch_distributed_tutorial_tpu.data import synthetic_images
from cs744_pytorch_distributed_tutorial_tpu.models import resnet18, resnet50


def _param_count(model, image_size):
    sample = jnp.zeros((1, image_size, image_size, 3), jnp.float32)
    params = jax.eval_shape(
        lambda: model.init(jax.random.key(0), sample, train=False)
    )["params"]
    return sum(int(np.prod(p.shape)) for p in jax.tree.leaves(params))


def test_imagenet_stem_param_counts_match_torchvision():
    """With the 7x7 stem and 1000 classes, the architectures are the
    standard ones — parameter counts must equal torchvision's published
    resnet18/resnet50 totals exactly."""
    assert _param_count(
        resnet18(num_classes=1000, cifar_stem=False), 224
    ) == 11_689_512
    assert _param_count(
        resnet50(num_classes=1000, cifar_stem=False), 224
    ) == 25_557_032


def test_imagenet_stem_downsamples_16x():
    """7x7/s2 conv + 3x3/s2 maxpool + 3 stage strides: 224 -> 7 before
    the global pool; spot-check via an intermediate-free forward."""
    model = resnet18(num_classes=12, cifar_stem=False)
    x = jnp.zeros((2, 64, 64, 3))
    variables = model.init(jax.random.key(0), x, train=False)
    logits = model.apply(variables, x, train=False)
    assert logits.shape == (2, 12)


def test_synthetic_images_shapes_and_determinism():
    a = synthetic_images(6, 2, image_size=72, num_classes=20, seed=3)
    b = synthetic_images(6, 2, image_size=72, num_classes=20, seed=3)
    assert a.train_images.shape == (6, 72, 72, 3)
    assert a.train_images.dtype == np.uint8
    assert a.train_labels.max() < 20
    np.testing.assert_array_equal(a.train_images, b.train_images)


def test_synthetic_cifar10_unchanged_by_generalization():
    """The golden-trace/bench generator must produce the round-1 byte
    stream: pin a digest of the first images."""
    from cs744_pytorch_distributed_tutorial_tpu.data import synthetic_cifar10

    ds = synthetic_cifar10(8, 4, seed=0)
    assert ds.train_images.shape == (8, 32, 32, 3)
    # Stable scalar fingerprints of the RNG draw sequence.
    assert int(ds.train_images.astype(np.int64).sum()) == 3159047
    assert ds.train_labels.tolist() == [5, 0, 0, 9, 1, 2, 1, 4]


@pytest.mark.slow
def test_imagenet_shaped_training_end_to_end(mesh4):
    """ResNet-18 with the ImageNet stem at 64x64/20 classes trains under
    DP allreduce: finite, decreasing-ish loss, eval runs."""
    from cs744_pytorch_distributed_tutorial_tpu.train import Trainer

    cfg = TrainConfig(
        model="resnet18",
        image_size=64,
        num_classes=20,
        imagenet_stem=True,
        sync="allreduce",
        num_devices=4,
        global_batch_size=16,
        synthetic_data=True,
        synthetic_train_size=64,
        synthetic_test_size=32,
        epochs=1,
        log_every=1,
    )
    tr = Trainer(cfg, mesh=mesh4)
    state, history = tr.fit()
    losses = [l for (_, _, l) in history["train_loss"]]
    assert np.isfinite(losses).all()
    assert history["eval"][-1]["count"] == 32


def test_real_data_rejects_non_cifar_shape():
    from cs744_pytorch_distributed_tutorial_tpu.data import load_cifar10

    with pytest.raises(ValueError, match="CIFAR-10 only"):
        load_cifar10("/nonexistent", synthetic=False, image_size=224)
