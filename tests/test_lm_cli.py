"""LM CLI (lm_cli.py): train + generate end-to-end from flags."""

import json

import numpy as np
import pytest

from cs744_pytorch_distributed_tutorial_tpu.data import byte_corpus
from cs744_pytorch_distributed_tutorial_tpu.lm_cli import main

TINY = [
    "--num-layers", "1", "--num-heads", "2", "--d-model", "16",
    "--d-ff", "32", "--max-seq-len", "64", "--seq-len", "16",
    "--global-batch-size", "4", "--num-seqs", "16", "--steps", "2",
]


def test_lm_cli_synthetic_train_and_generate(capsys):
    rc = main(TINY + [
        "--vocab-size", "32", "--data-parallel", "2", "--seq-parallel", "2",
        "--generate", "4", "--prompt-len", "4", "--json",
    ])
    assert rc == 0
    out = capsys.readouterr().out
    summary = json.loads(out.strip().splitlines()[-1])
    assert summary["steps"] == 2
    assert np.isfinite(summary["final_loss"])
    assert len(summary["sample"]) == 4
    assert all(0 <= t < 32 for t in summary["sample"])


@pytest.mark.slow
def test_lm_cli_byte_corpus(tmp_path, capsys):
    corpus = tmp_path / "corpus.txt"
    corpus.write_bytes(b"the quick brown fox jumps over the lazy dog " * 40)
    rc = main(TINY + [
        "--text-file", str(corpus), "--attention-impl", "dense",
        "--generate", "6", "--prompt", "the quick", "--temperature", "0",
        "--json",
    ])
    assert rc == 0
    summary = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert summary["vocab_size"] == 256
    assert isinstance(summary["sample"], str) and len(summary["sample"]) == 6


def test_lm_cli_eval_split(capsys):
    rc = main(TINY + [
        "--vocab-size", "32", "--data-parallel", "2", "--seq-parallel", "2",
        "--num-seqs", "24", "--eval-frac", "0.25", "--json",
    ])
    assert rc == 0
    summary = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert summary["eval"] is not None
    assert np.isfinite(summary["eval"]["loss"])
    assert summary["eval"]["perplexity"] == pytest.approx(
        np.exp(summary["eval"]["loss"]), rel=1e-5
    )


def test_byte_corpus_windows(tmp_path):
    f = tmp_path / "c.bin"
    f.write_bytes(bytes(range(100)))
    toks = byte_corpus(str(f), 9, shuffle=False)
    assert toks.shape == (10, 10)
    np.testing.assert_array_equal(toks[0], np.arange(10))
    np.testing.assert_array_equal(toks[1], np.arange(10, 20))

    overlapping = byte_corpus(str(f), 9, stride=1, shuffle=False)
    assert overlapping.shape == (91, 10)

    shuffled_a = byte_corpus(str(f), 9, seed=1)
    shuffled_b = byte_corpus(str(f), 9, seed=1)
    np.testing.assert_array_equal(shuffled_a, shuffled_b)

    with pytest.raises(ValueError, match="bytes"):
        byte_corpus(str(f), 200)


@pytest.mark.slow
def test_pipeline_parallel_route(capsys):
    """--pipeline-parallel routes to PipelineLMTrainer (gpipe or 1f1b);
    incompatible flags are rejected, not silently dropped."""
    import json as json_

    import pytest

    from cs744_pytorch_distributed_tutorial_tpu.lm_cli import main

    rc = main([
        "--pipeline-parallel", "2", "--pipeline-schedule", "1f1b",
        "--data-parallel", "2", "--num-layers", "2", "--num-heads", "2",
        "--d-model", "32", "--d-ff", "64", "--max-seq-len", "32",
        "--seq-len", "16", "--global-batch-size", "8", "--num-seqs", "16",
        "--steps", "2", "--log-every", "1", "--json",
    ])
    assert rc == 0
    summary = json_.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert summary["engine"] == "pipeline" and summary["finite"]

    # --tensor-parallel COMPOSES since the round-3 promotion (covered in
    # test_pipeline.py); sequence parallelism composes since round 4 —
    # but only with a sequence-parallel attention impl ("ring" is the
    # parser default, so the happy path needs no extra flag).
    with pytest.raises(SystemExit, match="does not compose"):
        main([
            "--pipeline-parallel", "2", "--seq-parallel", "2",
            "--attention-impl", "dense", "--steps", "1",
        ])
    rc = main([
        "--pipeline-parallel", "2", "--seq-parallel", "2",
        "--attention-impl", "ring", "--use-rope", "--num-layers", "2",
        "--num-heads", "2", "--d-model", "32", "--d-ff", "64",
        "--max-seq-len", "32", "--seq-len", "16",
        "--global-batch-size", "4", "--num-seqs", "8", "--steps", "1",
        "--log-every", "1", "--json",
    ])
    assert rc == 0
    summary = json_.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert summary["engine"] == "pipeline" and summary["finite"]
    assert summary["seq_parallel"] == 2


@pytest.mark.parametrize(
    "flags",
    [
        ["--int8-decode"],                      # weight scope only
        ["--int8-kv-cache"],                    # cache only (bf16 weights)
        ["--int8-decode", "--int8-kv-cache"],   # composed
        ["--int8-decode", "all"],               # explicit full weight scope
    ],
    ids=["weights", "kv-cache", "both", "all-scope"],
)
@pytest.mark.slow
def test_lm_cli_int8_decode(capsys, flags):
    rc = main(TINY + [
        "--vocab-size", "32", "--generate", "4", "--prompt-len", "4",
        "--temperature", "0", "--json", *flags,
    ])
    assert rc == 0
    summary = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert len(summary["sample"]) == 4
    assert all(0 <= t < 32 for t in summary["sample"])


def test_lm_cli_int8_head_scope_rejected_with_tied_embeddings(capsys):
    with pytest.raises(SystemExit):
        main(TINY + [
            "--vocab-size", "32", "--tie-embeddings", "--generate", "4",
            "--prompt-len", "4", "--temperature", "0", "--int8-decode",
            "--json",
        ])


@pytest.mark.slow
def test_lm_cli_llama_options_both_engines(capsys):
    # shard_map engine with rmsnorm + swiglu, incl. generation.
    rc = main(TINY + [
        "--vocab-size", "32", "--norm", "rmsnorm", "--mlp", "swiglu",
        "--use-rope", "--generate", "4", "--prompt-len", "4",
        "--temperature", "0", "--json",
    ])
    assert rc == 0
    summary = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert np.isfinite(summary["final_loss"]) and len(summary["sample"]) == 4
    # pipeline engine stages the same Block with the same options.
    rc = main([
        "--pipeline-parallel", "2", "--norm", "rmsnorm", "--mlp", "swiglu",
        "--num-layers", "2", "--num-heads", "2", "--d-model", "32",
        "--d-ff", "64", "--max-seq-len", "32", "--seq-len", "16",
        "--global-batch-size", "8", "--num-seqs", "16", "--steps", "2",
        "--log-every", "1", "--json",
    ])
    assert rc == 0
    summary = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert summary["engine"] == "pipeline" and summary["finite"]


def test_lm_cli_speculative_decode(capsys):
    rc = main(TINY + [
        "--vocab-size", "32", "--generate", "6", "--prompt-len", "4",
        "--temperature", "0", "--speculative-k", "2", "--draft-layers", "1",
        "--json",
    ])
    assert rc == 0
    summary = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert len(summary["sample"]) == 6
    # temperature > 0 routes to the rejection-sampling mode (round 4)
    rc = main(TINY + [
        "--vocab-size", "32", "--generate", "4", "--prompt-len", "4",
        "--speculative-k", "2", "--draft-layers", "1",
        "--temperature", "0.8", "--json",
    ])
    assert rc == 0
    summary = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert len(summary["sample"]) == 4
    # truncation breaks the exactness identity — still rejected
    with pytest.raises(SystemExit, match="temperature-only"):
        main(TINY + [
            "--vocab-size", "32", "--generate", "4", "--speculative-k", "2",
            "--temperature", "0.8", "--top-k", "4",
        ])


def test_lm_cli_pipeline_zero1_and_clip(capsys):
    # round 5: the pipeline engine accepts --zero1 (data-sharded AdamW
    # moments) and --grad-clip-norm (spec-aware global norm) instead of
    # rejecting them.
    rc = main([
        "--pipeline-parallel", "2", "--data-parallel", "2",
        "--num-layers", "2", "--num-heads", "2", "--d-model", "32",
        "--d-ff", "64", "--max-seq-len", "32", "--seq-len", "16",
        "--global-batch-size", "8", "--num-seqs", "16", "--steps", "2",
        "--zero1", "--grad-clip-norm", "0.5", "--log-every", "1",
        "--json",
    ])
    assert rc == 0
    summary = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert summary["engine"] == "pipeline" and summary["finite"]


@pytest.mark.slow
def test_lm_cli_speculative_decode_with_fsdp(capsys):
    # --fsdp leaves both target and draft params in chunked [dp, chunk]
    # layout; the decode path must unshard BOTH (ADVICE r4: the draft's
    # unshard result was computed but not passed to the generator).
    rc = main(TINY + [
        "--vocab-size", "32", "--data-parallel", "2", "--fsdp",
        "--generate", "6", "--prompt-len", "4", "--temperature", "0",
        "--speculative-k", "2", "--draft-layers", "1", "--json",
    ])
    assert rc == 0
    summary = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert len(summary["sample"]) == 6
    assert all(0 <= t < 32 for t in summary["sample"])
