"""Serve kill/resume (ServingEngine.snapshot/resume): chaos for serving.

A preempted instance must not corrupt streams: ``snapshot()`` captures
every unfinished request (in-flight ones with the recompute-preemption
transform pre-applied — produced tokens folded into the prompt), and
``resume()`` on a FRESH engine replays them token-for-token identically.
KV is deliberately not captured: recompute rebuilds it, and the
per-request PRNG streams (keyed by request id and absolute output-token
index) make the rebuild output-invariant — greedy bitwise, sampled via
PRNG replay. Plus the observability spine: per-request ``kind:"serve"``
lifecycle events (preempt / recovered), the ``recovered_requests``
counter in ``stats()``/loadgen summaries, and the
``benchmarks/metrics_summary.py`` chaos rows.

The chaos-smoke CI job runs this file on CPU; docs/reliability.md is the
operator story.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from cs744_pytorch_distributed_tutorial_tpu.models import TransformerLM
from cs744_pytorch_distributed_tutorial_tpu.serve import (
    Request,
    ServeConfig,
    ServingEngine,
    make_poisson_workload,
    run_poisson,
)

VOCAB = 61
CASES = [(3, 9), (7, 4), (12, 11), (5, 17), (9, 6)]


class _ListSink:
    def __init__(self):
        self.records = []

    def emit(self, record):
        self.records.append(dict(record))


@pytest.fixture(scope="module")
def tiny_lm():
    model = TransformerLM(
        vocab_size=VOCAB,
        num_layers=2,
        num_heads=2,
        d_model=32,
        d_ff=64,
        max_seq_len=64,
        attention_impl="dense",
        use_rope=True,
    )
    params = model.init(
        jax.random.key(0), jnp.zeros((1, 4), jnp.int32)
    )["params"]
    return model, params


def _submit_cases(eng, data_seed=7):
    rng = np.random.default_rng(data_seed)
    return [
        eng.submit(Request(
            prompt=rng.integers(1, VOCAB, size=plen).astype(np.int32),
            max_new_tokens=budget,
        ))
        for plen, budget in CASES
    ]


def _streams(reqs):
    """Full produced stream per request id — the preemption/recovery
    transform folds early generations into the prompt, so compare
    prompt-tail + generated."""
    return {
        r.req_id: list(r.prompt[r.orig_prompt_len:]) + list(r.generated)
        for r in reqs
    }


def _cfg(**kw):
    base = dict(num_slots=2, page_size=4, num_pages=33, max_pages_per_slot=8)
    base.update(kw)
    return ServeConfig(**base)


@pytest.mark.slow  # chaos-smoke CI runs these without the tier-1 filter
@pytest.mark.parametrize(
    "sample",
    [dict(), dict(temperature=0.9, top_k=20)],
    ids=["greedy", "sampled"],
)
def test_kill_resume_streams_token_identical(tiny_lm, sample):
    """Kill mid-decode, resume on a fresh engine: every request's final
    stream equals the uninterrupted run's, greedy AND sampled — the
    resumed prefill re-derives KV and the (req_id, token index) PRNG
    keys continue the stream exactly where the kill landed."""
    model, params = tiny_lm
    cfg = _cfg(seed=3, **sample)

    ref = ServingEngine(model, params, cfg)
    ref_reqs = _submit_cases(ref)
    ref.run()
    expect = _streams(ref_reqs)

    victim = ServingEngine(model, params, cfg)
    victim_reqs = _submit_cases(victim)
    for _ in range(5):  # mid-decode: slots live, tokens produced
        victim.step()
    assert any(r.generated for r in victim_reqs)
    assert victim.busy  # the kill lands with work in flight
    snap = victim.snapshot()
    assert any(rec["in_flight"] for rec in snap.requests)
    assert any(rec["replayed_tokens"] > 0 for rec in snap.requests)
    del victim  # the process is gone; only the snapshot survives

    fresh = ServingEngine(model, params, cfg)
    resumed = fresh.resume(snap)
    fresh.run()
    done = {r.req_id: r for r in resumed}
    # requests that completed on the victim engine before the kill are
    # not in the snapshot; every unfinished one must finish identically
    for rid, req in done.items():
        assert req.done_time is not None
        assert _streams([req])[rid] == expect[rid], rid
    finished_before = {r.req_id for r in victim_reqs} - set(done)
    assert set(done) | finished_before == set(expect)


@pytest.mark.slow  # chaos-smoke CI runs these without the tier-1 filter
@pytest.mark.slow  # chaos-smoke CI runs these without the tier-1 filter
def test_resume_counts_and_emits_recovered_events(tiny_lm):
    model, params = tiny_lm
    cfg = _cfg(seed=3)
    victim = ServingEngine(model, params, cfg)
    _submit_cases(victim)
    for _ in range(4):
        victim.step()
    snap = victim.snapshot()
    in_flight = sum(1 for rec in snap.requests if rec["in_flight"])
    assert in_flight > 0

    sink = _ListSink()
    fresh = ServingEngine(model, params, cfg, sink=sink)
    fresh.resume(snap)
    events = [r for r in sink.records if r.get("event") == "recovered"]
    assert len(events) == in_flight
    assert all(e["kind"] == "serve" for e in events)
    assert fresh.stats()["recovered_requests"] == in_flight
    fresh.run()
    # the counter is cumulative for the engine's lifetime
    assert fresh.stats()["recovered_requests"] == in_flight


@pytest.mark.slow  # chaos-smoke CI runs these without the tier-1 filter
@pytest.mark.slow  # chaos-smoke CI runs these without the tier-1 filter
def test_resume_guards(tiny_lm):
    model, params = tiny_lm
    victim = ServingEngine(model, params, _cfg(seed=3))
    _submit_cases(victim)
    for _ in range(3):
        victim.step()
    snap = victim.snapshot()

    busy = ServingEngine(model, params, _cfg(seed=3))
    busy.submit(Request(prompt=np.ones((4,), np.int32), max_new_tokens=4))
    with pytest.raises(RuntimeError, match="idle engine"):
        busy.resume(snap)

    reseeded = ServingEngine(model, params, _cfg(seed=4))
    with pytest.raises(ValueError, match="seed"):
        reseeded.resume(snap)


@pytest.mark.slow  # chaos-smoke CI runs these without the tier-1 filter
def test_snapshot_does_not_disturb_live_engine(tiny_lm):
    """snapshot() is a pure read: the live engine keeps serving and its
    outputs still match the uninterrupted reference."""
    model, params = tiny_lm
    cfg = _cfg(seed=3)
    ref = ServingEngine(model, params, cfg)
    ref_reqs = _submit_cases(ref)
    ref.run()

    eng = ServingEngine(model, params, cfg)
    reqs = _submit_cases(eng)
    for _ in range(4):
        eng.step()
    eng.snapshot()
    eng.run()
    assert _streams(reqs) == _streams(ref_reqs)


@pytest.mark.slow  # chaos-smoke CI runs these without the tier-1 filter
@pytest.mark.slow  # chaos-smoke CI runs these without the tier-1 filter
def test_preempt_events_match_counter(tiny_lm):
    """Each recompute preemption emits one kind:"serve" preempt event
    with the replayed-token count — the per-request chaos visibility
    metrics_summary tallies."""
    model, params = tiny_lm
    sink = _ListSink()
    cfg = ServeConfig(num_slots=3, page_size=4, num_pages=9,
                      max_pages_per_slot=7)
    eng = ServingEngine(model, params, cfg, sink=sink)
    rng = np.random.default_rng(13)
    for plen, budget in [(6, 18), (10, 14), (8, 16), (5, 20), (12, 12)]:
        eng.submit(Request(
            prompt=rng.integers(1, VOCAB, size=plen).astype(np.int32),
            max_new_tokens=budget,
        ))
    eng.run()
    assert eng.stats()["preemptions"] > 0, "pool was not tight enough"
    events = [r for r in sink.records if r.get("event") == "preempt"]
    assert len(events) == eng.stats()["preemptions"]
    assert all(e["kind"] == "serve" for e in events)
    assert all(e["replayed_tokens"] >= 0 for e in events)


@pytest.mark.slow  # chaos-smoke CI runs these without the tier-1 filter
def test_loadgen_reports_recovered_twin(tiny_lm):
    """A resumed engine driven by the load generator carries the
    recovery count into the serve_summary record and the bench-shaped
    serve_recovered twin regress.py gates."""
    model, params = tiny_lm
    cfg = _cfg(seed=3)
    victim = ServingEngine(model, params, cfg)
    _submit_cases(victim)
    for _ in range(4):
        victim.step()
    snap = victim.snapshot()

    sink = _ListSink()
    fresh = ServingEngine(model, params, cfg, sink=sink)
    fresh.resume(snap)
    recovered = fresh.stats()["recovered_requests"]
    assert recovered > 0
    wl = make_poisson_workload(
        num_requests=3, rate_rps=100.0, prompt_len=(3, 6),
        output_len=(2, 4), vocab_size=VOCAB, seed=5,
    )
    record = run_poisson(fresh, wl, sink=sink, warmup=False)
    assert record["recovered_requests"] == recovered
    twins = [
        r for r in sink.records
        if r.get("kind") == "bench" and r.get("metric") == "serve_recovered"
    ]
    assert len(twins) == 1 and twins[0]["value"] == recovered


def test_summarize_itl_excludes_kill_gap():
    """A recovered request's resume boundary marks where the clock
    epoch restarted: the diff across it "measures" the kill gap, not an
    inter-token latency, and must be excluded from ITL percentiles —
    while every real gap (including preemption stalls) still counts."""
    from cs744_pytorch_distributed_tutorial_tpu.serve.loadgen import (
        _summarize,
    )

    def req(token_times, boundaries):
        r = Request(prompt=np.ones((3,), np.int32), max_new_tokens=4)
        r.req_id = 0
        r.orig_prompt_len = 3
        r.orig_max_new_tokens = len(token_times)
        r.generated = [1] * len(token_times)
        r.arrival_time = token_times[0] - 0.001
        r.submit_time = r.arrival_time
        r.first_token_time = token_times[0]
        r.done_time = token_times[-1]
        r.token_times = list(token_times)
        r.resume_boundaries = list(boundaries)
        r.recovered = bool(boundaries)
        return r

    # 10 ms gaps with a 5 s kill gap before index-2's token
    times = [0.0, 0.010, 5.010, 5.020, 5.030]
    clean = _summarize("continuous", [req(times, [2])], 1.0, {})
    assert clean["itl_p50_ms"] == pytest.approx(10.0, abs=0.01)
    assert clean["itl_p99_ms"] == pytest.approx(10.0, abs=0.01)
    # without the boundary the kill gap poisons the tail
    dirty = _summarize("continuous", [req(times, [])], 1.0, {})
    assert dirty["itl_p99_ms"] > 1000.0
    # out-of-range boundaries (0, past the end) are ignored, not an error
    edge = _summarize(
        "continuous", [req(times, [0, 2, 99])], 1.0, {}
    )
    assert edge["itl_p99_ms"] == clean["itl_p99_ms"]


@pytest.mark.slow  # chaos-smoke CI runs these without the tier-1 filter
def test_resumed_requests_flag_recovered_and_bound_itl(tiny_lm):
    """End to end: resume sets the boundary at the replayed stream
    position, the per-request record carries recovered=True, and the
    run's ITL percentiles exclude the (here: artificial) kill gap."""
    model, params = tiny_lm
    cfg = _cfg(seed=3)
    victim = ServingEngine(model, params, cfg)
    _submit_cases(victim)
    for _ in range(4):
        victim.step()
    snap = victim.snapshot()
    # in-flight requests carry their pre-kill token_times into the
    # snapshot; fake a long outage so the kill gap is unmistakable
    for rec in snap.requests:
        rec["token_times"] = [t - 120.0 for t in rec["token_times"]]
        if rec.get("arrival_time") is not None:
            rec["arrival_time"] -= 120.0
    del victim

    sink = _ListSink()
    fresh = ServingEngine(model, params, cfg, sink=sink)
    resumed = fresh.resume(snap)
    fresh.run()
    streamed = [r for r in resumed if len(r.token_times) > 1]
    assert any(r.resume_boundaries for r in streamed)
    assert all(r.recovered for r in resumed)
    recs = [r for r in sink.records if r.get("event") == "request"]
    assert recs and all(r["recovered"] for r in recs)

    from cs744_pytorch_distributed_tutorial_tpu.serve.loadgen import (
        _summarize,
    )

    summary = _summarize("continuous", resumed, 1.0, {})
    # the 120 s fake outage must not appear in the ITL tail
    assert summary["itl_p99_ms"] < 60_000.0


def test_metrics_summary_counts_chaos_rows():
    """summarize() tallies the per-request lifecycle events and surfaces
    the recovered count from serve summaries (pure function — fed a
    synthetic record stream)."""
    import importlib.util
    from pathlib import Path

    spec = importlib.util.spec_from_file_location(
        "metrics_summary",
        Path(__file__).resolve().parents[1]
        / "benchmarks" / "metrics_summary.py",
    )
    ms = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(ms)

    records = [
        {"kind": "serve", "event": "preempt", "id": 1, "replayed_tokens": 3},
        {"kind": "serve", "event": "preempt", "id": 2, "replayed_tokens": 0},
        {"kind": "serve", "event": "recovered", "id": 1,
         "replayed_tokens": 4},
        {"kind": "serve_summary", "engine": "continuous", "requests": 5,
         "ttft_p50_ms": 1.0, "ttft_p99_ms": 2.0, "tokens_per_sec": 10.0,
         "preemptions": 2, "recovered_requests": 1},
    ]
    summary = ms.summarize(records)
    assert summary["serve_preempt_replays"] == 2
    assert summary["serve_recovered"] == 1
    assert summary["serve"]["continuous"]["recovered_requests"] == 1
