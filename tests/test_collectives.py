"""Collective primitives vs numpy ground truth on the virtual mesh.

Covers the XLA equivalents of every Gloo op the reference uses
(all_reduce, gather+scatter, isend/irecv — SURVEY §2.2) plus the ring
allreduce.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from cs744_pytorch_distributed_tutorial_tpu.parallel import collectives as C


def _run(fn, x, mesh, out_specs=P("data"), **shard_kw):
    return jax.shard_map(
        fn, mesh=mesh, in_specs=P("data"), out_specs=out_specs, **shard_kw
    )(x)


@pytest.fixture(scope="module")
def data8():
    rng = np.random.default_rng(0)
    return rng.normal(size=(8, 5)).astype(np.float32)


def test_all_reduce_mean(mesh8, data8):
    out = _run(lambda x: C.all_reduce_mean(x, "data"), data8, mesh8)
    expected = np.broadcast_to(data8.mean(axis=0), data8.shape)
    np.testing.assert_allclose(np.asarray(out), expected, rtol=1e-6)


def test_gather_scatter_mean_matches_allreduce(mesh8, data8):
    out = _run(lambda x: C.gather_scatter_mean(x, "data"), data8, mesh8)
    expected = np.broadcast_to(data8.mean(axis=0), data8.shape)
    np.testing.assert_allclose(np.asarray(out), expected, rtol=1e-6)


def test_star_mean(mesh8, data8):
    out = _run(
        lambda x: C.star_mean(x, "data", 8), data8, mesh8, check_vma=False
    )
    expected = np.broadcast_to(data8.mean(axis=0), data8.shape)
    np.testing.assert_allclose(np.asarray(out), expected, rtol=1e-6)


@pytest.mark.parametrize("shape", [(4,), (3, 5), (17,)])  # incl. non-divisible-by-8
def test_ring_all_reduce(mesh8, shape):
    rng = np.random.default_rng(1)
    data = rng.normal(size=(8, *shape)).astype(np.float32)
    out = _run(
        lambda x: C.ring_all_reduce(x[0], "data", 8)[None],
        data,
        mesh8,
        check_vma=False,
    )
    expected = np.broadcast_to(data.sum(axis=0), data.shape)
    np.testing.assert_allclose(np.asarray(out), expected, rtol=1e-5, atol=1e-5)


def test_send_recv(mesh8):
    data = np.arange(8, dtype=np.float32).reshape(8, 1)
    out = _run(
        lambda x: C.send_recv(x, "data", src=3, dst=5), data, mesh8,
        check_vma=False,
    )
    out = np.asarray(out).ravel()
    assert out[5] == 3.0
    assert all(out[i] == 0.0 for i in range(8) if i != 5)


def test_ring_shift(mesh8):
    data = np.arange(8, dtype=np.float32).reshape(8, 1)
    out = _run(
        lambda x: C.ring_shift(x, "data", 8, shift=1), data, mesh8,
        check_vma=False,
    )
    np.testing.assert_array_equal(
        np.asarray(out).ravel(), np.roll(np.arange(8, dtype=np.float32), 1)
    )
