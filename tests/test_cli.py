"""CLI: preset/flag plumbing and a tiny end-to-end run."""

import pytest

from cs744_pytorch_distributed_tutorial_tpu.cli import build_parser, config_from_args, main
from cs744_pytorch_distributed_tutorial_tpu.config import config_for_part


def _cfg(argv):
    return config_from_args(build_parser().parse_args(argv))


def test_part_presets_map_to_reference():
    """SURVEY §2.1: part -> sync mechanism, world 4, global batch 256."""
    assert _cfg(["--part", "1"]).sync == "none"
    assert _cfg(["--part", "2a"]).sync == "gather_scatter"
    assert _cfg(["--part", "2a_extra"]).sync == "p2p_star"
    assert _cfg(["--part", "2b"]).sync == "allreduce"
    cfg3 = _cfg(["--part", "3"])
    assert cfg3.sync == "auto"
    assert cfg3.num_devices == 4
    assert cfg3.global_batch_size == 256
    assert cfg3.per_device_batch_size == 64  # 64/rank (part2a.py:20)


def test_overrides_beat_preset():
    cfg = _cfg(["--part", "2b", "--sync", "ring", "--num-devices", "8",
                "--lr", "0.01"])
    assert cfg.sync == "ring"
    assert cfg.num_devices == 8
    assert cfg.learning_rate == 0.01


def test_bad_part_rejected():
    with pytest.raises(ValueError):
        config_for_part("4")


def test_cli_end_to_end(capsys):
    rc = main([
        "--part", "2b", "--model", "tiny_cnn", "--num-devices", "2",
        "--global-batch-size", "16", "--synthetic-data",
        "--synthetic-train-size", "64", "--synthetic-test-size", "16",
        "--json",
    ])
    assert rc == 0
    out = capsys.readouterr().out
    assert '"final_eval_accuracy"' in out


def test_round2_flags_map_to_config():
    from cs744_pytorch_distributed_tutorial_tpu.cli import (
        build_parser,
        config_from_args,
    )

    args = build_parser().parse_args(
        ["--model", "resnet18", "--fast-conv", "--no-augment"]
    )
    cfg = config_from_args(args)
    assert cfg.fast_conv is True
    assert cfg.augment is False
    # defaults when the flags are absent
    cfg2 = config_from_args(build_parser().parse_args([]))
    assert cfg2.fast_conv is False and cfg2.augment is True
