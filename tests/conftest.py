"""Test harness: multi-device-without-a-cluster.

The reference's verification strategy was "run on 4 CloudLab nodes and
eyeball the loss" (SURVEY §4). Here every collective path runs
single-process in CI on 8 virtual CPU devices via
``--xla_force_host_platform_device_count`` — set BEFORE the XLA backend
initializes. The environment's sitecustomize force-selects the TPU
('axon') platform via ``jax.config``, so we must override the config, not
just the env var.
"""

import os

os.environ["XLA_FLAGS"] = (
    os.environ.get("XLA_FLAGS", "") + " --xla_force_host_platform_device_count=8"
)

import jax

jax.config.update("jax_platforms", "cpu")

import pytest  # noqa: E402


@pytest.fixture(scope="session")
def devices():
    devs = jax.devices()
    assert len(devs) >= 8, f"expected >=8 forced CPU devices, got {devs}"
    return devs


@pytest.fixture(scope="session")
def mesh4():
    from cs744_pytorch_distributed_tutorial_tpu.parallel import make_mesh

    return make_mesh({"data": 4}, devices=jax.devices()[:4])


@pytest.fixture(scope="session")
def mesh8():
    from cs744_pytorch_distributed_tutorial_tpu.parallel import make_mesh

    return make_mesh({"data": 8})
