"""Test harness: multi-device-without-a-cluster.

The reference's verification strategy was "run on 4 CloudLab nodes and
eyeball the loss" (SURVEY §4). Here every collective path runs
single-process in CI on 8 virtual CPU devices via
``--xla_force_host_platform_device_count`` — set BEFORE the XLA backend
initializes. The environment's sitecustomize force-selects the TPU
('axon') platform via ``jax.config``, so we must override the config, not
just the env var.
"""

import os

os.environ["XLA_FLAGS"] = (
    os.environ.get("XLA_FLAGS", "")
    + " --xla_force_host_platform_device_count=8"
    # The concurrency-optimized thunk scheduler issues data-independent
    # collectives in per-device nondeterministic order; the in-process
    # CPU communicator's rendezvous then deadlocks (observed on
    # 1F1B x seq-parallel, where a tick's fwd and bwd halves are
    # independent). TPU hardware is indifferent (channel-keyed DMAs) —
    # this is a CPU-harness setting, not a model requirement.
    + " --xla_cpu_enable_concurrency_optimized_scheduler=false"
)

import jax

jax.config.update("jax_platforms", "cpu")

import pytest  # noqa: E402


@pytest.fixture(autouse=True)
def strict_jax_guard(request):
    """Opt-in strictness: tests marked ``@pytest.mark.strict_jax`` run
    under ``jax.checking_leaks()`` (tracer leaks raise at the leak site)
    and ``jax.transfer_guard("disallow")`` (any IMPLICIT host<->device
    transfer raises). Under the guard, fetch results with an explicit
    ``jax.device_get`` rather than ``float()``/``np.asarray`` — which is
    exactly the discipline graftlint GL001 enforces statically."""
    if request.node.get_closest_marker("strict_jax") is None:
        yield
        return
    with jax.checking_leaks(), jax.transfer_guard("disallow"):
        yield


@pytest.fixture(scope="session")
def devices():
    devs = jax.devices()
    assert len(devs) >= 8, f"expected >=8 forced CPU devices, got {devs}"
    return devs


@pytest.fixture(scope="session")
def mesh4():
    from cs744_pytorch_distributed_tutorial_tpu.parallel import make_mesh

    return make_mesh({"data": 4}, devices=jax.devices()[:4])


@pytest.fixture(scope="session")
def mesh8():
    from cs744_pytorch_distributed_tutorial_tpu.parallel import make_mesh

    return make_mesh({"data": 8})


# Shared tiny-CNN harness for the sharded-optimizer parity suites
# (test_zero1.py, test_fsdp.py): same config, same synthetic batches.
TINY_DP4_CFG = dict(
    model="tiny_cnn",
    num_devices=4,
    global_batch_size=32,
    synthetic_data=True,
    synthetic_train_size=128,
    synthetic_test_size=64,
)


def run_tiny_dp4_steps(
    sync: str,
    mesh,
    steps: int = 4,
    cfg_overrides: dict | None = None,
    data_seed: int = 0,
):
    """Train ``steps`` repeats of one fixed synthetic batch under strategy
    ``sync``; returns (losses, trainer, final_state). The ONE canonical
    step-driving discipline for the parity/golden suites — per-step
    randomness comes from the trainer folding cfg.seed with the step."""
    import jax

    from cs744_pytorch_distributed_tutorial_tpu.config import TrainConfig
    from cs744_pytorch_distributed_tutorial_tpu.data import synthetic_cifar10
    from cs744_pytorch_distributed_tutorial_tpu.parallel.mesh import (
        shard_global_batch,
    )
    from cs744_pytorch_distributed_tutorial_tpu.train import Trainer

    cfg = TrainConfig(**TINY_DP4_CFG, sync=sync, **(cfg_overrides or {}))
    tr = Trainer(cfg, mesh=mesh)
    state = tr.init()
    ds = synthetic_cifar10(TINY_DP4_CFG["global_batch_size"], 8, seed=data_seed)
    x, y = shard_global_batch(mesh, ds.train_images, ds.train_labels)
    key = jax.random.key(cfg.seed)
    losses = []
    for _ in range(steps):
        state, m = tr.train_step(state, x, y, key)
        losses.append(float(m["loss"]))
    return losses, tr, state
