"""Real-TPU (Mosaic-lowered) parity for the Pallas grouped matmuls.

Every gmm test in test_dropless_moe.py forces ``interpret=True`` so the
suite runs on the CPU harness — which leaves the Mosaic compile path
(the one production dropless MoE actually executes) without coverage: a
compile-side regression, e.g. in the ``(block_m, 1)`` lhs block of the
K=1 tgmm used for dbias, would only surface in manual benchmarks
(ADVICE round 5). These tests run the SAME oracles with
``interpret=False`` and are skipped automatically off-TPU.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from cs744_pytorch_distributed_tutorial_tpu.ops.gmm import (
    grouped_matmul,
    grouped_matmul_fused,
)

pytestmark = [
    pytest.mark.tpu,
    pytest.mark.skipif(
        jax.default_backend() != "tpu",
        reason="Mosaic lowering needs a real TPU backend",
    ),
]


def _oracle(x, w, gs):
    ids = np.repeat(np.arange(w.shape[0]), np.asarray(gs))
    return jnp.einsum(
        "nd,ndf->nf", x, jnp.asarray(w)[ids],
        precision=jax.lax.Precision.HIGHEST,
    )


@pytest.mark.parametrize(
    "m,e,gs_list",
    [
        (512, 4, [100, 156, 0, 256]),  # empty group, tile-unaligned splits
        (300, 3, [300, 0, 0]),         # everything in group 0, M % block != 0
    ],
)
def test_gmm_compiled_matches_oracle(m, e, gs_list):
    k, n = 128, 128
    rng = np.random.default_rng(m)
    x = jnp.array(rng.standard_normal((m, k)), jnp.float32)
    w = jnp.array(rng.standard_normal((e, k, n)), jnp.float32)
    gs = jnp.array(gs_list, jnp.int32)
    out = grouped_matmul(
        x, w, gs, impl="pallas", block_m=128, block_n=128, interpret=False
    )
    # f32 inputs on TPU default to bf16-accumulated passes; compare at
    # bf16-level tolerance against the HIGHEST-precision oracle.
    np.testing.assert_allclose(out, _oracle(x, w, gs), rtol=2e-2, atol=2e-2)


@pytest.mark.parametrize("activation", ["none", "gelu"])
def test_gmm_fused_epilogue_compiled(activation):
    m, e, k, n = 512, 4, 128, 128
    rng = np.random.default_rng(7)
    x = jnp.array(rng.standard_normal((m, k)), jnp.float32)
    w = jnp.array(rng.standard_normal((e, k, n)), jnp.float32)
    b = jnp.array(rng.standard_normal((e, n)), jnp.float32)
    gs = jnp.array([128, 100, 0, 284], jnp.int32)
    fused = grouped_matmul_fused(
        x, w, b, gs, activation=activation,
        block_m=128, block_n=128, interpret=False,
    )
    ids = np.repeat(np.arange(e), np.asarray(gs))
    ref = _oracle(x, w, gs) + jnp.asarray(b)[ids]
    if activation == "gelu":
        ref = jax.nn.gelu(ref)
    np.testing.assert_allclose(fused, ref, rtol=2e-2, atol=2e-2)


def test_gmm_fused_grads_compiled():
    """The custom_vjp pair (dx = gmm, dw = tgmm, dbias = the K=1 tgmm
    row-segment-sum) under the real Mosaic lowering, vs ragged AD."""
    m, e, k, n = 256, 4, 128, 128
    rng = np.random.default_rng(3)
    x = jnp.array(rng.standard_normal((m, k)), jnp.float32)
    w = jnp.array(rng.standard_normal((e, k, n)), jnp.float32)
    b = jnp.array(rng.standard_normal((e, n)), jnp.float32)
    gs = jnp.array([64, 0, 100, 92], jnp.int32)

    def loss_fused(x, w, b):
        return jnp.sum(
            grouped_matmul_fused(
                x, w, b, gs, activation="gelu",
                block_m=128, block_n=128, interpret=False,
            )
            ** 2
        )

    def loss_ref(x, w, b):
        ids = jnp.repeat(jnp.arange(e), gs, total_repeat_length=m)
        y = grouped_matmul(x, w, gs, impl="ragged") + b[ids]
        return jnp.sum(jax.nn.gelu(y) ** 2)

    gf = jax.grad(loss_fused, argnums=(0, 1, 2))(x, w, b)
    gr = jax.grad(loss_ref, argnums=(0, 1, 2))(x, w, b)
    for a, r in zip(gf, gr):
        np.testing.assert_allclose(a, r, rtol=3e-2, atol=3e-2)
