"""LM dropout rng plumbing — the round-1 deferred migration
(docs/roadmap.md): ``LMTrainer.train_step`` takes a step index that keys
the dropout mask stream.

Pinned properties:
- dropout=0 ignores the step entirely (the golden LM traces stay valid);
- dropout>0 is deterministic per step and varies across steps;
- tensor-parallel shards draw IDENTICAL masks (the MLP dropout applies
  to row-parallel partial sums before their psum), so the tp=2 and tp=1
  trajectories coincide exactly — the correctness condition called out
  in models/transformer.py.
"""

from __future__ import annotations

import jax
import numpy as np
import pytest

# LM fit runs per case: heavy compile.
pytestmark = pytest.mark.slow


def _trainer(mesh, **kw):
    from cs744_pytorch_distributed_tutorial_tpu.train.lm import (
        LMConfig,
        LMTrainer,
    )

    cfg = LMConfig(
        vocab_size=64,
        num_layers=2,
        num_heads=4,
        d_model=32,
        d_ff=64,
        max_seq_len=64,
        global_batch_size=4,
        seq_len=16,
        seed=7,
        **kw,
    )
    return LMTrainer(cfg, mesh=mesh)


def _tokens(seed=0):
    from cs744_pytorch_distributed_tutorial_tpu.data.text import (
        synthetic_tokens,
    )

    return synthetic_tokens(16, 16, 64, seed=seed)


def _run(tr, steps, step_indices=None):
    params, opt_state = tr.init()
    toks = _tokens()
    losses = []
    for s in range(steps):
        x, y = tr.shard_batch(toks[s * 4 : s * 4 + 4])
        idx = step_indices[s] if step_indices is not None else s
        params, opt_state, m = tr.train_step(params, opt_state, x, y, idx)
        losses.append(float(m["loss"]))
    return losses


def test_dropout_deterministic_per_step(mesh4):
    from cs744_pytorch_distributed_tutorial_tpu.parallel import make_mesh

    mesh = make_mesh({"data": 2, "seq": 2}, devices=jax.devices()[:4])
    tr = _trainer(mesh, data_parallel=2, seq_parallel=2, dropout_rate=0.3)
    a = _run(tr, 3)
    tr2 = _trainer(mesh, data_parallel=2, seq_parallel=2, dropout_rate=0.3)
    b = _run(tr2, 3)
    assert a == b  # same steps -> same masks -> identical trajectory


def test_dropout_masks_vary_with_step(mesh4):
    from cs744_pytorch_distributed_tutorial_tpu.parallel import make_mesh

    mesh = make_mesh({"data": 2, "seq": 2}, devices=jax.devices()[:4])
    # Same BATCH every time, only the step index differs: the loss after
    # one update differs iff the masks do.
    tr = _trainer(mesh, data_parallel=2, seq_parallel=2, dropout_rate=0.3)
    a = _run(tr, 2, step_indices=[0, 0])
    tr2 = _trainer(mesh, data_parallel=2, seq_parallel=2, dropout_rate=0.3)
    b = _run(tr2, 2, step_indices=[0, 1])
    assert a[0] == b[0]  # identical first step
    assert a[1] != b[1]  # different masks at the second


def test_dropout_zero_ignores_step(mesh4):
    from cs744_pytorch_distributed_tutorial_tpu.parallel import make_mesh

    mesh = make_mesh({"data": 2, "seq": 2}, devices=jax.devices()[:4])
    tr = _trainer(mesh, data_parallel=2, seq_parallel=2, dropout_rate=0.0)
    a = _run(tr, 2, step_indices=[0, 0])
    tr2 = _trainer(mesh, data_parallel=2, seq_parallel=2, dropout_rate=0.0)
    b = _run(tr2, 2, step_indices=[5, 9])
    assert a == b  # the step argument is inert without dropout


def test_dropout_identical_across_tensor_shards(mesh8):
    """tp=2 must reproduce tp=1 EXACTLY under dropout: tensor shards
    share masks by construction (rng folds data/seq indices only)."""
    from cs744_pytorch_distributed_tutorial_tpu.parallel import make_mesh

    mesh1 = make_mesh({"data": 2, "seq": 1}, devices=jax.devices()[:2])
    mesh2 = make_mesh(
        {"data": 2, "seq": 1, "tensor": 2}, devices=jax.devices()[:4]
    )
    tr1 = _trainer(mesh1, data_parallel=2, dropout_rate=0.25)
    tr2 = _trainer(
        mesh2, data_parallel=2, tensor_parallel=2, dropout_rate=0.25
    )
    a = _run(tr1, 3)
    b = _run(tr2, 3)
    np.testing.assert_allclose(a, b, rtol=2e-5)


def test_dropout_composes_with_remat(mesh4):
    """remat functionalizes Block.__call__; ``deterministic`` must ride
    as a STATIC argument (models/transformer.py static_argnums) — this
    pins the combination that raised TracerBoolConversionError when it
    was a traced kwarg."""
    from cs744_pytorch_distributed_tutorial_tpu.parallel import make_mesh

    mesh = make_mesh({"data": 2, "seq": 1}, devices=jax.devices()[:2])
    tr = _trainer(mesh, data_parallel=2, dropout_rate=0.3, remat=True)
    a = _run(tr, 2)
    assert all(np.isfinite(a))
    # remat is numerics-preserving: same trajectory as without it
    tr2 = _trainer(mesh, data_parallel=2, dropout_rate=0.3, remat=False)
    b = _run(tr2, 2)
    np.testing.assert_allclose(a, b, rtol=2e-6)
