"""Replica-divergence detection (race-detection analog, SURVEY §5.2)."""

import jax
import numpy as np
import pytest

from cs744_pytorch_distributed_tutorial_tpu.config import TrainConfig
from cs744_pytorch_distributed_tutorial_tpu.data import synthetic_cifar10
from cs744_pytorch_distributed_tutorial_tpu.parallel import make_mesh
from cs744_pytorch_distributed_tutorial_tpu.train import Trainer
from cs744_pytorch_distributed_tutorial_tpu.utils.debug import (
    DivergenceMonitor,
    tree_checksum,
)


def test_monitor_flags_divergence():
    m = DivergenceMonitor(rtol=1e-6)
    m.record(0, 0, 1.0)
    m.record(0, 1, 1.0)
    m.record(1, 0, 1.0)
    m.record(1, 1, 1.5)  # drifted replica
    m.record(2, 0, float("nan"))
    m.record(2, 1, 1.0)
    assert m.divergent_steps() == [1, 2]
    with pytest.raises(AssertionError, match="divergence"):
        m.assert_in_sync()


def test_monitor_tolerates_equal_replicas():
    m = DivergenceMonitor()
    for step in range(5):
        for replica in range(4):
            m.record(step, replica, 3.14 * (step + 1))
    assert m.divergent_steps() == []
    m.assert_in_sync()


def test_tree_checksum_orders_and_shapes():
    t1 = {"a": np.ones((2, 2), np.float32), "b": -np.ones(3, np.float32)}
    assert float(tree_checksum(t1)) == pytest.approx(7.0)
    assert float(tree_checksum({})) == 0.0


def test_training_with_sync_check_stays_in_sync():
    """A real DP run with allreduce sync must record checksums on every
    replica and report zero divergence."""
    mesh = make_mesh({"data": 4}, devices=jax.devices()[:4])
    ds = synthetic_cifar10(128, 32, seed=0)
    cfg = TrainConfig(model="tiny_cnn", sync="allreduce", num_devices=4,
                      global_batch_size=32, epochs=1, synthetic_data=True,
                      debug_sync_check=True)
    tr = Trainer(cfg, mesh=mesh)
    tr.fit(dataset=ds)  # fit itself asserts in-sync at the epoch boundary
    assert tr.sync_monitor.steps_recorded == 4  # 128/32 steps
    # every step saw all 4 replicas
    assert all(tr.sync_monitor.replicas_seen(s) == 4 for s in range(4))
    tr.sync_monitor.assert_in_sync()
