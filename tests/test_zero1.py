"""ZeRO-1 sharded optimizer (parallel/zero.py + sync="zero1").

The contract: zero1 is an optimizer-state LAYOUT, not a different
optimizer — its reduce-scatter/chunk-update/all-gather step must produce
the same parameter trajectory as the replicated allreduce strategy, while
holding only 1/axis_size of the momentum per device.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from conftest import TINY_DP4_CFG, run_tiny_dp4_steps

from cs744_pytorch_distributed_tutorial_tpu.config import TrainConfig
from cs744_pytorch_distributed_tutorial_tpu.train import Trainer


def test_zero1_matches_allreduce(mesh4):
    """Same batches, same seed: zero1 and allreduce must trace the same
    loss curve and land on the same params (reduce_scatter+all_gather is
    allreduce, just decomposed)."""
    l_ar, _, st_ar = run_tiny_dp4_steps("allreduce", mesh4)
    l_z, _, st_z = run_tiny_dp4_steps("zero1", mesh4)
    np.testing.assert_allclose(l_ar, l_z, rtol=1e-5)
    jax.tree.map(
        lambda a, b: np.testing.assert_allclose(a, b, rtol=1e-4, atol=1e-6),
        jax.device_get(st_ar.params),
        jax.device_get(st_z.params),
    )


def test_zero1_momentum_is_sharded(mesh4):
    """Each device holds only its [1, chunk] momentum shard — the memory
    claim of ZeRO-1."""
    _, _, state = run_tiny_dp4_steps("zero1", mesh4, steps=1)
    leaves = jax.tree.leaves(state.opt_state)
    assert leaves, "zero1 opt state is empty"
    for leaf in leaves:
        assert leaf.shape[0] == 4  # global leading axis == axis_size
        shard_rows = {s.data.shape[0] for s in leaf.addressable_shards}
        assert shard_rows == {1}  # one chunk row per device
        # momentum became non-zero after a step
    assert any(float(jnp.abs(l).max()) > 0 for l in leaves)


def test_zero1_uneven_param_sizes(mesh4):
    """Padding path: param sizes not divisible by axis_size still round-trip
    exactly (biases of size 10, BN scales of odd sizes, etc.)."""
    _, _, state = run_tiny_dp4_steps("zero1", mesh4, steps=2)
    # the head bias has 10 elements (not divisible by 4) — finite + updated
    bias = jax.device_get(state.params)["Dense_0"]["bias"]
    assert bias.shape == (10,)
    assert np.isfinite(bias).all()
    assert np.abs(bias).max() > 0


def test_zero1_rejects_fused_optimizer(mesh4):
    with pytest.raises(ValueError, match="zero1"):
        Trainer(
            TrainConfig(**TINY_DP4_CFG, sync="zero1", fused_optimizer=True),
            mesh=mesh4,
        )
