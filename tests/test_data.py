"""Data pipeline: sampler contract, loader shapes, augmentation."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from cs744_pytorch_distributed_tutorial_tpu.data import (
    CIFAR10_MEAN,
    CIFAR10_STD,
    BatchLoader,
    ShardedSampler,
    load_cifar10,
    synthetic_cifar10,
)
from cs744_pytorch_distributed_tutorial_tpu.data.augment import (
    augment_train_batch,
    eval_batch,
    random_crop_flip,
)


# ----------------------------------------------------------------- sampler
def test_sampler_shards_disjoint_and_cover():
    """DistributedSampler contract (master/part2a/part2a.py:107): equal
    sizes, disjoint, union covers the dataset (with wrap-around pad)."""
    n, shards = 103, 4
    all_idx = []
    sizes = set()
    for s in range(shards):
        idx = ShardedSampler(n, shards, s, seed=7).indices(epoch=0)
        sizes.add(len(idx))
        all_idx.append(idx)
    assert sizes == {26}  # ceil(103/4)
    union = np.concatenate(all_idx)
    assert set(union.tolist()) == set(range(n))


def test_sampler_epoch_reshuffles_deterministically():
    s = ShardedSampler(100, 2, 0, seed=1)
    e0a, e0b = s.indices(epoch=0), s.indices(epoch=0)
    e1 = s.indices(epoch=1)
    np.testing.assert_array_equal(e0a, e0b)
    assert not np.array_equal(e0a, e1)


def test_sampler_no_shuffle_is_strided():
    idx = ShardedSampler(8, 2, 1, shuffle=False).indices(0)
    np.testing.assert_array_equal(idx, [1, 3, 5, 7])


def test_sampler_drop_last():
    s = ShardedSampler(103, 4, 0, drop_last=True)
    assert len(s) == 25


# ----------------------------------------------------------------- dataset
def test_synthetic_deterministic_and_learnable_structure():
    a = synthetic_cifar10(100, 20, seed=0)
    b = synthetic_cifar10(100, 20, seed=0)
    np.testing.assert_array_equal(a.train_images, b.train_images)
    assert a.train_images.shape == (100, 32, 32, 3)
    assert a.train_images.dtype == np.uint8
    assert a.train_labels.dtype == np.int32
    # class structure: same-class images closer than cross-class on average
    same = cross = 0.0
    imgs = a.train_images.astype(np.float32)
    lab = a.train_labels
    c0 = imgs[lab == lab[0]]
    cX = imgs[lab != lab[0]]
    if len(c0) > 1 and len(cX) > 0:
        same = np.abs(c0[0] - c0[1]).mean()
        cross = np.abs(c0[0] - cX[0]).mean()
        assert same < cross


def test_load_cifar10_auto_falls_back(tmp_path):
    ds = load_cifar10(str(tmp_path), synthetic_train_size=64, synthetic_test_size=16)
    assert ds.synthetic
    assert len(ds.train_images) == 64


def test_load_cifar10_strict_raises(tmp_path):
    with pytest.raises(FileNotFoundError):
        load_cifar10(str(tmp_path), synthetic=False)


def test_load_cifar10_reads_pickle_format(tmp_path):
    """Write a miniature cifar-10-batches-py tree and read it back."""
    import os
    import pickle

    d = tmp_path / "cifar-10-batches-py"
    d.mkdir()
    rng = np.random.default_rng(0)
    for name, n in [(f"data_batch_{i}", 10) for i in range(1, 6)] + [("test_batch", 10)]:
        data = rng.integers(0, 256, size=(n, 3072), dtype=np.uint8)
        labels = rng.integers(0, 10, size=n).tolist()
        with open(os.path.join(d, name), "wb") as f:
            pickle.dump({b"data": data, b"labels": labels}, f)
    ds = load_cifar10(str(tmp_path))
    assert not ds.synthetic
    assert ds.train_images.shape == (50, 32, 32, 3)
    assert ds.test_images.shape == (10, 32, 32, 3)


# ----------------------------------------------------------------- augment
def test_normalize_matches_reference_constants():
    x = jnp.full((1, 32, 32, 3), 255, jnp.uint8)
    out = np.asarray(eval_batch(x))
    expected = (1.0 - CIFAR10_MEAN) / CIFAR10_STD
    np.testing.assert_allclose(out[0, 0, 0], expected, rtol=1e-5)


def test_crop_flip_shapes_and_determinism():
    imgs = jnp.asarray(
        np.random.default_rng(0).integers(0, 256, (4, 32, 32, 3), dtype=np.uint8)
    )
    key = jax.random.key(0)
    a = random_crop_flip(key, imgs)
    b = random_crop_flip(key, imgs)
    np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    assert a.shape == imgs.shape
    c = random_crop_flip(jax.random.key(1), imgs)
    assert not np.array_equal(np.asarray(a), np.asarray(c))


def test_crop_flip_output_is_a_valid_crop_window():
    """The MXU (one-hot contraction) crop must produce, for every image,
    exactly some 32x32 window of the pad-4 source, optionally h-flipped —
    the semantics of torchvision RandomCrop(32, padding=4)+HFlip
    (master/part1/part1.py:68-73)."""
    rng = np.random.default_rng(3)
    imgs = rng.integers(0, 256, (8, 32, 32, 3), dtype=np.uint8)
    out = np.asarray(random_crop_flip(jax.random.key(7), jnp.asarray(imgs)))
    pad = np.pad(imgs, ((0, 0), (4, 4), (4, 4), (0, 0)))
    for b in range(imgs.shape[0]):
        candidates = [
            win
            for oh in range(9)
            for ow in range(9)
            for win in (
                pad[b, oh : oh + 32, ow : ow + 32],
                pad[b, oh : oh + 32, ow : ow + 32][:, ::-1],
            )
        ]
        assert any(np.array_equal(out[b], c) for c in candidates), b


def test_augment_train_batch_is_normalized():
    imgs = jnp.asarray(
        np.random.default_rng(0).integers(0, 256, (8, 32, 32, 3), dtype=np.uint8)
    )
    out = np.asarray(augment_train_batch(jax.random.key(0), imgs))
    assert out.dtype == np.float32
    assert -3.5 < out.mean() < 3.5


# ----------------------------------------------------------------- loader
def test_batch_loader_shapes(mesh4):
    ds = synthetic_cifar10(64, 16, seed=0)
    loader = BatchLoader(ds.train_images, ds.train_labels, 16, mesh=mesh4, seed=0)
    batches = list(loader.epoch(0))
    assert len(batches) == 4 == len(loader)
    x, y = batches[0]
    assert x.shape == (16, 32, 32, 3)
    assert y.shape == (16,)
    # sharded along data axis
    assert x.sharding.spec == jax.sharding.PartitionSpec("data")


def test_batch_loader_epoch_determinism(mesh4):
    ds = synthetic_cifar10(64, 16, seed=0)
    loader = BatchLoader(ds.train_images, ds.train_labels, 16, mesh=mesh4, seed=0)
    a = [np.asarray(x)[0, 0, 0, 0] for x, _ in loader.epoch(0)]
    b = [np.asarray(x)[0, 0, 0, 0] for x, _ in loader.epoch(0)]
    assert a == b


def test_batch_loader_epoch_start_offsets_plan(mesh4):
    """epoch(e, start=k) yields exactly the tail of epoch(e)'s plan — the
    mid-epoch resume contract (no batches assembled for the skipped head)."""
    ds = synthetic_cifar10(64, 16, seed=0)
    loader = BatchLoader(
        ds.train_images, ds.train_labels, 16, mesh=mesh4, shuffle=True, seed=7
    )
    full = [(np.asarray(x), np.asarray(y)) for x, y in loader.epoch(3)]
    tail = [(np.asarray(x), np.asarray(y)) for x, y in loader.epoch(3, start=2)]
    assert len(tail) == len(full) - 2
    for (fx, fy), (tx, ty) in zip(full[2:], tail):
        np.testing.assert_array_equal(fx, tx)
        np.testing.assert_array_equal(fy, ty)
