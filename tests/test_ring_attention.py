"""Sequence/context parallelism: ring + Ulysses attention vs dense."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from cs744_pytorch_distributed_tutorial_tpu.parallel.ring_attention import (
    dense_attention,
    ring_attention,
    ring_flash_attention,
    ulysses_attention,
)

B, T, H, D = 2, 32, 8, 16


@pytest.fixture(scope="module")
def qkv():
    ks = jax.random.split(jax.random.key(0), 3)
    mk = lambda k: jax.random.normal(k, (B, T, H, D), jnp.float32)
    return mk(ks[0]), mk(ks[1]), mk(ks[2])


def _run_sharded(mesh, fn, q, k, v):
    n = mesh.shape["data"]
    mapped = jax.shard_map(
        lambda a, b, c: fn(a, b, c, "data", n),
        mesh=mesh,
        in_specs=(P(None, "data"),) * 3,
        out_specs=P(None, "data"),
        check_vma=False,
    )
    return np.asarray(jax.jit(mapped)(q, k, v))


@pytest.mark.parametrize("causal", [False, True])
def test_ring_matches_dense(mesh8, qkv, causal):
    q, k, v = qkv
    expected = np.asarray(dense_attention(q, k, v, causal=causal))
    got = _run_sharded(
        mesh8,
        lambda a, b, c, ax, n: ring_attention(a, b, c, ax, n, causal=causal),
        q, k, v,
    )
    np.testing.assert_allclose(got, expected, rtol=2e-5, atol=2e-5)


@pytest.mark.parametrize("causal", [False, True])
def test_ulysses_matches_dense(mesh8, qkv, causal):
    q, k, v = qkv
    expected = np.asarray(dense_attention(q, k, v, causal=causal))
    got = _run_sharded(
        mesh8,
        lambda a, b, c, ax, n: ulysses_attention(a, b, c, ax, n, causal=causal),
        q, k, v,
    )
    np.testing.assert_allclose(got, expected, rtol=2e-5, atol=2e-5)


@pytest.mark.parametrize("causal", [False, True])
def test_ulysses_flash_inner_matches_dense(mesh8, qkv, causal):
    """The all-to-all + Pallas-flash composition: sequence parallelism
    moves the data, the kernel does the math — same answer as dense."""
    q, k, v = qkv
    expected = np.asarray(dense_attention(q, k, v, causal=causal))
    got = _run_sharded(
        mesh8,
        lambda a, b, c, ax, n: ulysses_attention(
            a, b, c, ax, n, causal=causal, inner="flash", flash_interpret=True
        ),
        q, k, v,
    )
    np.testing.assert_allclose(got, expected, rtol=2e-5, atol=2e-5)


@pytest.mark.slow
def test_ulysses_flash_lm_trains():
    """attention_impl='ulysses_flash' end to end on a data x seq mesh."""
    from cs744_pytorch_distributed_tutorial_tpu.data import synthetic_tokens
    from cs744_pytorch_distributed_tutorial_tpu.parallel import make_mesh
    from cs744_pytorch_distributed_tutorial_tpu.train import LMConfig, LMTrainer

    cfg = LMConfig(vocab_size=64, num_layers=1, num_heads=4, d_model=32,
                   d_ff=64, max_seq_len=64, seq_len=32, global_batch_size=4,
                   attention_impl="ulysses_flash",
                   data_parallel=2, seq_parallel=2)
    tr = LMTrainer(cfg, mesh=make_mesh({"data": 2, "seq": 2}))
    tokens = synthetic_tokens(8, 32, 64, seed=0)
    params, _, losses = tr.fit(tokens, steps=2)
    assert np.isfinite(losses).all()

    # Loss agrees with the plain-ulysses impl on the same init.
    cfg2 = cfg.replace(attention_impl="ulysses")
    tr2 = LMTrainer(cfg2, mesh=make_mesh({"data": 2, "seq": 2}))
    p1, _ = tr.init()
    p2, _ = tr2.init()
    x, y = tr.shard_batch(tokens[:4])
    l1 = float(tr.eval_step(p1, x, y)["loss"])
    l2 = float(tr2.eval_step(p2, x, y)["loss"])
    assert l1 == pytest.approx(l2, rel=1e-5)


@pytest.mark.parametrize("causal", [False, True])
def test_ring_flash_matches_dense(mesh8, qkv, causal):
    """Ring rotation between chips + Pallas flash per hop, merged via
    logsumexp — same answer as dense attention.

    causal=False exercises the degenerate-cond hop dispatch that keeps
    the interpret-mode kernel partitionable on CPU (the PartitionId
    lowering gap _rfa_hop_case documents) — it used to be a strict
    xfail here."""
    q, k, v = qkv
    expected = np.asarray(dense_attention(q, k, v, causal=causal))
    got = _run_sharded(
        mesh8,
        lambda a, b, c, ax, n: ring_flash_attention(
            a, b, c, ax, n, causal, True
        ),
        q, k, v,
    )
    np.testing.assert_allclose(got, expected, rtol=2e-5, atol=2e-5)


@pytest.mark.slow
def test_ring_flash_gradients_match_dense(mesh4, qkv):
    """The ring FA-2 backward (per-hop flash_dq/flash_dkv against the
    merged lse, dk/dv accumulators riding the ring home) must agree with
    dense attention's gradients."""
    q, k, v = qkv
    n = mesh4.shape["data"]

    def dense_loss(q, k, v):
        return (dense_attention(q, k, v, causal=True) ** 2).sum()

    mapped = jax.shard_map(
        lambda a, b, c: ring_flash_attention(a, b, c, "data", n, True, True),
        mesh=mesh4,
        in_specs=(P(None, "data"),) * 3,
        out_specs=P(None, "data"),
        check_vma=False,
    )

    def rf_loss(q, k, v):
        return (mapped(q, k, v) ** 2).sum()

    g_dense = jax.grad(dense_loss, argnums=(0, 1, 2))(q, k, v)
    g_rf = jax.jit(jax.grad(rf_loss, argnums=(0, 1, 2)))(q, k, v)
    for gd, gr in zip(g_dense, g_rf):
        np.testing.assert_allclose(
            np.asarray(gr), np.asarray(gd), rtol=5e-4, atol=5e-4
        )


@pytest.mark.slow
def test_ring_flash_lm_trains():
    """attention_impl='ring_flash' end to end on a data x seq mesh."""
    from cs744_pytorch_distributed_tutorial_tpu.data import synthetic_tokens
    from cs744_pytorch_distributed_tutorial_tpu.parallel import make_mesh
    from cs744_pytorch_distributed_tutorial_tpu.train import LMConfig, LMTrainer

    cfg = LMConfig(vocab_size=64, num_layers=1, num_heads=4, d_model=32,
                   d_ff=64, max_seq_len=64, seq_len=32, global_batch_size=4,
                   attention_impl="ring_flash",
                   data_parallel=2, seq_parallel=2)
    tr = LMTrainer(cfg, mesh=make_mesh({"data": 2, "seq": 2}))
    tokens = synthetic_tokens(8, 32, 64, seed=0)
    params, _, losses = tr.fit(tokens, steps=2)
    assert np.isfinite(losses).all()

    # Same eval loss as the XLA ring on the same init.
    cfg2 = cfg.replace(attention_impl="ring")
    tr2 = LMTrainer(cfg2, mesh=make_mesh({"data": 2, "seq": 2}))
    p1, _ = tr.init()
    p2, _ = tr2.init()
    x, y = tr.shard_batch(tokens[:4])
    l1 = float(tr.eval_step(p1, x, y)["loss"])
    l2 = float(tr2.eval_step(p2, x, y)["loss"])
    assert l1 == pytest.approx(l2, rel=1e-5)


def test_ring_gradients_match_dense(mesh4, qkv):
    """Backward through the ring (ppermute transposes to the reverse
    ring) must agree with dense attention's gradients."""
    q, k, v = qkv
    n = mesh4.shape["data"]

    def dense_loss(q, k, v):
        return (dense_attention(q, k, v, causal=True) ** 2).sum()

    mapped = jax.shard_map(
        lambda a, b, c: ring_attention(a, b, c, "data", n, causal=True),
        mesh=mesh4,
        in_specs=(P(None, "data"),) * 3,
        out_specs=P(None, "data"),
        check_vma=False,
    )

    def ring_loss(q, k, v):
        return (mapped(q, k, v) ** 2).sum()

    g_dense = jax.grad(dense_loss, argnums=(0, 1, 2))(q, k, v)
    g_ring = jax.jit(jax.grad(ring_loss, argnums=(0, 1, 2)))(q, k, v)
    for gd, gr in zip(g_dense, g_ring):
        np.testing.assert_allclose(
            np.asarray(gr), np.asarray(gd), rtol=5e-4, atol=5e-4
        )


def test_ulysses_rejects_indivisible_heads(mesh8):
    q = jnp.zeros((1, 8, 3, 4))  # 3 heads, 8-way axis
    with pytest.raises(ValueError, match="divisible"):
        ulysses_attention(q, q, q, "data", 8)


def test_single_device_axis_is_dense(qkv):
    q, k, v = qkv
    np.testing.assert_allclose(
        np.asarray(ring_attention(q, k, v, "data", 1, causal=True)),
        np.asarray(dense_attention(q, k, v, causal=True)),
        rtol=1e-6,
    )


# ---------------------------------------------------------------------------
# Overlap-capable ring structure (round 3)
# ---------------------------------------------------------------------------
def _find_while_bodies(jaxpr, bodies=None):
    """Collect every loop body jaxpr (fori_loop lowers to ``scan`` for
    static trip counts, ``while`` otherwise) reachable from ``jaxpr``."""
    if bodies is None:
        bodies = []

    def subjaxprs(eqn):
        for v in eqn.params.values():
            for cand in v if isinstance(v, (list, tuple)) else [v]:
                if hasattr(cand, "eqns"):
                    yield cand
                elif hasattr(cand, "jaxpr"):
                    yield cand.jaxpr

    for eqn in jaxpr.eqns:
        if eqn.primitive.name in ("while", "scan"):
            bodies.extend(subjaxprs(eqn))
        for inner in subjaxprs(eqn):
            _find_while_bodies(inner, bodies)
    return bodies


def _ring_body_ppermutes(fn, mesh, q, k, v, n):
    """Trace the shard_mapped ring fn and return, for its hop-loop body:
    (top-level ppermute eqns, whether any ppermute hides inside a cond,
    whether any ppermute output feeds another eqn in the same body)."""
    mapped = jax.shard_map(
        lambda a, b, c: fn(a, b, c, "data", n),
        mesh=mesh,
        in_specs=(P(None, "data"),) * 3,
        out_specs=P(None, "data"),
        check_vma=False,
    )
    jaxpr = jax.make_jaxpr(mapped)(q, k, v)
    bodies = _find_while_bodies(jaxpr.jaxpr)
    assert bodies, "no while loop found in the traced ring attention"
    # The hop loop is the body that carries ppermutes at its top level.
    for body in bodies:
        perms = [e for e in body.eqns if e.primitive.name == "ppermute"]
        if not perms:
            continue
        in_cond = any(
            inner_e.primitive.name == "ppermute"
            for e in body.eqns
            if e.primitive.name == "cond"
            for br in e.params["branches"]
            for inner_e in br.jaxpr.eqns
        )
        perm_outs = {str(o) for e in perms for o in e.outvars}
        consumed = any(
            str(iv) in perm_outs
            for e in body.eqns
            if e.primitive.name != "ppermute"
            for iv in e.invars
            if not isinstance(iv, jax.extend.core.Literal)
        )
        return perms, in_cond, consumed
    raise AssertionError("no while body carries top-level ppermutes")


def test_ring_hop_structure_is_overlap_capable(mesh8, qkv):
    """The round-3 restructure (VERDICT r2 #7): each hop-loop tick must
    issue BOTH block transfers (k and v ppermutes) unconditionally at
    the body's top level — a lax.cond-wrapped collective cannot be
    scheduled async — and nothing else in the tick may consume their
    results (they flow straight to the carry), so the ICI transfer and
    the hop's attention math are schedulable concurrently."""
    q, k, v = qkv
    for fn in (
        lambda a, b, c, ax, n: ring_attention(a, b, c, ax, n, causal=True),
        lambda a, b, c, ax, n: ring_flash_attention(
            a, b, c, ax, n, True, True
        ),
    ):
        perms, in_cond, consumed = _ring_body_ppermutes(fn, mesh8, q, k, v, 8)
        assert len(perms) == 2, f"expected k+v ppermutes per tick, got {len(perms)}"
        assert not in_cond, "ppermute wrapped in lax.cond — not async-schedulable"
        assert not consumed, "a ppermute output is consumed inside its own tick"


def test_ring_peeled_final_hop_count(mesh8, qkv):
    """The dead final transfer is peeled, not cond-guarded: the hop loop
    trips axis_size - 1 times (its bound rides the carry as a literal in
    the cond jaxpr; cheaper to check behaviorally — parity above — plus
    structurally: exactly one while body carries the ppermutes)."""
    q, k, v = qkv
    mapped = jax.shard_map(
        lambda a, b, c: ring_attention(a, b, c, "data", 8, causal=False),
        mesh=mesh8,
        in_specs=(P(None, "data"),) * 3,
        out_specs=P(None, "data"),
        check_vma=False,
    )
    jaxpr = jax.make_jaxpr(mapped)(q, k, v)
    bodies = _find_while_bodies(jaxpr.jaxpr)
    with_perms = [
        b
        for b in bodies
        if any(e.primitive.name == "ppermute" for e in b.eqns)
    ]
    assert len(with_perms) == 1
