"""Model zoo: shapes, parameter counts, determinism.

The reference has no model tests; its implicit check is the architecture
table itself (``master/part1/model.py:3-8``). Here the VGG-11 parameter
count is verified analytically against that table: conv(3x3, bias) +
BN(scale, bias) per entry, Linear(512,10) head. BN running statistics are
state, not parameters.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from cs744_pytorch_distributed_tutorial_tpu.models import (
    MODEL_REGISTRY,
    VGG_CFGS,
    get_model,
)


def _n_params(tree):
    return sum(x.size for x in jax.tree.leaves(tree))


def _vgg_expected_params(cfg, num_classes=10):
    total, in_ch = 0, 3
    for entry in cfg:
        if entry == "M":
            continue
        total += 3 * 3 * in_ch * entry + entry  # conv kernel + bias
        total += 2 * entry  # BN scale + bias
        in_ch = entry
    total += 512 * num_classes + num_classes  # linear head
    return total


@pytest.mark.parametrize("name", ["vgg11", "vgg13", "vgg16", "vgg19"])
def test_vgg_param_count(name):
    model = get_model(name)
    variables = model.init(jax.random.key(0), jnp.zeros((1, 32, 32, 3)))
    assert _n_params(variables["params"]) == _vgg_expected_params(VGG_CFGS[name])


@pytest.mark.parametrize("name", ["vgg11", "resnet18", "tiny_cnn"])
def test_forward_shapes(name):
    model = get_model(name)
    x = jnp.zeros((2, 32, 32, 3))
    variables = model.init(jax.random.key(0), x)
    logits = model.apply(variables, x)
    assert logits.shape == (2, 10)
    assert logits.dtype == jnp.float32


def test_vgg_flatten_is_512():
    """32x32 through 5 maxpools -> 1x1x512, the reference's
    flatten_features=512 (model.py:39-40)."""
    model = get_model("vgg11")
    x = jnp.zeros((1, 32, 32, 3))
    variables = model.init(jax.random.key(0), x)
    # Dense kernel input dim encodes the flattened feature count.
    dense = [v for k, v in variables["params"].items() if "Dense" in k]
    assert dense[0]["kernel"].shape == (512, 10)


def test_train_mode_updates_batch_stats():
    model = get_model("tiny_cnn")
    x = jax.random.normal(jax.random.key(1), (4, 32, 32, 3))
    variables = model.init(jax.random.key(0), x)
    _, mutated = model.apply(variables, x, train=True, mutable=["batch_stats"])
    old = jax.tree.leaves(variables["batch_stats"])
    new = jax.tree.leaves(mutated["batch_stats"])
    assert any(not np.allclose(o, n) for o, n in zip(old, new))


def test_bfloat16_compute_float32_params():
    model = get_model("tiny_cnn", dtype=jnp.bfloat16)
    x = jnp.zeros((2, 32, 32, 3))
    variables = model.init(jax.random.key(0), x)
    assert all(p.dtype == jnp.float32 for p in jax.tree.leaves(variables["params"]))
    logits = model.apply(variables, x)
    assert logits.dtype == jnp.float32


def test_forward_deterministic():
    model = get_model("tiny_cnn")
    x = jax.random.normal(jax.random.key(2), (2, 32, 32, 3))
    variables = model.init(jax.random.key(0), x)
    a = model.apply(variables, x)
    b = model.apply(variables, x)
    np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_registry_rejects_unknown():
    with pytest.raises(ValueError):
        get_model("alexnet")


def test_resnet_imagenet_stem():
    model = get_model("resnet18", cifar_stem=False, num_classes=1000)
    x = jnp.zeros((1, 64, 64, 3))
    variables = model.init(jax.random.key(0), x)
    assert model.apply(variables, x).shape == (1, 1000)
