"""End-to-end training: the reference's implicit checks, made real.

Loss must decrease over an epoch on the learnable synthetic set; the
timing window and logging signals must appear; eval counts must add up
across shards (the working version of the reference's dead rank-0 send of
``correct`` — ``slave/part2b/part2b.py:67-69``).
"""

import jax
import numpy as np
import pytest

from cs744_pytorch_distributed_tutorial_tpu.config import TrainConfig, config_for_part
from cs744_pytorch_distributed_tutorial_tpu.data import synthetic_cifar10
from cs744_pytorch_distributed_tutorial_tpu.parallel import make_mesh
from cs744_pytorch_distributed_tutorial_tpu.train import Trainer


@pytest.fixture(scope="module")
def dataset():
    return synthetic_cifar10(512, 128, seed=11)


def _fit(cfg, dataset, mesh):
    tr = Trainer(cfg, mesh=mesh)
    return tr.fit(dataset=dataset)


@pytest.mark.strict_jax
def test_cifar_train_step_strict(dataset):
    """Two CIFAR train steps under leak checking and a transfer guard:
    the step path must neither leak tracers nor transfer implicitly —
    all placement is explicit (host_to_global / device_get)."""
    from cs744_pytorch_distributed_tutorial_tpu.parallel.mesh import (
        shard_global_batch,
    )

    with jax.transfer_guard("allow"):
        # One-time setup (trainer construction, init, data placement)
        # legitimately moves host constants to device.
        mesh = make_mesh({"data": 4}, devices=jax.devices()[:4])
        cfg = TrainConfig(
            model="tiny_cnn", sync="allreduce", num_devices=4,
            global_batch_size=32, synthetic_data=True,
        )
        tr = Trainer(cfg, mesh=mesh)
        state = tr.init()
        x, y = shard_global_batch(
            mesh, dataset.train_images[:32], dataset.train_labels[:32]
        )
        # Pre-place the key replicated on the mesh: a single-device key
        # would be implicitly resharded on every step call.
        from jax.sharding import NamedSharding, PartitionSpec

        key = jax.device_put(
            jax.random.key(0), NamedSharding(mesh, PartitionSpec())
        )
    # Steady state: the step itself and the explicit device_get fetch
    # run under the outer disallow guard — any implicit transfer on the
    # hot path fails the test.
    m = None
    for _ in range(2):
        state, m = tr.train_step(state, x, y, key)
    loss = float(jax.device_get(m["loss"]))
    assert np.isfinite(loss)


def test_dp_training_learns(dataset):
    mesh = make_mesh({"data": 4}, devices=jax.devices()[:4])
    cfg = TrainConfig(
        model="tiny_cnn", sync="allreduce", num_devices=4,
        global_batch_size=64, learning_rate=0.02, epochs=3,
        log_every=4, synthetic_data=True,
    )
    state, hist = _fit(cfg, dataset, mesh)
    losses = [l for (_, _, l) in hist["train_loss"]]
    assert losses[-1] < losses[0]
    accs = [e["accuracy"] for e in hist["eval"]]
    assert accs[-1] > 0.3  # synthetic classes are easily separable
    assert hist["eval"][-1]["count"] == 128  # all test shards counted


@pytest.mark.slow
def test_single_device_part1(dataset):
    mesh = make_mesh({"data": 1}, devices=jax.devices()[:1])
    cfg = config_for_part("1", model="tiny_cnn", global_batch_size=64,
                          learning_rate=0.02, epochs=1, synthetic_data=True)
    state, hist = _fit(cfg, dataset, mesh)
    assert len(hist["train_loss"]) >= 1
    assert hist["eval"][-1]["count"] == 128


def test_timing_window_recorded(dataset):
    mesh = make_mesh({"data": 2}, devices=jax.devices()[:2])
    cfg = TrainConfig(
        model="tiny_cnn", sync="allreduce", num_devices=2,
        global_batch_size=32, epochs=1, synthetic_data=True,
        timing_batches=(1, 3),
    )
    tr = Trainer(cfg, mesh=mesh)
    _, hist = tr.fit(dataset=dataset)
    assert hist["avg_batch_time"] is not None
    assert hist["avg_batch_time"] > 0


def test_batch_stats_stay_per_replica(dataset):
    """BN running stats must remain per-replica (local BN — DDP/reference
    semantics, SURVEY §7b): after training on different shards, replicas'
    stats differ."""
    mesh = make_mesh({"data": 4}, devices=jax.devices()[:4])
    cfg = TrainConfig(
        model="tiny_cnn", sync="allreduce", num_devices=4,
        global_batch_size=64, epochs=1, synthetic_data=True,
    )
    tr = Trainer(cfg, mesh=mesh)
    state, _ = tr.fit(dataset=dataset)
    stats = jax.tree.leaves(jax.device_get(state.batch_stats))
    # at least one leaf's replicas diverge
    assert any(
        not np.allclose(leaf[0], leaf[i])
        for leaf in stats
        for i in range(1, leaf.shape[0])
    )


def test_train_steps_scan_matches_loop(dataset):
    """The in-graph multi-step path (lax.scan) must be numerically
    equivalent to dispatching the same batches step by step."""
    from cs744_pytorch_distributed_tutorial_tpu.parallel.mesh import (
        replicated,
        shard_global_batch,
        shard_stacked_batches,
    )

    mesh = make_mesh({"data": 4}, devices=jax.devices()[:4])
    cfg = TrainConfig(model="tiny_cnn", sync="allreduce", num_devices=4,
                      global_batch_size=32, synthetic_data=True)
    tr = Trainer(cfg, mesh=mesh)
    n_steps, bsz = 3, 32
    xs = dataset.train_images[: n_steps * bsz].reshape(n_steps, bsz, 32, 32, 3)
    ys = dataset.train_labels[: n_steps * bsz].reshape(n_steps, bsz)
    key = jax.device_put(jax.random.key(9), replicated(mesh))

    s_loop = tr.init()
    for i in range(n_steps):
        x, y = shard_global_batch(mesh, xs[i], ys[i])
        s_loop, m_last = tr.train_step(s_loop, x, y, key)

    s_scan = tr.init()
    xst, yst = shard_stacked_batches(mesh, xs, ys)
    s_scan, ms = tr.train_steps(s_scan, xst, yst, key)

    assert ms["loss"].shape == (n_steps,)
    np.testing.assert_allclose(
        float(ms["loss"][-1]), float(m_last["loss"]), rtol=1e-5
    )
    assert int(jax.device_get(s_scan.step)) == n_steps
    for a, b in zip(jax.tree.leaves(s_loop.params), jax.tree.leaves(s_scan.params)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=1e-5, atol=1e-6)


def test_params_replicated_after_training(dataset):
    mesh = make_mesh({"data": 4}, devices=jax.devices()[:4])
    cfg = TrainConfig(
        model="tiny_cnn", sync="p2p_star", num_devices=4,
        global_batch_size=64, epochs=1, synthetic_data=True,
    )
    tr = Trainer(cfg, mesh=mesh)
    state, _ = tr.fit(dataset=dataset)
    # fetch per-device copies and compare
    p = jax.tree.leaves(state.params)[0]
    shards = [np.asarray(s.data) for s in p.addressable_shards]
    for s in shards[1:]:
        np.testing.assert_allclose(s, shards[0], rtol=1e-6)
