"""HF GPT-2 checkpoint import (models/hf_interop.py).

Pins logit parity between an ACTUAL ``transformers`` ``GPT2LMHeadModel``
(random-init from config — no download, zero egress) and the converted
``TransformerLM``, plus greedy-decode agreement and config inference.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
import pytest

transformers = pytest.importorskip("transformers")
torch = pytest.importorskip("torch")

from cs744_pytorch_distributed_tutorial_tpu.models import TransformerLM  # noqa: E402
from cs744_pytorch_distributed_tutorial_tpu.models.hf_interop import (  # noqa: E402
    gpt2_model_config,
    lm_params_from_hf_gpt2,
)


@pytest.fixture(scope="module")
def hf_model():
    cfg = transformers.GPT2Config(
        vocab_size=256,
        n_positions=64,
        n_embd=128,
        n_layer=2,
        n_head=2,
        resid_pdrop=0.0,
        embd_pdrop=0.0,
        attn_pdrop=0.0,
    )
    torch.manual_seed(11)
    m = transformers.GPT2LMHeadModel(cfg)
    m.eval()
    return m


def test_config_inference(hf_model):
    cfg = gpt2_model_config(hf_model.state_dict())
    assert cfg["vocab_size"] == 256
    assert cfg["num_layers"] == 2
    assert cfg["d_model"] == 128
    assert cfg["num_heads"] == 2  # head_dim fixed at 64
    assert cfg["d_ff"] == 512
    assert cfg["max_seq_len"] == 64
    assert cfg["tie_embeddings"] and cfg["attn_bias"]
    assert cfg["norm_eps"] == 1e-5


def test_logit_parity_vs_transformers(hf_model):
    sd = hf_model.state_dict()
    model = TransformerLM(**gpt2_model_config(sd), flash_interpret=True)
    params = lm_params_from_hf_gpt2(sd)
    # The converted tree must match what the model expects, exactly.
    ref = model.init(jax.random.key(0), jnp.zeros((1, 8), jnp.int32))[
        "params"
    ]
    assert jax.tree_util.tree_structure(ref) == jax.tree_util.tree_structure(
        params
    ), (jax.tree_util.tree_structure(ref), jax.tree_util.tree_structure(params))
    tokens = np.random.default_rng(0).integers(0, 256, (2, 16))
    logits = np.asarray(
        model.apply({"params": params}, jnp.asarray(tokens, jnp.int32))
    )
    with torch.no_grad():
        hf_logits = hf_model(torch.from_numpy(tokens)).logits.numpy()
    np.testing.assert_allclose(logits, hf_logits, rtol=1e-4, atol=1e-4)


def test_greedy_decode_matches_transformers_generate(hf_model):
    from cs744_pytorch_distributed_tutorial_tpu.infer import make_generator

    sd = hf_model.state_dict()
    model = TransformerLM(**gpt2_model_config(sd), flash_interpret=True)
    params = lm_params_from_hf_gpt2(sd)
    prompt = np.random.default_rng(1).integers(0, 256, (1, 8))
    gen = make_generator(model, max_new_tokens=6, temperature=0.0)
    ours = np.asarray(
        gen(params, jnp.asarray(prompt, jnp.int32), jax.random.key(0))
    )
    with torch.no_grad():
        hf = hf_model.generate(
            torch.from_numpy(prompt),
            max_new_tokens=6,
            do_sample=False,
            pad_token_id=0,
        ).numpy()[:, 8:]
    np.testing.assert_array_equal(ours, hf)


def test_non_gpt2_state_dict_rejected():
    with pytest.raises(ValueError, match="no transformer.h"):
        lm_params_from_hf_gpt2({"transformer.wte.weight": np.zeros((8, 4))})


def test_bf16_checkpoint_converts(hf_model):
    sd = {k: v.to(torch.bfloat16) if v.is_floating_point() else v
          for k, v in hf_model.state_dict().items()}
    params = lm_params_from_hf_gpt2(sd)
    assert params["tok_embed"]["embedding"].dtype == np.float32


def test_custom_head_count_override(hf_model):
    sd = hf_model.state_dict()
    cfg = gpt2_model_config(sd, num_heads=4)
    assert cfg["num_heads"] == 4
    with pytest.raises(ValueError, match="does not divide"):
        gpt2_model_config(sd, num_heads=3)
    with pytest.raises(ValueError, match="no transformer.h"):
        gpt2_model_config({"transformer.wte.weight": np.zeros((8, 4))})


from cs744_pytorch_distributed_tutorial_tpu.models.hf_interop import (  # noqa: E402
    llama_model_config,
    lm_params_from_hf_llama,
)


@pytest.fixture(scope="module")
def hf_llama():
    cfg = transformers.LlamaConfig(
        vocab_size=128,
        hidden_size=64,
        intermediate_size=128,
        num_hidden_layers=2,
        num_attention_heads=4,
        num_key_value_heads=2,
        max_position_embeddings=64,
        rope_theta=10000.0,
        attention_dropout=0.0,
    )
    torch.manual_seed(13)
    m = transformers.LlamaForCausalLM(cfg)
    m.eval()
    return m


def test_llama_config_inference(hf_llama):
    cfg = llama_model_config(
        hf_llama.state_dict(), num_heads=4, max_seq_len=64
    )
    assert cfg["vocab_size"] == 128 and cfg["d_model"] == 64
    assert cfg["num_layers"] == 2 and cfg["num_kv_heads"] == 2
    assert cfg["d_ff"] == 128
    assert cfg["norm"] == "rmsnorm" and cfg["mlp"] == "swiglu"
    assert cfg["use_rope"] and not cfg["tie_embeddings"]
    with pytest.raises(ValueError, match="wrong num_heads"):
        llama_model_config(hf_llama.state_dict(), num_heads=1)
    with pytest.raises(ValueError, match="no model.layers"):
        llama_model_config({"model.embed_tokens.weight": np.zeros((4, 4))},
                           num_heads=2)


def test_llama_logit_parity_vs_transformers(hf_llama):
    sd = hf_llama.state_dict()
    model = TransformerLM(
        **llama_model_config(sd, num_heads=4, max_seq_len=64),
        flash_interpret=True,
    )
    params = lm_params_from_hf_llama(sd)
    ref = model.init(jax.random.key(0), jnp.zeros((1, 8), jnp.int32))[
        "params"
    ]
    assert jax.tree_util.tree_structure(ref) == jax.tree_util.tree_structure(
        params
    )
    tokens = np.random.default_rng(2).integers(0, 128, (2, 16))
    logits = np.asarray(
        model.apply({"params": params}, jnp.asarray(tokens, jnp.int32))
    )
    with torch.no_grad():
        hf_logits = hf_llama(torch.from_numpy(tokens)).logits.numpy()
    np.testing.assert_allclose(logits, hf_logits, rtol=2e-4, atol=2e-4)


def test_llama_tied_embeddings_checkpoint(hf_llama):
    # safetensors drops tensors shared with embed_tokens: simulate a
    # tied checkpoint by removing lm_head.weight.
    sd = {k: v for k, v in hf_llama.state_dict().items()
          if k != "lm_head.weight"}
    cfg = llama_model_config(sd, num_heads=4, max_seq_len=64)
    assert cfg["tie_embeddings"] is True
    params = lm_params_from_hf_llama(sd)
    assert "lm_head" not in params
    model = TransformerLM(**cfg, flash_interpret=True)
    ref = model.init(jax.random.key(0), jnp.zeros((1, 8), jnp.int32))[
        "params"
    ]
    assert jax.tree_util.tree_structure(ref) == jax.tree_util.tree_structure(
        params
    )


def test_llama_greedy_decode_matches_transformers(hf_llama):
    from cs744_pytorch_distributed_tutorial_tpu.infer import make_generator

    sd = hf_llama.state_dict()
    model = TransformerLM(
        **llama_model_config(sd, num_heads=4, max_seq_len=64),
        flash_interpret=True,
    )
    params = lm_params_from_hf_llama(sd)
    prompt = np.random.default_rng(3).integers(0, 128, (1, 8))
    gen = make_generator(model, max_new_tokens=6, temperature=0.0)
    ours = np.asarray(
        gen(params, jnp.asarray(prompt, jnp.int32), jax.random.key(0))
    )
    with torch.no_grad():
        hf = hf_llama.generate(
            torch.from_numpy(prompt),
            max_new_tokens=6,
            do_sample=False,
            pad_token_id=0,
        ).numpy()[:, 8:]
    np.testing.assert_array_equal(ours, hf)
