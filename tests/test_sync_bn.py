"""SyncBN (TrainConfig.sync_bn): cross-replica batch statistics.

The reference's DP keeps BN statistics local per rank (DDP default;
manual parts never sync buffers — SURVEY §7 hard part b), which this
framework reproduces by default. sync_bn=True is the capability
addition: statistics psum across the data axis, so every replica's
running stats stay identical."""

import jax
import numpy as np
import pytest
from conftest import TINY_DP4_CFG, run_tiny_dp4_steps

from cs744_pytorch_distributed_tutorial_tpu.config import TrainConfig
from cs744_pytorch_distributed_tutorial_tpu.train import Trainer


def _stats_shards(state):
    leaf = jax.tree.leaves(state.batch_stats)[0]  # [num_devices, ...]
    return np.asarray(jax.device_get(leaf))


def test_sync_bn_makes_replica_stats_identical(mesh4):
    """With sync_bn every replica computes the SAME batch statistics, so
    the per-replica running-stats rows converge; local BN's rows differ
    (each replica saw a different shard)."""
    _, _, st_local = run_tiny_dp4_steps("allreduce", mesh4, steps=3)
    local = _stats_shards(st_local)
    assert not np.allclose(local[0], local[1]), "local BN rows should differ"

    _, _, st_sync = run_tiny_dp4_steps(
        "allreduce", mesh4, cfg_overrides={"sync_bn": True}, steps=3
    )
    sync = _stats_shards(st_sync)
    for row in sync[1:]:
        np.testing.assert_allclose(row, sync[0], rtol=1e-6)


@pytest.mark.slow
def test_sync_bn_single_device_matches_local(mesh4):
    """On a 1-sized axis the psum is the identity: sync_bn == local BN
    bit-for-bit (the reference semantics are untouched)."""
    import jax.numpy as jnp

    from cs744_pytorch_distributed_tutorial_tpu.data import synthetic_cifar10
    from cs744_pytorch_distributed_tutorial_tpu.parallel import make_mesh
    from cs744_pytorch_distributed_tutorial_tpu.parallel.mesh import (
        shard_global_batch,
    )

    mesh1 = make_mesh({"data": 1}, devices=jax.devices()[:1])
    ds = synthetic_cifar10(16, 4, seed=0)
    losses = {}
    for sync_bn in (False, True):
        cfg = TrainConfig(model="tiny_cnn", sync="auto", num_devices=1,
                          global_batch_size=16, synthetic_data=True,
                          sync_bn=sync_bn)
        tr = Trainer(cfg, mesh=mesh1)
        state = tr.init()
        x, y = shard_global_batch(mesh1, ds.train_images, ds.train_labels)
        state, m = tr.train_step(state, x, y, jax.random.key(0))
        losses[sync_bn] = float(m["loss"])
    assert losses[True] == losses[False]


def test_sync_bn_rejected_for_bn_free_models(mesh4):
    with pytest.raises(ValueError, match="no BN"):
        Trainer(
            TrainConfig(**{**TINY_DP4_CFG, "model": "vit_tiny"}, sync_bn=True),
            mesh=mesh4,
        )
