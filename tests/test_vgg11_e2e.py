"""VGG-11 — the reference's flagship model — through the real training
loop.

Round 1 exercised vgg11 only in shape/param tests; every e2e run used
tiny_cnn. These tests close that gap: ``Trainer.fit`` runs the actual
reference workload shape (``master/part1/part1.py:65-103`` — VGG-11,
SGD momentum, CrossEntropy, seed discipline) end to end on the CPU
mesh, and the recorded on-chip golden curve
(``benchmarks/vgg11_golden.json``, one epoch at the reference's exact
hyperparameters) is pinned for monotone-decrease shape.
"""

from __future__ import annotations

import json
import os

import pytest
import jax
import numpy as np


@pytest.mark.slow
def test_vgg11_through_trainer_fit(mesh4):
    from cs744_pytorch_distributed_tutorial_tpu.config import TrainConfig
    from cs744_pytorch_distributed_tutorial_tpu.data import synthetic_cifar10
    from cs744_pytorch_distributed_tutorial_tpu.train import Trainer

    cfg = TrainConfig(
        model="vgg11",
        sync="allreduce",
        num_devices=4,
        global_batch_size=8,
        synthetic_data=True,
        synthetic_train_size=16,
        synthetic_test_size=8,
        epochs=1,
        log_every=1,
    )
    tr = Trainer(cfg, mesh=mesh4)
    state, hist = tr.fit(dataset=synthetic_cifar10(16, 8, seed=0))

    assert int(jax.device_get(state.step)) == 2  # 16 / 8 = 2 batches
    losses = [l for _, _, l in hist["train_loss"]]
    assert len(losses) == 2 and all(np.isfinite(losses))
    ev = hist["eval"][-1]
    assert ev["count"] == 8 and 0.0 <= ev["accuracy"] <= 1.0


def test_vgg11_golden_curve_shape():
    """The on-chip golden run (reference hyperparameters: batch 256,
    SGD 0.1/0.9/1e-4, seed 5000, 1 epoch) must show the reference's
    qualitative signal — a decreasing loss curve and >chance accuracy
    (``master/part1/part1.py:60-62`` prints the same two numbers)."""
    path = os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        "benchmarks",
        "vgg11_golden.json",
    )
    rec = json.load(open(path))
    assert rec["batch"] == 256 and rec["seed"] == 5000
    losses = [l for _, _, l in rec["train_loss_every_20"]]
    assert len(losses) == 10
    assert losses[-1] < losses[0] * 0.6  # converging, not wandering
    # strictly better than chance on the 10-class eval
    assert rec["eval"]["accuracy"] > 0.2
