"""Profiler capture wired into Trainer.fit (utils/profiling.py, SURVEY §5.1)."""

import os

import pytest
from conftest import TINY_DP4_CFG

from cs744_pytorch_distributed_tutorial_tpu.config import TrainConfig
from cs744_pytorch_distributed_tutorial_tpu.train import Trainer


@pytest.mark.slow
def test_fit_captures_profile_trace(mesh4, tmp_path):
    """profile_dir + a window inside the run: fit records an XLA trace
    (TensorBoard profile-plugin layout) and training completes normally."""
    profile_dir = str(tmp_path / "trace")
    cfg = TrainConfig(
        **TINY_DP4_CFG,
        sync="allreduce",
        profile_dir=profile_dir,
        profile_start_step=1,
        profile_num_steps=2,
    )
    tr = Trainer(cfg, mesh=mesh4)
    _, history = tr.fit()
    assert history["eval"]
    # the capture produced the plugins/profile/<run>/ tree with event data
    hits = [
        os.path.join(root, f)
        for root, _, files in os.walk(profile_dir)
        for f in files
    ]
    assert hits, f"no profiler output under {profile_dir}"


def test_lm_fit_captures_profile_trace(tmp_path):
    """Same contract on the LM engine (LMConfig.profile_dir)."""
    from cs744_pytorch_distributed_tutorial_tpu.data import synthetic_tokens
    from cs744_pytorch_distributed_tutorial_tpu.parallel import make_mesh
    from cs744_pytorch_distributed_tutorial_tpu.train import LMConfig, LMTrainer

    profile_dir = str(tmp_path / "lm_trace")
    cfg = LMConfig(vocab_size=32, num_layers=1, num_heads=2, d_model=16,
                   d_ff=32, max_seq_len=32, seq_len=16, global_batch_size=4,
                   attention_impl="ring", data_parallel=2, seq_parallel=2,
                   profile_dir=profile_dir, profile_start_step=1,
                   profile_num_steps=2)
    tr = LMTrainer(cfg, mesh=make_mesh({"data": 2, "seq": 2}))
    _, _, losses = tr.fit(synthetic_tokens(8, 16, 32, seed=0), steps=4)
    assert len(losses) == 4
    hits = [
        os.path.join(root, f)
        for root, _, files in os.walk(profile_dir)
        for f in files
    ]
    assert hits, f"no profiler output under {profile_dir}"


def test_fit_profile_window_past_end_is_noop(mesh4, tmp_path):
    """A window that never opens (start beyond the run) must not trace or
    error."""
    profile_dir = str(tmp_path / "trace2")
    cfg = TrainConfig(
        **TINY_DP4_CFG,
        sync="allreduce",
        profile_dir=profile_dir,
        profile_start_step=10_000,
    )
    tr = Trainer(cfg, mesh=mesh4)
    _, history = tr.fit()
    assert history["eval"]
    assert not os.path.isdir(profile_dir) or not os.listdir(profile_dir)


def test_device_op_breakdown_cpu():
    """The round-2 instrument: per-op device time from a real profiler
    trace (host timers measure tunnel dispatch, not compute). CPU traces
    exercise the same parse path."""
    import jax
    import jax.numpy as jnp

    from cs744_pytorch_distributed_tutorial_tpu.utils.profiling import (
        device_op_breakdown,
    )

    @jax.jit
    def f(a):
        return (a @ a).sum() + jnp.tanh(a).sum()

    a = jnp.ones((256, 256))
    total, rows = device_op_breakdown(f, a, iters=2, top=10)
    assert total >= 0.0
    assert isinstance(rows, list)
    # on CPU the device lanes may be named differently per backend
    # version; the contract is "no crash, sane types", the TPU value was
    # validated by hand in benchmarks/ablate.py round-2 notes
    for ms, name in rows:
        assert ms >= 0.0 and isinstance(name, str)
