"""Profiler capture wired into Trainer.fit (utils/profiling.py, SURVEY §5.1)."""

import os

import pytest
from conftest import TINY_DP4_CFG

from cs744_pytorch_distributed_tutorial_tpu.config import TrainConfig
from cs744_pytorch_distributed_tutorial_tpu.train import Trainer


@pytest.mark.slow
def test_fit_captures_profile_trace(mesh4, tmp_path):
    """profile_dir + a window inside the run: fit records an XLA trace
    (TensorBoard profile-plugin layout) and training completes normally."""
    profile_dir = str(tmp_path / "trace")
    cfg = TrainConfig(
        **TINY_DP4_CFG,
        sync="allreduce",
        profile_dir=profile_dir,
        profile_start_step=1,
        profile_num_steps=2,
    )
    tr = Trainer(cfg, mesh=mesh4)
    _, history = tr.fit()
    assert history["eval"]
    # the capture produced the plugins/profile/<run>/ tree with event data
    hits = [
        os.path.join(root, f)
        for root, _, files in os.walk(profile_dir)
        for f in files
    ]
    assert hits, f"no profiler output under {profile_dir}"


def test_lm_fit_captures_profile_trace(tmp_path):
    """Same contract on the LM engine (LMConfig.profile_dir)."""
    from cs744_pytorch_distributed_tutorial_tpu.data import synthetic_tokens
    from cs744_pytorch_distributed_tutorial_tpu.parallel import make_mesh
    from cs744_pytorch_distributed_tutorial_tpu.train import LMConfig, LMTrainer

    profile_dir = str(tmp_path / "lm_trace")
    cfg = LMConfig(vocab_size=32, num_layers=1, num_heads=2, d_model=16,
                   d_ff=32, max_seq_len=32, seq_len=16, global_batch_size=4,
                   attention_impl="ring", data_parallel=2, seq_parallel=2,
                   profile_dir=profile_dir, profile_start_step=1,
                   profile_num_steps=2)
    tr = LMTrainer(cfg, mesh=make_mesh({"data": 2, "seq": 2}))
    _, _, losses = tr.fit(synthetic_tokens(8, 16, 32, seed=0), steps=4)
    assert len(losses) == 4
    hits = [
        os.path.join(root, f)
        for root, _, files in os.walk(profile_dir)
        for f in files
    ]
    assert hits, f"no profiler output under {profile_dir}"


def test_fit_profile_window_past_end_is_noop(mesh4, tmp_path):
    """A window that never opens (start beyond the run) must not trace or
    error."""
    profile_dir = str(tmp_path / "trace2")
    cfg = TrainConfig(
        **TINY_DP4_CFG,
        sync="allreduce",
        profile_dir=profile_dir,
        profile_start_step=10_000,
    )
    tr = Trainer(cfg, mesh=mesh4)
    _, history = tr.fit()
    assert history["eval"]
    assert not os.path.isdir(profile_dir) or not os.listdir(profile_dir)


def test_device_op_breakdown_cpu():
    """The round-2 instrument: per-op device time from a real profiler
    trace (host timers measure tunnel dispatch, not compute). CPU traces
    exercise the same parse path."""
    import jax
    import jax.numpy as jnp

    from cs744_pytorch_distributed_tutorial_tpu.utils.profiling import (
        device_op_breakdown,
    )

    @jax.jit
    def f(a):
        return (a @ a).sum() + jnp.tanh(a).sum()

    a = jnp.ones((256, 256))
    total, rows = device_op_breakdown(f, a, iters=2, top=10)
    assert total >= 0.0
    assert isinstance(rows, list)
    # on CPU the device lanes may be named differently per backend
    # version; the contract is "no crash, sane types", the TPU value was
    # validated by hand in benchmarks/ablate.py round-2 notes
    for ms, name in rows:
        assert ms >= 0.0 and isinstance(name, str)


# ---------------------------------------------------------------------------
# graftscope: segmented-step phase attribution (obs/phases.py)
# ---------------------------------------------------------------------------


def _cifar_step_inputs(mesh, cfg):
    """(trainer, state, x, y, key) — the canonical parity-suite recipe."""
    import jax

    from cs744_pytorch_distributed_tutorial_tpu.data import synthetic_cifar10
    from cs744_pytorch_distributed_tutorial_tpu.parallel.mesh import (
        shard_global_batch,
    )

    tr = Trainer(cfg, mesh=mesh)
    state = tr.init()
    ds = synthetic_cifar10(cfg.global_batch_size, 8, seed=0)
    x, y = shard_global_batch(mesh, ds.train_images, ds.train_labels)
    return tr, state, x, y, jax.random.key(cfg.seed)


@pytest.mark.parametrize(
    "sync,compress,overrides",
    [
        ("allreduce", "none", {}),  # bucketed flat allreduce (default)
        ("allreduce", "none", {"sync_bucket_mb": 0}),  # per-leaf
        ("ring", "none", {}),
        ("allreduce", "int8", {}),
        pytest.param(  # fused scatter/apply/gather
            "zero1", "none", {}, marks=pytest.mark.slow
        ),
        ("zero1", "none", {"sync_overlap": "bucket"}),
        pytest.param(
            "zero1", "int8", {"sync_overlap": "bucket+int8"},
            marks=pytest.mark.slow,
        ),
    ],
    ids=[
        "allreduce", "allreduce-perleaf", "ring", "int8",
        "zero1", "zero1-overlap", "zero1-int8",
    ],
)
def test_segmented_fused_parity_cifar(mesh4, sync, compress, overrides):
    """The segmented profiled step (forward/grads | sync | opt as separate
    jitted programs) must produce the SAME loss and params as the fused
    fast path — same tolerance discipline as test_sync_parity."""
    import jax
    import numpy as np

    from cs744_pytorch_distributed_tutorial_tpu.obs.phases import (
        PARITY_ATOL,
        PARITY_LOSS_RTOL,
        PARITY_RTOL,
        build_cifar_segments,
    )

    cfg = TrainConfig(
        **TINY_DP4_CFG, sync=sync, grad_compress=compress,
        compute_dtype="float32", **overrides,
    )
    tr, state, x, y, key = _cifar_step_inputs(mesh4, cfg)
    segs = build_cifar_segments(tr)
    new_f, m_f = segs.fused(state, x, y, key)
    new_s, loss_s = segs.segmented_step(state, x, y, key)
    loss_f = float(m_f["loss"])
    assert abs(float(loss_s) - loss_f) <= PARITY_LOSS_RTOL * max(
        1.0, abs(loss_f)
    )
    for a, b in zip(
        jax.tree.leaves(new_f.params), jax.tree.leaves(new_s.params)
    ):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=PARITY_RTOL, atol=PARITY_ATOL
        )


@pytest.mark.parametrize("compress", ["none", "int8"])
def test_segmented_fused_parity_lm(compress):
    """Same contract on the LM engine (pure-DP configs)."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from cs744_pytorch_distributed_tutorial_tpu.obs.phases import (
        PARITY_ATOL,
        PARITY_LOSS_RTOL,
        PARITY_RTOL,
        build_lm_segments,
    )
    from cs744_pytorch_distributed_tutorial_tpu.train import LMConfig, LMTrainer

    cfg = LMConfig(
        vocab_size=64, num_layers=2, num_heads=2, d_model=32, d_ff=64,
        max_seq_len=16, seq_len=16, global_batch_size=8, data_parallel=4,
        seq_parallel=1, grad_compress=compress,
    )
    tr = LMTrainer(cfg)
    params, opt_state = tr.init()
    import numpy as _np

    toks = _np.random.RandomState(0).randint(0, 64, size=(8, 17))
    x, y = tr.shard_batch(toks)
    segs = build_lm_segments(tr)
    step = jnp.int32(0)
    new_p, _new_o, m_f = segs.fused(params, opt_state, x, y, step)
    (seg_p, _seg_o), loss_s = segs.segmented_step(params, opt_state, x, y, step)
    loss_f = float(m_f["loss"])
    assert abs(float(loss_s) - loss_f) <= PARITY_LOSS_RTOL * max(
        1.0, abs(loss_f)
    )
    for a, b in zip(jax.tree.leaves(new_p), jax.tree.leaves(seg_p)):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=PARITY_RTOL, atol=PARITY_ATOL
        )


def test_cifar_segments_reject_fsdp(mesh4):
    """fsdp's gradient reduction is the AD transpose of its parameter
    all_gather — there is no separable sync phase, so segmentation must
    fail loudly, not silently mis-attribute. (zero1 IS segmentable:
    see the zero1 cases in the parity sweep above.)"""
    from cs744_pytorch_distributed_tutorial_tpu.obs.phases import (
        build_cifar_segments,
    )

    cfg = TrainConfig(**TINY_DP4_CFG, sync="fsdp")
    tr = Trainer(cfg, mesh=mesh4)
    with pytest.raises(ValueError, match="fsdp"):
        build_cifar_segments(tr)


def test_cifar_segments_reject_unbucketed_zero1(mesh4):
    """zero1 segmentation carves the BUCKETED schedule; the per-leaf
    fallback (sync_bucket_mb=0) has no bucket lanes to time."""
    from cs744_pytorch_distributed_tutorial_tpu.obs.phases import (
        build_cifar_segments,
    )

    cfg = TrainConfig(**TINY_DP4_CFG, sync="zero1", sync_bucket_mb=0)
    tr = Trainer(cfg, mesh=mesh4)
    with pytest.raises(ValueError, match="bucket"):
        build_cifar_segments(tr)


def test_profile_phases_end_to_end(mesh4):
    """profile_phases: parity gate + the four-phase report with
    sink-ready records and a renderable table."""
    from cs744_pytorch_distributed_tutorial_tpu.obs.phases import (
        PHASE_NAMES,
        phase_records_from_stream,
        profile_phases,
        render_phase_table,
    )

    cfg = TrainConfig(
        **TINY_DP4_CFG, sync="allreduce", compute_dtype="float32"
    )
    tr, state, x, y, key = _cifar_step_inputs(mesh4, cfg)
    report = profile_phases(tr, state, x, y, key, iters=1)
    assert report.parity_ok
    assert tuple(p.name for p in report.phases) == PHASE_NAMES
    assert report.sync_exposed_ms >= 0.0
    assert report.phase("grad_sync").comm_bytes > 0
    assert report.phase("grad_sync").roofline == "comms"
    records = report.records(run="test")
    assert len(phase_records_from_stream(records)) == len(PHASE_NAMES) + 1
    table = render_phase_table(records)
    assert "grad_sync" in table and "sync_exposed_ms" in table


# ---------------------------------------------------------------------------
# graftscope: straggler monitor + flight recorder (obs/flight.py)
# ---------------------------------------------------------------------------


def test_straggler_monitor_flags_seeded_outlier():
    from cs744_pytorch_distributed_tutorial_tpu.obs.flight import (
        StragglerMonitor,
    )

    mon = StragglerMonitor(min_samples=16, mad_k=5.0)
    outliers = []
    for step in range(64):
        wall = 0.102 if step % 2 else 0.098  # jittery but tight
        if step == 50:
            wall = 1.5  # the seeded straggler
        out = mon.record(step, wall)
        if out is not None:
            outliers.append(out)
    assert [o["step"] for o in outliers] == [50]
    assert outliers[0]["wall_s"] == 1.5
    assert outliers[0]["excess_sigma"] > 0
    stats = mon.stats()
    assert stats["outlier_count"] == 1
    assert stats["max_s"] == 1.5
    assert mon.tail(4)[-1]["step"] == 63


def test_straggler_monitor_quiet_on_uniform_and_warmup():
    """No outliers on uniform timing, and never before min_samples — the
    first post-compile steps must not page anyone."""
    from cs744_pytorch_distributed_tutorial_tpu.obs.flight import (
        StragglerMonitor,
    )

    mon = StragglerMonitor(min_samples=16)
    assert mon.record(0, 30.0) is None  # huge compile step: under warmup
    for step in range(1, 64):
        assert mon.record(step, 0.1) is None


def test_flight_recorder_dumps_on_watchdog():
    """StepWatchdog(flight_recorder=...) fires -> structured flight_dump
    event records land on the sink, tail first."""
    import time

    from cs744_pytorch_distributed_tutorial_tpu.obs.flight import (
        FlightRecorder,
        StragglerMonitor,
    )
    from cs744_pytorch_distributed_tutorial_tpu.utils.failure import (
        StepWatchdog,
    )

    events = []

    def emit(event, **fields):
        events.append({"event": event, **fields})

    mon = StragglerMonitor(min_samples=2)
    for step in range(8):
        mon.record(step, 0.1)
    fr = FlightRecorder(straggler=mon, emit=emit)
    wd = StepWatchdog(timeout_s=0.05, dump_stacks=False, flight_recorder=fr)
    try:
        wd.arm()
        deadline = time.monotonic() + 5.0
        while wd.fired == 0 and time.monotonic() < deadline:
            time.sleep(0.01)
        wd.disarm()
    finally:
        wd.close()
    assert wd.fired >= 1
    assert fr.dumps >= 1
    dump = [e for e in events if e["event"] == "flight_dump"]
    assert dump and dump[0]["reason"] == "watchdog"
    assert dump[0]["straggler_steps_recorded"] == 8
    steps = [e for e in events if e["event"] == "flight_step"]
    assert steps and steps[-1]["step"] == 7


def test_flight_recorder_excepthook_chains():
    """install() wraps sys.excepthook: a dump happens AND the previous
    hook still runs; uninstall() restores it."""
    import sys

    from cs744_pytorch_distributed_tutorial_tpu.obs.flight import (
        FlightRecorder,
    )

    events = []
    seen = []
    prev_hook = sys.excepthook
    sys.excepthook = lambda *a: seen.append(a)
    try:
        fr = FlightRecorder(emit=lambda event, **f: events.append(event))
        fr.install(sigterm=False, excepthook=True)
        try:
            raise RuntimeError("boom")
        except RuntimeError:
            sys.excepthook(*sys.exc_info())
        assert "flight_dump" in events
        assert len(seen) == 1  # the chained original hook ran
        fr.uninstall()
        assert sys.excepthook is not fr and len(events) >= 1
    finally:
        sys.excepthook = prev_hook


def test_flight_recorder_requires_a_sink():
    from cs744_pytorch_distributed_tutorial_tpu.obs.flight import (
        FlightRecorder,
    )

    with pytest.raises(ValueError):
        FlightRecorder()
