"""Gradient accumulation on the CIFAR engine (TrainConfig.accum_steps)."""

import jax
import numpy as np
import pytest
from conftest import TINY_DP4_CFG, run_tiny_dp4_steps

from cs744_pytorch_distributed_tutorial_tpu.config import TrainConfig
from cs744_pytorch_distributed_tutorial_tpu.data import synthetic_cifar10
from cs744_pytorch_distributed_tutorial_tpu.parallel.mesh import shard_global_batch
from cs744_pytorch_distributed_tutorial_tpu.train import Trainer


def _vit_losses(mesh4, accum, steps=3):
    cfg = TrainConfig(
        model="vit_tiny",
        sync="auto",
        num_devices=4,
        global_batch_size=16,
        synthetic_data=True,
        accum_steps=accum,
        learning_rate=0.01,
    )
    tr = Trainer(cfg, mesh=mesh4)
    state = tr.init()
    ds = synthetic_cifar10(16, 8, seed=0)
    x, y = shard_global_batch(mesh4, ds.train_images, ds.train_labels)
    key = jax.random.key(cfg.seed)
    losses = []
    for _ in range(steps):
        state, m = tr.train_step(state, x, y, key)
        losses.append(float(m["loss"]))
    return losses


@pytest.mark.slow
def test_accum_matches_unaccumulated_without_bn(mesh4):
    """ViT has no BatchNorm, so accumulation is numerically invisible (up
    to summation order): the loss trajectory must match accum=1."""
    np.testing.assert_allclose(
        _vit_losses(mesh4, 1), _vit_losses(mesh4, 2), rtol=2e-5
    )


@pytest.mark.parametrize("sync", ["allreduce", "zero1", "fsdp"])
@pytest.mark.slow
def test_accum_trains_under_each_strategy_family(mesh4, sync):
    """Accumulation composes with the manual, ZeRO-1, and ZeRO-3 paths
    (BN present: trajectories differ from accum=1, but training is sound)."""
    losses, _, _ = run_tiny_dp4_steps(
        sync, mesh4, cfg_overrides={"accum_steps": 2}
    )
    assert np.isfinite(losses).all()


def test_accum_validation(mesh4):
    with pytest.raises(ValueError, match="accum_steps"):
        Trainer(TrainConfig(**TINY_DP4_CFG, accum_steps=3), mesh=mesh4)
