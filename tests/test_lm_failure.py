"""Failure detection on the LM engine (LMConfig.halt_on_nonfinite /
step_timeout_s) — same contract as the CIFAR engine's suite."""

import jax.numpy as jnp
import numpy as np
import pytest

from cs744_pytorch_distributed_tutorial_tpu.data import synthetic_tokens
from cs744_pytorch_distributed_tutorial_tpu.parallel import make_mesh
from cs744_pytorch_distributed_tutorial_tpu.train import LMConfig, LMTrainer
from cs744_pytorch_distributed_tutorial_tpu.utils.failure import (
    NonFiniteLossError,
)

TINY = dict(vocab_size=32, num_layers=1, num_heads=2, d_model=16, d_ff=32,
            max_seq_len=64, seq_len=16, global_batch_size=4,
            attention_impl="ring", data_parallel=2, seq_parallel=2)


def _nan_injecting(trainer, fail_at_call: int):
    real = trainer.train_step
    calls = {"n": 0}

    def wrapped(params, opt_state, x, y, step=0):
        p, o, m = real(params, opt_state, x, y, step)
        calls["n"] += 1
        if calls["n"] == fail_at_call:
            m = dict(m, loss=jnp.float32(float("nan")))
        return p, o, m

    trainer.train_step = wrapped
    return calls


def test_lm_nan_loss_halts():
    mesh = make_mesh({"data": 2, "seq": 2})
    tr = LMTrainer(LMConfig(**TINY), mesh=mesh)
    _nan_injecting(tr, fail_at_call=2)
    tokens = synthetic_tokens(8, 16, 32, seed=0)
    with pytest.raises(NonFiniteLossError) as ei:
        tr.fit(tokens, steps=5)
    assert ei.value.step == 1  # 0-indexed second step


def test_lm_nan_ignored_when_disabled():
    mesh = make_mesh({"data": 2, "seq": 2})
    tr = LMTrainer(LMConfig(**TINY, halt_on_nonfinite=False), mesh=mesh)
    _nan_injecting(tr, fail_at_call=2)
    tokens = synthetic_tokens(8, 16, 32, seed=0)
    _, _, losses = tr.fit(tokens, steps=4)
    assert len(losses) == 4
    assert np.isnan(losses[1])


def test_lm_run_with_recovery_restarts_from_checkpoint(tmp_path):
    """A transient NaN triggers one restart; fit resumes from the
    checkpoint and completes all steps."""
    from cs744_pytorch_distributed_tutorial_tpu.utils.failure import (
        run_with_recovery,
    )

    mesh = make_mesh({"data": 2, "seq": 2})
    tr = LMTrainer(
        LMConfig(**TINY, checkpoint_dir=str(tmp_path), checkpoint_every=1),
        mesh=mesh,
    )
    real = tr.train_step
    calls = {"n": 0}

    def flaky(params, opt_state, x, y, step=0):
        p, o, m = real(params, opt_state, x, y, step)
        calls["n"] += 1
        if calls["n"] == 3:  # transient: fails once, clean on replay
            m = dict(m, loss=jnp.float32(float("inf")))
        return p, o, m

    tr.train_step = flaky
    tokens = synthetic_tokens(8, 16, 32, seed=0)
    params, opt, losses, restarts = run_with_recovery(
        tr, fit_args=(tokens, 4), max_restarts=2
    )
    assert restarts == 1
    assert np.isfinite(losses).all()


def test_lm_watchdog_runs_clean():
    """A generous timeout never fires on a healthy run (and the thread
    shuts down cleanly)."""
    mesh = make_mesh({"data": 2, "seq": 2})
    tr = LMTrainer(LMConfig(**TINY, step_timeout_s=120.0), mesh=mesh)
    tokens = synthetic_tokens(8, 16, 32, seed=0)
    _, _, losses = tr.fit(tokens, steps=3)
    assert len(losses) == 3 and np.isfinite(losses).all()
