"""Llama-family block options: RMSNorm + SwiGLU (models/transformer.py).

No counterpart in the reference (its only model is conv VGG-11,
``master/part1/model.py:30-46``) — these are model-zoo completeness
options on the transformer family: norm="rmsnorm" swaps every
LayerNorm for RMSNorm (final norm included), mlp="swiglu" swaps the
gelu MLP for silu(gate(x)) * up(x) with a third column-parallel
projection ``mlp_gate``. Verified: formula parity against hand-written
math, param-tree shape, tensor-parallel parity (the sharding rules
extend to mlp_gate), decode parity, and the int8 path covering the gate.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from cs744_pytorch_distributed_tutorial_tpu.models import TransformerLM
from cs744_pytorch_distributed_tutorial_tpu.models.transformer import Block


def _lm(**kw) -> TransformerLM:
    base = dict(
        vocab_size=128,
        num_layers=2,
        num_heads=4,
        d_model=64,
        d_ff=128,
        max_seq_len=32,
        dtype=jnp.float32,
        attention_impl="dense",
        use_rope=True,
        flash_interpret=True,
    )
    base.update(kw)
    return TransformerLM(**base)


def test_swiglu_formula_matches_hand_math():
    block = Block(
        num_heads=2, d_ff=32, dtype=jnp.float32, impl="dense",
        mlp="swiglu", norm="rmsnorm", flash_interpret=True,
    )
    x = jax.random.normal(jax.random.key(0), (2, 8, 16), jnp.float32)
    params = block.init(jax.random.key(1), x, True)["params"]

    def rms(v, scale):
        var = np.mean(np.asarray(v) ** 2, axis=-1, keepdims=True)
        return np.asarray(v) / np.sqrt(var + 1e-6) * np.asarray(scale)

    # Zero the attention kernels so attn_out == 0 and the block output
    # isolates the MLP sublayer against hand-written swiglu math.
    zeroed = jax.tree_util.tree_map(lambda a: a, params)
    for mod in ("q", "k", "v", "attn_out"):
        zeroed["attn"][mod]["kernel"] = jnp.zeros_like(
            zeroed["attn"][mod]["kernel"]
        )
    out = np.asarray(block.apply({"params": zeroed}, x, True))
    h2 = rms(x, zeroed["ln2"]["scale"])  # attn_out == 0 -> residual is x
    up = h2 @ np.asarray(zeroed["mlp_in"]["kernel"]) + np.asarray(
        zeroed["mlp_in"]["bias"]
    )
    gate = h2 @ np.asarray(zeroed["mlp_gate"]["kernel"])
    silu = gate / (1.0 + np.exp(-gate)) * up
    mlp = silu @ np.asarray(zeroed["mlp_out"]["kernel"]) + np.asarray(
        zeroed["mlp_out_bias"]
    )
    np.testing.assert_allclose(out, np.asarray(x) + mlp, rtol=2e-5, atol=2e-5)


def test_param_tree_has_gate_and_no_ln_bias():
    model = _lm(norm="rmsnorm", mlp="swiglu")
    params = model.init(jax.random.key(0), jnp.zeros((1, 8), jnp.int32))[
        "params"
    ]
    blk = params["block_0"]
    assert "mlp_gate" in blk and "kernel" in blk["mlp_gate"]
    assert blk["mlp_gate"]["kernel"].shape == (64, 128)
    # RMSNorm has scale only — no bias param.
    assert set(blk["ln1"].keys()) == {"scale"}
    assert set(params["ln_f"].keys()) == {"scale"}
    # gelu model has no gate.
    p2 = _lm().init(jax.random.key(0), jnp.zeros((1, 8), jnp.int32))["params"]
    assert "mlp_gate" not in p2["block_0"]


def test_unknown_options_rejected():
    with pytest.raises(ValueError, match="unknown norm"):
        _lm(norm="batchnorm").init(
            jax.random.key(0), jnp.zeros((1, 8), jnp.int32)
        )
    with pytest.raises(ValueError, match="unknown mlp"):
        _lm(mlp="geglu").init(
            jax.random.key(0), jnp.zeros((1, 8), jnp.int32)
        )


def test_tensor_parallel_swiglu_parity(devices):
    """mlp_gate is column-parallel: the TP model on a 2-device tensor
    axis must reproduce the single-device logits."""
    from jax.sharding import Mesh, PartitionSpec as P

    from cs744_pytorch_distributed_tutorial_tpu.models.transformer import (
        lm_param_specs,
    )

    full = _lm(norm="rmsnorm", mlp="swiglu")
    tokens = jax.random.randint(jax.random.key(2), (2, 8), 0, 128)
    params = full.init(jax.random.key(0), tokens)["params"]
    want = np.asarray(full.apply({"params": params}, tokens))

    mesh = Mesh(np.array(devices[:2]), ("tensor",))
    tp_model = full.clone(tensor_axis="tensor", tensor_axis_size=2)
    specs = lm_param_specs(params, "tensor")
    assert specs["block_0"]["mlp_gate"]["kernel"] == P(None, "tensor")

    def fwd(p, t):
        return tp_model.apply({"params": p}, t)

    got = jax.jit(
        jax.shard_map(
            fwd,
            mesh=mesh,
            in_specs=(specs, P()),
            out_specs=P(),
            check_vma=False,
        )
    )(params, tokens)
    np.testing.assert_allclose(np.asarray(got), want, rtol=2e-4, atol=2e-4)


def test_swiglu_decode_matches_teacher_forcing():
    from cs744_pytorch_distributed_tutorial_tpu.infer import make_generator

    model = _lm(norm="rmsnorm", mlp="swiglu")
    prompt = jax.random.randint(jax.random.key(3), (2, 8), 0, 128)
    params = model.init(jax.random.key(0), prompt)["params"]
    gen = make_generator(model, max_new_tokens=6, temperature=0.0)
    out = np.asarray(gen(params, prompt, jax.random.key(4)))
    # Teacher-forced re-check: feeding prompt+generated through the full
    # forward must greedily re-predict each generated token.
    seq = np.concatenate([np.asarray(prompt), out], axis=1)
    logits = np.asarray(model.apply({"params": params}, jnp.asarray(seq)))
    for i in range(out.shape[1]):
        np.testing.assert_array_equal(
            out[:, i], logits[:, 8 + i - 1].argmax(-1)
        )


def test_int8_all_scope_covers_gate():
    from cs744_pytorch_distributed_tutorial_tpu.ops.quant import (
        QUANT_MODULES,
        quantize_lm_params,
    )

    model = _lm(norm="rmsnorm", mlp="swiglu")
    params = model.init(jax.random.key(0), jnp.zeros((1, 8), jnp.int32))[
        "params"
    ]
    mods = tuple(sorted(QUANT_MODULES))
    qparams = quantize_lm_params(params, mods)
    assert qparams["block_0"]["mlp_gate"]["qkernel"].dtype == jnp.int8
    qmodel = model.clone(quant_dense=True, quant_modules=mods)
    ref = qmodel.init(jax.random.key(0), jnp.zeros((1, 8), jnp.int32))[
        "params"
    ]
    assert jax.tree_util.tree_structure(ref) == jax.tree_util.tree_structure(
        qparams
    )
    tokens = jax.random.randint(jax.random.key(5), (2, 8), 0, 128)
    logits = model.apply({"params": params}, tokens)
    qlogits = qmodel.apply({"params": qparams}, tokens)
    denom = np.maximum(np.abs(np.asarray(logits)), 1.0)
    assert (np.abs(np.asarray(qlogits) - np.asarray(logits)) / denom).max() < 0.1


def test_swiglu_moe_combination_rejected():
    with pytest.raises(ValueError, match="does not compose with MoE"):
        _lm(mlp="swiglu", num_experts=4).init(
            jax.random.key(0), jnp.zeros((1, 8), jnp.int32)
        )
