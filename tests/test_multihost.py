"""graftelastic: the multi-process elastic runtime
(``parallel/multihost.py`` + ``launch.py``).

Fast units pin each layer in isolation — the rendezvous store's
membership records, deterministic coordinator re-election, the
collective watchdog's bounded conversion of "blocked on a dead peer"
into ``ProcessLossError``, identity-label resolution (and the log
prefix built from it), and ``process_kill`` chaos targeting.

The slow tests are the acceptance e2es: a 4-process ``launch_local``
run survives SIGKILL of (i) a non-coordinator rank and (ii) the
coordinator itself — deterministic re-election, generation g+1 on the
shrunk world, disk resume, and a full loss trajectory matching an
uninterrupted shrunk-world oracle at rtol 1e-6. The multihost-smoke CI
job runs this file without the tier-1 ``-m 'not slow'`` filter.
"""

import logging
import os
import re
import signal
import subprocess
import sys
import time

import pytest

from cs744_pytorch_distributed_tutorial_tpu.parallel.multihost import (
    EXIT_PROCESS_LOSS,
    CollectiveWatchdog,
    RendezvousStore,
    WorkerContext,
    env_context,
    plan_next_generation,
    reset_runtime_labels,
    runtime_labels,
    set_runtime_labels,
)
from cs744_pytorch_distributed_tutorial_tpu.utils.failure import (
    ProcessLossError,
)


@pytest.fixture
def clean_labels():
    reset_runtime_labels()
    yield
    reset_runtime_labels()


# ------------------------------------------------------------- election
def test_reelection_plan_non_coordinator_death():
    world = {"generation": 0, "ranks": [0, 1, 2, 3], "coordinator_rank": 0}
    plan = plan_next_generation(world, dead=[2])
    assert plan == {
        "generation": 1,
        "ranks": [0, 1, 3],  # global ranks kept; process ids = position
        "coordinator_rank": 0,
        "parent_generation": 0,
        "dead": [2],
    }


def test_reelection_plan_coordinator_death_elects_lowest_survivor():
    world = {"generation": 0, "ranks": [0, 1, 2, 3], "coordinator_rank": 0}
    plan = plan_next_generation(world, dead=[0])
    assert plan["coordinator_rank"] == 1
    assert plan["ranks"] == [1, 2, 3]
    # Deterministic: every caller computes the identical plan.
    assert plan == plan_next_generation(world, dead=[0])
    # Cascading losses across generations keep the rule stable.
    again = plan_next_generation(plan, dead=[1])
    assert again["generation"] == 2
    assert again["coordinator_rank"] == 2
    assert again["ranks"] == [2, 3]


def test_reelection_plan_total_loss_has_no_coordinator():
    world = {"generation": 3, "ranks": [5, 7], "coordinator_rank": 5}
    plan = plan_next_generation(world, dead=[5, 7])
    assert plan["ranks"] == [] and plan["coordinator_rank"] is None


# ---------------------------------------------------------------- store
def test_rendezvous_store_world_heartbeat_death_roundtrip(tmp_path):
    store = RendezvousStore(str(tmp_path / "store"))
    assert store.latest_generation() is None
    spec = {"generation": 0, "ranks": [0, 1, 2], "coordinator_rank": 0}
    store.write_world(spec)
    store.write_world({"generation": 1, "ranks": [1, 2],
                       "coordinator_rank": 1})
    assert store.read_world(0) == spec
    assert store.latest_generation() == 1
    assert store.read_world(9) is None

    # Heartbeats: None before the first beat (startup grace is the
    # supervisor's concern), a small age right after one.
    assert store.heartbeat_age(0, 1) is None
    store.heartbeat(0, 1, step=4)
    age = store.heartbeat_age(0, 1)
    assert age is not None and 0 <= age < 5

    # Death notes merge across writes and are per-generation.
    store.mark_dead(0, [2])
    store.mark_dead(0, [0, 2])
    assert store.dead(0) == {0, 2}
    assert store.dead(1) == set()


def test_store_events_stamped_with_runtime_labels(tmp_path, clean_labels):
    store = RendezvousStore(str(tmp_path / "store"))
    set_runtime_labels(
        process_id=1, process_count=3, generation=2, global_rank=3
    )
    store.append_event("reelection", survivors=[1, 3])
    [ev] = store.events()
    assert ev["kind"] == "event" and ev["event"] == "reelection"
    assert ev["survivors"] == [1, 3]
    assert (ev["process_id"], ev["generation"], ev["global_rank"]) == (1, 2, 3)


# -------------------------------------------------------------- context
def test_worker_context_env_roundtrip():
    ctx = WorkerContext(
        store_dir="/tmp/s", generation=2, process_id=1, num_processes=3,
        coordinator="127.0.0.1:5000", global_rank=3,
    )
    assert env_context(ctx.env()) == ctx
    assert env_context({}) is None  # no contract -> single-process run


def test_runtime_labels_resolution_order(clean_labels, monkeypatch):
    # Default: single-process coordinates.
    assert runtime_labels() == {
        "process_id": 0, "process_count": 1, "generation": 0,
        "global_rank": 0,
    }
    # Supervisor environment.
    monkeypatch.setenv("GRAFT_ELASTIC_RANK", "1")
    monkeypatch.setenv("GRAFT_ELASTIC_WORLD", "3")
    monkeypatch.setenv("GRAFT_ELASTIC_GENERATION", "1")
    monkeypatch.setenv("GRAFT_ELASTIC_GLOBAL_RANK", "2")
    assert runtime_labels() == {
        "process_id": 1, "process_count": 3, "generation": 1,
        "global_rank": 2,
    }
    # Explicit labels (set at each elastic re-init) outrank the env.
    set_runtime_labels(
        process_id=0, process_count=2, generation=4, global_rank=3
    )
    assert runtime_labels() == {
        "process_id": 0, "process_count": 2, "generation": 4,
        "global_rank": 3,
    }


def test_log_prefix_re_resolves_per_record(clean_labels):
    """The satellite fix: ``[proc i/n]`` is computed per-record from
    ``runtime_labels`` — a survivor re-labelled at generation g+1 logs
    its NEW coordinates (with a gN suffix), not its birth ones."""
    from cs744_pytorch_distributed_tutorial_tpu.utils.logging import (
        _RankPrefixFilter,
    )

    filt = _RankPrefixFilter()

    def prefix():
        rec = logging.LogRecord(
            "graft", logging.INFO, __file__, 1, "msg", (), None
        )
        assert filt.filter(rec)
        return rec.rank_prefix

    set_runtime_labels(
        process_id=2, process_count=4, generation=0, global_rank=2
    )
    assert prefix() == "[proc 2/4] "  # generation 0: no suffix
    set_runtime_labels(
        process_id=1, process_count=3, generation=1, global_rank=2
    )
    assert prefix() == "[proc 1/3 g1] "  # re-resolved after re-init
    reset_runtime_labels()
    assert prefix() == ""  # single-process: stay quiet


# ------------------------------------------------------------- watchdog
def _ctx(tmp_path, *, generation=0, global_rank=0, world=2):
    return WorkerContext(
        store_dir=str(tmp_path / "store"), generation=generation,
        process_id=global_rank, num_processes=world,
        coordinator="127.0.0.1:1", global_rank=global_rank,
    )


def test_watchdog_converts_blocked_section_to_loss_within_deadline(tmp_path):
    store = RendezvousStore(str(tmp_path / "store"))
    store.write_world({"generation": 0, "ranks": [0, 1],
                       "coordinator_rank": 0})
    store.mark_dead(0, [1])
    losses = []
    wd = CollectiveWatchdog(
        store, _ctx(tmp_path), deadline_s=0.4, on_loss=losses.append,
        poll_s=0.05,
    )
    try:
        t0 = time.monotonic()
        with wd.watch():
            while not losses and time.monotonic() - t0 < 5:
                time.sleep(0.05)  # stand-in for "blocked in a psum"
        elapsed = time.monotonic() - t0
        # The acceptance bound: fired, and BOUNDED — after the deadline,
        # well before "indefinitely".
        assert wd.fired == 1
        assert 0.4 <= elapsed < 3.0, elapsed
        [err] = losses
        assert isinstance(err, ProcessLossError)
        assert err.generation == 0 and err.dead == (1,)
        events = [
            e for e in store.events() if e["event"] == "process_loss"
        ]
        assert len(events) == 1 and events[0]["dead"] == [1]
        assert events[0]["elapsed_s"] >= 0.4
    finally:
        wd.close()


def test_watchdog_without_dead_peer_rearms_instead_of_firing(tmp_path):
    store = RendezvousStore(str(tmp_path / "store"))
    store.write_world({"generation": 0, "ranks": [0, 1],
                       "coordinator_rank": 0})
    losses = []
    wd = CollectiveWatchdog(
        store, _ctx(tmp_path), deadline_s=0.2, on_loss=losses.append,
        poll_s=0.05, stale_after_s=60.0,
    )
    try:
        deadline = time.monotonic() + 0.8
        with wd.watch():
            while time.monotonic() < deadline:
                store.heartbeat(0, 1)  # peer is slow, not dead
                time.sleep(0.05)
        assert wd.fired == 0 and losses == []  # compile != process loss
    finally:
        wd.close()


def test_watchdog_death_evidence_notes_and_stale_heartbeats(tmp_path):
    store = RendezvousStore(str(tmp_path / "store"))
    store.write_world({"generation": 0, "ranks": [0, 1, 2, 3],
                       "coordinator_rank": 0})
    wd = CollectiveWatchdog(
        store, _ctx(tmp_path, world=4), deadline_s=30.0,
        on_loss=lambda e: None, stale_after_s=0.1, poll_s=5.0,
    )
    try:
        # Rank 3 never beat: still importing — NOT evidence of death.
        store.heartbeat(0, 2)
        assert wd.dead_peers() == []
        time.sleep(0.3)  # rank 2's beat goes stale
        store.mark_dead(0, [1])  # supervisor's death note
        assert wd.dead_peers() == [1, 2]
        # check() is the synchronous, catchable path between steps.
        with pytest.raises(ProcessLossError) as exc:
            wd.check()
        assert exc.value.dead == (1, 2)
    finally:
        wd.close()


def test_exit_code_constant_is_distinctive():
    # The supervisor classifies EXIT_PROCESS_LOSS as a survivor exit;
    # it must never collide with the codes it reads as death (-9) or
    # plain success.
    assert EXIT_PROCESS_LOSS not in (0, 1, -9, 128 + signal.SIGKILL)


# ------------------------------------------------------ chaos targeting
class _FakeTrainer:
    def __init__(self):
        self.steps = 0

    def train_step(self, *a, **k):
        self.steps += 1
        return ("state", {"loss": 1.0})


def test_process_kill_fires_only_on_matching_rank(monkeypatch):
    from cs744_pytorch_distributed_tutorial_tpu.utils.chaos import (
        ChaosMonkey,
        FaultSchedule,
    )

    kills = []
    monkeypatch.setattr(os, "kill", lambda pid, sig: kills.append((pid, sig)))

    # A non-target rank steps straight through the scheduled call.
    bystander = _FakeTrainer()
    ChaosMonkey(
        FaultSchedule({2: {"kind": "process_kill", "rank": 0}}), rank=1
    ).install(bystander)
    for _ in range(4):
        bystander.train_step()
    assert bystander.steps == 4 and kills == []

    # The target rank SIGKILLs itself at exactly the scheduled call.
    victim = _FakeTrainer()
    monkey = ChaosMonkey(
        FaultSchedule({2: {"kind": "process_kill", "rank": 0}}), rank=0
    )
    monkey.install(victim)
    victim.train_step()
    victim.train_step()
    assert kills == []
    victim.train_step()  # call index 2
    assert kills == [(os.getpid(), signal.SIGKILL)]
    assert monkey.injected == [(2, "process_kill")]


def test_process_kill_first_call_keeps_absolute_step_keys(monkeypatch):
    """A re-exec'd survivor resuming at step K passes ``first_call=K``:
    schedule keys stay ABSOLUTE step indices, and a re-parsed spec
    whose target died in a previous generation can never re-fire."""
    from cs744_pytorch_distributed_tutorial_tpu.utils.chaos import (
        ChaosMonkey,
        FaultSchedule,
    )

    kills = []
    monkeypatch.setattr(os, "kill", lambda pid, sig: kills.append(sig))

    # Same schedule, re-parsed at generation 1; the dead rank 2 is gone
    # and every survivor skips the spec at its original absolute index.
    survivor = _FakeTrainer()
    ChaosMonkey(
        FaultSchedule({4: {"kind": "process_kill", "rank": 2}}),
        rank=0, first_call=4,
    ).install(survivor)
    survivor.train_step()  # absolute call 4: target is dead, not us
    assert survivor.steps == 1 and kills == []

    # first_call offsets the index for a matching target too.
    victim = _FakeTrainer()
    ChaosMonkey(
        FaultSchedule({4: {"kind": "process_kill", "rank": 0}}),
        rank=0, first_call=4,
    ).install(victim)
    victim.train_step()
    assert kills == [signal.SIGKILL]


def test_process_kill_schedule_requires_target_rank():
    from cs744_pytorch_distributed_tutorial_tpu.utils.chaos import (
        FaultSchedule,
    )

    with pytest.raises(ValueError, match="needs a target"):
        FaultSchedule({1: "process_kill"})
    sched = FaultSchedule.seeded(
        7, 20, rate=1.0, kinds=("process_kill",), kill_rank=3
    )
    assert len(sched) > 0
    assert all(s["rank"] == 3 for s in sched.faults.values())


# ------------------------------------------------- e2e: kill/re-election
_LOSS_RE = re.compile(
    r"\[graftelastic\] gen=(\d+) grank=(\d+) step=(\d+) loss=([0-9.]+)"
)


def _store_root(tmp_path, name):
    """CI artifact hook: multihost-smoke sets GRAFT_ELASTIC_TEST_STORE
    so the per-rank logs + events.jsonl land in an uploaded directory."""
    base = os.environ.get("GRAFT_ELASTIC_TEST_STORE")
    if base:
        return os.path.join(base, name)
    return str(tmp_path / name)


def _run_elastic(store, *, steps, kill):
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = {
        **os.environ,
        "JAX_PLATFORMS": "cpu",
        "XLA_FLAGS": "",  # one CPU device per worker
        "PALLAS_AXON_POOL_IPS": "",
        "PYTHONPATH": repo,
    }
    proc = subprocess.run(
        [
            sys.executable, "-m",
            "cs744_pytorch_distributed_tutorial_tpu.launch",
            "--nprocs", "4", "--store", store,
            "--steps", str(steps), "--kill", kill,
            "--collective-deadline-s", "6",
        ],
        env=env, capture_output=True, text=True, timeout=480,
    )
    assert proc.returncode == 0, (
        f"supervisor failed rc={proc.returncode}\n"
        f"stdout:\n{proc.stdout}\nstderr:\n{proc.stderr}"
    )
    return RendezvousStore(store)


def _logged_trajectory(store, steps):
    """Per-step losses from the per-rank logs: identical across ranks
    within a (generation, step); the newest generation wins a step."""
    by_step: dict[tuple[int, int], dict[int, float]] = {}
    logdir = os.path.join(store.root, "logs")
    for name in sorted(os.listdir(logdir)):
        with open(os.path.join(logdir, name), encoding="utf-8") as f:
            for m in _LOSS_RE.finditer(f.read()):
                gen, grank, step, loss = (
                    int(m[1]), int(m[2]), int(m[3]), float(m[4])
                )
                by_step.setdefault((gen, step), {})[grank] = loss
    for (gen, step), ranks in by_step.items():
        assert len(set(ranks.values())) == 1, (
            f"ranks disagree at gen {gen} step {step}: {ranks}"
        )
    best: dict[int, tuple[int, float]] = {}
    for (gen, step), ranks in by_step.items():
        if step not in best or gen > best[step][0]:
            best[step] = (gen, next(iter(ranks.values())))
    assert sorted(best) == list(range(steps)), sorted(best)
    return [best[s][1] for s in range(steps)]


def _shrunk_world_oracle(steps, world):
    """Uninterrupted single-process run at the SHRUNK world size, same
    recipe as the demo worker (launch.py) — the trajectory the resumed
    generations must match."""
    import jax

    from cs744_pytorch_distributed_tutorial_tpu.config import TrainConfig
    from cs744_pytorch_distributed_tutorial_tpu.data import synthetic_cifar10
    from cs744_pytorch_distributed_tutorial_tpu.parallel import make_mesh
    from cs744_pytorch_distributed_tutorial_tpu.parallel.mesh import (
        shard_global_batch,
    )
    from cs744_pytorch_distributed_tutorial_tpu.train import Trainer

    mesh = make_mesh({"data": world}, devices=jax.devices()[:world])
    cfg = TrainConfig(
        model="tiny_cnn", sync="allreduce", sync_bn=True, augment=False,
        num_devices=world, global_batch_size=12, synthetic_data=True,
        synthetic_train_size=12, synthetic_test_size=8, seed=0,
        learning_rate=0.002,
    )
    tr = Trainer(cfg, mesh=mesh)
    state = tr.init()
    ds = synthetic_cifar10(12, 8, seed=0)
    x, y = shard_global_batch(mesh, ds.train_images, ds.train_labels)
    key = jax.random.key(cfg.seed)
    out = []
    for _ in range(steps):
        state, m = tr.train_step(state, x, y, key)
        out.append(float(jax.device_get(m["loss"])))
    return out


def _check_elastic_run(store, *, steps, killed, kill_step, survivors,
                       coordinator):
    evs = store.events()

    deaths = [e for e in evs if e["event"] == "worker_death"]
    assert {e["dead_rank"] for e in deaths} == {killed}
    assert all(e["reason"] == "sigkill" for e in deaths)

    injects = [e for e in evs if e["event"] == "chaos_inject"]
    assert len(injects) == 1  # the re-parsed gen-1 spec never re-fires
    assert injects[0]["global_rank"] == killed
    assert injects[0]["call"] == kill_step

    [reelection] = [e for e in evs if e["event"] == "reelection"]
    assert reelection["survivors"] == survivors
    assert reelection["coordinator_rank"] == coordinator
    assert reelection["dead"] == [killed]
    assert reelection["generation"] == 1

    gens = [e for e in evs if e["event"] == "generation_start"]
    assert [(e["generation"], e["world_size"]) for e in gens] == [
        (0, 4), (1, 3)
    ]
    assert gens[1]["ranks"] == survivors

    resumes = [e for e in evs if e["event"] == "recovery_resume"]
    assert len(resumes) == len(survivors)  # every survivor restored
    assert all(
        (e["step"], e["tier"], e["generation"]) == (kill_step, "disk", 1)
        for e in resumes
    )
    assert [e for e in evs if e["event"] == "run_complete"]

    got = _logged_trajectory(store, steps)
    # Steps before the kill ran at world 4, after at world 3; the demo
    # recipe is world-size invariant, so the WHOLE stitched trajectory
    # must match an uninterrupted world-3 run.
    import numpy as np

    np.testing.assert_allclose(
        got, _shrunk_world_oracle(steps, world=3), rtol=1e-6
    )


@pytest.mark.slow  # multihost-smoke CI runs these without the tier-1 filter
def test_elastic_launch_survives_non_coordinator_kill(tmp_path):
    """4-process launch_local, SIGKILL of rank 2 at step 4: the
    survivors re-exec into generation 1 as world [0, 1, 3] (coordinator
    unchanged), resume from the step-4 disk checkpoint, and the stitched
    loss trajectory matches the uninterrupted shrunk-world oracle."""
    store = _run_elastic(
        _store_root(tmp_path, "kill_noncoord"), steps=7, kill="4:2"
    )
    _check_elastic_run(
        store, steps=7, killed=2, kill_step=4, survivors=[0, 1, 3],
        coordinator=0,
    )


@pytest.mark.slow  # multihost-smoke CI runs these without the tier-1 filter
def test_elastic_launch_survives_coordinator_kill_and_reelects(tmp_path):
    """The hard case: SIGKILL of rank 0 — the coordinator — at step 3.
    The lowest surviving global rank (1) is deterministically re-elected
    as generation 1's coordinator (process_id 0), and the run still
    completes with an oracle-matching trajectory."""
    store = _run_elastic(
        _store_root(tmp_path, "kill_coord"), steps=6, kill="3:0"
    )
    _check_elastic_run(
        store, steps=6, killed=0, kill_step=3, survivors=[1, 2, 3],
        coordinator=1,
    )
