"""Headline benchmark: CIFAR-10 ResNet-18 training samples/sec/chip.

The driver's scored metric (BASELINE.json): ResNet-18 on CIFAR-10,
data-parallel training step, samples per second per chip. The reference
publishes no numbers (SURVEY §6) — it only *instruments* avg per-batch
wall-clock on 4-thread CPU ranks (``master/part1/part1.py:42-44``) — so
the baseline here is the value this repo established in round 1 on one
TPU v5e chip; ``vs_baseline`` tracks improvement against it.

Round-2 changes:
- the step is compiled with ``xla_tpu_scoped_vmem_limit_kib=65536``
  (v5e has far more physical VMEM than the 16 MiB scoped default; the
  larger budget lets XLA pick deeper fusions — measured ~7% step win);
- the headline batch stays 4096 (round 1's scored point), and the
  JSON line *also* reports the batch-1024 operating point (round 1's
  baseline batch) so ``vs_baseline_b1024`` measures code, not batch
  (VERDICT round 1, "normalize the benchmark baseline").

Prints ONE JSON line:
    {"metric": ..., "value": N, "unit": "samples/sec/chip", "vs_baseline": N, ...}
"""

from __future__ import annotations

import argparse
import sys
import time

import jax

# The analytic FLOPs model and the v5e peak moved to obs/flops.py (the
# telemetry layer computes live MFU from them); re-exported here so
# existing scripts importing bench.resnet18_cifar_train_flops_per_sample
# / bench.V5E_PEAK_FLOPS keep working.
from cs744_pytorch_distributed_tutorial_tpu.obs.flops import (  # noqa: F401
    V5E_PEAK_FLOPS,
    resnet18_cifar_train_flops_per_sample,
)
from cs744_pytorch_distributed_tutorial_tpu.obs.sinks import (
    JsonlSink,
    MultiSink,
    StreamSink,
)

# Round-1 measured values on one TPU v5e chip (bf16, sync='auto'):
# 32,954.6 sps at the scored batch 4096; ~32.2k at batch 1024.
ROUND1_BASELINE_SPS = 21_700.0  # the driver's original baseline
GLOBAL_BATCH = 4096
BATCH_SMALL = 1024
# The tunneled backend's first executions of a program can pay
# multi-second deferred-initialization costs beyond the compile call
# (see benchmarks/bench_lm.py) — warm well past them.
WARMUP_STEPS = 10
MEASURE_STEPS = 30

# v5e: 128 MiB physical VMEM/core vs the 16 MiB scoped-allocation
# default; a 64 MiB budget admits deeper fusions for the conv+BN step.
COMPILER_OPTIONS = {"xla_tpu_scoped_vmem_limit_kib": "65536"}


def _make_sink(metrics_dir: str | None):
    """Stdout always (the driver scrapes it); a JSONL file too when
    ``--metrics-dir`` is set — bench results land in the same stream
    format as training telemetry (``obs/``)."""
    sinks = [StreamSink(sys.stdout)]
    if metrics_dir:
        import os

        os.makedirs(metrics_dir, exist_ok=True)
        sinks.append(JsonlSink(os.path.join(metrics_dir, "metrics.jsonl")))
    return MultiSink(sinks)


def _measure(trainer, state, x, y, key, steps: int) -> float:
    """Steps/sec of the compiled per-step path. Each timing region is
    closed by fetching a concrete scalar derived from the LAST step's
    params: a host round-trip cannot complete before the dependent
    computation does. ``block_until_ready`` alone is NOT a reliable
    completion fence on this environment's tunneled TPU backend
    (measured ~190x inflation in round 1)."""
    if jax.default_backend() != "cpu":
        # Compile failures must surface, not silently fall back — a
        # default-compiled score would not be comparable to the
        # documented vmem-option configuration.
        fn = trainer.train_step.lower(state, x, y, key).compile(
            compiler_options=COMPILER_OPTIONS
        )
    else:  # CPU smoke runs: the TPU option doesn't exist there
        fn = trainer.train_step

    def fence(s) -> None:
        float(jax.tree.leaves(s.params)[0].ravel()[0])

    for _ in range(WARMUP_STEPS):
        state, _ = fn(state, x, y, key)
    fence(state)
    t0 = time.perf_counter()
    for _ in range(steps):
        state, _ = fn(state, x, y, key)
    fence(state)
    return steps / (time.perf_counter() - t0)


def _bench_at(
    batch: int,
    steps: int = MEASURE_STEPS,
    sync: str = "auto",
    grad_compress: str = "none",
    sync_overlap: str = "off",
) -> tuple[float, int]:
    """(samples/sec/chip, analytic gradient-sync payload bytes sent per
    device per step) for the given sync strategy/compression/overlap."""
    from cs744_pytorch_distributed_tutorial_tpu.config import TrainConfig
    from cs744_pytorch_distributed_tutorial_tpu.data import synthetic_cifar10
    from cs744_pytorch_distributed_tutorial_tpu.parallel import make_mesh
    from cs744_pytorch_distributed_tutorial_tpu.parallel.buckets import (
        sync_bytes_per_step,
    )
    from cs744_pytorch_distributed_tutorial_tpu.parallel.mesh import (
        shard_global_batch,
    )
    from cs744_pytorch_distributed_tutorial_tpu.train import Trainer

    n_chips = len(jax.devices())
    cfg = TrainConfig(
        model="resnet18",
        sync=sync,
        grad_compress=grad_compress,
        sync_overlap=sync_overlap,
        num_devices=n_chips,
        global_batch_size=batch,
        compute_dtype="bfloat16",
        synthetic_data=True,
    )
    mesh = make_mesh({"data": n_chips})
    trainer = Trainer(cfg, mesh=mesh)
    state = trainer.init()
    wire = sync_bytes_per_step(
        state.params,
        "int8_allreduce" if trainer._compress else sync,
        n_chips,
        reverse=trainer._overlap,
    )
    ds = synthetic_cifar10(batch, 16, seed=0)
    x, y = shard_global_batch(mesh, ds.train_images, ds.train_labels)
    key = jax.random.key(cfg.seed)
    sps = _measure(trainer, state, x, y, key, steps) * batch
    return sps / n_chips, wire


def sync_compare(
    sink,
    batch: int = BATCH_SMALL,
    steps: int = MEASURE_STEPS,
    *,
    phase_iters: int = 3,
) -> None:
    """Bytes-on-wire mode: samples/sec/chip AND analytic gradient payload
    bytes sent per device per step, one JSON line per sync setting —
    f32 per-leaf ('auto', the DDP analog), f32 bucketed flat allreduce,
    the int8-quantized bucket allreduce with error feedback, and the
    zero1 reduce-scatter schedule (parallel/zero.py). The bucketed rows
    also carry their OVERLAPPED throughput (``--sync-overlap``,
    parallel/overlap.py / parallel/zero.py), and each overlapped wire
    gets one ``kind="sync_compare"`` record comparing fused vs
    overlapped step wall and the sync_exposed_ms each leaves on the
    table (graftscope's attribution, obs/phases.py) — so
    metrics_summary.py renders an ``overlap <wire>`` row per sharded
    strategy alongside the pure-DP ones."""
    rows = (
        ("f32_per_leaf_auto", "auto", "none", None),
        ("f32_bucketed_allreduce", "allreduce", "none", "bucket"),
        ("int8_bucketed_allreduce", "allreduce", "int8", "bucket+int8"),
        ("f32_zero1_scatter", "zero1", "none", "bucket"),
    )
    for label, sync, compress, ov in rows:
        sps, wire = _bench_at(batch, steps, sync=sync, grad_compress=compress)
        rec = {
            "kind": "bench",
            "time": time.time(),
            "metric": "cifar10_resnet18_grad_sync",
            "sync": label,
            "batch": batch,
            "samples_per_sec_per_chip": round(sps, 1),
            "grad_sync_bytes_per_step": wire,
        }
        if ov is not None:
            sps_ov, _ = _bench_at(
                batch, steps, sync=sync, grad_compress=compress,
                sync_overlap=ov,
            )
            rec["sync_overlap"] = ov
            rec["samples_per_sec_per_chip_overlap"] = round(sps_ov, 1)
        sink.emit(rec)
    for label, sync, compress, ov in rows:
        if ov is None:
            continue
        rep_f, _ = _phase_report(
            batch, model="resnet18", sync=sync, grad_compress=compress,
            compute_dtype="bfloat16", iters=phase_iters,
        )
        rep_o, _ = _phase_report(
            batch, model="resnet18", sync=sync, grad_compress=compress,
            compute_dtype="bfloat16", sync_overlap=ov, iters=phase_iters,
        )
        sink.emit(
            {
                "kind": "sync_compare",
                "time": time.time(),
                "metric": "cifar10_resnet18_sync_overlap",
                "wire": label,
                "sync_overlap": ov,
                "batch": batch,
                "fused_step_ms": round(rep_f.fused_ms, 4),
                "overlap_step_ms": round(rep_o.fused_ms, 4),
                "sync_exposed_ms_fused": round(rep_f.sync_exposed_ms, 4),
                "sync_exposed_ms_overlap": round(rep_o.sync_exposed_ms, 4),
                "parity_ok": bool(rep_f.parity_ok and rep_o.parity_ok),
            }
        )


def _phase_report(
    batch: int,
    *,
    model: str = "resnet18",
    sync: str = "auto",
    grad_compress: str = "none",
    compute_dtype: str = "bfloat16",
    sync_overlap: str = "off",
    iters: int = 3,
):
    """Build a trainer for the given sync configuration and run the
    graftscope segmented profile (obs/phases.py). Returns
    ``(PhaseReport, n_chips)``; shared by ``--phase-breakdown`` and the
    overlap comparison inside ``--sync-compare``."""
    from cs744_pytorch_distributed_tutorial_tpu.config import TrainConfig
    from cs744_pytorch_distributed_tutorial_tpu.data import synthetic_cifar10
    from cs744_pytorch_distributed_tutorial_tpu.obs.phases import (
        profile_phases,
    )
    from cs744_pytorch_distributed_tutorial_tpu.parallel import make_mesh
    from cs744_pytorch_distributed_tutorial_tpu.parallel.mesh import (
        shard_global_batch,
    )
    from cs744_pytorch_distributed_tutorial_tpu.train import Trainer

    n_chips = len(jax.devices())
    cfg = TrainConfig(
        model=model,
        sync=sync,
        grad_compress=grad_compress,
        sync_overlap=sync_overlap,
        num_devices=n_chips,
        global_batch_size=batch,
        compute_dtype=compute_dtype,
        synthetic_data=True,
    )
    mesh = make_mesh({"data": n_chips})
    trainer = Trainer(cfg, mesh=mesh)
    state = trainer.init()
    ds = synthetic_cifar10(batch, 16, seed=0)
    x, y = shard_global_batch(mesh, ds.train_images, ds.train_labels)
    key = jax.random.key(cfg.seed)
    return profile_phases(trainer, state, x, y, key, iters=iters), n_chips


def phase_breakdown(
    sink,
    batch: int = GLOBAL_BATCH,
    *,
    model: str = "resnet18",
    sync: str = "auto",
    grad_compress: str = "none",
    compute_dtype: str = "bfloat16",
    sync_overlap: str = "off",
    iters: int = 3,
    metrics_dir: str | None = None,
) -> bool:
    """graftscope mode (obs/phases.py): compile forward / backward /
    grad-sync / optimizer as separate fenced segments, parity-check the
    segmented step against the fused fast path, and emit per-phase
    device time, flops, bytes, MFU, roofline class, and
    ``sync_exposed_ms`` — the optimization target for the sync-overlap
    work (ROADMAP item 2). Returns parity_ok (the caller exits nonzero
    on False: attribution of a step that computes something else is
    not a benchmark)."""
    report, n_chips = _phase_report(
        batch,
        model=model,
        sync=sync,
        grad_compress=grad_compress,
        compute_dtype=compute_dtype,
        sync_overlap=sync_overlap,
        iters=iters,
    )
    now = time.time()
    for rec in report.records(run=f"bench_{model}"):
        sink.emit({**rec, "time": now})
    sink.emit(
        {
            "kind": "bench",
            "time": now,
            "metric": f"cifar10_{model}_phase_breakdown",
            # Throughput derived from the fused-step time so regress.py
            # can gate this mode with the same tolerance arithmetic as
            # the headline metric.
            "value": round(batch / (report.fused_ms / 1e3) / n_chips, 1),
            "unit": "samples/sec/chip",
            "batch": batch,
            "sync_overlap": sync_overlap,
            "sync_exposed_ms": round(report.sync_exposed_ms, 4),
            "parity_ok": report.parity_ok,
        }
    )
    print(report.table(), file=sys.stderr)
    if metrics_dir:
        import json
        import os

        with open(os.path.join(metrics_dir, "phase_report.json"), "w") as f:
            json.dump(report.records(run=f"bench_{model}"), f, indent=1)
    return report.parity_ok


def _parse_args() -> argparse.Namespace:
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument(
        "--sync-compare",
        action="store_true",
        help="report samples/sec/chip and gradient bytes-on-wire per "
        "step for f32 per-leaf / f32 bucketed / int8 bucketed sync "
        "instead of the headline benchmark",
    )
    p.add_argument(
        "--phase-breakdown",
        action="store_true",
        help="graftscope mode: per-phase (forward/backward/grad-sync/"
        "optimizer) device time, flops, bytes, MFU, roofline class, and "
        "sync_exposed_ms, with segmented-vs-fused parity checking",
    )
    p.add_argument(
        "--batch", type=int, default=GLOBAL_BATCH,
        help="global batch size for --phase-breakdown (default %(default)s)",
    )
    p.add_argument(
        "--model", default="resnet18",
        help="model for --phase-breakdown (default %(default)s)",
    )
    p.add_argument(
        "--sync", default="auto",
        help="sync strategy for --phase-breakdown (default %(default)s)",
    )
    p.add_argument(
        "--grad-compress", default="none", choices=("none", "int8"),
        help="gradient compression for --phase-breakdown",
    )
    p.add_argument(
        "--sync-overlap", default="off",
        choices=("off", "bucket", "bucket+int8"),
        help="overlapped bucket sync schedule for --phase-breakdown "
        "(parallel/overlap.py; 'bucket' needs --grad-compress none, "
        "'bucket+int8' needs --grad-compress int8)",
    )
    p.add_argument(
        "--compute-dtype", default="bfloat16",
        help="compute dtype for --phase-breakdown (default %(default)s; "
        "float32 keeps the parity check at the strict f32 tolerance)",
    )
    p.add_argument(
        "--phase-iters", type=int, default=3,
        help="timed iterations per segment for --phase-breakdown",
    )
    p.add_argument(
        "--metrics-dir",
        default=None,
        help="also append the result records to METRICS_DIR/metrics.jsonl "
        "(the training-telemetry stream format)",
    )
    p.add_argument(
        "--serve",
        nargs=argparse.REMAINDER,
        default=None,
        help="delegate to the continuous-batching serving benchmark "
        "(serve_cli, docs/serving.md): every argument AFTER --serve "
        "passes through, e.g. bench.py --serve --requests 32 --gate "
        "or bench.py --serve --trace-dir /tmp/trace --window-every "
        "0.25 (graftserve spans + SLO windows, docs/observability.md). "
        "A --metrics-dir given before --serve is forwarded.",
    )
    return p.parse_args()


def main() -> None:
    args = _parse_args()
    if args.serve is not None:
        from cs744_pytorch_distributed_tutorial_tpu.serve_cli import (
            main as serve_main,
        )

        argv = list(args.serve)
        if args.metrics_dir and "--metrics-dir" not in argv:
            argv += ["--metrics-dir", args.metrics_dir]
        serve_main(argv)
        return
    sink = _make_sink(args.metrics_dir)
    try:
        if args.phase_breakdown:
            ok = phase_breakdown(
                sink,
                args.batch,
                model=args.model,
                sync=args.sync,
                grad_compress=args.grad_compress,
                compute_dtype=args.compute_dtype,
                sync_overlap=args.sync_overlap,
                iters=args.phase_iters,
                metrics_dir=args.metrics_dir,
            )
            if not ok:
                sys.exit(1)
            return
        if args.sync_compare:
            sync_compare(sink)
            return
        sps_big, wire = _bench_at(GLOBAL_BATCH)
        # Smaller batch -> shorter steps -> the tunnel's variable dispatch
        # jitter is a bigger fraction; a longer window stabilizes it.
        sps_small, _ = _bench_at(BATCH_SMALL, steps=90)
        flops = resnet18_cifar_train_flops_per_sample()
        sink.emit(
            {
                "kind": "bench",
                "time": time.time(),
                "metric": "cifar10_resnet18_train_samples_per_sec_per_chip",
                "value": round(sps_big, 1),
                "unit": "samples/sec/chip",
                "vs_baseline": round(sps_big / ROUND1_BASELINE_SPS, 3),
                "batch": GLOBAL_BATCH,
                "value_b1024": round(sps_small, 1),
                "vs_baseline_b1024": round(sps_small / ROUND1_BASELINE_SPS, 3),
                # Hardware-efficiency accounting (VERDICT r2 #5):
                # model FLOPs (2*MACs, 3x-forward train convention,
                # resnet18_cifar_train_flops_per_sample) against the
                # v5e bf16 peak. null off-TPU — the peak constant
                # would make any other backend's figure meaningless.
                "flops_per_sample": flops,
                # Analytic gradient-sync payload bytes SENT per device
                # per step under the configured sync (0 for 'auto' on
                # one chip; parallel/buckets.py::sync_bytes_per_step).
                "grad_sync_bytes_per_step": wire,
                "mfu": (
                    round(sps_big * flops / V5E_PEAK_FLOPS, 4)
                    if jax.default_backend() != "cpu"
                    else None
                ),
            }
        )
    finally:
        sink.close()


if __name__ == "__main__":
    main()
