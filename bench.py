"""Headline benchmark: CIFAR-10 ResNet-18 training samples/sec/chip.

The driver's scored metric (BASELINE.json): ResNet-18 on CIFAR-10,
data-parallel training step, samples per second per chip. The reference
publishes no numbers (SURVEY §6) — it only *instruments* avg per-batch
wall-clock on 4-thread CPU ranks (``master/part1/part1.py:42-44``) — so
the baseline here is the value this repo established in round 1 on one
TPU v5e chip; ``vs_baseline`` tracks improvement against it.

Prints ONE JSON line:
    {"metric": ..., "value": N, "unit": "samples/sec/chip", "vs_baseline": N}
"""

from __future__ import annotations

import json
import time

import jax
import numpy as np

# Round-1 measured value on one TPU v5 lite chip (bf16, global batch 1024,
# sync='auto'). Later rounds benchmark against this. NOTE: the scored run
# now uses GLOBAL_BATCH=4096 (below), so ~4% of vs_baseline comes from
# that operating-point change, not code — at the baseline's batch 1024
# this tree measures ~32.2k sps (vs_baseline ~1.49).
ROUND1_BASELINE_SPS = 21_700.0

# Batch 4096: measured sweep (512/1024/2048/4096/6144) shows per-chip
# throughput rising ~4% from 1024 to 4096 and flat beyond — the step is
# HBM-bandwidth-bound (XLA cost analysis: ~2.9 GF and ~16.4 KB accessed
# per sample fwd+bwd), so larger batches only amortize fixed overheads.
# 8192 exceeds the tunnel's compile transfer limit.
GLOBAL_BATCH = 4096
WARMUP_STEPS = 5
MEASURE_STEPS = 30


def main() -> None:
    from cs744_pytorch_distributed_tutorial_tpu.config import TrainConfig
    from cs744_pytorch_distributed_tutorial_tpu.data import synthetic_cifar10
    from cs744_pytorch_distributed_tutorial_tpu.parallel import make_mesh
    from cs744_pytorch_distributed_tutorial_tpu.parallel.mesh import (
        shard_global_batch,
    )
    from cs744_pytorch_distributed_tutorial_tpu.train import Trainer

    n_chips = len(jax.devices())
    cfg = TrainConfig(
        model="resnet18",
        sync="auto",
        num_devices=n_chips,
        global_batch_size=GLOBAL_BATCH,
        compute_dtype="bfloat16",
        synthetic_data=True,
    )
    mesh = make_mesh({"data": n_chips})
    trainer = Trainer(cfg, mesh=mesh)
    state = trainer.init()

    ds = synthetic_cifar10(GLOBAL_BATCH, 16, seed=0)
    x, y = shard_global_batch(mesh, ds.train_images, ds.train_labels)
    key = jax.random.key(cfg.seed)

    # Close each timing region by fetching a concrete scalar derived from
    # the LAST step's params: a host round-trip cannot complete before the
    # dependent computation — including that step's gradient sync and
    # optimizer update — does. ``block_until_ready`` alone is NOT a
    # reliable completion fence on this environment's tunneled TPU backend
    # (measured: it returned after 21 ms for 30 steps that the value fetch
    # showed actually took 3.98 s, a ~190x inflation). The in-graph
    # multi-step path (``Trainer.train_steps``) is benchmarked on CPU
    # meshes only for now: on this tunneled single-chip backend the
    # scanned program wedges the tunnel (observed twice), so the scored
    # number stays on the per-step dispatch path.
    def fence(s) -> None:
        float(jax.tree.leaves(s.params)[0].ravel()[0])

    for _ in range(WARMUP_STEPS):
        state, metrics = trainer.train_step(state, x, y, key)
    fence(state)

    t0 = time.perf_counter()
    for _ in range(MEASURE_STEPS):
        state, metrics = trainer.train_step(state, x, y, key)
    fence(state)
    elapsed = time.perf_counter() - t0

    sps = GLOBAL_BATCH * MEASURE_STEPS / elapsed
    sps_per_chip = sps / n_chips
    vs = sps_per_chip / ROUND1_BASELINE_SPS
    print(
        json.dumps(
            {
                "metric": "cifar10_resnet18_train_samples_per_sec_per_chip",
                "value": round(sps_per_chip, 1),
                "unit": "samples/sec/chip",
                "vs_baseline": round(vs, 3),
            }
        )
    )


if __name__ == "__main__":
    main()
