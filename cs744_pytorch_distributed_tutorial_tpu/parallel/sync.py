"""Gradient-sync strategies — the reference's four parts as plug-ins.

The reference implements the same data-parallel semantics four times as
copy-pasted scripts whose ONLY difference is the gradient-sync section of
``train_model`` (SURVEY §2.1, §3.5):

=================  =============================================  =====================
strategy           reference                                      mechanism here
=================  =============================================  =====================
``none``           part1 (single process, no comm)                identity
``gather_scatter`` part2a  (``master/part2a/part2a.py:42-52``)    all_gather -> mean
``p2p_star``       part2a_extra (``part2a_extra.py:41-58``)       sequential ppermute star
``allreduce``      part2b  (``master/part2b/part2b.py:43-45``)    in-graph pmean
``ring``           (TPU-native explicit variant)                  ppermute ring allreduce
``auto``           part3 DDP (``master/part3/part3.py:116``)      engine-inserted pmean
=================  =============================================  =====================

A strategy is ``fn(grads_pytree, axis_name, axis_size) -> grads_pytree``,
applied per-leaf inside the jitted train step under ``shard_map`` — the
SPMD analog of the reference's ``for p in model.parameters():`` loops.
All strategies compute the same mean; they differ (deliberately) in the
communication structure traced into the graph. ``auto`` is special-cased
by the engine: like DDP, the user-visible step has *no* explicit comm and
the framework inserts the averaging itself.
"""

from __future__ import annotations

from typing import Callable, Protocol

import jax

from cs744_pytorch_distributed_tutorial_tpu.parallel import collectives as C

SyncFn = Callable[[jax.Array, str, int], jax.Array]


def _none(g: jax.Array, axis_name: str, axis_size: int) -> jax.Array:
    """part1: single-process, no communication (``master/part1/part1.py``)."""
    return g


def _allreduce(g: jax.Array, axis_name: str, axis_size: int) -> jax.Array:
    """part2b: pre-divide + all_reduce(SUM) == pmean
    (``master/part2b/part2b.py:43-45``, divisor generalized from the
    hardcoded 4 to ``axis_size``)."""
    return C.all_reduce_mean(g, axis_name)


def _gather_scatter(g: jax.Array, axis_name: str, axis_size: int) -> jax.Array:
    """part2a: gather at rank 0, mean, scatter back
    (``master/part2a/part2a.py:42-52``)."""
    return C.gather_scatter_mean(g, axis_name)


def _p2p_star(g: jax.Array, axis_name: str, axis_size: int) -> jax.Array:
    """part2a_extra: the fully-serialized isend/irecv parameter-server star
    (``master/part2a/part2a_extra.py:41-58``)."""
    return C.star_mean(g, axis_name, axis_size)


def _ring(g: jax.Array, axis_name: str, axis_size: int) -> jax.Array:
    """Explicit bandwidth-optimal ring allreduce over ppermute hops."""
    return C.ring_all_reduce_mean(g, axis_name, axis_size)


# ``auto`` maps to allreduce numerics; the engine treats it as "framework
# inserts the sync" (DDP automation) rather than a user-plugged loop.
# ``zero1`` is identity HERE because its reduce-scatter is fused into the
# sharded-optimizer update (parallel/zero.py) — grads leave the loss
# local and the averaging happens chunk-wise inside ``Zero1SGD.apply``.
# ``fsdp`` likewise: its reduce-scatter is the AD transpose of the
# parameter all_gather (parallel/zero.py FsdpSGD), so no grad-sync pass
# exists to plug in.
SYNC_STRATEGIES: dict[str, SyncFn] = {
    "none": _none,
    "allreduce": _allreduce,
    "gather_scatter": _gather_scatter,
    "p2p_star": _p2p_star,
    "ring": _ring,
    "auto": _allreduce,
    "zero1": _none,
    "fsdp": _none,
}

#: Strategies whose outputs the VMA replication checker cannot statically
#: prove replicated (axis_index-routed selects; ``all_gather`` outputs),
#: so the enclosing ``shard_map`` needs ``check_vma=False``.
UNCHECKED_REPLICATION = {"p2p_star", "ring", "gather_scatter", "zero1", "fsdp"}


def get_sync(name: str) -> SyncFn:
    try:
        return SYNC_STRATEGIES[name]
    except KeyError:
        raise ValueError(
            f"unknown sync strategy {name!r}; choose from {sorted(SYNC_STRATEGIES)}"
        ) from None


def sync_grads(grads, name: str, axis_name: str, axis_size: int):
    """Apply strategy ``name`` leaf-wise over a gradient pytree."""
    fn = get_sync(name)
    return C.tree_map_sync(lambda g: fn(g, axis_name, axis_size), grads)
