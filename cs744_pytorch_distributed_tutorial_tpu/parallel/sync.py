"""Gradient-sync strategies — the reference's four parts as plug-ins.

The reference implements the same data-parallel semantics four times as
copy-pasted scripts whose ONLY difference is the gradient-sync section of
``train_model`` (SURVEY §2.1, §3.5):

=================  =============================================  =====================
strategy           reference                                      mechanism here
=================  =============================================  =====================
``none``           part1 (single process, no comm)                identity
``gather_scatter`` part2a  (``master/part2a/part2a.py:42-52``)    all_gather -> mean
``p2p_star``       part2a_extra (``part2a_extra.py:41-58``)       sequential ppermute star
``allreduce``      part2b  (``master/part2b/part2b.py:43-45``)    in-graph pmean
``ring``           (TPU-native explicit variant)                  ppermute ring allreduce
``auto``           part3 DDP (``master/part3/part3.py:116``)      engine-inserted pmean
=================  =============================================  =====================

A strategy is ``fn(grads_pytree, axis_name, axis_size) -> grads_pytree``,
applied per-leaf inside the jitted train step under ``shard_map`` — the
SPMD analog of the reference's ``for p in model.parameters():`` loops.
All strategies compute the same mean; they differ (deliberately) in the
communication structure traced into the graph. ``auto`` is special-cased
by the engine: like DDP, the user-visible step has *no* explicit comm and
the framework inserts the averaging itself.
"""

from __future__ import annotations

from typing import Callable

import jax
import jax.numpy as jnp
from jax import lax

from cs744_pytorch_distributed_tutorial_tpu.parallel import buckets as B
from cs744_pytorch_distributed_tutorial_tpu.parallel import collectives as C
from cs744_pytorch_distributed_tutorial_tpu.parallel.buckets import (
    DEFAULT_BUCKET_BYTES,
)

SyncFn = Callable[[jax.Array, str, int], jax.Array]

#: Quantization group size for the int8 strategies: each chunk of this
#: many elements shares one f32 scale, so the scale overhead is
#: 4/QUANT_CHUNK bytes per element (~1.6% at 256).
QUANT_CHUNK = 256


def _none(g: jax.Array, axis_name: str, axis_size: int) -> jax.Array:
    """part1: single-process, no communication (``master/part1/part1.py``)."""
    return g


def _allreduce(g: jax.Array, axis_name: str, axis_size: int) -> jax.Array:
    """part2b: pre-divide + all_reduce(SUM) == pmean
    (``master/part2b/part2b.py:43-45``, divisor generalized from the
    hardcoded 4 to ``axis_size``)."""
    return C.all_reduce_mean(g, axis_name)


def _gather_scatter(g: jax.Array, axis_name: str, axis_size: int) -> jax.Array:
    """part2a: gather at rank 0, mean, scatter back
    (``master/part2a/part2a.py:42-52``)."""
    return C.gather_scatter_mean(g, axis_name)


def _p2p_star(g: jax.Array, axis_name: str, axis_size: int) -> jax.Array:
    """part2a_extra: the fully-serialized isend/irecv parameter-server star
    (``master/part2a/part2a_extra.py:41-58``)."""
    return C.star_mean(g, axis_name, axis_size)


def _ring(g: jax.Array, axis_name: str, axis_size: int) -> jax.Array:
    """Explicit bandwidth-optimal ring allreduce over ppermute hops."""
    return C.ring_all_reduce_mean(g, axis_name, axis_size)


# --------------------------------------------------------------- int8 payloads
def _int8_allreduce_flat(
    x: jax.Array, axis_name: str, axis_size: int, quant_chunk: int = QUANT_CHUNK
) -> tuple[jax.Array, jax.Array]:
    """Quantized allreduce-mean of a flat f32 buffer; returns
    ``(mean, residual)`` where ``residual`` is everything THIS device
    knows the wire failed to deliver — the error-feedback payload.

    Structure (the reduce-scatter + all-gather decomposition with an int8
    wire format, per-SENDER scales keeping the reduction exact):

    1. pad to ``n * m * Q`` and quantize per chunk;
    2. ``all_to_all``: device d collects every sender's shard d —
       int8 codes + their f32 scales ((1 + 4/Q) bytes/element on the
       wire, vs 4 for f32);
    3. dequantize-and-sum in f32 (exact — each sender's own scale is
       applied, so no int8 overflow and no cross-sender rounding);
    4. requantize the averaged shard and ``all_gather`` codes + scales.

    The residual has two parts, both fully recoverable (two-stage EF):

    - sender error ``x - dequant(quant(x))`` — what this device's own
      contribution lost in step 2;
    - server error: device d is the reducer for shard d, so it alone
      knows ``shard_mean - dequant(requant(shard_mean))`` from step 4.
      It books ``n *`` that error into its shard of the residual — the
      next sync divides by n, so exactly the missing mean mass returns.

    Total payload per device: 2(n-1)/n * S * (1 + 4/Q) bytes — the same
    ring factor as a float allreduce at ~1/3.94 of the bytes.
    """
    from cs744_pytorch_distributed_tutorial_tpu.ops.quant import (
        dequantize_chunked,
        quantize_chunked,
    )

    n = axis_size
    size = x.size
    m = -(-size // (n * quant_chunk))  # chunks per shard
    pad = n * m * quant_chunk - size
    xp = jnp.pad(x.astype(jnp.float32), (0, pad))
    q, scale = quantize_chunked(xp, quant_chunk)  # [n*m, Q], [n*m]
    own_full = dequantize_chunked(q, scale)
    if n == 1:
        return own_full[:size], (xp - own_full)[:size]
    q = q.reshape(n, m, quant_chunk)
    scale = scale.reshape(n, m)
    # After all_to_all: row i of the result is sender i's shard `my_idx`.
    q_all = lax.all_to_all(q, axis_name, split_axis=0, concat_axis=0, tiled=False)
    s_all = lax.all_to_all(scale, axis_name, split_axis=0, concat_axis=0, tiled=False)
    shard_mean = (
        jnp.sum(q_all.astype(jnp.float32) * s_all[..., None], axis=0) / n
    ).reshape(-1)  # [m*Q]
    q2, s2 = quantize_chunked(shard_mean, quant_chunk)  # [m, Q], [m]
    q2g = lax.all_gather(q2, axis_name)  # [n, m, Q]
    s2g = lax.all_gather(s2, axis_name)  # [n, m]
    mean = dequantize_chunked(
        q2g.reshape(n * m, quant_chunk), s2g.reshape(-1)
    )[:size]
    # Two-stage residual: sender error everywhere + n * server error on
    # the shard this device reduced.
    resid = (xp - own_full).reshape(n, m * quant_chunk)
    server_err = shard_mean - dequantize_chunked(q2, s2)
    idx = lax.axis_index(axis_name)
    mine = lax.dynamic_index_in_dim(resid, idx, axis=0, keepdims=False)
    resid = lax.dynamic_update_index_in_dim(
        resid, mine + n * server_err, idx, axis=0
    )
    return mean, resid.reshape(-1)[:size]


def _int8_ring_flat(
    x: jax.Array, axis_name: str, axis_size: int, quant_chunk: int = QUANT_CHUNK
) -> tuple[jax.Array, jax.Array]:
    """EQuARX-style quantized ring allreduce-mean of a flat f32 buffer;
    returns ``(mean, residual)`` like ``_int8_allreduce_flat``.

    Reduce-scatter phase: the f32 running sum of each ring row is
    REQUANTIZED before every ``ppermute`` hop (int8 codes + per-chunk
    scales on the wire), and the receiver dequantizes and accumulates in
    f32. The accumulator is seeded from ``dequant(quant(x))`` so the
    initial quantization error lands in the residual and error feedback
    replays it; likewise the final quantization of the finished row —
    its owner books ``n *`` that error into its row of the residual
    (two-stage EF, see ``_int8_allreduce_flat``). Only the per-hop
    requantization of partial sums stays unfed-back — the (small) error
    the EQuARX design accepts for its bandwidth.
    All-gather phase: the finished row is quantized ONCE and its codes
    rotate verbatim — no re-rounding on the way out.
    """
    from cs744_pytorch_distributed_tutorial_tpu.ops.quant import (
        dequantize_chunked,
        quantize_chunked,
    )

    n = axis_size
    size = x.size
    cols = -(-size // n)
    cols = -(-cols // quant_chunk) * quant_chunk  # per-row chunk, Q-aligned
    pad = n * cols - size
    xp = jnp.pad(x.astype(jnp.float32), (0, pad))
    q0, s0 = quantize_chunked(xp, quant_chunk)
    own_full = dequantize_chunked(q0, s0)
    if n == 1:
        return own_full[:size], (xp - own_full)[:size]
    acc = own_full.reshape(n, cols)
    idx = lax.axis_index(axis_name)
    up = [(i, (i + 1) % n) for i in range(n)]

    def rs_step(s, acc):
        send_row = (idx - s) % n
        payload = lax.dynamic_index_in_dim(acc, send_row, axis=0, keepdims=False)
        q, sc = quantize_chunked(payload, quant_chunk)
        q_r = lax.ppermute(q, axis_name, perm=up)
        sc_r = lax.ppermute(sc, axis_name, perm=up)
        recvd = dequantize_chunked(q_r, sc_r)
        recv_row = (idx - s - 1) % n
        current = lax.dynamic_index_in_dim(acc, recv_row, axis=0, keepdims=False)
        return lax.dynamic_update_index_in_dim(
            acc, current + recvd, recv_row, axis=0
        )

    acc = lax.fori_loop(0, n - 1, rs_step, acc)

    # Device i finished row (i + 1) mod n: average it and quantize once.
    done_row = (idx + 1) % n
    mine = lax.dynamic_index_in_dim(acc, done_row, axis=0, keepdims=False) / n
    qf, sf = quantize_chunked(mine, quant_chunk)  # [cols/Q, Q], [cols/Q]
    out_q = jnp.zeros((n,) + qf.shape, jnp.int8)
    out_s = jnp.zeros((n,) + sf.shape, jnp.float32)
    out_q = lax.dynamic_update_index_in_dim(out_q, qf, done_row, axis=0)
    out_s = lax.dynamic_update_index_in_dim(out_s, sf, done_row, axis=0)

    def ag_step(s, carry):
        out_q, out_s, qc, sc = carry
        q_r = lax.ppermute(qc, axis_name, perm=up)
        s_r = lax.ppermute(sc, axis_name, perm=up)
        recv_row = (idx - s) % n
        out_q = lax.dynamic_update_index_in_dim(out_q, q_r, recv_row, axis=0)
        out_s = lax.dynamic_update_index_in_dim(out_s, s_r, recv_row, axis=0)
        return (out_q, out_s, q_r, s_r)

    out_q, out_s, _, _ = lax.fori_loop(0, n - 1, ag_step, (out_q, out_s, qf, sf))
    mean = dequantize_chunked(
        out_q.reshape(-1, quant_chunk), out_s.reshape(-1)
    )[:size]
    # Two-stage residual: seed error everywhere + n * final-quantization
    # error on the row this device finished.
    resid = (xp - own_full).reshape(n, cols)
    final_err = mine - dequantize_chunked(qf, sf)
    row = lax.dynamic_index_in_dim(resid, done_row, axis=0, keepdims=False)
    resid = lax.dynamic_update_index_in_dim(
        resid, row + n * final_err, done_row, axis=0
    )
    return mean, resid.reshape(-1)[:size]


def _int8_allreduce(g: jax.Array, axis_name: str, axis_size: int) -> jax.Array:
    """Leaf-wise int8 allreduce (residual DISCARDED — for standalone
    ``sync_grads`` use; the engine routes int8 syncs through
    ``sync_grads_compressed`` to keep the error-feedback state)."""
    mean, _ = _int8_allreduce_flat(g.reshape(-1), axis_name, axis_size)
    return mean.reshape(g.shape).astype(g.dtype)


def _int8_ring(g: jax.Array, axis_name: str, axis_size: int) -> jax.Array:
    """Leaf-wise EQuARX-style int8 ring allreduce (residual discarded)."""
    mean, _ = _int8_ring_flat(g.reshape(-1), axis_name, axis_size)
    return mean.reshape(g.shape).astype(g.dtype)


# ``auto`` maps to allreduce numerics; the engine treats it as "framework
# inserts the sync" (DDP automation) rather than a user-plugged loop.
# ``zero1`` is identity HERE because its reduce-scatter is fused into the
# sharded-optimizer update (parallel/zero.py) — grads leave the loss
# local and the averaging happens chunk-wise inside ``Zero1SGD.apply``.
# ``fsdp`` likewise: its reduce-scatter is the AD transpose of the
# parameter all_gather (parallel/zero.py FsdpSGD), so no grad-sync pass
# exists to plug in.
SYNC_STRATEGIES: dict[str, SyncFn] = {
    "none": _none,
    "allreduce": _allreduce,
    "gather_scatter": _gather_scatter,
    "p2p_star": _p2p_star,
    "ring": _ring,
    "auto": _allreduce,
    "zero1": _none,
    "fsdp": _none,
    "int8_allreduce": _int8_allreduce,
    "int8_ring": _int8_ring,
}

#: Strategies whose outputs the VMA replication checker cannot statically
#: prove replicated (axis_index-routed selects; ``all_gather`` outputs),
#: so the enclosing ``shard_map`` needs ``check_vma=False``.
UNCHECKED_REPLICATION = {
    "p2p_star",
    "ring",
    "gather_scatter",
    "zero1",
    "fsdp",
    "int8_allreduce",
    "int8_ring",
}

#: Strategies whose collective is elementwise-mean over flat data, so the
#: DDP-style bucketed path below may coalesce leaves into flat buffers.
_BUCKETED = {"allreduce", "ring"}


def get_sync(name: str) -> SyncFn:
    try:
        return SYNC_STRATEGIES[name]
    except KeyError:
        raise ValueError(
            f"unknown sync strategy {name!r}; choose from {sorted(SYNC_STRATEGIES)}"
        ) from None


def sync_grads(
    grads,
    name: str,
    axis_name: str,
    axis_size: int,
    bucket_bytes: int | None = DEFAULT_BUCKET_BYTES,
):
    """Apply strategy ``name`` over a gradient pytree.

    For ``allreduce`` and ``ring`` the DEFAULT path is bucketed: the tree
    is coalesced into a few flat buffers (``parallel/buckets.py``) and one
    collective per bucket replaces one per leaf — DDP's bucketing reducer,
    here as layout math. Bitwise-identical to the per-leaf path: ``pmean``
    is elementwise, and the ring layout preserves each element's ring-row
    (hence its summation order). ``bucket_bytes=None``/``0`` restores the
    per-leaf tracing; other strategies always trace per leaf (their
    communication SHAPE — star hops, gather trees — is the point).
    """
    fn = get_sync(name)
    # named_scope: pure HLO metadata (zero jaxpr eqns, graftcheck-TA003
    # invisible) that labels the collective rows in Perfetto captures —
    # graftscope's phase attribution relies on these names.
    if bucket_bytes and name in _BUCKETED and axis_size > 1:
        with jax.named_scope(f"graftscope/sync/{name}/bucketed"):
            rows = axis_size if name == "ring" else 0
            layout = B.bucket_layout(grads, bucket_bytes, rows=rows)
            bufs = B.flatten_for_sync(grads, layout)
            if name == "ring":
                synced = [
                    C.ring_all_reduce_rows(buf, axis_name, axis_size) / axis_size
                    for buf in bufs
                ]
            else:
                synced = [C.all_reduce_mean(buf, axis_name) for buf in bufs]
            return B.unflatten(synced, layout)
    with jax.named_scope(f"graftscope/sync/{name}"):
        return C.tree_map_sync(lambda g: fn(g, axis_name, axis_size), grads)


def sync_grads_compressed(
    grads,
    ef,
    name: str,
    axis_name: str,
    axis_size: int,
    *,
    bucket_bytes: int | None = DEFAULT_BUCKET_BYTES,
    quant_chunk: int = QUANT_CHUNK,
):
    """Int8-quantized gradient sync with error feedback.

    Per bucket: compress-and-sync ``b = g + ef`` (the gradient plus the
    residual this device failed to transmit last step), and carry forward
    the two-stage residual the wire kernel reports — sender quantization
    error plus this device's share of the reduce-side requantization
    error. That is EF-SGD's memory, which makes the compressed
    trajectory track the uncompressed one instead of accumulating
    quantization bias. ``ef`` is a pytree of f32 leaves shaped like
    ``grads`` (per-DEVICE state: each replica's residual is its own).
    Returns ``(mean_grads, new_ef)``.

    ``name`` picks the wire algorithm: ``int8_ring``/``ring`` the
    per-hop-requantizing ring, anything else the all_to_all + all_gather
    form. Bucketing always applies (``bucket_bytes=None`` means one
    bucket per leaf) so quantization chunks span leaf boundaries and tiny
    leaves don't each pay a collective.
    """
    flat_fn = (
        _int8_ring_flat if name in ("ring", "int8_ring") else _int8_allreduce_flat
    )
    wire = "int8_ring" if name in ("ring", "int8_ring") else "int8_allreduce"
    with jax.named_scope(f"graftscope/sync/{wire}"):
        layout = B.bucket_layout(grads, bucket_bytes or B.DEFAULT_BUCKET_BYTES, rows=0)
        g_bufs = B.flatten_for_sync(grads, layout)
        e_bufs = B.flatten_for_sync(ef, layout)
        means, residuals = [], []
        for g, e in zip(g_bufs, e_bufs):
            dtype = g.dtype
            b = g.astype(jnp.float32) + e.astype(jnp.float32)
            mean, resid = flat_fn(b, axis_name, axis_size, quant_chunk)
            means.append(mean.astype(dtype))
            residuals.append(resid)
        return B.unflatten(means, layout), B.unflatten(residuals, layout)


def sync_wire_bytes(
    params,
    name: str,
    axis_size: int,
    grad_compress: str = "none",
    *,
    quant_chunk: int = QUANT_CHUNK,
    bucket_bytes: int | None = None,
    overlap: bool = False,
) -> int:
    """Per-step gradient-sync payload bytes of the ACTIVE configuration.

    This is the strategy's own accounting (``buckets.sync_bytes_per_step``)
    resolved through the same knobs the engines resolve: ``name`` is the
    ``cfg.sync`` strategy, and ``grad_compress="int8"`` reroutes the wire
    math to the quantized payload regardless of the base strategy —
    exactly what ``sync_grads_compressed`` does to the collectives. Pass
    the engine's ``bucket_bytes`` so the int8 paths count their padded
    payload exactly (graftcheck TA003 holds this number to within 1% of
    the bytes derived from the traced jaxpr). ``overlap=True`` selects
    the overlapped schedule's reverse-order bucket layout
    (``parallel/overlap.py``) — same float bytes, but the int8 padding
    follows the reversed bucket partition. The telemetry layer records
    this number as ``grad_sync_bytes`` per step.
    """
    if name == "zero1" and grad_compress == "int8":
        # zero1's int8+EF wire flattens the rows=axis_size chunk
        # buckets through the quantized allreduce and still pays the
        # float delta all_gather — its own accounting branch.
        strategy = "zero1_int8"
    elif grad_compress == "int8" or name in ("int8_allreduce", "int8_ring"):
        strategy = "int8_ring" if name in ("ring", "int8_ring") else "int8_allreduce"
    else:
        strategy = name
    return B.sync_bytes_per_step(
        params,
        strategy,
        axis_size,
        quant_chunk=quant_chunk,
        bucket_bytes=bucket_bytes,
        reverse=overlap,
    )


# ----------------------------------------------------- schedule contracts
def sync_units(
    params,
    name: str,
    axis_size: int,
    *,
    bucket_bytes: int | None = DEFAULT_BUCKET_BYTES,
    grad_compress: str = "none",
    overlap: bool = False,
) -> int:
    """How many sync UNITS one pass over ``params`` issues collectives
    for: buckets where the strategy coalesces (``allreduce``/``ring``
    with bucketing on, every int8 path, bucketed zero1/fsdp), leaves
    everywhere else. This mirrors the routing in :func:`sync_grads`,
    :func:`sync_grads_compressed` and ``zero.Zero1SGD.apply`` exactly —
    it is the unit count :func:`expected_collective_schedule` scales by.
    ``overlap=True`` counts the overlapped schedule's reverse-order
    buckets (``parallel/overlap.py``: always bucketed, same collective
    classes per unit, but the reversed greedy walk can partition the
    tree into a different number of buckets).
    """
    leaves = len(jax.tree.leaves(params))
    if axis_size <= 1 or name == "none":
        return leaves
    # zero1/fsdp resolve FIRST: their units follow the rows=axis_size
    # chunk layout even when the int8 wire rides on top (zero1's
    # quantized allreduce flattens the same [axis_size, cols] buckets).
    if name in ("zero1", "fsdp"):
        if bucket_bytes:
            layout = B.bucket_layout(
                params, bucket_bytes, rows=axis_size, reverse=overlap
            )
            return len(layout.bucket_cols)
        return leaves
    if grad_compress == "int8" or name in ("int8_allreduce", "int8_ring"):
        layout = B.bucket_layout(
            params, bucket_bytes or B.DEFAULT_BUCKET_BYTES, rows=0, reverse=overlap
        )
        return len(layout.bucket_cols)
    if (bucket_bytes or overlap) and name in _BUCKETED:
        rows = axis_size if name == "ring" else 0
        layout = B.bucket_layout(
            params,
            bucket_bytes or B.DEFAULT_BUCKET_BYTES,
            rows=rows,
            reverse=overlap,
        )
        return len(layout.bucket_cols)
    return leaves


def expected_collective_schedule(
    name: str,
    axis_size: int,
    units: int,
    *,
    grad_compress: str = "none",
    syncs_per_step: int = 1,
) -> dict[str, int] | None:
    """The gradient-collective contract of one train step: canonical
    collective class -> count, for ``units`` sync units synced
    ``syncs_per_step`` times. graftcheck's TA003 asserts the traced jaxpr
    contains EXACTLY this multiset of non-trivial (payload beyond a
    scalar, group beyond one device) collectives — a drifted count means
    a strategy regressed into extra hops or silently stopped syncing.

    Counts per unit, ``n = axis_size``:

    - ``allreduce``/``auto``: 1 psum;
    - ``ring``/``p2p_star``: 2(n-1) ppermutes (reduce-scatter +
      all-gather hop sequences; the star serializes the same hop count
      through rank 0);
    - ``gather_scatter``: 1 all_gather (the mean + broadcast stay local);
    - ``int8_allreduce``: 2 all_to_alls + 2 all_gathers (codes and
      scales travel separately in each phase);
    - ``int8_ring``: 4(n-1) ppermutes (codes + scales per hop, both
      phases);
    - ``zero1``/``fsdp``: delegated to ``parallel.zero``'s own contract
      (with ``grad_compress="int8"``, zero1's int8+EF wire contract —
      2 all_to_alls + 3 all_gathers per unit, no reduce_scatter);
    - ``none`` (or 1-sized axis): no collectives.

    Returns None for unknown names (no contract to assert).
    """
    from cs744_pytorch_distributed_tutorial_tpu.parallel.zero import (
        fsdp_collective_schedule,
        zero1_collective_schedule,
        zero1_int8_collective_schedule,
    )

    n = int(axis_size)
    u = int(units) * int(syncs_per_step)
    if name == "none" or n <= 1:
        return {}
    if name == "zero1" and grad_compress == "int8":
        return zero1_int8_collective_schedule(u, n)
    if grad_compress == "int8" or name in ("int8_allreduce", "int8_ring"):
        if name in ("ring", "int8_ring"):
            return {"ppermute": 4 * (n - 1) * u}
        return {"all_to_all": 2 * u, "all_gather": 2 * u}
    if name in ("allreduce", "auto"):
        return {"psum": u}
    if name in ("ring", "p2p_star"):
        return {"ppermute": 2 * (n - 1) * u}
    if name == "gather_scatter":
        return {"all_gather": u}
    if name == "zero1":
        return zero1_collective_schedule(u, n)
    if name == "fsdp":
        return fsdp_collective_schedule(u, n)
    return None
