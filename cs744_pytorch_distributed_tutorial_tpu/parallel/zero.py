"""ZeRO-1: optimizer-state sharding over the data axis.

The reference keeps a full optimizer replica per rank (plain SGD over a
full model copy, ``master/part2a/part2a.py:127-128``; SURVEY §2.3 lists
ZeRO/FSDP as absent) — this module is the beyond-parity capability that
removes that redundancy, stage 1 of the ZeRO family expressed in the
TPU-native collective set:

- gradients are averaged with ``lax.psum_scatter`` (reduce-scatter), so
  each data-parallel device receives only its 1/axis_size chunk of the
  mean gradient — half the collective bytes of a full allreduce;
- the SGD momentum buffer exists ONLY as that chunk per device
  (``[axis_size, chunk]`` globally, sharded over the data axis);
- each device applies the torch-SGD update rule (decay into grad, then
  momentum trace — ``train/state.py``) to its chunk and one
  ``lax.all_gather`` of the parameter *deltas* restores replicated
  params.

reduce_scatter + all_gather is exactly the decomposition of a ring
allreduce, so the per-step communication volume matches ``allreduce``
while optimizer memory drops from O(params) to O(params / axis_size) per
device. Params themselves stay replicated (that is ZeRO-1's contract;
param sharding would be ZeRO-3/FSDP).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax


class Zero1SGD:
    """SGD(momentum, weight-decay) with data-axis-sharded momentum.

    ``init`` runs on host and returns GLOBAL momentum leaves of shape
    ``[axis_size, chunk]`` (the trainer shards their leading dim over the
    data axis); ``apply`` runs inside ``shard_map`` where each momentum
    leaf arrives as the local ``[1, chunk]`` shard.
    """

    def __init__(
        self,
        learning_rate: float,
        momentum: float,
        weight_decay: float,
        axis_name: str,
        axis_size: int,
    ):
        self.learning_rate = learning_rate
        self.momentum = momentum
        self.weight_decay = weight_decay
        self.axis_name = axis_name
        self.axis_size = axis_size

    def _chunk(self, size: int) -> int:
        return -(-size // self.axis_size)  # ceil

    def init(self, params):
        """Global momentum buffers: ``[axis_size, chunk]`` zeros per leaf."""
        return jax.tree.map(
            lambda p: jnp.zeros((self.axis_size, self._chunk(p.size)), p.dtype),
            params,
        )

    def apply(self, params, momenta, grads):
        """One ZeRO-1 step on local LOCAL grads (pre-sync): returns
        (replicated new params, local momentum shards)."""
        s = self.axis_size

        def leaf(p, m, g):
            chunk = self._chunk(p.size)
            pad = s * chunk - p.size
            g2d = jnp.pad(g.ravel(), (0, pad)).reshape(s, chunk)
            # reduce-scatter the SUM, then divide: each device now holds
            # only its chunk of the mean gradient.
            g_mine = (
                lax.psum_scatter(g2d, self.axis_name, scatter_dimension=0) / s
            )
            p2d = jnp.pad(p.ravel(), (0, pad)).reshape(s, chunk)
            p_mine = lax.dynamic_index_in_dim(
                p2d, lax.axis_index(self.axis_name), 0, keepdims=False
            )
            m_mine = m.reshape(chunk)
            # torch-SGD semantics (train/state.py): decay folds into the
            # gradient BEFORE the momentum trace.
            g_eff = g_mine + self.weight_decay * p_mine
            m_new = self.momentum * m_mine + g_eff
            delta_mine = -self.learning_rate * m_new
            delta = lax.all_gather(delta_mine, self.axis_name, axis=0)
            delta_flat = delta.reshape(s * chunk)[: p.size]
            return p + delta_flat.reshape(p.shape), m_new.reshape(1, chunk)

        out = jax.tree.map(leaf, params, momenta, grads)
        new_params = jax.tree.map(lambda _, o: o[0], params, out)
        new_momenta = jax.tree.map(lambda _, o: o[1], params, out)
        return new_params, new_momenta
