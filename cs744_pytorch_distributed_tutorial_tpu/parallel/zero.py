"""ZeRO-1: optimizer-state sharding over the data axis.

The reference keeps a full optimizer replica per rank (plain SGD over a
full model copy, ``master/part2a/part2a.py:127-128``; SURVEY §2.3 lists
ZeRO/FSDP as absent) — this module is the beyond-parity capability that
removes that redundancy, stage 1 of the ZeRO family expressed in the
TPU-native collective set:

- gradients are averaged with ``lax.psum_scatter`` (reduce-scatter), so
  each data-parallel device receives only its 1/axis_size chunk of the
  mean gradient — half the collective bytes of a full allreduce;
- the SGD momentum buffer exists ONLY as that chunk per device
  (``[axis_size, chunk]`` globally, sharded over the data axis);
- each device applies the torch-SGD update rule (decay into grad, then
  momentum trace — ``train/state.py``) to its chunk and one
  ``lax.all_gather`` of the parameter *deltas* restores replicated
  params.

reduce_scatter + all_gather is exactly the decomposition of a ring
allreduce, so the per-step communication volume matches ``allreduce``
while optimizer memory drops from O(params) to O(params / axis_size) per
device. Params themselves stay replicated (that is ZeRO-1's contract;
param sharding would be ZeRO-3/FSDP).
"""

from __future__ import annotations

import math

import jax
import jax.numpy as jnp
from jax import lax



def _shard_flat(params, axis_size: int):
    """GLOBAL param tree -> ``[axis_size, chunk]`` zero-padded flat
    shards (the shared ZeRO-3 layout; host-side)."""

    def leaf(p):
        chunk = -(-p.size // axis_size)
        return jnp.pad(p.ravel(), (0, axis_size * chunk - p.size)).reshape(
            axis_size, chunk
        )

    return jax.tree.map(leaf, params)


def _gather_flat(shards, shape_tree, axis_name: str):
    """Inside ``shard_map``: local ``[1, chunk]`` shards -> full params
    (the FSDP unshard; ``shape_tree`` leaves carry ``.shape``/``.dtype``,
    e.g. from ``jax.eval_shape`` of host init)."""

    def leaf(sh, sds):
        full = lax.all_gather(sh.reshape(-1), axis_name, axis=0)
        return (
            full.reshape(-1)[: math.prod(sds.shape)]
            .reshape(sds.shape)
            .astype(sds.dtype)
        )

    return jax.tree.map(leaf, shards, shape_tree)


class Zero1SGD:
    """SGD(momentum, weight-decay) with data-axis-sharded momentum.

    ``init`` runs on host and returns GLOBAL momentum leaves of shape
    ``[axis_size, chunk]`` (the trainer shards their leading dim over the
    data axis); ``apply`` runs inside ``shard_map`` where each momentum
    leaf arrives as the local ``[1, chunk]`` shard.
    """

    def __init__(
        self,
        learning_rate: float,
        momentum: float,
        weight_decay: float,
        axis_name: str,
        axis_size: int,
    ):
        self.learning_rate = learning_rate
        self.momentum = momentum
        self.weight_decay = weight_decay
        self.axis_name = axis_name
        self.axis_size = axis_size

    def _chunk(self, size: int) -> int:
        return -(-size // self.axis_size)  # ceil

    def init(self, params):
        """Global momentum buffers: ``[axis_size, chunk]`` zeros per leaf."""
        return jax.tree.map(
            lambda p: jnp.zeros((self.axis_size, self._chunk(p.size)), p.dtype),
            params,
        )

    def _sgd_chunk_update(self, p_mine, m_mine, g_mine):
        """torch-SGD rule on this device's flat chunk (train/state.py):
        decay folds into the gradient BEFORE the momentum trace. Returns
        (new_momentum, param_delta)."""
        g_eff = g_mine + self.weight_decay * p_mine
        m_new = self.momentum * m_mine + g_eff
        return m_new, -self.learning_rate * m_new

    def apply(self, params, momenta, grads):
        """One ZeRO-1 step on local LOCAL grads (pre-sync): returns
        (replicated new params, local momentum shards)."""
        s = self.axis_size

        def leaf(p, m, g):
            chunk = self._chunk(p.size)
            pad = s * chunk - p.size
            g2d = jnp.pad(g.ravel(), (0, pad)).reshape(s, chunk)
            # reduce-scatter the SUM, then divide: each device now holds
            # only its chunk of the mean gradient.
            g_mine = (
                lax.psum_scatter(g2d, self.axis_name, scatter_dimension=0) / s
            )
            p2d = jnp.pad(p.ravel(), (0, pad)).reshape(s, chunk)
            p_mine = lax.dynamic_index_in_dim(
                p2d, lax.axis_index(self.axis_name), 0, keepdims=False
            )
            m_mine = m.reshape(chunk)
            m_new, delta_mine = self._sgd_chunk_update(p_mine, m_mine, g_mine)
            delta = lax.all_gather(delta_mine, self.axis_name, axis=0)
            delta_flat = delta.reshape(s * chunk)[: p.size]
            return p + delta_flat.reshape(p.shape), m_new.reshape(1, chunk)

        out = jax.tree.map(leaf, params, momenta, grads)
        new_params = jax.tree.map(lambda _, o: o[0], params, out)
        new_momenta = jax.tree.map(lambda _, o: o[1], params, out)
        return new_params, new_momenta


class FsdpSGD(Zero1SGD):
    """ZeRO-3/FSDP: params AND optimizer state sharded over the data axis.

    Extends ``Zero1SGD``'s layout to the parameters themselves: each
    device persists only a ``[1, chunk]`` flat shard per leaf. The train
    step calls ``gather_params`` to materialize full parameters just-in-
    time (one ``all_gather`` per leaf — the FSDP unshard), runs
    forward/backward on them, and updates the local param+momentum
    shards. Persistent per-device memory for params+momentum is
    O(2 * params / axis_size); the full weights exist only transiently
    inside the step (XLA frees them after their last use).

    The gradient reduce-scatter is not written anywhere: differentiating
    *through* ``gather_params`` makes the AD transpose of ``all_gather``
    — which IS ``psum_scatter`` — deliver gradients already summed over
    the axis and scattered to this device's chunk. ``apply`` only divides
    by ``axis_size`` to turn the sum into the mean.

    Communication per step and leaf: one all_gather (params) + one
    reduce-scatter (grad cotangents) — the same total bytes as one
    allreduce, which is why FSDP's throughput tracks plain DP until
    params stop fitting.

    Inherits hyperparameters, chunk math, momentum ``init`` and the
    torch-SGD chunk rule from ``Zero1SGD``; ``init`` runs on host with the
    GLOBAL param tree (shard the params themselves with ``shard_params``),
    and the trainer remembers the original shapes for ``gather_params``.
    """

    def shard_params(self, params):
        """GLOBAL param tree -> ``[axis_size, chunk]`` flat shards."""
        return _shard_flat(params, self.axis_size)

    def gather_params(self, shards, shape_tree):
        """Local ``[1, chunk]`` shards -> full params (``_gather_flat``)."""
        return _gather_flat(shards, shape_tree, self.axis_name)

    def apply(self, param_shards, momenta, grad_chunks):
        """One FSDP step from CHUNKED grad sums (the ``[1, chunk]``
        cotangents of ``gather_params``'s inputs — already psum_scattered
        by the all_gather transpose): divide into means and apply the
        torch-SGD rule to the local shards."""
        s = self.axis_size

        def leaf(psh, m, g):
            chunk = psh.shape[-1]
            g_mine = g.reshape(chunk) / s
            p_mine = psh.reshape(chunk)
            m_mine = m.reshape(chunk)
            m_new, delta = self._sgd_chunk_update(p_mine, m_mine, g_mine)
            return (p_mine + delta).reshape(1, chunk), m_new.reshape(1, chunk)

        out = jax.tree.map(leaf, param_shards, momenta, grad_chunks)
        new_shards = jax.tree.map(lambda _, o: o[0], param_shards, out)
        new_momenta = jax.tree.map(lambda _, o: o[1], param_shards, out)
        return new_shards, new_momenta


class Zero1Adam:
    """ZeRO-1 AdamW for the LM engine: both Adam moments live ONLY as
    data-axis-sharded ``[axis_size, chunk]`` flat chunks per leaf —
    optimizer memory drops from 2x params to 2x params / axis_size per
    device, the lever that matters at transformer parameter counts
    (GPT-2-medium's f32 moments are ~2.8 GB replicated).

    The update math is optax.adamw's exactly (decoupled weight decay,
    bias correction, b1/b2/eps conventions), applied chunk-wise —
    elementwise, so chunking changes nothing but summation layout:
    the trajectory matches the replicated optimizer to float tolerance
    (tests/test_zero1_lm.py pins it).

    Communication per step and leaf: one ``psum_scatter`` of the LOCAL
    (unsynced) gradient — which IS the data-mean reduction, delivered
    pre-sharded at half an allreduce's bytes — plus one ``all_gather``
    of the parameter deltas; together the same bytes as the allreduce
    they replace (the ZeRO-1 identity, as Zero1SGD above). Sequence-
    axis replicas contribute via a pmean on the CHUNK (cheap: 1/dp of
    the leaf).

    ``init`` runs on host (global ``[axis_size, chunk]`` zeros; the
    trainer shards dim 0 over the data axis); ``apply`` runs inside
    ``shard_map`` where each moment leaf arrives as its ``[1, chunk]``
    local shard and params arrive replicated.
    """

    def __init__(
        self,
        schedule,
        b1: float,
        b2: float,
        eps: float,
        weight_decay: float,
        axis_name: str,
        axis_size: int,
        seq_axis: str | None = None,
        seq_size: int = 1,
    ):
        self.schedule = schedule
        self.b1, self.b2, self.eps = b1, b2, eps
        self.weight_decay = weight_decay
        self.axis_name = axis_name
        self.axis_size = axis_size
        self.seq_axis = seq_axis
        self.seq_size = seq_size

    def _chunk(self, size: int) -> int:
        return -(-size // self.axis_size)  # ceil

    def init(self, params):
        moment = lambda: jax.tree.map(
            lambda p: jnp.zeros(
                (self.axis_size, self._chunk(p.size)), jnp.float32
            ),
            params,
        )
        return {
            "mu": moment(),
            "nu": moment(),
            "count": jnp.zeros((), jnp.int32),
        }

    def _step_scalars(self, state):
        """(incremented count, lr, bias corrections) for one update.
        optax's scale_by_schedule evaluates the schedule at the count
        BEFORE this update (0 on the first step); the bias correction
        uses the incremented count — match both conventions exactly."""
        count = state["count"] + 1
        lr = (
            self.schedule(state["count"])
            if callable(self.schedule)
            else self.schedule
        )
        c1 = 1.0 - self.b1 ** count.astype(jnp.float32)
        c2 = 1.0 - self.b2 ** count.astype(jnp.float32)
        return count, lr, c1, c2

    def _adamw_chunk_update(self, p_mine, mu, nu, g_mine, c1, c2):
        """The optax.adamw rule on one f32 chunk: returns
        (new_mu, new_nu, update) with the decoupled-decay term folded in
        (the caller scales by -lr)."""
        mu_n = self.b1 * mu + (1.0 - self.b1) * g_mine
        nu_n = self.b2 * nu + (1.0 - self.b2) * g_mine * g_mine
        update = (
            mu_n / c1 / (jnp.sqrt(nu_n / c2) + self.eps)
            + self.weight_decay * p_mine
        )
        return mu_n, nu_n, update

    def apply(self, params, state, grads):
        """One ZeRO-1 AdamW step from LOCAL (pre-sync) grads: returns
        (replicated new params, new state with local moment shards)."""
        s = self.axis_size
        count, lr, c1, c2 = self._step_scalars(state)

        def leaf(p, mu, nu, g):
            chunk = self._chunk(p.size)
            pad = s * chunk - p.size
            g2d = jnp.pad(
                g.ravel().astype(jnp.float32), (0, pad)
            ).reshape(s, chunk)
            # Reduce-scatter the SUM, divide: this device's chunk of the
            # data-mean gradient; seq replicas then average on the chunk.
            g_mine = (
                lax.psum_scatter(g2d, self.axis_name, scatter_dimension=0)
                / s
            )
            if self.seq_axis is not None and self.seq_size > 1:
                g_mine = lax.pmean(g_mine, self.seq_axis)
            p2d = jnp.pad(
                p.ravel().astype(jnp.float32), (0, pad)
            ).reshape(s, chunk)
            p_mine = lax.dynamic_index_in_dim(
                p2d, lax.axis_index(self.axis_name), 0, keepdims=False
            )
            mu_n, nu_n, update = self._adamw_chunk_update(
                p_mine, mu.reshape(chunk), nu.reshape(chunk), g_mine, c1, c2
            )
            delta_mine = -lr * update
            delta = lax.all_gather(delta_mine, self.axis_name, axis=0)
            new_p = (p.ravel().astype(jnp.float32) + delta.reshape(-1)[: p.size])
            return (
                new_p.reshape(p.shape).astype(p.dtype),
                mu_n.reshape(1, chunk),
                nu_n.reshape(1, chunk),
            )

        out = jax.tree.map(leaf, params, state["mu"], state["nu"], grads)
        pick = lambda i: jax.tree.map(
            lambda _, o: o[i], params, out
        )
        return pick(0), {"mu": pick(1), "nu": pick(2), "count": count}


class FsdpAdam(Zero1Adam):
    """ZeRO-3/FSDP AdamW for the LM engine: params AND both moments
    persist only as data-axis-sharded ``[axis_size, chunk]`` flat
    chunks — per-device persistent memory for params+moments drops from
    3x params to 3x params / axis_size. The step gathers full params
    just-in-time (one ``all_gather`` per leaf — the FSDP unshard; XLA
    frees the full weights after their last use), and differentiating
    THROUGH that gather makes the AD transpose — ``psum_scatter`` —
    deliver gradients already summed over the axis and scattered to
    this device's chunk; ``apply`` divides into the mean and runs the
    optax-exact AdamW chunk rule from ``Zero1Adam``. No delta
    all_gather: parameters stay sharded. Communication per step and
    leaf: one all_gather (params) + one reduce-scatter (grad
    cotangents) — the same total bytes as ZeRO-1's pair.

    ``init``/chunk math inherit from ``Zero1Adam``; ``shard_params`` /
    ``gather_params`` mirror ``FsdpSGD``'s layout (host-side global
    ``[axis_size, chunk]`` shards; in-shard_map unshard needs the
    original shape tree).
    """

    def shard_params(self, params):
        """GLOBAL param tree -> ``[axis_size, chunk]`` flat shards."""
        return _shard_flat(params, self.axis_size)

    def gather_params(self, shards, shape_tree):
        """Local ``[1, chunk]`` shards -> full params (``_gather_flat``)."""
        return _gather_flat(shards, shape_tree, self.axis_name)

    def unshard_host(self, shards, shape_tree):
        """Host-side inverse of ``shard_params`` for export/decode: the
        global ``[axis_size, chunk]`` arrays already hold every chunk —
        reshape/slice, no collectives."""
        import numpy as np

        def leaf(sh, sds):
            flat = np.asarray(jax.device_get(sh)).reshape(-1)
            return flat[: math.prod(sds.shape)].reshape(sds.shape).astype(
                np.asarray([], sds.dtype).dtype
            )

        return jax.tree.map(leaf, shards, shape_tree)

    def apply(self, param_shards, state, grad_chunks):
        """One FSDP AdamW step from CHUNKED grad sums (the ``[1, chunk]``
        cotangents of ``gather_params`` — already psum_scattered by the
        all_gather transpose): divide into means, optionally seq-pmean,
        and run the shared AdamW chunk rule on the local shards."""
        s = self.axis_size
        count, lr, c1, c2 = self._step_scalars(state)

        def leaf(psh, mu, nu, g):
            chunk = psh.shape[-1]
            g_mine = g.reshape(chunk).astype(jnp.float32) / s
            if self.seq_axis is not None and self.seq_size > 1:
                g_mine = lax.pmean(g_mine, self.seq_axis)
            p_mine = psh.reshape(chunk).astype(jnp.float32)
            mu_n, nu_n, update = self._adamw_chunk_update(
                p_mine, mu.reshape(chunk), nu.reshape(chunk), g_mine, c1, c2
            )
            new_p = (p_mine - lr * update).astype(psh.dtype)
            return (
                new_p.reshape(1, chunk),
                mu_n.reshape(1, chunk),
                nu_n.reshape(1, chunk),
            )

        out = jax.tree.map(leaf, param_shards, state["mu"], state["nu"],
                           grad_chunks)
        pick = lambda i: jax.tree.map(lambda _, o: o[i], param_shards, out)
        return pick(0), {"mu": pick(1), "nu": pick(2), "count": count}
